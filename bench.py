"""Benchmark harness — the BASELINE.md configs on the live JAX backend.

Prints ONE JSON line to stdout:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
Everything else (per-config results, parity anchor) goes to stderr.

Configs (BASELINE.md / BASELINE.json):
  1. GCounter::merge  — 2 replicas, 4 actors (scalar CPU parity anchor)
  2. VClock::merge    — 1k clocks × 64 actors
  3. PNCounter::merge — 1M replicas × 32 actors
  4. Orswot::merge    — 100k sets × 16 actors
  5. LWWReg::merge    — 10M registers
  ★  North star: N-way Orswot anti-entropy to fixpoint, 64 actors,
     reported as merges/sec (pairwise object-merges per second), with
     value() parity vs the scalar engine asserted on a sample.

The reference publishes no numbers (BASELINE.md); vs_baseline is reported
against the BASELINE.json target of 10M merged replicas in <1s ⇒ 1e7
merges/sec ⇒ vs_baseline = value / 1e7.

Set CRDT_BENCH_SMALL=1 for a quick smoke run (CI / laptops).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

from benchkit import axon_bank, banked as banked_mod
from benchkit.core import (  # noqa: F401  (re-exported: stage code + tests)
    SMALL,
    _BUDGET_S,
    _JSON_STATE,
    _downshift,
    _sync_overhead,
    emit,
    install_budget_watchdog as _install_budget_watchdog,
    log,
    remaining_budget,
    run_stage,
    timeit_chained,
)

# legacy alias kept for banks/meta helpers that moved wholesale
AXON_ART_PATH = axon_bank.AXON_ART_PATH


def rand_clocks(rng, shape, hi=1000):
    return rng.randint(0, hi, size=shape).astype(np.uint32)


def bench_clock_merges():
    """Configs 2/3/5 as device-side anti-entropy chains: each iteration
    merges the (constant) other replica into the carried accumulator —
    data-dependent across iterations, so the whole chain executes on
    device and the tunnel sync is paid once (see ``timeit_chained``)."""
    import jax.numpy as jnp

    from crdt_tpu.ops import clock_ops

    rng = np.random.RandomState(0)

    # config 2: VClock 1k × 64
    n, a = (1000, 64) if not SMALL else (100, 16)
    x = jnp.asarray(rand_clocks(rng, (n, a)))
    y = jnp.asarray(rand_clocks(rng, (n, a)))
    t, _ = timeit_chained(lambda acc, yy: clock_ops.merge(acc, yy), x,
                          consts=(y,))
    log(f"config2 vclock_merge   n={n} A={a}: {t*1e6:.1f}us  {n/t/1e6:.2f}M merges/s")

    # config 3: PNCounter 1M × 32 (planes [N, 2, A])
    n, a = (1_000_000, 32) if not SMALL else (10_000, 8)
    p = jnp.asarray(rand_clocks(rng, (n, 2, a)))
    q = jnp.asarray(rand_clocks(rng, (n, 2, a)))
    t, _ = timeit_chained(lambda acc, qq: clock_ops.merge(acc, qq), p,
                          consts=(q,))
    log(f"config3 pncounter_merge n={n} A={a}: {t*1e3:.2f}ms  {n/t/1e6:.2f}M merges/s")

    # config 5: LWWReg 10M
    from crdt_tpu.ops import lww_ops

    n = 10_000_000 if not SMALL else 100_000
    va = jnp.asarray(rng.randint(0, 1 << 30, size=n).astype(np.uint32))
    ma = jnp.asarray(rng.randint(0, 1 << 30, size=n).astype(np.uint32))
    vb = jnp.asarray(rng.randint(0, 1 << 30, size=n).astype(np.uint32))
    mb = jnp.asarray(rng.randint(0, 1 << 30, size=n).astype(np.uint32))
    t, _ = timeit_chained(
        lambda acc, v2, m2: lww_ops.merge(acc[0], acc[1], v2, m2)[:2],
        (va, ma), consts=(vb, mb)
    )
    log(f"config5 lwwreg_merge   n={n}: {t*1e3:.2f}ms  {n/t/1e6:.2f}M merges/s")


def bench_orswot_pairwise():
    import jax
    import jax.numpy as jnp

    from crdt_tpu.ops import orswot_ops
    from crdt_tpu.utils.testdata import random_orswot_arrays

    rng = np.random.RandomState(1)
    # config 4: 100k sets × 16 actors
    n, a, m, d = (100_000, 16, 8, 4) if not SMALL else (2_000, 8, 4, 2)
    lhs = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, m, d))
    rhs = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, m, d))

    t, _ = timeit_chained(
        lambda acc, *r: orswot_ops.merge(*acc, *r, m, d)[:5], lhs,
        iters=4 if SMALL else 20, consts=rhs,
    )
    log(f"config4 orswot_merge   n={n} A={a} M={m}: {t*1e3:.2f}ms  {n/t/1e6:.2f}M merges/s")
    return n / t


def _native_fold_timing(templates, r, a, m, d, n_chunks):
    """Time the C++ row-kernel chunk fold (CPU backends), or None.

    The framework's best-engine-per-backend dispatch, not a different
    workload (same templates, same merge count, bit-exact kernels:
    crdt_tpu/native/crdt_core.cpp vs ops/orswot_ops.py).  Eager C calls
    cannot be hoisted or elided, so no salt chain is needed; promotion is
    gated by the same scalar-oracle parity sample as the jnp fold (a
    parity failure raises — a wrong kernel must not publish timings;
    only a missing/broken .so degrades to None)."""
    chunk = templates[0][0].shape[1]
    try:
        # import + one tiny warm call: the only failures that may
        # downgrade to the jnp headline are a missing/broken .so
        from crdt_tpu.native import engine as native_engine

        native_engine.vclock_merge(
            np.zeros((1, 2), np.uint32), np.zeros((1, 2), np.uint32)
        )
    except (ImportError, OSError, RuntimeError) as e:
        log(f"north★ native-engine fold unavailable: {str(e)[:200]}")
        return None

    # two reusable output-buffer sets per input shape: the C kernel fully
    # overwrites outputs, so ping-ponging avoids an mmap page-zeroing
    # pass per merge (~working-set bytes of pure overhead each call).
    # Keyed by shape because the parity sample folds 8-object slices
    # before the full chunks.
    _fold_bufs: dict = {}

    def native_fold_join(stack):
        # NOTE: the returned planes alias the shared buffer cache — a
        # later same-shape call overwrites them.  Both callers comply:
        # the parity sample consumes its result before the timing loop
        # runs, and the timing loop discards results.
        st = [np.asarray(x) for x in stack]
        acc = tuple(x[0] for x in st)
        if acc[0].shape not in _fold_bufs:
            # guarded (not setdefault): the default would re-build two
            # full-size buffer sets on every call
            _fold_bufs[acc[0].shape] = [
                tuple(np.empty_like(p) for p in acc) for _ in range(2)
            ]
        bufs = _fold_bufs[acc[0].shape]
        k = 0
        for i in range(1, r):
            acc = native_engine.orswot_merge(
                *acc, *(x[i] for x in st), out=bufs[k]
            )[:5]
            k ^= 1
        # defer plunger, as in fold_join (acc sits in bufs[k^1])
        return native_engine.orswot_merge(*acc, *acc, out=bufs[k])[:5]

    _north_star_parity(templates[0], r, a, m, d, native_fold_join)
    np_templates = [tuple(np.asarray(x) for x in tpl) for tpl in templates]
    t0n = time.perf_counter()
    for c in range(n_chunks):
        out_native = native_fold_join(np_templates[c % len(np_templates)])
    native_s = time.perf_counter() - t0n
    del out_native
    log(
        f"north★ native-engine fold: {native_s:.2f}s "
        f"({n_chunks * chunk * r / native_s / 1e6:.2f}M merges/s)"
    )
    return native_s


def bench_north_star():
    """BASELINE.md config ★ at its defined scale: 10M replica-objects
    total (R fleets × N objects), 64 actors, N-way anti-entropy to
    fixpoint with a defer plunger.

    The object axis is processed in device-sized chunks (that is what the
    object axis is for — each chunk's (R+1)-state working set must fit
    HBM); member tables are filled to capacity and a fraction of objects
    carry causally-future deferred removes so the replay path does real
    work.  value() parity vs the scalar engine is asserted on a sample of
    the first chunk."""
    import jax
    import jax.numpy as jnp

    from crdt_tpu.ops import orswot_ops

    rng = np.random.RandomState(2)
    if SMALL:
        n, a, m, d, r, chunk = 2_000, 16, 8, 2, 4, 1_000
        base, novel = 4, 1
    else:
        # n × r = 10M replica-objects (BASELINE.md:28); chunk keeps the
        # (r+1)-state working set ≈ 1.4 GB on device
        n, a, m, d, r, chunk = 1_250_000, 64, 16, 2, 8, 62_500
        base, novel = 6, 1
    deferred_frac = 0.25

    # two distinct chunk templates cycled over the object axis: data
    # content does not change the kernel's work (dense data-oblivious
    # kernels; the deferred cond branch is exercised by both templates),
    # while host-side generation stays a bounded cost.  Fleets share most
    # members per object (anti-entropy's real shape — the union must fit
    # m_cap or the fold would silently truncate, which the parity sample
    # below would catch).
    from crdt_tpu.utils.testdata import anti_entropy_fleets

    templates = []
    for _ in range(2):
        reps = anti_entropy_fleets(
            rng, chunk, a, m, d, r,
            base=base, novel=novel, deferred_frac=deferred_frac,
        )
        templates.append(
            tuple(jnp.stack([rep[k] for rep in reps]) for k in range(5))
        )

    if os.environ.get("CRDT_TREE_FOLD") == "1":
        # pairwise tree reduction: same R-1 merges, log-depth dependency
        # chain, each level one batched call.  Opt-in: measured 2.3x
        # SLOWER than the sequential fold on the CPU backend (the [R/2,
        # chunk] level-1 working set blows the cache hierarchy), so the
        # default stays sequential until the tree is measured faster on
        # the target backend.
        def fold_join(stack):
            return orswot_ops.fold_merge_tree(*stack, m, d)[:5]
    else:
        def fold_join(stack):
            acc = tuple(x[0] for x in stack)
            for i in range(1, r):
                acc = orswot_ops.merge(*acc, *(x[i] for x in stack), m, d)[:5]
            # defer plunger: one self-merge pass flushes deferred removes
            return orswot_ops.merge(*acc, *acc, m, d)[:5]

    # parity sample: the SELECTED fold on the first template's first
    # objects must reproduce the scalar engine's N-way merge value()
    _north_star_parity(templates[0], r, a, m, d, fold_join)

    full_chunks = max(2, n // chunk)
    n_chunks = full_chunks
    if _downshift():
        # CPU fallback: 4 chunks instead of 20 — the merges/s rate is
        # unchanged (same kernel, same per-chunk work), the wall time
        # fits the budget; the JSON records the actual total
        n_chunks = min(n_chunks, 4)
    elision = {"elision_check": "skipped"}  # per-step-dispatch paths can't hoist
    if n_chunks < full_chunks:
        # self-describing probe/fallback artifact (VERDICT r4 weak #5):
        # a reader of the JSON alone can tell a downshifted run from a
        # regression
        elision["northstar_downshift"] = f"{n_chunks}/{full_chunks}"

    # Native-engine contender FIRST on CPU backends: the C++ row kernel
    # measured ~3.7x the XLA:CPU fold at north-star shapes on one core,
    # and it is the cheap path — under a tight budget it banks a headline
    # before the jnp scan's compile even starts.  Parity-gated by the
    # same scalar-oracle sample as the jnp fold.
    native_s = None
    if (
        jax.default_backend() == "cpu"
        and os.environ.get("CRDT_SKIP_NATIVE_HEADLINE") != "1"
        and remaining_budget() > 45
    ):
        native_s = _native_fold_timing(templates, r, a, m, d, n_chunks)
        if native_s is not None:
            elision["native_s"] = round(native_s, 2)
            # bank a provisional headline immediately — a later crash or
            # budget kill keeps this line (emit_headline keeps a banked
            # on-chip capture ahead of this CPU number)
            banked_mod.emit_headline(
                n_chunks * chunk * r / native_s,
                {"kernel": "native_fold"},
                jax.default_backend(),
                banked_mod.IS_FALLBACK,
            )

    # stream all chunks in ONE dispatch: a device-side scan over
    # chunk pairs (both templates per step).  A carried salt XORs
    # each step's set-clock planes, making every iteration
    # data-dependent on the previous output — XLA's while-loop
    # invariant-code-motion cannot hoist the fold, and the tunnel's
    # fixed per-dispatch sync (~65 ms through the axon relay, see
    # reports/TPU_LATENCY.md) is paid once rather than per chunk.
    # The kernels are data-oblivious, so the XOR does not change the
    # work per fold; value()-parity is asserted on the unperturbed
    # sample above.
    from jax import lax

    t0_, t1_ = templates[0], templates[1]

    def salted_fold(tpl, salt):
        return fold_join((tpl[0] ^ salt,) + tpl[1:])

    def next_salt(acc):
        # the salt must max-reduce the DOTS plane (acc[2]), not the
        # clock: the merged clock is a cheap elementwise max computed
        # outside the member/deferred pipeline, so a clock-derived
        # salt would leave the expensive pipeline dead and XLA's DCE
        # would delete it — halving the work actually executed while
        # the merge count stays the same.  The full-tensor reduce
        # keeps every dots element (and, through the deferred
        # replay's data flow, the deferred pipeline) live.
        return (jnp.max(acc[2]) & jnp.uint32(7)) | jnp.uint32(1)

    @jax.jit
    def run_chunks(t0_, t1_):
        def body(carry, _):
            salt, _prev = carry
            o0 = salted_fold(t0_, salt)
            o1 = salted_fold(t1_, next_salt(o0))
            return (next_salt(o1), o1), None

        init = (jnp.uint32(1), tuple(x[0] for x in t0_))
        (salt, out), _ = lax.scan(body, init, None, length=n_chunks // 2)
        return out

    def run_scan_timed():
        out = run_chunks(t0_, t1_)
        jax.block_until_ready(out)  # compile + warmup (one full pass)
        sync_s = _sync_overhead()
        t0 = time.perf_counter()
        out = run_chunks(t0_, t1_)
        np.asarray(out[0].ravel()[0])  # scalar fetch forces completion
        return max(time.perf_counter() - t0 - sync_s, 1e-9), out

    t = scan_out = None
    # the scan's compile + two full passes cost real budget (113s/pass at
    # full CPU scale, ~23s downshifted); when the native contender has
    # already banked a headline and the budget is tight, skip the scan
    # rather than risk the artifact
    est_scan = 90 if _downshift() else 420
    if remaining_budget() > est_scan or native_s is None:
        for attempt in range(2):
            try:
                t, scan_out = run_scan_timed()
                break
            except Exception as e:  # transient remote-compile outage
                log(f"north★ scan attempt {attempt + 1} failed: {str(e)[:200]}")
                if attempt == 0:
                    time.sleep(20)
    else:
        log(
            f"north★ jnp scan: SKIPPED (remaining budget "
            f"{remaining_budget():.0f}s < est {est_scan}s; native headline "
            "already banked)"
        )
        elision["jnp_scan"] = "skipped_budget"
    run_stepped_path = os.environ.get("CRDT_RUN_ELISION_CHECK") == "1" or (
        # the elision check is VALIDATION: whenever the scan actually
        # ran, replay it per-step and demand bit-equality — never
        # budget-skipped (round 5 shipped elision_check: "skipped" on a
        # run whose scan HAD executed; a headline that might be
        # invariant-hoisted is not a headline).  The replay doubles as
        # the second timing path (async per-step dispatches measured
        # 20-30% faster than lax.scan on CPU), so its cost buys timing
        # evidence too.
        scan_out is not None
    ) or (
        # ...and the stepped path is also the scan-outage fallback: its
        # per-step dispatches chain asynchronously through a
        # device-value salt, so the tunnel's ~65 ms round-trip is
        # paid once at the final fetch instead of per chunk (the
        # last-resort host loop below pays it ~every chunk)
        t is None and native_s is None and remaining_budget() > 60
    )
    if run_stepped_path:
        # Work-elision check (VERDICT r2 weak #4): replay the exact
        # salt chain as per-step host dispatches — a separately
        # compiled program XLA cannot hoist across — and demand
        # bit-equality with the scan's final output.  If the scan's
        # while-loop had been invariant-hoisted or partially DCE'd
        # into computing fewer folds, the replay would diverge (salts
        # are data-dependent on every fold output) and its wall time
        # would dwarf the scan's.  A transient tunnel/compile outage
        # here must not crash a bench whose timing already landed —
        # only an actual mismatch is fatal.
        try:
            sf = jax.jit(salted_fold)
            ns_j = jax.jit(next_salt)

            def run_stepped():
                salt = jnp.uint32(1)
                out_r = None
                for _ in range(n_chunks // 2):
                    o0 = sf(t0_, salt)
                    o1 = sf(t1_, ns_j(o0))
                    salt = ns_j(o1)
                    out_r = o1
                # scalar fetch: block_until_ready alone does not force
                # completion through the tunnel (reports/TPU_LATENCY.md)
                np.asarray(out_r[0].ravel()[0])
                return out_r

            run_stepped()  # compile + warmup, mirroring run_scan_timed
            sync_s = _sync_overhead()
            t0r = time.perf_counter()
            out_r = run_stepped()
            t_replay = max(time.perf_counter() - t0r - sync_s, 1e-9)
            same = scan_out is None or all(
                bool(jnp.array_equal(x, y)) for x, y in zip(scan_out, out_r)
            )
        except Exception as e:
            log(f"north★ elision check errored (transient?): {str(e)[:200]}")
            elision["elision_check"] = "error"
        else:
            assert same, (
                "north★ elision check FAILED: scan output != per-step replay"
            )
            if scan_out is None:
                # scan never compiled: no hoisting question to answer
                # (each sf dispatch is a separately compiled program
                # XLA cannot elide across), but the stepped chain is
                # still a sync-free timing path
                log(
                    f"north★ stepped timing (scan unavailable): "
                    f"{t_replay:.2f}s"
                )
                elision.update(elision_check="scan_unavailable",
                               stepped_s=round(t_replay, 2),
                               timing_path="stepped")
                t = t_replay
            else:
                log(
                    f"north★ elision check: scan == per-step replay "
                    f"(bit-equal); scan {t:.2f}s vs replay {t_replay:.2f}s"
                )
                elision.update(elision_check="bit_equal",
                               scan_s=round(t, 2),
                               stepped_s=round(t_replay, 2))
                # The replay is not just a check — it is the second
                # timing path: per-step dispatches chain ASYNCHRONOUSLY
                # (the salt argument is a device value, so the host
                # never syncs mid-chain; the tunnel's ~65 ms round-trip
                # is paid once at the final fetch), and measured 20-30%
                # FASTER than the lax.scan on CPU — XLA's while-loop
                # materializes the carried state tuple each iteration,
                # overhead the straight-line per-step executions don't
                # pay.  The headline takes whichever path the backend
                # runs faster.
                if t_replay < t:
                    elision["timing_path"] = "stepped"
                    t = t_replay
                else:
                    elision["timing_path"] = "scan"
    if t is None and native_s is None and remaining_budget() > 30:
        # last resort: per-chunk host loop (pays the tunnel sync per
        # chunk — slower but never a crashed bench)
        log("north★ falling back to per-chunk host-loop timing")
        fold = jax.jit(fold_join)
        jax.block_until_ready(fold(templates[0]))
        t0 = time.perf_counter()
        for c in range(n_chunks):
            out = fold(templates[c % len(templates)])
        jax.block_until_ready(out)
        t = time.perf_counter() - t0

    # headline pick: fastest parity-gated path that actually ran (the
    # native contender timed itself before the scan on CPU backends)
    kernel_name = "jnp_fold"
    if native_s is not None:
        if t is None:
            log(f"north★ native-engine fold: {native_s:.2f}s (jnp path unavailable)")
            elision["timing_path"] = "native"
            t = native_s
            kernel_name = "native_fold"
        elif native_s < t:
            log(f"north★ native-engine fold: {native_s:.2f}s vs jnp {t:.2f}s")
            elision["jnp_s"] = round(t, 2)
            elision["timing_path"] = "native"
            t = native_s
            kernel_name = "native_fold"
        else:
            log(f"north★ native-engine fold: {native_s:.2f}s vs jnp {t:.2f}s (jnp wins)")
    if t is None:
        raise RuntimeError("north★: no timing path produced a measurement")

    merges = n_chunks * chunk * r  # (r-1) fold merges + 1 plunger per object
    elision["northstar_replica_objects"] = merges
    rate = merges / t
    state_bytes = sum(x.nbytes for x in templates[0])
    log(
        f"north★  orswot anti-entropy fixpoint n×R={n_chunks*chunk*r} "
        f"(chunks of {chunk}) A={a} M={m} deferred_frac={deferred_frac}: "
        f"{t:.2f}s  {rate/1e6:.2f}M merges/s  kernel={kernel_name}  "
        f"(working set {state_bytes/1e9:.2f} GB/chunk-fold)"
    )
    return rate, elision, templates, kernel_name


def bench_north_star_resident():
    """The north star over a REAL distinct fleet (VERDICT r2 weak #4):
    10M DISTINCT replica-objects — no template recycling — generated as
    compact columns on the host (~200x smaller than dense state), shipped
    to the device, expanded to dense planes THERE (`build_fleet_planes`
    under jit — the ingest is genuinely paid and timed), folded chunk by
    chunk with every chunk's state device-resident through its whole
    ingest+build+fold (no host round-trips; converged outputs are
    consumed into a digest rather than accumulated — see the in-loop
    note), one digest fetch forcing full completion.  Reports end-to-end
    seconds including generation + ingest + fold.

    Parity is asserted on the warmup chunk before anything is timed."""
    import functools

    import jax
    import jax.numpy as jnp

    from crdt_tpu.ops import orswot_ops
    from crdt_tpu.utils.testdata import build_fleet_planes, fleet_columns

    resident_downshift = None
    if SMALL:
        chunk, n_chunks, a, m, d, r, base, novel = 1_000, 4, 16, 8, 2, 4, 4, 1
    else:
        chunk, n_chunks, a, m, d, r, base, novel = 62_500, 20, 64, 16, 2, 8, 6, 1
        if _downshift():
            full = n_chunks
            n_chunks = 4  # CPU fallback: same per-chunk work, 5x less wall
            resident_downshift = f"{n_chunks}/{full}"
    deferred_frac = 0.25

    build = jax.jit(
        functools.partial(
            build_fleet_planes, a=a, m_cap=m, d=d, base=base, novel=novel
        )
    )

    @jax.jit
    def fold_digest(planes):
        acc = tuple(x[0] for x in planes)
        for i in range(1, r):
            acc = orswot_ops.merge(*acc, *(x[i] for x in planes), m, d)[:5]
        acc = orswot_ops.merge(*acc, *acc, m, d)[:5]  # defer plunger
        # cheap full-state digest: forces the whole fold without fetching
        # the converged planes off-device
        digest = jnp.max(acc[0]).astype(jnp.uint32) ^ (
            jnp.sum(acc[2].astype(jnp.uint32)) & jnp.uint32(0xFFFF)
        )
        return acc, digest

    def chunk_cols(c):
        # one independent stream per chunk: every object in the 10M fleet
        # is distinct data, generated reproducibly
        return fleet_columns(
            np.random.RandomState(1000 + c), chunk, a, m, d, r,
            base=base, novel=novel, deferred_frac=deferred_frac,
        )

    # warmup compiles build+fold AND runs the parity sample (untimed)
    warm_planes = build(chunk_cols(0))
    warm_out, warm_digest = fold_digest(warm_planes)
    jax.block_until_ready(warm_digest)
    sample_template = tuple(np.asarray(x[:, :8]) for x in warm_planes)
    _north_star_parity(
        tuple(jnp.asarray(x) for x in sample_template), r, a, m, d,
        lambda stack: fold_digest(tuple(x for x in stack))[0],
    )

    # each chunk's state is device-resident through its entire
    # ingest+build+fold (no host round-trips; the digest consumes the
    # converged output).  The outputs themselves are NOT accumulated:
    # retaining 20 converged chunks (~7 GB) on a 16 GB chip alongside the
    # build/fold transients risks an OOM and adds nothing the digest
    # doesn't already force.
    t0 = time.perf_counter()
    digest = jnp.uint32(0)
    for c in range(n_chunks):
        planes = build(jax.device_put(chunk_cols(c)))
        _out, dg = fold_digest(planes)
        digest = digest ^ dg
    final = int(np.asarray(digest))  # one fetch forces every chunk
    e2e = time.perf_counter() - t0
    merges = n_chunks * chunk * r
    log(
        f"north★ resident fleet: {n_chunks * chunk} distinct objects × {r} "
        f"replicas = {merges} replica-objects, A={a} M={m} "
        f"deferred_frac={deferred_frac}: e2e {e2e:.2f}s incl. column ingest "
        f"({merges / e2e / 1e6:.2f}M merges/s end-to-end; digest {final:#x})"
    )
    out = {
        "distinct_replica_objects": merges,
        "e2e_s": round(e2e, 2),
        "resident_merges_per_sec": round(merges / e2e, 1),
    }
    if resident_downshift:
        out["resident_downshift"] = resident_downshift
    return out


def bench_pallas_north_star(templates=None):
    """Guarded shot at the fused Pallas fold as the headline kernel.

    Runs LAST among the timed benches (after the resident fleet, before
    the validation subprocess): a Mosaic compile crash through the
    tunnel's remote-compile helper has been observed to wedge subsequent
    compiles (reports/PALLAS_TPU_ATTEMPT.txt), so nothing that still
    needs a compile may come after this.  TPU-only; every failure path
    degrades to ``None`` and the jnp headline stands.

    Parity gate: the fused fold must reproduce the scalar oracle on the
    sample (the same `_north_star_parity` the jnp fold passes) before its
    timing can be believed.  Timing: the same salted-scan chain as the
    jnp path (one dispatch, tunnel sync paid once)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    if jax.default_backend() != "tpu":
        return None
    if os.environ.get("CRDT_SKIP_PALLAS_HEADLINE") == "1":
        log("north★ pallas: skipped (CRDT_SKIP_PALLAS_HEADLINE=1)")
        return None
    from crdt_tpu.ops import orswot_pallas
    from crdt_tpu.utils.testdata import anti_entropy_fleets

    rng = np.random.RandomState(2)
    if SMALL:
        n, a, m, d, r, chunk = 2_000, 16, 8, 2, 4, 1_000
        base, novel = 4, 1
    else:
        n, a, m, d, r, chunk = 1_250_000, 64, 16, 2, 8, 62_500
        base, novel = 6, 1
    deferred_frac = 0.25
    n_chunks = max(2, n // chunk)

    # Which fused kernel contends (CRDT_PALLAS_KERNEL): "aligned" — the
    # union-aligned fold (ops/orswot_fold_aligned: one alignment, pure
    # elementwise steps; built to fix the fused fold's measured
    # VPU-compute bind, PERF.md 2026-08-01) — or "fused", the original
    # per-step tile merge, kept A/B-able until the aligned kernel wins
    # on-chip.  u_cap = m: the north-star fleets bound the per-object
    # union at base + r*novel <= m (utils/testdata.py), and the parity
    # gate below would catch an overflow-truncated fold.
    kernel_choice = os.environ.get("CRDT_PALLAS_KERNEL", "aligned")
    if kernel_choice == "aligned":
        from crdt_tpu.ops import orswot_fold_aligned

        def fold_kernel(*args, **kw):
            return orswot_fold_aligned.fold_merge(*args, u_cap=m, **kw)

        def pad_tiles(state):
            return orswot_fold_aligned.pad_to_tile(
                state, m, d, n_states=r + 1, u_cap=m
            )

        kernel_label = "pallas_aligned_fold"
    elif kernel_choice == "fused":
        fold_kernel = orswot_pallas.fold_merge

        def pad_tiles(state):
            return orswot_pallas.pad_to_tile(state, m, d, n_states=r + 1)

        kernel_label = "pallas_fused_fold"
    else:
        raise ValueError(
            f"CRDT_PALLAS_KERNEL={kernel_choice!r} is not aligned/fused"
        )

    # mirror the terminal-side compile helper's documented workaround
    # (reports/PALLAS_TPU_ATTEMPT.txt:12-14); harmless when unneeded
    os.environ.setdefault("TPU_ACCELERATOR_TYPE", "v5litepod-1")
    os.environ.setdefault("TPU_WORKER_HOSTNAMES", "localhost")
    try:
        if templates is None:
            # standalone call: rebuild the first template bench_north_star
            # would have handed over (same recipe, same RandomState seed);
            # only templates[0] is used since the single-template rewire
            reps = anti_entropy_fleets(
                rng, chunk, a, m, d, r,
                base=base, novel=novel, deferred_frac=deferred_frac,
            )
            templates = [
                tuple(jnp.stack([rep[k] for rep in reps]) for k in range(5))
            ]

        def fold_prebiased_roundtrip(stack):
            # the gate must validate the SAME compiled program the timing
            # runs: bias in, fold prebiased, unbias out
            biased = orswot_pallas.to_kernel_domain(stack)
            out = fold_kernel(
                *biased, m, d, interpret=False, prebiased=True
            )[:5]
            cdt = stack[0].dtype
            return (
                orswot_pallas.from_kernel_domain(out[0], cdt), out[1],
                orswot_pallas.from_kernel_domain(out[2], cdt), out[3],
                orswot_pallas.from_kernel_domain(out[4], cdt),
            )

        # parity gate BEFORE any timing — same oracle as the jnp fold,
        # through the prebiased compiled path the timing uses
        _north_star_parity(templates[0], r, a, m, d, fold_prebiased_roundtrip)

        # pre-pad to the Pallas tile AND pre-bias into the kernel's
        # int32 domain ONCE, outside the timed loop: fold_merge would
        # otherwise re-pad and re-convert (two full working-set copies,
        # ~2x the fold's own traffic) inside every chunk-fold.  XOR
        # salting commutes with the bias, so the salt chain is unchanged.
        # ONE template only: with both, XLA's layout copies around the
        # custom call put the program at 17.3 GB on a 16 GB chip (local
        # AOT memory analysis); one template + the salt chain is 8.8 GB
        # and the kernels are data-oblivious, so per-chunk distinctness
        # is cosmetic for the work measured.
        tpl = orswot_pallas.to_kernel_domain(pad_tiles(templates[0]))

        # Bridge path first: an axon-format executable of this exact
        # scan, self-banked by a previous bench run right after its
        # helper compile succeeded, sidesteps the remote-compile helper
        # entirely.  (The scalar-oracle sample gate above has already
        # passed this run before any banked timing is trusted.)
        if not SMALL:
            bridged = axon_bank.pallas_bridge_rate(tpl, n_chunks, chunk, r)
            if bridged is not None:
                return bridged, kernel_label

        def fold_biased(stack):
            return fold_kernel(
                *stack, m, d, interpret=False, prebiased=True
            )[:5]

        def salted_fold(tpl_, salt):
            return fold_biased((tpl_[0] ^ salt,) + tpl_[1:])

        def next_salt(acc):
            # biased domain: max is order-preserving, low bits unchanged
            return (jnp.max(acc[2]).astype(jnp.int32) & jnp.int32(7)) | jnp.int32(1)

        @jax.jit
        def run_chunks(tpl_):
            def body(carry, _):
                salt, _prev = carry
                o = salted_fold(tpl_, salt)
                return (next_salt(o), o), None

            init = (jnp.int32(1), tuple(x[0] for x in tpl_))
            (salt, out), _ = lax.scan(body, init, None, length=n_chunks)
            return out

        # explicit compile so the executable object is in hand for
        # axon-side banking (a plain first call would hide it)
        compiled = run_chunks.trace(tpl).lower().compile()
        out = compiled(tpl)
        jax.block_until_ready(out)  # warmup
        if not SMALL:
            axon_bank.pallas_bank_executable(compiled, n_chunks, chunk, r, out)
        sync_s = _sync_overhead()
        t0 = time.perf_counter()
        out = compiled(tpl)
        np.asarray(out[0].ravel()[0])
        t = max(time.perf_counter() - t0 - sync_s, 1e-9)
        rate = n_chunks * chunk * r / t
        log(
            f"north★ {kernel_label}: {t:.2f}s  {rate/1e6:.2f}M merges/s "
            f"(same scale/salt-chain as the jnp fold)"
        )
        return round(rate, 1), kernel_label
    except Exception as e:
        log(f"north★ pallas attempt failed (jnp headline stands): {str(e)[:300]}")
        return None


def bench_e2e_wire():
    """One timed end-to-end replication loop at north-star scale
    (VERDICT r4 item 3): wire blobs in → parse → anti-entropy fold to
    fixpoint → ``to_wire`` blobs out.  This is the TPU-native form of
    the reference's full replication story — the reference delegates
    transport to the user and replication is "serialize, ship, merge"
    (`/root/reference/src/lib.rs:62-83`).

    Two loops are timed on the same downshifted workload and both land
    in the JSON:

    * **serial** — the round-5 shape (``from_wire`` per fleet → fold →
      ``to_wire``), which allocates a fresh dense plane set per fleet.
      This is the loop whose ingest collapsed 160× in ``BENCH_r05.json``
      (root cause: allocation/page-fault churn, NOT a Python fallback —
      see PERF.md "wire-loop pipeline").
    * **pipelined** — :class:`crdt_tpu.batch.wireloop.PipelinedWireLoop`:
      reused staging buffers, background parse overlapped with the fold,
      ping-pong fold accumulators.  The headline ``e2e_wire_*`` fields
      come from this loop; ``pipeline: "overlapped"`` marks it.

    Per-stage ``native_fraction`` (and any fallback reasons) are
    reported from the tracing counters, so a silent-fallback regression
    is visible from the artifact alone.

    Shape mirrors the north star: R replica fleets of the same objects,
    processed in chunk-sized slices (the (R+1)-state working set must
    fit HBM); ONE chunk template's blob lists are cycled across chunks.
    Parity gates: on a sample of objects the pipelined loop's emitted
    blob must be BYTE-identical to ``to_binary`` of the scalar engine's
    left fold + self-merge plunger over ``from_binary`` of the input
    blobs; and the serial and pipelined loops must emit byte-identical
    chunks."""
    import jax

    from crdt_tpu.batch import OrswotBatch
    from crdt_tpu.batch.wireloop import PipelinedWireLoop
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.utils import tracing
    from crdt_tpu.utils.interning import Universe
    from crdt_tpu.utils.serde import from_binary, to_binary
    from crdt_tpu.utils.testdata import anti_entropy_fleets

    rng = np.random.RandomState(11)
    if SMALL:
        n, a, m, d, r, chunk = 2_000, 16, 8, 2, 4, 1_000
        base, novel = 4, 1
    else:
        n, a, m, d, r, chunk = 1_250_000, 64, 16, 2, 8, 62_500
        base, novel = 6, 1
    full_chunks = max(2, n // chunk)
    n_chunks = full_chunks
    if _downshift():
        n_chunks = min(n_chunks, 2)
    # the serial comparator re-pays its allocation churn every chunk, so
    # 2 chunks measure it faithfully; the pipelined loop runs the full
    # (downshifted) chunk count for the headline
    serial_chunks = min(n_chunks, 2)
    cfg = CrdtConfig(
        num_actors=a, member_capacity=m, deferred_capacity=d,
        counter_bits=32,
    )
    uni = Universe.identity(cfg)

    reps = anti_entropy_fleets(
        rng, chunk, a, m, d, r, base=base, novel=novel, deferred_frac=0.25,
    )
    # setup: encode each replica fleet to blobs via the native encoder
    # (the loop under test starts AT the blobs)
    rep_blobs = [OrswotBatch(*rep).to_wire(uni) for rep in reps]

    # best engine per backend, as the north star: on CPU the C++ row
    # kernels parse AND fold (bit-exact with orswot_ops.merge incl. slot
    # order), on accelerators the jitted jnp fold with async dispatch
    fold_path = None
    if (
        jax.default_backend() != "cpu"
        or os.environ.get("CRDT_SKIP_NATIVE_HEADLINE") == "1"
    ):
        fold_path = "jnp"
    loop = PipelinedWireLoop(uni, fold_path=fold_path)

    # --- parity gate: byte-identical blobs vs the scalar engine -------
    # through the SAME staged fold path the timing uses
    sample = list(range(4))
    sample_blobs = [[rep_blobs[rr][i] for i in sample] for rr in range(r)]
    got = loop.run([sample_blobs], overlap=False)["out_blobs"]
    for pos, i in enumerate(sample):
        acc = from_binary(rep_blobs[0][i])
        for rr in range(1, r):
            acc.merge(from_binary(rep_blobs[rr][i]))
        acc.merge(acc.clone())  # defer plunger (self-merge, as the fold)
        assert got[pos] == to_binary(acc), (
            f"e2e wire loop parity: object {i} blob != scalar fold blob"
        )
    log(
        "e2e wire parity sample: loop blobs == scalar fold blobs "
        f"(fold={loop.fold_path})"
    )

    # --- serial comparator (the round-5 loop, timed for the A/B) ------
    def serial_loop(chunks):
        stage = {"ingest": 0.0, "fold": 0.0, "egress": 0.0}
        blobs_out = None
        t_all0 = time.perf_counter()
        for _ in range(chunks):
            t0 = time.perf_counter()
            fleets = [OrswotBatch.from_wire(blobs, uni) for blobs in rep_blobs]
            stage["ingest"] += time.perf_counter() - t0
            t0 = time.perf_counter()
            names = ("clock", "ids", "dots", "d_ids", "d_clocks")
            if loop.fold_path == "native":
                staged = [
                    tuple(np.asarray(getattr(f, nm)) for nm in names)
                    for f in fleets
                ]
                acc = staged[0]
                for rr in range(1, r):
                    acc = loop._merge_native(
                        acc, staged[rr], loop._pingpong[(rr - 1) & 1]
                    )
                acc = loop._merge_native(acc, acc, loop._pingpong[(r - 1) & 1])
            else:
                # keep the planes device-resident, as the round-5 serial
                # loop did — a np.asarray round-trip here would charge
                # the comparator D2H transfers the old loop never paid
                staged = [
                    tuple(getattr(f, nm) for nm in names) for f in fleets
                ]
                acc = staged[0]
                for rr in range(1, r):
                    acc = loop._merge_jnp(acc, staged[rr])
                acc = loop._merge_jnp(acc, acc)
                if loop._overflow is not None:
                    # the comparator's own overflow must raise HERE, not
                    # leak into the pipelined run's first round
                    from crdt_tpu.error import raise_for_overflow

                    ov, loop._overflow = loop._overflow, None
                    raise_for_overflow(ov, "e2e serial fold")
            stage["fold"] += time.perf_counter() - t0
            t0 = time.perf_counter()
            blobs_out = loop._egress(acc)
            stage["egress"] += time.perf_counter() - t0
        return time.perf_counter() - t_all0, stage, blobs_out

    # warmup: one full untimed iteration of each loop so kernel compiles
    # and buffer pools exist OUTSIDE the timed regions (the serial
    # comparator borrows the loop's fold/egress primitives — one
    # implementation under test — so its buffers must exist first)
    loop._ensure_buffers(chunk)
    serial_loop(1)
    warm = loop.run([rep_blobs], overlap=True)

    serial_s, serial_stage, serial_blobs = serial_loop(serial_chunks)

    # --- the timed pipelined loop -------------------------------------
    counters0 = tracing.counters()
    res = loop.run([rep_blobs] * n_chunks, overlap=True)
    e2e_s = res["e2e_s"]
    assert len(res["out_blobs"]) == chunk
    # serial and pipelined must emit byte-identical chunks (same blobs
    # in, same fold, same encoder)
    assert res["out_blobs"] == serial_blobs, (
        "e2e wire: pipelined chunk != serial chunk"
    )

    merges = res["merges"]
    speedup = (serial_s / serial_chunks) / (e2e_s / n_chunks)
    log(
        f"e2e wire pipelined: {merges} replica-objects blobs-in→blobs-out "
        f"in {e2e_s:.2f}s (parse {res['stage_s']['parse']:.2f} fold "
        f"{res['stage_s']['fold']:.2f} egress {res['stage_s']['egress']:.2f})"
        f" = {merges/e2e_s/1e6:.2f}M merges/s end-to-end; serial comparator "
        f"{serial_s:.2f}s/{serial_chunks} chunks (ingest "
        f"{serial_stage['ingest']:.2f} fold {serial_stage['fold']:.2f} "
        f"egress {serial_stage['egress']:.2f}) -> pipelined is "
        f"{speedup:.2f}x per chunk"
    )
    deltas = tracing.counters_since(counters0)
    out = {
        "e2e_wire_s": round(e2e_s, 2),
        "e2e_wire_replica_objects": merges,
        "e2e_wire_merges_per_sec": round(merges / e2e_s, 1),
        "e2e_wire_ingest_s": round(res["stage_s"]["parse"], 2),
        "e2e_wire_fold_s": round(res["stage_s"]["fold"], 2),
        "e2e_wire_egress_s": round(res["stage_s"]["egress"], 2),
        "e2e_wire_fold_path": loop.fold_path,
        "pipeline": res["pipeline"],
        "e2e_wire_serial_s": round(serial_s, 2),
        "e2e_wire_serial_chunks": serial_chunks,
        "e2e_wire_serial_ingest_s": round(serial_stage["ingest"], 2),
        "e2e_wire_serial_fold_s": round(serial_stage["fold"], 2),
        "e2e_wire_serial_egress_s": round(serial_stage["egress"], 2),
        "e2e_wire_pipeline_speedup": round(speedup, 2),
    }
    # same-shape parse microbench: ONE fleet through the same warm
    # staging buffers, isolated from the loop — the in-artifact
    # reference the e2e ingest rate is judged against (done-bar: e2e
    # ingest within ~2x of the microbench on IDENTICAL shapes; the old
    # 160x gap was vs a 2-member/A=16 synthetic microbench)
    from crdt_tpu.batch.wirebulk import orswot_planes_from_wire

    t0 = time.perf_counter()
    probe_planes = orswot_planes_from_wire(
        rep_blobs[0], uni, out=loop._staging[0] if loop._staging else None
    )
    t_probe = max(time.perf_counter() - t0, 1e-9)
    if probe_planes is not None:
        # None = no native fast path at all — a microsecond no-op whose
        # "rate" would be garbage in the artifact
        out["e2e_shape_ingest_obj_per_sec"] = round(chunk / t_probe, 1)
    if res["stage_s"]["parse"] > 0:
        out["e2e_wire_parse_obj_per_sec"] = round(
            n_chunks * r * chunk / res["stage_s"]["parse"], 1
        )

    nf_in = res["ingest_native_fraction"]
    nf_out = res["egress_native_fraction"]
    if nf_in is not None:
        out["e2e_wire_ingest_native_fraction"] = round(nf_in, 4)
    if nf_out is not None:
        out["e2e_wire_egress_native_fraction"] = round(nf_out, 4)
    reasons = {
        k: v for k, v in deltas.items() if ".fallback_reason." in k
    }
    if reasons:
        out["e2e_wire_fallback_reasons"] = reasons
    if n_chunks < full_chunks:
        out["e2e_wire_downshift"] = f"{n_chunks}/{full_chunks}"
    del warm
    return out


def bench_sync():
    """Digest-driven delta anti-entropy at bench-fleet shape (the
    `crdt_tpu.sync` subsystem): two replicas of the same fleet diverge
    on 1% of objects per round, then reconcile through a
    :class:`~crdt_tpu.sync.SyncSession` — digest vectors first, then
    only the diverged rows' wire blobs.

    The headline number is ``sync_delta_ratio``: payload bytes the delta
    session shipped over what a full-state exchange ships for the same
    fleet (the pre-sync replication cost).  At 1% divergence the done-bar
    is ≤ 0.10; a ratio drifting toward 1.0 means the delta path
    degenerated (digest churn, fallback storms) and
    ``benchkit/artifacts.py`` flags the movement round-over-round like
    any other metric.  Parity gate: the reconciled fleets must be
    byte-identical to the plain full-state merge of the same inputs."""
    import jax

    from crdt_tpu.batch import OrswotBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.sync.session import SyncSession, sync_pair
    from crdt_tpu.utils import tracing
    from crdt_tpu.utils.interning import Universe
    from crdt_tpu.utils.testdata import anti_entropy_fleets

    rng = np.random.RandomState(13)
    if SMALL:
        n, a, m, d = 2_000, 16, 8, 2
    else:
        n, a, m, d = 62_500, 64, 16, 2
    divergence = 0.01
    cfg = CrdtConfig(
        num_actors=a, member_capacity=m, deferred_capacity=d,
        counter_bits=32,
    )
    uni = Universe.identity(cfg)

    import jax.numpy as jnp

    reps = anti_entropy_fleets(
        rng, n, a, m, d, 1, base=min(4, m - 2), novel=0, deferred_frac=0.25,
    )
    fleet_a = OrswotBatch(*(jnp.asarray(x) for x in reps[0]))
    # canonicalize: testdata plants some already-applicable deferred
    # removes straight into the planes; one plunger self-merge flushes
    # them so merge is idempotent on the fleet and the byte-parity gate
    # below compares like with like
    fleet_a = fleet_a.merge(fleet_a)
    # replica B: same state, plus local ops on a 1% row sample — the
    # per-round divergence the digest exchange must localize
    k = max(1, int(n * divergence))
    rows = np.sort(rng.choice(n, size=k, replace=False)).astype(np.int64)
    sub = jax.tree_util.tree_map(lambda p: p[rows], fleet_a)
    counters = jnp.max(sub.clock, axis=-1) + 1
    sub = sub.apply_add(
        np.zeros(k, np.int32), counters,
        np.full(k, 1 << 20, np.int32),
    )
    fleet_b = jax.tree_util.tree_map(
        lambda p, s: p.at[rows].set(s), fleet_a, sub
    )

    # full-state reference: what the pre-sync protocol ships each round
    full_bytes = sum(len(b) for b in fleet_a.to_wire(uni))

    counters0 = tracing.counters()
    sa = SyncSession(fleet_a, uni, full_state_bytes=full_bytes)
    sb = SyncSession(fleet_b, uni, full_state_bytes=full_bytes)
    t0 = time.perf_counter()
    ra, rb = sync_pair(sa, sb)
    wall = time.perf_counter() - t0
    deltas = tracing.counters_since(counters0)

    assert ra.converged and rb.converged, "sync session did not converge"
    # parity gate: the reconciled fleets must equal the full-state merge
    # byte-for-byte (sampled to keep the gate cheap at full scale)
    ref = fleet_a.merge(fleet_b)
    sample = np.concatenate([rows[:8], np.arange(min(8, n))])
    from crdt_tpu.sync.delta import gather_blobs

    want = gather_blobs(ref, sample, uni)
    assert gather_blobs(sa.batch, sample, uni) == want, (
        "sync parity: session fleet != full-state merge (peer A)"
    )
    assert gather_blobs(sb.batch, sample, uni) == want, (
        "sync parity: session fleet != full-state merge (peer B)"
    )

    payload_bytes = ra.delta_bytes_sent + ra.full_bytes_sent
    ratio = tracing.delta_ratio(payload_bytes, full_bytes)
    log(
        f"sync: {n} objects, {ra.diverged} diverged ({divergence:.0%}) -> "
        f"digest {ra.digest_bytes_sent}B + delta {ra.delta_bytes_sent}B vs "
        f"full-state {full_bytes}B per round; delta_ratio={ratio:.4f} "
        f"(wall {wall:.2f}s, fallback={ra.full_state_fallback})"
    )
    if ratio is not None and ratio > 0.10:
        log(
            f"sync WARNING: delta_ratio {ratio:.3f} > 0.10 at 1% divergence "
            "— the delta path is degenerating (see PERF.md sync section)"
        )
    out = {
        "sync_objects": n,
        "sync_diverged_objects": ra.diverged,
        "sync_delta_ratio": round(ratio, 4) if ratio is not None else None,
        "sync_digest_bytes": ra.digest_bytes_sent,
        "sync_delta_bytes": payload_bytes,
        "sync_full_state_bytes": full_bytes,
        "sync_wall_s": round(wall, 3),
        "sync_full_state_fallback": bool(
            ra.full_state_fallback or rb.full_state_fallback
        ),
    }
    reasons = {k: v for k, v in deltas.items() if ".fallback_reason." in k}
    if reasons:
        out["sync_fallback_reasons"] = reasons
    return out


def bench_digest_tree():
    """Hierarchical digest trees vs the flat digest exchange (the
    `crdt_tpu.sync.tree` subsystem): digest bytes per round at 0 /
    0.1% / 1% / 10% / 100% divergence, uniform AND hot-key (Zipf)
    shaped, on a live fleet plus a planner-level 1M-object rung.

    Headline ratios (``tree_ratio_*``: tree-mode digest bytes per round
    over ONE flat digest frame, per side):

    * converged: the O(log N) claim at its best — one root frame
      instead of u64[N]; done-bar ≤ 0.05.
    * 1% uniform: descent's worst realistic shape (every top subtree
      dirty); done-bar ≤ 0.15.  Hot-key divergence (Zipf 1.2 — same
      diverged-row count clustered into few subtrees) is reported next
      to it and must come in cheaper.
    * dense (100%): the cutover guarantee — total tree bytes never
      regress past flat + one root frame.

    Parity gates: every tree session must converge, and the 1%-uniform
    tree-mode fleets must end digest-identical to flat-mode sessions
    reconciling the same inputs."""
    import jax

    from crdt_tpu.batch import OrswotBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.sync import digest as digest_mod
    from crdt_tpu.sync import tree as tree_mod
    from crdt_tpu.sync.delta import encode_digest_frame
    from crdt_tpu.sync.session import SyncSession, sync_pair
    from crdt_tpu.utils.interning import Universe
    from crdt_tpu.utils.testdata import anti_entropy_fleets
    from crdt_tpu.utils.workload import WorkloadGen

    rng = np.random.RandomState(17)
    if SMALL:
        n, n_sim = 8_192, 65_536
    else:
        n, n_sim = 65_536, 1_048_576
    a, m, d = 16, 8, 2
    cfg = CrdtConfig(num_actors=a, member_capacity=m, deferred_capacity=d,
                     counter_bits=32)
    uni = Universe.identity(cfg)

    import jax.numpy as jnp

    reps = anti_entropy_fleets(
        rng, n, a, m, d, 1, base=min(4, m - 2), novel=0, deferred_frac=0.25,
    )
    fleet_a = OrswotBatch(*(jnp.asarray(x) for x in reps[0]))
    fleet_a = fleet_a.merge(fleet_a)  # canonicalize (plunger), as bench_sync

    def diverge(rows):
        k = rows.shape[0]
        sub = jax.tree_util.tree_map(lambda p: p[rows], fleet_a)
        counters = jnp.max(sub.clock, axis=-1) + 1
        sub = sub.apply_add(
            np.zeros(k, np.int32), counters, np.full(k, 1 << 20, np.int32))
        return jax.tree_util.tree_map(
            lambda p, s: p.at[rows].set(s), fleet_a, sub)

    # the flat reference: ONE digest frame (lanes + version vector),
    # the fixed per-round cost the tree replaces
    t0 = time.perf_counter()
    tree_a = tree_mod.build_tree(digest_mod.digest_of(fleet_a, uni))
    build_ms = (time.perf_counter() - t0) * 1e3
    flat_bytes = len(encode_digest_frame(
        digest_mod.digest_of(fleet_a, uni),
        digest_mod.version_vector(fleet_a)))

    shapes = [("converged", 0.0, None), ("0p1", 0.001, None),
              ("1", 0.01, None), ("1_hot", 0.01, 1.2),
              ("10", 0.1, None), ("dense", 1.0, None)]
    out = {"tree_objects": n, "tree_flat_digest_bytes": flat_bytes,
           "tree_build_ms": round(build_ms, 2)}
    flat_1pct_digest = None
    for label, frac, zipf in shapes:
        k = int(n * frac)
        if k:
            if zipf:
                rows = WorkloadGen(n, seed=23, zipf_s=zipf).sample_rows(k)
            else:
                rows = np.sort(rng.choice(n, size=k, replace=False)
                               ).astype(np.int64)
            fleet_b = diverge(rows)
        else:
            fleet_b = fleet_a
        sa = SyncSession(fleet_a, uni, digest_tree=True)
        sb = SyncSession(fleet_b, uni, digest_tree=True)
        t0 = time.perf_counter()
        ra, rb = sync_pair(sa, sb)
        wall = time.perf_counter() - t0
        assert ra.converged and rb.converged, f"tree sync ({label})"
        assert ra.tree_mode, f"session did not negotiate tree mode ({label})"
        ratio = ra.tree_bytes_sent / flat_bytes
        out[f"tree_ratio_{label}"] = round(ratio, 4)
        log(
            f"digest_tree[{label}]: {k} diverged -> tree {ra.tree_bytes_sent}B"
            f" vs flat-frame {flat_bytes}B (ratio {ratio:.4f}, "
            f"levels {ra.tree_levels}, subtrees {ra.subtrees_diverged}, "
            f"wall {wall:.2f}s)"
        )
        if label == "1":
            # parity: flat-mode sessions on the same inputs end
            # digest-identical to the descent-mode fleets
            fa, fb = SyncSession(fleet_a, uni), SyncSession(fleet_b, uni)
            rfa, _ = sync_pair(fa, fb)
            assert rfa.converged
            flat_1pct_digest = rfa.digest_bytes_sent
            assert np.array_equal(
                digest_mod.digest_of(sa.batch, uni),
                digest_mod.digest_of(fa.batch, uni),
            ), "tree-mode fleet != flat-mode fleet at 1% divergence"
    if flat_1pct_digest:
        out["tree_flat_session_digest_bytes_1"] = flat_1pct_digest

    # acceptance bars
    if out["tree_ratio_converged"] > 0.05:
        log(f"digest_tree WARNING: converged ratio "
            f"{out['tree_ratio_converged']:.4f} > 0.05")
    if out["tree_ratio_1"] > 0.15:
        log(f"digest_tree WARNING: 1%-uniform ratio "
            f"{out['tree_ratio_1']:.4f} > 0.15")
    root_frame = 8 + 4 * (tree_mod.root_frame_lanes(tree_a) - 1) + 14 + a * 8
    assert out["tree_ratio_dense"] * flat_bytes <= flat_bytes + root_frame, (
        "dense divergence regressed past flat + one root frame"
    )

    # planner rung: 1M-object descent byte-accounting on synthetic
    # digest vectors (the fleet itself would not fit a bench box)
    base = rng.randint(0, 1 << 31, size=n_sim).astype(np.uint64)
    sim_tree = tree_mod.build_tree(base)
    sim_flat = 8 * n_sim
    for label, frac, zipf in [("converged", 0.0, None), ("0p1", 0.001, None),
                              ("1", 0.01, None), ("1_hot", 0.01, 1.2)]:
        k = int(n_sim * frac)
        peer = base.copy()
        if k:
            if zipf:
                rows = WorkloadGen(n_sim, seed=29, zipf_s=zipf).sample_rows(k)
            else:
                rows = rng.choice(n_sim, size=k, replace=False)
            # DISTINCT nonzero deltas per row: a shared constant would
            # XOR-cancel in any parent with two diverged children and
            # fake descent into missing real divergence
            peer[rows] ^= (rng.randint(1, 1 << 31, size=k).astype(np.uint64)
                           << np.uint64(16)) | np.uint64(1)
        leaves, stats = tree_mod.simulate_descent(
            sim_tree, tree_mod.build_tree(peer), flat_bytes=sim_flat)
        out[f"tree_sim_ratio_{label}_1m"] = round(
            stats.payload_bytes / sim_flat, 4)
        log(f"digest_tree[sim {n_sim} {label}]: {k} diverged -> "
            f"{stats.payload_bytes}B vs flat {sim_flat}B "
            f"(ratio {stats.payload_bytes / sim_flat:.4f}, "
            f"levels {stats.levels})")
    return out


def bench_oplog():
    """Op-based write front-end (the `crdt_tpu.oplog` subsystem): user
    writes as columnar op batches folded into the dense planes by the
    scatter-fold kernel, instead of arriving as state blobs.

    Reports ops/s through ``OpApplier.apply_ops`` at 1k/16k/64k-op
    batches (each fold is ONE jitted scatter — ``oplog_apply_steps``
    pins that), plus the wire economics: bytes/op through the op-frame
    codec against what delta sync pays to move the same writes (the
    one-side session cost — two digest frames over the whole fleet plus
    the diverged-row delta frame — per touched object).  The done-bar
    is ``oplog_vs_delta_ratio <= 0.10``: an op frame must cost at most
    10% of the per-object delta-sync cost, or the op path has no reason
    to exist.  Parity gate: a sampled op batch folded by the kernel
    must digest-match the scalar engine applying the same ops one at a
    time (`orswot.rs:60-83`)."""
    import jax

    from crdt_tpu.batch import OrswotBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.oplog import OpApplier, derive_add_ctx, encode_ops_frame
    from crdt_tpu.scalar.orswot import Orswot
    from crdt_tpu.sync import digest as digest_mod
    from crdt_tpu.sync.delta import (
        encode_delta_frame, encode_digest_frame, gather_blobs,
    )
    from crdt_tpu.utils.interning import Universe

    rng = np.random.RandomState(17)
    if SMALL:
        n, a, m, batches, reps = 4_096, 16, 16, (256, 1_024, 4_096), 3
    else:
        n, a, m, batches, reps = 65_536, 64, 16, (1_000, 16_384, 65_536), 5
    cfg = CrdtConfig(num_actors=a, member_capacity=m, deferred_capacity=2,
                     counter_bits=32)
    uni = Universe.identity(cfg)

    # a realistic fleet: objects carry history (multi-member, multi-
    # actor clocks), because that is exactly when re-shipping state per
    # write is expensive and ops win
    import jax.numpy as jnp

    from crdt_tpu.utils.testdata import anti_entropy_fleets

    reps_planes = anti_entropy_fleets(
        rng, n, a, m, 2, 1, base=min(10, m - 4), novel=0,
    )
    fleet = OrswotBatch(*(jnp.asarray(x) for x in reps_planes[0]))
    fleet = fleet.merge(fleet)  # canonicalize (plunger), as bench_sync

    # -- parity gate vs the scalar engine (always runs with the stage) --
    k = 48
    pobj = rng.randint(0, 64, k)
    pactor = rng.randint(0, a, k).astype(np.int32)
    pmember = rng.randint(1 << 16, (1 << 16) + 6, k).astype(np.int32)
    head = jax.tree_util.tree_map(lambda p: p[:64], fleet)
    pops, _ = derive_add_ctx(np.asarray(head.clock), pobj, pactor,
                             member=pmember)
    folded_head, prep = OpApplier(uni).apply_ops(head, pops)
    scal = head.to_scalar(uni)
    for i in range(k):
        o = scal[int(pobj[i])]
        o.apply(o.add(int(pmember[i]),
                      o.value().derive_add_ctx(int(pactor[i]))))
    ref_head = OrswotBatch.from_scalar(scal, uni)
    assert np.array_equal(
        np.asarray(digest_mod.digest_of(folded_head)),
        np.asarray(digest_mod.digest_of(ref_head)),
    ), "oplog parity: scatter-fold != scalar apply loop"
    assert prep.merge_steps == 1 and prep.still_parked == 0, prep

    # -- throughput: ops/s per batch size -------------------------------
    out = {"oplog_objects": n}
    clock_host = np.asarray(fleet.clock)
    rates = {}
    steps_16k = None
    ops_by_b = {}
    for b in batches:
        ops, _ = derive_add_ctx(
            clock_host, rng.randint(0, n, b),
            rng.randint(0, a, b).astype(np.int32),
            member=rng.randint(1 << 16, (1 << 16) + 4, b).astype(np.int32),
        )
        ops_by_b[b] = ops
        applier = OpApplier(uni)
        folded, rep = applier.apply_ops(fleet, ops)  # warm/compile
        jax.block_until_ready(folded.clock)
        assert rep.still_parked == 0, rep
        t0 = time.perf_counter()
        for _ in range(reps):
            folded, rep = applier.apply_ops(fleet, ops)
        jax.block_until_ready(folded.clock)
        wall = time.perf_counter() - t0
        rates[b] = b * reps / wall
        if b == batches[1]:
            steps_16k = rep.merge_steps
        log(f"oplog: {b} ops -> {rates[b]:,.0f} ops/s "
            f"({rep.merge_steps} scatter step, rm_rounds={rep.rm_rounds})")
    out["oplog_apply_ops_per_sec"] = round(max(rates.values()))
    out["oplog_apply_ops_per_sec_small"] = round(rates[batches[0]])
    out["oplog_apply_steps"] = steps_16k

    # -- wire economics: op frame vs the delta-sync equivalent ----------
    b_mid = batches[1]
    ops = ops_by_b[b_mid]
    frame = encode_ops_frame(ops)
    bytes_per_op = len(frame) / b_mid
    folded, _ = OpApplier(uni).apply_ops(fleet, ops)
    touched = np.unique(ops.obj)
    # what ONE side of a delta session pays to move the same writes:
    # two digest frames over the whole fleet (phase 1 + converged
    # check) and the diverged rows' delta frame
    digest_frame = encode_digest_frame(
        np.asarray(digest_mod.digest_of(folded), dtype=np.uint64))
    delta_frame = encode_delta_frame(
        n, touched, gather_blobs(folded, touched, uni))
    delta_total = 2 * len(digest_frame) + len(delta_frame)
    delta_per_obj = delta_total / touched.size
    ratio = bytes_per_op / delta_per_obj
    out["oplog_bytes_per_op"] = round(bytes_per_op, 2)
    out["oplog_delta_bytes_per_object"] = round(delta_per_obj, 2)
    out["oplog_vs_delta_ratio"] = round(ratio, 4)
    log(
        f"oplog: {bytes_per_op:.1f} B/op on the wire vs "
        f"{delta_per_obj:.1f} B/object delta-sync equivalent "
        f"({touched.size} touched objects) -> ratio {ratio:.3f}"
    )
    if ratio > 0.10:
        log(
            f"oplog WARNING: wire bytes/op is {ratio:.1%} of the "
            "per-object delta-sync cost (bar: 10%) — the op frame "
            "degenerated or the fleet shape got too lean (see PERF.md "
            "op-based replication section)"
        )
    return out


def bench_reads():
    """Batched read front-end (the `crdt_tpu.serve` subsystem): client
    reads resolved straight from the dense planes by ONE jitted gather
    per batch, instead of cloning objects back to the scalar engine.

    Reports reads/s at 1k/16k/64k-object fleets under the Zipf mixed
    read/write workload (``WorkloadGen.draw_mixed`` — the same key
    stream drives both sides), with ops/s through the scatter-fold
    alongside so the artifact shows the read and write front-ends from
    the same round.  Parity gate: a ≥4k-read batch (mixed ``contains``
    and ``value()`` reads) must come back byte-identical — val,
    add-clock and rm-clock rows — to the scalar ``ReadCtx`` loop
    (`orswot.rs:60-83` read semantics)."""
    import jax
    import jax.numpy as jnp

    from crdt_tpu import serve
    from crdt_tpu.batch import OrswotBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.oplog import OpApplier, derive_add_ctx
    from crdt_tpu.utils.interning import Universe
    from crdt_tpu.utils.testdata import anti_entropy_fleets
    from crdt_tpu.utils.workload import WorkloadGen

    rng = np.random.RandomState(23)
    if SMALL:
        a, m, ladder, batch, reps = 16, 16, (1_024, 4_096), 2_048, 3
    else:
        a, m, ladder, batch, reps = 64, 16, (1_024, 16_384, 65_536), \
            8_192, 5
    cfg = CrdtConfig(num_actors=a, member_capacity=m, deferred_capacity=2,
                     counter_bits=32)
    uni = Universe.identity(cfg)

    # -- parity gate vs the scalar ReadCtx loop (always runs) -----------
    # a 256-object head with real history, read 4096 times (the
    # acceptance bar: one gather step resolving a >=4k batch)
    head_n, preads = 256, 4_096
    head_planes = anti_entropy_fleets(
        rng, head_n, a, m, 2, 1, base=min(10, m - 4), novel=0,
    )[0]
    head = OrswotBatch(*(jnp.asarray(x) for x in head_planes))
    head = head.merge(head)  # canonicalize, as bench_sync/bench_oplog
    scal = head.to_scalar(uni)
    pobj = rng.randint(0, head_n, preads)
    # half contains() on plausible members, half value() reads
    pmember = rng.randint(0, 2 * m, preads).astype(np.int32)
    pmember[rng.random_sample(preads) < 0.5] = serve.NO_MEMBER
    frame = serve.gather(head, pobj, member=pmember)

    def _row(vc) -> np.ndarray:
        r = np.zeros(a, np.uint64)
        for actor, cnt in vc.dots.items():
            r[int(actor)] = cnt
        return r

    bad = 0
    for i in range(preads):
        o = scal[int(pobj[i])]
        if pmember[i] == serve.NO_MEMBER:
            rc = o.value()
            want_val = len(rc.val)
        else:
            rc = o.contains(int(pmember[i]))
            want_val = int(bool(rc.val))
        if int(frame.val[i]) != want_val or \
                not np.array_equal(frame.add_clock[i], _row(rc.add_clock)) \
                or not np.array_equal(frame.rm_clock[i],
                                      _row(rc.rm_clock)):
            bad += 1
    assert bad == 0, \
        f"serve parity: {bad}/{preads} gathered reads != scalar ReadCtx"

    # -- throughput: mixed reads/s + ops/s per fleet size ---------------
    out = {"serve_parity_rows": preads}
    read_rates, op_rates = {}, {}
    for n in ladder:
        planes = anti_entropy_fleets(
            rng, n, a, m, 2, 1, base=min(10, m - 4), novel=0,
        )[0]
        fleet = OrswotBatch(*(jnp.asarray(x) for x in planes))
        fleet = fleet.merge(fleet)
        clock_host = np.asarray(fleet.clock)
        gen = WorkloadGen(n, seed=29, zipf_s=1.1, burst_len=4,
                          read_frac=0.5)
        keys, is_read = gen.draw_mixed(batch * reps)
        rkeys, wkeys = keys[is_read], keys[~is_read]
        rmember = rng.randint(0, 2 * m, rkeys.size).astype(np.int32)
        rmember[rng.random_sample(rkeys.size) < 0.25] = serve.NO_MEMBER
        ops, _ = derive_add_ctx(
            clock_host, wkeys,
            rng.randint(0, a, wkeys.size).astype(np.int32),
            member=rng.randint(1 << 16, (1 << 16) + 4,
                               wkeys.size).astype(np.int32),
        )
        applier = OpApplier(uni)

        def _read_pass():
            done = 0
            while done < rkeys.size:
                f = serve.gather(fleet, rkeys[done:done + batch],
                                 member=rmember[done:done + batch])
                done += min(batch, rkeys.size - done)
            return f

        # warm/compile both legs off the clock (the tail gather pads to
        # a second pow2 shape, so a full pass is the honest warm-up)
        f = _read_pass()
        folded, _ = applier.apply_ops(fleet, ops)
        jax.block_until_ready((f.val, folded.clock))
        t0 = time.perf_counter()
        f = _read_pass()
        jax.block_until_ready(f.val)
        read_wall = time.perf_counter() - t0
        t0 = time.perf_counter()
        folded, _ = applier.apply_ops(fleet, ops)
        jax.block_until_ready(folded.clock)
        op_wall = time.perf_counter() - t0
        read_rates[n] = rkeys.size / read_wall
        op_rates[n] = wkeys.size / op_wall
        log(f"serve: {n} objects -> {read_rates[n]:,.0f} reads/s "
            f"({rkeys.size} reads in {batch}-row gathers), "
            f"{op_rates[n]:,.0f} ops/s alongside")
    out["serve_objects"] = max(ladder)
    out["serve_reads_per_sec"] = round(max(read_rates.values()))
    out["serve_reads_per_sec_small"] = round(read_rates[ladder[0]])
    out["serve_mixed_ops_per_sec"] = round(max(op_rates.values()))
    out["serve_read_batch"] = batch
    return out


def bench_obs_overhead():
    """Always-on observability cost gate (the obs subsystem's bench
    satellite): the counters/gauges/events added across the wire and
    sync paths are deliberately per-BULK-call, so their total cost must
    be noise.  This stage measures the per-operation cost of each
    always-on instrument (registry-forwarded counter increment,
    ``record_sync`` with its frame-size histogram, gauge set, flight-
    recorder append), scales it by a deliberately generous per-fleet
    operation count for the e2e wire workload, and asserts the result
    is <1% of the measured ``bench_e2e_wire`` wall time.  If counting
    ever regresses to per-blob (the failure mode this gate exists for),
    the scaled estimate blows through 1% immediately."""
    from crdt_tpu.obs import events as obs_events
    from crdt_tpu.obs import metrics as obs_metrics
    from crdt_tpu.utils import tracing

    iters = 20_000 if SMALL else 100_000

    def per_op(fn):
        t0 = time.perf_counter()
        for i in range(iters):
            fn(i)
        return (time.perf_counter() - t0) / iters

    count_s = per_op(lambda i: tracing.count("obs.overhead.count_probe"))
    sync_s = per_op(
        lambda i: tracing.record_sync("probe", nbytes=1024, objects=1)
    )
    g = obs_metrics.registry().gauge("obs.overhead.gauge_probe")
    gauge_s = per_op(g.set)
    rec = obs_events.FlightRecorder(capacity=256)  # private ring: the
    # probe must not wash real sessions out of the global recorder
    event_s = per_op(lambda i: rec.record("obs.overhead.event_probe", n=i))
    out = {
        "obs_count_ns": round(count_s * 1e9, 1),
        "obs_record_sync_ns": round(sync_s * 1e9, 1),
        "obs_gauge_set_ns": round(gauge_s * 1e9, 1),
        "obs_event_ns": round(event_s * 1e9, 1),
    }
    log(
        f"obs overhead: count {out['obs_count_ns']}ns  record_sync "
        f"{out['obs_record_sync_ns']}ns  gauge {out['obs_gauge_set_ns']}ns  "
        f"event {out['obs_event_ns']}ns per op"
    )

    e2e_s = _JSON_STATE.get("e2e_wire_s")
    if e2e_s:
        # the e2e workload shape, re-derived as bench_e2e_wire derives it
        if SMALL:
            n, chunk, r = 2_000, 1_000, 4
        else:
            n, chunk, r = 1_250_000, 62_500, 8
        n_chunks = max(2, n // chunk)
        if _downshift():
            n_chunks = min(n_chunks, 2)
        # ~10 always-on ops actually fire per fleet in the e2e loop
        # (record_wire counts, native engine call counters, wireloop
        # gauges — all per BULK call); 32 is the headroom that keeps the
        # gate meaningful without flaking.  record_sync is per sync
        # frame, not part of this loop — reported above, gated out.
        ops = n_chunks * r * 32
        worst = max(count_s, gauge_s, event_s)
        frac = ops * worst / e2e_s
        out["obs_overhead_frac"] = round(frac, 6)
        log(
            f"obs overhead: {ops} ops x {worst*1e9:.0f}ns = "
            f"{ops*worst*1e3:.2f}ms vs e2e_wire {e2e_s:.2f}s "
            f"-> {frac:.4%} (bar: <1%)"
        )
        # only gate against a reference big enough to be a denominator:
        # a SMALL/smoke e2e finishes in ~10ms, where fixed microsecond
        # costs are a huge fraction of nothing
        if e2e_s >= 0.5:
            assert frac < 0.01, (
                f"always-on observability costs {frac:.2%} of "
                "bench_e2e_wire wall time (bar: <1%) — did counting "
                "regress to per-blob?"
            )
        else:
            log(
                f"obs overhead: e2e_wire {e2e_s}s too small to gate "
                "against (smoke shape); per-op costs recorded"
            )
    else:
        log("obs overhead: e2e_wire did not run; per-op costs only")
    return out


def bench_latency():
    """Latency-observatory stage (budget-skippable): fault-injected
    50/100/200 ms-RTT delay links driving real sync sessions, reporting
    session wall vs the transport's measured SRTT, the profiler's
    network_wait_frac, and write-to-visible lag percentiles; plus the
    adaptive-vs-static retransmit story (adaptive RTO tighter than the
    static timer on loopback, retransmit count not regressing at
    200 ms RTT) and the always-on profiler/stamp overhead gate (<1% of
    ``bench_e2e_wire`` wall, the bench_obs_overhead discipline).

    The windowed-ARQ flip (ISSUE 16) turns the 100 ms rung from a
    measurement into a GATE: a shaped session must finish ≤3x RTT with
    ``network_wait_frac`` < 0.5 (stop-and-wait ran ~5-10x RTT at >90%
    network wait — those numbers stay in the artifact as
    ``latency_100ms_stopwait_*`` for the regression diff), and a
    diverged digest-tree descent must complete in ≤2 RTT-equivalents
    (``tree_round_trips`` from the session report: one root exchange
    plus one speculative blast)."""
    import dataclasses
    import threading

    import jax.numpy as jnp

    from crdt_tpu.batch import OrswotBatch
    from crdt_tpu.cluster import ResilientTransport, RetryPolicy, queue_pair
    from crdt_tpu.cluster.faults import (
        FaultPlan, FaultyTransport, LatencyTransport, latency_pair,
    )
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.obs.latency import LagTracker, SessionProfile
    from crdt_tpu.sync.session import SyncSession
    from crdt_tpu.utils.interning import Universe
    from crdt_tpu.utils.testdata import anti_entropy_fleets

    rng = np.random.RandomState(23)
    n, a, m, d = (512, 8, 8, 2) if SMALL else (4096, 16, 8, 2)
    cfg = CrdtConfig(num_actors=a, member_capacity=m, deferred_capacity=d,
                     counter_bits=32)
    uni = Universe.identity(cfg)

    def diverged_pair():
        import jax

        reps = anti_entropy_fleets(rng, n, a, m, d, 1, base=min(4, m - 2),
                                   novel=0, deferred_frac=0.25)
        fa = OrswotBatch(*(jnp.asarray(x) for x in reps[0]))
        fa = fa.merge(fa)
        k = max(1, n // 100)
        rows = np.sort(rng.choice(n, size=k, replace=False)).astype(np.int64)
        sub = jax.tree_util.tree_map(lambda p: p[rows], fa)
        sub = sub.apply_add(np.zeros(k, np.int32),
                            jnp.max(sub.clock, axis=-1) + 1,
                            np.full(k, 1 << 20, np.int32))
        fb = jax.tree_util.tree_map(lambda p, s: p.at[rows].set(s), fa, sub)
        return fa, fb

    def run_session(fa, fb, ta, tb, *, lag_a=None, lag_b=None,
                    digest_tree=False):
        sa = SyncSession(fa, uni, peer="lat-b", lag_tracker=lag_a,
                         digest_tree=digest_tree)
        sb = SyncSession(fb, uni, peer="lat-a", lag_tracker=lag_b,
                         digest_tree=digest_tree)
        res = {}

        def side_b():
            res["b"] = sb.sync(tb)

        t = threading.Thread(target=side_b, daemon=True)
        t.start()
        t0 = time.perf_counter()
        res["a"] = sa.sync(ta)
        wall = time.perf_counter() - t0
        t.join(timeout=60.0)
        assert res["a"].converged and res["b"].converged
        return res["a"], res["b"], wall

    out = {}
    policy = RetryPolicy(send_deadline_s=30.0, recv_deadline_s=30.0,
                         ack_timeout_s=0.1, max_backoff_s=2.0,
                         retry_budget=256)
    # warm the session kernels (digest/gather/apply/merge jit compiles)
    # over an unshaped link so the shaped rungs measure PROTOCOL
    # latency, not first-call compilation
    wa, wb = diverged_pair()
    ta, tb = latency_pair(0.0, default_timeout=30.0)
    run_session(wa, wb,
                ResilientTransport(ta, policy, name="warm-a", seed=90),
                ResilientTransport(tb, policy, name="warm-b", seed=91))
    rtts_ms = (50,) if SMALL else (50, 100, 200)
    for rtt_ms in rtts_ms:
        one_way = rtt_ms / 2e3
        fa, fb = diverged_pair()
        if rtt_ms == 100:
            # the delay-REORDER shape (ROADMAP WAN schedules): 20% of
            # one side's frames ship behind their successor, under the
            # propagation delay, absorbed by the ARQ below the session
            qa, qb = queue_pair(default_timeout=30.0)
            faulty = FaultyTransport(qa, FaultPlan(seed=11, delay=0.2),
                                     name=f"lat{rtt_ms}-reorder")
            ta = LatencyTransport(faulty, one_way, name=f"lat{rtt_ms}-a")
            tb = LatencyTransport(qb, one_way, name=f"lat{rtt_ms}-b")
        else:
            ta, tb = latency_pair(one_way, default_timeout=30.0)
        ra = ResilientTransport(ta, policy, name=f"lat{rtt_ms}-a", seed=1)
        rb = ResilientTransport(tb, policy, name=f"lat{rtt_ms}-b", seed=2)
        lag_a, lag_b = LagTracker(), LagTracker()
        # stamp a write the session will make visible at the peer: the
        # write-to-visible measurement rides the real sidecar
        clock_a = np.asarray(fa.clock)
        lag_a.record_ingest(0, int(clock_a[:, 0].max()))
        rep_a, _rep_b, wall = run_session(fa, fb, ra, rb,
                                          lag_a=lag_a, lag_b=lag_b)
        prof = rep_a.profile
        srtt = ra.rtt.snapshot()["srtt_s"] or 0.0
        lag = lag_b.snapshot()["peers"].get("lat-a", {})
        rtt_s = rtt_ms / 1e3
        out[f"latency_{rtt_ms}ms_wall_over_rtt"] = round(wall / rtt_s, 3)
        out[f"latency_{rtt_ms}ms_srtt_over_rtt"] = round(
            srtt / rtt_s, 3)
        out[f"latency_{rtt_ms}ms_network_wait_frac"] = round(
            prof.network_wait_frac, 4)
        out[f"latency_{rtt_ms}ms_unaccounted_frac"] = round(
            prof.unaccounted_ns / prof.wall_ns if prof.wall_ns else 0.0, 5)
        out[f"latency_{rtt_ms}ms_lag_p99_over_rtt"] = round(
            lag.get("p99_s", 0.0) / rtt_s, 3)
        log(f"latency: {rtt_ms}ms RTT  session wall {wall*1e3:.0f}ms "
            f"({wall / rtt_s:.1f}x RTT)  srtt {srtt*1e3:.0f}ms  "
            f"network_wait {prof.network_wait_frac:.0%}  "
            f"unaccounted {out[f'latency_{rtt_ms}ms_unaccounted_frac']:.2%}  "
            f"lag p99 {lag.get('p99_s', 0.0)*1e3:.0f}ms  "
            f"retransmits {ra.retransmits + rb.retransmits}")
        # a shaped-RTT session must be wire-dominated and fully
        # accounted — the acceptance pins (|unaccounted| <= 10% wall)
        assert abs(prof.unaccounted_ns) <= 0.10 * prof.wall_ns, (
            f"profiler lost {prof.unaccounted_ns / prof.wall_ns:.1%} "
            f"of a {rtt_ms}ms-RTT session wall (bar: 10%)"
        )
        if rtt_ms == 100:
            # the reorder-faulted measurement rung must still negotiate
            # streaming (the gate rung below pins the wall/wait numbers
            # on a clean shaped link, where a 0.2s reordered straggler
            # can't charge the session for the fault plan's delay)
            assert rep_a.streaming, (
                "100ms-RTT session did not negotiate streaming — both "
                "transports are windowed; the hello advertisement broke"
            )
        if rtt_ms == 200:
            # the adaptive timer (srtt+4var ≈ 0.2s+) must keep spurious
            # retransmits at the static-0.1s timer's 200ms-RTT level or
            # better; only the pre-sample opening frames may fire the
            # static timer, so the count stays O(1) instead of
            # once-per-frame — the no-regression acceptance pin
            retr = ra.retransmits + rb.retransmits
            out["latency_200ms_retransmits"] = retr
            assert retr <= 6, (
                f"{retr} retransmits at 200ms RTT — the adaptive timer "
                "is not suppressing spurious retransmission"
            )

    if not SMALL:
        # THE GATE (ISSUE 16 flip): a shaped 100ms session carrying
        # RTT-scale compute must no longer be wire-dominated.  The
        # session floor is ~1 RTT of irreducible light-cone waits (one
        # flight for hello+eager-digest, one for the post-apply
        # converged check), so the divergence is CALIBRATED on this
        # machine: time one warm 256-row gather/apply chunk, then size
        # the diverged set so the streamed delta phase carries RTT-scale
        # real work.  On a multi-core runner the gate is ABSOLUTE (wall
        # ≤3x RTT AND network_wait_frac < 0.5) — the peer's kernels run
        # on their own core, so local compute genuinely overlaps the
        # flights.  A single-core runner physically cannot exhibit that
        # overlap in-process (both peers' kernels serialize onto one
        # core: wall = waits + BOTH computes, which pushes the absolute
        # pair to its infeasibility boundary), so the gate degrades —
        # loudly — to the RELATIVE form on the identical workload:
        # windowed wall strictly below stop-and-wait wall, and
        # network_wait_frac at least 0.25 below it (stop-and-wait
        # lock-steps every frame at ~0.9 wait).  Both modes keep the
        # stop-and-wait control numbers in the artifact for the diff.
        from crdt_tpu.sync.delta import (
            DELTA_CHUNK_ROWS, OrswotDeltaApplier, apply_delta_rows,
            gather_blobs,
        )
        from crdt_tpu.sync import digest as digest_g
        import jax as _jaxg

        multi_core = (os.cpu_count() or 1) >= 2
        n_gate = 16384
        rng_g = np.random.RandomState(31)
        reps_g = anti_entropy_fleets(rng_g, n_gate, a, m, d, 1,
                                     base=min(4, m - 2), novel=0,
                                     deferred_frac=0.25)
        fg = OrswotBatch(*(jnp.asarray(x) for x in reps_g[0]))
        fg = fg.merge(fg)
        # calibrate: warm + time the per-chunk cost on a scratch copy
        # (digest/version-vector warm on the copy too — the gate must
        # measure protocol latency, not n=16384 first-call compiles)
        applier_g = OrswotDeltaApplier(uni)
        ids0 = np.arange(DELTA_CHUNK_ROWS, dtype=np.int64)
        scratch = _jaxg.tree_util.tree_map(lambda p: p + 0, fg)
        digest_g.digest_of(scratch, uni)
        digest_g.version_vector(scratch)
        for _ in range(2):  # jit + memo warmup
            scratch = apply_delta_rows(scratch, ids0,
                                       gather_blobs(fg, ids0, uni),
                                       uni, applier=applier_g)
        t0 = time.perf_counter()
        for _ in range(3):
            scratch = apply_delta_rows(scratch, ids0,
                                       gather_blobs(fg, ids0, uni),
                                       uni, applier=applier_g)
        per_chunk_s = (time.perf_counter() - t0) / 3
        # multi-core: target ~1.4 RTT of delta compute (inside the
        # feasible band (waits, 3·RTT − waits)).  Single-core: keep the
        # session short — the relative gate needs identical workloads,
        # not a particular compute/RTT ratio
        target_s = 0.14 if multi_core else 0.06
        chunks_g = int(np.clip(round(target_s / max(per_chunk_s, 1e-4)),
                               4, 24))
        k_gate = chunks_g * DELTA_CHUNK_ROWS
        rows_g = np.sort(rng_g.choice(n_gate, size=k_gate,
                                      replace=False)).astype(np.int64)
        sub_g = _jaxg.tree_util.tree_map(lambda p: p[rows_g], fg)
        sub_g = sub_g.apply_add(np.zeros(k_gate, np.int32),
                                jnp.max(sub_g.clock, axis=-1) + 1,
                                np.full(k_gate, 1 << 20, np.int32))
        fg2 = _jaxg.tree_util.tree_map(lambda p, s: p.at[rows_g].set(s),
                                       fg, sub_g)
        one_way = 0.05
        rtt_s = 0.1

        def gate_run(window, tag, seed):
            # best-of-2: thread-scheduler noise on a shaped link is
            # real; the gate measures the protocol, not the scheduler
            # (sync never mutates the caller's batches, so the same
            # pair replays the same divergence)
            best = None
            for rep_i in range(2):
                ta_, tb_ = latency_pair(one_way, default_timeout=60.0)
                pol = dataclasses.replace(policy, window=window)
                ra_ = ResilientTransport(ta_, pol, name=f"{tag}-a",
                                         seed=seed + 2 * rep_i)
                rb_ = ResilientTransport(tb_, pol, name=f"{tag}-b",
                                         seed=seed + 2 * rep_i + 1)
                rep_, _rep_b, wall_ = run_session(fg, fg2, ra_, rb_)
                if best is None or wall_ < best[1]:
                    best = (rep_, wall_)
            return best

        rep_g, wall_g = gate_run(policy.window, "lat100g", seed=3)
        prof_g = rep_g.profile
        frac_g = prof_g.network_wait_frac
        out["latency_100ms_gated_wall_over_rtt"] = round(wall_g / rtt_s, 3)
        out["latency_100ms_gated_network_wait_frac"] = round(frac_g, 4)
        out["latency_100ms_gated_chunks"] = rep_g.delta_chunks_sent
        out["latency_100ms_gate_absolute"] = bool(multi_core)
        log(f"latency: 100ms GATE n={n_gate} diverged {k_gate} "
            f"({chunks_g} chunks, {per_chunk_s*1e3:.1f}ms/chunk)  wall "
            f"{wall_g*1e3:.0f}ms ({wall_g / rtt_s:.1f}x RTT)  "
            f"network_wait {frac_g:.0%}")
        assert rep_g.streaming and rep_g.delta_chunks_sent == chunks_g
        # the stop-and-wait control on the IDENTICAL calibrated
        # workload and link shape
        rep2, wall2 = gate_run(1, "lat100sw", seed=7)
        prof2 = rep2.profile
        frac2 = prof2.network_wait_frac
        out["latency_100ms_stopwait_wall_over_rtt"] = round(
            wall2 / rtt_s, 3)
        out["latency_100ms_stopwait_network_wait_frac"] = round(frac2, 4)
        log(f"latency: 100ms RTT stop-and-wait control  wall "
            f"{wall2*1e3:.0f}ms ({wall2 / rtt_s:.1f}x RTT)  "
            f"network_wait {frac2:.0%}")
        assert not rep2.streaming, \
            "window-1 control session negotiated streaming"
        if multi_core:
            assert wall_g <= 3.0 * rtt_s, (
                f"100ms-RTT gated session took {wall_g / rtt_s:.1f}x "
                "RTT (gate: <=3x) — the windowed transport is not "
                "pipelining the session phases"
            )
            assert frac_g < 0.5, (
                f"100ms-RTT gated session spent {frac_g:.0%} of its "
                "wall blocked on the wire (gate: <50%) — sends are "
                "lock-stepping again"
            )
        else:
            log("latency: single-core runner — absolute 100ms gate "
                "infeasible in-process (both peers' kernels serialize "
                "onto one core); gating windowed-vs-stopwait instead")
            assert wall_g < wall2, (
                f"windowed session ({wall_g*1e3:.0f}ms) not faster "
                f"than stop-and-wait ({wall2*1e3:.0f}ms) on the "
                "identical workload"
            )
            assert frac_g <= frac2 - 0.25, (
                f"windowed network_wait_frac {frac_g:.2f} not at "
                f"least 0.25 below stop-and-wait's {frac2:.2f} — "
                "the pipelined phases are not hiding the wire"
            )

    # the ≤2-RTT descent gate: a diverged digest-tree fleet over the
    # windowed transport must locate its diverged leaves in one root
    # exchange plus ONE speculative blast — round-trip count asserted
    # from the session report, so the gate is link-speed independent
    n_tree = 4096 if SMALL else 65536
    rng_t = np.random.RandomState(29)
    reps = anti_entropy_fleets(rng_t, n_tree, a, m, d, 1,
                               base=min(4, m - 2), novel=0,
                               deferred_frac=0.25)
    ft = OrswotBatch(*(jnp.asarray(x) for x in reps[0]))
    ft = ft.merge(ft)
    k_tree = max(1, n_tree // 100)
    rows = np.sort(rng_t.choice(n_tree, size=k_tree,
                                replace=False)).astype(np.int64)
    import jax as _jax
    sub = _jax.tree_util.tree_map(lambda p: p[rows], ft)
    sub = sub.apply_add(np.zeros(k_tree, np.int32),
                        jnp.max(sub.clock, axis=-1) + 1,
                        np.full(k_tree, 1 << 20, np.int32))
    ft2 = _jax.tree_util.tree_map(lambda p, s: p.at[rows].set(s), ft, sub)
    ta, tb = latency_pair(0.005, default_timeout=30.0)
    ra = ResilientTransport(ta, policy, name="tree-a", seed=7)
    rb = ResilientTransport(tb, policy, name="tree-b", seed=8)
    rep_t, _rep_tb, wall_t = run_session(ft, ft2, ra, rb, digest_tree=True)
    out["latency_tree_descent_rtts"] = rep_t.tree_round_trips
    out["latency_tree_descent_spec_hit_frac"] = round(
        rep_t.spec_hits / max(1, rep_t.spec_hits + rep_t.spec_misses), 4)
    log(f"latency: tree descent n={n_tree}  "
        f"round_trips {rep_t.tree_round_trips}  levels {rep_t.tree_levels}  "
        f"spec hit/miss {rep_t.spec_hits}/{rep_t.spec_misses}  "
        f"wall {wall_t*1e3:.0f}ms")
    assert rep_t.tree_mode and rep_t.diverged == k_tree
    assert rep_t.tree_round_trips <= 2, (
        f"diverged {n_tree}-object descent took "
        f"{rep_t.tree_round_trips} round trips (gate: <=2 — one root "
        "exchange + one speculative blast)"
    )

    # adaptive-vs-static on loopback: after a handful of acked frames
    # the adaptive RTO must sit well under the static 100ms timer
    ta, tb = latency_pair(0.0005, default_timeout=10.0)
    ra = ResilientTransport(ta, policy, name="loop-a", seed=3)
    rb = ResilientTransport(tb, policy, name="loop-b", seed=4)
    got = []

    def consume():
        for _ in range(16):
            got.append(rb.recv(timeout=10.0))

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    for i in range(16):
        ra.send(b"probe-%02d" % i)
    t.join(timeout=30.0)
    ra.flush(timeout=10.0)  # fold the tail acks into the estimator
    out["latency_loopback_rto_s"] = round(ra.current_rto(), 5)
    out["latency_loopback_rto_over_static"] = round(
        ra.current_rto() / policy.ack_timeout_s, 4)
    log(f"latency: loopback adaptive RTO {ra.current_rto()*1e3:.1f}ms vs "
        f"static {policy.ack_timeout_s*1e3:.0f}ms "
        f"({out['latency_loopback_rto_over_static']:.2f}x)")
    assert ra.current_rto() < policy.ack_timeout_s, (
        "adaptive RTO did not tighten below the static timer on loopback"
    )

    # always-on overhead: per-op cost of a profile stamp + an ingest
    # stamp, scaled by a generous per-session stamp count against the
    # e2e reference — the bench_obs_overhead discipline
    iters = 20_000 if SMALL else 100_000
    prof = SessionProfile()

    def per_op(fn):
        t0 = time.perf_counter()
        for i in range(iters):
            fn(i)
        return (time.perf_counter() - t0) / iters

    def stamp(i):
        with prof.clock("kernel"):
            pass

    stamp_s = per_op(stamp)
    lt = LagTracker()
    ingest_s = per_op(lambda i: lt.record_ingest(i & 63, i))
    out["latency_profile_stamp_ns"] = round(stamp_s * 1e9, 1)
    out["latency_ingest_stamp_ns"] = round(ingest_s * 1e9, 1)
    e2e_s = _JSON_STATE.get("e2e_wire_s")
    if e2e_s and e2e_s >= 0.5:
        if SMALL:
            n_e2e, chunk, r = 2_000, 1_000, 4
        else:
            n_e2e, chunk, r = 1_250_000, 62_500, 8
        n_chunks = max(2, n_e2e // chunk)
        if _downshift():
            n_chunks = min(n_chunks, 2)
        # ~64 stamps per session and an ingest stamp per bulk submit is
        # the generous ceiling; both are per BULK call, never per op
        ops = n_chunks * r * 64
        frac = ops * max(stamp_s, ingest_s) / e2e_s
        out["latency_overhead_frac"] = round(frac, 6)
        log(f"latency: observatory overhead {ops} stamps x "
            f"{max(stamp_s, ingest_s)*1e9:.0f}ns vs e2e_wire {e2e_s:.2f}s "
            f"-> {frac:.4%} (bar: <1%)")
        assert frac < 0.01, (
            f"latency observatory costs {frac:.2%} of bench_e2e_wire "
            "wall (bar: <1%) — did stamping regress to per-op?"
        )
    return out


def bench_fleet_obs():
    """Fleet-observatory cost gate (the obs/fleet satellite): snapshot
    encode + CRDT merge cost as a function of node count, and the
    piggyback's share of a real sync session's wall time.  The
    piggyback rides EVERY gossip session, so its budget is noise:
    the bar is <5% of session wall.  Costs are measured on synthetic
    per-node slices shaped like a live registry (manifest-conformant
    names, histograms, convergence state, an event tail) so the JSON
    numbers track the real payload round over round."""
    from crdt_tpu.obs import convergence as obs_conv
    from crdt_tpu.obs import events as obs_events
    from crdt_tpu.obs import fleet as obs_fleet
    from crdt_tpu.obs import metrics as obs_metrics

    n_metrics = 40 if SMALL else 150

    def synth_observatory(node: str) -> obs_fleet.FleetObservatory:
        reg = obs_metrics.MetricsRegistry()
        for i in range(n_metrics):
            reg.counter_inc(f"wire.sync.leg{i}.bytes", i * 7 + 1)
        for i in range(max(4, n_metrics // 4)):
            reg.gauge_set(f"sync.peer.p{i}.divergence", float(i))
        for i in range(64):
            reg.observe("sync.digest_exchange", 0.0005 * (i + 1))
        trk = obs_conv.ConvergenceTracker(registry=reg)
        trk.observe_session(node, converged=True, rounds=1,
                            payload_bytes=1024, full_state_bytes=65536)
        rec = obs_events.FlightRecorder(capacity=256)
        for i in range(128):
            rec.record("sync.phase", session=f"s{i:04d}", phase="digest",
                       trace=f"t{i:04d}")
        return obs_fleet.FleetObservatory(node, registry=reg, tracker=trk,
                                          recorder=rec)

    out = {}
    for n_nodes in (2, 8, 32):
        observatories = [synth_observatory(f"b{i}") for i in range(n_nodes)]
        t0 = time.perf_counter()
        frames = [o.encode() for o in observatories]
        encode_s = time.perf_counter() - t0
        sink = observatories[0]
        t0 = time.perf_counter()
        for f in frames:
            sink.merge_frame(f)
        merge_s = time.perf_counter() - t0
        assert len(sink.merged(refresh=False).slices) == n_nodes
        if n_nodes == 32:
            out["fleet_obs_encode_ms_per_node"] = round(
                encode_s / n_nodes * 1e3, 3)
            out["fleet_obs_merge_ms_per_node"] = round(
                merge_s / n_nodes * 1e3, 3)
            out["fleet_obs_frame_bytes"] = len(sink.encode(refresh=False))
        log(f"fleet obs: {n_nodes} nodes  encode {encode_s*1e3:.1f}ms  "
            f"merge {merge_s*1e3:.1f}ms  frame "
            f"{len(frames[0])/1024:.1f}KB")

    # piggyback share of a real session: one delta sync at bench shape,
    # then the exact per-session piggyback work (encode both sides,
    # merge both frames) measured against that session's wall
    import jax.numpy as jnp

    from crdt_tpu.batch import OrswotBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.sync.session import SyncSession, sync_pair
    from crdt_tpu.utils.interning import Universe
    from crdt_tpu.utils.testdata import anti_entropy_fleets

    rng = np.random.RandomState(17)
    n, a, m, d = (2_000, 16, 8, 2) if SMALL else (20_000, 32, 16, 2)
    cfg = CrdtConfig(num_actors=a, member_capacity=m, deferred_capacity=d,
                     counter_bits=32)
    uni = Universe.identity(cfg)
    reps = anti_entropy_fleets(rng, n, a, m, d, 1, base=min(4, m - 2),
                               novel=0, deferred_frac=0.25)
    fleet_a = OrswotBatch(*(jnp.asarray(x) for x in reps[0]))
    fleet_a = fleet_a.merge(fleet_a)
    k = max(1, n // 100)
    rows = np.sort(rng.choice(n, size=k, replace=False)).astype(np.int64)
    import jax

    sub = jax.tree_util.tree_map(lambda p: p[rows], fleet_a)
    sub = sub.apply_add(np.zeros(k, np.int32),
                        jnp.max(sub.clock, axis=-1) + 1,
                        np.full(k, 1 << 20, np.int32))
    fleet_b = jax.tree_util.tree_map(lambda p, s: p.at[rows].set(s),
                                     fleet_a, sub)
    sa = SyncSession(fleet_a, uni)
    sb = SyncSession(fleet_b, uni)
    t0 = time.perf_counter()
    ra, rb = sync_pair(sa, sb)
    session_wall = time.perf_counter() - t0
    assert ra.converged and rb.converged

    oa, ob = synth_observatory("pa"), synth_observatory("pb")
    t0 = time.perf_counter()
    fa = oa.encode()
    fb = ob.encode()
    ob.merge_frame(fa)
    oa.merge_frame(fb)
    piggy_s = time.perf_counter() - t0
    frac = piggy_s / session_wall if session_wall else 0.0
    out["fleet_obs_piggyback_frac"] = round(frac, 5)
    log(f"fleet obs: piggyback {piggy_s*1e3:.2f}ms vs session "
        f"{session_wall*1e3:.1f}ms -> {frac:.3%} (bar: <5%)")
    # only gate against a session long enough to be a denominator (a
    # smoke-shape sync finishes in ms, where any fixed cost dominates)
    if session_wall >= 0.2:
        assert frac < 0.05, (
            f"fleet-snapshot piggyback costs {frac:.1%} of session wall "
            "(bar: <5%) — did the snapshot stop being bounded?"
        )
    else:
        log("fleet obs: session too fast to gate against (smoke shape); "
            "per-op costs recorded")
    return out


def bench_capacity_obs():
    """Capacity-observatory cost gate (the obs/capacity satellite): one
    occupancy sample is one jitted reduction + a six-int host fetch,
    and the gossip scheduler takes one per ROUND — so its cost must be
    noise next to a round's real work.  Measures per-sample wall at
    1k/64k/1M objects (plus the op-log/gap-buffer samples), pins the
    reported plane bytes against the actual buffer nbytes at every
    size, and asserts the largest per-sample cost is <1% of the
    measured ``bench_e2e_wire`` wall."""
    from crdt_tpu.batch import OrswotBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.obs import metrics as obs_metrics
    from crdt_tpu.obs.capacity import CapacityTracker
    from crdt_tpu.oplog import OpBatch, OpLog
    from crdt_tpu.utils.interning import Universe

    cfg = CrdtConfig(num_actors=8, member_capacity=8, deferred_capacity=4,
                     counter_bits=32)
    uni = Universe.identity(cfg)
    sizes = (1_000, 16_000, 64_000) if SMALL else (1_000, 64_000, 1_000_000)
    # private registry: bench probe gauges must not shadow live ones
    trk = CapacityTracker(registry=obs_metrics.MetricsRegistry())
    out = {}
    worst_s = 0.0
    for n in sizes:
        batch = OrswotBatch.zeros(n, uni)
        trk.sample(batch)  # compile + warm
        iters = 5
        t0 = time.perf_counter()
        for _ in range(iters):
            occ = trk.sample(batch)
        per = (time.perf_counter() - t0) / iters
        nbytes = sum(x.nbytes for x in (batch.clock, batch.ids, batch.dots,
                                        batch.d_ids, batch.d_clocks))
        assert occ.bytes == nbytes, (
            f"reported plane bytes {occ.bytes} != buffer nbytes {nbytes} "
            f"at N={n}"
        )
        out[f"capacity_sample_ms_{n}"] = round(per * 1e3, 4)
        worst_s = max(worst_s, per)
        log(f"capacity obs: N={n}  sample {per*1e3:.3f}ms  "
            f"plane bytes {nbytes/1e6:.1f}MB (exact)")
        del batch
    olog = OpLog(uni, capacity=1 << 16)
    olog.append(OpBatch(kind=np.full(1024, 0, np.uint8),
                        obj=np.arange(1024) % 64,
                        actor=np.zeros(1024, np.int32),
                        counter=np.arange(1, 1025, dtype=np.uint64),
                        member=np.arange(1024, dtype=np.int32)))
    t0 = time.perf_counter()
    for _ in range(20):
        trk.sample_oplog(olog)
    out["capacity_oplog_sample_ms"] = round(
        (time.perf_counter() - t0) / 20 * 1e3, 4)

    e2e_s = _JSON_STATE.get("e2e_wire_s")
    if e2e_s:
        frac = worst_s / e2e_s
        out["capacity_sample_frac"] = round(frac, 6)
        log(f"capacity obs: worst sample {worst_s*1e3:.2f}ms vs e2e_wire "
            f"{e2e_s:.2f}s -> {frac:.4%} (bar: <1%)")
        # same denominators discipline as bench_obs_overhead: only gate
        # when the e2e reference is big enough to be a denominator
        if e2e_s >= 0.5:
            assert frac < 0.01, (
                f"one capacity sample costs {frac:.2%} of bench_e2e_wire "
                "wall (bar: <1%) — did the occupancy fetch stop being one "
                "small reduction?"
            )
        else:
            log("capacity obs: e2e_wire too small to gate against "
                "(smoke shape); per-sample costs recorded")
    else:
        log("capacity obs: e2e_wire did not run; per-sample costs only")
    return out


def bench_kernel_obs():
    """Runtime kernel-observatory cost gate + coverage tail (the PR 14
    tentpole's bench satellite).  (1) Per-call wrapper overhead,
    measured directly: the same warmed jitted kernel dispatched through
    its ``observed_kernel`` wrapper vs bare, scaled by a generous
    per-fleet kernel-call count for the e2e wire workload and gated
    <1% of the measured ``bench_e2e_wire`` wall.  (2) Steady-state
    invariant: the measurement loop itself must record ZERO compile
    events after its warmup call (``storm_report`` over the loop's
    window).  (3) Coverage tail: per-kernel compile counts and p50
    wall for every kernel the bench run exercised, so a kernel family
    going dark diffs round over round (``kernel`` family collapse in
    benchkit/artifacts.py), plus one blocking-mode GB/s + XLA
    cost-analysis capture for the fold kernel as the roofline anchor."""
    import jax.numpy as jnp

    from crdt_tpu.batch import vclock_batch
    from crdt_tpu.obs import kernels as obs_kernels

    obs = obs_kernels.kernel_observatory()

    plane = jnp.zeros((256, 8), dtype=jnp.uint32)
    wrapped = vclock_batch._merge          # the observed wrapper
    bare = wrapped._fn                     # the jitted target inside it
    wrapped(plane, plane)                  # warm (compile outside the loop)
    warm_seq = obs_kernels.last_event_seq()

    iters = 2_000 if SMALL else 10_000

    def per_call(fn):
        t0 = time.perf_counter()
        for _ in range(iters):
            fn(plane, plane)
        return (time.perf_counter() - t0) / iters

    bare_s = per_call(bare)
    wrapped_s = per_call(wrapped)
    overhead_s = max(0.0, wrapped_s - bare_s)
    out = {
        "kernel_obs_call_ns_bare": round(bare_s * 1e9, 1),
        "kernel_obs_call_ns_wrapped": round(wrapped_s * 1e9, 1),
        "kernel_obs_overhead_ns": round(overhead_s * 1e9, 1),
    }
    log(f"kernel obs: dispatch {bare_s*1e6:.1f}us bare / "
        f"{wrapped_s*1e6:.1f}us wrapped -> +{overhead_s*1e9:.0f}ns/call")

    # steady state: the 2*iters same-shape dispatches above must not
    # have produced a single compile event past the warmup boundary
    storm = obs_kernels.storm_report(since_seq=warm_seq)
    assert storm["compiles"] == 0, (
        f"steady-state dispatch loop recompiled: {storm['kernels']} — "
        "a wrapper or cache-key regression is churning the jit cache"
    )

    # one blocking-mode pass so the fold kernel owns a GB/s roofline
    # coordinate + its XLA cost analysis in the artifact
    obs_kernels.set_blocking(True)
    try:
        for _ in range(10):
            wrapped(plane, plane)
    finally:
        obs_kernels.set_blocking(False)
    prof = obs.profile("batch.vclock.merge")
    cost = prof.capture_cost()
    if cost is not None:
        out["kernel_obs_fold_cost_flops"] = cost["flops"]
        out["kernel_obs_fold_cost_bytes"] = cost["bytes_accessed"]
    table = {
        row["label"]: {
            "compiles": row["compiles"],
            "wall_p50_s": row["wall_p50_s"],
        }
        for row in obs.table() if row["calls"] or row["compiles"]
    }
    out["kernel_obs_exercised"] = len(table)
    out["kernel_obs_compiles_total"] = sum(
        r["compiles"] for r in table.values())
    out["kernel_obs_table"] = table
    dm = obs_kernels.sample_device_memory()
    if dm is not None:
        out["kernel_obs_devicemem_mb"] = round(dm["live_bytes"] / 1e6, 3)
    log(f"kernel obs: {len(table)} kernels exercised this run, "
        f"{out['kernel_obs_compiles_total']} compiles total")

    e2e_s = _JSON_STATE.get("e2e_wire_s")
    if e2e_s:
        # the e2e loop's kernel-call volume, shaped like
        # bench_obs_overhead's estimate: one fold call per chunk per
        # fleet is the real rate; 16x is deliberate headroom
        if SMALL:
            n, chunk, r = 2_000, 1_000, 4
        else:
            n, chunk, r = 1_250_000, 62_500, 8
        n_chunks = max(2, n // chunk)
        if _downshift():
            n_chunks = min(n_chunks, 2)
        calls = n_chunks * r * 16
        frac = calls * overhead_s / e2e_s
        out["kernel_obs_overhead_frac"] = round(frac, 6)
        log(f"kernel obs: {calls} calls x {overhead_s*1e9:.0f}ns = "
            f"{calls*overhead_s*1e3:.2f}ms vs e2e_wire {e2e_s:.2f}s "
            f"-> {frac:.4%} (bar: <1%)")
        if e2e_s >= 0.5:
            assert frac < 0.01, (
                f"always-on kernel observatory costs {frac:.2%} of "
                "bench_e2e_wire wall (bar: <1%) — did the per-call path "
                "start blocking or tracing eagerly?"
            )
        else:
            log("kernel obs: e2e_wire too small to gate against "
                "(smoke shape); per-call costs recorded")
    else:
        log("kernel obs: e2e_wire did not run; per-call costs only")
    return out


def bench_gc():
    """Causal-GC cost + reclamation gauge (the `crdt_tpu.gc` stage):
    tombstone settling and plane re-packing wall at 1k/64k/1M objects
    over a burst-over-provisioned fleet (4x the config rung — the shape
    the executor's regrow ladder leaves behind), plus bytes reclaimed.

    Parity-gated: a fleet with real op history (including deferred
    rows) compacted by the full GcEngine pass must digest-match its
    untouched twin — compaction is representation-only, and a stage
    that reclaimed bytes by touching state must fail here, not in a
    fleet."""
    import jax

    from crdt_tpu.batch import OrswotBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.gc import GcEngine, GcPolicy
    from crdt_tpu.gc.compact import settle_orswot
    from crdt_tpu.gc.repack import repack_orswot
    from crdt_tpu.obs import convergence as obs_convergence
    from crdt_tpu.obs import metrics as obs_metrics
    from crdt_tpu.scalar.ctx import RmCtx
    from crdt_tpu.scalar.orswot import Orswot
    from crdt_tpu.scalar.vclock import VClock
    from crdt_tpu.sync import digest as digest_mod
    from crdt_tpu.utils.interning import Universe

    cfg = CrdtConfig(num_actors=8, member_capacity=8, deferred_capacity=4,
                     counter_bits=32)
    uni = Universe.identity(cfg)

    # -- parity gate (always runs with the stage) ---------------------------
    rng = np.random.RandomState(29)
    states = []
    for i in range(256):
        s = Orswot()
        for j in range(int(rng.randint(1, 5))):
            s.apply(s.add(int(rng.randint(0, 500)),
                          s.value().derive_add_ctx(int(rng.randint(0, 4)))))
        if i % 9 == 0:  # a causally-future remove → a deferred row
            future = VClock()
            future.witness(7, int(rng.randint(50, 90)))
            s.apply(s.remove(0, RmCtx(clock=future)))
        states.append(s)
    twin = OrswotBatch.from_scalar(states, uni)
    big = twin.with_capacity(32, 16)
    eng = GcEngine(
        GcPolicy(interval_rounds=1),
        tracker=obs_convergence.ConvergenceTracker(
            obs_metrics.MetricsRegistry()),
    )
    compacted, report = eng.collect(big, universe=uni)
    want = np.asarray(digest_mod.digest_of(twin), np.uint64)
    got = np.asarray(digest_mod.digest_of(compacted), np.uint64)
    assert np.array_equal(got, want), (
        "GC parity gate: compacted fleet's digest vector diverged from "
        "its untruncated twin"
    )
    assert report.shrunk and report.reclaimed_bytes > 0
    log(f"gc parity: 256-object history fleet compacted "
        f"({report.reclaimed_bytes}B reclaimed, member capacity "
        f"{report.member_capacity[0]}->{report.member_capacity[1]}), "
        "digest vectors byte-identical")

    # -- the cost/reclamation curve -----------------------------------------
    sizes = (1_000, 16_000, 64_000) if SMALL else (1_000, 64_000, 1_000_000)
    out = {"gc_reclaimed_frac": None}
    for n in sizes:
        fleet = OrswotBatch.zeros(n, uni)
        col = np.zeros(n, np.int32)
        for j in range(3):  # 3 live members per object
            fleet = fleet.apply_add(
                col, np.full(n, j + 1, np.uint32),
                np.full(n, j, np.int32))
        grown = fleet.with_capacity(cfg.member_capacity * 4,
                                    cfg.deferred_capacity * 4)
        bytes_before = sum(
            x.nbytes for x in (grown.clock, grown.ids, grown.dots,
                               grown.d_ids, grown.d_clocks))
        settled, _ = settle_orswot(grown)  # compile + warm
        jax.block_until_ready(settled.ids)
        iters = 3 if n < 1_000_000 else 1
        t0 = time.perf_counter()
        for _ in range(iters):
            settled, _ = settle_orswot(grown)
            jax.block_until_ready(settled.ids)
        settle_ms = (time.perf_counter() - t0) / iters * 1e3

        reg = obs_metrics.MetricsRegistry()
        shrunk, reclaimed = repack_orswot(
            settled, cfg.member_capacity, cfg.deferred_capacity,
            registry=reg)  # compile + warm
        jax.block_until_ready(shrunk.ids)
        t0 = time.perf_counter()
        for _ in range(iters):
            shrunk, reclaimed = repack_orswot(
                settled, cfg.member_capacity, cfg.deferred_capacity,
                registry=reg)
            jax.block_until_ready(shrunk.ids)
        repack_ms = (time.perf_counter() - t0) / iters * 1e3

        out[f"gc_settle_ms_{n}"] = round(settle_ms, 3)
        out[f"gc_repack_ms_{n}"] = round(repack_ms, 3)
        out[f"gc_reclaimed_bytes_{n}"] = int(reclaimed)
        out["gc_reclaimed_frac"] = round(reclaimed / bytes_before, 4)
        log(f"gc: N={n}  settle {settle_ms:.2f}ms  repack "
            f"{repack_ms:.2f}ms  reclaimed {reclaimed/1e6:.1f}MB of "
            f"{bytes_before/1e6:.1f}MB "
            f"({reclaimed / bytes_before:.0%})")
        del fleet, grown, settled, shrunk
    return out


def bench_durable():
    """Durability cost gauge (the `crdt_tpu.durable` stage): snapshot
    write (checkpoint + CRC envelope + fsync + rename) and restore
    (decode + digest-root verify) wall at 1k/64k/1M objects, plus the
    per-op WAL append overhead — the only durable cost on the WRITE
    hot path, gated <5% of the measured ``bench_e2e_wire`` wall at the
    e2e op volume (checkpoints run at round end, off the hot path —
    reported, not gated).

    Parity-gated: every restore must round-trip digest-identical (the
    snapshot store's own root check enforces it; a silent skip would
    surface here as a CheckpointFormatError)."""
    import shutil
    import tempfile

    from crdt_tpu.batch import OrswotBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.durable import Durability, recover
    from crdt_tpu.oplog.records import OpBatch
    from crdt_tpu.sync import digest as digest_mod

    cfg = CrdtConfig(num_actors=8, member_capacity=8, deferred_capacity=4,
                     counter_bits=32)
    from crdt_tpu.utils.interning import Universe

    uni = Universe.identity(cfg)
    sizes = (1_000, 16_000, 64_000) if SMALL else (1_000, 64_000, 1_000_000)
    out = {}
    tmp_root = tempfile.mkdtemp(prefix="bench_durable_")
    try:
        for n in sizes:
            fleet = OrswotBatch.zeros(n, uni)
            col = np.zeros(n, np.int32)
            for j in range(3):
                fleet = fleet.apply_add(
                    col, np.full(n, j + 1, np.uint32),
                    np.full(n, j, np.int32))
            dur = Durability(os.path.join(tmp_root, f"n{n}"),
                             interval_rounds=1, retain=2)
            t0 = time.perf_counter()
            snap = dur.checkpoint(fleet, uni, wal_seq=dur.wal.head_seq)
            snapshot_ms = (time.perf_counter() - t0) * 1e3
            t0 = time.perf_counter()
            rec = recover(os.path.join(tmp_root, f"n{n}"))
            restore_ms = (time.perf_counter() - t0) * 1e3
            want = np.asarray(digest_mod.digest_of(fleet, uni), np.uint64)
            got = np.asarray(
                digest_mod.digest_of(rec.batch, rec.universe), np.uint64)
            assert np.array_equal(got, want), (
                "durable parity gate: restored fleet's digest vector "
                "diverged from the live one"
            )
            out[f"durable_snapshot_ms_{n}"] = round(snapshot_ms, 3)
            out[f"durable_restore_ms_{n}"] = round(restore_ms, 3)
            out[f"durable_snapshot_bytes_{n}"] = int(snap.nbytes)
            log(f"durable: N={n}  snapshot {snapshot_ms:.1f}ms "
                f"({snap.nbytes / 1e6:.1f}MB)  restore+verify "
                f"{restore_ms:.1f}ms")
            dur.close()
            del fleet, rec

        # WAL append: the per-write hot-path cost (fsync'd frames)
        dur = Durability(os.path.join(tmp_root, "wal"), retain=2)
        b = 256
        ops = OpBatch(
            kind=np.zeros(b, np.uint8),
            obj=np.arange(b, dtype=np.int64) % 997,
            actor=np.zeros(b, np.int32),
            counter=np.arange(1, b + 1, dtype=np.uint64),
            member=np.arange(b, dtype=np.int32))
        reps = 8 if SMALL else 64
        dur.wal_append(ops)  # warm (opens the segment)
        t0 = time.perf_counter()
        for _ in range(reps):
            dur.wal_append(ops)
        wal_s = time.perf_counter() - t0
        per_op_us = wal_s / (reps * b) * 1e6
        out["durable_wal_append_us_per_op"] = round(per_op_us, 3)
        log(f"durable: WAL append {per_op_us:.2f}us/op "
            f"({b}-op fsync'd frames)")
        dur.close()
    finally:
        shutil.rmtree(tmp_root, ignore_errors=True)

    e2e_s = _JSON_STATE.get("e2e_wire_s")
    if e2e_s:
        # the e2e workload's op volume, shaped as 256-op frames — what
        # WAL-ahead ingest would add to that run's wall
        if SMALL:
            n, chunk, r = 2_000, 1_000, 4
        else:
            n, chunk, r = 1_250_000, 62_500, 8
        ops_total = max(2, n // chunk) * r * b
        frac = (ops_total * per_op_us * 1e-6) / e2e_s
        out["durable_wal_frac"] = round(frac, 5)
        log(f"durable: WAL-ahead at e2e volume = {ops_total} ops x "
            f"{per_op_us:.2f}us = {ops_total * per_op_us * 1e-3:.0f}ms "
            f"vs e2e_wire {e2e_s:.2f}s -> {frac:.2%} (bar: <5%)")
        if e2e_s >= 0.5:
            assert frac < 0.05, (
                f"WAL-ahead ingest costs {frac:.1%} of bench_e2e_wire "
                "wall (bar: <5%) — did the append stop batching frames?"
            )
        else:
            log("durable: e2e_wire too small to gate against (smoke "
                "shape); per-op costs recorded")
    else:
        log("durable: e2e_wire did not run; per-op costs only")
    return out


def bench_stability():
    """Convergence-observatory cost gate (the obs/stability stage):
    (1) the jitted frontier fold (``clock[N, A] -> vv[S, A]``) wall at
    1k/64k/1M objects — it runs once per converged session (memoized
    per batch, so idle rounds pay zero); (2) one full lattice-audit
    pass (sampled self-merge through the wire codec + digest
    re-check + frontier soundness cross-checks) at each size — it runs
    once per gossip round, so its cost is gated <1% of the measured
    ``bench_e2e_wire`` wall; (3) zero violations asserted across every
    healthy audit (the ``stability.audit.violations`` counter must not
    move — a mover here is a lattice-stack bug, not a perf story)."""
    import jax.numpy as jnp

    from crdt_tpu.batch import OrswotBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.obs import stability as stability_mod
    from crdt_tpu.utils import tracing as _tracing
    from crdt_tpu.utils.interning import Universe

    cfg = CrdtConfig(num_actors=8, member_capacity=8, deferred_capacity=4,
                     counter_bits=32)
    uni = Universe.identity(cfg)
    sizes = (1_000, 16_000, 64_000) if SMALL else (1_000, 64_000, 1_000_000)
    out = {}
    worst_audit_s = 0.0
    violations_before = _tracing.counters().get(
        "stability.audit.violations", 0)
    for n in sizes:
        batch = OrswotBatch.zeros(n, uni)
        col = np.zeros(n, np.int32)
        for j in range(3):
            batch = batch.apply_add(
                col, np.full(n, j + 1, np.uint32),
                np.full(n, j, np.int32))
        subtrees, span = stability_mod.subtree_layout(n)
        clock = np.asarray(batch.clock)
        pad = subtrees * span - n
        if pad:
            clock = np.concatenate(
                [clock, np.zeros((pad, clock.shape[1]), clock.dtype)])
        dev = jnp.asarray(clock)
        kern = stability_mod._frontier_kernel(subtrees)
        np.asarray(kern(dev))  # compile + warm
        iters = 10
        t0 = time.perf_counter()
        for _ in range(iters):
            np.asarray(kern(dev))
        fold_s = (time.perf_counter() - t0) / iters
        out[f"stability_frontier_fold_ms_{n}"] = round(fold_s * 1e3, 4)

        trk = stability_mod.StabilityTracker(seed=n)
        rep = trk.audit(batch, uni, sample=8)  # warm the sampled path
        assert rep.ok, f"healthy audit reported violations: {rep.violations}"
        iters = 5
        t0 = time.perf_counter()
        for _ in range(iters):
            rep = trk.audit(batch, uni, sample=8)
            assert rep.ok, \
                f"healthy audit reported violations: {rep.violations}"
        audit_s = (time.perf_counter() - t0) / iters
        out[f"stability_audit_ms_{n}"] = round(audit_s * 1e3, 4)
        worst_audit_s = max(worst_audit_s, audit_s)
        log(f"stability: N={n}  frontier fold {fold_s*1e3:.3f}ms "
            f"({subtrees} subtrees)  audit {audit_s*1e3:.3f}ms "
            f"({rep.checks} checks, 0 violations)")
        del batch, dev

    assert _tracing.counters().get(
        "stability.audit.violations", 0) == violations_before, (
        "the healthy bench run moved stability.audit.violations — the "
        "lattice auditor found a real bug; read the "
        "stability.audit_violation flight events"
    )
    e2e_s = _JSON_STATE.get("e2e_wire_s")
    if e2e_s:
        # one audit per gossip round: the per-round observatory cost
        frac = worst_audit_s / e2e_s
        out["stability_audit_frac"] = round(frac, 6)
        log(f"stability: worst audit {worst_audit_s*1e3:.2f}ms vs "
            f"e2e_wire {e2e_s:.2f}s -> {frac:.4%} (bar: <1%)")
        if e2e_s >= 0.5:
            assert frac < 0.01, (
                f"one lattice audit costs {frac:.2%} of bench_e2e_wire "
                "wall (bar: <1%) — did the sample stop being "
                "budget-bounded?"
            )
        else:
            log("stability: e2e_wire too small to gate against (smoke "
                "shape); per-pass costs recorded")
    else:
        log("stability: e2e_wire did not run; per-pass costs only")
    return out


def bench_heat():
    """Heat-observatory cost + correctness gate (the obs/heat stage):
    (1) the always-on record path's per-update wall (one subtree fold
    + one Space-Saving sketch update at the steady-state 4k batch
    shape) gated <1% of the measured ``bench_e2e_wire`` wall — the
    sketch rides EVERY serve gather / op drain / delta apply, so its
    unit cost is the whole story; (2) on a seeded
    ``WorkloadGen(zipf_s=1.2)`` mixed run at 1k and 64k objects:
    top-16 recall >= 0.9 vs exact host counts and the fitted Zipf
    exponent within +-0.15 of ground truth (the acceptance bar)."""
    from crdt_tpu.obs import heat as heat_mod
    from crdt_tpu.obs.metrics import MetricsRegistry
    from crdt_tpu.utils.workload import WorkloadGen

    sizes = (1_000, 16_000) if SMALL else (1_000, 64_000)
    batch_rows = 4_096
    draws = 60_000 if SMALL else 200_000
    out = {}
    worst_update_s = 0.0
    for n in sizes:
        gen = WorkloadGen(n, seed=29, zipf_s=1.2, read_frac=0.5)
        trk = heat_mod.HeatTracker(registry=MetricsRegistry())
        exact = np.zeros(n, np.int64)
        for _ in range(draws // batch_rows):
            keys, is_read = gen.draw_mixed(batch_rows)
            np.add.at(exact, keys, 1)
            reads, writes = keys[is_read], keys[~is_read]
            if reads.size:
                trk.record_reads(reads, n, mode="eventual")
            if writes.size:
                trk.record_writes(writes, n)
        hot = trk.hot(16)
        true_top = set(np.argsort(-exact, kind="stable")[:16].tolist())
        recall = len({h["obj"] for h in hot} & true_top) / 16
        s_hat = trk.snapshot()["zipf"]["s_hat"]
        out[f"heat_topk_recall_{n}"] = round(recall, 3)
        assert recall >= 0.9, (
            f"heat sketch top-16 recall {recall:.2f} < 0.9 at N={n} — "
            "the Space-Saving table lost real heavy hitters"
        )
        assert s_hat is not None, f"no Zipf fit at N={n}"
        zipf_err = abs(s_hat - 1.2)
        out[f"heat_zipf_err_{n}"] = round(zipf_err, 4)
        assert zipf_err <= 0.15, (
            f"heat Zipf estimate {s_hat:.3f} off ground truth 1.2 by "
            f"{zipf_err:.3f} (bar: <=0.15) at N={n}"
        )
        # per-update wall at the warm steady-state batch shape: one
        # subtree fold + one sketch update + <=16 counter incs
        keys = gen.draw(batch_rows)
        trk.record_reads(keys, n)  # warm this exact rung
        iters = 30
        t0 = time.perf_counter()
        for _ in range(iters):
            trk.record_reads(keys, n)
        upd_s = (time.perf_counter() - t0) / iters
        out[f"heat_update_ms_{n}"] = round(upd_s * 1e3, 4)
        worst_update_s = max(worst_update_s, upd_s)
        log(f"heat: N={n}  recall@16 {recall:.2f}  zipf "
            f"{s_hat:.3f} (err {zipf_err:.3f})  update "
            f"{upd_s*1e3:.3f}ms/{batch_rows} rows")
    e2e_s = _JSON_STATE.get("e2e_wire_s")
    if e2e_s:
        frac = worst_update_s / e2e_s
        out["heat_update_frac"] = round(frac, 6)
        log(f"heat: worst update {worst_update_s*1e3:.2f}ms vs "
            f"e2e_wire {e2e_s:.2f}s -> {frac:.4%} (bar: <1%)")
        if e2e_s >= 0.5:
            assert frac < 0.01, (
                f"one always-on heat update costs {frac:.2%} of "
                "bench_e2e_wire wall (bar: <1%) — the sketch stopped "
                "being a per-batch rounding error"
            )
        else:
            log("heat: e2e_wire too small to gate against (smoke "
                "shape); per-update costs recorded")
    else:
        log("heat: e2e_wire did not run; per-update costs only")
    return out


def bench_mesh():
    """Mesh-sharded fleet stage (crdt_tpu.mesh): the whole anti-entropy
    round as ONE pjit'd step over the object mesh, at 1k/64k/1M objects
    across mesh sizes {1,2,4,8} (clamped to visible devices) — step
    wall per rung plus the digest all_gather's byte bill, parity-gated
    byte-identical to the unsharded merge+digest control at every
    (size, mesh) point."""
    import jax

    from crdt_tpu import mesh as mesh_mod
    from crdt_tpu.batch import OrswotBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.sync import digest as digest_mod
    from crdt_tpu.utils.interning import Universe
    from crdt_tpu.utils.testdata import anti_entropy_fleets

    n_dev = len(jax.devices())
    sizes = [s for s in mesh_mod.MESH_SIZES if s <= n_dev]
    if len(sizes) < len(mesh_mod.MESH_SIZES):
        log(f"mesh: {n_dev} visible device(s) — running mesh {sizes} "
            "only (XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            "unlocks the full ladder)")
    a, m, d = 8, 8, 2
    uni = Universe.identity(CrdtConfig(num_actors=a, member_capacity=m,
                                       deferred_capacity=d,
                                       counter_bits=32))
    rng = np.random.RandomState(23)
    fleet_sizes = (1_000, 16_000) if SMALL else (1_000, 64_000, 1_000_000)
    template_rows = 65_536
    out = {}
    for n in fleet_sizes:
        if remaining_budget() < 15:
            log(f"mesh: budget low, stopping before N={n}")
            break
        # host-side generation stays bounded: fleets above the template
        # size tile a 64k template (content repetition does not change
        # the kernels' work — dense data-oblivious planes)
        rows = min(n, template_rows)
        reps = anti_entropy_fleets(rng, rows, a, m, d, 2, base=3,
                                   novel=1, deferred_frac=0.25)
        planes = []
        for rep in reps:
            if n > rows:
                tiles = -(-n // rows)
                rep = tuple(np.concatenate([p] * tiles, axis=0)[:n]
                            for p in rep)
            planes.append(rep)
        A = OrswotBatch(*planes[0])
        B = OrswotBatch(*planes[1])
        control = np.asarray(digest_mod.digest_of(A.merge(B), uni),
                             dtype=np.uint64)
        for S in sizes:
            sa = mesh_mod.ShardedBatch.shard(A, uni, shards=S)
            sb = mesh_mod.ShardedBatch.shard(B, uni, shards=S)
            res = mesh_mod.anti_entropy_step(sa, sb)  # warm + parity
            assert np.array_equal(res.digests, control), (
                f"mesh step digests diverged from the unsharded "
                f"control at N={n}, mesh={S}"
            )
            iters = 3 if n >= 64_000 else 10
            t0 = time.perf_counter()
            for _ in range(iters):
                mesh_mod.anti_entropy_step(sa, sb, check=False)
            step_s = (time.perf_counter() - t0) / iters
            gather_bytes = sa.layout.padded * res.digests.dtype.itemsize
            out[f"mesh_step_ms_{n}_s{S}"] = round(step_s * 1e3, 3)
            out[f"mesh_gather_bytes_{n}_s{S}"] = int(gather_bytes)
            log(f"mesh: N={n} S={S} step {step_s*1e3:.2f}ms  digest "
                f"all_gather {gather_bytes}B  parity OK")
    return out


def bench_bandwidth_floor():
    """Same-window HBM bandwidth floor (VERDICT r3 item 1): a chained
    elementwise ``jnp.maximum`` over the north-star chunk's 256 MB dots
    plane — the cheapest op touching the same footprint the merge
    kernels stream.  On the tunneled chip this is the platform ceiling
    (measured 8.5 GB/s vs ~819 GB/s datasheet, reports/TPU_LATENCY.md
    item 6), so quoting the headline relative to it separates kernel
    efficiency from tunnel degradation.  TPU-only."""
    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        return None
    from crdt_tpu.utils.benchtime import chain_timer, sync_overhead

    if SMALL:
        n, a, m = 2_000, 16, 8
    else:
        n, a, m = 62_500, 64, 16
    rng = np.random.RandomState(7)
    dots = jnp.asarray(rng.randint(0, 100, size=(n, m, a), dtype=np.uint32))
    dots_b = jnp.asarray(rng.randint(0, 100, size=(n, m, a), dtype=np.uint32))
    t, _ = chain_timer(
        lambda s, db: (jnp.maximum(s[0], db),),
        (dots,),
        8,
        consts=(dots_b,),
        sync_overhead_s=sync_overhead(),
    )
    # read a + read b + write out per iteration
    floor = 3 * dots.nbytes / t / 1e9
    log(f"bandwidth floor: maximum(dots,dots) {floor:.2f} GB/s (this window)")
    return {"floor_gb_per_s": round(floor, 2)}


def _north_star_parity(template, r, a, m, d, fold_join):
    """Cross-check THE fold being timed (sequential or tree, whichever
    ``fold_join`` the bench selected) against the scalar oracle on a
    sample — a fold regression must fail here, not publish timings."""
    import jax.numpy as jnp

    from crdt_tpu.scalar.orswot import Orswot
    from crdt_tpu.utils.testdata import dense_row_to_scalar

    sample = 8
    small = tuple(np.asarray(x[:, :sample]) for x in template)
    got = [
        np.asarray(x)
        for x in fold_join(tuple(jnp.asarray(x) for x in small))
    ]

    for obj in range(sample):
        merged = Orswot()
        for i in range(r):
            merged.merge(
                dense_row_to_scalar(*(x[i, obj] for x in small))
            )
        merged.merge(Orswot())  # defer plunger
        got_members = {int(mid) for mid in got[1][obj] if int(mid) != -1}
        want_members = set(merged.value().val)
        assert got_members == want_members, (
            f"north★ parity violation at object {obj}: "
            f"{sorted(got_members)} != {sorted(want_members)}"
        )
    log(f"north★ parity sample: batch fold == scalar fold on {sample} objects")


def parity_anchor():
    """Config 1 + value() parity: scalar CPU reference vs batch path."""
    from crdt_tpu import GCounter, Orswot
    from crdt_tpu.batch import GCounterBatch, OrswotBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.utils.interning import Universe

    # GCounter: 2 replicas, 4 actors (config 1)
    uni = Universe(CrdtConfig(num_actors=4, member_capacity=8, deferred_capacity=4))
    a, b = GCounter(), GCounter()
    for actor in ("A", "B", "A", "C"):
        a.apply(a.inc(actor))
    for actor in ("B", "D"):
        b.apply(b.inc(actor))
    expected = a.clone()
    expected.merge(b)
    got = (
        GCounterBatch.from_scalar([a], uni)
        .merge(GCounterBatch.from_scalar([b], uni))
        .to_scalar(uni)[0]
    )
    # a = {A:2, B:1, C:1}, b = {B:1, D:1} ⇒ join value 2+1+1+1 = 5
    assert got.value() == expected.value() == 5, (got.value(), expected.value())

    # Orswot sample: batch N-way join value() == scalar N-way join value()
    uni = Universe(CrdtConfig(num_actors=8, member_capacity=16, deferred_capacity=8))
    rng = np.random.RandomState(3)
    fleets = []
    for _ in range(4):
        row = []
        for _ in range(8):
            s = Orswot()
            for _ in range(rng.randint(0, 6)):
                actor, member = int(rng.randint(0, 8)), int(rng.randint(0, 9))
                ctx = s.value().derive_add_ctx(actor)
                s.apply(s.add(member, ctx))
            row.append(s)
        fleets.append(row)
    batches = [OrswotBatch.from_scalar(row, uni) for row in fleets]
    acc = batches[0]
    for nxt in batches[1:]:
        acc = acc.merge(nxt)
    got_sets = acc.value_sets(uni)
    expected_sets = []
    for i in range(8):
        merged = Orswot()
        for row in fleets:
            merged.merge(row[i])
        merged.merge(Orswot())
        expected_sets.append(merged.value().val)
    assert got_sets == expected_sets, "value() parity violation"
    log("config1 parity anchor: scalar == batch (GCounter value, Orswot value sets)")


_PROBE_LOG = os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_probe_diag.txt")


def bench_bulk_ingest():
    """Scalar↔dense bulk conversion at north-star-relevant volume: 1M
    scalar Orswots in and back out (VERDICT r01 item 8 — the per-element
    loops this replaced made real-data ingest the dominant end-to-end
    cost)."""
    from crdt_tpu.batch import OrswotBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.scalar.orswot import Orswot
    from crdt_tpu.scalar.vclock import VClock
    from crdt_tpu.utils.interning import Universe

    def run_once(n, rng):
        actors = rng.randint(0, 16, size=(n, 3))
        counters = rng.randint(1, 50, size=(n, 3))
        members = rng.randint(0, 1 << 22, size=(n, 2))
        states = []
        for i in range(n):
            s = Orswot()
            s.clock = VClock({int(actors[i, 0]): int(counters[i, 0]),
                              int(actors[i, 1]): int(counters[i, 1])})
            s.entries[int(members[i, 0])] = VClock({int(actors[i, 0]): int(counters[i, 0])})
            s.entries[int(members[i, 1])] = VClock({int(actors[i, 1]): int(counters[i, 1])})
            states.append(s)

        uni = Universe(CrdtConfig(num_actors=16, member_capacity=4, deferred_capacity=2))
        t0 = time.perf_counter()
        batch = OrswotBatch.from_scalar(states, uni)
        t_in = time.perf_counter() - t0
        t0 = time.perf_counter()
        back = batch.to_scalar(uni)
        t_out = time.perf_counter() - t0
        sample = rng.randint(0, n, size=16)
        for i in sample:
            assert back[i].value().val == states[i].value().val, \
                "ingest round-trip parity"
        return t_in, t_out

    def _uv(v):
        out = bytearray()
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                out.append(b | 0x80)
            else:
                out.append(b)
                return bytes(out)

    def synth_wire_blobs(n, rng):
        """Wire blobs for the bench's 2-dot/2-member shape, synthesized
        without scalar objects (1M ``to_binary`` calls cost ~110s; this
        loop ~15s — setup, not measurement).  Byte-compatible with the
        serde grammar; a parity gate on REAL to_binary blobs runs first."""
        actors = rng.randint(0, 16, size=(n, 2))
        counters = rng.randint(1, 50, size=(n, 2))
        members = rng.randint(0, 1 << 22, size=(n, 2))
        blobs = []
        ap = blobs.append
        for i in range(n):
            a0, a1 = int(actors[i, 0]), int(actors[i, 1])
            if a0 == a1:
                a1 = (a1 + 1) % 16
            c0, c1 = int(counters[i, 0]), int(counters[i, 1])
            m0, m1 = int(members[i, 0]), int(members[i, 1])
            if m0 == m1:
                m1 = (m1 + 1) % (1 << 22)  # dict semantics would dedupe
            p0 = b"\x03" + _uv(2 * a0) + b"\x03" + _uv(2 * c0)
            p1 = b"\x03" + _uv(2 * a1) + b"\x03" + _uv(2 * c1)
            if a1 < a0:
                p0, p1 = p1, p0
            # members in to_binary's canonical order: sorted by ENCODED
            # key bytes (serde sorts enc_bytes_of(member), which is NOT
            # numeric order for LEB128) — the parser's strictly-ascending
            # check (round 4) rejects anything else to the Python path,
            # which silently cost this stage ~50% native coverage
            k0 = b"\x03" + _uv(2 * m0)
            k1 = b"\x03" + _uv(2 * m1)
            ent0 = k0 + b"\x20" + _uv(1) + b"\x03" + _uv(2 * a0) + b"\x03" + _uv(2 * c0)
            ent1 = k1 + b"\x20" + _uv(1) + b"\x03" + _uv(2 * a1) + b"\x03" + _uv(2 * c1)
            if k1 < k0:
                ent0, ent1 = ent1, ent0
            ap(b"\x26" + _uv(2) + p0 + p1 + _uv(2) + ent0 + ent1 + _uv(0))
        return blobs

    def bench_wire_path(rng):
        """The bulk wire path: native parallel decode into dense planes
        (identity universe) + device-side COO egress (VERDICT r3 item 3)."""
        from crdt_tpu.utils.interning import Universe as _Universe

        import jax
        import jax.numpy as jnp

        iuni = _Universe.identity(CrdtConfig(
            num_actors=16, member_capacity=4, deferred_capacity=2,
            counter_bits=32,
        ))
        # parity gate: real to_binary blobs through from_wire must match
        # the Python decode path bit-for-bit on clock/member planes
        from crdt_tpu.utils.serde import from_binary, to_binary

        probe_states = []
        for _ in range(512):
            s = Orswot()
            a = int(rng.randint(0, 16))
            s.clock = VClock({a: int(rng.randint(1, 50))})
            s.entries[int(rng.randint(0, 1 << 22))] = VClock(
                {a: int(s.clock.dots[a])}
            )
            probe_states.append(s)
        pb = [to_binary(s) for s in probe_states]
        # host route for the parity gate: exact-plane comparison needs
        # the wire slot order (the device route canonicalizes slots)
        wq = OrswotBatch.from_wire(pb, iuni, via_device=False)
        wr = OrswotBatch.from_scalar([from_binary(x) for x in pb], iuni)
        for name, x, y in (("clock", wq.clock, wr.clock),
                           ("ids", wq.ids, wr.ids), ("dots", wq.dots, wr.dots)):
            assert bool(jnp.array_equal(x, y)), f"wire parity: {name} diverged"

        # egress parity gate too: to_wire must be byte-identical to
        # to_binary of the scalars
        assert wq.to_wire(iuni) == pb, "wire egress parity diverged"

        n_wire_full = 1_000_000
        n_wire = 200_000 if (_downshift() or SMALL) else n_wire_full
        blobs = synth_wire_blobs(n_wire, rng)  # untimed setup
        from crdt_tpu.utils import tracing

        counters0 = tracing.counters()
        t0 = time.perf_counter()
        wb = OrswotBatch.from_wire(blobs, iuni)
        jax.block_until_ready(wb.clock)
        t_wire = max(time.perf_counter() - t0, 1e-9)
        t0 = time.perf_counter()
        out_blobs = wb.to_wire(iuni)
        t_enc = max(time.perf_counter() - t0, 1e-9)
        del out_blobs
        wire_deltas = tracing.counters_since(counters0)
        t0 = time.perf_counter()
        coo = wb.to_coo()
        for part in coo:
            for col in part:
                np.asarray(col)  # force device->host of the compact columns
        t_coo = max(time.perf_counter() - t0, 1e-9)
        log(
            f"ingest  from_wire {n_wire} blobs: {t_wire:.2f}s "
            f"({n_wire/t_wire/1e6:.2f}M obj/s)  to_wire egress: {t_enc:.2f}s "
            f"({n_wire/t_enc/1e6:.2f}M obj/s)  to_coo egress: {t_coo:.2f}s "
            f"({n_wire/t_coo/1e6:.2f}M obj/s)"
        )
        wire_out = {
            "ingest_wire_obj_per_sec": round(n_wire / t_wire, 1),
            "egress_wire_obj_per_sec": round(n_wire / t_enc, 1),
            "egress_coo_obj_per_sec": round(n_wire / t_coo, 1),
        }
        # path-taken accounting (VERDICT r5 weak #2): the silent-fallback
        # class of regression must be visible from the artifact alone
        nf_in = tracing.native_fraction(wire_deltas, "wire.orswot.from_wire")
        nf_out = tracing.native_fraction(wire_deltas, "wire.orswot.to_wire")
        if nf_in is not None:
            wire_out["ingest_wire_native_fraction"] = round(nf_in, 4)
        if nf_out is not None:
            wire_out["egress_wire_native_fraction"] = round(nf_out, 4)
        reasons = {
            k: v for k, v in wire_deltas.items() if ".fallback_reason." in k
        }
        if reasons:
            wire_out["wire_fallback_reasons"] = reasons
        if n_wire < n_wire_full and not SMALL:
            wire_out["wire_downshift"] = f"{n_wire}/{n_wire_full}"
        return wire_out

    n_full = 1_000_000 if not SMALL else 20_000
    rng = np.random.RandomState(4)
    n = n_full
    if not SMALL:
        # size the measured volume to the budget from a 20k probe: the
        # tunneled TPU path has measured as slow as ~21k obj/s in /
        # ~4.5k obj/s out (BENCH_tpu_window.json), where 1M objects
        # would eat ~270s; the obj/s rates the JSON reports are
        # volume-independent at these scales
        t_in_p, t_out_p = run_once(20_000, np.random.RandomState(7))
        per_obj = (t_in_p + t_out_p) / 20_000 + 30e-6  # +scalar-build cost
        slice_budget = max(45.0, min(remaining_budget() * 0.3, 240.0))
        n = int(min(n_full, max(50_000, slice_budget / per_obj)))
    t_in, t_out = run_once(n, rng)
    log(
        f"ingest  from_scalar {n} objects: {t_in:.1f}s ({n/t_in/1e3:.0f}k obj/s)  "
        f"to_scalar: {t_out:.1f}s ({n/t_out/1e3:.0f}k obj/s)"
    )
    out = {
        "ingest_obj_per_sec": round(n / t_in, 1),
        "egress_obj_per_sec": round(n / t_out, 1),
        "ingest_objects": n,
    }
    # the BULK path: native wire decode + COO egress.  A broken native
    # build degrades to the scalar-path numbers above, never a lost bench.
    try:
        out.update(bench_wire_path(rng))
    except Exception as e:  # noqa: BLE001
        log(f"ingest wire path unavailable: {type(e).__name__}: {str(e)[:200]}")
    return out


def bench_kernelcheck():
    """Kernelcheck coverage gauge (the static-analysis bench satellite):
    runs the jaxpr tier exactly as ``scripts/ci.sh`` does — a CPU-pinned
    subprocess of ``python -m crdt_tpu.analysis --kernels --json`` — and
    reports analyzer wall time plus kernels-covered counts into the
    artifact tail.  The point is the COVERAGE trend, not the seconds: a
    new kernel module escaping the manifest shows up here as a
    kernels/cases count that stopped growing while the tree did (and as
    a hard tier-1 failure via the kernel-manifest AST rule); a wall-time
    blowup means a ladder got expensive enough to threaten the <60 s CI
    budget."""
    import subprocess

    proc = subprocess.run(
        [sys.executable, "-m", "crdt_tpu.analysis", "--kernels", "--json"],
        cwd=os.path.dirname(os.path.abspath(__file__)),
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    out = json.loads(proc.stdout)
    kc = out["kernelcheck"]
    log(
        f"kernelcheck: rc={proc.returncode}  {kc['kernels']} kernels "
        f"({kc['traced']} traced, {kc['cases']} cases), "
        f"{len(out['findings'])} finding(s), {kc['elapsed_s']}s"
    )
    return {
        "kernelcheck_rc": proc.returncode,
        "kernelcheck_kernels": kc["kernels"],
        "kernelcheck_traced": kc["traced"],
        "kernelcheck_cases": kc["cases"],
        "kernelcheck_findings": len(out["findings"]),
        "kernelcheck_trace_errors": len(kc["trace_errors"]),
        "kernelcheck_wall_s": kc["elapsed_s"],
    }


def bench_shardcheck():
    """Shardcheck coverage gauge: runs the sharding-contract tier
    exactly as ``scripts/ci.sh`` does — a CPU-pinned subprocess of
    ``python -m crdt_tpu.analysis --shard --json`` — and reports
    analyzer wall plus contract-coverage counts.  As with kernelcheck,
    the trend is the point: every manifested kernel must carry a
    ShardContract (the manifest refuses undeclared rows, so coverage is
    structurally 100% — the count that matters here is kernels/cases
    growing WITH the tree), and a wall-time blowup means the mesh-case
    ladder is threatening the <60 s CI budget."""
    import subprocess

    proc = subprocess.run(
        [sys.executable, "-m", "crdt_tpu.analysis", "--shard", "--json"],
        cwd=os.path.dirname(os.path.abspath(__file__)),
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    out = json.loads(proc.stdout)
    sc = out["shardcheck"]
    contracts = " ".join(
        f"{k}={v}" for k, v in sorted(sc["contracts"].items()))
    log(
        f"shardcheck: rc={proc.returncode}  {sc['kernels']} kernels "
        f"({contracts}; {sc['traced']} traced, {sc['cases']} cases incl "
        f"{sc['mesh_cases']} mesh-shaped), "
        f"{len(out['findings'])} finding(s), {sc['elapsed_s']}s"
    )
    return {
        "shardcheck_rc": proc.returncode,
        "shardcheck_kernels": sc["kernels"],
        "shardcheck_traced": sc["traced"],
        "shardcheck_cases": sc["cases"],
        "shardcheck_mesh_cases": sc["mesh_cases"],
        "shardcheck_contracts": sc["contracts"],
        "shardcheck_findings": len(out["findings"]),
        "shardcheck_trace_errors": len(sc["trace_errors"]),
        "shardcheck_wall_s": sc["elapsed_s"],
    }


def bench_tpu_validation():
    """On a real TPU backend: compiled-Pallas parity + timing and
    accel-vs-CPU merge parity, in a killable subprocess (a Mosaic hang
    through the remote tunnel must not wedge the bench).  Failures leave a
    captured repro in ``reports/PALLAS_TPU_ATTEMPT.txt``."""
    import subprocess
    import sys

    import jax

    if os.environ.get("CRDT_SKIP_TPU_VALIDATE") == "1":
        # a compiled-Pallas (Mosaic) crash can wedge the tunnel's
        # remote-compile helper; orchestration scripts set this on every
        # bench run except the last of a tunnel window
        log("tpu-validate: skipped (CRDT_SKIP_TPU_VALIDATE=1)")
        return
    if jax.default_backend() != "tpu":
        log("tpu-validate: skipped (backend is not tpu)")
        return
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "scripts", "tpu_validate.py")
    try:
        proc = subprocess.run(
            [sys.executable, script],
            # the run now includes two north-star-scale compiles (see
            # scripts/tpu_validate.py check_pallas_northstar)
            timeout=float(os.environ.get("CRDT_TPU_VALIDATE_TIMEOUT", "1800")),
            capture_output=True,
            text=True,
        )
        for line in proc.stdout.strip().splitlines():
            log(f"tpu-validate: {line}")
        if proc.returncode != 0:
            _write_pallas_repro(
                f"rc={proc.returncode}\nstdout:\n{proc.stdout}\n"
                f"stderr tail:\n{proc.stderr[-4000:]}"
            )
    except subprocess.TimeoutExpired as te:
        err = te.stderr or b""
        if isinstance(err, bytes):
            err = err.decode(errors="replace")
        out = te.stdout or b""
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        # checks that finished before the hang printed flushed JSON lines
        # — surface them, they are results, not casualties
        for line in out.strip().splitlines():
            log(f"tpu-validate: {line}")
        log("tpu-validate: TIMED OUT (Mosaic hang? repro captured)")
        _write_pallas_repro(
            f"timeout after {te.timeout}s — the compiled-Pallas attempt hung "
            f"through the tunnel\nstdout (completed checks):\n{out}\n"
            f"stderr tail:\n{err[-4000:]}"
        )


def _write_pallas_repro(body: str) -> None:
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "reports", "PALLAS_TPU_ATTEMPT.txt")
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(
                "# compiled-Pallas TPU attempt — captured failure\n"
                "# repro: python scripts/tpu_validate.py --pallas\n" + body + "\n"
            )
        log(f"tpu-validate: failure details written to {path}")
    except OSError:
        pass


def _probe_backend(total_budget_s: float) -> bool:
    """True when the default JAX backend initializes in a fresh process.

    Remote-TPU tunnels can wedge so hard that ``jax.devices()`` blocks
    forever; probing in a killable subprocess lets the harness fall back
    to CPU instead of hanging the whole benchmark run.  The probe retries
    with growing timeouts until ``total_budget_s`` is spent, and writes
    every attempt's captured stderr to ``bench_probe_diag.txt`` so a
    wedged tunnel leaves an actionable diagnostic behind."""
    import datetime
    import subprocess
    import sys

    # devices() + one tiny dispatch: a tunnel that enumerates devices but
    # cannot execute must not be declared healthy
    probe_src = (
        "import jax, jax.numpy as jnp; ds = jax.devices(); "
        "x = (jnp.ones((8,)) + 1).block_until_ready(); "
        "print('PROBE_OK', jax.default_backend(), len(ds))"
    )
    lines = [
        f"# backend probe diagnostics — {datetime.datetime.now().isoformat()}",
        f"# JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS')!r}  "
        f"budget={total_budget_s:.0f}s",
    ]
    attempt, spent = 0, 0.0
    fast_failures = 0
    ok = False
    while spent < total_budget_s and not ok:
        attempt += 1
        timeout_s = min(60.0 * (2 ** (attempt - 1)), total_budget_s - spent)
        if timeout_s <= 1:
            break
        t0 = time.perf_counter()
        timed_out = False
        try:
            proc = subprocess.run(
                [sys.executable, "-u", "-c", probe_src],
                timeout=timeout_s,
                capture_output=True,
                text=True,
            )
            ok = proc.returncode == 0 and "PROBE_OK" in proc.stdout
            detail = (
                f"rc={proc.returncode} stdout={proc.stdout.strip()!r} "
                f"stderr_tail={proc.stderr[-2000:]!r}"
            )
        except subprocess.TimeoutExpired as te:
            timed_out = True
            err = te.stderr or b""
            if isinstance(err, bytes):
                err = err.decode(errors="replace")
            detail = f"TIMEOUT after {timeout_s:.0f}s stderr_tail={err[-2000:]!r}"
        dt = time.perf_counter() - t0
        spent += dt
        lines.append(f"attempt {attempt}: {dt:.1f}s — {detail}")
        log(f"backend probe attempt {attempt}: {'ok' if ok else detail[:200]}")
        if not ok and not timed_out:
            # deterministic failure (plugin/import error), not a slow
            # tunnel — retrying for the whole budget would spawn hundreds
            # of identical failing subprocesses
            fast_failures += 1
            if fast_failures >= 2:
                lines.append("# two non-timeout failures — deterministic, not retrying")
                break
    lines.append(f"# verdict: {'backend healthy' if ok else 'backend unreachable — falling back to cpu'}")
    try:
        with open(_PROBE_LOG, "w") as f:
            f.write("\n".join(lines) + "\n")
    except OSError:
        pass
    return ok


def _emit_regression_warnings(quiet=False):
    """Diff the current record against the latest prior BENCH_r*.json
    and emit `regression_warnings` (VERDICT r5 weak #6).  Called twice:
    once before the required validation stage (so a watchdog kill
    mid-validation still leaves the field in the banked record) and
    once after the last stage (final values win — emit() reprints the
    whole record)."""
    try:
        from benchkit import artifacts

        prior_name, prior = artifacts.latest_prior_artifact(
            os.path.dirname(os.path.abspath(__file__))
        )
        if prior is None:
            emit(regression_warnings=[], regression_baseline=None)
            return
        warns = artifacts.regression_warnings(prior, _JSON_STATE)
        if not quiet:
            for w in warns[:8]:
                log(f"regression warning vs {prior_name}: {w}")
        # counter-family diff: a family that vanished round-over-round
        # (especially a *.native leaf) is the silent-fallback smell the
        # always-on counters exist to catch
        fam_warns = artifacts.counter_family_warnings(
            prior.get("obs_counters"), _JSON_STATE.get("obs_counters")
        )
        if not quiet:
            for w in fam_warns[:8]:
                log(f"counter family warning vs {prior_name}: {w}")
        emit(regression_warnings=warns, regression_baseline=prior_name,
             counter_family_warnings=fam_warns)
    except Exception as e:  # noqa: BLE001 — diffing must never cost the bench
        log(f"artifact diffing failed: {type(e).__name__}: {str(e)[:200]}")


def _emit_obs_snapshot():
    """Publish the always-on counter registry into the artifact tail so
    :mod:`benchkit.artifacts` can diff counter FAMILIES round over
    round (the obs tentpole): every counter the run incremented, by
    name.  Values are workload-sized so the ratio differ skips them
    (nested dict); what matters is which families exist at all."""
    try:
        from crdt_tpu.utils import tracing

        emit(obs_counters=tracing.counters())
    except Exception as e:  # noqa: BLE001 — telemetry must never cost the bench
        log(f"obs snapshot failed: {type(e).__name__}: {str(e)[:200]}")


def main():
    _install_budget_watchdog()
    banked = banked_mod.load_banked()
    if banked is not None:
        banked_mod.BANKED_HEADLINE = True
        emit(
            value=banked["value"],
            kernel=banked.get("kernel", "tpu_window_capture"),
            platform="tpu",
            backend_fallback=False,
            headline_source="banked_window",
            banked_captured_at=banked.get("captured_at"),
            banked_captured_rev=banked.get("captured_rev"),
        )

    plat = os.environ.get("CRDT_BENCH_PLATFORM")
    fallback = False
    probe_budget = float(os.environ.get("CRDT_BENCH_PROBE_TIMEOUT", "120"))
    # the probe must leave enough budget for the CPU-fallback body
    probe_budget = min(probe_budget, max(30.0, remaining_budget() - 300))
    if not plat and not _probe_backend(probe_budget):
        log(
            f"WARNING: default backend unreachable within the {probe_budget:.0f}s "
            "probe budget (wedged tunnel?) — falling back to cpu; numbers are NOT "
            f"accelerator numbers (platform recorded in the JSON line; probe "
            f"diagnostics in {_PROBE_LOG})"
        )
        plat = "cpu"
        fallback = True
    banked_mod.IS_FALLBACK = fallback

    import jax

    # local smoke runs force a platform (the ambient axon plugin overrides
    # the JAX_PLATFORMS env var, so use the config knob directly)
    if plat:
        jax.config.update("jax_platforms", plat)

    backend = jax.default_backend()
    log(f"backend: {backend}  devices: {len(jax.devices())}  small={SMALL}  "
        f"budget={_BUDGET_S:.0f}s (remaining {remaining_budget():.0f}s)")

    # validation gates are REQUIRED: never budget-skipped (VERDICT r5
    # weak #3 — budget starvation was eating validation while contender
    # stages ran; a bench whose parity anchor never ran has no business
    # publishing numbers)
    run_stage("parity_anchor", 20, parity_anchor, required=True)
    # the headline FIRST: everything else is secondary evidence (stage
    # order is budget-risk order, not report order)
    ns = run_stage("north_star", 90, bench_north_star)
    if ns is not None:
        rate, elision, ns_templates, ns_kernel = ns
        banked_mod.emit_headline(rate, {"kernel": ns_kernel}, backend, fallback)
        emit(**elision)
    else:
        rate, elision, ns_templates, ns_kernel = None, {}, None, None

    rate4 = run_stage("config4", 45, bench_orswot_pairwise)
    if rate4 is not None:
        emit(config4_merges_per_sec=round(rate4, 1))
    run_stage("clock_merges", 60, bench_clock_merges)
    ingest = run_stage("ingest", 60, bench_bulk_ingest)
    if ingest is not None:
        emit(**ingest)
    e2e_wire = run_stage("e2e_wire", 120, bench_e2e_wire)
    if e2e_wire is not None:
        emit(**e2e_wire)
    # budget-skippable by design (required=False): the sync stage is a
    # contender metric, and must never crowd out the parity anchor or
    # the TPU validation below
    sync_res = run_stage("sync", 60, bench_sync)
    if sync_res is not None:
        emit(**sync_res)
    # budget-skippable: digest-tree descent vs the flat exchange —
    # digest bytes per round at 0/0.1%/1%/10%/dense divergence (uniform
    # + Zipf hot-key), live sessions at bench-fleet shape plus the
    # 1M-object planner rung; parity- and cutover-gated inside
    tree_res = run_stage("digest_tree", 90, bench_digest_tree)
    if tree_res is not None:
        emit(**tree_res)
    # budget-skippable: the op-based write front-end (ops/s through the
    # scatter-fold + wire bytes/op vs the delta-sync equivalent;
    # parity-gated against the scalar apply loop inside the stage)
    oplog_res = run_stage("oplog", 45, bench_oplog)
    if oplog_res is not None:
        emit(**oplog_res)
    # budget-skippable: the batched read front-end (reads/s through the
    # jitted gather at 1k/16k/64k-object fleets under the Zipf mixed
    # read/write workload, ops/s through the scatter-fold alongside;
    # parity-gated against the scalar ReadCtx loop inside the stage)
    reads_res = run_stage("reads", 45, bench_reads)
    if reads_res is not None:
        emit(**reads_res)
    # budget-skippable: the <1% always-on metrics gate (needs e2e_wire's
    # wall time above to have something to be a fraction OF)
    obs_res = run_stage("obs_overhead", 15, bench_obs_overhead)
    if obs_res is not None:
        emit(**obs_res)
    # budget-skippable: the latency observatory — shaped 50/100/200ms
    # RTT sessions (wall vs SRTT, network_wait_frac, lag percentiles),
    # adaptive-vs-static retransmit timers, and the <1% stamp-overhead
    # gate (families collapsed in benchkit/artifacts.py)
    lat_res = run_stage("latency", 30, bench_latency)
    if lat_res is not None:
        emit(**lat_res)
    # budget-skippable: fleet-observatory encode/merge costs + the <5%
    # piggyback-per-session gate (benchkit/artifacts.py ratio-compares
    # the scale-free ms/frac fields round over round)
    fleet_res = run_stage("fleet_obs", 20, bench_fleet_obs)
    if fleet_res is not None:
        emit(**fleet_res)
    # budget-skippable: plane-occupancy sampling cost (per-sample ms at
    # 1k/64k/1M objects + the <1%-of-e2e gate; exact-bytes parity is
    # asserted inside the stage)
    cap_res = run_stage("capacity_obs", 20, bench_capacity_obs)
    if cap_res is not None:
        emit(**cap_res)
    # budget-skippable: the runtime kernel observatory — per-call
    # wrapper overhead gated <1% of bench_e2e_wire wall, the
    # zero-recompile steady-state assertion, and the per-kernel
    # compile/p50 coverage tail (the `kernel` family collapse in
    # benchkit/artifacts.py warns when a kernel goes dark)
    kobs_res = run_stage("kernel_obs", 20, bench_kernel_obs)
    if kobs_res is not None:
        emit(**kobs_res)
    # budget-skippable: causal-GC settle/re-pack wall + bytes reclaimed
    # over a burst-over-provisioned fleet, parity-gated (digest vectors
    # byte-identical vs the untruncated twin); the `gc` counter family
    # in the obs tail warns if collection stops running round over round
    gc_res = run_stage("gc", 30, bench_gc)
    if gc_res is not None:
        emit(**gc_res)
    # budget-skippable: durability costs — snapshot/restore wall at
    # 1k/64k/1M objects (restore parity-gated by the store's own
    # digest-root check) + fsync'd WAL append overhead, gated <5% of
    # bench_e2e_wire wall at the e2e op volume; the `durable` counter
    # family in the obs tail warns if the layer stops running
    durable_res = run_stage("durable", 30, bench_durable)
    if durable_res is not None:
        emit(**durable_res)
    # budget-skippable: convergence-observatory costs — frontier fold +
    # lattice-audit wall at 1k/64k/1M objects, audit gated <1% of
    # bench_e2e_wire wall, zero violations asserted on the healthy run;
    # the `stability` counter family in the obs tail warns if the
    # auditor stops running
    stability_res = run_stage("stability", 20, bench_stability)
    if stability_res is not None:
        emit(**stability_res)
    # budget-skippable: heat & placement observatory — per-update
    # sketch/fold wall at the steady-state 4k batch shape, gated <1% of
    # bench_e2e_wire wall; top-k recall and Zipf-estimate error asserted
    # at 1k/64k objects; the `heat` counter family in the obs tail warns
    # if traffic attribution stops
    heat_res = run_stage("heat", 25, bench_heat)
    if heat_res is not None:
        emit(**heat_res)
    # budget-skippable: mesh-sharded fleets — one pjit'd anti-entropy
    # step per rung at 1k/64k/1M objects across mesh {1,2,4,8}, digest
    # vectors parity-gated byte-identical to the unsharded control
    mesh_res = run_stage("mesh", 90, bench_mesh)
    if mesh_res is not None:
        emit(**mesh_res)
    # budget-skippable: kernelcheck coverage gauge (analyzer wall time +
    # kernels-covered counts, so a kernel module escaping the manifest
    # shows in the artifact tail as a coverage count that stopped moving)
    kc_res = run_stage("kernelcheck", 40, bench_kernelcheck)
    if kc_res is not None:
        emit(**kc_res)
    # budget-skippable: shardcheck coverage gauge — the sharding-contract
    # tier's wall time plus per-class contract counts (pointwise /
    # reduction / replicated / host_only), so the artifact tail shows
    # contract coverage growing with the kernel manifest
    sc_res = run_stage("shardcheck", 60, bench_shardcheck)
    if sc_res is not None:
        emit(**sc_res)
    # provisional regression tail first: a watchdog kill inside the
    # required validation stage below must not cost the field entirely
    _emit_obs_snapshot()
    _emit_regression_warnings(quiet=True)
    # TPU validation runs BEFORE the optional contenders (resident /
    # pallas / floor) and is never budget-skipped: it is a killable
    # subprocess, so its compiles cannot wedge this process's tunnel
    # helper, and an artifact must not trade validation for contender
    # stages (VERDICT r5 weak #3).  On non-TPU backends it is a no-op.
    run_stage("tpu_validation", 240, bench_tpu_validation, required=True)
    resident = run_stage("resident", 90, bench_north_star_resident)
    if resident is not None:
        emit(
            distinct_objects=resident["distinct_replica_objects"],
            e2e_s=resident["e2e_s"],
            resident_merges_per_sec=resident["resident_merges_per_sec"],
            **(
                {"resident_downshift": resident["resident_downshift"]}
                if "resident_downshift" in resident else {}
            ),
        )
    # the Pallas attempt runs AFTER every jnp metric is banked (a Mosaic
    # crash can wedge the tunnel's compile helper) and can only ever
    # raise the headline, never lose it
    # without a banked executable the attempt pays a ~10-min Mosaic
    # compile (local v5e AOT: 583 s for the aligned scan) — under a
    # tight driver budget the stage should skip cleanly up front and
    # leave the compile to the watcher's 4200 s window budget, instead
    # of blocking until the watchdog rescues the run
    pallas_est = 120 if os.path.exists(AXON_ART_PATH) else 420
    pallas_res = run_stage(
        "pallas_north_star", pallas_est, bench_pallas_north_star, ns_templates
    )
    if pallas_res is not None:
        pallas_rate, pallas_kernel = pallas_res
        if rate is None or pallas_rate > rate:
            kf = {"kernel": pallas_kernel}
            if rate is not None:
                kf["jnp_merges_per_sec"] = round(rate, 1)
            banked_mod.emit_headline(pallas_rate, kf, backend, fallback)
        else:
            emit(pallas_merges_per_sec=pallas_rate, pallas_kernel=pallas_kernel)
    floor = run_stage("bandwidth_floor", 45, bench_bandwidth_floor)
    if floor is not None:
        emit(**floor)
        # quote the live on-chip headline as effective GB/s vs the
        # same-window floor, so the number survives tunnel degradation
        # (VERDICT r3 item 1); only meaningful for kernels with audited
        # traffic accounting and only when the headline is live-TPU
        hl_kernel = _JSON_STATE.get("kernel")
        hl_rate = _JSON_STATE.get("value")
        bpm = axon_bank.BYTES_PER_MERGE.get(hl_kernel)
        if (
            bpm is not None
            and hl_rate
            and floor["floor_gb_per_s"] > 0  # rounded; a dead-slow tunnel can floor at 0.0
            and _JSON_STATE.get("headline_source") == "live"
            and _JSON_STATE.get("platform") == "tpu"
        ):
            eff = hl_rate * bpm / 1e9
            emit(
                headline_eff_gb_per_s=round(eff, 2),
                headline_vs_floor=round(eff / floor["floor_gb_per_s"], 3),
            )

    # final regression tail: recompute over the complete record (the
    # provisional pass before tpu_validation only covered the stages
    # that had run by then)
    _emit_obs_snapshot()
    _emit_regression_warnings()

    if _JSON_STATE.get("value") is None:
        # nothing measured and nothing banked: emit an explicit-failure
        # record rather than no line at all
        _JSON_STATE["value"] = 0.0
        emit(platform=backend, backend_fallback=fallback,
             headline_source="none")
    else:
        emit()  # final re-print so the last stdout line is the full record


if __name__ == "__main__":
    main()
