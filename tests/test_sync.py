"""The digest/delta anti-entropy subsystem (`crdt_tpu.sync`).

Covers the acceptance bar of the sync PR: delta sync converges to
byte-identical state vs the full-state merge path on the same op
history (seeded property sweep across orswot/counter/lww fleets),
idempotent re-sync ships zero deltas, malformed frames are clean
`SyncProtocolError`s (never parser crashes), and a forced digest
collision falls back to full state and still converges byte-identical.
"""

import numpy as np
import pytest

from crdt_tpu.batch import (
    GCounterBatch, LWWRegBatch, OrswotBatch, PNCounterBatch,
)
from crdt_tpu.config import CrdtConfig
from crdt_tpu.error import SyncProtocolError
from crdt_tpu.scalar.gcounter import GCounter
from crdt_tpu.scalar.lwwreg import LWWReg
from crdt_tpu.scalar.orswot import Orswot
from crdt_tpu.scalar.pncounter import PNCounter
from crdt_tpu.sync import digest as sync_digest
from crdt_tpu.sync import delta as sync_delta
from crdt_tpu.sync.delta import (
    OrswotDeltaApplier,
    decode_frame,
    diverged_indices,
    encode_delta_frame,
    encode_digest_frame,
    encode_full_frame,
    gather_blobs,
)
from crdt_tpu.sync.session import SyncSession, sync_pair
from crdt_tpu.utils.interning import Universe

pytestmark = pytest.mark.sync


def _uni(**kw):
    cfg = dict(num_actors=8, member_capacity=16, deferred_capacity=4,
               counter_bits=32)
    cfg.update(kw)
    return Universe.identity(CrdtConfig(**cfg))


def _orswot_fleet(n, seed, actor=1, extra_on=(), rng_members=50):
    """n scalar Orswots from a seed-shared history, plus local ops under
    ``actor`` on the ``extra_on`` rows (the divergence)."""
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        s = Orswot()
        for _ in range(rng.randint(1, 5)):
            s.apply(s.add(int(rng.randint(0, rng_members)),
                          s.value().derive_add_ctx(0)))
        if i % 5 == 0:
            read = s.value()
            if read.val:
                m = sorted(read.val)[0]
                s.apply(s.remove(m, s.contains(m).derive_rm_ctx()))
        out.append(s)
    for i in extra_on:
        s = out[i]
        s.apply(s.add(900 + actor, s.value().derive_add_ctx(actor)))
    return out


# ---- digest canonicality ---------------------------------------------------


def test_digest_slot_order_and_capacity_invariant():
    uni = _uni()
    fleet = _orswot_fleet(64, seed=3)
    b = OrswotBatch.from_scalar(fleet, uni)
    d = sync_digest.digest_of(b)
    # the wire host route preserves wire slot order; the from_scalar
    # route uses insertion order — both must digest identically
    via_wire = OrswotBatch.from_wire(b.to_wire(uni), uni, via_device=False)
    assert np.array_equal(d, sync_digest.digest_of(via_wire))
    # growing the padded capacities is a representation change only
    grown = b.with_capacity(member_capacity=32, deferred_capacity=8)
    assert np.array_equal(d, sync_digest.digest_of(grown))


def test_digest_distinguishes_states():
    uni = _uni()
    base = _orswot_fleet(64, seed=4)
    b = OrswotBatch.from_scalar(base, uni)
    d = sync_digest.digest_of(b)
    # one extra dot on one object must flip exactly that object's lane
    mutated = [s.clone() for s in base]
    mutated[17].apply(
        mutated[17].add(999, mutated[17].value().derive_add_ctx(2))
    )
    d2 = sync_digest.digest_of(OrswotBatch.from_scalar(mutated, uni))
    assert d[17] != d2[17]
    mask = np.ones(64, bool)
    mask[17] = False
    assert np.array_equal(d[mask], d2[mask])


def test_digest_deferred_state_is_visible():
    """A buffered (causally-future) remove is real state and must be
    digested — two replicas differing only in a deferred row diverge."""
    uni = _uni()
    s1, s2 = Orswot(), Orswot()
    for s in (s1, s2):
        s.apply(s.add(1, s.value().derive_add_ctx(0)))
    ctx = s2.contains(1).derive_rm_ctx()
    ctx.clock.witness(5, 10)  # a write s2 has not seen -> remove buffers
    s2.apply(s2.remove(1, ctx))
    assert len(s2.deferred) == 1
    d = sync_digest.digest_of(OrswotBatch.from_scalar([s1, s2], uni))
    assert d[0] != d[1]


def test_counter_and_lww_digests():
    uni = _uni()
    pns = []
    for i in range(8):
        c = PNCounter()
        for _ in range(i + 1):
            c.apply(c.inc(i % 4))
        pns.append(c)
    d = sync_digest.digest_of(PNCounterBatch.from_scalar(pns, uni))
    assert len(set(d.tolist())) == len(pns)
    regs = [LWWReg(val=i, marker=10 + i) for i in range(8)]
    dl = sync_digest.digest_of(LWWRegBatch.from_scalar(regs, uni))
    assert len(set(dl.tolist())) == len(regs)
    # marker-only difference must be visible (same value id)
    regs2 = [LWWReg(val=i, marker=11 + i) for i in range(8)]
    dl2 = sync_digest.digest_of(LWWRegBatch.from_scalar(regs2, uni))
    assert not np.array_equal(dl, dl2)


def test_version_vector_summary():
    uni = _uni()
    fleet = _orswot_fleet(16, seed=9)
    b = OrswotBatch.from_scalar(fleet, uni)
    vv = sync_digest.version_vector(b)
    assert vv.shape == (8,)
    assert vv.dtype == np.uint64
    assert int(np.asarray(b.clock).max()) == int(vv.max())
    fold, count = sync_digest.fleet_summary(sync_digest.digest_of(b))
    assert count == 16


# ---- frame codec -----------------------------------------------------------


def test_frame_roundtrip():
    d = np.arange(10, dtype=np.uint64)
    ftype, payload = decode_frame(encode_digest_frame(d, np.arange(4)))
    got, vv = sync_delta.decode_digest_payload(payload)
    assert np.array_equal(got, d) and np.array_equal(vv, np.arange(4))
    ids = np.array([3, 7], dtype=np.int64)
    ftype, payload = decode_frame(encode_delta_frame(100, ids, [b"ab", b"c"]))
    n, got_ids, blobs = sync_delta.decode_delta_payload(payload)
    assert (n, blobs) == (100, [b"ab", b"c"])
    assert np.array_equal(got_ids, ids)
    ftype, payload = decode_frame(encode_full_frame([b"x", b"", b"yz"]))
    assert sync_delta.decode_full_payload(payload) == [b"x", b"", b"yz"]


@pytest.mark.parametrize("mutate", ["truncate", "tamper", "version", "type"])
def test_malformed_frames_rejected_cleanly(mutate):
    frame = encode_delta_frame(
        8, np.array([1, 2], dtype=np.int64), [b"hello", b"world"]
    )
    if mutate == "truncate":
        bad = frame[:-3]
    elif mutate == "tamper":
        i = len(frame) - 4  # flip a payload byte -> CRC must catch it
        bad = frame[:i] + bytes([frame[i] ^ 0x40]) + frame[i + 1:]
    elif mutate == "version":
        bad = bytes([frame[0] + 1]) + frame[1:]
    else:
        bad = frame[:1] + bytes([0x7F]) + frame[2:]
    with pytest.raises(SyncProtocolError):
        decode_frame(bad)


def test_truncated_delta_inside_session_is_clean():
    """A tampered frame arriving mid-session surfaces as
    SyncProtocolError from sync(), never a parser crash."""
    uni = _uni()
    b = OrswotBatch.from_scalar(_orswot_fleet(8, seed=5), uni)
    session = SyncSession(b, uni)
    peer_digest = encode_digest_frame(np.zeros(8, np.uint64))
    good_delta = encode_delta_frame(8, np.array([0]), [b"\x26\x00\x00\x00"])
    frames = iter([peer_digest, good_delta[:-2]])
    with pytest.raises(SyncProtocolError):
        session.sync(lambda f: None, lambda: next(frames))


def test_fleet_size_mismatch_fails_loudly():
    uni = _uni()
    a = OrswotBatch.from_scalar(_orswot_fleet(8, seed=6), uni)
    b = OrswotBatch.from_scalar(_orswot_fleet(12, seed=6), uni)
    with pytest.raises(SyncProtocolError):
        sync_pair(SyncSession(a, uni), SyncSession(b, uni))


# ---- indexed gather / warm apply -------------------------------------------


def test_gather_blobs_matches_to_wire_subset():
    uni = _uni()
    b = OrswotBatch.from_scalar(_orswot_fleet(64, seed=7), uni)
    full = b.to_wire(uni)
    ids = np.array([0, 5, 31, 63], dtype=np.int64)
    assert gather_blobs(b, ids, uni) == [full[i] for i in ids]
    assert gather_blobs(b, np.zeros(0, np.int64), uni) == []


def test_delta_applier_reuses_buffers():
    uni = _uni()
    base = _orswot_fleet(32, seed=8)
    a = OrswotBatch.from_scalar(base, uni)
    peer_fleet = [s.clone() for s in base]
    for i in (2, 9):
        peer_fleet[i].apply(
            peer_fleet[i].add(901, peer_fleet[i].value().derive_add_ctx(3))
        )
    peer = OrswotBatch.from_scalar(peer_fleet, uni)
    applier = OrswotDeltaApplier(uni)
    ids = np.array([2, 9], dtype=np.int64)
    blobs = gather_blobs(peer, ids, uni)
    out1 = applier.apply(a, ids, blobs)
    staging_before = applier._staging
    # second apply with the same delta size must reuse the same buffers
    out2 = applier.apply(out1, ids, blobs)
    assert applier._staging is staging_before
    ref = a.merge(peer)
    want = gather_blobs(ref, ids, uni)
    assert gather_blobs(out1, ids, uni) == want
    # idempotence: re-applying the same delta changes nothing
    assert out2.to_wire(uni) == out1.to_wire(uni)


def test_delta_applier_rejects_out_of_range_ids():
    uni = _uni()
    a = OrswotBatch.from_scalar(_orswot_fleet(8, seed=10), uni)
    applier = OrswotDeltaApplier(uni)
    with pytest.raises(SyncProtocolError):
        applier.apply(a, np.array([99], dtype=np.int64), [b"\x26\x00\x00\x00"])


# ---- session convergence (the property sweep) ------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_orswot_delta_sync_matches_full_state_merge(seed):
    """The acceptance bar: on the same op history, the delta session's
    converged fleets are byte-identical to the full-state merge."""
    rng = np.random.RandomState(100 + seed)
    n = int(rng.randint(20, 120))
    k = int(rng.randint(1, max(2, n // 8)))
    rows_a = rng.choice(n, size=k, replace=False)
    rows_b = rng.choice(n, size=k, replace=False)
    uni = _uni()
    a = OrswotBatch.from_scalar(
        _orswot_fleet(n, seed=seed, actor=1, extra_on=rows_a), uni
    )
    b = OrswotBatch.from_scalar(
        _orswot_fleet(n, seed=seed, actor=2, extra_on=rows_b), uni
    )
    ref = a.merge(b)
    sa, sb = SyncSession(a, uni), SyncSession(b, uni)
    ra, rb = sync_pair(sa, sb)
    assert ra.converged and rb.converged
    assert sa.batch.to_wire(uni) == ref.to_wire(uni) == sb.batch.to_wire(uni)
    # digest vectors agree with the reference fleet's too
    assert np.array_equal(
        sync_digest.digest_of(sa.batch), sync_digest.digest_of(ref)
    )
    want_div = len(set(rows_a.tolist()) | set(rows_b.tolist()))
    assert ra.diverged == want_div


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_counter_fleets_delta_sync(seed):
    rng = np.random.RandomState(200 + seed)
    n = 60
    uni = _uni()

    def pn_fleet(bump_rows):
        rng2 = np.random.RandomState(300 + seed)
        out = []
        for i in range(n):
            c = PNCounter()
            for _ in range(rng2.randint(1, 6)):
                c.apply(c.inc(int(rng2.randint(0, 8))))
            out.append(c)
        for i in bump_rows:
            out[i].apply(out[i].dec(int(rng.randint(0, 8))))
        return out

    rows = rng.choice(n, size=4, replace=False)
    a = PNCounterBatch.from_scalar(pn_fleet([]), uni)
    b = PNCounterBatch.from_scalar(pn_fleet(rows), uni)
    ref = a.merge(b)
    sa, sb = SyncSession(a, uni), SyncSession(b, uni)
    ra, rb = sync_pair(sa, sb)
    assert ra.converged and ra.diverged == len(set(rows.tolist()))
    assert sa.batch.to_wire(uni) == ref.to_wire(uni) == sb.batch.to_wire(uni)

    def gc_fleet(bump_rows):
        rng2 = np.random.RandomState(400 + seed)
        out = []
        for i in range(n):
            c = GCounter()
            for _ in range(rng2.randint(1, 4)):
                c.apply(c.inc(int(rng2.randint(0, 8))))
            out.append(c)
        for i in bump_rows:
            out[i].apply(out[i].inc(1))
        return out

    a = GCounterBatch.from_scalar(gc_fleet([]), uni)
    b = GCounterBatch.from_scalar(gc_fleet(rows), uni)
    ref = a.merge(b)
    sa, sb = SyncSession(a, uni), SyncSession(b, uni)
    ra, _rb = sync_pair(sa, sb)
    assert ra.converged
    assert sa.batch.to_wire(uni) == ref.to_wire(uni)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_lww_fleets_delta_sync(seed):
    rng = np.random.RandomState(500 + seed)
    n = 60
    uni = _uni()

    def fleet(bump_rows):
        rng2 = np.random.RandomState(600 + seed)
        out = [
            LWWReg(val=int(rng2.randint(0, 1000)),
                   marker=int(rng2.randint(1, 100)))
            for _ in range(n)
        ]
        for i in bump_rows:
            out[i] = LWWReg(val=int(rng.randint(0, 1000)), marker=500 + i)
        return out

    rows = rng.choice(n, size=3, replace=False)
    a = LWWRegBatch.from_scalar(fleet([]), uni)
    b = LWWRegBatch.from_scalar(fleet(rows), uni)
    ref = a.merge(b)
    sa, sb = SyncSession(a, uni), SyncSession(b, uni)
    ra, _rb = sync_pair(sa, sb)
    assert ra.converged and ra.diverged == len(set(rows.tolist()))
    assert sa.batch.to_wire(uni) == ref.to_wire(uni) == sb.batch.to_wire(uni)


def test_idempotent_resync_ships_zero_deltas():
    uni = _uni()
    a = OrswotBatch.from_scalar(
        _orswot_fleet(50, seed=11, actor=1, extra_on=[1, 2]), uni
    )
    b = OrswotBatch.from_scalar(
        _orswot_fleet(50, seed=11, actor=2, extra_on=[3]), uni
    )
    sa, sb = SyncSession(a, uni), SyncSession(b, uni)
    sync_pair(sa, sb)
    # second session over the converged fleets: one digest exchange,
    # zero delta/full bytes, zero objects shipped
    sa2, sb2 = SyncSession(sa.batch, uni), SyncSession(sb.batch, uni)
    ra2, rb2 = sync_pair(sa2, sb2)
    for r in (ra2, rb2):
        assert r.converged
        assert r.diverged == 0
        assert r.delta_objects_sent == 0
        assert r.delta_bytes_sent == 0 and r.full_bytes_sent == 0
        assert r.digest_rounds == 1


def test_flat_session_ships_phase1_digest_eagerly():
    """A flat (non-tree, non-full-state) session ships its phase-1
    digest inside the hello flight — same wire sequence, one wait
    instead of two — and the counter pins the path; a digest-tree
    session must NOT take it (phase 1 there is the root frame)."""
    from crdt_tpu.utils import tracing

    uni = _uni()
    a = OrswotBatch.from_scalar(
        _orswot_fleet(40, seed=13, actor=1, extra_on=[2]), uni)
    b = OrswotBatch.from_scalar(
        _orswot_fleet(40, seed=13, actor=2, extra_on=[7]), uni)
    before = tracing.counters()
    sa, sb = SyncSession(a, uni), SyncSession(b, uni)
    ra, rb = sync_pair(sa, sb)
    assert ra.converged and rb.converged
    deltas = tracing.counters_since(before)
    assert deltas.get("sync.digest.eager", 0) == 2  # both peers
    assert sa.batch.to_wire(uni) == a.merge(b).to_wire(uni)

    before = tracing.counters()
    st_a = SyncSession(sa.batch, uni, digest_tree=True)
    st_b = SyncSession(sb.batch, uni, digest_tree=True)
    rt_a, _ = sync_pair(st_a, st_b)
    assert rt_a.converged and rt_a.tree_mode
    deltas = tracing.counters_since(before)
    assert deltas.get("sync.digest.eager", 0) == 0


def test_forced_digest_collision_falls_back_to_full_state():
    """Phase-1 digests that collide on diverged rows ship nothing for
    them; the canonical verify catches it and the full-state retry must
    still converge byte-identical."""
    uni = _uni()
    a = OrswotBatch.from_scalar(_orswot_fleet(40, seed=12, actor=1), uni)
    b = OrswotBatch.from_scalar(
        _orswot_fleet(40, seed=12, actor=2, extra_on=[4, 14, 24]), uni
    )
    ref = a.merge(b)

    # total collision: every lane equal, nothing flagged in phase 1
    zero = lambda batch: np.zeros(40, np.uint64)  # noqa: E731
    sa, sb = (SyncSession(x, uni, digest_fn=zero) for x in (a, b))
    ra, rb = sync_pair(sa, sb)
    assert ra.converged and ra.full_state_fallback
    assert ra.delta_objects_sent == 0
    assert sa.batch.to_wire(uni) == ref.to_wire(uni) == sb.batch.to_wire(uni)

    # partial collision: two diverged rows hidden, one flagged — the
    # delta pass fixes the flagged row, the verify catches the hidden
    # ones, the retry converges
    def partial(batch):
        d = sync_digest.digest_of(batch).copy()
        d[[4, 24]] = 0
        return d

    sa, sb = (SyncSession(x, uni, digest_fn=partial) for x in (a, b))
    ra, rb = sync_pair(sa, sb)
    assert ra.converged and ra.full_state_fallback
    assert ra.diverged == 1 and ra.delta_objects_sent == 1
    assert ra.digest_rounds == 3
    assert sa.batch.to_wire(uni) == ref.to_wire(uni) == sb.batch.to_wire(uni)


def test_wide_divergence_uses_full_state_threshold():
    uni = _uni()
    a = OrswotBatch.from_scalar(_orswot_fleet(30, seed=13, actor=1), uni)
    # a completely different history: every row diverges
    b = OrswotBatch.from_scalar(_orswot_fleet(30, seed=14, actor=2), uni)
    ref = a.merge(b)
    sa, sb = SyncSession(a, uni), SyncSession(b, uni)
    ra, _rb = sync_pair(sa, sb)
    assert ra.converged and ra.full_state_fallback
    assert ra.delta_bytes_sent == 0  # threshold sent FULL, not a delta
    assert sa.batch.to_wire(uni) == ref.to_wire(uni)


def test_full_state_mode_still_version_tagged():
    """--full-state keeps the legacy exchange but every frame still
    carries the protocol version byte."""
    uni = _uni()
    a = OrswotBatch.from_scalar(_orswot_fleet(16, seed=15, actor=1), uni)
    b = OrswotBatch.from_scalar(_orswot_fleet(16, seed=15, actor=2,
                                              extra_on=[0]), uni)
    frames_a: list = []
    sa = SyncSession(a, uni, full_state=True)
    sb = SyncSession(b, uni, full_state=True)
    import threading

    from crdt_tpu.sync.session import queue_transport

    (send_a, recv_a), (send_b, recv_b) = queue_transport()

    def wrapped_send(f):
        frames_a.append(f)
        send_a(f)

    t = threading.Thread(target=lambda: sb.sync(send_b, recv_b), daemon=True)
    t.start()
    ra = sa.sync(wrapped_send, recv_a)
    t.join(timeout=60)
    assert ra.converged
    # the hello ships at the baseline version (it precedes negotiation),
    # every later frame at the negotiated one — all within the compat set
    assert frames_a and all(
        f[0] in sync_delta.COMPAT_VERSIONS for f in frames_a
    )
    assert frames_a[0][0] == sync_delta.BASELINE_VERSION
    assert any(f[0] == sync_delta.PROTOCOL_VERSION for f in frames_a[1:])
    assert sa.batch.to_wire(uni) == sb.batch.to_wire(uni)


def test_diverged_indices_shape_guard():
    with pytest.raises(SyncProtocolError):
        diverged_indices(np.zeros(3, np.uint64), np.zeros(4, np.uint64))


def test_peer_disconnect_mid_frame_is_sync_protocol_error():
    """A peer hanging up mid-frame must surface as SyncProtocolError —
    the sync taxonomy's I/O-boundary fault — never as the transport's
    bare ConnectionError/EOFError (or struct.error from a half-parsed
    header), and the failed session must leave a ``sync.error`` event
    in the flight recorder before the raise propagates."""
    from crdt_tpu.obs import events as obs_events

    uni = _uni()
    a = OrswotBatch.from_scalar(_orswot_fleet(8, seed=31, actor=1), uni)

    for hangup in (ConnectionResetError("peer closed mid-frame"),
                   EOFError("stream ended inside a frame")):
        session = SyncSession(a, uni, peer="hangup")
        sent: list = []

        def recv_then_die():
            raise hangup

        with pytest.raises(SyncProtocolError) as exc_info:
            session.sync(sent.append, recv_then_die)
        # the cause chain keeps the transport detail, the type is ours
        assert exc_info.value.__cause__ is hangup
        assert not isinstance(exc_info.value, (ConnectionError, EOFError))
        evs = obs_events.recorder().snapshot(kind="sync.error",
                                             session=session.session_id)
        assert evs, "disconnect left no sync.error event"
        assert "mid-session" in evs[-1]["fields"]["error"]
