"""kernelcheck self-tests: the jaxpr tier's repo gate, the fixture
regression matrix, manifest coverage, and the KC01/conftest skew
cross-check.

The AST tier's tests (tests/test_analysis.py) stay jax-free; this
module deliberately is NOT — tracing kernels is the whole point — and
runs under the same `analysis` marker.
"""

import json
import os
import subprocess
import sys

import pytest

from crdt_tpu.analysis.core import Baseline, ParsedFile, repo_root
from crdt_tpu.analysis.kernels import (
    MANIFEST, iter_jit_sites, manifest_keys,
)

pytestmark = pytest.mark.analysis

REPO = repo_root()
FIXDIR = os.path.join(REPO, "tests", "analysis_fixtures")
sys.path.insert(0, FIXDIR)


def _run_specs(specs, baseline=None):
    from crdt_tpu.analysis.jaxpr_rules import run_kernelcheck

    return run_kernelcheck(specs=specs, baseline=baseline)


# ---- the repo-wide gate -----------------------------------------------------


@pytest.fixture(scope="module")
def repo_gate():
    """One subprocess run of the real CLI gate, shared by the gate
    tests: `python -m crdt_tpu.analysis --kernels --json` exactly as
    scripts/ci.sh invokes it."""
    proc = subprocess.run(
        [sys.executable, "-m", "crdt_tpu.analysis", "--kernels", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    return proc


def test_repo_gate_exits_zero_with_empty_baseline(repo_gate):
    """The shipped tree is contract-clean: exit 0, zero live findings,
    zero trace errors, nothing parked for the KC rules in the
    baseline."""
    assert repo_gate.returncode == 0, repo_gate.stdout + repo_gate.stderr
    out = json.loads(repo_gate.stdout)
    assert out["ok"] is True
    assert out["findings"] == []
    assert out["kernelcheck"]["trace_errors"] == []
    with open(os.path.join(REPO, "crdt_tpu", "analysis",
                           "baseline.json")) as fh:
        entries = json.load(fh)
    assert [e for e in entries if e["rule"].startswith("KC")] == []


def test_repo_gate_is_fast_and_covers_the_manifest(repo_gate):
    """<60 s on CPU, every buildable spec traced, every jit site under
    crdt_tpu/ accounted for."""
    out = json.loads(repo_gate.stdout)
    kc = out["kernelcheck"]
    assert kc["elapsed_s"] < 60.0, f"kernelcheck took {kc['elapsed_s']}s"
    n_build = sum(1 for s in MANIFEST if s.build is not None)
    assert kc["traced"] == n_build
    assert kc["cases"] >= 2 * kc["traced"]  # ladders, not single traces
    # declared-no-trace rows are reported, never silent
    assert {s["kernel"] for s in kc["skipped"]} == {
        s.name for s in MANIFEST if s.build is None}
    # the AST extractor saw every site the manifest claims (coverage
    # itself is enforced by the kernel-manifest rule in tier 1)
    assert kc["jit_sites"] > 0
    assert kc["jit_sites"] <= len(manifest_keys()) + len(MANIFEST)


def test_mosaic_specs_traced_real_pallas_regions(repo_gate):
    """Each mosaic spec traced >=1 pallas_call and is 64-bit-clean —
    the static KC01 pin on the Pallas-skew class."""
    mosaic = json.loads(repo_gate.stdout)["kernelcheck"]["mosaic"]
    assert set(mosaic) == {s.name for s in MANIFEST if s.mosaic}
    for name, stats in mosaic.items():
        assert stats["pallas_calls"] > 0, f"{name} traced no pallas_call"
        assert stats["wide_ops"] == 0, (
            f"{name} leaked {stats['wide_ops']} 64-bit ops into Mosaic")


def test_kc01_agrees_with_conftest_skew_gate(repo_gate):
    """The static gate and the runtime xfail gate can never disagree
    silently: the Mosaic kernels are 64-bit-clean at the jaxpr level
    (previous test), so any runtime xfail of the Pallas suites must be
    purely version-gated — i.e. conftest's predicate and kernelcheck's
    recorded skew reason are the SAME `config.pallas_mosaic_skew()`
    value.  If KC01 ever finds real 64-bit content, the gate exits 1
    regardless of the jax version, and a pragma sanctioning it is
    re-flagged as stale the moment the skew lifts (pinned below in
    test_stale_kc01_sanction_reflagged_when_skew_lifts)."""
    from crdt_tpu.config import pallas_mosaic_skew

    kc = json.loads(repo_gate.stdout)["kernelcheck"]
    assert kc["skew_reason"] == pallas_mosaic_skew()


# ---- fixture matrix: every rule fires with the right id + kernel name ------


@pytest.fixture(scope="module")
def bad_result():
    import kernels_bad

    result, report = _run_specs(kernels_bad.SPECS)
    assert report.trace_errors == [], report.trace_errors
    return result


@pytest.mark.parametrize("rule,kernel", [
    ("KC01", "fixture.i64_lowering"),
    ("KC02", "fixture.float_scatter"),
    ("KC03", "fixture.baked_const"),
    ("KC04", "fixture.shape_special"),
    ("KC05", "fixture.hidden_callback"),
])
def test_bad_fixture_fails_with_rule_and_kernel_name(bad_result, rule,
                                                     kernel):
    hits = [f for f in bad_result.findings if f.rule == rule]
    assert hits, f"{rule} produced no finding"
    assert any(kernel in f.message for f in hits), (
        rule, [f.message for f in hits])
    # findings carry a real location (jaxpr source frame or jit site)
    for f in hits:
        assert f.path and f.line >= 1


def test_bad_fixture_findings_anchor_in_the_fixture(bad_result):
    """KC01/KC02/KC05 anchor at the offending equation's source line in
    the fixture file — the 'jaxpr location' acceptance: a pragma ON
    THAT LINE is what sanctions the idiom."""
    for rule in ("KC01", "KC02", "KC05"):
        hits = [f for f in bad_result.findings if f.rule == rule]
        assert any(
            f.path == "tests/analysis_fixtures/kernels_bad.py" and f.line > 1
            for f in hits), (rule, [(f.path, f.line) for f in hits])


def test_ok_twins_suppressed_or_clean():
    import kernels_ok

    baseline = Baseline([{
        "rule": "KC03",
        "path": "tests/analysis_fixtures/kernels_ok.py",
        "message": "kernel fixture_ok.baselined_const*",
        "justification": "fixture: demonstrates baseline parking for "
                         "const findings (no per-equation source frame "
                         "to hang a pragma on)",
    }])
    result, report = _run_specs(kernels_ok.SPECS, baseline=baseline)
    assert report.trace_errors == [], report.trace_errors
    assert result.findings == [], [f.render() for f in result.findings]
    # the pragma'd sin really fired and was suppressed — not inert
    assert {f.rule for f in result.suppressed} == {"KC02"}
    assert [f.rule for f in result.baselined] == ["KC03"]
    assert result.stale_baseline == []


def test_stale_kc01_sanction_reflagged_when_skew_lifts(monkeypatch):
    """A pragma sanctioning KC01 is only valid while the runtime skew
    gate reports a skew: on a fixed jax the suppression re-arms as a
    live 'stale sanction' finding (the cross-check screw)."""
    import kernels_bad

    import crdt_tpu.config as config

    spec = [s for s in kernels_bad.SPECS
            if s.name == "fixture.i64_lowering"]
    result, _ = _run_specs(spec)
    line = next(f.line for f in result.findings if f.rule == "KC01")

    # sanction it: pragma on the offending line, via a patched pragma
    # map (the fixture file on disk stays sin-without-pragma)
    real_suppressed = ParsedFile.suppressed

    def fake_suppressed(self, rule, ln):
        if (self.rel.endswith("kernels_bad.py") and rule == "KC01"
                and ln == line):
            return True
        return real_suppressed(self, rule, ln)

    monkeypatch.setattr(ParsedFile, "suppressed", fake_suppressed)
    result2, _ = _run_specs(spec)
    assert all(f.rule != "KC01" or "stale" in f.message
               for f in result2.findings)
    assert any(f.rule == "KC01" for f in result2.suppressed)

    # now lift the skew: the sanction must re-flag as live
    monkeypatch.setattr(config, "pallas_mosaic_skew", lambda: None)
    result3, _ = _run_specs(spec)
    stale = [f for f in result3.findings
             if f.rule == "KC01" and "stale KC01 sanction" in f.message]
    assert stale, [f.render() for f in result3.findings]


# ---- the tier-1 AST rule: kernel-manifest ----------------------------------


def test_unmanifested_jit_entry_point_fails_source_lint():
    """A new @jax.jit under crdt_tpu/ without a KernelSpec row fails
    crdtlint BEFORE kernelcheck ever runs (the single-source
    discipline, same as obs/namespace.py for metric names)."""
    from crdt_tpu.analysis import run_lint

    src = (
        "import jax\n"
        "@jax.jit\n"
        "def rogue_kernel(x):\n"
        "    return x + 1\n"
    )
    pf = ParsedFile("x", "crdt_tpu/batch/rogue.py", src)
    result = run_lint([pf], only_rules=["kernel-manifest"])
    assert [f.rule for f in result.findings] == ["kernel-manifest"]
    assert "rogue_kernel" in result.findings[0].message
    assert result.findings[0].line == 3


def test_every_jit_call_form_is_extracted():
    """The extractor names every jit application form the tree uses:
    decorator, partial-decorator, direct call, lambda, computed."""
    src = (
        "import functools, jax\n"
        "@jax.jit\n"
        "def plain(x): return x\n"
        "@functools.partial(jax.jit, static_argnums=(1,))\n"
        "def with_static(x, k): return x\n"
        "def factory():\n"
        "    def kernel(x): return x\n"
        "    return jax.jit(kernel)\n"
        "class Loop:\n"
        "    def warm(self):\n"
        "        self._f = jax.jit(functools.partial(plain))\n"
        "probe = jax.jit(lambda x: x + 1)\n"
    )
    names = {s.name for s in iter_jit_sites(
        ParsedFile("x", "crdt_tpu/batch/forms.py", src).tree)}
    assert names == {
        "plain", "with_static", "factory.kernel", "Loop.warm.<jit>",
        "<lambda>",
    }


def test_stale_manifest_row_fails_source_lint():
    """A manifest row pointing at a deleted/moved jit site is flagged
    when the row's target file is in the scanned set."""
    from crdt_tpu.analysis import run_lint

    spec = MANIFEST[0]
    pf = ParsedFile("x", spec.path, "import jax\n")  # site gone
    result = run_lint([pf], only_rules=["kernel-manifest"])
    assert any(
        f.rule == "kernel-manifest" and "stale manifest row" in f.message
        and spec.name in f.message
        for f in result.findings), [f.render() for f in result.findings]


def test_manifest_covers_every_site_on_the_real_tree():
    """100% coverage, asserted directly against the source tree (the
    CLI gate asserts it too, via the kernel-manifest rule)."""
    from crdt_tpu.analysis.core import default_targets, load_files

    files, errors = load_files(default_targets(), root=REPO)
    assert not errors
    covered = manifest_keys()
    missing = []
    for pf in files:
        if (not pf.rel.startswith("crdt_tpu/")
                or pf.rel.startswith("crdt_tpu/analysis/")):
            continue
        for site in iter_jit_sites(pf.tree):
            if (pf.rel, site.name) not in covered:
                missing.append((pf.rel, site.name))
    assert missing == []


def test_manifest_rows_are_unique_and_well_formed():
    names = [s.name for s in MANIFEST]
    assert len(names) == len(set(names))
    for s in MANIFEST:
        assert s.path.startswith("crdt_tpu/")
        assert s.determinism in (
            "bitwise", "integer-lattice", "float-accum")
        assert s.compile_budget >= 1
        assert (s.build is None) == bool(s.notrace_reason)
