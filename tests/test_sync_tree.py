"""Hierarchical digest trees + name-keyed salts (`crdt_tpu.sync.tree`).

Covers the ISSUE 11 acceptance bar: tree-root equality ⟺ flat
digest-vector equality on seeded random histories (incl. post-GC /
repacked replicas), interning-order salt invariance across universes
that never shared an intern table, the v3 subtree descent converging
byte-identical to flat mode — including under 20% frame loss — the
mixed-version fleet falling back to flat loudly (counter, never a
``SyncProtocolError``), the dense-divergence cutover, digest
memoization (a second idle sync performs ZERO digest-kernel calls),
and the seeded workload generator's skew/burst knobs.
"""

import threading

import numpy as np
import pytest

from crdt_tpu.batch import GCounterBatch, OrswotBatch
from crdt_tpu.config import CrdtConfig
from crdt_tpu.error import SyncProtocolError
from crdt_tpu.scalar.gcounter import GCounter
from crdt_tpu.scalar.orswot import Orswot
from crdt_tpu.sync import digest as sync_digest
from crdt_tpu.sync import delta as sync_delta
from crdt_tpu.sync import tree as sync_tree
from crdt_tpu.sync.delta import (
    BASELINE_VERSION,
    COMPAT_VERSIONS,
    decode_frame,
    decode_tree_level_payload,
    decode_tree_root_payload,
    encode_tree_level_frame,
    encode_tree_root_frame,
)
from crdt_tpu.sync.session import SyncSession, sync_pair
from crdt_tpu.utils.interning import Registry, Universe
from crdt_tpu.utils.workload import WorkloadGen

pytestmark = pytest.mark.sync


def _uni(**kw):
    cfg = dict(num_actors=8, member_capacity=16, deferred_capacity=4,
               counter_bits=32)
    cfg.update(kw)
    return Universe.identity(CrdtConfig(**cfg))


def _orswot_fleet(n, seed, actor=1, extra_on=(), rng_members=50):
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        s = Orswot()
        for _ in range(rng.randint(1, 5)):
            s.apply(s.add(int(rng.randint(0, rng_members)),
                          s.value().derive_add_ctx(0)))
        if i % 5 == 0:
            read = s.value()
            if read.val:
                m = sorted(read.val)[0]
                s.apply(s.remove(m, s.contains(m).derive_rm_ctx()))
        out.append(s)
    for i in extra_on:
        s = out[i]
        s.apply(s.add(900 + actor, s.value().derive_add_ctx(actor)))
    return out


# ---- the tree itself -------------------------------------------------------


def test_tree_structure_and_root_is_xor_fold():
    d = np.arange(1, 41, dtype=np.uint64) * np.uint64(0x9E3779B97F4A7C15)
    t = sync_tree.build_tree(d)
    assert [lv.shape[0] for lv in t.levels] == [40, 3, 1]
    # the root is the XOR fold of the position-mixed leaf lanes
    assert t.root == int(np.bitwise_xor.reduce(t.levels[0]))
    # every parent is the XOR of its (zero-padded) children
    for lvl in range(1, t.num_levels):
        for p in range(t.level_size(lvl)):
            kids = t.child_lanes(lvl - 1, np.array([p]))
            assert int(np.bitwise_xor.reduce(kids)) == int(t.levels[lvl][p])
    # the leaf mix is a per-position bijection: diverged positions
    # match the raw vector's exactly
    d2 = d.copy()
    d2[[3, 17]] ^= np.uint64(0xABCD)
    t2 = sync_tree.build_tree(d2)
    assert np.nonzero(t.levels[0] != t2.levels[0])[0].tolist() == [3, 17]
    # ...and an IDENTICAL delta at two positions must not XOR-cancel
    # out of the root (the bulk-write cancellation class the mix kills)
    assert t.root != t2.root


def test_tree_edge_sizes():
    assert sync_tree.build_tree(np.zeros(0, np.uint64)).root == 0
    one = sync_tree.build_tree(np.array([7], np.uint64))
    assert one.num_levels == 1 and one.root == int(one.levels[0][0])
    assert one.root != sync_tree.build_tree(np.array([8], np.uint64)).root
    exact = sync_tree.build_tree(np.arange(256, dtype=np.uint64))
    assert [lv.shape[0] for lv in exact.levels] == [256, 16, 1]


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_root_equality_iff_flat_vector_equality(seed):
    """The property sweep: on seeded random histories, tree roots agree
    exactly when the flat digest vectors do, and a descent recovers the
    exact flat diverged set."""
    rng = np.random.RandomState(700 + seed)
    n = int(rng.randint(20, 200))
    uni = _uni()
    a = OrswotBatch.from_scalar(_orswot_fleet(n, seed=seed), uni)
    da = sync_digest.digest_of(a, uni)
    ta = sync_digest.digest_tree_of(a, uni)
    # identical history -> identical vector -> identical root
    b_same = OrswotBatch.from_scalar(_orswot_fleet(n, seed=seed), uni)
    assert np.array_equal(da, sync_digest.digest_of(b_same, uni))
    assert sync_digest.digest_tree_of(b_same, uni).root == ta.root

    k = int(rng.randint(1, max(2, n // 6)))
    rows = np.sort(rng.choice(n, size=k, replace=False))
    b = OrswotBatch.from_scalar(
        _orswot_fleet(n, seed=seed, actor=2, extra_on=rows), uni)
    db = sync_digest.digest_of(b, uni)
    tb = sync_digest.digest_tree_of(b, uni)
    assert not np.array_equal(da, db) and ta.root != tb.root
    leaves, stats = sync_tree.simulate_descent(ta, tb)
    assert np.array_equal(leaves, np.nonzero(da != db)[0])
    assert not stats.cutover and not stats.collision


def test_tree_matches_flat_after_gc_settle_and_repack():
    """Post-GC/repacked replicas digest (and therefore tree) identical
    to their never-compacted twin — representation changed, state did
    not."""
    from crdt_tpu.gc.compact import settle_orswot
    from crdt_tpu.gc.repack import repack_orswot

    uni = _uni(member_capacity=8)
    base = OrswotBatch.from_scalar(_orswot_fleet(48, seed=9), uni)
    grown = base.with_capacity(member_capacity=32, deferred_capacity=8)
    settled, _ = settle_orswot(grown)
    packed, _reclaimed = repack_orswot(settled, member_capacity=8,
                                       deferred_capacity=4)
    want = sync_digest.digest_of(base.merge(base), uni)
    assert np.array_equal(want, sync_digest.digest_of(packed, uni))
    assert sync_digest.digest_tree_of(packed, uni).root \
        == sync_tree.build_tree(want).root
    _leaves, stats = sync_tree.simulate_descent(
        sync_digest.digest_tree_of(packed, uni), sync_tree.build_tree(want))
    assert _leaves.size == 0 and not stats.collision


# ---- name-keyed salts ------------------------------------------------------


def _interleaved_universes():
    """Two universes interning the SAME names in DIFFERENT orders."""
    cfg = CrdtConfig(num_actors=8, member_capacity=16, deferred_capacity=4,
                     counter_bits=32)
    actors = ["alice", "bob", "carol"]
    members = [f"m{i}" for i in range(20)]
    u1 = Universe(cfg, actors=Registry(capacity=8), members=Registry())
    u2 = Universe(cfg, actors=Registry(capacity=8), members=Registry())
    u1.actors.intern_all(actors)
    u1.members.intern_all(members)
    u2.actors.intern_all(list(reversed(actors)))
    u2.members.intern_all(list(reversed(members)))
    return u1, u2, actors, members


def _named_fleet(n, actors, members, seed=5):
    """Scalar states over the NAME values themselves — ``from_scalar``
    interns them through whichever universe ingests the fleet."""
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        s = Orswot()
        for _ in range(rng.randint(1, 6)):
            actor = actors[rng.randint(0, len(actors))]
            member = members[rng.randint(0, len(members))]
            s.apply(s.add(member, s.value().derive_add_ctx(actor)))
        out.append(s)
    return out


def test_salt_invariance_across_interning_orders():
    """Two nodes that interned the same names in different orders still
    compare digests — lane keys come from the NAMES, not the dense
    indices (the prerequisite for gossip between independently-started
    hosts)."""
    u1, u2, actors, members = _interleaved_universes()
    fleet = _named_fleet(40, actors, members)
    b1 = OrswotBatch.from_scalar(fleet, u1)
    b2 = OrswotBatch.from_scalar(fleet, u2)
    d1 = sync_digest.digest_of(b1, u1)
    d2 = sync_digest.digest_of(b2, u2)
    assert np.array_equal(d1, d2)
    assert sync_digest.digest_tree_of(b1, u1).root \
        == sync_digest.digest_tree_of(b2, u2).root
    # and the planes really ARE laid out differently (the invariance is
    # doing work, not comparing identical buffers)
    assert not np.array_equal(np.asarray(b1.ids), np.asarray(b2.ids))

    # counter planes too: actor columns permuted between universes
    counters = []
    for i in range(12):
        g = GCounter()
        for _ in range(i + 1):
            g.apply(g.inc(actors[i % len(actors)]))
        counters.append(g)
    c1 = sync_digest.digest_of(GCounterBatch.from_scalar(counters, u1), u1)
    c2 = sync_digest.digest_of(GCounterBatch.from_scalar(counters, u2), u2)
    assert np.array_equal(c1, c2)


def test_interned_int_names_match_identity_universe():
    """An interned universe over int names (in scrambled order) digests
    identically to an identity universe — int salts are the same
    SplitMix the identity path computes on device."""
    cfg = CrdtConfig(num_actors=8, member_capacity=16, deferred_capacity=4,
                     counter_bits=32)
    uid = Universe.identity(cfg)
    uin = Universe(cfg, actors=Registry(capacity=8), members=Registry())
    uin.actors.intern_all([3, 0, 1, 2])     # scrambled int actor names
    uin.members.intern_all([17, 4, 99, 23])  # scrambled int member names
    rngs = np.random.RandomState(11)
    fleet = []
    for _ in range(24):
        s = Orswot()
        for _ in range(rngs.randint(1, 5)):
            actor = int(rngs.randint(0, 4))
            member = [17, 4, 99, 23][rngs.randint(0, 4)]
            s.apply(s.add(member, s.value().derive_add_ctx(actor)))
        fleet.append(s)
    di = sync_digest.digest_of(OrswotBatch.from_scalar(fleet, uid), uid)
    dn = sync_digest.digest_of(OrswotBatch.from_scalar(fleet, uin), uin)
    assert np.array_equal(di, dn)


def test_stable_name_salt_is_deterministic_and_domain_separated():
    s = sync_digest.stable_name_salt
    from crdt_tpu.sync.digest import _T_ASALT, _T_MSALT

    assert s("alice", _T_ASALT) == s("alice", _T_ASALT)
    assert s("alice", _T_ASALT) != s("alice", _T_MSALT)
    assert s("alice", _T_ASALT) != s("bob", _T_ASALT)
    assert s(5, _T_MSALT) != s("5", _T_MSALT)
    assert s(b"x", _T_MSALT) != s("x", _T_MSALT)


# ---- tree frames -----------------------------------------------------------


def test_tree_frame_roundtrip():
    t = sync_tree.build_tree(np.arange(100, dtype=np.uint64))
    vv = np.arange(4, dtype=np.uint64)
    ftype, payload = decode_frame(encode_tree_root_frame(t, vv))
    assert ftype == sync_delta.FRAME_TREE
    k, n, levels, root, children, got_vv = decode_tree_root_payload(payload)
    assert (k, n, levels, root) == (16, 100, t.num_levels, t.root)
    assert np.array_equal(children,
                          sync_tree.wire_lanes(t.levels[-2]))
    assert np.array_equal(got_vv, vv)

    parents = np.array([0, 3], dtype=np.int64)
    lanes = t.child_lanes(0, parents)
    ftype, payload = decode_frame(encode_tree_level_frame(0, parents, lanes))
    level, got_p, got_l = decode_tree_level_payload(payload)
    assert level == 0 and np.array_equal(got_p, parents)
    assert np.array_equal(got_l, sync_tree.wire_lanes(lanes))


def test_malformed_tree_frames_rejected_cleanly():
    t = sync_tree.build_tree(np.arange(64, dtype=np.uint64))
    frame = encode_tree_root_frame(t)
    with pytest.raises(SyncProtocolError):
        decode_frame(frame[:-3])  # truncation dies at the CRC
    _, payload = decode_frame(frame)
    with pytest.raises(SyncProtocolError):
        decode_tree_root_payload(payload[:-2])
    with pytest.raises(SyncProtocolError):
        decode_tree_level_payload(payload)  # wrong subframe tag
    with pytest.raises(SyncProtocolError):
        decode_tree_root_payload(b"")


def test_envelope_accepts_both_compat_versions():
    d = np.arange(4, dtype=np.uint64)
    for ver in sorted(COMPAT_VERSIONS):
        frame = sync_delta.encode_digest_frame(d, version=ver)
        assert frame[0] == ver
        decode_frame(frame)
    for bad in (1, 5):
        frame = sync_delta.encode_digest_frame(d, version=bad)
        with pytest.raises(SyncProtocolError):
            decode_frame(frame)
    # hellos always ship at the baseline (they precede negotiation)
    hello = sync_delta.encode_hello_frame("t", "n", False)
    assert hello[0] == BASELINE_VERSION


# ---- descent sessions ------------------------------------------------------


def test_converged_tree_session_is_one_root_frame():
    uni = _uni()
    a = OrswotBatch.from_scalar(_orswot_fleet(120, seed=21), uni)
    b = OrswotBatch.from_scalar(_orswot_fleet(120, seed=21), uni)
    sa = SyncSession(a, uni, digest_tree=True)
    sb = SyncSession(b, uni, digest_tree=True)
    ra, rb = sync_pair(sa, sb)
    for r in (ra, rb):
        assert r.converged and r.tree_mode
        assert r.diverged == 0 and r.digest_rounds == 1
        assert r.delta_bytes_sent == 0 and r.full_bytes_sent == 0
        assert r.digest_bytes_sent == 0  # no flat vector ever shipped
        assert r.tree_frames_sent == 1   # the root frame IS the session
        assert r.tree_bytes_sent < 8 * 120  # and it beats the flat frame


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_tree_session_matches_flat_session_byte_identical(seed):
    rng = np.random.RandomState(800 + seed)
    n = int(rng.randint(40, 160))
    k = int(rng.randint(1, max(2, n // 10)))
    rows_a = rng.choice(n, size=k, replace=False)
    rows_b = rng.choice(n, size=k, replace=False)
    uni = _uni()
    a = OrswotBatch.from_scalar(
        _orswot_fleet(n, seed=seed, actor=1, extra_on=rows_a), uni)
    b = OrswotBatch.from_scalar(
        _orswot_fleet(n, seed=seed, actor=2, extra_on=rows_b), uni)
    sa = SyncSession(a, uni, digest_tree=True)
    sb = SyncSession(b, uni, digest_tree=True)
    ra, rb = sync_pair(sa, sb)
    assert ra.converged and rb.converged and ra.tree_mode
    fa, fb = SyncSession(a, uni), SyncSession(b, uni)
    rfa, _rfb = sync_pair(fa, fb)
    assert rfa.converged and not rfa.tree_mode
    # byte-identical to the flat-mode session AND the plain merge
    ref = a.merge(b).to_wire(uni)
    assert sa.batch.to_wire(uni) == ref == sb.batch.to_wire(uni)
    assert fa.batch.to_wire(uni) == ref
    # the descent located the exact flat diverged set
    assert ra.diverged == rfa.diverged
    assert ra.subtrees_diverged >= 1


def test_dense_divergence_cutover_falls_back_to_flat():
    """A fleet small enough that one descent level out-costs the flat
    frame: both peers take the shared cutover decision, fall back to
    the flat exchange, and still converge — total tree spend is the
    root frame only."""
    from crdt_tpu.utils import tracing

    uni = _uni()
    n = 17  # levels [17, 2, 1]: one level ship (2 parents) > 8n bytes
    a = OrswotBatch.from_scalar(_orswot_fleet(n, seed=31, actor=1), uni)
    b = OrswotBatch.from_scalar(
        _orswot_fleet(n, seed=31, actor=2, extra_on=[0, 16]), uni)
    before = tracing.counters().get("sync.tree.cutover", 0)
    sa = SyncSession(a, uni, digest_tree=True)
    sb = SyncSession(b, uni, digest_tree=True)
    ra, rb = sync_pair(sa, sb)
    assert ra.converged and rb.converged
    assert ra.tree_mode                      # the descent started...
    assert ra.tree_frames_sent == 1          # ...but spent only the root
    assert ra.digest_bytes_sent > 0          # flat exchange took over
    assert tracing.counters()["sync.tree.cutover"] >= before + 2
    assert sa.batch.to_wire(uni) == a.merge(b).to_wire(uni)


def test_mixed_version_fleet_falls_back_flat_loudly():
    """A v3 tree-capable node gossiping with a v2 node: capability off,
    counter recorded, flat exchange, NO SyncProtocolError — the PR 6/7
    capability discipline."""
    from crdt_tpu.utils import tracing

    uni = _uni()
    a = OrswotBatch.from_scalar(_orswot_fleet(30, seed=41, actor=1), uni)
    b = OrswotBatch.from_scalar(
        _orswot_fleet(30, seed=41, actor=2, extra_on=[3]), uni)

    before = dict(tracing.counters())
    sa = SyncSession(a, uni, digest_tree=True)
    sb = SyncSession(b, uni, protocol_version=2)  # a faithful v2 peer
    frames_a: list = []
    from crdt_tpu.sync.session import queue_transport

    (send_a, recv_a), (send_b, recv_b) = queue_transport()

    def wrapped(f):
        frames_a.append(f)
        send_a(f)

    t = threading.Thread(target=lambda: sb.sync(send_b, recv_b), daemon=True)
    t.start()
    ra = sa.sync(wrapped, recv_a)
    t.join(timeout=60)
    assert ra.converged and not ra.tree_mode
    assert ra.protocol_version == 2  # negotiated down
    # every post-hello frame the v3 side sent speaks v2 on the wire —
    # a REAL v2 build would parse this session end to end
    assert frames_a and all(f[0] == 2 for f in frames_a)
    deltas = {k: v - before.get(k, 0)
              for k, v in tracing.counters().items()}
    assert deltas.get("sync.tree.fallback.version", 0) == 1
    assert sa.batch.to_wire(uni) == sb.batch.to_wire(uni)

    # capability-off peer (same version, no tree): same discipline
    before = dict(tracing.counters())
    sa2 = SyncSession(sa.batch, uni, digest_tree=True)
    sb2 = SyncSession(sb.batch, uni)  # v3 but no digest_tree
    ra2, _ = sync_pair(sa2, sb2)
    assert ra2.converged and not ra2.tree_mode
    deltas = {k: v - before.get(k, 0)
              for k, v in tracing.counters().items()}
    assert deltas.get("sync.tree.fallback.capability", 0) == 1


def test_descent_under_20pct_loss_converges_byte_identical():
    """Three digest-tree nodes gossiping over links dropping 20% of
    frames (ARQ-hardened) converge to digest vectors byte-identical to
    a flat-mode control fleet on the same histories."""
    from crdt_tpu.cluster import (
        ClusterNode, GossipScheduler, Membership, queue_pair,
    )
    from crdt_tpu.cluster.faults import FaultPlan, FaultyTransport
    from crdt_tpu.cluster.transport import ResilientTransport, RetryPolicy

    uni = _uni()
    fast = RetryPolicy(send_deadline_s=3.0, recv_deadline_s=3.0,
                       ack_timeout_s=0.05, max_backoff_s=0.3,
                       retry_budget=400)

    def build(digest_tree):
        nodes = []
        for i in range(3):
            batch = OrswotBatch.from_scalar(
                _orswot_fleet(60, seed=51, actor=i + 1,
                              extra_on=[(7 * i + j) % 60 for j in range(4)]),
                uni)
            nodes.append(ClusterNode(f"n{i}", batch, uni,
                                     busy_timeout_s=5.0,
                                     digest_tree=digest_tree))
        seeds = iter(range(1000, 4000))

        def make_dialer(i):
            def dial(peer):
                j = int(peer.peer_id[1:])
                s = next(seeds)
                ta, tb = queue_pair(default_timeout=10.0)
                fa = FaultyTransport(ta, FaultPlan(seed=s, drop=0.2))
                fb = FaultyTransport(tb, FaultPlan(seed=s + 1, drop=0.2))
                ra = ResilientTransport(fa, fast, seed=s + 2)
                rb = ResilientTransport(fb, fast, seed=s + 3)

                def serve():
                    try:
                        nodes[j].accept(rb, peer_id=f"n{i}")
                    except Exception:
                        pass
                    finally:
                        rb.close()

                threading.Thread(target=serve, daemon=True).start()
                return ra
            return dial

        scheds = []
        for i in range(3):
            m = Membership(suspect_after=3, dead_after=6)
            for j in range(3):
                if j != i:
                    m.add(f"n{j}")
            scheds.append(GossipScheduler(nodes[i], m, make_dialer(i),
                                          fanout=2, session_timeout_s=30.0,
                                          seed=i))
        return nodes, scheds

    results = {}
    for mode in (True, False):
        nodes, scheds = build(mode)
        for _ in range(4):
            for sched in scheds:
                sched.run_round()
            digests = [n.digest() for n in nodes]
            if all(np.array_equal(d, digests[0]) for d in digests[1:]):
                break
        digests = [n.digest() for n in nodes]
        assert all(np.array_equal(d, digests[0]) for d in digests[1:]), \
            f"fleet (tree={mode}) did not converge under loss"
        results[mode] = digests[0]
    # descent-mode fleet == flat-mode fleet, byte for byte
    assert np.array_equal(results[True], results[False])


def test_speculative_descent_pins_hits_misses_and_round_trips():
    """The v4 streaming descent over a windowed transport: the whole
    multi-level walk completes in TWO round-trip equivalents (root
    exchange + one speculative blast), the blast both hits (the true
    frontier's blocks are consumed) and misses (the k-ary expansion of
    a sparse frontier over-ships, and the surplus is discarded cleanly
    — ``sync.tree.speculate.{hit,miss}`` fire), and the result is
    byte-identical to the lock-step control on the same histories."""
    from crdt_tpu.cluster import ResilientTransport, RetryPolicy, queue_pair
    from crdt_tpu.utils import tracing

    uni = _uni()
    n = 600  # levels [600, 38, 3, 1]: a two-level speculative blast
    rows_a = [5, 300]
    rows_b = [450]
    a = OrswotBatch.from_scalar(
        _orswot_fleet(n, seed=71, actor=1, extra_on=rows_a), uni)
    b = OrswotBatch.from_scalar(
        _orswot_fleet(n, seed=71, actor=2, extra_on=rows_b), uni)
    ref = a.merge(b).to_wire(uni)

    before = tracing.counters()
    fast = RetryPolicy(send_deadline_s=5.0, recv_deadline_s=5.0,
                       ack_timeout_s=0.05, max_backoff_s=0.3,
                       retry_budget=400)
    ta, tb = queue_pair(default_timeout=10.0)
    ra = ResilientTransport(ta, fast, name="spec-a", seed=81)
    rb = ResilientTransport(tb, fast, name="spec-b", seed=82)
    sa = SyncSession(a, uni, digest_tree=True)
    sb = SyncSession(b, uni, digest_tree=True)
    res = {}

    def run_b():
        res["b"] = sb.sync(rb)

    t = threading.Thread(target=run_b, daemon=True)
    t.start()
    res["a"] = sa.sync(ra)
    t.join(timeout=60.0)
    assert not t.is_alive()
    rep_a, rep_b = res["a"], res["b"]
    for rep in (rep_a, rep_b):
        assert rep.converged and rep.tree_mode and rep.streaming
        # the ISSUE's latency bar: root exchange + ONE blast, however
        # many levels deep the tree is
        assert rep.tree_round_trips == 2
        assert rep.spec_hits > 0
        # 3 diverged leaves in a fan-out-16 expansion: most speculated
        # blocks are surplus and must be discarded, not applied
        assert rep.spec_misses > rep.spec_hits
    assert sa.batch.to_wire(uni) == ref == sb.batch.to_wire(uni)
    deltas = tracing.counters_since(before)
    assert deltas.get("sync.tree.spec_blasts", 0) == 2
    assert deltas.get("sync.tree.speculate.hit", 0) > 0
    assert deltas.get("sync.tree.speculate.miss", 0) > 0

    # lock-step control (no transport → no streaming): same bytes,
    # strictly more round trips
    sa2 = SyncSession(a, uni, digest_tree=True)
    sb2 = SyncSession(b, uni, digest_tree=True)
    rc_a, rc_b = sync_pair(sa2, sb2)
    assert rc_a.converged and rc_a.tree_mode and not rc_a.streaming
    assert rc_a.spec_hits == rc_a.spec_misses == 0
    assert rc_a.tree_round_trips > rep_a.tree_round_trips
    assert sa2.batch.to_wire(uni) == ref == sb2.batch.to_wire(uni)
    # both modes located the identical diverged leaf set
    assert rc_a.diverged == rep_a.diverged
    ra.close()
    rb.close()


# ---- digest memoization ----------------------------------------------------


def test_second_idle_sync_runs_zero_digest_kernels(monkeypatch):
    from crdt_tpu.utils import tracing

    uni = _uni()
    a = OrswotBatch.from_scalar(_orswot_fleet(40, seed=61, actor=1,
                                              extra_on=[2]), uni)
    b = OrswotBatch.from_scalar(_orswot_fleet(40, seed=61, actor=2), uni)
    calls = {"n": 0}
    real = sync_digest._compute_digest

    def counting(batch, universe):
        calls["n"] += 1
        return real(batch, universe)

    monkeypatch.setattr(sync_digest, "_compute_digest", counting)
    sa, sb = (SyncSession(x, uni, digest_tree=True) for x in (a, b))
    ra, _ = sync_pair(sa, sb)
    assert ra.converged
    assert calls["n"] > 0
    # second, idle sync over the SAME (converged) batch objects: the
    # memo keyed on the plane version stamp serves everything
    calls["n"] = 0
    before = dict(tracing.counters())
    sa2 = SyncSession(sa.batch, uni, digest_tree=True)
    sb2 = SyncSession(sb.batch, uni, digest_tree=True)
    ra2, _ = sync_pair(sa2, sb2)
    assert ra2.converged and ra2.diverged == 0
    assert calls["n"] == 0, "idle re-sync re-ran a digest kernel"
    deltas = {k: v - before.get(k, 0) for k, v in tracing.counters().items()}
    assert deltas.get("sync.digest.cache.hit", 0) >= 2
    assert deltas.get("sync.digest.cache.miss", 0) == 0
    # flat idle re-sync hits the same memo
    calls["n"] = 0
    fa2, fb2 = SyncSession(sa.batch, uni), SyncSession(sb.batch, uni)
    rf, _ = sync_pair(fa2, fb2)
    assert rf.converged and calls["n"] == 0


def test_digest_cache_invalidates_on_new_batch_and_interning():
    uni = _uni()
    a = OrswotBatch.from_scalar(_orswot_fleet(20, seed=71), uni)
    d1 = sync_digest.digest_of(a, uni)
    assert sync_digest.digest_of(a, uni) is d1  # pure hit
    grown = a.with_capacity(member_capacity=32, deferred_capacity=8)
    d2 = sync_digest.digest_of(grown, uni)     # new object -> recompute
    assert d2 is not d1 and np.array_equal(d1, d2)

    # interned universes: interning a NEW name changes the salt key, so
    # a stale salt table can never be served
    u1, u2, actors, members = _interleaved_universes()
    b1 = OrswotBatch.from_scalar(_named_fleet(10, actors, members), u1)
    before = sync_digest.digest_of(b1, u1)
    u1.members.intern("brand-new-name")  # table grows; key changes
    again = sync_digest.digest_of(b1, u1)
    assert again is not before
    assert np.array_equal(before, again)  # the name is unused: same lanes


# ---- workload generator ----------------------------------------------------


def test_workloadgen_deterministic_and_bursty():
    g1 = WorkloadGen(500, seed=3, zipf_s=1.1, burst_len=5)
    g2 = WorkloadGen(500, seed=3, zipf_s=1.1, burst_len=5)
    a = g1.draw(37)
    # chunked draws see the same stream (bursts carry across calls)
    b = np.concatenate([g2.draw(10), g2.draw(20), g2.draw(7)])
    assert np.array_equal(a, b)
    # fixed-length bursts
    full = WorkloadGen(500, seed=4, burst_len=5).draw(50).reshape(10, 5)
    assert all(len(set(row.tolist())) == 1 for row in full)


def test_workloadgen_zipf_skew_and_clustering():
    uniform = WorkloadGen(10_000, seed=9).draw(5000)
    skewed = WorkloadGen(10_000, seed=9, zipf_s=1.3).draw(5000)
    # skew concentrates mass on the low ranks
    assert np.median(skewed) < np.median(uniform) / 4
    # and clusters divergence into fewer k-ary subtrees — the tree
    # bench's "hot keys are descent's best case" claim
    k = 64
    u_rows = WorkloadGen(10_000, seed=11).sample_rows(k)
    z_rows = WorkloadGen(10_000, seed=11, zipf_s=1.3).sample_rows(k)
    assert u_rows.shape == z_rows.shape == (k,)
    assert len(set(u_rows.tolist())) == k  # distinct
    assert len(set(z_rows.tolist())) == k
    subtrees = lambda rows: len(set((rows // 16).tolist()))  # noqa: E731
    assert subtrees(z_rows) < subtrees(u_rows)


def test_workloadgen_validation():
    with pytest.raises(ValueError):
        WorkloadGen(0)
    with pytest.raises(ValueError):
        WorkloadGen(10, zipf_s=-1.0)
    with pytest.raises(ValueError):
        WorkloadGen(10, burst_len=0)
    assert WorkloadGen(5, seed=1).sample_rows(99).shape == (5,)
