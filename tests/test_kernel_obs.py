"""The runtime kernel observatory (`crdt_tpu.obs.kernels`).

Covers the PR 14 acceptance bar: the manifest↔runtime cross-check
(every traceable KernelSpec row instruments, every runtime label IS a
manifest row), compile/recompile tracking with arg-shape-stamped
``kernel.compile`` events and the KC04 budget as a live gauge, the
recompile-storm oracle (a steady-state sync+GC epoch records ZERO
compile events after warmup; a forced regrow-ladder walk records
exactly the ladder's compiles, each ladder-attributed), wrapper
transparency (``__wrapped__``/attribute forwarding/error accounting),
device-memory gauges against the capacity tracker, and the
``/kernels`` HTTP surface.
"""

import json
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

from crdt_tpu.analysis.kernels import MANIFEST
from crdt_tpu.batch import OrswotBatch
from crdt_tpu.batch import vclock_batch
from crdt_tpu.config import CrdtConfig
from crdt_tpu.obs import events as obs_events
from crdt_tpu.obs import kernels as obs_kernels
from crdt_tpu.obs import metrics as obs_metrics
from crdt_tpu.obs import namespace
from crdt_tpu.parallel.executor import JoinExecutor, JoinStats
from crdt_tpu.scalar.orswot import Orswot
from crdt_tpu.utils.interning import Universe

pytestmark = pytest.mark.obs


def _counters():
    obs_kernels.publish()  # drain pending per-call aggregates first
    return obs_metrics.registry().counters_snapshot()


def _snap():
    obs_kernels.publish()
    return obs_metrics.registry().snapshot()


def _delta(before, after, name):
    return after.get(name, 0) - before.get(name, 0)


def _consume_ladder_credit(name):
    """Earlier tests may have regrown fleets (bumping the ladder
    epoch) after this kernel's last compile; consume the stale credit
    so classification assertions below see only THIS test's
    transitions."""
    prof = obs_kernels.kernel_observatory().profile(name)
    with prof._lock:
        prof._ladder_seen = obs_kernels._ladder_epoch()


# ---- manifest <-> runtime cross-check --------------------------------------


def test_manifest_runtime_crosscheck():
    """Single source of kernel identity, pinned dynamically: warming
    the manifest instruments EVERY traceable row (imports attach the
    decorated kernels, build closures attach the factory kernels), and
    the runtime registry holds nothing the manifest doesn't name."""
    instrumented = obs_kernels.warm_manifest()
    traceable = {s.name for s in MANIFEST if s.build is not None}
    notrace = {s.name for s in MANIFEST if s.build is None}
    assert instrumented == traceable, (
        f"missing from runtime registry: {sorted(traceable - instrumented)}; "
        f"unmanifested runtime labels: {sorted(instrumented - traceable)}"
    )
    # declared-no-trace rows are visible as explicit, reasoned gaps
    table = {r["kernel"]: r for r in obs_kernels.kernel_observatory().table()}
    assert set(table) == traceable | notrace
    for name in notrace:
        assert not table[name]["instrumented"]
        assert table[name]["notrace_reason"]


def test_instrument_rejects_unmanifested_names():
    with pytest.raises(ValueError, match="no KernelSpec row"):
        obs_kernels.kernel_observatory().instrument(
            "batch.orswot.not_a_kernel", lambda: None)


def test_every_published_kernel_name_has_a_namespace_row():
    obs_kernels.warm_manifest()
    prof = obs_kernels.kernel_observatory().profile("batch.vclock.merge")
    prof._ensure_handles()
    snap = obs_metrics.registry().snapshot()
    for kind in ("counters", "gauges", "histograms"):
        for name in snap[kind]:
            if name.startswith(("kernel.", "devicemem.")):
                assert namespace.match(name, kind[:-1]) is not None, (
                    kind, name)


# ---- compile tracking ------------------------------------------------------


def test_compile_counting_events_and_budget_gauge():
    _consume_ladder_credit("batch.vclock.merge")
    before = _counters()
    seq = obs_kernels.last_event_seq()
    # a shape no other test uses: N=97 guarantees a fresh jit cache key
    plane = jnp.zeros((97, 8), dtype=jnp.uint32)
    vclock_batch._merge(plane, plane)
    vclock_batch._merge(plane, plane)  # same shape: cache hit, no compile
    after = _counters()
    assert _delta(before, after, "kernel.batch_vclock_merge.compiles") == 1
    assert _delta(before, after, "kernel.batch_vclock_merge.calls") == 2
    assert _delta(before, after, "kernel.compiles") == 1
    evs = [e for e in obs_events.recorder().snapshot(kind="kernel.compile")
           if e["seq"] > seq
           and e["fields"]["kernel"] == "batch.vclock.merge"]
    assert len(evs) == 1
    f = evs[0]["fields"]
    assert "uint32[97, 8]" in f["shapes"]
    assert f["count"] == 1 and f["wall_s"] > 0
    assert not f["ladder"]  # no regrow stamped around this compile
    prof = obs_kernels.kernel_observatory().profile("batch.vclock.merge")
    gauges = _snap()["gauges"]
    assert gauges["kernel.batch_vclock_merge.compile_budget_frac"] == \
        pytest.approx(prof.compiles / prof.compile_budget)
    assert gauges["kernel.budget.watermark"] in (0, 1, 2)


def test_wall_histogram_steady_state_and_storm_report():
    plane = jnp.zeros((89, 8), dtype=jnp.uint32)
    vclock_batch._merge(plane, plane)  # warm (compiles)
    seq = obs_kernels.last_event_seq()
    hist_before = _snap()["histograms"].get(
        "kernel.batch_vclock_merge.wall", {"count": 0})["count"]
    for _ in range(20):
        vclock_batch._merge(plane, plane)
    storm = obs_kernels.storm_report(since_seq=seq)
    assert storm["compiles"] == 0 and not storm["storm"]
    hist_after = _snap()["histograms"][
        "kernel.batch_vclock_merge.wall"]["count"]
    assert hist_after - hist_before == 20


def test_blocking_mode_fills_gbps_and_bytes():
    plane = jnp.zeros((83, 8), dtype=jnp.uint32)
    before = _counters()
    obs_kernels.set_blocking(True)
    try:
        vclock_batch._merge(plane, plane)  # compile call (event, no hist)
        vclock_batch._merge(plane, plane)
    finally:
        obs_kernels.set_blocking(False)
    after = _counters()
    per_call = 3 * plane.nbytes  # two inputs + one output
    assert _delta(before, after, "kernel.batch_vclock_merge.bytes") == \
        2 * per_call
    gauges = _snap()["gauges"]
    assert gauges["kernel.batch_vclock_merge.gbps"] > 0


def test_cost_analysis_capture_is_lazy_and_memoized():
    plane = jnp.zeros((79, 8), dtype=jnp.uint32)
    vclock_batch._merge(plane, plane)
    prof = obs_kernels.kernel_observatory().profile("batch.vclock.merge")
    cost = prof.capture_cost()
    assert cost is not None and cost["bytes_accessed"] > 0
    assert prof.capture_cost() is cost  # memoized until the next compile
    gauges = _snap()["gauges"]
    assert gauges["kernel.batch_vclock_merge.cost_bytes"] == \
        cost["bytes_accessed"]


# ---- wrapper transparency --------------------------------------------------


def test_wrapper_is_transparent():
    wrapped = vclock_batch._merge
    assert isinstance(wrapped, obs_kernels._ObservedKernel)
    # kernelcheck's _unjit discipline: __wrapped__ is the PLAIN function
    plain = wrapped.__wrapped__
    assert not hasattr(plain, "_cache_size")
    out = plain(np.zeros((2, 2), np.uint32), np.ones((2, 2), np.uint32))
    assert np.asarray(out).max() == 1
    # unknown attributes forward to the jitted target
    assert callable(wrapped.lower)
    assert wrapped._cache_size() >= 0


def test_wrapper_counts_raising_kernels():
    before = _counters()
    with pytest.raises(Exception):
        # mismatched ranks: jax rejects at trace time; the error must
        # be counted, never swallowed
        vclock_batch._merge(jnp.zeros((4, 4), jnp.uint32),
                            jnp.zeros((3, 3), jnp.uint32))
    after = _counters()
    assert _delta(before, after, "kernel.batch_vclock_merge.errors") == 1


# ---- the recompile-storm oracle --------------------------------------------


def _fleet_batches(uni, member_rows):
    batches = []
    for row in member_rows:
        s = Orswot()
        for member, actor in row:
            s.apply(s.add(member, s.value().derive_add_ctx(actor)))
        batches.append(OrswotBatch.from_scalar([s], uni))
    return batches


def test_regrow_ladder_walk_compiles_exactly_once_per_rung():
    """The forced ladder walk: member_capacity 2 -> 4 -> 8 under the
    executor's overflow recovery.  The merge kernel compiles exactly
    once per rung (base warmup + one per regrow), and every
    post-regrow compile is ladder-attributed — the storm oracle's
    negative control."""
    # num_actors=5 keeps every shape unique to this test, so compile
    # counts are exact regardless of suite order
    uni = Universe(CrdtConfig(num_actors=5, member_capacity=2,
                              deferred_capacity=2, counter_bits=32))
    rows = [[("a", 0), ("b", 0)], [("c", 1), ("d", 1)], [("e", 2), ("f", 2)]]
    batches = _fleet_batches(uni, rows)
    _consume_ladder_credit("batch.orswot.merge")
    before = _counters()
    seq = obs_kernels.last_event_seq()
    stats = JoinStats()
    JoinExecutor(strategy="sequential").join_all(batches, stats=stats)
    after = _counters()
    assert stats.overflow_regrows == 2  # 2 -> 4 -> 8
    rungs = stats.overflow_regrows + 1
    assert _delta(before, after,
                  "kernel.batch_orswot_merge.compiles") == rungs
    evs = [e["fields"] for e in
           obs_events.recorder().snapshot(kind="kernel.compile")
           if e["seq"] > seq
           and e["fields"]["kernel"] == "batch.orswot.merge"]
    assert len(evs) == rungs
    # base-rung compile precedes any regrow stamp; the two post-regrow
    # compiles are each ladder-attributed
    assert [f["ladder"] for f in evs] == [False, True, True]
    report = obs_kernels.storm_report(since_seq=seq)
    merge = report["kernels"]["batch.orswot.merge"]
    assert merge["ladder"] == stats.overflow_regrows


def test_steady_state_sync_gc_epoch_records_zero_compiles():
    """The storm oracle's positive control: after a warmup epoch
    (diverged sync + GC settle), an identical steady-state epoch — an
    idle re-sync and another settle over unchanged shapes — must not
    produce a single compile event."""
    from crdt_tpu.gc.compact import settle_orswot
    from crdt_tpu.sync.session import SyncSession, sync_pair

    uni = Universe(CrdtConfig(num_actors=6, member_capacity=8,
                              deferred_capacity=4, counter_bits=32))

    def batch_of(member_rows, actor):
        scalars = []
        for ms in member_rows:
            s = Orswot()
            for m in ms:
                s.apply(s.add(m, s.value().derive_add_ctx(actor)))
            scalars.append(s)
        return OrswotBatch.from_scalar(scalars, uni)

    a = batch_of([["a1", "a2"], ["shared"]], 0)
    b = batch_of([["b1"], ["shared", "b2"]], 1)
    # warmup epoch: digest + delta + merge + settle kernels all compile
    sa, sb = SyncSession(a, uni), SyncSession(b, uni)
    ra, rb = sync_pair(sa, sb)
    assert ra.converged and rb.converged
    settled, _ = settle_orswot(sa.batch)
    # ...and one converged-idle session: a CLEAN re-sync is where the
    # stability frontier records its evidence (PR 15), so its fold
    # kernel belongs to the warmup's kernel set like every other
    sw_a = SyncSession(settled, uni)
    sw_b = SyncSession(sb.batch, uni)
    rw_a, _rw_b = sync_pair(sw_a, sw_b)
    assert rw_a.converged and rw_a.delta_objects_sent == 0
    seq = obs_kernels.last_event_seq()
    before = _counters()
    # steady-state epoch: idle re-sync over the converged fleet +
    # another settle at unchanged capacities — zero compiles allowed
    sa2, sb2 = SyncSession(settled, uni), SyncSession(sb.batch, uni)
    ra2, rb2 = sync_pair(sa2, sb2)
    assert ra2.converged and ra2.delta_objects_sent == 0
    settle_orswot(sa2.batch)
    after = _counters()
    storm = obs_kernels.storm_report(since_seq=seq)
    assert storm["compiles"] == 0, (
        f"steady-state epoch recompiled: {storm['kernels']}"
    )
    assert _delta(before, after, "kernel.compiles") == 0
    assert not storm["storm"]


# ---- device memory ---------------------------------------------------------


def test_device_memory_gauges_track_live_arrays():
    from crdt_tpu.obs.capacity import CapacityTracker

    reg = obs_metrics.MetricsRegistry()
    trk = CapacityTracker(registry=reg)
    uni = Universe.identity(CrdtConfig(
        num_actors=8, member_capacity=8, deferred_capacity=4,
        counter_bits=32))
    batch = OrswotBatch.zeros(64, uni)
    occ = trk.sample(batch)
    out = trk.sample_device_memory()
    snap = reg.snapshot()["gauges"]
    assert out["arrays"] > 0
    # the device holds AT LEAST the tracked planes
    assert out["live_bytes"] >= occ.bytes
    assert snap["devicemem.live_bytes"] == out["live_bytes"]
    assert snap["devicemem.tracked_bytes"] == occ.bytes
    assert 0.0 < snap["devicemem.tracked_frac"] <= 1.0
    # per-dtype families cover the total
    dtype_bytes = sum(v for k, v in snap.items()
                      if k.startswith("devicemem.dtype."))
    assert dtype_bytes == out["live_bytes"]
    assert reg.snapshot()["counters"]["devicemem.samples"] == 1


def test_kernel_rows_ride_the_fleet_lattice():
    """Per-node kernel health rides the PR 6 fleet observatory for
    free: a fleet slice captured from the default registry carries the
    kernel counters (publish() drains the pending aggregates at slice
    capture, same read-boundary discipline as /metrics)."""
    from crdt_tpu.obs import fleet as obs_fleet

    plane = jnp.zeros((71, 8), dtype=jnp.uint32)
    vclock_batch._merge(plane, plane)
    snap = obs_fleet.capture_slice("n-kernel-obs")
    counters = snap.slices["n-kernel-obs"]["counters"]
    assert counters["kernel.batch_vclock_merge.calls"] >= 1
    assert counters["kernel.batch_vclock_merge.compiles"] >= 1
    assert "kernel.batch_vclock_merge.wall" in \
        snap.slices["n-kernel-obs"]["histograms"]


# ---- the /kernels surface --------------------------------------------------


def test_kernels_endpoint_prom_and_json():
    from crdt_tpu.obs.export import start_metrics_server

    plane = jnp.zeros((73, 8), dtype=jnp.uint32)
    vclock_batch._merge(plane, plane)
    server = start_metrics_server()
    try:
        base = f"http://127.0.0.1:{server.port}/kernels"
        text = urllib.request.urlopen(base).read().decode()
        assert "crdt_tpu_kernel_batch_vclock_merge_compiles_total" in text
        assert "crdt_tpu_devicemem_live_bytes" in text
        # the kernel plane only: no sync/cluster families leak in
        assert "crdt_tpu_sync_" not in text
        j = json.loads(
            urllib.request.urlopen(base + "?format=json").read())
        rows = {r["kernel"]: r for r in j["kernels"]}
        assert len(rows) == len(MANIFEST)
        row = rows["batch.vclock.merge"]
        assert row["instrumented"] and row["calls"] >= 1
        assert row["compile_budget_frac"] == pytest.approx(
            row["compiles"] / row["compile_budget"], abs=1e-4)
        assert row["wall_p50_s"] is None or row["wall_p50_s"] >= 0
        assert "storm" in j and "unexplained" in j["storm"]
    finally:
        server.stop()
