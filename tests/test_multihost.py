"""The multi-host backend's single-process contracts on the CPU mesh.

``crdt_tpu.parallel.multihost`` scales the collective-join layer across
hosts (DCN) and slices (ICI).  Real multi-process runs need a cluster;
what MUST hold everywhere — and is tested here on the virtual 8-device
mesh — is the degenerate-case contract: ``initialize`` is a no-op
single-process, ``make_multihost_mesh`` yields a mesh the existing
collectives run on unchanged (axis names are the single-host
convention), ``local_shard`` tiles the object space exactly, and
``global_batch_from_local`` assembles sharded global arrays that feed
straight into a collective join.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from crdt_tpu import Orswot
from crdt_tpu.batch import OrswotBatch
from crdt_tpu.config import CrdtConfig
from crdt_tpu.parallel import (
    allgather_join_orswot,
    global_batch_from_local,
    initialize,
    local_shard,
    make_multihost_mesh,
    topology,
)
from crdt_tpu.utils.interning import Universe

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device CPU mesh (see conftest)"
)


def test_initialize_single_process_noop():
    topo = initialize()  # no coordinator configured anywhere -> no-op
    assert topo == topology()
    assert topo["processes"] == 1
    assert topo["process_id"] == 0
    assert topo["devices"] == len(jax.devices())
    # idempotent
    assert initialize() == topo


def test_make_multihost_mesh_single_process_merges_axes():
    # dcn_axes degrade into plain mesh axes with one process; DCN-first
    # ordering is preserved so specs written for the hybrid layout hold
    mesh = make_multihost_mesh({"replicas": 2, "objects": 2}, {"pods": 2})
    assert mesh.axis_names == ("pods", "replicas", "objects")
    assert mesh.devices.shape == (2, 2, 2)

    # no dcn axes at all -> identical to make_mesh
    mesh2 = make_multihost_mesh({"replicas": 8})
    assert mesh2.axis_names == ("replicas",)
    assert mesh2.devices.shape == (8,)


def test_local_shard_tiles_exactly():
    for n, k in [(10, 3), (8, 8), (7, 2), (5, 6)]:
        covered = []
        for i in range(k):
            s = local_shard(n, k, i)
            covered.extend(range(n)[s])
        assert covered == list(range(n)), (n, k)
        sizes = [len(range(n)[local_shard(n, k, i)]) for i in range(k)]
        assert max(sizes) - min(sizes) <= 1, (n, k)


def test_global_batch_from_local_feeds_collective_join():
    """Assemble per-'host' planes into a global sharded batch and run
    the stock ORSWOT all-gather join over it — the multi-host ingest
    path composing with the unchanged collective layer."""
    uni = Universe(CrdtConfig(num_actors=8, member_capacity=16, deferred_capacity=8))
    rng = np.random.RandomState(5)

    n_replicas, n_objects = 4, 6
    fleet = []
    for r in range(n_replicas):
        row = []
        for i in range(n_objects):
            o = Orswot()
            for k in range(rng.randint(1, 4)):
                actor = int(rng.randint(0, 4))
                op = o.add(int(rng.randint(0, 10)), o.value().derive_add_ctx(actor))
                o.apply(op)
            row.append(o)
        fleet.append(row)

    batches = [OrswotBatch.from_scalar(row, uni) for row in fleet]
    stacked_np = jax.tree_util.tree_map(
        lambda *xs: np.asarray(jnp.stack(xs)), *batches
    )

    mesh = make_multihost_mesh({"replicas": 4, "objects": 2})
    stacked = global_batch_from_local(mesh, stacked_np, axis="replicas")
    for leaf in jax.tree_util.tree_leaves(stacked):
        assert leaf.sharding.spec[0] == "replicas"

    joined = allgather_join_orswot(stacked, mesh, axis="replicas")

    expected = [Orswot() for _ in range(n_objects)]
    for row in fleet:
        for e, o in zip(expected, row):
            e.merge(o)
    for e in expected:
        e.merge(Orswot())  # defer plunger

    shard = OrswotBatch(
        clock=joined.clock[0], ids=joined.ids[0], dots=joined.dots[0],
        d_ids=joined.d_ids[0], d_clocks=joined.d_clocks[0],
    )
    plunged = shard.merge(OrswotBatch.zeros(n_objects, uni))
    got = plunged.to_scalar(uni)
    assert [sorted(g.value().val) for g in got] == [
        sorted(e.value().val) for e in expected
    ]


def test_allgather_join_orswot_object_axis_sharded():
    """The hybrid layout single-host: objects sharded over one mesh axis
    (the DCN tier in a real deployment), replicas collectively joined
    over the other — results must match the unsharded-object join and
    the scalar oracle."""
    uni = Universe(CrdtConfig(num_actors=8, member_capacity=16, deferred_capacity=8))
    rng = np.random.RandomState(11)
    n_replicas, n_objects = 4, 8

    fleet = []
    for r in range(n_replicas):
        row = []
        for i in range(n_objects):
            o = Orswot()
            for _ in range(rng.randint(1, 4)):
                op = o.add(int(rng.randint(0, 12)),
                           o.value().derive_add_ctx(int(rng.randint(0, 4))))
                o.apply(op)
            row.append(o)
        fleet.append(row)

    batches = [OrswotBatch.from_scalar(row, uni) for row in fleet]
    stacked_np = jax.tree_util.tree_map(
        lambda *xs: np.asarray(jnp.stack(xs)), *batches
    )

    mesh = make_multihost_mesh({"replicas": 4}, {"objects": 2})
    from jax.sharding import NamedSharding, PartitionSpec as P

    stacked = jax.tree_util.tree_map(
        lambda x: jax.device_put(
            x, NamedSharding(mesh, P("replicas", "objects",
                                     *([None] * (x.ndim - 2))))
        ),
        stacked_np,
    )
    joined = allgather_join_orswot(
        stacked, mesh, axis="replicas", object_axis="objects"
    )

    expected = [Orswot() for _ in range(n_objects)]
    for row in fleet:
        for e, o in zip(expected, row):
            e.merge(o)
    for e in expected:
        e.merge(Orswot())

    for r in range(n_replicas):
        shard = OrswotBatch(
            clock=joined.clock[r], ids=joined.ids[r], dots=joined.dots[r],
            d_ids=joined.d_ids[r], d_clocks=joined.d_clocks[r],
        )
        got = shard.merge(OrswotBatch.zeros(n_objects, uni)).to_scalar(uni)
        assert [sorted(g.value().val) for g in got] == [
            sorted(e.value().val) for e in expected
        ], f"replica {r}"


def test_object_axis_overflow_flags_are_global():
    """With objects sharded over a second axis, the overflow flags must
    be identical on every object partition (OR-reduced across the axis)
    — a shard-local flag would diverge SPMD control flow multi-process:
    the overflowed process raises, its peers proceed and then hang at
    the next collective."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from crdt_tpu.error import CapacityOverflowError

    uni = Universe(CrdtConfig(num_actors=8, member_capacity=2, deferred_capacity=2))
    n_replicas, n_objects = 4, 4

    # only the LAST object's member union overflows m_cap=2
    fleet = []
    for r in range(n_replicas):
        row = []
        for i in range(n_objects):
            o = Orswot()
            members = [0] if i < n_objects - 1 else [r * 2, r * 2 + 1]
            for m in members:
                o.apply(o.add(m, o.value().derive_add_ctx(r)))
            row.append(o)
        fleet.append(row)

    batches = [OrswotBatch.from_scalar(row, uni) for row in fleet]
    stacked_np = jax.tree_util.tree_map(
        lambda *xs: np.asarray(jnp.stack(xs)), *batches
    )
    mesh = make_multihost_mesh({"replicas": 4}, {"objects": 2})
    stacked = jax.tree_util.tree_map(
        lambda x: jax.device_put(
            x, NamedSharding(mesh, P("replicas", "objects",
                                     *([None] * (x.ndim - 2))))
        ),
        stacked_np,
    )

    # the public API must raise (host-side reduce sees the flag)...
    with pytest.raises(CapacityOverflowError):
        allgather_join_orswot(stacked, mesh, axis="replicas",
                              object_axis="objects")

    # ...and the on-device flags must already be global: every object
    # partition carries the same OR-reduced [member, deferred] pair
    from crdt_tpu.parallel.collective import _orswot_join_fn

    arrays = (stacked.clock, stacked.ids, stacked.dots, stacked.d_ids,
              stacked.d_clocks)
    join = _orswot_join_fn(mesh, "replicas", 2, 2,
                           tuple(a.ndim for a in arrays), None, "objects")
    _, overflow = join(arrays)
    per_shard = [np.asarray(s.data).reshape(-1, 2).any(axis=0)
                 for s in overflow.addressable_shards]
    for flags in per_shard[1:]:
        np.testing.assert_array_equal(flags, per_shard[0])
    assert per_shard[0][0]  # member overflow visible on EVERY partition
