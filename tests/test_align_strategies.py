"""The two member-alignment strategies (O(M²) match vs sort+gather) must
produce identical merges — `compact_by_id` canonicalizes slot order, so the
dispatch threshold is purely a performance knob, never a semantics one."""

import numpy as np
import pytest

import jax.numpy as jnp

from crdt_tpu.ops import orswot_ops
from crdt_tpu.utils.testdata import random_orswot_arrays


@pytest.mark.parametrize("seed", [0, 1])
def test_match_and_sorted_align_agree(monkeypatch, seed):
    rng = np.random.RandomState(seed)
    n, a, m, d = 64, 8, 6, 3
    lhs = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, m, d, np.uint32))
    rhs = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, m, d, np.uint32))

    monkeypatch.setattr(orswot_ops, "_ALIGN_MATCH_MAX_M", 1 << 30)
    via_match = orswot_ops.merge(*lhs, *rhs, m, d)
    monkeypatch.setattr(orswot_ops, "_ALIGN_MATCH_MAX_M", 0)
    via_sort = orswot_ops.merge(*lhs, *rhs, m, d)

    names = ("clock", "ids", "dots", "d_ids", "d_clocks", "overflow")
    for name, x, y in zip(names, via_match, via_sort):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y), err_msg=name)


def test_large_m_sorted_path_is_pad_invariant():
    """Above the threshold merge dispatches to the sorted alignment; the
    result on slot-padded inputs must equal the small-M merge of the same
    logical states (padding with empty slots is semantically a no-op)."""
    rng = np.random.RandomState(2)
    n, a, m_small, d = 8, 4, 6, 2
    big_m = orswot_ops._ALIGN_MATCH_MAX_M + 8
    lhs = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, m_small, d, np.uint32))
    rhs = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, m_small, d, np.uint32))

    def pad(state):
        clock, ids, dots, d_ids, d_clocks = state
        extra = big_m - m_small
        return (
            clock,
            jnp.pad(ids, ((0, 0), (0, extra)), constant_values=-1),
            jnp.pad(dots, ((0, 0), (0, extra), (0, 0))),
            d_ids,
            d_clocks,
        )

    cap = 2 * m_small  # union always fits
    out_big = orswot_ops.merge(*pad(lhs), *pad(rhs), big_m, d)
    out_small = orswot_ops.merge(*lhs, *rhs, cap, d)
    np.testing.assert_array_equal(np.asarray(out_big[0]), np.asarray(out_small[0]))
    np.testing.assert_array_equal(
        np.asarray(out_big[1])[..., :cap], np.asarray(out_small[1])
    )
    np.testing.assert_array_equal(
        np.asarray(out_big[2])[..., :cap, :], np.asarray(out_small[2])
    )
    assert not (np.asarray(out_big[1])[..., cap:] != -1).any()
    assert not np.asarray(out_big[5]).any(), "padded merge must not overflow"


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_fast_and_deferred_paths_agree_without_deferred(seed):
    """Differential invariant behind the lax.cond dispatch: on
    deferred-free inputs the rank-select fast path and the full deferred
    pipeline must be bit-identical (replay over empty tables is the
    identity)."""
    import jax.numpy as jnp

    from crdt_tpu.ops import clock_ops, orswot_ops
    from crdt_tpu.utils.testdata import random_orswot_arrays

    rng = np.random.RandomState(seed)
    n, a, m, d = 64, 8, 6, 3
    L = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, m, d))
    R = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, m, d))
    clock = clock_ops.merge(L[0], R[0])

    fast = orswot_ops._merge_narrow_fast(clock, *L, *R, m, d)
    slow = orswot_ops._merge_narrow_deferred(clock, *L, *R, m, d)
    for f, s in zip(fast, slow):
        np.testing.assert_array_equal(np.asarray(f), np.asarray(s))


def test_stable_order_scatterless_matches_scatter(monkeypatch):
    """Both permutation-inverse paths must agree on random keys with
    duplicates (stability ties broken by slot index)."""
    rng = np.random.RandomState(3)
    keys = jnp.asarray(rng.randint(0, 7, size=(64, 24)).astype(np.int32))

    monkeypatch.setenv("CRDT_SCATTERLESS", "0")
    want = np.asarray(orswot_ops._stable_order(keys))
    monkeypatch.setenv("CRDT_SCATTERLESS", "1")
    got = np.asarray(orswot_ops._stable_order(keys))
    assert np.array_equal(got, want)
    # and it really is the stable ascending order
    gathered = np.take_along_axis(np.asarray(keys), got, axis=-1)
    assert (np.diff(gathered, axis=-1) >= 0).all()
