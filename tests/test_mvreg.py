"""MVReg tests — mirrors `/root/reference/test/mvreg.rs`.

Includes the op-compatibility filter (`test/mvreg.rs:120-143`), the
no-collapse-of-equal-concurrent-values regressions (`test/mvreg.rs:36-79`),
and the seven quickcheck properties (`test/mvreg.rs:157-320`).
"""

import dataclasses

from hypothesis import assume, given
from hypothesis import strategies as st

from crdt_tpu import Dot, MVReg, VClock
from crdt_tpu.scalar.mvreg import Put


@dataclasses.dataclass
class RegFixture:
    reg: MVReg
    ops: list


def build_test_reg(prim_ops):
    """`test/mvreg.rs:145-155`."""
    reg = MVReg()
    ops = []
    for val, actor in prim_ops:
        ctx = reg.read().derive_add_ctx(actor)
        op = reg.set(val, ctx)
        reg.apply(op)
        ops.append(op)
    return RegFixture(reg=reg, ops=ops)


def ops_are_not_compatible(opss):
    """`test/mvreg.rs:120-143`: reject op sequences that reuse an actor
    version across registers."""
    for a_ops in opss:
        for b_ops in opss:
            if b_ops is a_ops:
                continue
            a_clock, b_clock = VClock(), VClock()
            for (_, a_actor), (_, b_actor) in zip(a_ops, b_ops):
                a_clock.apply(a_clock.inc(a_actor))
                b_clock.apply(b_clock.inc(b_actor))
                if b_clock.get(a_actor) == a_clock.get(a_actor):
                    return True
    return False


prim_ops = st.lists(st.tuples(st.integers(0, 255), st.integers(0, 255)), max_size=10)


def test_apply():
    reg = MVReg()
    clock = Dot(2, 1).to_vclock()
    reg.apply(Put(clock=clock.clone(), val=71))
    read_ctx = reg.read()
    assert read_ctx.add_clock == clock
    assert read_ctx.val == [71]


def test_set_should_not_mutate_reg():
    reg = MVReg()
    ctx = reg.read().derive_add_ctx(1)
    op = reg.set(32, ctx)
    assert reg == MVReg()
    reg.apply(op)

    read_ctx = reg.read()
    assert read_ctx.val == [32]
    assert read_ctx.add_clock == Dot(1, 1).to_vclock()


def test_concurrent_update_with_same_value_dont_collapse_on_merge():
    """`test/mvreg.rs:36-57`: collapsing breaks commutativity."""
    r1, r2 = MVReg(), MVReg()
    ctx_4 = r1.read().derive_add_ctx(4)
    ctx_7 = r2.read().derive_add_ctx(7)

    r1.apply(r1.set(23, ctx_4))
    r2.apply(r2.set(23, ctx_7))

    r1.merge(r2)
    read_ctx = r1.read()
    assert read_ctx.val == [23, 23]
    assert read_ctx.add_clock == VClock.from_iter([(4, 1), (7, 1)])


def test_concurrent_update_with_same_value_dont_collapse_on_apply():
    """`test/mvreg.rs:59-79`."""
    r1, r2 = MVReg(), MVReg()
    ctx_4 = r1.read().derive_add_ctx(4)
    ctx_7 = r2.read().derive_add_ctx(7)

    r1.apply(r1.set(23, ctx_4))
    r1.apply(r2.set(23, ctx_7))

    read_ctx = r1.read()
    assert read_ctx.val == [23, 23]
    assert read_ctx.add_clock == VClock.from_iter([(4, 1), (7, 1)])


def test_multi_val():
    r1, r2 = MVReg(), MVReg()
    ctx_1 = r1.read().derive_add_ctx(1)
    ctx_2 = r2.read().derive_add_ctx(2)
    r1.apply(r1.set(32, ctx_1))
    r2.apply(r2.set(82, ctx_2))
    r1.merge(r2)
    assert sorted(r1.read().val) == [32, 82]


def test_op_commute_quickcheck1():
    reg1, reg2 = MVReg(), MVReg()
    op1 = Put(clock=Dot(1, 1).to_vclock(), val=1)
    op2 = Put(clock=Dot(2, 1).to_vclock(), val=2)

    reg2.apply(op2)
    reg2.apply(op1)
    reg1.apply(op1)
    reg1.apply(op2)

    assert reg1 == reg2


@given(prim_ops, st.integers(0, 255))
def test_prop_set_with_ctx_from_read(r_ops, a):
    reg = build_test_reg(r_ops).reg
    write_ctx = reg.read().derive_add_ctx(a)
    reg.apply(reg.set(23, write_ctx))
    assert reg.read().val == [23]


@given(prim_ops)
def test_prop_merge_idempotent(r_ops):
    r = build_test_reg(r_ops).reg
    r_snapshot = r.clone()
    r.merge(r_snapshot)
    assert r == r_snapshot


@given(prim_ops, prim_ops)
def test_prop_merge_commutative(r1_ops, r2_ops):
    assume(not ops_are_not_compatible([r1_ops, r2_ops]))
    r1 = build_test_reg(r1_ops).reg
    r2 = build_test_reg(r2_ops).reg

    r1_snapshot = r1.clone()
    r1.merge(r2)
    r2.merge(r1_snapshot)
    assert r1 == r2


@given(prim_ops, prim_ops, prim_ops)
def test_prop_merge_associative(r1_ops, r2_ops, r3_ops):
    assume(not ops_are_not_compatible([r1_ops, r2_ops, r3_ops]))
    r1 = build_test_reg(r1_ops).reg
    r2 = build_test_reg(r2_ops).reg
    r3 = build_test_reg(r3_ops).reg
    r1_snapshot = r1.clone()

    r1.merge(r2)  # r1 ^ r2
    r1.merge(r3)  # (r1 ^ r2) ^ r3
    r2.merge(r3)  # r2 ^ r3
    r2.merge(r1_snapshot)  # r1 ^ (r2 ^ r3)

    assert r1 == r2


@given(prim_ops)
def test_prop_truncate(r_ops):
    r = build_test_reg(r_ops).reg
    r_snapshot = r.clone()

    # truncating with the empty clock is a no-op
    r.truncate(VClock())
    assert r == r_snapshot

    # truncating with the merge of all val clocks empties the register
    clock = r.read().add_clock
    r.truncate(clock)
    assert r == MVReg()


@given(prim_ops)
def test_prop_op_idempotent(r_ops):
    test = build_test_reg(r_ops)
    r = test.reg
    r_snapshot = r.clone()
    for op in test.ops:
        r.apply(op)
    assert r == r_snapshot


@given(prim_ops, prim_ops)
def test_prop_op_commutative(o1_ops, o2_ops):
    assume(not ops_are_not_compatible([o1_ops, o2_ops]))
    o1 = build_test_reg(o1_ops)
    o2 = build_test_reg(o2_ops)
    r1, r2 = o1.reg, o2.reg

    for op in o2.ops:
        r1.apply(op)
    for op in o1.ops:
        r2.apply(op)
    assert r1 == r2


@given(prim_ops, prim_ops, prim_ops)
def test_prop_op_associative(o1_ops, o2_ops, o3_ops):
    assume(not ops_are_not_compatible([o1_ops, o2_ops, o3_ops]))
    o1 = build_test_reg(o1_ops)
    o2 = build_test_reg(o2_ops)
    o3 = build_test_reg(o3_ops)
    r1, r2 = o1.reg, o2.reg

    for op in o2.ops:
        r1.apply(op)
    for op in o3.ops:
        r1.apply(op)
    for op in o3.ops:
        r2.apply(op)
    for op in o1.ops:
        r2.apply(op)
    assert r1 == r2
