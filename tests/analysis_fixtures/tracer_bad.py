"""Fixture: tracer-hygiene violations (MUST trigger).

Host coercion and branching on traced args inside @jit, int64 in a
pallas-importing module, dict iteration feeding jit.  Parsed, never
imported — jax need not exist on the box.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl  # scopes pallas-int64 in


@jax.jit
def bad_merge(clock, flags):
    if flags:                                 # line 17: branch on traced arg
        clock = clock + 1
    return bool(flags), float(clock)          # line 19: two host coercions


@functools.partial(jax.jit, static_argnames=("m_cap",))
def ok_static_branch(clock, m_cap):
    if m_cap:  # static arg: NOT a finding
        clock = clock + 1
    return clock


@jax.jit
def bad_dict_fold(state):
    acc = 0
    for k, v in state.items():                # line 32: dict order traces
        acc = acc + v
    return acc


def kernel_index(block):
    # int64 plumbing in a pallas module: Mosaic has no 64-bit lowering
    idx = jnp.zeros((8,), dtype=jnp.int64)    # line 40
    return pl.load(block, idx)


_jit_apply = jax.jit(lambda *planes: planes)


def bad_splat(plane_map):
    return _jit_apply(*plane_map.values())    # line 47: dict order as args
