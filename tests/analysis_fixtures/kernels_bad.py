"""Fixture: kernel-contract violations (MUST trigger KC01-KC05).

Unlike the AST fixture twins (parsed, never imported), this module is
imported AND traced by ``tests/test_kernelcheck.py`` — the jaxpr tier
needs real traceable kernels.  Each ``fixture.*`` spec commits exactly
one sin:

* ``fixture.i64_lowering``   — an int64 op inside a pallas_call (KC01)
* ``fixture.float_scatter``  — float scatter-add, no unique_indices (KC02)
* ``fixture.baked_const``    — a 1 MB closure-captured array (KC03)
* ``fixture.shape_special``  — statics keyed on raw batch size (KC04)
* ``fixture.hidden_callback``— pure_callback in a hot-path kernel (KC05)

jax imports live inside the builders so merely importing this module
stays cheap; tests/ is outside the default scan set, so the repo-wide
gates never see these.
"""

import numpy as np

from crdt_tpu.analysis.kernels import KernelSpec, TraceCase

HERE = "tests/analysis_fixtures/kernels_bad.py"


def _sds(shape, dtype):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(shape, getattr(jnp, dtype))


def _b_i64_pallas():
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kern(x_ref, o_ref):
        o_ref[...] = (x_ref[...].astype(jnp.int64) + 1).astype(jnp.int32)

    def widen(x):
        return pl.pallas_call(
            kern, out_shape=jax.ShapeDtypeStruct((8, 128), jnp.int32),
            interpret=False,
        )(x)

    return [TraceCase("r0", widen, (_sds((8, 128), "int32"),))]


def _b_float_scatter():
    def fold(x, idx, upd):
        return x.at[idx].add(upd)  # order-unspecified float accumulation

    return [TraceCase(
        "r0", fold,
        (_sds((64,), "float32"), _sds((16,), "int32"),
         _sds((16,), "float32")))]


def _b_baked_const():
    import jax.numpy as jnp

    big = np.ones((512, 512), np.float32)  # 1 MB baked into every lowering

    def shift(x):
        return x + jnp.asarray(big)

    return [TraceCase("r0", shift, (_sds((512, 512), "float32"),))]


def _b_shape_special():
    import functools

    def head(x, k):
        return x[:k]  # k is the RAW batch size: one lowering per call

    return [
        TraceCase(f"B{k}", functools.partial(head, k=k),
                  (_sds((16,), "uint32"),), key=(k,))
        for k in (3, 5, 7, 11)
    ]


def _b_hidden_callback():
    import jax
    import jax.numpy as jnp

    def probe(x):
        host = jax.pure_callback(
            lambda v: np.asarray(v) * 2,
            jax.ShapeDtypeStruct((8,), jnp.float32), x)
        return host + 1

    return [TraceCase("r0", probe, (_sds((8,), "float32"),))]


SPECS = (
    KernelSpec("fixture.i64_lowering", HERE, "widen", mosaic=True,
               build=_b_i64_pallas),
    KernelSpec("fixture.float_scatter", HERE, "fold",
               determinism="float-accum", build=_b_float_scatter),
    KernelSpec("fixture.baked_const", HERE, "shift", build=_b_baked_const),
    KernelSpec("fixture.shape_special", HERE, "head", compile_budget=2,
               build=_b_shape_special),
    KernelSpec("fixture.hidden_callback", HERE, "probe",
               build=_b_hidden_callback),
)
