"""Fixture: the wire error-contract twin (MUST NOT trigger).

The same decode shapes done right: CrdtError subclasses (including the
sanctioned convert-in-try idiom), specific excepts, record_wire on
every leg.
"""

import struct

from crdt_tpu.error import SyncProtocolError, WireFormatError


def decode_frame(frame):
    if len(frame) < 8:
        raise SyncProtocolError("short frame")
    try:
        kind, length = struct.unpack_from("<II", frame)
        if length > len(frame):
            raise ValueError("overrun")  # converted below: not a finding
    except (struct.error, ValueError) as e:
        raise SyncProtocolError(f"malformed frame: {e}") from None
    return kind, frame[8:8 + length]


def decode_blob(blob):
    if not blob:
        raise WireFormatError("empty blob")
    return blob[1:]


class CountedBatch:
    def from_wire(self, blobs, universe):
        from crdt_tpu.batch.wirebulk import record_wire

        record_wire("counted", "from_wire", native=len(blobs))
        return [b.decode() for b in blobs]

    def to_wire(self, universe):
        # delegation to a recording helper counts too
        return self._planes_to_wire()

    def _planes_to_wire(self):
        from crdt_tpu.batch.wirebulk import record_wire

        record_wire("counted", "to_wire", native=1)
        return [b"ok"]
