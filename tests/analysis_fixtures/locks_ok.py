"""Fixture: the lock-discipline twin (MUST NOT trigger).

The same shapes, either properly locked or pragma'd with the reason the
discipline is deliberately waived (the Gauge last-write-wins contract).
"""

import threading


class DisciplinedAccumulator:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0
        self.last = None

    def add(self, n):
        with self._lock:
            self.total = self.total + n
            self.last = n

    def sneak(self, n):
        # gauge contract: the racing write that wins IS the level
        self.last = n  # crdtlint: disable=lock-discipline

    def bump(self):
        with self._lock:
            self.total += 1
