"""Fixture: the lock-discipline twin (MUST NOT trigger).

The same shapes, either properly locked or pragma'd with the reason the
discipline is deliberately waived (the Gauge last-write-wins contract).
The deadlock twins: both methods take the locks in ONE global order, a
re-acquire uses RLock, and blocking calls either move outside the
critical section or carry a pragma naming the serialization contract.
"""

import os
import time
import threading


class DisciplinedAccumulator:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0
        self.last = None

    def add(self, n):
        with self._lock:
            self.total = self.total + n
            self.last = n

    def sneak(self, n):
        # gauge contract: the racing write that wins IS the level
        self.last = n  # crdtlint: disable=lock-discipline

    def bump(self):
        with self._lock:
            self.total += 1


class OrderedLocks:
    """Lock order is a -> b, everywhere — no cycle, no finding."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.RLock()

    def forward(self):
        with self._a:
            with self._b:
                pass

    def also_forward(self):
        with self._a:
            with self._b:
                pass

    def reenter(self):
        with self._b:
            with self._b:  # RLock IS reentrant — not a finding
                pass


class SyncOutsideLock:
    def __init__(self, fh, sock):
        self._lock = threading.Lock()
        self._fh = fh
        self._sock = sock

    def flush(self):
        with self._lock:
            fileno = self._fh.fileno()
        os.fsync(fileno)                     # sync outside the lock

    def push(self, payload):
        # fsync-before-ack shape: the serialization is the contract
        with self._lock:
            self._sock.sendall(payload)  # crdtlint: disable=hold-and-block

    def throttle(self):
        time.sleep(0.01)                     # sleep outside any lock
