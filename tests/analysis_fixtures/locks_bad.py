"""Fixture: lock-discipline violations (MUST trigger).

A lock-owning class that writes the same attribute under the lock in
one method and bare in another, plus an unlocked read-modify-write —
the lost-increment shape the Counter contract forbids.
"""

import threading


class RacyAccumulator:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0
        self.last = None

    def add(self, n):
        with self._lock:
            self.total = self.total + n      # locked write ...
            self.last = n

    def sneak(self, n):
        self.last = n                        # line 23: ... unlocked write

    def bump(self):
        self.total += 1                      # line 26: unlocked RMW
