"""Fixture: lock-discipline violations (MUST trigger).

A lock-owning class that writes the same attribute under the lock in
one method and bare in another, plus an unlocked read-modify-write —
the lost-increment shape the Counter contract forbids.  The two
deadlock shapes ride along: an acquisition-order cycle between two
locks (plus a non-reentrant re-acquire), and a blocking syscall made
while a lock is held.
"""

import os
import time
import threading


class RacyAccumulator:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0
        self.last = None

    def add(self, n):
        with self._lock:
            self.total = self.total + n      # locked write ...
            self.last = n

    def sneak(self, n):
        self.last = n                        # line 29: ... unlocked write

    def bump(self):
        self.total += 1                      # line 32: unlocked RMW


class DeadlockProne:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        with self._a:
            with self._b:                    # a -> b ...
                pass

    def backward(self):
        with self._b:
            with self._a:                    # ... and b -> a: cycle
                pass

    def reenter(self):
        with self._a:
            with self._a:                    # non-reentrant re-acquire
                pass


class SyncUnderLock:
    def __init__(self, fh, sock):
        self._lock = threading.Lock()
        self._fh = fh
        self._sock = sock

    def flush(self):
        with self._lock:
            os.fsync(self._fh.fileno())      # fsync under the lock

    def push(self, payload):
        with self._lock:
            self._sock.sendall(payload)      # socket send under the lock

    def throttle(self):
        with self._lock:
            time.sleep(0.01)                 # timer under the lock
