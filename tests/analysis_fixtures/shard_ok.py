"""Fixture: the sharding-contract twins (MUST NOT trigger live).

The same shapes as ``shard_bad.py``, each either contract-satisfying
or pragma-suppressed — never unanalyzed:

* ``fixture_shard.pointwise_clean`` — honestly shard-local pointwise
* ``fixture_shard.routed_gather``   — gathers the object axis through
  a leaf DECLARED routed (the mesh layer rebases ids per shard), so
  SC01's exemption applies
* ``fixture_shard.declared_psum``   — the psum kernel with the psum on
  its reduction contract (SC02 clean)
* ``fixture_shard.pragma_sum``      — the SC01 sin with a pragma on
  the offending line: the finding FIRES and is suppressed, proving the
  twin is analyzed rather than inert
* ``fixture_shard.even_rungs``      — extents that divide every
  declared mesh size (SC04/SC05 clean across two rungs)

:data:`SC03_OK_SRC` is the lexical twin: the kernel output stays on
device in one function and carries a cadence pragma in the other.
"""

from crdt_tpu.analysis.kernels import (
    KernelSpec, TraceCase, pointwise, reduction,
)

HERE = "tests/analysis_fixtures/shard_ok.py"


def _sds(shape, dtype):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(shape, getattr(jnp, dtype))


def _b_pointwise_clean():
    def scale(x):
        return x * 2 + 1

    return [TraceCase("r0", scale, (_sds((8, 4), "float32"),))]


def _b_routed_gather():
    def route(x, idx):
        return x[idx]  # idx carries object IDS: declared routed

    return [TraceCase("r0", route,
                      (_sds((8, 4), "float32"), _sds((3,), "int32")))]


def _b_declared_psum():
    import jax

    def norm(x):
        return jax.vmap(lambda r: r + jax.lax.psum(r, "i"),
                        axis_name="i")(x)

    return [TraceCase("r0", norm, (_sds((8, 4), "float32"),))]


def _b_pragma_sum():
    import jax.numpy as jnp

    def center(x):
        return x - jnp.sum(x, axis=0)  # crdtlint: disable=SC01 — fixture: demonstrates pragma suppression on the anchor line

    return [TraceCase("r0", center, (_sds((8, 4), "float32"),))]


def _b_even_rungs():
    def scale(x):
        return x * 2

    return [
        TraceCase("r8", scale, (_sds((8, 4), "float32"),), key=(8,)),
        TraceCase("r16", scale, (_sds((16, 4), "float32"),), key=(16,)),
    ]


SPECS = (
    KernelSpec("fixture_shard.pointwise_clean", HERE, "scale",
               build=_b_pointwise_clean, sharding=pointwise()),
    KernelSpec("fixture_shard.routed_gather", HERE, "route",
               build=_b_routed_gather,
               sharding=pointwise((0, 0), routed=(1,))),
    KernelSpec("fixture_shard.declared_psum", HERE, "norm",
               build=_b_declared_psum,
               sharding=reduction(0, collectives=("psum",))),
    KernelSpec("fixture_shard.pragma_sum", HERE, "center",
               build=_b_pragma_sum, sharding=pointwise()),
    KernelSpec("fixture_shard.even_rungs", HERE, "scale",
               build=_b_even_rungs, sharding=pointwise()),
)


#: SC03 twins: on-device return, and a pragma'd deliberate sample point
SC03_OK_SRC = """\
import jax


@jax.jit
def _fold(x):
    return x.sum()


def on_device(x):
    total = _fold(x)
    return total


def sample_point(x):
    total = _fold(x)
    return int(total)  # crdtlint: disable=SC03 — fixture: one-int gauge fetch, once per cadence
"""
