"""Fixture: sharding-contract violations (MUST trigger SC01-SC05).

Like ``kernels_bad.py``, this module is imported AND traced by
``tests/test_shardcheck.py`` — the contract tier needs real traceable
kernels.  Each ``fixture_shard.*`` spec commits exactly one sin:

* ``fixture_shard.cross_object``    — pointwise-declared kernel that
  folds the object axis (SC01)
* ``fixture_shard.undeclared_psum`` — reduction lowering a psum it
  never declared (SC02 extra)
* ``fixture_shard.phantom_pmax``    — reduction declaring a pmax the
  jaxpr never lowers (SC02 missing)
* ``fixture_shard.ragged_rung``     — object extent 6 over mesh size 4
  (SC04)
* ``fixture_shard.budget_blowout``  — 2 distinct lowerings per mesh
  size against compile_budget=1 (SC05)

SC03 is lexical (the hot-path AST scan), so its sin ships as source
text (:data:`SC03_BAD_SRC`) the test mounts at a ``crdt_tpu/batch/``
rel path.  jax imports live inside the builders; tests/ is outside the
default scan set, so the repo-wide gates never see these.
"""

from crdt_tpu.analysis.kernels import (
    KernelSpec, TraceCase, pointwise, reduction,
)

HERE = "tests/analysis_fixtures/shard_bad.py"


def _sds(shape, dtype):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(shape, getattr(jnp, dtype))


def _b_cross_object():
    import jax.numpy as jnp

    def center(x):
        # folds the object axis, then broadcasts it back over every
        # object: each output row depends on ALL rows
        return x - jnp.sum(x, axis=0)

    return [TraceCase("r0", center, (_sds((8, 4), "float32"),))]


def _b_undeclared_psum():
    import jax

    def norm(x):
        return jax.vmap(lambda r: r + jax.lax.psum(r, "i"),
                        axis_name="i")(x)

    return [TraceCase("r0", norm, (_sds((8, 4), "float32"),))]


def _b_phantom_pmax():
    def bump(x):
        return x + 1  # lowers nothing collective

    return [TraceCase("r0", bump, (_sds((8, 4), "float32"),))]


def _b_ragged_rung():
    def scale(x):
        return x * 2

    return [TraceCase("r6", scale, (_sds((6, 4), "float32"),))]


def _b_budget_blowout():
    def scale(x):
        return x * 2

    return [
        TraceCase("r8", scale, (_sds((8, 4), "float32"),), key=(8,)),
        TraceCase("r16", scale, (_sds((16, 4), "float32"),), key=(16,)),
    ]


SPECS = (
    KernelSpec("fixture_shard.cross_object", HERE, "center",
               build=_b_cross_object, sharding=pointwise()),
    KernelSpec("fixture_shard.undeclared_psum", HERE, "norm",
               build=_b_undeclared_psum,
               sharding=reduction(0, collectives=())),
    KernelSpec("fixture_shard.phantom_pmax", HERE, "bump",
               build=_b_phantom_pmax,
               sharding=reduction(0, collectives=("pmax",))),
    KernelSpec("fixture_shard.ragged_rung", HERE, "scale",
               build=_b_ragged_rung, sharding=pointwise()),
    KernelSpec("fixture_shard.budget_blowout", HERE, "scale",
               compile_budget=1, build=_b_budget_blowout,
               sharding=pointwise()),
)


#: SC03 sin as source text: a local bound from a jitted kernel call
#: round-trips through int() inside a (mounted) mesh hot-path module
SC03_BAD_SRC = """\
import jax


@jax.jit
def _fold(x):
    return x.sum()


def sample(x):
    total = _fold(x)
    return int(total)
"""
