"""Fixture: the telemetry twin (MUST NOT trigger — pragma-suppressed).

Same violation shapes as ``telemetry_bad.py`` on a distinct name (so
the cross-file dedup can't fold the two fixtures together), with
per-line pragmas; the findings land in the ``suppressed`` bucket, not
the live set.
"""

from crdt_tpu.utils import tracing


def recover(batch):
    tracing.count("executor.twin_probe")  # crdtlint: disable=metric-type-collision,metric-namespace
    with tracing.span("executor.twin_probe"):  # crdtlint: disable=metric-type-collision,metric-namespace
        batch = batch.with_capacity(8, 8)
    return batch


def rogue_metric():
    tracing.count("totally.undocumented.metric")  # crdtlint: disable=metric-namespace
