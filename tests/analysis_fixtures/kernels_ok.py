"""Fixture: kernelcheck ok-twins — sanctioned idioms and pragma'd sins.

Two flavours, per the fixture-suite contract (ok twins must be
SUPPRESSED where they sin, not merely inert):

* genuinely clean idioms that must produce NO finding at all: the
  integer scatter-``max`` witness fold (the dot-witness rule every
  apply kernel uses), a float scatter-add with ``unique_indices=True``,
  a large array passed as an argument instead of captured, statics
  keyed on the padded capacity so the ladder shares one lowering, and
  a host callback in a spec declared ``hot_path=False``;
* the same sins as ``kernels_bad.py`` carrying a ``# crdtlint:
  disable=KCxx`` pragma with a justification — they must land in the
  ``suppressed`` bucket, proving the pragma machinery reaches
  jaxpr-tier findings through the equations' source locations.
"""

import numpy as np

from crdt_tpu.analysis.kernels import KernelSpec, TraceCase

HERE = "tests/analysis_fixtures/kernels_ok.py"


def _sds(shape, dtype):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(shape, getattr(jnp, dtype))


# -- genuinely clean idioms ---------------------------------------------------


def _b_witness_fold():
    def fold(clock, obj, actor, counter):
        # the sanctioned idiom: integer scatter-max IS the dot-witness
        # rule, associative+commutative, delivery-order free
        return clock.at[obj, actor].max(counter)

    return [TraceCase(
        "r0", fold,
        (_sds((8, 8), "uint64"), _sds((16,), "int32"),
         _sds((16,), "int32"), _sds((16,), "uint64")))]


def _b_unique_float_scatter():
    def fold(x, idx, upd):
        # unique indices: no accumulation, order cannot matter
        return x.at[idx].add(upd, unique_indices=True)

    return [TraceCase(
        "r0", fold,
        (_sds((64,), "float32"), _sds((16,), "int32"),
         _sds((16,), "float32")))]


def _b_const_as_arg():
    def shift(x, table):
        return x + table  # the 1 MB table rides as an ARGUMENT

    return [TraceCase(
        "r0", shift,
        (_sds((512, 512), "float32"), _sds((512, 512), "float32")))]


def _b_padded_shapes():
    import functools

    def head(x, k):
        return x[:k]

    # raw batch sizes 3/5/7 all pad to capacity 8: ONE cache key
    return [
        TraceCase(f"B{b}", functools.partial(head, k=8),
                  (_sds((16,), "uint32"),), key=(8,))
        for b in (3, 5, 7)
    ]


def _b_cold_callback():
    import jax
    import jax.numpy as jnp

    def probe(x):
        host = jax.pure_callback(
            lambda v: np.asarray(v) * 2,
            jax.ShapeDtypeStruct((8,), jnp.float32), x)
        return host + 1

    return [TraceCase("r0", probe, (_sds((8,), "float32"),))]


# -- pragma'd sins (must be suppressed, not clean) ----------------------------


def _b_sanctioned_float_scatter():
    def fold(x, idx, upd):
        # sanctioned: bench-only diagnostic fold, never feeds a digest
        return x.at[idx].add(upd)  # crdtlint: disable=KC02

    return [TraceCase(
        "r0", fold,
        (_sds((64,), "float32"), _sds((16,), "int32"),
         _sds((16,), "float32")))]


def _b_sanctioned_const():
    import jax.numpy as jnp

    big = np.ones((512, 512), np.float32)

    def shift(x):
        return x + jnp.asarray(big)

    return [TraceCase("r0", shift, (_sds((512, 512), "float32"),))]


SPECS = (
    KernelSpec("fixture_ok.witness_fold", HERE, "fold",
               determinism="integer-lattice", build=_b_witness_fold),
    KernelSpec("fixture_ok.unique_float_scatter", HERE, "fold",
               build=_b_unique_float_scatter),
    KernelSpec("fixture_ok.const_as_arg", HERE, "shift",
               build=_b_const_as_arg),
    KernelSpec("fixture_ok.padded_shapes", HERE, "head", compile_budget=1,
               build=_b_padded_shapes),
    # a declared cold path: callbacks allowed (KC05 scopes to hot_path)
    KernelSpec("fixture_ok.cold_callback", HERE, "probe", hot_path=False,
               build=_b_cold_callback),
    KernelSpec("fixture_ok.sanctioned_float_scatter", HERE, "fold",
               determinism="float-accum",
               build=_b_sanctioned_float_scatter),
    # consts carry no per-equation source frame, so KC03 sanctions go
    # through baseline.json (justification mandatory) rather than a
    # line pragma — the test parks this one via a baseline entry
    KernelSpec("fixture_ok.baselined_const", HERE, "shift",
               build=_b_sanctioned_const),
)
