"""Fixture: wire error-contract violations (MUST trigger).

A frame-decode path raising bare ValueError, a swallowing ``except
Exception``, and a bulk wire leg that never feeds record_wire.
"""

import struct


def decode_frame(frame):
    if len(frame) < 8:
        raise ValueError("short frame")       # line 12: bare ValueError
    kind, length = struct.unpack_from("<II", frame)
    try:
        payload = frame[8:8 + length]
    except Exception:                          # line 16: swallowed
        payload = b""
    return kind, payload


class SilentBatch:
    def from_wire(self, blobs, universe):      # line 22: no record_wire
        return [b.decode() for b in blobs]

    def to_wire(self, universe):
        from crdt_tpu.batch.wirebulk import record_wire

        record_wire("silent", "to_wire", native=1)
        return [b"ok"]
