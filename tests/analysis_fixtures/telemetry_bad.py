"""Fixture: telemetry-namespace violations (MUST trigger).

Reintroduces the exact PR 3 bug — a counter and a span histogram
sharing ``executor.regrow`` — plus a metric outside the documented
namespace manifest.  Never imported; the lint only parses it.
"""

from crdt_tpu.utils import tracing


def recover(batch):
    # the PR 3 collision: count() claims executor.regrow as a counter...
    tracing.count("executor.regrow")                    # line 13
    # ...while the span forwards it into a histogram of the same name
    with tracing.span("executor.regrow"):               # line 15
        batch = batch.with_capacity(8, 8)
    return batch


def rogue_metric():
    # not a documented family: no NameSpec row covers it
    tracing.count("totally.undocumented.metric")        # line 22
