"""Fixture: the tracer-hygiene twin (MUST NOT trigger).

Same shapes made hygienic (static args, sorted iteration, i32) or
pragma'd where the coercion is deliberate.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl  # noqa: F401


@functools.partial(jax.jit, static_argnames=("flags",))
def ok_merge(clock, flags):
    if flags:  # static: concrete Python value at trace time
        clock = clock + 1
    return clock


@jax.jit
def ok_where(clock, flags):
    return jnp.where(flags, clock + 1, clock)


@jax.jit
def deliberate_coercion(clock, flags):
    return bool(flags)  # crdtlint: disable=jit-host-coercion


@jax.jit
def ok_sorted_fold(state):
    acc = 0
    for k in sorted(state):  # canonical order: not a finding
        acc = acc + state[k]
    return acc


def kernel_index(block):
    idx = jnp.zeros((8,), dtype=jnp.int32)  # i32: the Mosaic-safe dtype
    return pl.load(block, idx)


_jit_apply = jax.jit(lambda *planes: planes)


def ok_splat(plane_map):
    return _jit_apply(*sorted(plane_map.values()))  # canonicalized
