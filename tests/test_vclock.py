"""VClock tests — mirrors `/root/reference/test/vclock.rs`.

Six quickcheck properties (`test/vclock.rs:14-67`) as hypothesis properties,
plus the unit tests including the full ordering matrix
(`test/vclock.rs:134-189`).
"""

from hypothesis import given
from hypothesis import strategies as st

from crdt_tpu import VClock

actors = st.integers(min_value=0, max_value=255)
counters = st.integers(min_value=0, max_value=2**64 - 1)


def build_vclock(prims):
    """`test/vclock.rs:5-12`: inc+apply per listed actor."""
    v = VClock()
    for actor in prims:
        op = v.inc(actor)
        v.apply(op)
    return v


@given(st.lists(actors))
def test_prop_from_iter_of_iter_is_nop(prims):
    clock = build_vclock(prims)
    assert clock == VClock.from_iter(iter(clock.clone()))


@given(st.lists(st.tuples(actors, counters)))
def test_prop_from_iter_order_of_dots_should_not_matter(dots):
    reverse = VClock.from_iter(reversed(dots))
    forward = VClock.from_iter(dots)
    assert reverse == forward


@given(st.lists(st.tuples(actors, counters)))
def test_prop_from_iter_dots_should_be_idempotent(dots):
    single = VClock.from_iter(dots)
    double = VClock.from_iter(list(dots) + list(dots))
    assert single == double


@given(st.lists(actors))
def test_prop_truncate_self_is_nop(prims):
    clock = build_vclock(prims)
    clock_truncated = clock.clone()
    clock_truncated.truncate(clock)
    assert clock_truncated == clock


@given(st.lists(actors))
def test_prop_subtract_with_empty_is_nop(prims):
    clock = build_vclock(prims)
    subbed = clock.clone()
    subbed.subtract(VClock())
    assert subbed == clock


@given(st.lists(actors))
def test_prop_subtract_self_is_empty(prims):
    clock = build_vclock(prims)
    subbed = clock.clone()
    subbed.subtract(clock)
    assert subbed == VClock()


def test_subtract():
    a = VClock.from_iter([(1, 4), (2, 3), (5, 9)])
    b = VClock.from_iter([(1, 5), (2, 3), (5, 8)])
    expected = VClock.from_iter([(5, 9)])
    a.subtract(b)
    assert a == expected


def test_merge():
    a = VClock.from_iter([(1, 1), (2, 2), (4, 4)])
    b = VClock.from_iter([(3, 3), (4, 3)])
    a.merge(b)
    c = VClock.from_iter([(1, 1), (2, 2), (3, 3), (4, 4)])
    assert a == c


def test_merge_less_left():
    a, b = VClock(), VClock()
    a.witness(5, 5)
    b.witness(6, 6)
    b.witness(7, 7)
    a.merge(b)
    assert a.get(5) == 5
    assert a.get(6) == 6
    assert a.get(7) == 7


def test_merge_less_right():
    a, b = VClock(), VClock()
    a.witness(6, 6)
    a.witness(7, 7)
    b.witness(5, 5)
    a.merge(b)
    assert a.get(5) == 5
    assert a.get(6) == 6
    assert a.get(7) == 7


def test_merge_same_id():
    a, b = VClock(), VClock()
    a.witness(1, 1)
    a.witness(2, 1)
    b.witness(1, 1)
    b.witness(3, 1)
    a.merge(b)
    assert a.get(1) == 1
    assert a.get(2) == 1
    assert a.get(3) == 1


def test_vclock_ordering():
    assert VClock() == VClock()

    a, b = VClock(), VClock()
    a.witness("A", 1)
    a.witness("A", 2)
    a.witness("A", 0)
    b.witness("A", 1)
    # a {A:2}, b {A:1} — a dominates
    assert a > b
    assert b < a
    assert a != b

    b.witness("A", 3)
    # a {A:2}, b {A:3} — b dominates
    assert b > a
    assert a < b
    assert a != b

    a.witness("B", 1)
    # a {A:2, B:1}, b {A:3} — concurrent
    assert a != b
    assert not (a > b)
    assert not (b > a)
    assert a.concurrent(b)

    a.witness("A", 3)
    # a {A:3, B:1}, b {A:3} — a dominates
    assert a > b
    assert b < a
    assert a != b

    b.witness("B", 2)
    # a {A:3, B:1}, b {A:3, B:2} — b dominates
    assert b > a
    assert a < b
    assert a != b

    a.witness("B", 2)
    # equal
    assert not (b > a)
    assert not (a > b)
    assert a == b


def test_truncate_doc_example():
    """Doctest from `vclock.rs:88-102`."""
    c = VClock()
    c.witness(23, 6)
    c.witness(89, 14)
    c2 = c.clone()

    c.truncate(c2)  # no-op
    assert c == c2

    c.witness(43, 1)
    assert c.get(43) == 1
    c.truncate(c2)  # removes the 43 => 1 entry
    assert c.get(43) == 0


def test_witness_dominated_is_ignored():
    """Doctest from `vclock.rs:148-163`."""
    a, b = VClock(), VClock()
    a.witness("A", 2)
    a.witness("A", 0)  # ignored — 2 dominates 0
    b.witness("A", 1)
    assert a > b


def test_concurrent_doc_example():
    """Doctest from `vclock.rs:189-199`."""
    a, b = VClock(), VClock()
    a_op = a.inc("A")
    a.apply(a_op)
    b_op = b.inc("B")
    b.apply(b_op)
    assert a.concurrent(b)


def test_from_dot():
    from crdt_tpu import Dot

    clock = Dot("A", 3).to_vclock()
    assert clock.get("A") == 3
    assert len(clock) == 1
