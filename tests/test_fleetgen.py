"""Column-encoded fleet generation (`fleet_columns` + device-side
`build_fleet_planes`) — the resident north-star ingest path.

The dense planes the device builds from compact columns must satisfy the
batch-layout invariants (testdata module docstring) and, folded, agree
with the scalar reference engine — the same contract
`anti_entropy_fleets` meets, at ~200x less host->device transfer.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from crdt_tpu.ops import orswot_ops
from crdt_tpu.scalar.orswot import Orswot
from crdt_tpu.utils.testdata import (
    build_fleet_planes,
    dense_row_to_scalar,
    fleet_columns,
)


def _build(seed=11, n=64, a=16, m_cap=12, d=2, r=4, base=5, novel=1,
           deferred_frac=0.3):
    rng = np.random.RandomState(seed)
    cols = fleet_columns(rng, n, a, m_cap, d, r, base=base, novel=novel,
                         deferred_frac=deferred_frac)
    planes = build_fleet_planes(
        cols, a=a, m_cap=m_cap, d=d, base=base, novel=novel
    )
    return cols, tuple(np.asarray(x) for x in planes)


def test_planes_satisfy_layout_invariants():
    _, (clock, ids, dots, d_ids, d_clocks) = _build()
    r, n, m = ids.shape
    # unique member ids within each (replica, object)
    for rep in range(r):
        for i in range(n):
            live = ids[rep, i][ids[rep, i] != -1]
            assert len(set(live.tolist())) == live.size
    # live slots carry non-empty dot clocks; empty slots carry none
    live_mask = ids != -1
    assert bool(np.all((dots.sum(axis=-1) > 0) == live_mask))
    # the set clock covers every entry dot
    assert bool(np.all(clock >= dots.max(axis=2)))
    # deferred rows only on replica 0, citing a counter past the set clock
    assert bool(np.all(d_ids[1:] == -1))
    hit = d_ids[0, :, 0] != -1
    assert hit.any(), "deferred_frac=0.3 over 64 objects produced no rows"
    ahead = d_clocks[0, hit, 0]
    assert bool(np.all((ahead > clock[0, hit]).sum(axis=-1) == 1))


def test_build_is_deterministic_and_jittable():
    cols, planes = _build()
    jitted = jax.jit(
        lambda c: build_fleet_planes(c, a=16, m_cap=12, d=2, base=5, novel=1)
    )
    again = jitted({k: jnp.asarray(v) for k, v in cols.items()})
    for x, y in zip(planes, again):
        np.testing.assert_array_equal(x, np.asarray(y))


def test_fold_matches_scalar_oracle():
    """Left fold + defer plunger over the built planes == scalar N-way
    merge, per object (the parity contract the bench asserts on a
    sample)."""
    _, planes = _build(n=32)
    r = planes[0].shape[0]
    m, d = planes[1].shape[-1], planes[3].shape[-1]

    acc = tuple(jnp.asarray(x[0]) for x in planes)
    for i in range(1, r):
        acc = orswot_ops.merge(*acc, *(jnp.asarray(x[i]) for x in planes), m, d)[:5]
    acc = orswot_ops.merge(*acc, *acc, m, d)[:5]
    got = [np.asarray(x) for x in acc]

    for obj in range(32):
        merged = Orswot()
        for rep in range(r):
            merged.merge(dense_row_to_scalar(*(x[rep, obj] for x in planes)))
        merged.merge(Orswot())
        got_members = {int(mid) for mid in got[1][obj] if int(mid) != -1}
        assert got_members == set(merged.value().val), f"object {obj}"


def test_columns_are_compact():
    """The whole point: columns must stay ~2 orders of magnitude smaller
    than the dense planes they expand into."""
    cols, planes = _build(n=256)
    col_bytes = sum(v.nbytes for v in cols.values())
    plane_bytes = sum(x.nbytes for x in planes)
    assert col_bytes * 50 < plane_bytes, (col_bytes, plane_bytes)


def test_union_bound_and_uint8_guard():
    rng = np.random.RandomState(0)
    with pytest.raises(ValueError, match="union bound"):
        fleet_columns(rng, 4, 8, m_cap=4, d=1, r=4, base=3, novel=1)
    with pytest.raises(ValueError, match="uint8"):
        fleet_columns(rng, 4, 300, m_cap=8, d=1, r=2, base=3, novel=1)
