"""Convergence-observatory tests — divergence aging, the stability
frontier, the lattice auditor (crdt_tpu/obs/stability.py, ISSUE 15).

The acceptance pins: (1) the frontier soundness property — under a
seeded random op/merge/GC history with 20% frame loss, delay-reorder
and one kill -9 durable rejoin, the published frontier clock never
exceeds any live peer's true applied clock at any observation point,
and is monotone non-decreasing per observer; (2) the lattice auditor
records ZERO violations across a healthy run and fires a loud
``stability.audit_violation`` flight event when a plane is deliberately
corrupted (a lying frontier floor; a non-idempotent merge).
"""

import itertools
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from crdt_tpu.batch import OrswotBatch
from crdt_tpu.cluster import (
    ClusterNode,
    FaultPlan,
    FaultyTransport,
    GossipScheduler,
    Membership,
    ResilientTransport,
    RetryPolicy,
    queue_pair,
)
from crdt_tpu.config import CrdtConfig
from crdt_tpu.error import PeerUnavailableError
from crdt_tpu.obs import convergence as obs_convergence
from crdt_tpu.obs import events as obs_events
from crdt_tpu.obs import metrics as obs_metrics
from crdt_tpu.obs import namespace as obs_namespace
from crdt_tpu.obs import stability as obs_stability
from crdt_tpu.obs.stability import (
    StabilityTracker,
    subtree_layout,
    subtree_version_vectors,
)
from crdt_tpu.scalar.orswot import Orswot
from crdt_tpu.sync import digest as sync_digest
from crdt_tpu.sync import tree as sync_tree
from crdt_tpu.sync.session import SyncSession, sync_pair
from crdt_tpu.utils import tracing
from crdt_tpu.utils.interning import Universe

pytestmark = pytest.mark.stability

FAST = RetryPolicy(send_deadline_s=3.0, recv_deadline_s=3.0,
                   ack_timeout_s=0.05, max_backoff_s=0.3,
                   retry_budget=400)


def _uni(num_actors=8, member_capacity=16, deferred_capacity=4):
    return Universe.identity(CrdtConfig(
        num_actors=num_actors, member_capacity=member_capacity,
        deferred_capacity=deferred_capacity, counter_bits=32))


def _orswot_fleet(n, seed, actor=1, extra_on=()):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        s = Orswot()
        for _ in range(rng.randint(1, 4)):
            s.apply(s.add(int(rng.randint(0, 50)),
                          s.value().derive_add_ctx(0)))
        out.append(s)
    for i in extra_on:
        s = out[i]
        s.apply(s.add(900 + actor, s.value().derive_add_ctx(actor)))
    return out


def _vv(batch):
    return np.asarray(sync_digest.version_vector(batch), np.uint64)


def _pad(v, width):
    v = np.asarray(v, np.uint64).reshape(-1)
    if v.size < width:
        v = np.concatenate([v, np.zeros(width - v.size, np.uint64)])
    return v


def _dominates(a, b):
    """a >= b element-wise after zero-padding."""
    width = max(len(a), len(b))
    return bool((_pad(a, width) >= _pad(b, width)).all())


# ---------------------------------------------------------------------------
# subtree layout + the frontier fold kernel
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n", [0, 1, 5, 16, 17, 255, 256, 257, 5000])
def test_subtree_layout_matches_tree_coverage(n):
    subtrees, span = subtree_layout(n)
    assert subtrees <= sync_tree.TREE_K or n <= sync_tree.TREE_K
    if n == 0:
        assert subtrees == 0
        return
    # coverage: the subtree ranges tile [0, n) exactly
    assert (subtrees - 1) * span < n <= subtrees * span
    # consistency with the real digest tree: the top children level
    tree = sync_tree.build_tree(
        np.arange(1, n + 1, dtype=np.uint64))
    if tree.num_levels >= 2:
        assert subtrees == tree.level_size(tree.num_levels - 2)
        assert span == sync_tree.TREE_K ** (tree.num_levels - 2)


@pytest.mark.parametrize("n", [3, 16, 40, 257])
def test_frontier_fold_matches_numpy_segment_max(n):
    uni = _uni()
    batch = OrswotBatch.from_scalar(
        _orswot_fleet(n, seed=3, actor=2, extra_on=[0, n - 1]), uni)
    svv = subtree_version_vectors(batch)
    clock = np.asarray(batch.clock)
    subtrees, span = subtree_layout(n)
    pad = subtrees * span - n
    padded = np.concatenate(
        [clock, np.zeros((pad, clock.shape[1]), clock.dtype)])
    ref = padded.reshape(subtrees, span, -1).max(axis=1).astype(np.uint64)
    assert svv.shape == (subtrees, clock.shape[1])
    assert np.array_equal(svv, ref)


def test_subtree_vv_is_memoized_per_batch_object():
    uni = _uni()
    batch = OrswotBatch.from_scalar(_orswot_fleet(20, seed=5), uni)
    a = subtree_version_vectors(batch)
    b = subtree_version_vectors(batch)
    assert a is b  # cache hit — idle rounds run zero frontier folds


# ---------------------------------------------------------------------------
# plane 1: divergence aging
# ---------------------------------------------------------------------------


def test_divergence_aging_birth_to_resolution():
    clock = [0.0]
    reg = obs_metrics.MetricsRegistry()
    trk = StabilityTracker(registry=reg, clock=lambda: clock[0])
    # n=40 objects -> span 16: rows 0..15 are subtree 0, 16.. subtree 1
    trk.observe_descent("p", [0, 5, 17], 40)
    clock[0] = 2.0
    # subtree 1 (row 17) resolves; subtree 0 stays diverged (row 3)
    trk.observe_descent("p", [3], 40)
    snap = reg.snapshot()
    hist = snap["histograms"]["sync.stability.divergence_age_s"]
    assert hist["count"] == 1
    assert abs(hist["sum"] - 2.0) < 1e-9
    assert snap["gauges"]["sync.stability.outstanding"] == 1
    # the episode keeps its ORIGINAL birth: age grows across exchanges
    assert snap["gauges"]["sync.peer.p.divergence_age_s"] == \
        pytest.approx(2.0)
    clock[0] = 7.5
    trk.observe_descent("p", [], 40)  # clean exchange resolves the rest
    snap = reg.snapshot()
    assert snap["gauges"]["sync.stability.outstanding"] == 0
    assert snap["gauges"]["sync.peer.p.divergence_age_s"] == 0.0
    assert snap["gauges"]["sync.stability.divergence_age_max_s"] == \
        pytest.approx(7.5)
    hist = snap["histograms"]["sync.stability.divergence_age_s"]
    assert hist["count"] == 2


def test_divergence_resolution_counts_and_fires_event():
    before = tracing.counters().get("sync.stability.resolved", 0)
    trk = StabilityTracker(registry=obs_metrics.MetricsRegistry())
    trk.observe_descent("q", [1, 2], 40)
    trk.observe_descent("q", [], 40)
    assert tracing.counters().get("sync.stability.resolved", 0) \
        == before + 1  # rows 1, 2 share subtree 0: one episode
    evs = [e for e in obs_events.recorder().snapshot()
           if e["kind"] == "stability.resolved"
           and e["fields"].get("peer") == "q"]
    assert evs and evs[-1]["fields"]["subtrees"] == 1


def test_converged_session_resolves_all_aging():
    uni = _uni()
    batch = OrswotBatch.from_scalar(_orswot_fleet(40, seed=9), uni)
    reg = obs_metrics.MetricsRegistry()
    trk = StabilityTracker(registry=reg)
    trk.observe_descent("p", [0, 17, 39], 40)
    trk.observe_converged("p", batch)
    snap = reg.snapshot()
    assert snap["gauges"]["sync.stability.outstanding"] == 0
    assert trk.oldest_divergence_age_s() == 0.0


# ---------------------------------------------------------------------------
# plane 2: the stability frontier
# ---------------------------------------------------------------------------


def test_frontier_equals_peer_min_and_fleet_vv():
    uni = _uni()
    batch = OrswotBatch.from_scalar(
        _orswot_fleet(40, seed=11, actor=1, extra_on=[1, 20]), uni)
    trk = StabilityTracker(registry=obs_metrics.MetricsRegistry())
    trk.observe_converged("a", batch)
    rep = trk.frontier(batch, peers=["a"])
    assert rep.peers == 1 and rep.unheard == 0
    # one peer converged with the whole state: frontier == local VV
    assert np.array_equal(rep.clock, _vv(batch))
    # per-subtree rows never below the fleet-min clock
    assert (rep.subtree_clocks >= rep.clock).all()


def test_frontier_unheard_roster_peer_pins_zero():
    uni = _uni()
    batch = OrswotBatch.from_scalar(_orswot_fleet(40, seed=12), uni)
    trk = StabilityTracker(registry=obs_metrics.MetricsRegistry())
    rep = trk.frontier(batch, peers=["ghost"])
    assert rep.unheard == 1
    assert int(rep.clock.max(initial=0)) == 0
    assert int(rep.subtree_clocks.max(initial=0)) == 0


def test_frontier_liveness_stale_freeze_and_quarantine():
    clock = [0.0]
    uni = _uni()
    batch = OrswotBatch.from_scalar(_orswot_fleet(40, seed=13), uni)
    trk = StabilityTracker(registry=obs_metrics.MetricsRegistry(),
                           stale_after_s=10.0, quarantine_s=100.0,
                           clock=lambda: clock[0])
    trk.observe_converged("a", batch)
    clock[0] = 50.0  # past stale, inside quarantine: frozen, contributing
    rep = trk.frontier(batch, peers=["a"])
    assert rep.peers == 1 and rep.stale == 1 and rep.frozen
    assert np.array_equal(rep.clock, _vv(batch))
    clock[0] = 200.0  # past quarantine: excluded from the minimum
    rep = trk.frontier(batch, peers=["a"])
    assert rep.excluded == 1 and rep.peers == 0
    # never-heard roster peers quarantine off their first sighting too
    rep = trk.frontier(batch, peers=["a", "ghost"])
    assert rep.unheard == 1
    clock[0] = 301.0
    rep = trk.frontier(batch, peers=["a", "ghost"])
    assert rep.unheard == 0 and rep.excluded == 2


def test_frontier_monotone_per_observer_and_restore_floor():
    uni = _uni()
    batch = OrswotBatch.from_scalar(
        _orswot_fleet(40, seed=14, actor=2, extra_on=[0, 1, 2]), uni)
    trk = StabilityTracker(registry=obs_metrics.MetricsRegistry())
    trk.observe_converged("a", batch)
    first = trk.frontier(batch, peers=["a"]).clock.copy()
    assert first.max(initial=0) > 0
    # a NEW unheard roster peer would pin zero — the published series
    # must not regress (monotone per observer, by the published floor)
    second = trk.frontier(batch, peers=["a", "newcomer"]).clock
    assert np.array_equal(second, first)
    # restore() floors a FRESH tracker (the kill -9 rejoin path)
    trk2 = StabilityTracker(registry=obs_metrics.MetricsRegistry())
    trk2.restore(first)
    rep = trk2.frontier(batch, peers=["a"])  # 'a' unheard here
    assert rep.unheard == 1
    assert np.array_equal(rep.clock, first)
    assert trk2.frontier_clock() is not None


def test_frontier_gauges_and_namespace_conformance():
    uni = _uni()
    batch = OrswotBatch.from_scalar(_orswot_fleet(40, seed=15), uni)
    reg = obs_metrics.MetricsRegistry()
    trk = StabilityTracker(registry=reg)
    trk.observe_descent("a", [0, 17], 40)
    trk.observe_converged("a", batch)
    trk.frontier(batch, peers=["a", "ghost"])
    trk.audit(batch, uni, sample=4)
    snap = reg.snapshot()
    for kind, table in (("gauge", snap["gauges"]),
                        ("histogram", snap["histograms"])):
        for name in table:
            assert obs_namespace.match(name, kind) is not None, (
                f"published {kind} {name!r} has no namespace manifest row"
            )
    assert "stability.frontier.max_counter" in snap["gauges"]
    assert "stability.frontier.subtree.0.max_counter" in snap["gauges"]
    assert snap["gauges"]["stability.frontier.subtrees"] == 3


# ---------------------------------------------------------------------------
# session integration
# ---------------------------------------------------------------------------


def test_session_feeds_aging_and_frontier():
    uni = _uni()
    a = OrswotBatch.from_scalar(
        _orswot_fleet(40, seed=21, actor=1, extra_on=[1]), uni)
    b = OrswotBatch.from_scalar(
        _orswot_fleet(40, seed=21, actor=2, extra_on=[20]), uni)
    ta, tb = StabilityTracker(), StabilityTracker()
    sa = SyncSession(a, uni, peer="b", stability=ta)
    sb = SyncSession(b, uni, peer="a", stability=tb)
    ra, rb = sync_pair(sa, sb)
    assert ra.converged and rb.converged and ra.diverged > 0
    # the session resolved what it diverged...
    assert ta.oldest_divergence_age_s() == 0.0
    # ...but a DELTA session's frontier evidence is deferred: the peer
    # has not committed the merge yet, so the frontier stays unheard
    rep = ta.frontier(sa.batch, peers=["b"])
    assert rep.unheard == 1 and int(rep.clock.max(initial=0)) == 0
    # the next idle re-sync is the clean exchange that commits it
    sa2 = SyncSession(sa.batch, uni, peer="b", stability=ta)
    sb2 = SyncSession(sb.batch, uni, peer="a", stability=tb)
    ra2, rb2 = sync_pair(sa2, sb2)
    assert ra2.converged and ra2.diverged == 0
    rep = ta.frontier(sa2.batch, peers=["b"])
    assert np.array_equal(rep.clock, _vv(sa2.batch))
    rep_b = tb.frontier(sb2.batch, peers=["a"])
    assert np.array_equal(rep.clock, rep_b.clock)  # same converged state


def test_failed_session_leaves_divergence_outstanding():
    import queue

    from crdt_tpu.error import SyncProtocolError

    uni = _uni()
    a = OrswotBatch.from_scalar(
        _orswot_fleet(40, seed=22, actor=1, extra_on=[1]), uni)
    b = OrswotBatch.from_scalar(
        _orswot_fleet(40, seed=22, actor=2, extra_on=[1]), uni)
    trk = StabilityTracker()
    sa = SyncSession(a, uni, peer="b", stability=trk)
    sb = SyncSession(b, uni, peer="a")

    a2b: "queue.Queue[bytes]" = queue.Queue()
    b2a: "queue.Queue[bytes]" = queue.Queue()
    recvs = [0]

    def cut_recv():
        # hello + digest arrive, then the link dies: the session has
        # learned the diverged set but never resolves it
        recvs[0] += 1
        if recvs[0] > 2:
            raise EOFError("injected cut")
        return b2a.get(timeout=5)

    t = threading.Thread(
        target=lambda: _swallow(
            lambda: sb.sync(b2a.put, lambda: a2b.get(timeout=2))),
        daemon=True)
    t.start()
    with pytest.raises(SyncProtocolError):
        sa.sync(a2b.put, cut_recv)
    t.join(timeout=10)
    assert trk.oldest_divergence_age_s() > 0.0  # still outstanding


def _swallow(fn):
    try:
        fn()
    except Exception:
        pass


# ---------------------------------------------------------------------------
# plane 3: the lattice auditor
# ---------------------------------------------------------------------------


def test_audit_healthy_counts_checks_zero_violations():
    uni = _uni()
    batch = OrswotBatch.from_scalar(
        _orswot_fleet(40, seed=31, actor=1, extra_on=[2]), uni)
    before = tracing.counters().get("stability.audit.violations", 0)
    trk = StabilityTracker(
        registry=obs_metrics.MetricsRegistry(),
        tracker=obs_convergence.ConvergenceTracker(
            registry=obs_metrics.MetricsRegistry()))
    trk.observe_converged("a", batch)
    trk.frontier(batch, peers=["a"])
    rep = trk.audit(batch, uni, sample=8)
    assert rep.ok and rep.checks >= 8 and rep.sampled == 8
    assert tracing.counters().get("stability.audit.violations", 0) \
        == before
    assert trk.snapshot()["audit"]["violations"] == 0


def test_audit_trips_on_corrupted_frontier_plane():
    """Deliberate plane corruption #1: a frontier floor lying ABOVE the
    local version vector must fire the frontier_local violation with a
    loud flight event."""
    uni = _uni()
    batch = OrswotBatch.from_scalar(_orswot_fleet(40, seed=32), uni)
    trk = StabilityTracker(
        registry=obs_metrics.MetricsRegistry(),
        tracker=obs_convergence.ConvergenceTracker(
            registry=obs_metrics.MetricsRegistry()))
    trk.restore(np.full(_vv(batch).size, 999, np.uint64))
    trk.frontier(batch, peers=[])
    rep = trk.audit(batch, uni, sample=4)
    assert any(v["plane"] == "frontier_local" for v in rep.violations)
    evs = [e for e in obs_events.recorder().snapshot()
           if e["kind"] == "stability.audit_violation"]
    assert evs and evs[-1]["fields"]["plane"] == "frontier_local"
    assert trk.snapshot()["audit"]["last_violation"]["plane"] == \
        "frontier_local"


def test_audit_trips_on_corrupted_frontier_vs_peer_vv():
    """Deliberate plane corruption #2: frontier evidence claiming a
    peer converged at clocks ABOVE what that peer freshly advertises
    must fire frontier_peer_vv."""
    uni = _uni()
    batch = OrswotBatch.from_scalar(
        _orswot_fleet(40, seed=33, actor=3, extra_on=[0]), uni)
    conv = obs_convergence.ConvergenceTracker(
        registry=obs_metrics.MetricsRegistry())
    trk = StabilityTracker(registry=obs_metrics.MetricsRegistry(),
                           tracker=conv)
    # corrupt the evidence plane: "a converged with the full state"...
    trk.observe_converged("a", batch)
    trk.frontier(batch, peers=["a"])
    # ...while a's own advertised version vector says it holds nothing
    conv.observe_version_vector("a", [0] * 8)
    rep = trk.audit(batch, uni, sample=0)
    assert any(v["plane"] == "frontier_peer_vv" for v in rep.violations)


def test_audit_trips_on_non_idempotent_merge(monkeypatch):
    """Deliberate plane corruption #3: a merge that is not idempotent
    (one bit of drift per self-merge) must fail the sampled digest
    re-check."""
    uni = _uni()
    batch = OrswotBatch.from_scalar(
        _orswot_fleet(40, seed=34, actor=1, extra_on=[5]), uni)
    orig = OrswotBatch.merge

    def drifting_merge(self, other, **kw):
        out = orig(self, other, **kw)
        return out.replace(clock=out.clock.at[0, 0].add(1))

    trk = StabilityTracker(registry=obs_metrics.MetricsRegistry())
    monkeypatch.setattr(OrswotBatch, "merge", drifting_merge)
    rep = trk.audit(batch, uni, sample=8)
    assert any(v["plane"] == "merge_idempotence" for v in rep.violations)


def test_maybe_audit_cadence():
    uni = _uni()
    batch = OrswotBatch.from_scalar(_orswot_fleet(20, seed=35), uni)
    trk = StabilityTracker(registry=obs_metrics.MetricsRegistry(),
                           audit_every=3, audit_sample=2)
    ran = [trk.maybe_audit(batch, uni) is not None for _ in range(6)]
    assert ran == [False, False, True, False, False, True]
    off = StabilityTracker(registry=obs_metrics.MetricsRegistry(),
                           audit_every=0)
    assert off.maybe_audit(batch, uni) is None


# ---------------------------------------------------------------------------
# surfaces: /stability, the fleet lattice min-join, durable persistence
# ---------------------------------------------------------------------------


def test_stability_endpoint_serves_snapshot():
    from crdt_tpu.obs.export import start_metrics_server

    uni = _uni()
    batch = OrswotBatch.from_scalar(
        _orswot_fleet(40, seed=41, actor=1, extra_on=[1]), uni)
    trk = StabilityTracker(
        registry=obs_metrics.MetricsRegistry(),
        tracker=obs_convergence.ConvergenceTracker(
            registry=obs_metrics.MetricsRegistry()))
    trk.observe_descent("a", [17], 40)
    trk.observe_converged("a", batch)
    trk.frontier(batch, peers=["a"])
    trk.audit(batch, uni, sample=4)
    srv = start_metrics_server(port=0, stability=trk)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/stability", timeout=10) as r:
            doc = json.loads(r.read().decode())
    finally:
        srv.stop()
    assert doc["frontier"]["fleet_min"] == _vv(batch).tolist()
    assert doc["frontier"]["subtrees"] == 3
    assert doc["audit"]["violations"] == 0
    assert doc["aging"]["resolved_total"] >= 1


def test_fleet_lattice_min_join():
    from crdt_tpu.obs import fleet as obs_fleet

    def slice_with(node, max_counter, sub0):
        return {
            "ts": 1.0, "seq": 1, "counters": {},
            "gauges": {
                "stability.frontier.max_counter": [1.0, 1, max_counter],
                "stability.frontier.subtree.0.max_counter":
                    [1.0, 1, sub0],
                "stability.frontier.peers": [1.0, 1, 2],
            },
            "histograms": {}, "convergence": [1.0, 1, {}],
            "events_dropped": 0, "events": [],
        }

    snap = obs_fleet.FleetSnapshot({"n0": slice_with("n0", 7, 9),
                                    "n1": slice_with("n1", 4, 11)})
    stab = snap.fleet_stability()
    # min-join on the clock leaves; count gauges stay per-node
    assert stab["stability.frontier.max_counter"] == \
        {"min": 4.0, "nodes": 2}
    assert stab["stability.frontier.subtree.0.max_counter"]["min"] == 9.0
    assert "stability.frontier.peers" not in stab
    text = obs_fleet.fleet_prometheus_text(snap)
    assert "crdt_tpu_fleet_stability_frontier_max_counter_min 4" in text
    assert snap.to_json()["fleet"]["stability"]


def test_snapshot_persists_and_recovers_frontier(tmp_path):
    from crdt_tpu.durable import Durability, recover

    uni = _uni()
    batch = OrswotBatch.from_scalar(
        _orswot_fleet(24, seed=42, actor=2, extra_on=[0, 3]), uni)
    trk = StabilityTracker(registry=obs_metrics.MetricsRegistry())
    trk.observe_converged("peer", batch)
    rep = trk.frontier(batch, peers=["peer"])
    dur = Durability(tmp_path)
    dur.checkpoint(batch, uni, frontier=trk.frontier_clock())
    dur.close()
    rec = recover(tmp_path)
    assert rec.frontier is not None
    assert np.array_equal(
        np.asarray(rec.frontier, np.uint64).reshape(-1), rep.clock)
    # restore: the rejoined observer's frontier floors at the clock
    trk2 = StabilityTracker(registry=obs_metrics.MetricsRegistry())
    trk2.restore(rec.frontier)
    rep2 = trk2.frontier(rec.batch, peers=["peer"])
    assert _dominates(rep2.clock, rep.clock)
    assert np.array_equal(rep2.clock, rep.clock)  # nothing converged yet


def test_pre_frontier_snapshots_still_restore(tmp_path):
    """Additive payload key: a snapshot written WITHOUT a frontier
    (the pre-PR-15 shape) decodes with ``frontier=None``."""
    from crdt_tpu.durable.snapshot import SnapshotStore

    uni = _uni()
    batch = OrswotBatch.from_scalar(_orswot_fleet(8, seed=43), uni)
    store = SnapshotStore(tmp_path)
    store.write(batch, uni)
    snap = store.load_latest()
    assert snap is not None and snap.frontier is None


# ---------------------------------------------------------------------------
# satellite: roster admission seeds the convergence gauges
# ---------------------------------------------------------------------------


def test_membership_admission_seeds_silent_peer_gauges():
    reg = obs_metrics.MetricsRegistry()
    conv = obs_convergence.ConvergenceTracker(registry=reg)
    m = Membership(registry=reg, tracker=conv)
    m.add("silent")
    snap = reg.snapshot()["gauges"]
    assert snap["sync.peer.silent.staleness_s"] == float("inf")
    assert snap["sync.peer.silent.divergence"] == -1
    assert snap["sync.peer.silent.divergence_frac"] == -1
    # never-synced peers still rank first for the gossip scheduler
    assert conv.urgency("silent") == (
        float("inf"), float("inf"), float("inf"))
    # a real exchange overwrites the sentinels...
    conv.observe_divergence("silent", 3, 40)
    snap = reg.snapshot()["gauges"]
    assert snap["sync.peer.silent.divergence"] == 3
    # ...and re-admission must NOT clobber observed state back to -1
    m.add("silent")
    assert reg.snapshot()["gauges"]["sync.peer.silent.divergence"] == 3


def test_seeded_staleness_renders_as_prometheus_inf():
    from crdt_tpu.obs.export import prometheus_text

    reg = obs_metrics.MetricsRegistry()
    conv = obs_convergence.ConvergenceTracker(registry=reg)
    conv.register_peer("quiet")
    text = prometheus_text(reg, tracker=conv)
    assert "crdt_tpu_sync_peer_quiet_staleness_s +Inf" in text


# ---------------------------------------------------------------------------
# THE acceptance property: frontier soundness under faults + kill -9
# ---------------------------------------------------------------------------


def _faulty_mesh(nodes, loss=0.20, delay=0.15):
    """queue_pair gossip mesh with seeded loss + delay-reorder on every
    link, over a MUTABLE node list (a None slot refuses like a dead
    host)."""
    seeds = itertools.count(5000)

    def make_dialer(i):
        def dial(peer):
            j = int(peer.peer_id[1:])
            if nodes[j] is None:
                raise PeerUnavailableError(f"n{j} is down (killed)")
            s = next(seeds)
            ta, tb = queue_pair(default_timeout=10.0)
            fa = FaultyTransport(
                ta, FaultPlan(seed=s, drop=loss, delay=delay),
                name=f"n{i}->n{j}")
            fb = FaultyTransport(
                tb, FaultPlan(seed=s + 1, drop=loss, delay=delay),
                name=f"n{j}->n{i}")
            ra = ResilientTransport(fa, FAST, name=f"n{i}->n{j}",
                                    seed=s + 2)
            rb = ResilientTransport(fb, FAST, name=f"n{j}->n{i}",
                                    seed=s + 3)

            def serve(target=nodes[j], label=f"n{i}"):
                try:
                    target.accept(rb, peer_id=label)
                except Exception:
                    pass
                finally:
                    rb.close()

            threading.Thread(target=serve, daemon=True).start()
            return ra
        return dial

    scheds = []
    for i, node in enumerate(nodes):
        if node is None:
            scheds.append(None)
            continue
        m = Membership(suspect_after=2, dead_after=5)
        for j in range(len(nodes)):
            if j != i:
                m.add(f"n{j}")
        scheds.append(GossipScheduler(
            node, m, make_dialer(i), fanout=2,
            session_timeout_s=60.0, seed=i))
    return scheds


def test_acceptance_frontier_soundness_sweep(tmp_path):
    """ISSUE 15 acceptance: a seeded random op/merge/GC history on a
    3-node durable fleet under 20% loss + delay-reorder, with one
    kill -9 + durable rejoin — at EVERY observation point the published
    frontier clock of every live observer (a) never exceeds any live
    peer's true applied clock, and (b) is monotone non-decreasing per
    observer; the always-on lattice auditor ends with zero
    violations."""
    try:
        _frontier_soundness_sweep(tmp_path)
    finally:
        obs_convergence.tracker().reset()


def _frontier_soundness_sweep(tmp_path):
    from crdt_tpu.durable import Durability, recover
    from crdt_tpu.gc import GcEngine, GcPolicy
    from crdt_tpu.oplog import OpLog

    obs_convergence.tracker().reset()
    violations_before = tracing.counters().get(
        "stability.audit.violations", 0)
    uni = _uni(num_actors=8)
    n_nodes, n_objects = 3, 32
    base = _orswot_fleet(n_objects, seed=77)
    rng = np.random.RandomState(770)

    def make_node(i, batch, applier=None, stability=None):
        return ClusterNode(
            f"n{i}", batch, uni, busy_timeout_s=5.0,
            oplog=OpLog(uni), applier=applier,
            gc=GcEngine(GcPolicy(interval_rounds=2)),
            durability=Durability(tmp_path / f"n{i}"),
            stability_tracker=stability)

    nodes = [make_node(i, OrswotBatch.from_scalar(base, uni))
             for i in range(n_nodes)]
    scheds = _faulty_mesh(nodes)

    last_frontier = {}

    def observe_everything(tag):
        """One observation point: every live observer publishes its
        frontier; soundness + monotonicity assert against every live
        peer's TRUE applied clock."""
        live = [(i, n) for i, n in enumerate(nodes) if n is not None]
        applied = {f"n{i}": _vv(n.batch) for i, n in live}
        for i, n in live:
            roster = [f"n{j}" for j in range(n_nodes) if j != i]
            rep = n.stability.frontier(n.batch, peers=roster)
            assert rep is not None
            clock = np.asarray(rep.clock, np.uint64)
            for peer, vv in applied.items():
                assert _dominates(vv, clock), (
                    f"[{tag}] n{i}'s frontier {clock.tolist()} exceeds "
                    f"{peer}'s applied clock {vv.tolist()}"
                )
            prev = last_frontier.get(n.stability)
            if prev is not None:
                assert _dominates(clock, prev), (
                    f"[{tag}] n{i}'s frontier regressed: "
                    f"{prev.tolist()} -> {clock.tolist()}"
                )
            last_frontier[n.stability] = clock

    def inject_writes(count):
        for _ in range(count):
            i = int(rng.randint(0, n_nodes))
            if nodes[i] is None:
                continue
            objs = rng.randint(0, n_objects, rng.randint(1, 4))
            nodes[i].submit_writes(
                objs, rng.randint(200, 240, objs.size).astype(np.int32),
                actor=i + 1)

    killed_at = None
    for sweep in range(1, 9):
        if sweep == 3:
            # kill -9 between sweeps, AFTER a final checkpoint lands:
            # state a peer recorded clean-exchange evidence about is
            # then provably on n1's disk, so the restored applied clock
            # dominates every frontier claim (the between-checkpoint
            # window is the documented at-least-once caveat, exercised
            # by the durable suite, not asserted sound here).  The
            # checkpoint is non-blocking — retry past straggling
            # acceptor legs from the last round.
            for _ in range(100):
                if nodes[1].checkpoint() is not None:
                    break
                time.sleep(0.05)
            else:
                raise AssertionError("pre-kill checkpoint never ran")
            nodes[1] = None
            scheds[1] = None
            killed_at = sweep
        elif killed_at is not None and nodes[1] is None \
                and sweep == killed_at + 2:
            rec = recover(tmp_path / "n1")
            assert rec is not None
            stability = StabilityTracker()
            if rec.frontier is not None:
                stability.restore(rec.frontier)
            nodes[1] = make_node(1, rec.batch, applier=rec.applier,
                                 stability=stability)
            scheds[1] = _faulty_mesh(nodes)[1]
        if sweep <= 5:
            inject_writes(4)
        for i, sched in enumerate(scheds):
            if sched is None:
                continue
            sched.run_round()
            observe_everything(f"sweep{sweep}.n{i}")
        observe_everything(f"sweep{sweep}.end")

    # quiesce: no more writes, sweep until byte-identical digests
    for _ in range(8):
        for sched in scheds:
            if sched is not None:
                sched.run_round()
        observe_everything("quiesce")
        ds = [n.digest() for n in nodes if n is not None]
        if all(np.array_equal(ds[0], d) for d in ds[1:]):
            break
    else:
        raise AssertionError("fleet failed to converge after the sweep")

    # settled frontier == fleet VV min at quiescence (every observer
    # re-converges with every peer within a few staleness-ranked rounds)
    target = _vv(nodes[0].batch)
    for _ in range(10):
        settled = True
        for i, n in enumerate(nodes):
            roster = [f"n{j}" for j in range(n_nodes) if j != i]
            rep = n.stability.frontier(n.batch, peers=roster)
            if not np.array_equal(
                    _pad(rep.clock, target.size), target):
                settled = False
        if settled:
            break
        for sched in scheds:
            sched.run_round()
        observe_everything("settle")
    assert settled, "frontier failed to settle at the fleet VV min"

    # the always-on auditor (one pass per round per node) saw a clean
    # lattice throughout
    assert tracing.counters().get("stability.audit.violations", 0) \
        == violations_before, "lattice auditor flagged a healthy fleet"
    assert tracing.counters().get("stability.audit.checks", 0) > 0
