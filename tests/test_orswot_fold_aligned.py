"""Parity: the union-aligned fused fold vs the sequential jnp fold.

The jnp pairwise path (``orswot_ops``) is bit-exact against the scalar
engine (``tests/test_parity.py``), so equality here gives transitive
parity with the reference semantics
(`/root/reference/src/orswot.rs:89-156`).

Contract under test (module docstring of ``orswot_fold_aligned``): when
no overflow is flagged the outputs are bit-identical to the sequential
left fold + defer plunger; when the union outgrows ``u_cap`` the member
overflow flag must be set.  Fleets come from ``anti_entropy_fleets`` —
the bounded-union anti-entropy shape the fold is for — plus adversarial
deferred-heavy and degenerate cases.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from crdt_tpu.ops import orswot_fold_aligned, orswot_ops, orswot_pallas
from crdt_tpu.utils.testdata import anti_entropy_fleets


def _stack(reps):
    return tuple(jnp.stack([rep[i] for rep in reps]) for i in range(5))


def _jnp_fold(stacked, m_cap, d_cap, plunger=True):
    acc = tuple(x[0] for x in stacked)
    over = jnp.zeros(stacked[0].shape[1:-1] + (2,), bool)
    for i in range(1, stacked[0].shape[0]):
        out = orswot_ops.merge(*acc, *(x[i] for x in stacked), m_cap, d_cap)
        acc, over = out[:5], over | out[5]
    if plunger:
        out = orswot_ops.merge(*acc, *acc, m_cap, d_cap)
        acc, over = out[:5], over | out[5]
    return acc + (over,)


def _assert_same(ref, got):
    names = ("clock", "ids", "dots", "d_ids", "d_clocks", "overflow")
    for name, r, g in zip(names, ref, got):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g), err_msg=name)


def _fleet_stack(seed, n, a, m, d, r, **kw):
    rng = np.random.RandomState(seed)
    return _stack(anti_entropy_fleets(rng, n, a, m, d, r, **kw))


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize(
    "shape",
    [
        # (n, a, m, d, r, base, novel) — union base + r*novel <= m
        (33, 8, 8, 2, 4, 3, 1),
        (17, 4, 12, 2, 5, 6, 1),
        (21, 16, 6, 2, 3, 3, 1),
    ],
)
def test_fold_parity_no_deferred(seed, shape):
    n, a, m, d, r, base, novel = shape
    stacked = _fleet_stack(seed, n, a, m, d, r, base=base, novel=novel)
    ref = _jnp_fold(stacked, m, d)
    got = orswot_fold_aligned.fold_merge(*stacked, m, d, interpret=True)
    assert not np.asarray(ref[5]).any()
    _assert_same(ref, got)


@pytest.mark.parametrize("seed", [3, 4])
@pytest.mark.parametrize("deferred_frac", [0.3, 1.0])
def test_fold_parity_with_deferred(seed, deferred_frac):
    n, a, m, d, r = 29, 8, 10, 2, 4
    stacked = _fleet_stack(
        seed, n, a, m, d, r, base=4, novel=1, deferred_frac=deferred_frac
    )
    ref = _jnp_fold(stacked, m, d)
    got = orswot_fold_aligned.fold_merge(*stacked, m, d, interpret=True)
    assert not np.asarray(ref[5]).any()
    _assert_same(ref, got)


def test_fold_parity_north_star_shape():
    """The exact BASELINE.md north-star config at miniature n."""
    stacked = _fleet_stack(
        5, 64, 64, 16, 2, 8, base=6, novel=1, deferred_frac=0.25
    )
    ref = _jnp_fold(stacked, 16, 2)
    got = orswot_fold_aligned.fold_merge(*stacked, 16, 2, u_cap=16, interpret=True)
    assert not np.asarray(ref[5]).any()
    _assert_same(ref, got)


def test_fold_no_plunger():
    stacked = _fleet_stack(6, 19, 8, 8, 2, 4, base=3, novel=1, deferred_frac=0.5)
    ref = _jnp_fold(stacked, 8, 2, plunger=False)
    got = orswot_fold_aligned.fold_merge(
        *stacked, 8, 2, interpret=True, plunger=False
    )
    _assert_same(ref, got)


def test_fold_not_multiple_of_tile():
    # n deliberately prime so the object axis needs padding
    stacked = _fleet_stack(7, 13, 4, 6, 2, 3, base=3, novel=1)
    ref = _jnp_fold(stacked, 6, 2)
    got = orswot_fold_aligned.fold_merge(*stacked, 6, 2, interpret=True)
    _assert_same(ref, got)


def test_union_overflow_flagged():
    """Disjoint member sets per replica: union = r * m members > u_cap
    must set the member-overflow flag (conservative contract)."""
    from crdt_tpu.utils.testdata import random_orswot_arrays

    rng = np.random.RandomState(8)
    n, a, m, d, r = 9, 4, 4, 2, 6
    reps = []
    for rep in range(r):
        clock, ids, dots, dids, dclocks = random_orswot_arrays(
            rng, n, a, m, d, np.uint32, min_live=m
        )
        # force disjoint id spaces per replica so the union is r*m
        ids = np.where(ids != -1, ids + (rep << 25), -1).astype(np.int32)
        reps.append((clock, ids, dots, dids, dclocks))
    stacked = _stack(reps)
    got = orswot_fold_aligned.fold_merge(
        *stacked, m, d, u_cap=8, interpret=True
    )
    # union is 24 distinct ids per object > u_cap=8
    assert np.asarray(got[5])[:, 0].all()


def test_r1_fold_is_plunger_only():
    stacked = _fleet_stack(9, 11, 4, 6, 2, 1, base=3, novel=1, deferred_frac=1.0)
    ref = _jnp_fold(stacked, 6, 2)
    got = orswot_fold_aligned.fold_merge(*stacked, 6, 2, interpret=True)
    _assert_same(ref, got)


def test_prebiased_roundtrip_and_salt_commute():
    """The bench hot path: pad + bias outside, fold in the kernel domain;
    XOR clock salting commutes with the bias."""
    m, d, r = 10, 2, 4
    stacked = _fleet_stack(10, 23, 8, m, d, r, base=4, novel=1, deferred_frac=0.3)
    ref = _jnp_fold(stacked, m, d)

    padded = orswot_fold_aligned.pad_to_tile(stacked, m, d, n_states=r + 1)
    biased = orswot_pallas.to_kernel_domain(padded)
    got = orswot_fold_aligned.fold_merge(
        *biased, m, d, interpret=True, prebiased=True
    )
    n = stacked[0].shape[1]
    unb = (
        orswot_pallas.from_kernel_domain(got[0], jnp.uint32)[:n],
        got[1][:n],
        orswot_pallas.from_kernel_domain(got[2], jnp.uint32)[:n],
        got[3][:n],
        orswot_pallas.from_kernel_domain(got[4], jnp.uint32)[:n],
        got[5][:n],
    )
    _assert_same(ref, unb)

    # salt the clock planes in both domains; outputs must agree
    salt = jnp.uint32(5)
    salted_ref = orswot_fold_aligned.fold_merge(
        *((stacked[0] ^ salt,) + stacked[1:]), m, d, interpret=True
    )
    biased_salted = (biased[0] ^ jnp.int32(5),) + biased[1:]
    salted_got = orswot_fold_aligned.fold_merge(
        *biased_salted, m, d, interpret=True, prebiased=True
    )
    unb_s = (
        orswot_pallas.from_kernel_domain(salted_got[0], jnp.uint32)[:n],
        salted_got[1][:n],
        orswot_pallas.from_kernel_domain(salted_got[2], jnp.uint32)[:n],
        salted_got[3][:n],
        orswot_pallas.from_kernel_domain(salted_got[4], jnp.uint32)[:n],
        salted_got[5][:n],
    )
    _assert_same(salted_ref, unb_s)


def test_u64_counters_rejected():
    stacked = _fleet_stack(11, 5, 4, 6, 2, 2, base=3, novel=1)
    as_u64 = (stacked[0].astype(jnp.uint64), stacked[1],
              stacked[2].astype(jnp.uint64), stacked[3],
              stacked[4].astype(jnp.uint64))
    with pytest.raises(TypeError):
        orswot_fold_aligned.fold_merge(*as_u64, 6, 2, interpret=True)


def test_full_uint32_counter_range_parity():
    """Counters spanning the sign boundary of the biased domain."""
    rng = np.random.RandomState(12)
    n, a, m, d, r = 17, 4, 8, 2, 4
    reps = anti_entropy_fleets(rng, n, a, m, d, r, base=4, novel=1)
    bumped = []
    for clock, ids, dots, dids, dclocks in reps:
        hi = dots.astype(np.uint64) * np.uint64(42949672)  # spread to 2^32
        dots = np.minimum(hi, np.uint64(0xFFFF_FFFF)).astype(np.uint32)
        clock = dots.max(axis=1)
        bumped.append((clock, ids, dots, dids, dclocks))
    stacked = _stack(bumped)
    ref = _jnp_fold(stacked, m, d)
    got = orswot_fold_aligned.fold_merge(*stacked, m, d, interpret=True)
    assert not np.asarray(ref[5]).any()
    _assert_same(ref, got)


@pytest.mark.parametrize("impl", ["rank", "pallas"])
def test_ops_fold_merge_dispatch_parity(impl):
    """The first-class ``orswot_ops.fold_merge`` API: every impl choice
    produces the sequential left fold + plunger bit-exactly (the pallas
    choice dispatches the union-aligned fused kernel)."""
    stacked = _fleet_stack(20, 23, 8, 8, 2, 4, base=3, novel=1,
                           deferred_frac=0.4)
    ref = _jnp_fold(stacked, 8, 2)
    got = orswot_ops.fold_merge(*stacked, 8, 2, impl=impl)
    _assert_same(ref, got)


def test_ops_fold_merge_pallas_u64_degrades_to_sequential():
    """u64 planes are ineligible for the fused kernel: a pallas request
    must still produce the fold (via the sequential pairwise path)."""
    stacked = _fleet_stack(21, 9, 4, 6, 2, 3, base=3, novel=1)
    as_u64 = (stacked[0].astype(jnp.uint64), stacked[1],
              stacked[2].astype(jnp.uint64), stacked[3],
              stacked[4].astype(jnp.uint64))
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # the documented u64 fast-path warning
        ref = _jnp_fold(as_u64, 6, 2)
        got = orswot_ops.fold_merge(*as_u64, 6, 2, impl="pallas")
    _assert_same(ref, got)


def test_north_star_traffic_budget():
    """VERDICT r4 item 1's traffic model, pinned: <= 8 KB of HBM bytes
    per merge at the north-star shapes, computed from the kernel's
    ACTUAL padded argument/output arrays (what the pallas_call's
    BlockSpecs stream — the kernel holds the whole tile working set in
    VMEM, so arguments + outputs ARE the HBM traffic; an intermediate
    spill would surface in the AOT memory plan, which the fold_aligned_ns
    target reports).  Also pins the bench's documented
    pallas_aligned_fold bytes/merge constant against drift."""
    from benchkit.axon_bank import BYTES_PER_MERGE

    n, a, m, d, r = 512, 64, 16, 2, 8  # north-star shapes at reduced n
    stacked = _fleet_stack(30, n, a, m, d, r, base=6, novel=1)
    padded = orswot_fold_aligned.pad_to_tile(
        stacked, 16, 2, n_states=r + 1, u_cap=16
    )
    n_pad = padded[0].shape[1]
    in_bytes = sum(np.asarray(x).nbytes for x in padded)
    out = orswot_fold_aligned.fold_merge(
        *padded, 16, 2, u_cap=16, interpret=True
    )
    # overflow plane is int32 on-kernel; count the kernel-side widths
    out_bytes = sum(np.asarray(x).nbytes for x in out[:5]) + n_pad * 2 * 4
    per_merge = (in_bytes + out_bytes) / (n_pad * r)
    assert per_merge <= 8_192, per_merge
    # the bench quotes effective GB/s from this constant — keep it honest
    assert abs(per_merge - BYTES_PER_MERGE["pallas_aligned_fold"]) / \
        BYTES_PER_MERGE["pallas_aligned_fold"] < 0.02, per_merge
