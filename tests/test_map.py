"""Map tests — mirrors `/root/reference/test/map.rs` (all 13 unit/regression
tests and 9 quickcheck properties) plus the in-module suite
`/root/reference/src/map.rs:353-434`.

TestMap is the nested ``Map<u8, Map<u8, MVReg<u8, u8>, u8>, u8>``
(`test/map.rs:8`); op vectors are generated exactly as `test/map.rs:13-46`.
"""

from hypothesis import assume, given
from hypothesis import strategies as st

from crdt_tpu import Dot, Map, MVReg, VClock
from crdt_tpu.scalar.map import Nop, Rm, Up
from crdt_tpu.scalar.mvreg import Put
from crdt_tpu.utils.serde import MapOf


def new_test_map() -> Map:
    return Map(MapOf(MVReg))


def new_inner_map() -> Map:
    return Map(MVReg)


def build_opvec(prims):
    """`test/map.rs:13-46`."""
    actor, ops_data = prims
    ops = []
    for i, (choice, inner_choice, key, inner_key, val) in enumerate(ops_data):
        clock = Dot(actor, i).to_vclock()
        if choice % 3 == 0:
            if inner_choice % 3 == 0:
                inner = Up(dot=clock.inc(actor), key=inner_key, op=Put(clock=clock, val=val))
            elif inner_choice % 3 == 1:
                inner = Rm(clock=clock, key=inner_key)
            else:
                inner = Nop()
            op = Up(dot=clock.inc(actor), key=key, op=inner)
        elif choice % 3 == 1:
            op = Rm(clock=clock, key=key)
        else:
            op = Nop()
        ops.append(op)
    return actor, ops


def apply_ops(m, ops):
    for op in ops:
        m.apply(op)


op_prims = st.tuples(
    st.integers(0, 255),
    st.lists(
        st.tuples(*(st.integers(0, 255) for _ in range(5))),
        max_size=8,
    ),
)


# -- unit / regression tests -------------------------------------------------


def test_new():
    m = new_test_map()
    assert m.len().val == 0


def test_update():
    """`test/map.rs:55-106`."""
    m = new_test_map()

    # constructs a default value if the key does not exist
    ctx = m.get(101).derive_add_ctx(1)
    op = m.update(101, ctx, lambda inner, c: inner.update(110, c, lambda r, c2: r.set(2, c2)))

    assert op == Up(
        dot=Dot(1, 1),
        key=101,
        op=Up(dot=Dot(1, 1), key=110, op=Put(clock=Dot(1, 1).to_vclock(), val=2)),
    )

    assert m == new_test_map()

    m.apply(op)

    inner = m.get(101).val
    assert inner is not None
    assert inner.get(110).val.read().val == [2]

    # the map gives the latest val to the closure
    def updater(inner_map, c):
        def reg_updater(reg, c2):
            assert reg.read().val == [2]
            return reg.set(6, c2)

        return inner_map.update(110, c, reg_updater)

    op2 = m.update(101, m.get(101).derive_add_ctx(1), updater)
    m.apply(op2)

    assert m.get(101).val.get(110).val.read().val == [6]


def test_remove():
    """`test/map.rs:109-133`."""
    m = new_test_map()
    add_ctx = m.get(101).derive_add_ctx(1)
    op = m.update(101, add_ctx.clone(), lambda mm, c: mm.update(110, c, lambda r, c2: r.set(0, c2)))

    inner_map = new_inner_map()
    inner_op = inner_map.update(110, add_ctx, lambda r, c: r.set(0, c))
    inner_map.apply(inner_op)

    m.apply(op)

    read_ctx = m.get(101)
    assert read_ctx.val == inner_map
    assert m.len().val == 1
    rm_op = m.rm(101, read_ctx.derive_rm_ctx())

    m.apply(rm_op)
    assert m.get(101).val is None
    assert m.len().val == 0


def test_reset_remove_semantics():
    """`test/map.rs:136-169`."""
    m1 = new_test_map()
    op1 = m1.update(
        101,
        m1.get(101).derive_add_ctx(74),
        lambda mm, c: mm.update(110, c, lambda r, c2: r.set(32, c2)),
    )
    m1.apply(op1)

    m2 = m1.clone()

    read_ctx = m1.get(101)
    op2 = m1.rm(101, read_ctx.derive_rm_ctx())
    m1.apply(op2)

    op3 = m2.update(
        101,
        m2.get(101).derive_add_ctx(37),
        lambda mm, c: mm.update(220, c, lambda r, c2: r.set(5, c2)),
    )
    m2.apply(op3)

    m1_snapshot = m1.clone()
    m1.merge(m2)
    m2.merge(m1_snapshot)
    assert m1 == m2

    inner_map = m1.get(101).val
    assert inner_map.get(220).val.read().val == [5]
    assert inner_map.get(110).val is None
    assert inner_map.len().val == 1


def test_updating_with_current_clock_should_be_a_nop():
    """`test/map.rs:172-190`: a dot with counter 0 is already seen."""
    m1 = new_test_map()
    m1.apply(
        Up(
            dot=Dot(1, 0),
            key=0,
            op=Up(dot=Dot(1, 0), key=1, op=Put(clock=VClock(), val=235)),
        )
    )
    assert m1 == new_test_map()


def test_concurrent_update_and_remove_add_bias():
    """`test/map.rs:193-223`."""
    m1 = new_test_map()
    m2 = new_test_map()

    op1 = Rm(clock=Dot(1, 1).to_vclock(), key=102)
    op2 = m2.update(102, m2.get(102).derive_add_ctx(2), lambda _, __: Nop())

    m1.apply(op1)
    m2.apply(op2)

    m1_clone = m1.clone()
    m2_clone = m2.clone()

    m1_clone.merge(m2)
    m2_clone.merge(m1)

    assert m1_clone == m2_clone

    m1.apply(op2)
    m2.apply(op1)

    assert m1 == m2
    assert m1 == m1_clone

    # we bias towards adds
    assert m1.get(102).val is not None


def test_op_exchange_commutes_quickcheck1():
    """`test/map.rs:226-249`: needs a true causal register (MVReg)."""
    m1 = new_inner_map()
    m2 = new_inner_map()

    m1_op1 = m1.update(0, m1.get(0).derive_add_ctx(1), lambda r, c: r.set(0, c))
    m1.apply(m1_op1)

    m1_op2 = m1.rm(0, m1.get(0).derive_rm_ctx())
    m1.apply(m1_op2)

    m2_op1 = m2.update(0, m2.get(0).derive_add_ctx(2), lambda r, c: r.set(0, c))
    m2.apply(m2_op1)

    m1.apply(m2_op1)
    m2.apply(m1_op1)
    m2.apply(m1_op2)

    assert m1 == m2


def test_op_deferred_remove():
    """`test/map.rs:252-295`."""
    m1 = new_inner_map()
    m2 = m1.clone()
    m3 = m1.clone()

    m1_up1 = m1.update(0, m1.get(0).derive_add_ctx(1), lambda r, c: r.set(0, c))
    m1.apply(m1_up1)

    m1_up2 = m1.update(1, m1.get(1).derive_add_ctx(1), lambda r, c: r.set(1, c))
    m1.apply(m1_up2)

    m2.apply(m1_up1)
    m2.apply(m1_up2)

    read_ctx = m2.get(0)
    m2_rm = m2.rm(0, read_ctx.derive_rm_ctx())
    m2.apply(m2_rm)

    assert m2.get(0).val is None
    m3.apply(m2_rm)
    m3.apply(m1_up1)
    m3.apply(m1_up2)

    m1.apply(m2_rm)

    assert m2.get(0).val is None
    assert m3.get(1).val.read().val == [1]

    assert m2 == m3
    assert m1 == m2
    assert m1 == m3


def test_merge_deferred_remove():
    """`test/map.rs:298-342`."""
    m1 = new_test_map()
    m2 = new_test_map()
    m3 = new_test_map()

    m1_up1 = m1.update(
        0, m1.get(0).derive_add_ctx(1), lambda mm, c: mm.update(0, c, lambda r, c2: r.set(0, c2))
    )
    m1.apply(m1_up1)

    m1_up2 = m1.update(
        1, m1.get(1).derive_add_ctx(1), lambda mm, c: mm.update(1, c, lambda r, c2: r.set(1, c2))
    )
    m1.apply(m1_up2)

    m2.apply(m1_up1)
    m2.apply(m1_up2)

    m2_rm = m2.rm(0, m2.get(0).derive_rm_ctx())
    m2.apply(m2_rm)

    m3.merge(m2)
    m3.merge(m1)
    m1.merge(m2)

    assert m2.get(0).val is None
    assert m3.get(1).val.get(1).val.read().val == [1]

    assert m2 == m3
    assert m1 == m2
    assert m1 == m3


def test_commute_quickcheck_bug():
    """`test/map.rs:345-372`."""
    ops = [
        Rm(clock=Dot(45, 1).to_vclock(), key=0),
        Up(
            dot=Dot(45, 2),
            key=0,
            op=Up(dot=Dot(45, 1), key=0, op=Put(clock=VClock(), val=0)),
        ),
    ]
    m = new_test_map()
    apply_ops(m, ops)

    m_snapshot = m.clone()
    empty_m = new_test_map()
    m.merge(empty_m)
    empty_m.merge(m_snapshot)

    assert m == empty_m


def test_idempotent_quickcheck_bug1():
    """`test/map.rs:375-400`."""
    ops = [
        Up(dot=Dot(21, 5), key=0, op=Nop()),
        Up(
            dot=Dot(21, 6),
            key=1,
            op=Up(dot=Dot(21, 1), key=0, op=Put(clock=VClock(), val=0)),
        ),
    ]
    m = new_test_map()
    apply_ops(m, ops)

    m_snapshot = m.clone()
    m.merge(m_snapshot)
    assert m == m_snapshot


def test_idempotent_quickcheck_bug2():
    """`test/map.rs:403-422`."""
    m = new_test_map()
    m.apply(
        Up(
            dot=Dot(32, 5),
            key=0,
            op=Up(dot=Dot(32, 5), key=0, op=Put(clock=VClock(), val=0)),
        )
    )
    m_snapshot = m.clone()
    m.merge(m_snapshot)
    assert m == m_snapshot


def test_nop_on_new_map_should_remain_a_new_map():
    m = new_test_map()
    m.apply(Nop())
    assert m == new_test_map()


def test_op_exchange_same_as_merge_quickcheck1():
    """`test/map.rs:432-471`."""
    op1 = Up(dot=Dot(38, 4), key=216, op=Nop())
    op2 = Up(
        dot=Dot(91, 9),
        key=216,
        op=Up(dot=Dot(91, 1), key=37, op=Put(clock=Dot(91, 1).to_vclock(), val=94)),
    )
    m1 = new_test_map()
    m2 = new_test_map()
    m1.apply(op1)
    m2.apply(op2)

    m1_merge = m1.clone()
    m1_merge.merge(m2)

    m2_merge = m2.clone()
    m2_merge.merge(m1)

    m1.apply(op2)
    m2.apply(op1)

    assert m1 == m2
    assert m1_merge == m2_merge
    assert m1 == m1_merge
    assert m2 == m2_merge
    assert m1 == m2_merge
    assert m2 == m1_merge


def test_idempotent_quickcheck1():
    """`test/map.rs:474-510`."""
    ops = [
        Up(
            dot=Dot(62, 9),
            key=47,
            op=Up(dot=Dot(62, 1), key=65, op=Put(clock=Dot(62, 1).to_vclock(), val=240)),
        ),
        Up(
            dot=Dot(62, 11),
            key=60,
            op=Up(dot=Dot(62, 1), key=193, op=Put(clock=Dot(62, 1).to_vclock(), val=28)),
        ),
    ]
    m = new_test_map()
    apply_ops(m, ops)
    m_snapshot = m.clone()
    m.merge(m_snapshot)
    assert m == m_snapshot


# -- in-module tests (`src/map.rs:353-434`) ---------------------------------


def test_get():
    """`src/map.rs:363-378`."""
    from crdt_tpu.scalar.map import Entry

    m = new_test_map()
    assert m.get(0).val is None

    op_1 = m.clock.inc(1)
    m.clock.apply(op_1)

    m.entries[0] = Entry(clock=m.clock.clone(), val=new_inner_map())
    assert m.get(0).val == new_inner_map()


def test_op_exchange_converges_quickcheck1():
    """`src/map.rs:380-433`."""
    op_actor1 = Up(
        dot=Dot(0, 3),
        key=9,
        op=Up(dot=Dot(0, 3), key=0, op=Put(clock=Dot(0, 3).to_vclock(), val=0)),
    )
    op_1_actor2 = Up(dot=Dot(1, 1), key=9, op=Rm(clock=Dot(1, 1).to_vclock(), key=0))
    op_2_actor2 = Rm(clock=Dot(1, 2).to_vclock(), key=9)

    m1 = new_test_map()
    m2 = new_test_map()

    m1.apply(op_actor1)
    assert m1.clock == Dot(0, 3).to_vclock()
    assert m1.entries[9].clock == Dot(0, 3).to_vclock()
    assert len(m1.entries[9].val.deferred) == 0

    m2.apply(op_1_actor2)
    m2.apply(op_2_actor2)
    assert m2.clock == Dot(1, 1).to_vclock()
    assert 9 not in m2.entries
    assert m2.deferred.get(Dot(1, 2).to_vclock().key()) == {9}

    # m1 <- m2
    m1.apply(op_1_actor2)
    m1.apply(op_2_actor2)

    # m2 <- m1
    m2.apply(op_actor1)

    assert m1 == m2


# -- quickcheck properties (`test/map.rs:518-745`) ---------------------------


@given(op_prims, op_prims)
def test_prop_op_exchange_same_as_merge(p1, p2):
    a1, ops1 = build_opvec(p1)
    a2, ops2 = build_opvec(p2)
    assume(a1 != a2)

    m1, m2 = new_test_map(), new_test_map()
    apply_ops(m1, ops1)
    apply_ops(m2, ops2)

    m_merged = m1.clone()
    m_merged.merge(m2)

    apply_ops(m1, ops2)
    apply_ops(m2, ops1)

    assert m1 == m_merged
    assert m2 == m_merged


@given(op_prims, op_prims)
def test_prop_op_exchange_converges(p1, p2):
    a1, ops1 = build_opvec(p1)
    a2, ops2 = build_opvec(p2)
    assume(a1 != a2)

    m1, m2 = new_test_map(), new_test_map()
    apply_ops(m1, ops1)
    apply_ops(m2, ops2)
    apply_ops(m1, ops2)
    apply_ops(m2, ops1)
    assert m1 == m2


@given(op_prims, op_prims, op_prims)
def test_prop_op_exchange_associative(p1, p2, p3):
    a1, ops1 = build_opvec(p1)
    a2, ops2 = build_opvec(p2)
    a3, ops3 = build_opvec(p3)
    assume(a1 != a2 and a1 != a3 and a2 != a3)

    m1, m2, m3 = new_test_map(), new_test_map(), new_test_map()
    apply_ops(m1, ops1)
    apply_ops(m2, ops2)
    apply_ops(m3, ops3)

    apply_ops(m1, ops2)
    apply_ops(m1, ops3)

    apply_ops(m2, ops3)
    apply_ops(m2, ops1)

    assert m1 == m2


@given(op_prims)
def test_prop_op_idempotent(p):
    _, ops = build_opvec(p)
    m = new_test_map()
    apply_ops(m, ops)
    m_snapshot = m.clone()
    apply_ops(m, ops)
    assert m == m_snapshot


@given(op_prims, op_prims, op_prims)
def test_prop_merge_associative(p1, p2, p3):
    a1, ops1 = build_opvec(p1)
    a2, ops2 = build_opvec(p2)
    a3, ops3 = build_opvec(p3)
    assume(a1 != a2 and a1 != a3 and a2 != a3)

    m1, m2, m3 = new_test_map(), new_test_map(), new_test_map()
    apply_ops(m1, ops1)
    apply_ops(m2, ops2)
    apply_ops(m3, ops3)

    m1_snapshot = m1.clone()

    # (m1 ^ m2) ^ m3
    m1.merge(m2)
    m1.merge(m3)

    # m1 ^ (m2 ^ m3)
    m2.merge(m3)
    m1_snapshot.merge(m2)

    assert m1 == m1_snapshot


@given(op_prims, op_prims)
def test_prop_merge_commutative(p1, p2):
    a1, ops1 = build_opvec(p1)
    a2, ops2 = build_opvec(p2)
    assume(a1 != a2)

    m1, m2 = new_test_map(), new_test_map()
    apply_ops(m1, ops1)
    apply_ops(m2, ops2)

    m1_snapshot = m1.clone()
    m1.merge(m2)
    m2.merge(m1_snapshot)
    assert m1 == m2


@given(op_prims)
def test_prop_merge_idempotent(p):
    _, ops = build_opvec(p)
    m = new_test_map()
    apply_ops(m, ops)
    m_snapshot = m.clone()
    m.merge(m_snapshot)
    assert m == m_snapshot


@given(op_prims)
def test_prop_truncate_with_empty_vclock_is_nop(p):
    _, ops = build_opvec(p)
    m = new_test_map()
    apply_ops(m, ops)
    m_snapshot = m.clone()
    m.truncate(VClock())
    assert m == m_snapshot


def test_raising_nested_op_does_not_lose_entry():
    """A malformed nested op must not delete the key's accumulated state."""
    import pytest

    m = new_inner_map()
    m.apply(m.update(0, m.get(0).derive_add_ctx(1), lambda r, c: r.set(7, c)))
    snapshot_val = m.get(0).val
    with pytest.raises(TypeError):
        m.apply(Up(dot=Dot(1, 99), key=0, op="not an op"))
    assert m.get(0).val is not None
    assert m.get(0).val.read().val == snapshot_val.read().val
