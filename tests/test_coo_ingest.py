"""Columnar (COO) ingest/egress on OrswotBatch.

`from_coo` must build the same CRDT states `from_scalar` builds (slot
order may differ — canonical ascending-id vs insertion order — which is
internal representation, not state), and `from_coo(to_coo(b))` must be a
state-equivalent round trip including deferred rows.
"""

import numpy as np
import pytest

from crdt_tpu.batch import OrswotBatch
from crdt_tpu.config import CrdtConfig
from crdt_tpu.scalar.orswot import Orswot
from crdt_tpu.scalar.vclock import VClock
from crdt_tpu.utils.interning import Universe


def _universe(m=4, d=2):
    return Universe(CrdtConfig(num_actors=8, member_capacity=m,
                               deferred_capacity=d, counter_bits=32))


def _random_states(rng, n, uni):
    states = []
    for _ in range(n):
        s = Orswot()
        for _ in range(rng.randint(0, 4)):
            actor, member = int(rng.randint(0, 8)), int(rng.randint(0, 12))
            ctx = s.value().derive_add_ctx(actor)
            s.apply(s.add(member, ctx))
        if rng.rand() < 0.4 and s.entries:
            # a causally-future remove that defers
            member = next(iter(s.entries))
            future = VClock({int(rng.randint(0, 8)): int(rng.randint(50, 60))})
            s.apply_remove(member, future)
        states.append(s)
    return states


def _coo_from_scalars(states, uni):
    """Columnar coordinates as a data pipeline would produce them."""
    co, ca, cc = [], [], []
    do, dm, da, dc = [], [], [], []
    qo, qr, qm = [], [], []
    ho, hr, ha, hc = [], [], [], []
    for i, s in enumerate(states):
        for actor, counter in s.clock.dots.items():
            co.append(i); ca.append(uni.actor_idx(actor)); cc.append(counter)
        for member, vc in s.entries.items():
            for actor, counter in vc.dots.items():
                do.append(i); dm.append(uni.member_id(member))
                da.append(uni.actor_idx(actor)); dc.append(counter)
        row = 0
        for ck, members in s.deferred.items():
            for member in members:
                qo.append(i); qr.append(row); qm.append(uni.member_id(member))
                for actor, counter in ck:
                    ho.append(i); hr.append(row)
                    ha.append(uni.actor_idx(actor)); hc.append(counter)
                row += 1
    arr = lambda xs, dt: np.asarray(xs, dtype=dt)
    return (
        (arr(co, np.int64), arr(ca, np.int32), arr(cc, np.uint32)),
        (arr(do, np.int64), arr(dm, np.int32), arr(da, np.int32), arr(dc, np.uint32)),
        (arr(qo, np.int64), arr(qr, np.int32), arr(qm, np.int32)),
        (arr(ho, np.int64), arr(hr, np.int32), arr(ha, np.int32), arr(hc, np.uint32)),
    )


def test_from_coo_matches_from_scalar():
    rng = np.random.RandomState(31)
    uni = _universe()
    states = _random_states(rng, 40, uni)
    want = OrswotBatch.from_scalar(states, uni)

    clock_c, dot_c, defm, defc = _coo_from_scalars(states, uni)
    got = OrswotBatch.from_coo(
        40, uni, clock_coords=clock_c, dot_coords=dot_c,
        deferred_members=defm, deferred_coords=defc,
    )
    # states must be equal; slot order is internal (canonical ascending id
    # for from_coo vs insertion order for from_scalar), so compare as CRDTs
    assert got.to_scalar(uni) == want.to_scalar(uni)


def test_coo_roundtrip():
    rng = np.random.RandomState(37)
    uni = _universe()
    states = _random_states(rng, 25, uni)
    batch = OrswotBatch.from_scalar(states, uni)
    clock_c, dot_c, defm, defc = batch.to_coo()
    back = OrswotBatch.from_coo(
        25, uni, clock_coords=clock_c, dot_coords=dot_c,
        deferred_members=defm, deferred_coords=defc,
    )
    assert back.to_scalar(uni) == batch.to_scalar(uni)


def test_from_coo_duplicate_coords_join_by_max():
    uni = _universe()
    actor = uni.actor_idx("a2")
    member = uni.member_id("widget")
    got = OrswotBatch.from_coo(
        1, uni,
        clock_coords=(np.array([0, 0]), np.array([actor, actor]), np.array([5, 9])),
        dot_coords=(np.array([0, 0]), np.array([member, member]),
                    np.array([actor, actor]), np.array([9, 5])),
    )
    s = got.to_scalar(uni)[0]
    assert s.clock.dots == {"a2": 9}
    assert s.entries == {"widget": VClock({"a2": 9})}


def test_from_coo_member_overflow_raises():
    uni = _universe(m=2)
    with pytest.raises(ValueError, match="member_capacity"):
        OrswotBatch.from_coo(
            1, uni,
            clock_coords=(np.array([]), np.array([]), np.array([])),
            dot_coords=(np.zeros(3, np.int64), np.array([1, 2, 3]),
                        np.zeros(3, np.int32), np.ones(3, np.uint32)),
        )


def test_from_coo_rejects_half_a_deferred_pair():
    uni = _universe()
    empty3 = (np.array([]), np.array([]), np.array([]))
    empty4 = empty3 + (np.array([]),)
    with pytest.raises(ValueError, match="supplied together"):
        OrswotBatch.from_coo(
            1, uni, clock_coords=empty3, dot_coords=empty4,
            deferred_members=(np.array([0]), np.array([0]), np.array([1])),
        )


def test_from_coo_rejects_negative_member_and_row():
    uni = _universe()
    empty3 = (np.array([]), np.array([]), np.array([]))
    with pytest.raises(ValueError, match="negative member id"):
        OrswotBatch.from_coo(
            1, uni, clock_coords=empty3,
            dot_coords=(np.array([0]), np.array([-1]),
                        np.array([0]), np.array([5])),
        )
    with pytest.raises(ValueError, match="row indices"):
        OrswotBatch.from_coo(
            1, uni, clock_coords=empty3,
            dot_coords=empty3 + (np.array([]),),
            deferred_members=(np.array([0]), np.array([-1]), np.array([1])),
            deferred_coords=(np.array([0]), np.array([0]),
                             np.array([0]), np.array([5])),
        )


def test_from_coo_deferred_row_overflow_raises():
    uni = _universe(d=1)
    with pytest.raises(ValueError, match="deferred_capacity"):
        OrswotBatch.from_coo(
            1, uni,
            clock_coords=(np.array([]), np.array([]), np.array([])),
            dot_coords=(np.array([]), np.array([]), np.array([]), np.array([])),
            deferred_members=(np.array([0]), np.array([1]), np.array([4])),
            deferred_coords=(np.array([0]), np.array([1]),
                             np.array([0]), np.array([7])),
        )

def test_from_coo_rejects_negative_deferred_member():
    """A -1 (EMPTY) deferred member id would make the row invisible to
    kernels while its clock still scatters into d_clocks (advisor r2)."""
    uni = _universe()
    empty3 = (np.array([]), np.array([]), np.array([]))
    with pytest.raises(ValueError, match="negative member id.*deferred"):
        OrswotBatch.from_coo(
            1, uni, clock_coords=empty3,
            dot_coords=empty3 + (np.array([]),),
            deferred_members=(np.array([0]), np.array([0]), np.array([-1])),
            deferred_coords=(np.array([0]), np.array([0]),
                             np.array([0]), np.array([5])),
        )


def test_from_coo_rejects_conflicting_deferred_member_assignment():
    """Duplicate (obj, row) keys naming different members must raise, not
    silently last-write-win (deferred rows are assignments, not lattice
    cells — advisor r2)."""
    uni = _universe()
    empty3 = (np.array([]), np.array([]), np.array([]))
    with pytest.raises(ValueError, match="conflicting deferred_members"):
        OrswotBatch.from_coo(
            2, uni, clock_coords=empty3,
            dot_coords=empty3 + (np.array([]),),
            deferred_members=(np.array([1, 0, 1]), np.array([0, 0, 0]),
                              np.array([3, 2, 4])),
            deferred_coords=(np.array([1, 0, 1]), np.array([0, 0, 0]),
                             np.array([0, 1, 2]), np.array([5, 5, 5])),
        )
    # duplicate (obj, row) with the SAME member id is idempotent re-ingest,
    # not a conflict
    b = OrswotBatch.from_coo(
        1, uni, clock_coords=empty3,
        dot_coords=empty3 + (np.array([]),),
        deferred_members=(np.array([0, 0]), np.array([0, 0]),
                          np.array([3, 3])),
        deferred_coords=(np.array([0, 0]), np.array([0, 0]),
                         np.array([0, 0]), np.array([5, 9])),
    )
    assert int(np.asarray(b.d_ids)[0, 0]) == 3
    assert int(np.asarray(b.d_clocks)[0, 0, 0]) == 9


class TestDeviceCellPaths:
    """The jitted compaction/expansion paths (`via_device=True`) exist so
    only compact columns cross the host<->device boundary on accelerator
    backends (the axon tunnel moves dense planes at ~10 MB/s).  Under
    the CPU test backend they run the same jitted kernels and must be
    bit-identical to the host numpy paths."""

    def _planes(self, b):
        return (b.clock, b.ids, b.dots, b.d_ids, b.d_clocks)

    def test_from_scalar_device_expand_matches_host(self):
        rng = np.random.RandomState(7)
        uni = _universe()
        states = _random_states(rng, 40, uni)
        host = OrswotBatch.from_scalar(states, uni, via_device=False)
        dev = OrswotBatch.from_scalar(states, uni, via_device=True)
        for h, d in zip(self._planes(host), self._planes(dev)):
            assert np.array_equal(np.asarray(h), np.asarray(d))

    def test_from_coo_device_expand_matches_host_with_duplicates(self):
        uni = _universe()
        actor = uni.actor_idx("a2")
        member = uni.member_id("widget")
        kw = dict(
            clock_coords=(np.array([0, 0]), np.array([actor, actor]),
                          np.array([5, 9])),
            dot_coords=(np.array([0, 0]), np.array([member, member]),
                        np.array([actor, actor]), np.array([9, 5])),
        )
        host = OrswotBatch.from_coo(1, uni, via_device=False, **kw)
        dev = OrswotBatch.from_coo(1, uni, via_device=True, **kw)
        for h, d in zip(self._planes(host), self._planes(dev)):
            assert np.array_equal(np.asarray(h), np.asarray(d))

    def test_to_scalar_device_compact_matches_host(self):
        rng = np.random.RandomState(11)
        uni = _universe()
        states = _random_states(rng, 40, uni)
        batch = OrswotBatch.from_scalar(states, uni)
        assert batch.to_scalar(uni, via_device=True) == batch.to_scalar(
            uni, via_device=False
        )

    def test_to_coo_device_compact_matches_host(self):
        rng = np.random.RandomState(13)
        uni = _universe()
        states = _random_states(rng, 30, uni)
        batch = OrswotBatch.from_scalar(states, uni)
        for host_cols, dev_cols in zip(
            batch.to_coo(via_device=False), batch.to_coo(via_device=True)
        ):
            for h, d in zip(host_cols, dev_cols):
                assert np.array_equal(np.asarray(h), np.asarray(d))

    def test_empty_batch_device_paths(self):
        uni = _universe()
        batch = OrswotBatch.zeros(3, uni)
        assert batch.to_scalar(uni, via_device=True) == [
            Orswot(), Orswot(), Orswot()
        ]
        for cols in batch.to_coo(via_device=True):
            for c in cols:
                assert np.asarray(c).shape[0] == 0

    def test_from_coo_device_accepts_lists_and_empty_columns(self):
        # np.asarray([]) is float64; the device path must still index
        # planes with integer coordinates (code-review regression)
        uni = _universe()
        b = OrswotBatch.from_coo(
            2, uni, clock_coords=([], [], []), dot_coords=([], [], [], []),
            via_device=True,
        )
        assert b.to_scalar(uni) == [Orswot(), Orswot()]
        actor = uni.actor_idx("a1")
        member = uni.member_id("w")
        b2 = OrswotBatch.from_coo(
            2, uni,
            clock_coords=([0], [actor], [3]),
            dot_coords=([0], [member], [actor], [3]),
            via_device=True,
        )
        s = b2.to_scalar(uni)[0]
        assert s.entries == {"w": VClock({"a1": 3})}


def test_to_scalar_sliced_path_matches_monolithic(monkeypatch):
    """The host-path egress slicing (perf: superlinear per-call cost)
    must be invisible: sliced output == monolithic output, including a
    non-multiple tail slice and deferred rows."""
    import numpy as np

    from crdt_tpu.batch import orswot_batch as ob
    from crdt_tpu.batch.orswot_batch import OrswotBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.scalar.orswot import Orswot
    from crdt_tpu.scalar.vclock import VClock
    from crdt_tpu.utils.interning import Universe

    rng = np.random.RandomState(9)
    states = []
    for i in range(23):
        s = Orswot()
        actor = int(rng.randint(0, 4))
        s.clock = VClock({actor: int(rng.randint(1, 9))})
        s.entries[int(rng.randint(0, 50))] = s.clock.clone()
        if i % 5 == 0:  # causally-future deferred remove
            s.deferred[VClock({actor: 99}).key()] = {int(rng.randint(0, 50))}
        states.append(s)

    uni = Universe(CrdtConfig(num_actors=4, member_capacity=4, deferred_capacity=2))
    batch = OrswotBatch.from_scalar(states, uni)

    # via_device pinned False so the sliced HOST path runs even when the
    # ambient backend is an accelerator (auto-detect would skip it)
    monolithic = batch.to_scalar(uni, via_device=False)
    monkeypatch.setattr(ob, "_EGRESS_SLICE", 4)  # force slicing + tail merge
    sliced = batch.to_scalar(uni, via_device=False)
    assert sliced == monolithic == states
    # 23 = 5 full slices of 4 + remainder 3 > slice/2=2 → own slice; also
    # cover the merge-into-previous case
    monkeypatch.setattr(ob, "_EGRESS_SLICE", 10)  # 23 → 10 + 13 (merged tail)
    assert batch.to_scalar(uni, via_device=False) == states
