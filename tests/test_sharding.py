"""Multi-device sharding tests on the virtual 8-device CPU mesh.

Validates the collective-join layer (SURVEY.md §2.3, §5): the all-reduce-max
clock join, the ORSWOT all-gather + canonical-fold join with merge as the
combiner, and anti-entropy-to-fixpoint — all against scalar N-way merges.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import assert_no_collectives

from crdt_tpu import Dot, Orswot, VClock
from crdt_tpu.batch import OrswotBatch, VClockBatch
from crdt_tpu.config import CrdtConfig
from crdt_tpu.parallel import (
    all_reduce_clock_join,
    allgather_join_orswot,
    anti_entropy,
    make_mesh,
    replicate,
    shard_batch,
    tree_reduce_merge,
)
from crdt_tpu.scalar.orswot import Add, Rm
from crdt_tpu.utils.interning import Universe

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device CPU mesh (see conftest)"
)


def small_universe():
    return Universe(CrdtConfig(num_actors=8, member_capacity=16, deferred_capacity=8))


def random_orswots(seed, n_replicas, n_objects):
    """n_replicas × n_objects scalar Orswots with random op histories."""
    rng = np.random.RandomState(seed)
    fleet = []
    for r in range(n_replicas):
        row = []
        for i in range(n_objects):
            s = Orswot()
            for _ in range(rng.randint(0, 8)):
                actor = int(rng.randint(0, 8))
                member = int(rng.randint(0, 8))
                counter = int(rng.randint(1, 6))
                if rng.rand() < 0.7:
                    s.apply(Add(dot=Dot(actor, counter), member=member))
                else:
                    s.apply(Rm(clock=Dot(actor, counter).to_vclock(), member=member))
            row.append(s)
        fleet.append(row)
    return fleet


def scalar_global_join(fleet):
    """Reference N-way join with defer plunger (`test/orswot.rs:53-62`)."""
    n_objects = len(fleet[0])
    out = []
    for i in range(n_objects):
        merged = Orswot()
        for row in fleet:
            merged.merge(row[i])
        merged.merge(Orswot())
        out.append(merged)
    return out


def test_all_reduce_clock_join():
    """8 replica shards of clocks join to the pointwise max everywhere."""
    mesh = make_mesh({"replicas": 8})
    uni = small_universe()
    rng = np.random.RandomState(0)
    n_objects = 16
    replicas = []
    for _ in range(8):
        replicas.append(
            [VClock.from_iter([(int(a), int(rng.randint(1, 9))) for a in rng.choice(8, 3)])
             for _ in range(n_objects)]
        )
    stacks = jnp.stack(
        [VClockBatch.from_scalar(r, uni).clocks for r in replicas]
    )  # [8, N, A]

    joined = all_reduce_clock_join(stacks, mesh, axis="replicas")
    expected = jnp.max(stacks, axis=0)
    # every replica shard holds the global join
    for r in range(8):
        np.testing.assert_array_equal(np.asarray(joined[r]), np.asarray(expected))


def test_allgather_join_orswot_matches_scalar():
    """All-gather + canonical fold with ORSWOT merge combiner == scalar
    N-way merge."""
    mesh = make_mesh({"replicas": 8})
    uni = small_universe()
    fleet = random_orswots(seed=3, n_replicas=8, n_objects=6)

    batches = [OrswotBatch.from_scalar(row, uni) for row in fleet]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *batches)

    joined = allgather_join_orswot(stacked, mesh, axis="replicas")

    # the join must be fully reduced on every device; flush deferred with
    # one plunger merge, then compare against the scalar N-way join
    expected = scalar_global_join(fleet)
    for r in range(8):
        shard = OrswotBatch(
            clock=joined.clock[r], ids=joined.ids[r], dots=joined.dots[r],
            d_ids=joined.d_ids[r], d_clocks=joined.d_clocks[r],
        )
        plunged = shard.merge(OrswotBatch.zeros(6, uni))
        got = plunged.to_scalar(uni)
        assert got == expected, f"replica shard {r} diverged"


@pytest.mark.parametrize("impl", ["unrolled", "pallas"])
def test_allgather_join_orswot_merge_impl_variants(impl):
    """The merge-impl variants (unrolled — the TPU default — and the
    fused pallas kernel, interpret-emulated on the CPU mesh) compose
    with the collective join: the combiner inside the all-gather fold
    routes through orswot_ops.merge via the explicit ``impl=`` argument
    (a static jit arg, so each impl compiles its own entry — no env vars
    or cache clearing), and must behave identically under shard_map's
    per-shard (rank-2) views.  u32 counters — the variants' supported
    width."""
    mesh = make_mesh({"replicas": 8})
    uni = Universe(CrdtConfig(num_actors=8, member_capacity=16,
                              deferred_capacity=8, counter_bits=32))
    fleet = random_orswots(seed=5, n_replicas=8, n_objects=6)

    batches = [OrswotBatch.from_scalar(row, uni) for row in fleet]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *batches)
    joined = allgather_join_orswot(stacked, mesh, axis="replicas", impl=impl)

    expected = scalar_global_join(fleet)
    shard = OrswotBatch(
        clock=joined.clock[0], ids=joined.ids[0], dots=joined.dots[0],
        d_ids=joined.d_ids[0], d_clocks=joined.d_clocks[0],
    )
    plunged = shard.merge(OrswotBatch.zeros(6, uni))
    assert plunged.to_scalar(uni) == expected


def test_allgather_join_map_matches_scalar():
    """Map collective join (`map.rs:192-269` combiner incl. nested value
    merge + reset-remove) == scalar N-way left fold, on every device."""
    import random as pyrandom

    from crdt_tpu import Dot, Map, MVReg, VClock
    from crdt_tpu.batch import MapBatch, MVRegKernel
    from crdt_tpu.parallel.collective import allgather_join_map
    from crdt_tpu.scalar.map import Rm as MapRm, Up
    from crdt_tpu.scalar.mvreg import Put

    mesh = make_mesh({"replicas": 8})
    uni = small_universe()
    rng = pyrandom.Random(23)
    n_objects = 4

    def random_map():
        m = Map(MVReg)
        for _ in range(rng.randrange(0, 8)):
            actor = rng.randrange(0, 8)
            counter = rng.randrange(1, 6)
            key = rng.randrange(0, 5)
            clock = VClock.from_iter([(actor, counter)])
            if rng.random() < 0.25:
                m.apply(MapRm(clock=clock, key=key))
            else:
                m.apply(
                    Up(dot=Dot(actor, counter), key=key,
                       op=Put(clock=clock, val=rng.randrange(0, 9)))
                )
        return m

    fleet = [[random_map() for _ in range(n_objects)] for _ in range(8)]
    val_kernel = MVRegKernel.from_config(uni.config)
    batches = [MapBatch.from_scalar(row, uni, val_kernel) for row in fleet]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *batches)

    joined = allgather_join_map(stacked, mesh, axis="replicas")

    expected = []
    for i in range(n_objects):
        acc = fleet[0][i].clone()
        for r in range(1, 8):
            acc.merge(fleet[r][i])
        expected.append(acc)

    for r in range(8):
        shard_state = jax.tree_util.tree_map(lambda x: x[r], joined.state)
        shard = MapBatch.from_state(shard_state, joined.kernel)
        got = shard.to_scalar(uni)
        assert got == expected, f"replica shard {r} diverged"


def test_anti_entropy_fixpoint_matches_scalar():
    uni = small_universe()
    fleet = random_orswots(seed=11, n_replicas=5, n_objects=8)
    batches = [OrswotBatch.from_scalar(row, uni) for row in fleet]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *batches)

    merged, rounds = anti_entropy(stacked)
    assert rounds <= 3
    got = merged.to_scalar(uni)
    expected = scalar_global_join(fleet)
    assert got == expected


def test_fold_reduce_matches_sequential():
    from crdt_tpu.parallel import fold_reduce_merge

    uni = small_universe()
    fleet = random_orswots(seed=5, n_replicas=7, n_objects=4)
    batches = [OrswotBatch.from_scalar(row, uni) for row in fleet]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *batches)

    def pair(a, b):
        return a.merge(b, check=False)

    merged = fold_reduce_merge(stacked, pair)
    # left fold == explicit sequential merge, bit for bit
    seq = batches[0]
    for b in batches[1:]:
        seq = seq.merge(b, check=False)
    for x, y in zip(jax.tree_util.tree_leaves(merged), jax.tree_util.tree_leaves(seq)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_tree_reduce_matches_fold_for_commutative_merge():
    """tree_reduce_merge == fold_reduce_merge for clock-shaped (truly
    commutative) joins, including odd replica counts (the halving carry)."""
    from crdt_tpu.parallel import fold_reduce_merge

    uni = small_universe()
    rng = np.random.RandomState(17)
    for n_replicas in (2, 5, 8):  # even, odd (carry path), power of two
        stacks = jnp.stack(
            [
                VClockBatch.from_scalar(
                    [
                        VClock.from_iter(
                            [(int(a), int(rng.randint(1, 9))) for a in rng.choice(8, 3)]
                        )
                        for _ in range(6)
                    ],
                    uni,
                ).clocks
                for _ in range(n_replicas)
            ]
        )  # [R, N, A]
        tree = tree_reduce_merge(stacks, jnp.maximum)
        fold = fold_reduce_merge(stacks, jnp.maximum)
        np.testing.assert_array_equal(np.asarray(tree), np.asarray(fold))
        np.testing.assert_array_equal(
            np.asarray(tree), np.asarray(jnp.max(stacks, axis=0))
        )


def test_replicate_places_full_copy_everywhere():
    mesh = make_mesh({"objects": 8})
    uni = small_universe()
    fleet = random_orswots(seed=21, n_replicas=1, n_objects=4)
    batch = OrswotBatch.from_scalar(fleet[0], uni)
    rep = replicate(batch, mesh)
    # fully-replicated sharding: every leaf is addressable whole on each device
    for leaf in jax.tree_util.tree_leaves(rep):
        assert leaf.sharding.is_fully_replicated
    np.testing.assert_array_equal(np.asarray(rep.clock), np.asarray(batch.clock))


def test_sharded_pairwise_merge_no_collectives():
    """Object-axis sharding: pairwise merge of two sharded batches matches
    the unsharded result, and the shard_map-based merge compiles with zero
    cross-device traffic (objects are independent)."""
    mesh = make_mesh({"objects": 8})
    uni = small_universe()
    fleet = random_orswots(seed=9, n_replicas=2, n_objects=32)
    a = OrswotBatch.from_scalar(fleet[0], uni)
    b = OrswotBatch.from_scalar(fleet[1], uni)
    expected = a.merge(b).to_scalar(uni)

    a_sharded = shard_batch(a, mesh, "objects")
    b_sharded = shard_batch(b, mesh, "objects")
    # plain jit path: correct under sharding (the partitioner may insert a
    # scalar-sized collective for the deferred-dispatch predicate)
    got = a_sharded.merge(b_sharded).to_scalar(uni)
    assert got == expected

    # the headline zero-traffic claim lives in the shard_map path, where
    # the deferred/deferred-free dispatch is also decided per shard
    from crdt_tpu.parallel.collective import shard_local_pairwise_merge

    state5, overflow = shard_local_pairwise_merge(a_sharded, b_sharded, mesh, "objects")
    got_local = OrswotBatch(*state5).to_scalar(uni)
    assert got_local == expected
    assert not bool(np.asarray(overflow).any())

    m_cap, d_cap = a.ids.shape[-1], a.d_ids.shape[-1]
    from crdt_tpu.parallel.collective import shard_local_merge_fn

    compiled = shard_local_merge_fn(mesh, "objects", m_cap, d_cap).lower(
        tuple(jax.tree_util.tree_leaves(a_sharded)),
        tuple(jax.tree_util.tree_leaves(b_sharded)),
    ).compile()
    hlo = compiled.as_text()
    assert_no_collectives(hlo, "shard-local merge")


# -- LWWReg / MVReg / GSet collective joins ----------------------------------


def test_allgather_join_lww_matches_scalar():
    """Marker-argmax collective join (`lwwreg.rs:43-67`) == scalar N-way
    left fold, on every device (BASELINE config 5's join path)."""
    from crdt_tpu.batch import LWWRegBatch
    from crdt_tpu.parallel import allgather_join_lww
    from crdt_tpu.scalar.lwwreg import LWWReg

    mesh = make_mesh({"replicas": 8})
    uni = small_universe()
    rng = np.random.RandomState(11)
    n = 24
    # distinct markers per (replica, object) => no conflicts; value is a
    # function of the marker so ties (none here) would agree anyway
    markers = rng.permutation(8 * n).reshape(8, n) + 1
    fleet = [
        [LWWReg(val=int(markers[r, i]) * 7, marker=int(markers[r, i]))
         for i in range(n)]
        for r in range(8)
    ]

    batches = [LWWRegBatch.from_scalar(row, uni) for row in fleet]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *batches)
    joined, conflict = allgather_join_lww(stacked, mesh, axis="replicas")
    assert not bool(jnp.any(conflict))

    expected = []
    for i in range(n):
        acc = fleet[0][i].clone()
        for r in range(1, 8):
            acc.merge(fleet[r][i])
        expected.append(acc)
    for r in range(8):
        shard = LWWRegBatch(vals=joined.vals[r], markers=joined.markers[r])
        assert shard.to_scalar(uni) == expected, f"replica shard {r} diverged"


def test_allgather_join_lww_conflict_surfaces():
    """An equal-marker/different-value pair anywhere in the fold raises
    host-side and the bitmap pinpoints the register — including the
    intermediate-max case where the global max marker is unique but two
    earlier replicas collide (`lwwreg.rs:56-66` pairwise semantics)."""
    from crdt_tpu.batch import LWWRegBatch
    from crdt_tpu.error import ConflictingMarker
    from crdt_tpu.parallel import allgather_join_lww
    from crdt_tpu.scalar.lwwreg import LWWReg

    mesh = make_mesh({"replicas": 8})
    uni = small_universe()
    n = 4
    fleet = [[LWWReg(val=100 + r, marker=1 + r) for _ in range(n)] for r in range(8)]
    # register 2: replicas 3 and 4 share marker 50 with different values,
    # replica 7 holds the unique global max 99
    fleet[3][2] = LWWReg(val=111, marker=50)
    fleet[4][2] = LWWReg(val=222, marker=50)
    fleet[7][2] = LWWReg(val=333, marker=99)

    batches = [LWWRegBatch.from_scalar(row, uni) for row in fleet]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *batches)
    with pytest.raises(ConflictingMarker):
        allgather_join_lww(stacked, mesh, axis="replicas")

    joined, conflict = allgather_join_lww(stacked, mesh, axis="replicas", check=False)
    bitmap = np.asarray(conflict[0])
    assert bitmap.tolist() == [False, False, True, False]
    # scalar fold agrees that the walk conflicts at register 2
    acc = fleet[0][2].clone()
    with pytest.raises(ConflictingMarker):
        for r in range(1, 8):
            acc.merge(fleet[r][2])


def test_allgather_join_mvreg_matches_scalar():
    """Antichain gather-fold join (`mvreg.rs:121-153`) == scalar N-way left
    fold on every device; concurrent values from different replicas all
    survive, dominated ones collapse."""
    from crdt_tpu.batch import MVRegBatch
    from crdt_tpu.parallel import allgather_join_mvreg
    from crdt_tpu.scalar.mvreg import MVReg

    mesh = make_mesh({"replicas": 8})
    uni = small_universe()
    rng = np.random.RandomState(13)
    n = 6
    fleet = []
    for r in range(8):
        row = []
        for i in range(n):
            reg = MVReg()
            for _ in range(rng.randint(0, 3)):
                actor = int(rng.randint(0, 8))
                ctx = reg.read().derive_add_ctx(actor)
                reg.apply(reg.set(int(rng.randint(0, 50)), ctx))
            row.append(reg)
        fleet.append(row)

    batches = [MVRegBatch.from_scalar(row, uni) for row in fleet]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *batches)
    joined = allgather_join_mvreg(stacked, mesh, axis="replicas")

    expected = []
    for i in range(n):
        acc = fleet[0][i].clone()
        for r in range(1, 8):
            acc.merge(fleet[r][i])
        expected.append(acc)
    for r in range(8):
        shard = MVRegBatch(clocks=joined.clocks[r], vals=joined.vals[r])
        got = shard.to_scalar(uni)
        # MVReg equality is set-equality over (clock, val) pairs
        # (`mvreg.rs:74-96`)
        assert got == expected, f"replica shard {r} diverged"


def test_allgather_join_gset_matches_scalar():
    """Bitmap-OR all-reduce == scalar N-way union (`gset.rs:30-34`)."""
    from crdt_tpu.batch import GSetBatch
    from crdt_tpu.parallel import allgather_join_gset
    from crdt_tpu.scalar.gset import GSet

    mesh = make_mesh({"replicas": 8})
    uni = small_universe()
    rng = np.random.RandomState(17)
    n, cap = 10, 16
    fleet = [
        [GSet({int(m) for m in rng.choice(12, rng.randint(0, 6), replace=False)})
         for _ in range(n)]
        for _ in range(8)
    ]

    batches = [GSetBatch.from_scalar(row, uni, cap) for row in fleet]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *batches)
    joined = allgather_join_gset(stacked, mesh, axis="replicas")

    expected = []
    for i in range(n):
        acc = fleet[0][i].clone()
        for r in range(1, 8):
            acc.merge(fleet[r][i])
        expected.append(acc)
    for r in range(8):
        shard = GSetBatch(bits=joined.bits[r])
        assert shard.to_scalar(uni) == expected, f"replica shard {r} diverged"


@pytest.mark.parametrize("seed", [29, 31])
def test_allgather_join_lww_random_histories(seed):
    """Randomized LWW fleets (distinct markers): collective join == scalar
    N-way fold on every replica row."""
    from crdt_tpu.batch import LWWRegBatch
    from crdt_tpu.parallel import allgather_join_lww
    from crdt_tpu.scalar.lwwreg import LWWReg

    mesh = make_mesh({"replicas": 8})
    uni = small_universe()
    rng = np.random.RandomState(seed)
    n = 10
    markers = rng.permutation(8 * n).reshape(8, n) + 1
    fleet = []
    for r in range(8):
        row = []
        for i in range(n):
            reg = LWWReg()
            m = int(markers[r, i])
            # the write plus an idempotent redelivery (equal marker, same
            # value — a no-op, not a conflict); markers are a global
            # permutation so there are no cross-replica ties
            reg.update(val=m * 13, marker=m)
            reg.update(val=m * 13, marker=m)
            row.append(reg)
        fleet.append(row)
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs),
        *[LWWRegBatch.from_scalar(row, uni) for row in fleet],
    )
    joined, conflict = allgather_join_lww(stacked, mesh)
    assert not bool(jnp.any(conflict))
    expected = []
    for i in range(n):
        acc = fleet[0][i].clone()
        for r in range(1, 8):
            acc.merge(fleet[r][i])
        expected.append(acc)
    for r in range(8):
        got = LWWRegBatch(vals=joined.vals[r], markers=joined.markers[r]).to_scalar(uni)
        assert got == expected, f"replica shard {r} diverged (seed {seed})"


@pytest.mark.parametrize("seed", [37, 41])
def test_allgather_join_mvreg_random_histories(seed):
    """Randomized MVReg op histories incl. dominating overwrites: the
    collective join keeps exactly the mutually-undominated values the
    scalar N-way fold keeps."""
    from crdt_tpu.batch import MVRegBatch
    from crdt_tpu.parallel import allgather_join_mvreg
    from crdt_tpu.scalar.mvreg import MVReg

    mesh = make_mesh({"replicas": 8})
    uni = small_universe()
    rng = np.random.RandomState(seed)
    n = 6
    fleet = []
    for r in range(8):
        row = []
        for i in range(n):
            reg = MVReg()
            for _ in range(rng.randint(0, 4)):
                actor = int(rng.randint(0, 8))
                ctx = reg.read().derive_add_ctx(actor)
                reg.apply(reg.set(int(rng.randint(0, 40)), ctx))
            row.append(reg)
        fleet.append(row)
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs),
        *[MVRegBatch.from_scalar(row, uni) for row in fleet],
    )
    joined = allgather_join_mvreg(stacked, mesh)
    expected = []
    for i in range(n):
        acc = fleet[0][i].clone()
        for r in range(1, 8):
            acc.merge(fleet[r][i])
        expected.append(acc)
    for r in range(8):
        got = MVRegBatch(clocks=joined.clocks[r], vals=joined.vals[r]).to_scalar(uni)
        assert got == expected, f"replica shard {r} diverged (seed {seed})"


def test_sharded_truncate_matches_unsharded():
    """Causal::truncate is elementwise over the object axis: on a sharded
    fleet it must match the unsharded result and, under ``shard_map``,
    compile with zero cross-device traffic (`orswot.rs:159-172`)."""
    from functools import partial

    from jax.sharding import PartitionSpec as P
    from crdt_tpu.parallel._compat import shard_map

    from crdt_tpu.batch.orswot_batch import _truncate

    mesh = make_mesh({"objects": 8})
    uni = small_universe()
    fleet = random_orswots(seed=21, n_replicas=1, n_objects=32)[0]
    batch = OrswotBatch.from_scalar(fleet, uni)

    # truncate each object by its own clock's GLB with a fixed horizon
    rng = np.random.RandomState(3)
    horizon = jnp.asarray(
        rng.randint(0, 4, size=batch.clock.shape), dtype=batch.clock.dtype
    )
    expected = batch.truncate(horizon).to_scalar(uni)

    sharded = shard_batch(batch, mesh, "objects")
    got = sharded.truncate(horizon).to_scalar(uni)
    assert got == expected

    m_cap, d_cap = batch.ids.shape[-1], batch.d_ids.shape[-1]
    spec = P("objects")
    fn = shard_map(
        partial(_truncate, m_cap=m_cap, d_cap=d_cap),
        mesh=mesh,
        in_specs=(spec,) * 6,
        out_specs=((spec,) * 5, spec),
        check_vma=False,
    )
    args = (sharded.clock, sharded.ids, sharded.dots,
            sharded.d_ids, sharded.d_clocks, horizon)
    (state5, overflow) = fn(*args)
    got_local = OrswotBatch(*state5).to_scalar(uni)
    assert got_local == expected

    hlo = jax.jit(fn).lower(*args).compile().as_text()
    assert_no_collectives(hlo, "shard-local truncate")
