"""Checkpoint / resume tests (SURVEY.md §5).

State-based CRDTs: the state is the checkpoint, resume = merge
(`/root/reference/src/lib.rs:62-83`, `traits.rs:36`).  A batch checkpoint
must restore bit-exact SoA buffers and an equivalent interning universe, and
a resumed-then-merged state must equal merging the originals.
"""

import io

import numpy as np

from crdt_tpu import Orswot
from crdt_tpu.batch import LWWRegBatch, OrswotBatch
from crdt_tpu.config import CrdtConfig
from crdt_tpu.utils import checkpoint
from crdt_tpu.utils.interning import Universe


def _orswot_fixture(n_actors=4):
    universe = Universe(CrdtConfig(num_actors=n_actors, member_capacity=8,
                                   deferred_capacity=4))
    states = []
    for i in range(6):
        s = Orswot()
        for k in range(i % 3 + 1):
            member = f"m{k}"
            op = s.add(member, s.value().derive_add_ctx(f"actor{(i + k) % n_actors}"))
            s.apply(op)
        states.append(s)
    return OrswotBatch.from_scalar(states, universe), universe, states


def _assert_batch_equal(a, b):
    import dataclasses

    for f in dataclasses.fields(a):
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f.name)), np.asarray(getattr(b, f.name)), err_msg=f.name
        )


def test_orswot_batch_roundtrip(tmp_path):
    batch, universe, _ = _orswot_fixture()
    path = tmp_path / "ck.npz"
    checkpoint.save(path, batch, universe)
    loaded, uni2 = checkpoint.load(path)
    assert type(loaded) is OrswotBatch
    _assert_batch_equal(batch, loaded)
    assert uni2.actors.values() == universe.actors.values()
    assert uni2.members.values() == universe.members.values()
    assert uni2.config == universe.config


def test_roundtrip_bytes_and_resume_merge():
    batch, universe, states = _orswot_fixture()
    blob = checkpoint.save_bytes(batch, universe)
    loaded, uni2 = checkpoint.load_bytes(blob)

    # resume = merge: merging the restored batch into a diverged batch gives
    # the same result as merging the original
    other = OrswotBatch.from_scalar(
        [s.clone() for s in states[::-1]], universe
    )
    merged_orig = other.merge(batch)
    merged_restored = other.merge(loaded)
    _assert_batch_equal(merged_orig, merged_restored)

    # and scalar parity survives the round-trip
    assert [s.value().val for s in loaded.to_scalar(uni2)] == [
        s.value().val for s in states
    ]


def test_lwwreg_batch_roundtrip(tmp_path):
    from crdt_tpu import LWWReg

    universe = Universe()
    regs = [LWWReg(val=i * 10, marker=i + 1) for i in range(5)]
    batch = LWWRegBatch.from_scalar(regs, universe)
    path = tmp_path / "lww.npz"
    checkpoint.save(path, batch, universe)
    loaded, _ = checkpoint.load(path)
    assert type(loaded) is LWWRegBatch
    _assert_batch_equal(batch, loaded)


def test_extensionless_path_roundtrips(tmp_path):
    """np.savez silently appends .npz; save/load must stay symmetric."""
    batch, universe, _ = _orswot_fixture()
    path = tmp_path / "ck"  # no extension
    checkpoint.save(path, batch, universe)
    loaded, _ = checkpoint.load(path)
    _assert_batch_equal(batch, loaded)


def test_rejects_unknown_type():
    universe = Universe()
    try:
        checkpoint.save(io.BytesIO(), object(), universe)
    except TypeError as e:
        assert "checkpointable" in str(e)
    else:
        raise AssertionError("expected TypeError")


def test_rejects_value_kernels():
    """Value-kernel helpers are exported from ``batch`` but are config, not
    state — save() must not treat them as checkpointable batch types."""
    from crdt_tpu.batch import MVRegKernel

    universe = Universe()
    try:
        checkpoint.save(
            io.BytesIO(), MVRegKernel.from_config(universe.config), universe
        )
    except TypeError as e:
        assert "checkpointable" in str(e)
    else:
        raise AssertionError("expected TypeError")


def test_container_is_plain_npz(tmp_path):
    """The container must be readable by plain numpy (no pickle)."""
    batch, universe, _ = _orswot_fixture()
    path = tmp_path / "ck.npz"
    checkpoint.save(path, batch, universe)
    with np.load(path, allow_pickle=False) as z:
        assert "clock" in z.files and "__meta__" in z.files


def test_map_batch_roundtrip(tmp_path):
    """MapBatch: nested vals pytree + static kernel survive the checkpoint
    (leaves stored under path-encoded names, kernel as a metadata spec)."""
    from crdt_tpu import Dot, Map, MVReg, VClock
    from crdt_tpu.batch import MapBatch, MVRegKernel
    from crdt_tpu.batch.val_kernels import MapKernel
    from crdt_tpu.scalar.map import Up
    from crdt_tpu.scalar.mvreg import Put

    universe = Universe(
        CrdtConfig(num_actors=4, member_capacity=8, deferred_capacity=4,
                   mv_capacity=4, key_capacity=4)
    )
    maps = []
    for i in range(3):
        m = Map(MVReg)
        for j in range(i + 1):
            clock = VClock.from_iter([(f"a{j}", j + 1)])
            m.apply(Up(dot=Dot(f"a{j}", j + 1), key=j,
                       op=Put(clock=clock, val=i * 10 + j)))
        maps.append(m)
    mv = MVRegKernel.from_config(universe.config)
    batch = MapBatch.from_scalar(maps, universe, mv)
    path = tmp_path / "mapck"
    checkpoint.save(path, batch, universe)
    loaded, uni2 = checkpoint.load(path)
    assert type(loaded) is MapBatch
    assert loaded.kernel == batch.kernel
    np.testing.assert_array_equal(np.asarray(loaded.keys), np.asarray(batch.keys))
    assert loaded.to_scalar(uni2) == maps

    # nested Map<K, Map<K2, MVReg>> kernel spec round-trips too
    nested_kernel = MapKernel.from_config(universe.config, mv)
    nested = MapBatch.from_scalar(
        [Map(lambda: Map(MVReg)) for _ in range(2)], universe, nested_kernel
    )
    buf = checkpoint.save_bytes(nested, universe)
    loaded2, _ = checkpoint.load_bytes(buf)
    assert loaded2.kernel == nested.kernel


def test_mvreg_batch_roundtrip(tmp_path):
    from crdt_tpu.batch import MVRegBatch
    from crdt_tpu.scalar.mvreg import MVReg

    universe = Universe()
    regs = []
    for i in range(4):
        r = MVReg()
        r.apply(r.set(f"v{i}", r.read().derive_add_ctx(i % 3)))
        if i % 2:
            # concurrent write from another actor -> a real antichain
            r2 = MVReg()
            r2.apply(r2.set(f"w{i}", r2.read().derive_add_ctx(5)))
            r.merge(r2)
        regs.append(r)
    batch = MVRegBatch.from_scalar(regs, universe)
    path = tmp_path / "mv.npz"
    checkpoint.save(path, batch, universe)
    loaded, uni2 = checkpoint.load(path)
    assert type(loaded) is MVRegBatch
    _assert_batch_equal(batch, loaded)
    assert loaded.to_scalar(uni2) == regs


def test_gset_batch_roundtrip(tmp_path):
    from crdt_tpu.batch import GSetBatch
    from crdt_tpu.scalar.gset import GSet

    universe = Universe()
    sets = [GSet({f"m{j}" for j in range(i + 1)}) for i in range(4)]
    batch = GSetBatch.from_scalar(sets, universe, member_capacity=8)
    path = tmp_path / "gs.npz"
    checkpoint.save(path, batch, universe)
    loaded, uni2 = checkpoint.load(path)
    assert type(loaded) is GSetBatch
    _assert_batch_equal(batch, loaded)
    assert loaded.to_scalar(uni2) == sets


def test_corrupt_container_raises_valueerror():
    """load_bytes is the state-replication receive path; corrupt payloads
    must raise ValueError, not zipfile/KeyError internals (same totality
    contract as serde.from_binary)."""
    import pytest

    from crdt_tpu.utils.serde import to_binary

    for bad in [b"", b"garbage-not-a-zip", b"PK\x03\x04truncated"]:
        with pytest.raises(ValueError):
            checkpoint.load_bytes(bad)

    # a real npz that is not a checkpoint (missing __meta__/__universe__)
    buf = io.BytesIO()
    np.savez(buf, a=np.arange(3))
    with pytest.raises(ValueError):
        checkpoint.load_bytes(buf.getvalue())

    # meta decodes to a non-dict
    buf = io.BytesIO()
    np.savez(
        buf,
        __meta__=np.frombuffer(to_binary(42), dtype=np.uint8),
        __universe__=np.frombuffer(to_binary({}), dtype=np.uint8),
    )
    with pytest.raises(ValueError):
        checkpoint.load_bytes(buf.getvalue())


def test_truncated_checkpoint_raises_valueerror():
    universe = Universe()
    sets = [Orswot() for _ in range(2)]
    for i, s in enumerate(sets):
        s.apply(s.add(f"m{i}", s.value().derive_add_ctx(1)))
    batch = OrswotBatch.from_scalar(sets, universe)
    data = checkpoint.save_bytes(batch, universe)

    import pytest

    for cut in (1, len(data) // 2, len(data) - 3):
        with pytest.raises(ValueError):
            checkpoint.load_bytes(data[:cut])


def test_missing_file_still_filenotfound(tmp_path):
    import pytest

    with pytest.raises(FileNotFoundError):
        checkpoint.load(tmp_path / "nope.npz")


def test_bare_npy_payload_raises_valueerror():
    """np.load on a bare .npy returns an ndarray, not an NpzFile; the
    receive path must reject it as a non-checkpoint, not crash on the
    missing context-manager protocol."""
    import pytest

    buf = io.BytesIO()
    np.save(buf, np.arange(4))
    with pytest.raises(ValueError, match="not a checkpoint container"):
        checkpoint.load_bytes(buf.getvalue())


def test_corrupted_member_crc_raises_valueerror():
    """Npz member reads are lazy: a bit-flip inside a member surfaces as
    zipfile.BadZipFile at z[key] — must be converted to ValueError."""
    import pytest

    universe = Universe()
    sets = [Orswot()]
    sets[0].apply(sets[0].add("m", sets[0].value().derive_add_ctx(1)))
    data = bytearray(checkpoint.save_bytes(OrswotBatch.from_scalar(sets, universe), universe))

    # flip one byte inside the first stored member's payload (past the
    # 30-byte local header + name), leaving the zip directory intact
    name_len = data[26] | (data[27] << 8)
    payload_at = 30 + name_len + 64
    data[payload_at] ^= 0xFF
    with pytest.raises(ValueError):
        checkpoint.load_bytes(bytes(data))


def test_missing_field_arrays_raise_valueerror():
    """A structurally valid npz that lacks a field's arrays must fail at
    load time, not return a silently-corrupt batch."""
    import zipfile as zf

    import pytest

    universe = Universe()
    sets = [Orswot()]
    sets[0].apply(sets[0].add("m", sets[0].value().derive_add_ctx(1)))
    data = checkpoint.save_bytes(OrswotBatch.from_scalar(sets, universe), universe)

    # rebuild the zip without one data member
    src = zf.ZipFile(io.BytesIO(data))
    victim = next(n for n in src.namelist() if not n.startswith("__"))
    out = io.BytesIO()
    with zf.ZipFile(out, "w") as dst:
        for n in src.namelist():
            if n != victim:
                dst.writestr(n, src.read(n))
    with pytest.raises(ValueError):
        checkpoint.load_bytes(out.getvalue())


def test_directory_path_keeps_io_error(tmp_path):
    """Real I/O failures are not data corruption: loading a directory
    surfaces the OS error, not a 'corrupt checkpoint' ValueError."""
    import pytest

    with pytest.raises(IsADirectoryError):
        checkpoint.load(tmp_path)
