"""Checkpoint / resume tests (SURVEY.md §5).

State-based CRDTs: the state is the checkpoint, resume = merge
(`/root/reference/src/lib.rs:62-83`, `traits.rs:36`).  A batch checkpoint
must restore bit-exact SoA buffers and an equivalent interning universe, and
a resumed-then-merged state must equal merging the originals.
"""

import io

import numpy as np
import pytest

from crdt_tpu import Orswot
from crdt_tpu.batch import LWWRegBatch, OrswotBatch
from crdt_tpu.config import CrdtConfig
from crdt_tpu.utils import checkpoint
from crdt_tpu.utils.interning import Universe


def _orswot_fixture(n_actors=4):
    universe = Universe(CrdtConfig(num_actors=n_actors, member_capacity=8,
                                   deferred_capacity=4))
    states = []
    for i in range(6):
        s = Orswot()
        for k in range(i % 3 + 1):
            member = f"m{k}"
            op = s.add(member, s.value().derive_add_ctx(f"actor{(i + k) % n_actors}"))
            s.apply(op)
        states.append(s)
    return OrswotBatch.from_scalar(states, universe), universe, states


def _assert_batch_equal(a, b):
    import dataclasses

    for f in dataclasses.fields(a):
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f.name)), np.asarray(getattr(b, f.name)), err_msg=f.name
        )


def test_orswot_batch_roundtrip(tmp_path):
    batch, universe, _ = _orswot_fixture()
    path = tmp_path / "ck.npz"
    checkpoint.save(path, batch, universe)
    loaded, uni2 = checkpoint.load(path)
    assert type(loaded) is OrswotBatch
    _assert_batch_equal(batch, loaded)
    assert uni2.actors.values() == universe.actors.values()
    assert uni2.members.values() == universe.members.values()
    assert uni2.config == universe.config


def test_roundtrip_bytes_and_resume_merge():
    batch, universe, states = _orswot_fixture()
    blob = checkpoint.save_bytes(batch, universe)
    loaded, uni2 = checkpoint.load_bytes(blob)

    # resume = merge: merging the restored batch into a diverged batch gives
    # the same result as merging the original
    other = OrswotBatch.from_scalar(
        [s.clone() for s in states[::-1]], universe
    )
    merged_orig = other.merge(batch)
    merged_restored = other.merge(loaded)
    _assert_batch_equal(merged_orig, merged_restored)

    # and scalar parity survives the round-trip
    assert [s.value().val for s in loaded.to_scalar(uni2)] == [
        s.value().val for s in states
    ]


def test_lwwreg_batch_roundtrip(tmp_path):
    from crdt_tpu import LWWReg

    universe = Universe()
    regs = [LWWReg(val=i * 10, marker=i + 1) for i in range(5)]
    batch = LWWRegBatch.from_scalar(regs, universe)
    path = tmp_path / "lww.npz"
    checkpoint.save(path, batch, universe)
    loaded, _ = checkpoint.load(path)
    assert type(loaded) is LWWRegBatch
    _assert_batch_equal(batch, loaded)


def test_extensionless_path_roundtrips(tmp_path):
    """np.savez silently appends .npz; save/load must stay symmetric."""
    batch, universe, _ = _orswot_fixture()
    path = tmp_path / "ck"  # no extension
    checkpoint.save(path, batch, universe)
    loaded, _ = checkpoint.load(path)
    _assert_batch_equal(batch, loaded)


def test_rejects_unknown_type():
    universe = Universe()
    try:
        checkpoint.save(io.BytesIO(), object(), universe)
    except TypeError as e:
        assert "checkpointable" in str(e)
    else:
        raise AssertionError("expected TypeError")


def test_rejects_value_kernels():
    """Value-kernel helpers are exported from ``batch`` but are config, not
    state — save() must not treat them as checkpointable batch types."""
    from crdt_tpu.batch import MVRegKernel

    universe = Universe()
    try:
        checkpoint.save(
            io.BytesIO(), MVRegKernel.from_config(universe.config), universe
        )
    except TypeError as e:
        assert "checkpointable" in str(e)
    else:
        raise AssertionError("expected TypeError")


def test_container_is_plain_npz(tmp_path):
    """The container must be readable by plain numpy (no pickle)."""
    batch, universe, _ = _orswot_fixture()
    path = tmp_path / "ck.npz"
    checkpoint.save(path, batch, universe)
    with np.load(path, allow_pickle=False) as z:
        assert "clock" in z.files and "__meta__" in z.files


def test_map_batch_roundtrip(tmp_path):
    """MapBatch: nested vals pytree + static kernel survive the checkpoint
    (leaves stored under path-encoded names, kernel as a metadata spec)."""
    from crdt_tpu import Dot, Map, MVReg, VClock
    from crdt_tpu.batch import MapBatch, MVRegKernel
    from crdt_tpu.batch.val_kernels import MapKernel
    from crdt_tpu.scalar.map import Up
    from crdt_tpu.scalar.mvreg import Put

    universe = Universe(
        CrdtConfig(num_actors=4, member_capacity=8, deferred_capacity=4,
                   mv_capacity=4, key_capacity=4)
    )
    maps = []
    for i in range(3):
        m = Map(MVReg)
        for j in range(i + 1):
            clock = VClock.from_iter([(f"a{j}", j + 1)])
            m.apply(Up(dot=Dot(f"a{j}", j + 1), key=j,
                       op=Put(clock=clock, val=i * 10 + j)))
        maps.append(m)
    mv = MVRegKernel.from_config(universe.config)
    batch = MapBatch.from_scalar(maps, universe, mv)
    path = tmp_path / "mapck"
    checkpoint.save(path, batch, universe)
    loaded, uni2 = checkpoint.load(path)
    assert type(loaded) is MapBatch
    assert loaded.kernel == batch.kernel
    np.testing.assert_array_equal(np.asarray(loaded.keys), np.asarray(batch.keys))
    assert loaded.to_scalar(uni2) == maps

    # nested Map<K, Map<K2, MVReg>> kernel spec round-trips too
    nested_kernel = MapKernel.from_config(universe.config, mv)
    nested = MapBatch.from_scalar(
        [Map(lambda: Map(MVReg)) for _ in range(2)], universe, nested_kernel
    )
    buf = checkpoint.save_bytes(nested, universe)
    loaded2, _ = checkpoint.load_bytes(buf)
    assert loaded2.kernel == nested.kernel


def test_mvreg_batch_roundtrip(tmp_path):
    from crdt_tpu.batch import MVRegBatch
    from crdt_tpu.scalar.mvreg import MVReg

    universe = Universe()
    regs = []
    for i in range(4):
        r = MVReg()
        r.apply(r.set(f"v{i}", r.read().derive_add_ctx(i % 3)))
        if i % 2:
            # concurrent write from another actor -> a real antichain
            r2 = MVReg()
            r2.apply(r2.set(f"w{i}", r2.read().derive_add_ctx(5)))
            r.merge(r2)
        regs.append(r)
    batch = MVRegBatch.from_scalar(regs, universe)
    path = tmp_path / "mv.npz"
    checkpoint.save(path, batch, universe)
    loaded, uni2 = checkpoint.load(path)
    assert type(loaded) is MVRegBatch
    _assert_batch_equal(batch, loaded)
    assert loaded.to_scalar(uni2) == regs


def test_gset_batch_roundtrip(tmp_path):
    from crdt_tpu.batch import GSetBatch
    from crdt_tpu.scalar.gset import GSet

    universe = Universe()
    sets = [GSet({f"m{j}" for j in range(i + 1)}) for i in range(4)]
    batch = GSetBatch.from_scalar(sets, universe, member_capacity=8)
    path = tmp_path / "gs.npz"
    checkpoint.save(path, batch, universe)
    loaded, uni2 = checkpoint.load(path)
    assert type(loaded) is GSetBatch
    _assert_batch_equal(batch, loaded)
    assert loaded.to_scalar(uni2) == sets


def test_corrupt_container_raises_valueerror():
    """load_bytes is the state-replication receive path; corrupt payloads
    must raise ValueError, not zipfile/KeyError internals (same totality
    contract as serde.from_binary)."""
    import pytest

    from crdt_tpu.utils.serde import to_binary

    for bad in [b"", b"garbage-not-a-zip", b"PK\x03\x04truncated"]:
        with pytest.raises(ValueError):
            checkpoint.load_bytes(bad)

    # a real npz that is not a checkpoint (missing __meta__/__universe__)
    buf = io.BytesIO()
    np.savez(buf, a=np.arange(3))
    with pytest.raises(ValueError):
        checkpoint.load_bytes(buf.getvalue())

    # meta decodes to a non-dict
    buf = io.BytesIO()
    np.savez(
        buf,
        __meta__=np.frombuffer(to_binary(42), dtype=np.uint8),
        __universe__=np.frombuffer(to_binary({}), dtype=np.uint8),
    )
    with pytest.raises(ValueError):
        checkpoint.load_bytes(buf.getvalue())


def test_truncated_checkpoint_raises_valueerror():
    universe = Universe()
    sets = [Orswot() for _ in range(2)]
    for i, s in enumerate(sets):
        s.apply(s.add(f"m{i}", s.value().derive_add_ctx(1)))
    batch = OrswotBatch.from_scalar(sets, universe)
    data = checkpoint.save_bytes(batch, universe)

    import pytest

    for cut in (1, len(data) // 2, len(data) - 3):
        with pytest.raises(ValueError):
            checkpoint.load_bytes(data[:cut])


def test_missing_file_still_filenotfound(tmp_path):
    import pytest

    with pytest.raises(FileNotFoundError):
        checkpoint.load(tmp_path / "nope.npz")


def test_bare_npy_payload_raises_valueerror():
    """np.load on a bare .npy returns an ndarray, not an NpzFile; the
    receive path must reject it as a non-checkpoint, not crash on the
    missing context-manager protocol."""
    import pytest

    buf = io.BytesIO()
    np.save(buf, np.arange(4))
    with pytest.raises(ValueError, match="not a checkpoint container"):
        checkpoint.load_bytes(buf.getvalue())


def test_corrupted_member_crc_raises_valueerror():
    """Npz member reads are lazy: a bit-flip inside a member surfaces as
    zipfile.BadZipFile at z[key] — must be converted to ValueError."""
    import pytest

    universe = Universe()
    sets = [Orswot()]
    sets[0].apply(sets[0].add("m", sets[0].value().derive_add_ctx(1)))
    data = bytearray(checkpoint.save_bytes(OrswotBatch.from_scalar(sets, universe), universe))

    # flip one byte inside the first stored member's payload (past the
    # 30-byte local header + name), leaving the zip directory intact
    name_len = data[26] | (data[27] << 8)
    payload_at = 30 + name_len + 64
    data[payload_at] ^= 0xFF
    with pytest.raises(ValueError):
        checkpoint.load_bytes(bytes(data))


def test_missing_field_arrays_raise_valueerror():
    """A structurally valid npz that lacks a field's arrays must fail at
    load time, not return a silently-corrupt batch."""
    import zipfile as zf

    import pytest

    universe = Universe()
    sets = [Orswot()]
    sets[0].apply(sets[0].add("m", sets[0].value().derive_add_ctx(1)))
    data = checkpoint.save_bytes(OrswotBatch.from_scalar(sets, universe), universe)

    # rebuild the zip without one data member
    src = zf.ZipFile(io.BytesIO(data))
    victim = next(n for n in src.namelist() if not n.startswith("__"))
    out = io.BytesIO()
    with zf.ZipFile(out, "w") as dst:
        for n in src.namelist():
            if n != victim:
                dst.writestr(n, src.read(n))
    with pytest.raises(ValueError):
        checkpoint.load_bytes(out.getvalue())


def test_directory_path_keeps_io_error(tmp_path):
    """Real I/O failures are not data corruption: loading a directory
    surfaces the OS error, not a 'corrupt checkpoint' ValueError."""
    import pytest

    with pytest.raises(IsADirectoryError):
        checkpoint.load(tmp_path)


def test_gcounter_batch_roundtrip(tmp_path):
    from crdt_tpu.batch import GCounterBatch
    from crdt_tpu.scalar.gcounter import GCounter

    universe = Universe()
    counters = []
    for i in range(5):
        c = GCounter()
        for j in range(i + 1):
            c.apply(c.inc(f"a{j % 3}"))
        counters.append(c)
    batch = GCounterBatch.from_scalar(counters, universe)
    path = tmp_path / "gc.npz"
    checkpoint.save(path, batch, universe)
    loaded, uni2 = checkpoint.load(path)
    assert type(loaded) is GCounterBatch
    _assert_batch_equal(batch, loaded)
    assert [c.value() for c in loaded.to_scalar(uni2)] == [
        c.value() for c in counters
    ]


def test_pncounter_batch_roundtrip(tmp_path):
    from crdt_tpu.batch import PNCounterBatch
    from crdt_tpu.scalar.pncounter import PNCounter

    universe = Universe()
    counters = []
    for i in range(5):
        c = PNCounter()
        for j in range(i + 2):
            c.apply(c.inc(f"a{j % 3}"))
        if i % 2:
            c.apply(c.dec("a0"))
        counters.append(c)
    batch = PNCounterBatch.from_scalar(counters, universe)
    path = tmp_path / "pn.npz"
    checkpoint.save(path, batch, universe)
    loaded, uni2 = checkpoint.load(path)
    assert type(loaded) is PNCounterBatch
    _assert_batch_equal(batch, loaded)
    assert [c.value() for c in loaded.to_scalar(uni2)] == [
        c.value() for c in counters
    ]


# ---- the all-families property sweep (ISSUE 12 satellite) ------------------
#
# For EVERY plane family: a seeded random diverged pair (A, B) must
# satisfy  load(save(A)) == A  (bit-exact buffers + scalar parity) and
# the resume-by-merge identity  B.merge(load(save(A))) == B.merge(A) —
# the reference's whole durability contract (`lib.rs:62-83`,
# `traits.rs:36`) across the batch engine.


def _orswot_pair(rng, universe):
    from crdt_tpu.batch import OrswotBatch

    def states(extra_actor):
        out = []
        for i in range(6):
            s = Orswot()
            for _ in range(int(rng.randint(1, 4))):
                s.apply(s.add(int(rng.randint(0, 20)),
                              s.value().derive_add_ctx(
                                  f"a{int(rng.randint(0, 3))}")))
            if i % 2:
                s.apply(s.add(100 + i, s.value().derive_add_ctx(extra_actor)))
            out.append(s)
        return out

    return (OrswotBatch.from_scalar(states("x"), universe),
            OrswotBatch.from_scalar(states("y"), universe))


def _gcounter_pair(rng, universe):
    from crdt_tpu.batch import GCounterBatch
    from crdt_tpu.scalar.gcounter import GCounter

    def states():
        out = []
        for _ in range(6):
            c = GCounter()
            for _ in range(int(rng.randint(1, 6))):
                c.apply(c.inc(f"a{int(rng.randint(0, 3))}"))
            out.append(c)
        return out

    return (GCounterBatch.from_scalar(states(), universe),
            GCounterBatch.from_scalar(states(), universe))


def _pncounter_pair(rng, universe):
    from crdt_tpu.batch import PNCounterBatch
    from crdt_tpu.scalar.pncounter import PNCounter

    def states():
        out = []
        for _ in range(6):
            c = PNCounter()
            for _ in range(int(rng.randint(1, 6))):
                c.apply(c.inc(f"a{int(rng.randint(0, 3))}"))
            if rng.randint(0, 2):
                c.apply(c.dec(f"a{int(rng.randint(0, 3))}"))
            out.append(c)
        return out

    return (PNCounterBatch.from_scalar(states(), universe),
            PNCounterBatch.from_scalar(states(), universe))


def _gset_pair(rng, universe):
    from crdt_tpu.batch import GSetBatch
    from crdt_tpu.scalar.gset import GSet

    def states():
        return [
            GSet({int(m) for m in rng.randint(0, 30, rng.randint(1, 6))})
            for _ in range(6)
        ]

    # interned member ids are registry-dense: capacity must cover every
    # distinct member both sides ever intern
    return (GSetBatch.from_scalar(states(), universe, member_capacity=32),
            GSetBatch.from_scalar(states(), universe, member_capacity=32))


def _mvreg_pair(rng, universe):
    from crdt_tpu.batch import MVRegBatch
    from crdt_tpu.scalar.mvreg import MVReg

    def states():
        out = []
        for i in range(6):
            r = MVReg()
            r.apply(r.set(int(rng.randint(0, 50)),
                          r.read().derive_add_ctx(int(rng.randint(0, 3)))))
            if i % 2:
                r2 = MVReg()
                r2.apply(r2.set(int(rng.randint(50, 99)),
                                r2.read().derive_add_ctx(5)))
                r.merge(r2)
            out.append(r)
        return out

    return (MVRegBatch.from_scalar(states(), universe),
            MVRegBatch.from_scalar(states(), universe))


def _lwwreg_pair(rng, universe):
    from crdt_tpu import LWWReg
    from crdt_tpu.batch import LWWRegBatch

    def states():
        return [LWWReg(val=int(rng.randint(0, 99)),
                       marker=int(rng.randint(1, 50)))
                for _ in range(6)]

    return (LWWRegBatch.from_scalar(states(), universe),
            LWWRegBatch.from_scalar(states(), universe))


def _map_pair(rng, universe):
    from crdt_tpu import Dot, Map, MVReg, VClock
    from crdt_tpu.batch import MapBatch, MVRegKernel
    from crdt_tpu.scalar.map import Up
    from crdt_tpu.scalar.mvreg import Put

    kernel = MVRegKernel.from_config(universe.config)

    def states():
        out = []
        for i in range(3):
            m = Map(MVReg)
            for j in range(int(rng.randint(1, 3))):
                clock = VClock.from_iter([(f"a{j}", int(rng.randint(1, 5)))])
                m.apply(Up(dot=Dot(f"a{j}", int(rng.randint(1, 5))), key=j,
                           op=Put(clock=clock, val=int(rng.randint(0, 99)))))
            out.append(m)
        return out

    return (MapBatch.from_scalar(states(), universe, kernel),
            MapBatch.from_scalar(states(), universe, kernel))


_FAMILY_PAIRS = {
    "orswot": (_orswot_pair, True),
    "gcounter": (_gcounter_pair, True),
    "pncounter": (_pncounter_pair, True),
    "gset": (_gset_pair, True),
    "mvreg": (_mvreg_pair, True),
    "lwwreg": (_lwwreg_pair, True),
    "map": (_map_pair, False),   # static kernel field: compare via to_scalar
}


def _uni_for(family):
    cfg = CrdtConfig(num_actors=8, member_capacity=8, deferred_capacity=4,
                     mv_capacity=4, key_capacity=4)
    return Universe(cfg)


@pytest.mark.parametrize("family", sorted(_FAMILY_PAIRS))
@pytest.mark.parametrize("seed", [0, 1])
def test_family_roundtrip_and_resume_merge_identity(family, seed):
    make_pair, arrays_comparable = _FAMILY_PAIRS[family]
    rng = np.random.RandomState(seed * 101 + 7)
    universe = _uni_for(family)
    a, b = make_pair(rng, universe)

    blob = checkpoint.save_bytes(a, universe)
    loaded, uni2 = checkpoint.load_bytes(blob)
    assert type(loaded) is type(a)
    if arrays_comparable:
        _assert_batch_equal(a, loaded)
        merged_orig = b.merge(a)
        merged_restored = b.merge(loaded)
        _assert_batch_equal(merged_orig, merged_restored)
    else:
        assert loaded.to_scalar(uni2) == a.to_scalar(universe)
        assert b.merge(loaded).to_scalar(universe) \
            == b.merge(a).to_scalar(universe)
    # restored universe is equivalent
    assert uni2.actors.values() == universe.actors.values()
    assert uni2.members.values() == universe.members.values()


def test_post_gc_settled_repacked_state_roundtrips():
    """ISSUE 12 satellite: a fleet that GC settled AND re-packed down
    the capacity ladder must checkpoint/restore digest-identical —
    durability composes with compaction, not just with fresh planes."""
    from crdt_tpu.batch import OrswotBatch
    from crdt_tpu.gc import GcEngine, GcPolicy
    from crdt_tpu.obs import convergence as obs_convergence
    from crdt_tpu.obs import metrics as obs_metrics
    from crdt_tpu.scalar.ctx import RmCtx
    from crdt_tpu.scalar.vclock import VClock
    from crdt_tpu.sync import digest as digest_mod

    cfg = CrdtConfig(num_actors=8, member_capacity=8, deferred_capacity=4,
                     counter_bits=32)
    uni = Universe.identity(cfg)
    rng = np.random.RandomState(31)
    states = []
    for i in range(64):
        s = Orswot()
        for _ in range(int(rng.randint(1, 5))):
            s.apply(s.add(int(rng.randint(0, 200)),
                          s.value().derive_add_ctx(int(rng.randint(0, 4)))))
        if i % 9 == 0:  # a causally-future remove -> a deferred row
            future = VClock()
            future.witness(7, int(rng.randint(50, 90)))
            s.apply(s.remove(0, RmCtx(clock=future)))
        states.append(s)
    twin = OrswotBatch.from_scalar(states, uni)
    big = twin.with_capacity(32, 16)
    eng = GcEngine(
        GcPolicy(interval_rounds=1),
        tracker=obs_convergence.ConvergenceTracker(
            obs_metrics.MetricsRegistry()))
    compacted, report = eng.collect(big, universe=uni)
    assert report.shrunk  # the fixture really exercised the repack

    blob = checkpoint.save_bytes(compacted, uni)
    loaded, uni2 = checkpoint.load_bytes(blob)
    _assert_batch_equal(compacted, loaded)
    want = np.asarray(digest_mod.digest_of(twin), np.uint64)
    got = np.asarray(digest_mod.digest_of(loaded), np.uint64)
    np.testing.assert_array_equal(got, want)

    # resume-by-merge across the GC boundary: merging the restored
    # compacted fleet equals merging the never-compacted twin
    other = OrswotBatch.from_scalar(states[::-1], uni).with_capacity(32, 16)
    a = np.asarray(digest_mod.digest_of(other.merge(loaded)), np.uint64)
    b = np.asarray(digest_mod.digest_of(other.merge(twin)), np.uint64)
    np.testing.assert_array_equal(a, b)
