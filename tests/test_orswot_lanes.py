"""Parity: layout-experiment ORSWOT merges vs the production jnp path.

Both variants in ``crdt_tpu.ops.orswot_lanes`` — the unrolled standard-
layout merge and the lanes-last (object-axis-minor) merge — must be
bit-identical to ``orswot_ops.merge``, which is itself bit-exact against
the scalar engine (``tests/test_parity.py``) and thereby the reference
(`/root/reference/src/orswot.rs:89-156`).  Deferred-bearing states are
included: ``random_orswot_arrays(deferred_frac=...)`` plants causally-
future remove rows, so the replay path is exercised, not just the fast
path.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from crdt_tpu.ops import orswot_lanes, orswot_ops
from crdt_tpu.utils.testdata import random_orswot_arrays


def _pair(rng, n, a, m, d, deferred_frac=0.0):
    lhs = tuple(
        jnp.asarray(x)
        for x in random_orswot_arrays(
            rng, n, a, m, d, np.uint32, deferred_frac=deferred_frac
        )
    )
    rhs = tuple(
        jnp.asarray(x)
        for x in random_orswot_arrays(
            rng, n, a, m, d, np.uint32, deferred_frac=deferred_frac
        )
    )
    return lhs, rhs


def _assert_same(ref, got):
    """Bit-equality on every object the production path doesn't flag as
    overflowed.  ``orswot_ops`` counts member survivors *pre*-replay (the
    conservative contract — the host discards flagged objects and
    regrows), while the unrolled tile math replays before compaction and
    only overflows when the *post*-replay survivors exceed capacity, so
    on ref-flagged objects the two legitimately diverge; everywhere else
    they must agree exactly, and the unrolled flag must never fire where
    the conservative one didn't."""
    ref_over = np.asarray(ref[5])
    got_over = np.asarray(got[5])
    ok = ~ref_over.any(axis=-1)
    assert not (got_over & ~ref_over).any(), "unrolled overflow without ref overflow"
    names = ("clock", "ids", "dots", "d_ids", "d_clocks")
    for name, r, g in zip(names, ref[:5], got[:5]):
        np.testing.assert_array_equal(
            np.asarray(r)[ok], np.asarray(g)[ok], err_msg=name
        )


@pytest.mark.parametrize("deferred_frac", [0.0, 0.4])
@pytest.mark.parametrize("shape", [(17, 4, 3, 2), (33, 8, 4, 2), (21, 16, 8, 4)])
def test_unrolled_merge_parity(shape, deferred_frac):
    n, a, m, d = shape
    rng = np.random.RandomState(11)
    lhs, rhs = _pair(rng, n, a, m, d, deferred_frac)
    _assert_same(
        orswot_ops.merge(*lhs, *rhs, m, d),
        orswot_lanes.merge_unrolled(*lhs, *rhs, m, d),
    )


@pytest.mark.parametrize("deferred_frac", [0.0, 0.4])
@pytest.mark.parametrize("shape", [(17, 4, 3, 2), (33, 8, 4, 2), (21, 16, 8, 4)])
def test_lanes_merge_parity(shape, deferred_frac):
    n, a, m, d = shape
    rng = np.random.RandomState(13)
    lhs, rhs = _pair(rng, n, a, m, d, deferred_frac)
    _assert_same(
        orswot_ops.merge(*lhs, *rhs, m, d),
        orswot_lanes.merge_lanes(*lhs, *rhs, m, d),
    )


def test_merge_impl_dispatch(monkeypatch):
    """CRDT_MERGE_IMPL routes orswot_ops.merge to the layout variants;
    all three implementations agree on non-overflow objects, and the
    lanes route falls back to rank for batch ranks it cannot transpose."""
    rng = np.random.RandomState(23)
    lhs, rhs = _pair(rng, 19, 4, 3, 2, deferred_frac=0.3)
    outs = {}
    for impl in ("rank", "unrolled", "lanes"):
        monkeypatch.setenv("CRDT_MERGE_IMPL", impl)
        outs[impl] = orswot_ops.merge(*lhs, *rhs, 3, 2)
    for impl in ("unrolled", "lanes"):
        _assert_same(outs["rank"], outs[impl])

    # rank > 2 under lanes: must fall through, not mis-transpose
    monkeypatch.setenv("CRDT_MERGE_IMPL", "lanes")
    stacked_l = tuple(jnp.stack([x, x]) for x in lhs)
    stacked_r = tuple(jnp.stack([x, x]) for x in rhs)
    got = orswot_ops.merge(*stacked_l, *stacked_r, 3, 2)
    monkeypatch.setenv("CRDT_MERGE_IMPL", "rank")
    want = orswot_ops.merge(*stacked_l, *stacked_r, 3, 2)
    for w, g in zip(want, got):
        np.testing.assert_array_equal(np.asarray(w), np.asarray(g))

    # unknown impl names error instead of silently picking a variant
    monkeypatch.setenv("CRDT_MERGE_IMPL", "pallas")
    with pytest.raises(ValueError, match="CRDT_MERGE_IMPL"):
        orswot_ops.merge(*lhs, *rhs, 3, 2)


from hypothesis import given, settings
from hypothesis import strategies as st


import functools

import jax as _jax


@functools.lru_cache(maxsize=None)
def _jitted(impl, m, d):
    """One compiled merge per (impl, caps): example iterations then cost
    dispatch, not tracing (eager tiny-shape merges are ~1s each)."""
    fn = {
        "rank": orswot_ops.merge,  # traced with CRDT_MERGE_IMPL unset
        "unrolled": orswot_lanes.merge_unrolled,
        "lanes": orswot_lanes.merge_lanes,
    }[impl]
    return _jax.jit(lambda lhs, rhs: fn(*lhs, *rhs, m, d))


@pytest.mark.parametrize(
    "shape", [(7, 1, 1, 1), (7, 3, 2, 1), (7, 8, 5, 3)]
)
@settings(max_examples=25)  # shapes fixed → 3 compiles per impl, data varies
@given(seed=st.integers(0, 2**31 - 1), deferred_frac=st.sampled_from([0.0, 0.5]))
def test_impl_agreement_property(shape, seed, deferred_frac):
    """All three merge implementations agree on random states across the
    shape grid (incl. single-slot tables and deferred-bearing batches) —
    the randomized analogue of the fixed-seed parity cases above."""
    n, a, m, d = shape
    rng = np.random.RandomState(seed)
    lhs, rhs = _pair(rng, n, a, m, d, deferred_frac)
    ref = _jitted("rank", m, d)(lhs, rhs)
    _assert_same(ref, _jitted("unrolled", m, d)(lhs, rhs))
    _assert_same(ref, _jitted("lanes", m, d)(lhs, rhs))


def test_full_uint32_counter_range_parity():
    """The lanes tile math works in the bias-mapped signed domain
    (``x ^ 0x8000_0000``); counters at and above ``2**31`` must stay
    bit-exact through both layout variants."""
    rng = np.random.RandomState(29)
    n, a, m, d = 16, 4, 4, 2
    lhs, rhs = _pair(rng, n, a, m, d, deferred_frac=0.4)

    def inflate(state):
        clock, ids, dots, dids, dclocks = state
        big = jnp.uint32(1 << 31)
        up = lambda x: jnp.where(x > 0, x + big, x)  # keep 0 = absent
        return up(clock), ids, up(dots), dids, up(dclocks)

    lhs, rhs = inflate(lhs), inflate(rhs)
    ref = orswot_ops.merge(*lhs, *rhs, m, d)
    _assert_same(ref, orswot_lanes.merge_unrolled(*lhs, *rhs, m, d))
    _assert_same(ref, orswot_lanes.merge_lanes(*lhs, *rhs, m, d))
    assert int(np.asarray(ref[0]).max()) >= 1 << 31


def test_lanes_roundtrip():
    rng = np.random.RandomState(17)
    state = tuple(
        jnp.asarray(x)
        for x in random_orswot_arrays(rng, 9, 4, 3, 2, np.uint32, deferred_frac=0.5)
    )
    back = orswot_lanes.from_lanes(orswot_lanes.to_lanes(state))
    for want, got in zip(state, back):
        np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


def test_lanes_fold_stays_transposed():
    """A left fold in the transposed layout (transpose once, fold R, egress
    once) matches the production fold — the deployment shape for TPU."""
    from crdt_tpu.utils.testdata import anti_entropy_fleets

    rng = np.random.RandomState(19)
    n, a, m, d, r = 15, 8, 8, 2, 4
    fleets = [
        tuple(jnp.asarray(x) for x in rep)
        for rep in anti_entropy_fleets(
            rng, n, a, m, d, r, base=4, novel=1, deferred_frac=0.3
        )
    ]

    want = fleets[0]
    over = np.zeros((n,), bool)
    for nxt in fleets[1:]:
        *want, o = orswot_ops.merge(*want, *nxt, m, d)
        over |= np.asarray(o).any(axis=-1)
    *want, o = orswot_ops.merge(*want, *want, m, d)  # defer plunger
    over |= np.asarray(o).any(axis=-1)
    ok = ~over  # conservative-overflow objects diverge by contract

    acc = orswot_lanes.to_lanes(fleets[0])
    for nxt in fleets[1:]:
        acc, _ = orswot_lanes.merge_t(acc, orswot_lanes.to_lanes(nxt), m, d)
    acc, _ = orswot_lanes.merge_t(acc, acc, m, d)
    got = orswot_lanes.from_lanes(acc)
    assert ok.sum() >= n // 2, "fold test data mostly overflowed; regenerate"
    for name, w, g in zip(("clock", "ids", "dots", "d_ids", "d_clocks"), want, got):
        np.testing.assert_array_equal(
            np.asarray(w)[ok], np.asarray(g)[ok], err_msg=name
        )

    # the stacked fold driver (the bench's CRDT_LANES=1 path) must match
    # the manual per-fleet fold above
    stack = tuple(
        jnp.stack([fleet[k] for fleet in fleets]) for k in range(5)
    )
    out, _ = orswot_lanes.fold_merge_t(
        orswot_lanes.stacked_to_lanes(stack), m, d
    )
    got2 = orswot_lanes.from_lanes(out)
    for name, w, g in zip(("clock", "ids", "dots", "d_ids", "d_clocks"), got, got2):
        np.testing.assert_array_equal(
            np.asarray(w), np.asarray(g), err_msg=f"stacked fold {name}"
        )
