"""Executable API examples — the reference ships runnable doctests on most
public APIs (`/root/reference/src/vclock.rs:88-102`, `map.rs:35-80`,
`lib.rs:53-60`); this runner keeps ours compiling-and-passing the same way.
"""

import doctest

import pytest

import crdt_tpu
import crdt_tpu.scalar.gcounter
import crdt_tpu.scalar.gset
import crdt_tpu.scalar.lwwreg
import crdt_tpu.scalar.map
import crdt_tpu.scalar.mvreg
import crdt_tpu.scalar.orswot
import crdt_tpu.scalar.pncounter
import crdt_tpu.scalar.vclock

MODULES = [
    crdt_tpu,
    crdt_tpu.scalar.vclock,
    crdt_tpu.scalar.gcounter,
    crdt_tpu.scalar.pncounter,
    crdt_tpu.scalar.lwwreg,
    crdt_tpu.scalar.mvreg,
    crdt_tpu.scalar.gset,
    crdt_tpu.scalar.orswot,
    crdt_tpu.scalar.map,
]


@pytest.mark.parametrize("module", MODULES, ids=lambda m: m.__name__)
def test_doctests(module):
    result = doctest.testmod(module, verbose=False)
    assert result.attempted > 0, f"{module.__name__} has no doctests"
    assert result.failed == 0
