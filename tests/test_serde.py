"""Serialization round-trip tests — mirrors the doctest at
`/root/reference/src/lib.rs:53-60` and exercises every type + op codec.

Also checks determinism: equal CRDTs encode to equal bytes (the codec doubles
as a content digest for anti-entropy).
"""

from hypothesis import given
from hypothesis import strategies as st

from crdt_tpu import (
    Dot,
    GCounter,
    GSet,
    LWWReg,
    Map,
    MVReg,
    Orswot,
    PNCounter,
    VClock,
    from_binary,
    to_binary,
)
from crdt_tpu.utils.serde import MapOf


def roundtrip(x):
    data = to_binary(x)
    back = from_binary(data)
    assert back == x
    # determinism: re-encoding the decoded value gives identical bytes
    assert to_binary(back) == data
    return back


def test_orswot_roundtrip_doc():
    """`lib.rs:53-60`."""
    a = Orswot()
    op = a.add(1, a.value().derive_add_ctx(1))
    a.apply(op)
    decoded = roundtrip(a)
    assert decoded.value().val == {1}


def test_primitives():
    for x in [None, True, False, 0, -1, 2**64, "héllo", b"bytes", [1, [2]], (1, "a"),
              {1: "a", "b": 2}, {1, 2, 3}, frozenset({4}), 3.25]:
        roundtrip(x)


def test_vclock_and_dot():
    roundtrip(VClock.from_iter([(1, 4), (2, 3), ("actor", 9)]))
    roundtrip(Dot("A", 3))


def test_counters():
    g = GCounter()
    g.apply(g.inc("A"))
    roundtrip(g)

    p = PNCounter()
    p.apply(p.inc("A"))
    p.apply(p.dec("B"))
    roundtrip(p)


def test_lwwreg_and_gset():
    roundtrip(LWWReg(val=42, marker=7))
    roundtrip(GSet({1, 2, 3}))


def test_mvreg():
    r = MVReg()
    r.apply(r.set(32, r.read().derive_add_ctx(1)))
    roundtrip(r)


def test_orswot_with_deferred():
    from crdt_tpu import RmCtx

    a = Orswot()
    a.apply(a.add("x", a.value().derive_add_ctx(1)))
    a.apply(a.remove("y", RmCtx(clock=Dot(9, 4).to_vclock())))
    assert len(a.deferred) == 1
    roundtrip(a)


def test_map_nested():
    m = Map(MapOf(MVReg))
    op = m.update(
        101, m.get(101).derive_add_ctx(1),
        lambda mm, c: mm.update(110, c, lambda r, c2: r.set(2, c2)),
    )
    m.apply(op)
    back = roundtrip(m)
    assert back.get(101).val.get(110).val.read().val == [2]
    # ops round-trip too
    roundtrip(op)


def test_ops_roundtrip():
    from crdt_tpu.scalar.map import Nop as MapNop, Rm as MapRm
    from crdt_tpu.scalar.mvreg import Put
    from crdt_tpu.scalar.orswot import Add, Rm as ORm
    from crdt_tpu.scalar.pncounter import Dir, Op as PNOp

    roundtrip(Add(dot=Dot(1, 1), member="m"))
    roundtrip(ORm(clock=Dot(1, 1).to_vclock(), member="m"))
    roundtrip(Put(clock=Dot(2, 1).to_vclock(), val=71))
    roundtrip(PNOp(dot=Dot(1, 2), dir=Dir.POS))
    roundtrip(PNOp(dot=Dot(1, 2), dir=Dir.NEG))
    roundtrip(MapNop())
    roundtrip(MapRm(clock=Dot(1, 1).to_vclock(), key=9))


def test_ctxs_roundtrip():
    a = Orswot()
    a.apply(a.add(1, a.value().derive_add_ctx(1)))
    read_ctx = a.value()
    roundtrip(read_ctx)
    roundtrip(read_ctx.derive_add_ctx(2))
    roundtrip(read_ctx.derive_rm_ctx())


@given(st.lists(st.tuples(st.integers(0, 50), st.integers(0, 2**32), st.booleans()), max_size=15))
def test_prop_orswot_state_roundtrips(prims):
    from crdt_tpu import RmCtx

    a = Orswot()
    for actor, counter, is_add in prims:
        if is_add:
            a.apply(a.add(counter % 17, a.value().derive_add_ctx(actor)))
        else:
            a.apply(a.remove(counter % 17, RmCtx(clock=Dot(actor, counter % 5).to_vclock())))
    roundtrip(a)


@given(st.lists(st.tuples(st.integers(0, 255), st.integers(0, 255)), max_size=10))
def test_prop_equal_states_encode_equal_bytes(prims):
    """Determinism under different insertion orders."""
    a = VClock.from_iter(prims)
    b = VClock.from_iter(list(reversed(prims)))
    assert a == b
    assert to_binary(a) == to_binary(b)


def test_truncated_str_raises():
    """Truncated payload bytes must raise, not silently decode a prefix."""
    import pytest

    data = to_binary("hello")
    with pytest.raises(ValueError):
        from_binary(data[:3])


def test_mvreg_equal_states_encode_equal_bytes():
    """Merge order must not leak into the encoding (set-equality type)."""
    r1, r2 = MVReg(), MVReg()
    r1.apply(r1.set(1, r1.read().derive_add_ctx(4)))
    r2.apply(r2.set(2, r2.read().derive_add_ctx(7)))
    a, b = r1.clone(), r2.clone()
    a.merge(r2)
    b.merge(r1)
    assert a == b
    assert to_binary(a) == to_binary(b)


# -- fuzz: malformed input must fail with ValueError, nothing else ----------
#
# The reference delegates this to bincode's typed Result (`lib.rs:79-83`);
# our contract is the same at the API boundary: from_binary either returns a
# value or raises ValueError.  Corrupt wires must not leak TypeError /
# RecursionError / UnicodeDecodeError out of the codec.


def _decode_is_total(data: bytes):
    try:
        from_binary(data)
    except ValueError:
        pass  # the one contract exception (UnicodeDecodeError subclasses it)


@given(st.binary(max_size=512))
def test_prop_random_bytes_decode_totally(data):
    _decode_is_total(data)


def _fuzz_corpus():
    vc = VClock.from_iter([(1, 3), (2, 5)])
    o = Orswot()
    o.apply(o.add("m", o.value().derive_add_ctx(1)))
    m = Map(MVReg)
    m.apply(m.update("k", m.len().derive_add_ctx(2), lambda r, c: r.set(9, c)))
    return [to_binary(x) for x in (vc, o, m, {"a": [1, (2.5, None)]}, "héllo")]


_CORPUS = _fuzz_corpus()


@given(
    st.integers(0, len(_CORPUS) - 1),
    st.integers(0, 4096),
    st.integers(0, 255),
    st.sampled_from(["flip", "insert", "delete", "truncate"]),
)
def test_prop_mutated_encodings_decode_totally(which, pos, byte, mode):
    data = bytearray(_CORPUS[which])
    pos %= max(1, len(data))
    if mode == "flip":
        data[pos] = byte
    elif mode == "insert":
        data.insert(pos, byte)
    elif mode == "delete":
        del data[pos]
    else:
        data = data[:pos]
    _decode_is_total(bytes(data))


def test_varint_bomb_raises_valueerror():
    """An unbounded run of 0x80 continuation bytes used to decode with
    quadratic big-int cost (asymmetric CPU-DoS on the replication
    receive path); the _MAX_VARINT_BYTES guard must reject it while
    arbitrary-precision int payloads well past 64 bits keep working."""
    import pytest

    from crdt_tpu.utils.serde import _MAX_VARINT_BYTES

    # 0x03 = the int tag; then an endless continuation run
    bomb = bytes([0x03]) + bytes([0x80]) * (_MAX_VARINT_BYTES + 10) + bytes([0x01])
    with pytest.raises(ValueError, match="varint"):
        from_binary(bomb)
    # legitimate big ints (beyond u64) still round-trip
    big = 1 << 200
    assert from_binary(to_binary(big)) == big
    assert from_binary(to_binary(-big)) == -big


def test_nesting_bomb_raises_valueerror():
    """~2 KB of list tags nests one level per byte pair; the explicit
    _MAX_DEPTH guard must reject it deterministically (long before the
    interpreter stack is at risk)."""
    import pytest

    bomb = bytes([0x07, 0x01]) * 2000 + bytes([0x00])
    with pytest.raises(ValueError, match="nesting deeper"):
        from_binary(bomb)


def test_val_type_nesting_bomb_raises_valueerror():
    """The Map val_type decoder recurses separately from _decode; a run of
    MapOf tags must hit the same deterministic depth bound."""
    import pytest

    bomb = bytes([0x27]) + bytes([0x51]) * 2000
    with pytest.raises(ValueError, match="nesting deeper"):
        from_binary(bomb)


def test_unhashable_set_element_raises_valueerror():
    """A set whose element decodes to a list is unhashable — TypeError in
    the body, ValueError at the boundary."""
    import pytest

    # T_SET, count=1, element = empty list
    data = bytes([0x09, 0x01, 0x07, 0x00])
    with pytest.raises(ValueError):
        from_binary(data)
