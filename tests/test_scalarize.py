"""Native dense->scalar egress (`crdt_tpu/native/scalarize.c`).

Contract: ``OrswotBatch.to_scalar`` through the C extension is
object-identical to the Python egress loop — same ``to_binary`` bytes,
same dict insertion order, same deferred keys — for identity AND
interned universes (names are resolved host-side and passed in, so the
fast path is universe-agnostic).
"""

import numpy as np
import pytest

from crdt_tpu import Orswot, to_binary
from crdt_tpu.batch import OrswotBatch
from crdt_tpu.config import CrdtConfig
from crdt_tpu.utils.interning import Universe


def _random_states(rng, n, actor_of, member_of, n_actors=8):
    states = []
    for _ in range(n):
        s = Orswot()
        for _ in range(int(rng.randint(1, 5))):
            s.apply(s.add(
                member_of(int(rng.randint(0, 30))),
                s.value().derive_add_ctx(actor_of(int(rng.randint(0, n_actors)))),
            ))
        if rng.rand() < 0.4 and s.entries:
            m = next(iter(s.entries))
            ctx = s.contains(m).derive_rm_ctx()
            ctx.clock.witness(
                actor_of(int(rng.randint(0, n_actors))),
                int(rng.randint(100, 200)),
            )
            s.apply(s.remove(m, ctx))
        states.append(s)
    return states


def _both_paths(states, uni):
    from crdt_tpu.native import scalarize

    batch = OrswotBatch.from_scalar(states, uni)
    if not scalarize.available():
        pytest.skip("scalarize extension unavailable")
    native = batch.to_scalar(uni)
    # disable the extension for this comparison only
    saved_mod, saved_err = scalarize._mod, scalarize._error
    scalarize._mod, scalarize._error = None, "disabled for test"
    try:
        python_path = batch.to_scalar(uni)
    finally:
        scalarize._mod, scalarize._error = saved_mod, saved_err
    return native, python_path


def _assert_object_identical(native, python_path):
    assert len(native) == len(python_path)
    for a, b in zip(native, python_path):
        assert to_binary(a) == to_binary(b)
        assert a.clock.dots == b.clock.dots
        assert list(a.entries) == list(b.entries)  # insertion order too
        assert {k: v.dots for k, v in a.entries.items()} == {
            k: v.dots for k, v in b.entries.items()
        }
        assert a.deferred == b.deferred


def test_identity_universe_parity():
    uni = Universe.identity(
        CrdtConfig(num_actors=8, member_capacity=8, deferred_capacity=4)
    )
    rng = np.random.RandomState(0)
    states = _random_states(rng, 300, actor_of=lambda a: a, member_of=lambda m: m)
    _assert_object_identical(*_both_paths(states, uni))


def test_interned_universe_parity():
    uni = Universe(
        CrdtConfig(num_actors=8, member_capacity=8, deferred_capacity=4)
    )
    rng = np.random.RandomState(7)
    states = _random_states(
        rng, 300,
        actor_of=lambda a: f"node-{a}", member_of=lambda m: f"fruit-{m}",
    )
    _assert_object_identical(*_both_paths(states, uni))


def test_empty_and_degenerate_objects():
    uni = Universe.identity(
        CrdtConfig(num_actors=4, member_capacity=4, deferred_capacity=2)
    )
    states = [Orswot() for _ in range(5)]  # all empty
    s = Orswot()
    s.apply(s.add(1, s.value().derive_add_ctx(0)))
    states.append(s)
    native, python_path = _both_paths(states, uni)
    _assert_object_identical(native, python_path)
    assert native[0].value().val == set()
    assert native[5].value().val == {1}


def test_deferred_key_layout_matches_vclock_key():
    """The C path calls VClock.key() itself, so the deferred dict keys
    must be exactly what the scalar class produces (repr-sorted)."""
    uni = Universe.identity(
        CrdtConfig(num_actors=16, member_capacity=4, deferred_capacity=4)
    )
    s = Orswot()
    s.apply(s.add(2, s.value().derive_add_ctx(1)))
    ctx = s.contains(2).derive_rm_ctx()
    # multi-actor clock where repr order (10 < 2 lexicographically)
    # differs from numeric order
    ctx.clock.witness(10, 500)
    ctx.clock.witness(2, 600)
    s.apply(s.remove(2, ctx))
    assert s.deferred
    native, python_path = _both_paths([s], uni)
    _assert_object_identical(native, python_path)
    assert list(native[0].deferred) == list(python_path[0].deferred)
