"""Importing crdt_tpu (scalar engine) must not mutate global JAX config.

Note: this environment preloads jax into every interpreter (axon site hook),
so we can't assert jax is absent from sys.modules — instead assert that the
import leaves ``jax_enable_x64`` untouched.  x64 is flipped lazily by the
batch/ops/parallel modules via :func:`crdt_tpu.config.enable_x64`.
"""

import subprocess
import sys


def test_import_does_not_flip_x64():
    code = (
        "import crdt_tpu\n"
        "import jax\n"
        "assert not jax.config.jax_enable_x64, 'import crdt_tpu flipped x64'\n"
        "import crdt_tpu.config as c\n"
        "c.enable_x64()\n"
        "assert jax.config.jax_enable_x64, 'enable_x64() did not flip x64'\n"
    )
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
