"""ISSUE 17 acceptance — live mixed reads+writes on a lossy gossip
fleet.

A 3-node queue-pair gossip mesh under 20% frame loss + delay-reorder
serves reads through :mod:`crdt_tpu.serve` WHILE writes land and
anti-entropy runs.  The pins:

* read-your-writes is NEVER violated for an acknowledged write — every
  admitted ryw read at the writer's ack floor (``write_vv``) sees the
  written member;
* monotonic-read tokens never regress per node, across the whole run;
* every frontier-stable row is ≤ the PR 15 stability frontier —
  audited EXTERNALLY against the tracker's subtree clocks, not trusted
  from the serve path's own stamp — and at quiescence (frontier ==
  fleet VV min) a frontier-mode read returns every row stable;
* the always-on lattice auditor records zero violations.
"""

import itertools
import threading

import numpy as np
import pytest

from crdt_tpu import serve
from crdt_tpu.batch import OrswotBatch
from crdt_tpu.cluster import (
    ClusterNode,
    FaultPlan,
    FaultyTransport,
    GossipScheduler,
    Membership,
    ResilientTransport,
    RetryPolicy,
    queue_pair,
)
from crdt_tpu.config import CrdtConfig
from crdt_tpu.error import ConsistencyUnavailableError, PeerUnavailableError
from crdt_tpu.obs.stability import subtree_layout
from crdt_tpu.oplog import OpLog
from crdt_tpu.scalar.orswot import Orswot
from crdt_tpu.sync import digest as sync_digest
from crdt_tpu.utils import tracing
from crdt_tpu.utils.interning import Universe

pytestmark = pytest.mark.serve

FAST = RetryPolicy(send_deadline_s=3.0, recv_deadline_s=3.0,
                   ack_timeout_s=0.05, max_backoff_s=0.3,
                   retry_budget=400)


def _uni():
    return Universe.identity(CrdtConfig(
        num_actors=8, member_capacity=24, deferred_capacity=4,
        counter_bits=32))


def _base_fleet(n, seed):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        s = Orswot()
        for _ in range(rng.randint(1, 4)):
            s.apply(s.add(int(rng.randint(0, 50)),
                          s.value().derive_add_ctx(0)))
        out.append(s)
    return out


def _pad(v, width):
    v = np.asarray(v, np.uint64).reshape(-1)
    if v.size < width:
        v = np.concatenate([v, np.zeros(width - v.size, np.uint64)])
    return v


def _dominates(a, b):
    width = max(len(a), len(b))
    return bool((_pad(a, width) >= _pad(b, width)).all())


def _faulty_mesh(nodes, loss=0.20, delay=0.15):
    """The test_stability queue-pair mesh: seeded loss + delay-reorder
    on every link."""
    seeds = itertools.count(7000)

    def make_dialer(i):
        def dial(peer):
            j = int(peer.peer_id[1:])
            if nodes[j] is None:
                raise PeerUnavailableError(f"n{j} is down")
            s = next(seeds)
            ta, tb = queue_pair(default_timeout=10.0)
            fa = FaultyTransport(
                ta, FaultPlan(seed=s, drop=loss, delay=delay),
                name=f"n{i}->n{j}")
            fb = FaultyTransport(
                tb, FaultPlan(seed=s + 1, drop=loss, delay=delay),
                name=f"n{j}->n{i}")
            ra = ResilientTransport(fa, FAST, name=f"n{i}->n{j}",
                                    seed=s + 2)
            rb = ResilientTransport(fb, FAST, name=f"n{j}->n{i}",
                                    seed=s + 3)

            def serve_peer(target=nodes[j], label=f"n{i}"):
                try:
                    target.accept(rb, peer_id=label)
                except Exception:
                    pass
                finally:
                    rb.close()

            threading.Thread(target=serve_peer, daemon=True).start()
            return ra
        return dial

    scheds = []
    for i, node in enumerate(nodes):
        m = Membership(suspect_after=2, dead_after=5)
        for j in range(len(nodes)):
            if j != i:
                m.add(f"n{j}")
        scheds.append(GossipScheduler(
            node, m, make_dialer(i), fanout=2,
            session_timeout_s=60.0, seed=i))
    return scheds


def _audit_frontier_rows(node, frame):
    """External ≤-frontier audit: every row the serve path stamped
    ST_OK must be dominated by its subtree's frontier clock as the
    STABILITY TRACKER publishes it (clocks only grow, so auditing
    after the fact can only be stricter)."""
    subs = node.stability.subtree_frontier_clocks()
    assert subs is not None, \
        "frontier rows stamped OK with no published subtree clocks"
    n = int(node.batch.clock.shape[0])
    _, span = subtree_layout(n)
    audited = 0
    for i in range(len(frame)):
        if int(frame.status[i]) != serve.ST_OK:
            continue
        sub = min(int(frame.obj[i]) // span, subs.shape[0] - 1)
        assert _dominates(subs[sub], frame.add_clock[i]), (
            f"{node.node_id}: frontier-stable row obj={int(frame.obj[i])} "
            f"clock {frame.add_clock[i].tolist()} exceeds subtree {sub} "
            f"frontier {np.asarray(subs[sub]).tolist()}"
        )
        audited += 1
    return audited


def test_acceptance_live_reads_on_lossy_fleet():
    audit_before = tracing.counters().get("stability.audit.violations", 0)
    uni = _uni()
    n_nodes, n_objects = 3, 32
    base = _base_fleet(n_objects, seed=171)
    nodes = [
        ClusterNode(f"n{i}", OrswotBatch.from_scalar(base, uni), uni,
                    busy_timeout_s=5.0, oplog=OpLog(uni))
        for i in range(n_nodes)
    ]
    scheds = _faulty_mesh(nodes)
    loops = [serve.ServeLoop(node, park_timeout_s=10.0) for node in nodes]
    rosters = [[f"n{j}" for j in range(n_nodes) if j != i]
               for i in range(n_nodes)]
    rng = np.random.RandomState(1717)

    tokens = [loops[i].token() for i in range(n_nodes)]
    ryw_checked = frontier_rows_audited = 0

    for sweep in range(5):
        for i, node in enumerate(nodes):
            # live writes, then the ryw probe at the ack floor
            node.submit_writes(
                rng.randint(0, n_objects, 3),
                rng.randint(200, 212, 3).astype(np.int32), actor=i + 1)
            probe_obj = np.array([int(rng.randint(0, n_objects))])
            probe_member = np.array([220 + i], np.int32)
            node.submit_writes(probe_obj, probe_member, actor=i + 1)
            ack = node.write_vv()
            frame = loops[i].serve(serve.ReadRequest.reads(
                probe_obj, member=probe_member, mode="ryw", require=ack))
            assert int(frame.val[0]) == 1, (
                f"{node.node_id} sweep {sweep}: read-your-writes "
                f"VIOLATED for acknowledged member {int(probe_member[0])}"
            )
            assert serve.covers(frame.token, ack)
            ryw_checked += 1

            # monotonic: the returned token may never regress
            frame = loops[i].serve(serve.ReadRequest.reads(
                rng.randint(0, n_objects, 8), mode="monotonic",
                require=tokens[i]))
            assert np.all(frame.token >= tokens[i]), (
                f"{node.node_id} sweep {sweep}: monotonic token "
                f"REGRESSED {tokens[i].tolist()} -> "
                f"{frame.token.tolist()}"
            )
            tokens[i] = frame.token

            # frontier-stable: externally audited row-for-row
            node.stability.frontier(node.batch, peers=rosters[i])
            try:
                frame = loops[i].serve(serve.ReadRequest.reads(
                    rng.randint(0, n_objects, 8), mode="frontier"))
                frontier_rows_audited += _audit_frontier_rows(node, frame)
            except ConsistencyUnavailableError as e:
                assert e.reason == "no_frontier"

        for sched in scheds:
            sched.run_round()

    # writes stopped: gossip to byte-identical digests
    converged = False
    for _ in range(25):
        for sched in scheds:
            sched.run_round()
        digests = [np.asarray(n.digest()) for n in nodes]
        if all(np.array_equal(digests[0], d) for d in digests[1:]):
            converged = True
            break
    assert converged, "fleet failed to converge after reads+writes"

    # publish settled frontiers; at quiescence frontier == fleet VV min
    target = np.asarray(sync_digest.version_vector(nodes[0].batch),
                        np.uint64)
    settled = False
    for _ in range(10):
        reps = [nodes[i].stability.frontier(nodes[i].batch,
                                            peers=rosters[i])
                for i in range(n_nodes)]
        if all(r is not None and np.array_equal(
                np.asarray(r.clock, np.uint64), target) for r in reps):
            settled = True
            break
        for sched in scheds:
            sched.run_round()
    assert settled, "stability frontier never settled at quiescence"

    # ... and a frontier-mode read now returns EVERY row stable
    for i, node in enumerate(nodes):
        frame = loops[i].serve(serve.ReadRequest.reads(
            np.arange(n_objects), mode="frontier"))
        assert bool((frame.status == serve.ST_OK).all()), (
            f"{node.node_id}: unstable rows under a settled frontier"
        )
        frontier_rows_audited += _audit_frontier_rows(node, frame)

    assert ryw_checked == 5 * n_nodes
    assert frontier_rows_audited > 0
    assert tracing.counters().get("stability.audit.violations", 0) \
        == audit_before, "lattice auditor recorded violations"
