"""Op-based write front-end tests — columnar op log, batched causal
contexts, scatter-fold apply, op-frame codec (crdt_tpu.oplog).

The acceptance bar (ISSUE 7): a 5-node gossip fleet ingesting >=10k
live ops — injected mid-round, over links dropping 20% of frames with
duplicated and reordered delivery, with op batches themselves
re-delivered to second nodes — converges to byte-identical digest
vectors, and the digest oracle confirms a PURE op-based replica (base
state + every op applied through the scatter-fold, no sync at all)
agrees with the state-replicated fleet.  Everything else pins the
pieces: the batched ``derive_add_ctx`` matching the scalar
clone-and-increment loop dot-for-dot (`ctx.rs:45-53`), idempotence
under duplicate/reordered/delayed op delivery (the CmRDT contract),
causal-gap park/release, and the codec's loud-rejection matrix.
"""

import threading

import numpy as np
import pytest

import jax.numpy as jnp

from crdt_tpu.batch import OrswotBatch
from crdt_tpu.batch.gcounter_batch import GCounterBatch
from crdt_tpu.batch.lwwreg_batch import LWWRegBatch
from crdt_tpu.batch.wireloop import PipelinedOpLoop
from crdt_tpu.cluster import (
    ClusterNode,
    FaultPlan,
    FaultyTransport,
    GossipScheduler,
    Membership,
    ResilientTransport,
    RetryPolicy,
    queue_pair,
)
from crdt_tpu.config import CrdtConfig
from crdt_tpu.error import (
    ConflictingMarker,
    OpLogOverflowError,
    SyncProtocolError,
    WireFormatError,
)
from crdt_tpu.oplog import (
    NO_MEMBER,
    OP_ADD,
    OP_INC,
    OP_RM,
    OP_SET,
    OpApplier,
    OpBatch,
    OpLog,
    apply_gcounter_ops,
    apply_lww_ops,
    decode_ops_frame,
    derive_add_ctx,
    derive_rm_ctx,
    encode_ops_frame,
)
from crdt_tpu.oplog.wire import OPLOG_PROTOCOL_VERSION
from crdt_tpu.scalar.ctx import sequential_add_ctxs
from crdt_tpu.scalar.orswot import Orswot
from crdt_tpu.scalar.vclock import VClock
from crdt_tpu.sync import digest as digest_mod
from crdt_tpu.utils import tracing
from crdt_tpu.utils.interning import Universe

pytestmark = pytest.mark.oplog

FAST = RetryPolicy(send_deadline_s=3.0, recv_deadline_s=3.0,
                   ack_timeout_s=0.05, max_backoff_s=0.3,
                   retry_budget=400)


def _uni(**kw):
    cfg = dict(num_actors=8, member_capacity=16, deferred_capacity=4,
               counter_bits=32)
    cfg.update(kw)
    return Universe.identity(CrdtConfig(**cfg))


def _base_fleet(n, seed, uni, members=12):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        s = Orswot()
        for _ in range(rng.randint(1, 5)):
            s.apply(s.add(int(rng.randint(0, members)),
                          s.value().derive_add_ctx(0)))
        out.append(s)
    return OrswotBatch.from_scalar(out, uni), out


def _digest(batch):
    return np.asarray(digest_mod.digest_of(batch), dtype=np.uint64)


# ---- batched derive_add_ctx vs the scalar loop -----------------------------


def test_derive_add_ctx_matches_scalar_loop():
    """The parity pin (`ctx.rs:45-53`): the batched derive must assign
    exactly the dot sequence the scalar clone-and-increment loop would
    — interleaved actors on one object, fresh-actor bootstrap from the
    implied 0, and multiple writes per (object, actor) — and the full
    AddCtx clocks must match too.  Seeded sweep; no hypothesis
    dependency."""
    rng = np.random.RandomState(11)
    for case in range(25):
        n, a = int(rng.randint(1, 12)), int(rng.randint(2, 7))
        b = int(rng.randint(1, 64))
        # random base clocks, with some all-zero objects (fresh actors)
        base = rng.randint(0, 9, size=(n, a)).astype(np.uint64)
        base[rng.rand(n) < 0.3] = 0
        obj = rng.randint(0, n, b)
        actor = rng.randint(0, a, b)
        ops, ctx = derive_add_ctx(base, obj, actor,
                                  member=rng.randint(0, 50, b))
        for o in range(n):
            rows = np.nonzero(obj == o)[0]
            if not rows.size:
                continue
            vc = VClock({i: int(base[o, i]) for i in range(a)
                         if base[o, i]})
            oracle = sequential_add_ctxs(vc, [int(actor[r]) for r in rows])
            for r, want in zip(rows, oracle):
                assert int(ops.counter[r]) == want.dot.counter, (
                    f"case {case}: dot counter diverged at write {r}"
                )
                want_clock = np.zeros(a, np.uint64)
                for act, cnt in want.clock.dots.items():
                    want_clock[act] = cnt
                assert np.array_equal(ctx[r], want_clock), (
                    f"case {case}: AddCtx clock diverged at write {r}"
                )


def test_derive_rm_ctx_gathers_current_clock():
    uni = _uni()
    batch, _ = _base_fleet(6, 3, uni)
    ops = derive_rm_ctx(np.asarray(batch.clock), [1, 4], [0, 0])
    assert np.array_equal(ops.rm_clocks[0], np.asarray(batch.clock)[1])
    assert np.array_equal(ops.rm_clocks[1], np.asarray(batch.clock)[4])
    assert (ops.kind == OP_RM).all() and (ops.counter == 0).all()


def test_derive_rejects_bad_inputs():
    base = np.zeros((4, 4), np.uint64)
    with pytest.raises(ValueError, match="outside the universe"):
        derive_add_ctx(base, [0], [7])
    with pytest.raises(ValueError, match="removes derive a clock"):
        derive_add_ctx(base, [0], [0], kind=OP_RM)
    with pytest.raises(ValueError, match="shape mismatch"):
        derive_rm_ctx(base, [0, 1], [5])


# ---- scatter-fold apply: parity, idempotence, commutativity ----------------


def test_apply_ops_matches_scalar_apply_loop():
    """Folding a mixed add/remove batch through the scatter kernels
    digest-matches the scalar engine applying the same ops one at a
    time (`orswot.rs:60-83`)."""
    uni = _uni()
    rng = np.random.RandomState(5)
    batch, scal = _base_fleet(24, 5, uni)
    b = 120
    obj = rng.randint(0, 24, b)
    actor = rng.randint(0, 8, b)
    member = rng.randint(0, 12, b)
    ops, _ = derive_add_ctx(np.asarray(batch.clock), obj, actor,
                            member=member)
    folded, rep = OpApplier(uni).apply_ops(batch, ops)
    assert rep.applied_adds == b and rep.merge_steps == 1
    for r in range(b):
        s = scal[int(obj[r])]
        s.apply(s.add(int(member[r]),
                      s.value().derive_add_ctx(int(actor[r]))))
    assert np.array_equal(
        _digest(folded), _digest(OrswotBatch.from_scalar(scal, uni)))

    # removes: two per object on a few objects -> round-scheduled kernel
    robj = np.asarray([0, 0, 3, 3, 7], np.int64)
    rmem = []
    for i, o in enumerate(robj):
        vals = sorted(folded.value_sets(uni)[int(o)])
        rmem.append(vals[i % len(vals)])
    rops = derive_rm_ctx(np.asarray(folded.clock), robj,
                         np.asarray(rmem, np.int32))
    folded2, rep2 = OpApplier(uni).apply_ops(folded, rops)
    assert rep2.applied_rms == 5 and rep2.rm_rounds == 2
    for o, m in zip(robj, rmem):
        s = scal[int(o)]
        if int(m) in s.value().val:
            s.apply(s.remove(int(m), s.contains(int(m)).derive_rm_ctx()))
    assert np.array_equal(
        _digest(folded2), _digest(OrswotBatch.from_scalar(scal, uni)))


def test_redelivery_idempotence_under_fault_schedules():
    """THE CmRDT contract under the cluster's own fault injector:
    op frames shipped through a FaultyTransport that duplicates and
    delay-reorders (no loss — delivery, not transport, is under test)
    and applied in ARRIVAL order must land the fleet on the digest of
    one clean in-order apply; duplicated frames are pure no-ops after
    first apply."""
    uni = _uni()
    rng = np.random.RandomState(9)
    base, _ = _base_fleet(32, 9, uni)
    clock = np.asarray(base.clock).copy()
    frames = []
    for _ in range(12):
        b = int(rng.randint(4, 24))
        ops, _ = derive_add_ctx(clock, rng.randint(0, 32, b),
                                rng.randint(0, 8, b),
                                member=rng.randint(0, 12, b))
        np.maximum.at(clock, (ops.obj, ops.actor), ops.counter)
        frames.append(encode_ops_frame(ops))

    # reference: clean in-order apply
    ref_app = OpApplier(uni)
    ref = base
    for f in frames:
        ref, _ = ref_app.apply_ops(ref, decode_ops_frame(f))
    assert len(ref_app.parked) == 0

    # faulted delivery: duplicates + delay-reorders, deterministic seed
    from crdt_tpu.error import SyncTimeoutError

    for seed in (1, 2, 3):
        ta, tb = queue_pair(default_timeout=5.0)
        faulty = FaultyTransport(
            ta, FaultPlan(seed=seed, duplicate=0.3, delay=0.3))
        for f in frames:
            faulty.send(f)
        # a delay fault may still HOLD the last frame (flushed by the
        # next send) — resend the final frame until the injector has
        # nothing in hand; the extra copies are just more duplicates,
        # which is the point of this test
        for _ in range(3):
            faulty.send(frames[-1])
        arrived = []
        while True:
            try:
                arrived.append(tb.recv(timeout=0.2))
            except SyncTimeoutError:
                break
        assert len(arrived) > len(frames)  # duplicates arrived too
        app = OpApplier(uni)
        got_batch = base
        dup_total = 0
        for f in arrived:
            got_batch, rep = app.apply_ops(got_batch, decode_ops_frame(f))
            dup_total += rep.duplicates
        # delay can park an out-of-order dot; one empty re-check drains
        got_batch, _ = app.apply_ops(got_batch, OpBatch.empty())
        assert len(app.parked) == 0
        assert np.array_equal(_digest(got_batch), _digest(ref)), (
            f"seed {seed}: faulted delivery diverged"
        )
        if len(arrived) > len(frames):
            assert dup_total > 0, "duplicated frames applied as new ops"


def test_causal_gap_park_and_release():
    uni = _uni()
    batch = OrswotBatch.zeros(4, uni)
    app = OpApplier(uni)
    # counters 2 and 3 arrive before 1: both park (the contiguity rule
    # must not release 3 just because 2 is also pending)
    early = OpBatch(kind=[OP_ADD] * 2, obj=[1, 1], actor=[5, 5],
                    counter=[2, 3], member=[7, 8])
    batch, rep = app.apply_ops(batch, early)
    assert rep.parked == 2 and rep.applied == 0 and rep.still_parked == 2
    assert batch.value_sets(uni)[1] == set()
    # the missing predecessor closes the gap; everything releases
    fill = OpBatch(kind=[OP_ADD], obj=[1], actor=[5], counter=[1],
                   member=[6])
    batch, rep = app.apply_ops(batch, fill)
    assert rep.released == 2 and rep.applied == 3 and rep.still_parked == 0
    assert batch.value_sets(uni)[1] == {6, 7, 8}


def test_park_buffer_is_bounded():
    uni = _uni()
    app = OpApplier(uni, park_capacity=3)
    batch = OrswotBatch.zeros(2, uni)
    gapped = OpBatch(kind=[OP_ADD] * 4, obj=[0] * 4, actor=[1] * 4,
                     counter=[10, 11, 12, 13], member=[1, 2, 3, 4])
    with pytest.raises(OpLogOverflowError, match="park_capacity"):
        app.apply_ops(batch, gapped)


def test_oplog_bounds_and_watermark():
    uni = _uni()
    log = OpLog(uni, capacity=10)
    ops = OpBatch(kind=[OP_ADD] * 6, obj=[0] * 6, actor=[2] * 6,
                  counter=[1, 2, 3, 4, 5, 6], member=[0] * 6)
    log.append(ops)
    assert len(log) == 6 and int(log.watermark[2]) == 6
    with pytest.raises(OpLogOverflowError, match="capacity"):
        log.append(ops)
    drained = log.drain()
    assert len(drained) == 6 and len(log) == 0
    assert int(log.watermark[2]) == 6  # high-watermark survives drains


# ---- the op-frame codec ----------------------------------------------------


def test_ops_frame_roundtrip():
    uni = _uni()
    rng = np.random.RandomState(21)
    base, _ = _base_fleet(16, 21, uni)
    adds, _ = derive_add_ctx(np.asarray(base.clock),
                             rng.randint(0, 16, 40),
                             rng.randint(0, 8, 40),
                             member=rng.randint(0, 12, 40))
    rms = derive_rm_ctx(np.asarray(base.clock), [2, 9], [0, 1])
    ops = OpBatch.concat([adds, rms])
    frame = encode_ops_frame(ops)
    back = decode_ops_frame(frame, num_actors=8)
    for col in ("kind", "obj", "actor", "counter", "member"):
        assert np.array_equal(getattr(back, col), getattr(ops, col)), col
    assert np.array_equal(back.rm_clocks, ops.rm_clocks)
    # an op is a few dozen bytes, not a state blob
    assert len(frame) / len(ops) < 50


def test_ops_frame_rejection_matrix():
    """Every malformed-frame class rejects loudly with the typed error
    AND leaves its reason counter — never a misparse, never a bare
    ValueError."""
    ops = OpBatch(kind=[OP_ADD], obj=[0], actor=[1], counter=[1],
                  member=[3])
    frame = bytearray(encode_ops_frame(ops))

    before = tracing.counters()
    cases = []

    with pytest.raises(SyncProtocolError, match="truncated"):
        decode_ops_frame(bytes(frame[:6]))
    cases.append("truncated")

    wrong_ver = bytearray(frame)
    wrong_ver[0] = OPLOG_PROTOCOL_VERSION + 1
    with pytest.raises(SyncProtocolError, match="version"):
        decode_ops_frame(bytes(wrong_ver))
    cases.append("version_mismatch")

    wrong_type = bytearray(frame)
    wrong_type[1] = 0x7F
    with pytest.raises(SyncProtocolError, match="unknown op frame type"):
        decode_ops_frame(bytes(wrong_type))
    cases.append("unknown_type")

    with pytest.raises(SyncProtocolError, match="length mismatch"):
        decode_ops_frame(bytes(frame[:-3]))
    cases.append("length_mismatch")

    tampered = bytearray(frame)
    tampered[-1] ^= 0xFF
    with pytest.raises(SyncProtocolError, match="CRC"):
        decode_ops_frame(bytes(tampered))
    cases.append("crc_mismatch")

    deltas = tracing.counters_since(before)
    for reason in cases:
        assert deltas.get(f"oplog.frames.rejected.{reason}", 0) >= 1, reason

    # payload-grammar faults are WireFormatError (decode-path contract)
    bad_kind = OpBatch(kind=[OP_ADD], obj=[0], actor=[0], counter=[1],
                       member=[0])
    bk_frame = bytearray(encode_ops_frame(bad_kind))
    # kind column is the first payload byte after the 14B header + 6B
    # column header
    bk_frame[20] = 99
    import struct
    import zlib
    payload = bytes(bk_frame[14:])
    struct.pack_into("<I", bk_frame, 2, zlib.crc32(payload))
    with pytest.raises(WireFormatError, match="unknown kind"):
        decode_ops_frame(bytes(bk_frame))

    with pytest.raises(WireFormatError, match="outside the receiving"):
        decode_ops_frame(encode_ops_frame(OpBatch(
            kind=[OP_ADD], obj=[0], actor=[7], counter=[1], member=[0],
        )), num_actors=4)

    # clock triples may only name remove rows
    sneaky = OpBatch(kind=[OP_ADD], obj=[0], actor=[0], counter=[1],
                     member=[0],
                     rm_clocks=np.ones((1, 4), np.uint64))
    with pytest.raises(WireFormatError, match="non-remove"):
        decode_ops_frame(encode_ops_frame(sneaky))


def test_ops_frame_empty_is_valid():
    back = decode_ops_frame(encode_ops_frame(OpBatch.empty()))
    assert len(back) == 0


# ---- counter / LWW scatter folds -------------------------------------------


def test_counter_and_lww_op_folds():
    uni = _uni()
    g = GCounterBatch.zeros(3, uni)
    ops, _ = derive_add_ctx(np.asarray(g.clocks), [0, 0, 1], [2, 2, 3],
                            kind=OP_INC)
    assert (ops.member == NO_MEMBER).all()
    g2 = apply_gcounter_ops(g, ops)
    assert list(np.asarray(g2.value())[:2]) == [2, 1]
    # redelivery and reorder both absorb into max
    g3 = apply_gcounter_ops(g2, ops.select(np.asarray([2, 0, 1])))
    assert np.array_equal(np.asarray(g3.value()), np.asarray(g2.value()))

    lww = LWWRegBatch(vals=jnp.zeros(3, jnp.uint64),
                      markers=jnp.zeros(3, jnp.uint64))
    sets = OpBatch(kind=[OP_SET] * 3, obj=[0, 0, 2], actor=[0] * 3,
                   counter=[4, 9, 2], member=[10, 20, 30])
    l2 = apply_lww_ops(lww, sets)
    assert int(np.asarray(l2.vals)[0]) == 20
    with pytest.raises(ConflictingMarker):
        apply_lww_ops(l2, OpBatch(kind=[OP_SET], obj=[0], actor=[0],
                                  counter=[9], member=[55]))
    _, conflict = apply_lww_ops(
        l2, OpBatch(kind=[OP_SET], obj=[0], actor=[0], counter=[9],
                    member=[55]), check=False)
    assert conflict[0] and not conflict[1:].any()


# ---- pipelined op ingest ---------------------------------------------------


def test_pipelined_op_loop_overlap_parity():
    """The staging-pool/decode-fold overlap path produces exactly the
    serial result, and both match a plain OpApplier fold."""
    uni = _uni()
    rng = np.random.RandomState(31)
    base, _ = _base_fleet(40, 31, uni)
    clock = np.asarray(base.clock).copy()
    frames = []
    for _ in range(8):
        b = int(rng.randint(8, 40))
        ops, _ = derive_add_ctx(clock, rng.randint(0, 40, b),
                                rng.randint(0, 8, b),
                                member=rng.randint(0, 12, b))
        np.maximum.at(clock, (ops.obj, ops.actor), ops.counter)
        frames.append(encode_ops_frame(ops))
    over, st_over = PipelinedOpLoop(uni).run(base, frames, overlap=True)
    serial, st_serial = PipelinedOpLoop(uni).run(base, frames,
                                                overlap=False)
    assert st_over["pipeline"] == "overlapped"
    assert st_over["ops"] == st_serial["ops"] > 0
    assert np.array_equal(_digest(over), _digest(serial))
    ref = base
    app = OpApplier(uni)
    for f in frames:
        ref, _ = app.apply_ops(ref, decode_ops_frame(f))
    assert np.array_equal(_digest(over), _digest(ref))


# ---- session piggyback + ClusterNode.submit_ops ----------------------------


def _sync_nodes(a, b, timeout=15.0):
    ta, tb = queue_pair(default_timeout=timeout)
    err = []

    def accept():
        try:
            b.accept(tb, peer_id=a.node_id)
        except BaseException as e:  # surfaced via the initiator assert
            err.append(e)

    t = threading.Thread(target=accept, daemon=True)
    t.start()
    rep = a.sync_with(b.node_id, ta)
    t.join(timeout)
    assert not err, err
    return rep


def test_submit_ops_idle_node_folds_immediately():
    uni = _uni()
    base, _ = _base_fleet(16, 41, uni)
    node = ClusterNode("w", base, uni)
    pending = node.submit_writes([3, 3, 5], [9, 10, 9], actor=2)
    assert pending == 0
    assert {9, 10} <= node.batch.value_sets(uni)[3]
    assert 9 in node.batch.value_sets(uni)[5]


def test_mid_session_writes_queue_then_piggyback_and_drain():
    """A write submitted while the node is mid-session must (a) never
    be lost, (b) queue rather than block, (c) ship to the session peer
    in the SAME session via the ops piggyback, and (d) fold locally at
    the session tail."""
    uni = _uni()
    base, _ = _base_fleet(16, 43, uni)
    a = ClusterNode("a", base, uni, oplog=OpLog(uni))
    b = ClusterNode("b", base, uni, oplog=OpLog(uni))
    # simulate "mid-session": hold the busy lock while writing
    a._busy.acquire()
    try:
        pending = a.submit_writes([1, 2], [11, 11], actor=3)
        assert pending == 2, "mid-session write should queue, not fold"
    finally:
        a._busy.release()
    rep = _sync_nodes(a, b)
    assert rep.ops_sent == 2, rep
    assert rep.converged
    # both sides hold the write now; digests agree including it
    assert 11 in a.batch.value_sets(uni)[1]
    assert 11 in b.batch.value_sets(uni)[1]
    assert np.array_equal(np.asarray(a.digest()), np.asarray(b.digest()))
    assert len(a._oplog) == 0


def test_submit_ops_accepts_wire_frames():
    uni = _uni()
    base, _ = _base_fleet(8, 47, uni)
    node = ClusterNode("w", base, uni)
    ops, _ = derive_add_ctx(np.asarray(base.clock), [0], [1], member=[7])
    assert node.submit_ops(encode_ops_frame(ops)) == 0
    assert 7 in node.batch.value_sets(uni)[0]
    with pytest.raises(TypeError, match="OpBatch"):
        node.submit_ops([1, 2, 3])


def test_write_clock_covers_queued_dots():
    """Minting against a busy node must see queued ops' dots — dot
    reuse is the one-shot dot contract violation (`error.rs:9-13`)."""
    uni = _uni()
    base, _ = _base_fleet(8, 53, uni)
    node = ClusterNode("w", base, uni)
    node._busy.acquire()
    try:
        node.submit_writes([0], [1], actor=4)
        node.submit_writes([0], [2], actor=4)
        log = node._oplog.pending()
        assert sorted(int(c) for c in log.counter) == [1, 2], (
            "second mint reused the first's dot"
        )
    finally:
        node._busy.release()
    node.submit_ops(OpBatch.empty())  # no-op submit drains the queue
    assert {1, 2} <= node.batch.value_sets(uni)[0]


# ---- THE acceptance run ----------------------------------------------------


def _op_fleet(n_nodes, n_objects, uni, *, loss, dup, delay):
    """N in-process replicas of the SAME base fleet over fault-injected
    queue links (test_cluster's harness shape), all with the op
    front-end armed."""
    base_planes, _ = _base_fleet(n_objects, seed=71, uni=uni, members=10)
    nodes = [
        ClusterNode(f"n{i}", base_planes, uni, busy_timeout_s=5.0,
                    oplog=OpLog(uni, capacity=1 << 18))
        for i in range(n_nodes)
    ]
    seeds = iter(range(5000, 9000))

    def make_dialer(i):
        def dial(peer):
            j = int(peer.peer_id[1:])
            s = next(seeds)
            ta, tb = queue_pair(default_timeout=10.0)
            plan = FaultPlan(seed=s, drop=loss, duplicate=dup, delay=delay)
            plan_b = FaultPlan(seed=s + 1, drop=loss, duplicate=dup,
                               delay=delay)
            fa = FaultyTransport(ta, plan, name=f"n{i}->n{j}")
            fb = FaultyTransport(tb, plan_b, name=f"n{j}->n{i}")
            ra = ResilientTransport(fa, FAST, name=f"n{i}->n{j}", seed=s + 2)
            rb = ResilientTransport(fb, FAST, name=f"n{j}->n{i}", seed=s + 3)

            def serve():
                try:
                    nodes[j].accept(rb, peer_id=f"n{i}")
                except Exception:
                    pass
                finally:
                    rb.close()

            threading.Thread(target=serve, daemon=True).start()
            return ra
        return dial

    scheds = []
    for i in range(n_nodes):
        m = Membership(suspect_after=3, dead_after=6)
        for j in range(n_nodes):
            if j != i:
                m.add(f"n{j}")
        scheds.append(GossipScheduler(
            nodes[i], m, make_dialer(i), fanout=2,
            session_timeout_s=60.0, seed=i,
        ))
    return nodes, scheds


def test_acceptance_mixed_op_state_fleet_convergence():
    """ISSUE 7's acceptance bar: a 5-node gossip fleet ingests >=10k
    live ops — injected mid-round through submit_writes, with a third
    of the batches RE-delivered to a second random node (op-level
    duplication + out-of-order arrival, on top of 20% frame loss with
    duplicate/delay-reorder links) — and after writes stop the fleet
    converges to byte-identical digest vectors that match a PURE
    op-based replica folding the same ops with no sync at all."""
    uni = _uni(num_actors=8, member_capacity=32)
    n_objects = 128
    nodes, scheds = _op_fleet(5, n_objects, uni,
                              loss=0.20, dup=0.03, delay=0.03)
    rng = np.random.RandomState(2024)
    streams = {i: [] for i in range(5)}  # per-node op batches, in order
    total = 0

    def write_burst(count):
        """Mint `count` writes spread over random nodes, recording each
        minted batch for the oracle (minting under the node's own mint
        lock, exactly what submit_writes does, but keeping the OpBatch
        so the oracle can replay it).  A third of the batches are ALSO
        delivered to a second random node as a wire frame — op-level
        duplication, out of causal order for that node until state sync
        catches it up (the parked-gap path in the wild)."""
        nonlocal total
        per_node = np.bincount(rng.randint(0, 5, count), minlength=5)
        for i, cnt in enumerate(per_node):
            if not cnt:
                continue
            node = nodes[i]
            with node._mint:
                ops, _ = derive_add_ctx(
                    node.write_clock(), rng.randint(0, n_objects, cnt),
                    np.full(cnt, i + 1, np.int32),
                    member=rng.randint(100, 112, cnt).astype(np.int32))
                node.submit_ops(ops)
            streams[i].append(ops)
            total += cnt
            if rng.rand() < 0.33:
                nodes[int(rng.randint(0, 5))].submit_ops(
                    encode_ops_frame(ops))

    write_sweeps = 4
    sweeps = 0
    converged = False
    for sweeps in range(1, 30):
        writing = sweeps <= write_sweeps
        if writing:
            write_burst(2600)
        for sched in scheds:
            if writing:
                write_burst(120)
            sched.run_round()
        digests = [np.asarray(n.digest()) for n in nodes]
        converged = all(np.array_equal(digests[0], d)
                        for d in digests[1:])
        if converged and not writing:
            break
    assert total >= 10_000, f"only {total} ops injected"
    assert converged, "fleet failed to converge after writes stopped"
    for d in [np.asarray(n.digest()) for n in nodes][1:]:
        assert digests[0].tobytes() == d.tobytes()

    # every queued/parked op drained
    for node in nodes:
        assert len(node._oplog) == 0
        assert len(node._applier.parked) == 0

    # THE digest oracle: a pure op-based replica — base state + every
    # node's op stream folded through the scatter kernel, no sync ever
    # — must agree byte-for-byte with the state-replicated fleet
    base_planes, _ = _base_fleet(n_objects, seed=71, uni=uni, members=10)
    ref = base_planes
    app = OpApplier(uni)
    for i in range(5):
        for ops in streams[i]:
            ref, _ = app.apply_ops(ref, ops)
    assert len(app.parked) == 0
    assert np.array_equal(_digest(ref), digests[0]), (
        "op-based replica disagrees with the state-replicated fleet"
    )


def test_small_mixed_op_state_fleet_convergence():
    """The tier-1-sized sibling of the acceptance run: 3 nodes, 20%
    loss, ~1.2k live ops with op-level duplication — seconds, not
    minutes, same oracle."""
    uni = _uni(num_actors=8, member_capacity=32)
    n_objects = 48
    nodes, scheds = _op_fleet(3, n_objects, uni,
                              loss=0.20, dup=0.03, delay=0.03)
    rng = np.random.RandomState(77)
    streams = []
    total = 0

    def burst(count):
        nonlocal total
        per_node = np.bincount(rng.randint(0, 3, count), minlength=3)
        for i, cnt in enumerate(per_node):
            if not cnt:
                continue
            node = nodes[i]
            with node._mint:
                ops, _ = derive_add_ctx(
                    node.write_clock(), rng.randint(0, n_objects, cnt),
                    np.full(cnt, i + 1, np.int32),
                    member=rng.randint(100, 110, cnt).astype(np.int32))
                node.submit_ops(ops)
            streams.append((i, ops))
            total += cnt
            if rng.rand() < 0.4:
                nodes[int(rng.randint(0, 3))].submit_ops(
                    encode_ops_frame(ops))

    converged = False
    for sweeps in range(1, 16):
        writing = sweeps <= 3
        if writing:
            burst(400)
        for sched in scheds:
            sched.run_round()
        digests = [np.asarray(n.digest()) for n in nodes]
        converged = all(np.array_equal(digests[0], d)
                        for d in digests[1:])
        if converged and not writing:
            break
    assert total >= 1_000 and converged, (total, converged)

    base_planes, _ = _base_fleet(n_objects, seed=71, uni=uni, members=10)
    ref = base_planes
    app = OpApplier(uni)
    by_node = {0: [], 1: [], 2: []}
    for i, ops in streams:
        by_node[i].append(ops)
    for i in range(3):
        for ops in by_node[i]:
            ref, _ = app.apply_ops(ref, ops)
    assert len(app.parked) == 0
    assert np.array_equal(_digest(ref), digests[0])
