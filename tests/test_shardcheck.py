"""shardcheck self-tests: the sharding-contract tier's repo gate, the
SC01-SC05 fixture matrix, 100% contract coverage, the stale-sanction
re-flag, and the tier-1 regression pin on un-declared manifest rows.

Like tests/test_kernelcheck.py this module imports jax (tracing under
abstract meshes is the whole point) and runs under the `analysis`
marker.
"""

import json
import os
import subprocess
import sys

import pytest

from crdt_tpu.analysis.core import Baseline, ParsedFile, repo_root
from crdt_tpu.analysis.kernels import MANIFEST, SHARD_CLASSES

pytestmark = pytest.mark.analysis

REPO = repo_root()
FIXDIR = os.path.join(REPO, "tests", "analysis_fixtures")
sys.path.insert(0, FIXDIR)


def _run_specs(specs, baseline=None):
    from crdt_tpu.analysis.shard_rules import run_shardcheck

    return run_shardcheck(specs=specs, baseline=baseline)


# ---- the repo-wide gate -----------------------------------------------------


@pytest.fixture(scope="module")
def repo_gate():
    """One subprocess run of the real CLI gate, shared by the gate
    tests: `python -m crdt_tpu.analysis --shard --json` exactly as
    scripts/ci.sh invokes it — CPU backend, no TPU required."""
    proc = subprocess.run(
        [sys.executable, "-m", "crdt_tpu.analysis", "--shard", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    return proc


def test_repo_gate_exits_zero_with_empty_baseline(repo_gate):
    """The shipped tree is contract-clean: exit 0, zero live findings,
    zero trace errors, nothing parked for the SC rules in the
    baseline (pragmas with justifications are the only sanctions)."""
    assert repo_gate.returncode == 0, repo_gate.stdout + repo_gate.stderr
    out = json.loads(repo_gate.stdout)
    assert out["ok"] is True
    assert out["findings"] == []
    assert out["shardcheck"]["trace_errors"] == []
    with open(os.path.join(REPO, "crdt_tpu", "analysis",
                           "baseline.json")) as fh:
        entries = json.load(fh)
    assert [e for e in entries if e["rule"].startswith("SC")] == []


def test_repo_gate_is_fast_and_covers_every_contract(repo_gate):
    """<60 s on CPU; every manifest row carries a contract; every
    buildable non-host_only row traced, with mesh-shaped cases; the
    provenance walker saw no unknown primitives (an unknown prim is a
    silently-unanalyzed data path)."""
    out = json.loads(repo_gate.stdout)
    sc = out["shardcheck"]
    assert sc["elapsed_s"] < 60.0, f"shardcheck took {sc['elapsed_s']}s"
    assert sc["kernels"] == len(MANIFEST)
    assert sum(sc["contracts"].values()) == len(MANIFEST)
    assert set(sc["contracts"]) <= set(SHARD_CLASSES)
    n_traceable = sum(
        1 for s in MANIFEST
        if s.build is not None and s.sharding.sclass != "host_only")
    assert sc["traced"] == n_traceable
    assert sc["cases"] > sc["traced"]          # ladders, not single traces
    assert sc["mesh_cases"] > 0                # shard-shaped re-traces ran
    assert sc["unknown_prims"] == []
    # declared-no-trace rows are reported, never silent
    assert {s["kernel"] for s in sc["skipped"]} == {
        s.name for s in MANIFEST
        if s.build is None or s.sharding.sclass == "host_only"}
    # the SC03 lexical scan actually walked the hot-path packages
    assert sc["sc03_files"] > 10


def test_every_manifest_row_declares_a_contract():
    """100% coverage asserted directly: `sharding=None` rows cannot
    ship (the kernel-manifest tier-1 rule pins the same invariant)."""
    missing = [s.name for s in MANIFEST if s.sharding is None]
    assert missing == []
    for s in MANIFEST:
        assert s.sharding.sclass in SHARD_CLASSES, s.name


def test_reduction_collective_declarations_match_traces(repo_gate):
    """The report's per-kernel lowered-collective sets agree with the
    manifest declarations — SC02 holding on the real tree, visible in
    the artifact rather than only as absence-of-findings."""
    sc = json.loads(repo_gate.stdout)["shardcheck"]
    declared = {s.name: sorted(s.sharding.collectives) for s in MANIFEST}
    for kernel, lowered in sc["collectives"].items():
        assert sorted(lowered) == declared[kernel], kernel


# ---- fixture matrix: every rule fires with the right id + anchor -----------


@pytest.fixture(scope="module")
def bad_result():
    import shard_bad

    result, report = _run_specs(shard_bad.SPECS)
    assert report.trace_errors == [], report.trace_errors
    return result


@pytest.mark.parametrize("rule,kernel", [
    ("SC01", "fixture_shard.cross_object"),
    ("SC02", "fixture_shard.undeclared_psum"),
    ("SC02", "fixture_shard.phantom_pmax"),
    ("SC04", "fixture_shard.ragged_rung"),
    ("SC05", "fixture_shard.budget_blowout"),
])
def test_bad_fixture_fails_with_rule_and_kernel_name(bad_result, rule,
                                                     kernel):
    hits = [f for f in bad_result.findings if f.rule == rule]
    assert hits, f"{rule} produced no finding"
    assert any(kernel in f.message for f in hits), (
        rule, [f.message for f in hits])
    for f in hits:
        assert f.path and f.line >= 1


def test_bad_fixture_findings_anchor_in_the_fixture(bad_result):
    """SC01 and the extra-collective SC02 anchor at the offending
    equation's source line in the fixture — the 'equation user frame'
    acceptance: a pragma ON THAT LINE is what sanctions the idiom."""
    for rule in ("SC01", "SC02"):
        hits = [f for f in bad_result.findings if f.rule == rule]
        assert any(
            f.path == "tests/analysis_fixtures/shard_bad.py" and f.line > 1
            for f in hits), (rule, [(f.path, f.line) for f in hits])


def test_sc03_fires_on_mounted_hot_path_source():
    """The lexical SC03 scan flags an int() round-trip on a jitted
    kernel's output when the source sits at a mesh hot-path rel."""
    import shard_bad

    from crdt_tpu.analysis.shard_rules import check_host_roundtrips

    pf = ParsedFile("x", "crdt_tpu/batch/_fixture_sc03.py",
                    shard_bad.SC03_BAD_SRC)
    findings = check_host_roundtrips([pf], specs=())
    assert [f.rule for f in findings] == ["SC03"]
    assert "int()" in findings[0].message
    assert findings[0].line == shard_bad.SC03_BAD_SRC.splitlines().index(
        "    return int(total)") + 1


def test_sc03_ok_twin_clean_or_pragma_suppressed():
    import shard_ok

    from crdt_tpu.analysis.shard_rules import check_host_roundtrips

    pf = ParsedFile("x", "crdt_tpu/batch/_fixture_sc03.py",
                    shard_ok.SC03_OK_SRC)
    findings = check_host_roundtrips([pf], specs=())
    # the sample-point sin fires and its pragma suppresses it — the
    # twin is analyzed, not inert
    assert [f.rule for f in findings] == ["SC03"]
    assert pf.suppressed("SC03", findings[0].line)


def test_ok_twins_suppressed_or_clean():
    import shard_ok

    result, report = _run_specs(shard_ok.SPECS)
    assert report.trace_errors == [], report.trace_errors
    assert result.findings == [], [f.render() for f in result.findings]
    # the pragma'd SC01 sin really fired and was suppressed in the
    # fixture file — not inert
    fixture_sup = [f for f in result.suppressed
                   if f.path == "tests/analysis_fixtures/shard_ok.py"]
    assert {f.rule for f in fixture_sup} == {"SC01"}
    assert result.stale_baseline == []


def test_routed_gather_is_sanctioned_only_when_declared():
    """The same gather flips SC01 on/off with the `routed` declaration
    — the exemption is the contract, not walker blindness."""
    import dataclasses

    import shard_ok

    spec = next(s for s in shard_ok.SPECS
                if s.name == "fixture_shard.routed_gather")
    undeclared = dataclasses.replace(
        spec, sharding=dataclasses.replace(spec.sharding, routed=()))
    result, _ = _run_specs([undeclared])
    assert any(f.rule == "SC01" for f in result.findings), [
        f.render() for f in result.findings]


def test_baseline_parks_a_contract_finding():
    """The shared baseline machinery covers SC findings (justification
    required by the Baseline schema, same as the other tiers)."""
    import shard_bad

    spec = [s for s in shard_bad.SPECS
            if s.name == "fixture_shard.phantom_pmax"]
    baseline = Baseline([{
        "rule": "SC02",
        "path": "tests/analysis_fixtures/shard_bad.py",
        "message": "kernel fixture_shard.phantom_pmax: declares*",
        "justification": "fixture: demonstrates baseline parking for "
                         "site-anchored contract findings",
    }])
    result, _ = _run_specs(spec, baseline=baseline)
    assert result.findings == [], [f.render() for f in result.findings]
    assert [f.rule for f in result.baselined] == ["SC02"]


def test_stale_sc_sanction_reflagged_when_contract_traces_clean(
        monkeypatch):
    """A pragma sanctioning SC01 on a kernel that now traces clean is
    itself a live finding (the KC01 stale-sanction discipline): fix
    the sin in the pragma'd fixture kernel and the suppression re-arms
    as 'stale SC01 sanction'."""
    import shard_ok

    # keep the pragma'd file, but swap the kernel body for a clean one
    def _b_clean():
        import jax  # noqa: F401

        def center(x):
            return x * 2

        from crdt_tpu.analysis.kernels import TraceCase
        return [TraceCase("r0", center, shard_ok._b_pragma_sum()[0].args)]

    import dataclasses

    spec = next(s for s in shard_ok.SPECS
                if s.name == "fixture_shard.pragma_sum")
    clean = dataclasses.replace(spec, build=_b_clean)
    result, _ = _run_specs([clean])
    stale = [f for f in result.findings
             if f.rule == "SC01" and "stale SC01 sanction" in f.message
             and f.path == "tests/analysis_fixtures/shard_ok.py"]
    assert stale, [f.render() for f in result.findings]


# ---- the tier-1 regression pin ---------------------------------------------


def test_undeclared_manifest_row_fails_source_lint(monkeypatch):
    """Un-declaring any manifest row's sharding contract fails the
    tier-1 kernel-manifest rule — contract coverage can never silently
    regress below 100%."""
    import dataclasses

    import crdt_tpu.analysis.kernels as kernels
    from crdt_tpu.analysis import run_lint

    stripped = (dataclasses.replace(MANIFEST[0], sharding=None),
                ) + tuple(MANIFEST[1:])
    monkeypatch.setattr(kernels, "MANIFEST", stripped)
    pf = ParsedFile("x", "crdt_tpu/batch/_none.py", "import jax\n")
    result = run_lint([pf], only_rules=["kernel-manifest"])
    hits = [f for f in result.findings
            if "declares no sharding contract" in f.message]
    assert hits and MANIFEST[0].name in hits[0].message, [
        f.render() for f in result.findings]


def test_malformed_contract_fails_source_lint(monkeypatch):
    """A collective-carrying pointwise contract is malformed at the
    source tier (collectives belong to reduction rows only)."""
    import dataclasses

    import crdt_tpu.analysis.kernels as kernels
    from crdt_tpu.analysis import run_lint
    from crdt_tpu.analysis.kernels import pointwise

    bad_contract = dataclasses.replace(
        pointwise(), collectives=("psum",))
    bad = (dataclasses.replace(MANIFEST[0], sharding=bad_contract),
           ) + tuple(MANIFEST[1:])
    monkeypatch.setattr(kernels, "MANIFEST", bad)
    pf = ParsedFile("x", "crdt_tpu/batch/_none.py", "import jax\n")
    result = run_lint([pf], only_rules=["kernel-manifest"])
    assert any("malformed sharding contract" in f.message
               for f in result.findings), [
        f.render() for f in result.findings]
