"""Bulk wire-format ingest (`OrswotBatch.from_wire` + the native parallel
decoder `crdt_tpu/native/wire_ingest.cpp`).

Contract under test: ``from_wire(blobs, uni)`` is semantically identical
to ``from_scalar([from_binary(b) for b in blobs], uni)`` for every input
— the native fast path (identity universe, integer keys) never changes
what a blob means, only how fast it lands; anything outside the
integer-keyed grammar falls back to the Python decoder per blob.
"""

import numpy as np
import pytest
from hypothesis import given, settings
from hypothesis import strategies as st

from crdt_tpu import Orswot, from_binary, to_binary
from crdt_tpu.batch import OrswotBatch
from crdt_tpu.config import CrdtConfig
from crdt_tpu.utils.interning import Universe


def _identity_uni(**kw):
    base = dict(num_actors=8, member_capacity=8, deferred_capacity=4)
    base.update(kw)
    return Universe.identity(CrdtConfig(**base))


def _random_states(rng, n, n_actors=8, deferred_frac=0.3):
    states = []
    for _ in range(n):
        s = Orswot()
        for j in range(int(rng.randint(1, 5))):
            member = int(rng.randint(0, 40))
            actor = int(rng.randint(0, n_actors))
            s.apply(s.add(member, s.value().derive_add_ctx(actor)))
        if rng.rand() < deferred_frac and s.entries:
            # causally-future remove: buffers in the deferred table
            member = next(iter(s.entries))
            ctx = s.contains(member).derive_rm_ctx()
            ctx.clock.witness(int(rng.randint(0, n_actors)),
                              int(rng.randint(100, 200)))
            s.apply(s.remove(member, ctx))
        states.append(s)
    return states


@pytest.mark.parametrize("counter_bits", [32, 64])
def test_from_wire_matches_python_decode(counter_bits):
    rng = np.random.RandomState(41)
    uni = _identity_uni(counter_bits=counter_bits)
    states = _random_states(rng, 64)
    blobs = [to_binary(s) for s in states]

    # via_device=False: the host route preserves wire slot order, which
    # is what makes exact-plane comparison against from_scalar possible
    # (the device route canonicalizes slots to ascending id — covered by
    # test_from_wire_via_device_route_matches_host_route)
    got = OrswotBatch.from_wire(blobs, uni, via_device=False)
    want = OrswotBatch.from_scalar([from_binary(b) for b in blobs], uni)

    # set clock / member tables are deterministic (wire order == decode
    # order); deferred row ORDER may differ (python sets vs wire order),
    # so compare those semantically below
    np.testing.assert_array_equal(np.asarray(got.clock), np.asarray(want.clock))
    np.testing.assert_array_equal(np.asarray(got.ids), np.asarray(want.ids))
    np.testing.assert_array_equal(np.asarray(got.dots), np.asarray(want.dots))
    assert got.to_scalar(uni) == states  # full state incl. deferred


def test_from_wire_deferred_resolves_like_scalar():
    """Wire-ingested deferred rows must REPLAY identically: merge a state
    that covers the buffered clock and compare against the scalar path."""
    uni = _identity_uni()
    a = Orswot()
    a.apply(a.add(7, a.value().derive_add_ctx(1)))
    ctx = a.contains(7).derive_rm_ctx()
    ctx.clock.witness(2, 50)  # future dot: defers
    a.apply(a.remove(7, ctx))
    b = Orswot()
    for c in range(50):
        b.apply(b.add(9, b.value().derive_add_ctx(2)))

    batch_a = OrswotBatch.from_wire([to_binary(a)], uni)
    batch_b = OrswotBatch.from_wire([to_binary(b)], uni)
    merged = batch_a.merge(batch_b).merge(
        OrswotBatch.from_scalar([Orswot()], uni)
    )

    oracle = a.clone()
    oracle.merge(b)
    oracle.merge(Orswot())
    assert merged.to_scalar(uni)[0].value().val == oracle.value().val


def test_from_wire_fallback_non_int_members():
    """A string-keyed blob is outside the fast-path grammar; with an
    identity universe the Python fallback must raise exactly as
    from_scalar would (identity registries hold ints only)."""
    uni = _identity_uni()
    s = Orswot()
    s.apply(s.add("name", s.value().derive_add_ctx(0)))
    with pytest.raises(ValueError, match="identity registry"):
        OrswotBatch.from_wire([to_binary(s)], uni)

    # with a standard universe the same blobs take the Python path whole
    std = Universe(CrdtConfig(num_actors=8, member_capacity=8,
                              deferred_capacity=4))
    batch = OrswotBatch.from_wire([to_binary(s)], std)
    assert batch.to_scalar(std)[0] == s


def test_from_wire_mixed_fallback_rows():
    """Int-keyed and non-conforming blobs in ONE batch: fast rows parse
    natively, flagged rows patch through the Python decoder.  A u64
    counter >= 2^63 zigzags past the native varint's u64 range (status 1,
    deterministic) while the Python path handles it fine — so this
    actually drives the row-patching scatter, not just the fast path."""
    from crdt_tpu.scalar.vclock import VClock

    rng = np.random.RandomState(43)
    uni = _identity_uni(counter_bits=64)
    states = _random_states(rng, 12)
    big = Orswot()
    big.clock = VClock({3: 2**63 + 5})
    big.entries[17] = VClock({3: 2**63 + 5})
    states[3] = big
    states[9] = Orswot()  # empty state: trivially conformant
    blobs = [to_binary(s) for s in states]
    got = OrswotBatch.from_wire(blobs, uni)
    assert got.to_scalar(uni) == states
    # the patched row really carries the big counter
    assert int(np.asarray(got.clock)[3, 3]) == 2**63 + 5


def test_from_wire_counter_overflow_matches_python_path():
    """u32 build + a counter in [2^32, 2^64): the native parser must NOT
    silently wrap — it flags the blob and the Python fallback raises the
    same OverflowError the pure-Python path raises (causal counters must
    never regress silently)."""
    from crdt_tpu.scalar.vclock import VClock

    uni = _identity_uni(counter_bits=32)
    s = Orswot()
    s.clock = VClock({1: 2**32 + 7})
    blob = to_binary(s)
    with pytest.raises(OverflowError):
        OrswotBatch.from_wire([blob], uni)
    with pytest.raises(OverflowError):
        OrswotBatch.from_scalar([from_binary(blob)], uni)


def test_from_wire_max_int32_member_both_paths():
    """Member id 2^31 - 1 is a valid int32 id on BOTH paths (the identity
    registry bound and the native decoder's check must agree)."""
    uni = _identity_uni()
    s = Orswot()
    s.apply(s.add((1 << 31) - 1, s.value().derive_add_ctx(0)))
    blob = to_binary(s)
    fast = OrswotBatch.from_wire([blob], uni)
    slow = OrswotBatch.from_scalar([from_binary(blob)], uni)
    np.testing.assert_array_equal(np.asarray(fast.ids), np.asarray(slow.ids))
    assert fast.to_scalar(uni) == [s]


def test_from_wire_overflow_raises():
    uni = _identity_uni(member_capacity=2)
    s = Orswot()
    for member in (1, 2, 3):
        s.apply(s.add(member, s.value().derive_add_ctx(0)))
    with pytest.raises(ValueError, match="member_capacity"):
        OrswotBatch.from_wire([to_binary(s)], uni)


def test_from_wire_actor_out_of_range_raises():
    uni = _identity_uni(num_actors=2)
    s = Orswot()
    s.apply(s.add(1, s.value().derive_add_ctx(5)))  # actor 5 >= 2
    with pytest.raises(ValueError, match="actor"):
        OrswotBatch.from_wire([to_binary(s)], uni)


@pytest.mark.parametrize("counter_bits", [32, 64])
def test_to_wire_matches_python_encode(counter_bits):
    """Bulk egress parity: to_wire must be BYTE-identical to to_binary of
    the per-object scalars — including the codec's deterministic
    orderings (encoded-bytes pair sort, repr-sorted clock keys)."""
    rng = np.random.RandomState(53)
    uni = _identity_uni(counter_bits=counter_bits)
    states = _random_states(rng, 48)
    batch = OrswotBatch.from_scalar(states, uni)
    got = batch.to_wire(uni)
    want = [to_binary(s) for s in batch.to_scalar(uni)]
    assert got == want


def test_to_wire_ordering_edge_cases():
    """The three orderings diverge exactly where this state puts them:
    members {100, 8192} sort 8192-first under encoded-bytes order
    (varint [0x80,0x80,0x01] < [0xC8,0x01]) though 100 < 8192 numerically;
    deferred clock keys sort pairs by repr, so actors {2, 10} order
    10-first ("10" < "2")."""
    from crdt_tpu.scalar.vclock import VClock

    uni = _identity_uni(num_actors=16, member_capacity=8,
                        deferred_capacity=4)
    s = Orswot()
    for member in (100, 8192, 63, 64):
        s.apply(s.add(member, s.value().derive_add_ctx(2)))
    # deferred remove witnessed by a clock over actors {2, 10}
    ctx = s.contains(100).derive_rm_ctx()
    ctx.clock.witness(10, 500)
    ctx.clock.witness(2, 400)
    s.apply(s.remove(100, ctx))
    # second member buffered under the SAME clock (grouping leg)
    ctx2 = s.contains(8192).derive_rm_ctx()
    ctx2.clock = VClock({2: 400, 10: 500})
    s.apply(s.remove(8192, ctx2))

    batch = OrswotBatch.from_scalar([s], uni)
    got = batch.to_wire(uni)
    want = [to_binary(x) for x in batch.to_scalar(uni)]
    assert got == want
    # and the round trip re-ingests to the same state
    assert OrswotBatch.from_wire(got, uni).to_scalar(uni) == batch.to_scalar(uni)


def test_to_wire_u64_high_counter_falls_back():
    """u64 counters >= 2^63 exceed the native encoder's zigzag range; the
    Python path must take over with identical bytes."""
    from crdt_tpu.scalar.vclock import VClock

    uni = _identity_uni(counter_bits=64)
    s = Orswot()
    s.clock = VClock({1: 2**63 + 9})
    s.entries[5] = VClock({1: 2**63 + 9})
    batch = OrswotBatch.from_scalar([s], uni)
    got = batch.to_wire(uni)
    assert got == [to_binary(x) for x in batch.to_scalar(uni)]
    assert from_binary(got[0]).clock.dots[1] == 2**63 + 9


def test_from_wire_via_device_route_matches_host_route():
    """``via_device=True`` routes the parsed state through COO columns +
    the device-side expand (dense planes never transit the tunnel on a
    real accelerator); the result must be semantically identical to the
    host route — member slots canonicalize to ascending-id order."""
    rng = np.random.RandomState(61)
    uni = _identity_uni()
    states = _random_states(rng, 24)
    blobs = [to_binary(s) for s in states]
    host = OrswotBatch.from_wire(blobs, uni, via_device=False)
    dev = OrswotBatch.from_wire(blobs, uni, via_device=True)
    assert dev.to_scalar(uni) == host.to_scalar(uni) == states
    # and the wire bytes agree too (to_binary is canonical)
    assert dev.to_wire(uni) == host.to_wire(uni)


def test_wire_roundtrip_fuzz():
    """from_wire(to_wire(batch)) is the identity on scalar states across
    random deferred-bearing fleets, both widths."""
    rng = np.random.RandomState(59)
    for bits in (32, 64):
        uni = _identity_uni(counter_bits=bits)
        states = _random_states(rng, 40)
        batch = OrswotBatch.from_scalar(states, uni)
        blobs = batch.to_wire(uni)
        back = OrswotBatch.from_wire(blobs, uni)
        assert back.to_scalar(uni) == batch.to_scalar(uni)


@given(
    seed=st.integers(0, 999),
    pos=st.integers(0, 4096),
    byte=st.integers(0, 255),
    mode=st.sampled_from(["flip", "insert", "delete", "truncate"]),
)
def test_wire_parser_total_on_mutated_blobs(seed, pos, byte, mode):
    """The C parser consumes UNTRUSTED replication bytes: any mutation of
    a valid blob must either ingest to exactly what the documented
    contract produces — ``from_scalar([from_binary(blob)])``, i.e. the
    Python decode THROUGH the dense engine (which canonicalizes
    adversarial-only structures like duplicate-actor clock keys the same
    last-wins way) — or surface as the codec's contract exceptions.
    Never crash, never silently diverge from the Python pipeline."""
    rng = np.random.RandomState(seed)
    uni = _identity_uni()
    s = _random_states(rng, 1)[0]
    data = bytearray(to_binary(s))
    if mode == "insert":
        # pos == len(data) appends TRAILING garbage — the framing case
        # (parser must demand consumed == blob length, not stop early)
        pos %= len(data) + 1
        data.insert(pos, byte)
    else:
        pos %= max(1, len(data))
        if mode == "flip":
            data[pos] = byte
        elif mode == "delete":
            del data[pos]
        else:
            data = data[:pos]
    blob = bytes(data)

    try:
        want = OrswotBatch.from_scalar(
            [from_binary(blob)], uni
        ).to_scalar(uni)
    except Exception:
        want = None  # the python pipeline rejects it; from_wire must too
    try:
        got = OrswotBatch.from_wire([blob], uni, via_device=False)
    except (ValueError, OverflowError, TypeError):
        # BOTH directions must agree: from_wire's non-fast-path blobs go
        # through the python pipeline itself, and its hard errors
        # (capacity/actor range, malformed decoded types) are the same
        # checks from_scalar makes — so a clean rejection here implies
        # the python pipeline rejected the blob too
        assert want is None, (
            "from_wire rejected a blob the python pipeline accepts"
        )
        return
    # ingest succeeded: the python pipeline must agree on the state
    assert want is not None, (
        "from_wire accepted a blob the python pipeline rejects"
    )
    assert got.to_scalar(uni) == want


# -- MVReg / LWWReg wire legs -------------------------------------------------


def _random_mvregs(rng, n, n_actors=8):
    from crdt_tpu.scalar.mvreg import MVReg

    regs = []
    for _ in range(n):
        reg = MVReg()
        for actor in rng.choice(n_actors, size=int(rng.randint(1, 4)),
                                replace=False):
            ctx = reg.read().derive_add_ctx(int(actor))
            reg.apply(reg.set(int(rng.randint(0, 1000)), ctx))
        regs.append(reg)
    return regs


@pytest.mark.parametrize("counter_bits", [32, 64])
def test_mvreg_wire_roundtrip_and_parity(counter_bits):
    """MVReg leg of the bulk wire path: ingest matches the Python
    pipeline, egress is byte-identical to to_binary, round trip is the
    identity on scalars."""
    from crdt_tpu.batch import MVRegBatch

    rng = np.random.RandomState(67)
    uni = _identity_uni(counter_bits=counter_bits)
    regs = _random_mvregs(rng, 40)
    blobs = [to_binary(r) for r in regs]

    got = MVRegBatch.from_wire(blobs, uni)
    want = MVRegBatch.from_scalar([from_binary(b) for b in blobs], uni)
    np.testing.assert_array_equal(np.asarray(got.clocks), np.asarray(want.clocks))
    np.testing.assert_array_equal(np.asarray(got.vals), np.asarray(want.vals))

    out = got.to_wire(uni)
    assert out == [to_binary(r) for r in got.to_scalar(uni)]
    back = MVRegBatch.from_wire(out, uni)
    assert back.to_scalar(uni) == got.to_scalar(uni)


def test_mvreg_wire_fallbacks():
    from crdt_tpu.batch import MVRegBatch
    from crdt_tpu.scalar.mvreg import MVReg

    uni = _identity_uni(mv_capacity=2)
    # overflow: 3 concurrent values > mv_capacity 2 → same error as
    # from_scalar
    regs = []
    for actor in range(3):
        r = MVReg()
        r.apply(r.set(actor, r.read().derive_add_ctx(actor)))
        regs.append(r)
    merged = regs[0]
    merged.merge(regs[1])
    merged.merge(regs[2])
    with pytest.raises(ValueError, match="mv_capacity"):
        MVRegBatch.from_wire([to_binary(merged)], uni)

    # non-int payload: python fallback raises the identity-registry error
    s = MVReg()
    s.apply(s.set("text", s.read().derive_add_ctx(0)))
    with pytest.raises(ValueError, match="identity registry"):
        MVRegBatch.from_wire([to_binary(s)], uni)


def test_mvreg_wire_mixed_patch_path():
    """A u64 counter >= 2^63 is outside the native zigzag (status 1) but
    fine for the Python decoder — drives the row-patch splice alongside
    natively-parsed rows."""
    from crdt_tpu.batch import MVRegBatch
    from crdt_tpu.scalar.mvreg import MVReg
    from crdt_tpu.scalar.vclock import VClock

    rng = np.random.RandomState(73)
    uni = _identity_uni(counter_bits=64)
    regs = _random_mvregs(rng, 10)
    big = MVReg([(VClock({2: 2**63 + 3}), 42)])
    regs[4] = big
    blobs = [to_binary(r) for r in regs]
    got = MVRegBatch.from_wire(blobs, uni)
    want = MVRegBatch.from_scalar([from_binary(b) for b in blobs], uni)
    np.testing.assert_array_equal(np.asarray(got.clocks), np.asarray(want.clocks))
    np.testing.assert_array_equal(np.asarray(got.vals), np.asarray(want.vals))
    assert int(np.asarray(got.clocks)[4, 0, 2]) == 2**63 + 3


def test_lww_wire_roundtrip_and_parity():
    """LWW leg: both directions byte/plane-faithful, incl. the mixed
    patch path (a marker >= 2^63 is outside the native zigzag range and
    routes through the Python decoder per blob)."""
    from crdt_tpu.batch import LWWRegBatch
    from crdt_tpu.scalar.lwwreg import LWWReg

    rng = np.random.RandomState(71)
    uni = _identity_uni()
    regs = [
        LWWReg(int(rng.randint(0, 1000)), int(rng.randint(1, 10**9)))
        for _ in range(50)
    ]
    regs[7] = LWWReg(5, 2**63 + 11)  # native flags it; python patches it
    blobs = [to_binary(r) for r in regs]

    got = LWWRegBatch.from_wire(blobs, uni)
    want = LWWRegBatch.from_scalar([from_binary(b) for b in blobs], uni)
    np.testing.assert_array_equal(np.asarray(got.vals), np.asarray(want.vals))
    np.testing.assert_array_equal(
        np.asarray(got.markers), np.asarray(want.markers)
    )
    assert int(np.asarray(got.markers)[7]) == 2**63 + 11

    # egress: the big marker forces the whole-batch Python path; bytes
    # still identical.  Without it, the native path must agree too.
    assert got.to_wire(uni) == blobs
    small = LWWRegBatch.from_scalar(regs[:7], uni)
    assert small.to_wire(uni) == blobs[:7]


def test_gset_wire_roundtrip_and_parity():
    """GSet leg: bitmap ingest/egress, sorted-items byte parity, overflow
    and non-int fallbacks."""
    from crdt_tpu.batch import GSetBatch
    from crdt_tpu.scalar.gset import GSet

    rng = np.random.RandomState(79)
    uni = _identity_uni()
    U = 64
    sets = []
    for _ in range(30):
        s = GSet()
        for _ in range(int(rng.randint(0, 6))):
            s.insert(int(rng.randint(0, U)))
        sets.append(s)
    blobs = [to_binary(s) for s in sets]

    got = GSetBatch.from_wire(blobs, uni, U)
    want = GSetBatch.from_scalar([from_binary(b) for b in blobs], uni, U)
    np.testing.assert_array_equal(np.asarray(got.bits), np.asarray(want.bits))
    out = got.to_wire(uni)
    assert out == [to_binary(s) for s in got.to_scalar(uni)] == blobs

    # member beyond the bitmap: same error as from_scalar
    big = GSet({U + 5})
    with pytest.raises(ValueError, match="universe overflow"):
        GSetBatch.from_wire([to_binary(big)], uni, U)
    # non-int member: python fallback raises the identity-registry error
    s = GSet({"txt"})
    with pytest.raises(ValueError, match="identity registry"):
        GSetBatch.from_wire([to_binary(s)], uni, U)


def test_identity_universe_checkpoint_roundtrip():
    """Identity universes survive checkpoint save/load as identity (a
    value-list restore would rebuild a dict registry whose lookups fail
    for never-interned ids)."""
    from crdt_tpu.utils.checkpoint import load_bytes, save_bytes

    rng = np.random.RandomState(47)
    uni = _identity_uni()
    states = _random_states(rng, 8)
    batch = OrswotBatch.from_wire([to_binary(s) for s in states], uni)
    loaded, uni2 = load_bytes(save_bytes(batch, uni))
    assert uni2.is_identity
    assert loaded.to_scalar(uni2) == states


# ---------------------------------------------------------------------------
# clock-shaped legs: VClock / GCounter / PNCounter
# ---------------------------------------------------------------------------


def _random_vclock(rng, n_actors=8, hi=100):
    from crdt_tpu.scalar.vclock import VClock

    vc = VClock()
    for a in range(n_actors):
        if rng.rand() < 0.5:
            vc.dots[a] = int(rng.randint(1, hi))
    return vc


@pytest.mark.parametrize("counter_bits", [32, 64])
def test_vclock_wire_roundtrip_and_parity(counter_bits):
    """Causality-kernel leg of the bulk wire path (tag 0x20)."""
    from crdt_tpu.batch.vclock_batch import VClockBatch

    rng = np.random.RandomState(91)
    uni = _identity_uni(counter_bits=counter_bits)
    clocks = [_random_vclock(rng) for _ in range(40)]
    blobs = [to_binary(c) for c in clocks]

    got = VClockBatch.from_wire(blobs, uni)
    want = VClockBatch.from_scalar([from_binary(b) for b in blobs], uni)
    np.testing.assert_array_equal(np.asarray(got.clocks), np.asarray(want.clocks))

    assert got.to_wire(uni) == blobs  # byte-identical egress
    assert VClockBatch.from_wire(got.to_wire(uni), uni).to_scalar(uni) == clocks


@pytest.mark.parametrize("counter_bits", [32, 64])
def test_gcounter_wire_roundtrip_and_parity(counter_bits):
    """GCounter leg (tag 0x22 — a GCounter IS a VClock, gcounter.rs:26-28)."""
    from crdt_tpu.batch.gcounter_batch import GCounterBatch
    from crdt_tpu.scalar.gcounter import GCounter

    rng = np.random.RandomState(92)
    uni = _identity_uni(counter_bits=counter_bits)
    states = [GCounter(_random_vclock(rng)) for _ in range(40)]
    blobs = [to_binary(s) for s in states]

    got = GCounterBatch.from_wire(blobs, uni)
    want = GCounterBatch.from_scalar([from_binary(b) for b in blobs], uni)
    np.testing.assert_array_equal(np.asarray(got.clocks), np.asarray(want.clocks))

    assert got.to_wire(uni) == blobs
    # values survive the loop (the counter's actual API surface)
    assert [g.value() for g in GCounterBatch.from_wire(blobs, uni).to_scalar(uni)] == [
        s.value() for s in states
    ]


@pytest.mark.parametrize("counter_bits", [32, 64])
def test_pncounter_wire_roundtrip_and_parity(counter_bits):
    """PNCounter leg (tag 0x23 — two clock bodies, P then N)."""
    from crdt_tpu.batch.pncounter_batch import PNCounterBatch
    from crdt_tpu.scalar.gcounter import GCounter
    from crdt_tpu.scalar.pncounter import PNCounter

    rng = np.random.RandomState(93)
    uni = _identity_uni(counter_bits=counter_bits)
    states = [
        PNCounter(GCounter(_random_vclock(rng)), GCounter(_random_vclock(rng)))
        for _ in range(40)
    ]
    blobs = [to_binary(s) for s in states]

    got = PNCounterBatch.from_wire(blobs, uni)
    want = PNCounterBatch.from_scalar([from_binary(b) for b in blobs], uni)
    np.testing.assert_array_equal(np.asarray(got.planes), np.asarray(want.planes))

    assert got.to_wire(uni) == blobs
    assert [p.value() for p in PNCounterBatch.from_wire(blobs, uni).to_scalar(uni)] == [
        s.value() for s in states
    ]


def test_clockish_wire_empty_and_zero_rows():
    """Empty batches and all-zero clocks round-trip (0-pair bodies)."""
    from crdt_tpu.scalar.vclock import VClock
    from crdt_tpu.batch.vclock_batch import VClockBatch

    uni = _identity_uni()
    assert VClockBatch.from_wire([], uni).clocks.shape == (0, 8)
    assert VClockBatch.zeros(0, uni).to_wire(uni) == []

    blobs = [to_binary(VClock()), to_binary(VClock({3: 7}))]
    got = VClockBatch.from_wire(blobs, uni)
    assert got.to_wire(uni) == blobs


def test_clockish_wire_mixed_patch_path():
    """u64 counters >= 2^63 are outside the native zigzag (status 1) but
    fine for Python — drives the row-patch splice next to fast rows, and
    the egress guard routes the whole batch through the Python encoder."""
    from crdt_tpu.scalar.vclock import VClock
    from crdt_tpu.batch.vclock_batch import VClockBatch

    rng = np.random.RandomState(94)
    uni = _identity_uni(counter_bits=64)
    clocks = [_random_vclock(rng) for _ in range(10)]
    clocks[3] = VClock({1: 2**63 + 11})
    blobs = [to_binary(c) for c in clocks]
    got = VClockBatch.from_wire(blobs, uni)
    want = VClockBatch.from_scalar([from_binary(b) for b in blobs], uni)
    np.testing.assert_array_equal(np.asarray(got.clocks), np.asarray(want.clocks))
    assert int(np.asarray(got.clocks)[3, 1]) == 2**63 + 11
    assert got.to_wire(uni) == blobs  # python-path egress, still byte-equal


def test_clockish_wire_actor_out_of_range_raises():
    from crdt_tpu.scalar.vclock import VClock
    from crdt_tpu.batch.vclock_batch import VClockBatch
    from crdt_tpu.batch.pncounter_batch import PNCounterBatch
    from crdt_tpu.scalar.gcounter import GCounter
    from crdt_tpu.scalar.pncounter import PNCounter

    uni = _identity_uni()
    with pytest.raises(ValueError, match="identity registry"):
        VClockBatch.from_wire([to_binary(VClock({100: 1}))], uni)
    bad = PNCounter(GCounter(VClock({0: 1})), GCounter(VClock({100: 1})))
    with pytest.raises(ValueError, match="identity registry"):
        PNCounterBatch.from_wire([to_binary(bad)], uni)


def test_clockish_wire_duplicate_actor_canonicalizes_last_wins():
    """Adversarial blob with a repeated actor key (to_binary never emits
    one): the C scatter and the Python dict decode both keep the LAST
    pair — the through-pipeline contract, like the ORSWOT leg's fuzz."""
    import io

    from crdt_tpu.batch.vclock_batch import VClockBatch

    def uv(v):
        out = bytearray()
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                out.append(b | 0x80)
            else:
                out.append(b)
                return bytes(out)

    def pair(actor, counter):
        return b"\x03" + uv(actor << 1) + b"\x03" + uv(counter << 1)

    blob = b"\x20" + uv(2) + pair(1, 5) + pair(1, 9)
    uni = _identity_uni()
    got = VClockBatch.from_wire([blob], uni)
    assert int(np.asarray(got.clocks)[0, 1]) == 9
    # the Python pipeline agrees (dict insertion: last wins)
    want = VClockBatch.from_scalar([from_binary(blob)], uni)
    np.testing.assert_array_equal(np.asarray(got.clocks), np.asarray(want.clocks))


def test_clockish_wire_non_identity_universe_falls_back():
    """Interning universes take the Python path end-to-end; results and
    bytes match the scalar pipeline exactly."""
    from crdt_tpu.scalar.vclock import VClock
    from crdt_tpu.batch.gcounter_batch import GCounterBatch
    from crdt_tpu.scalar.gcounter import GCounter

    cfg = CrdtConfig(num_actors=4)
    uni = Universe(cfg)
    states = [GCounter(VClock({"a": 3, "b": 1})), GCounter(VClock({"c": 9}))]
    blobs = [to_binary(s) for s in states]
    got = GCounterBatch.from_wire(blobs, uni)
    assert got.to_scalar(uni) == states
    assert got.to_wire(uni) == blobs


def test_pncounter_wire_mixed_patch_path():
    """PNCounter rides the shared planes_from_wire/planes_to_wire flow;
    drive its status-1 splice (u64 counter >= 2^63 in the N plane) and
    the egress guard through the public methods."""
    from crdt_tpu.batch.pncounter_batch import PNCounterBatch
    from crdt_tpu.scalar.gcounter import GCounter
    from crdt_tpu.scalar.pncounter import PNCounter
    from crdt_tpu.scalar.vclock import VClock

    rng = np.random.RandomState(95)
    uni = _identity_uni(counter_bits=64)
    states = [
        PNCounter(GCounter(_random_vclock(rng)), GCounter(_random_vclock(rng)))
        for _ in range(8)
    ]
    states[5] = PNCounter(GCounter(VClock({0: 4})),
                          GCounter(VClock({3: 2**63 + 7})))
    blobs = [to_binary(s) for s in states]
    got = PNCounterBatch.from_wire(blobs, uni)
    want = PNCounterBatch.from_scalar([from_binary(b) for b in blobs], uni)
    np.testing.assert_array_equal(np.asarray(got.planes), np.asarray(want.planes))
    assert int(np.asarray(got.planes)[5, 1, 3]) == 2**63 + 7
    assert got.to_wire(uni) == blobs  # python-path egress, byte-equal


# ---------------------------------------------------------------------------
# Map<K, MVReg> leg
# ---------------------------------------------------------------------------


def _random_map_mvregs(rng, n, n_actors=8, deferred_frac=0.3):
    from crdt_tpu.scalar.map import Map
    from crdt_tpu.scalar.mvreg import MVReg

    maps = []
    for i in range(n):
        m = Map(MVReg)
        for _ in range(int(rng.randint(0, 4))):
            key = int(rng.randint(0, 30))
            actor = int(rng.randint(0, n_actors))
            ctx = m.get(key).derive_add_ctx(actor)
            val = int(rng.randint(0, 100))
            m.apply(m.update(key, ctx, lambda v, c, _v=val: v.set(_v, c)))
        if rng.rand() < deferred_frac and m.entries:
            key = next(iter(m.entries))
            ctx = m.get(key).derive_rm_ctx()
            ctx.clock.witness(int(rng.randint(0, n_actors)),
                              int(rng.randint(100, 200)))
            m.apply(m.rm(key, ctx))
        maps.append(m)
    return maps


def _map_uni(counter_bits=64):
    return Universe.identity(CrdtConfig(
        num_actors=8, key_capacity=4, deferred_capacity=4, mv_capacity=2,
        counter_bits=counter_bits,
    ))


@pytest.mark.parametrize("counter_bits", [32, 64])
def test_map_mvreg_wire_roundtrip_and_parity(counter_bits):
    """Map<K, MVReg> leg: ingest matches the Python pipeline plane-for-
    plane (wire order == decode order), egress is byte-identical to
    to_binary, round trip is the identity on scalars incl. deferred."""
    from crdt_tpu.batch.map_batch import MapBatch
    from crdt_tpu.batch.val_kernels import MVRegKernel

    rng = np.random.RandomState(101)
    uni = _map_uni(counter_bits)
    vk = MVRegKernel.from_config(uni.config)
    maps = _random_map_mvregs(rng, 30)
    blobs = [to_binary(m) for m in maps]

    got = MapBatch.from_wire(blobs, uni, vk)
    want = MapBatch.from_scalar([from_binary(b) for b in blobs], uni, vk)
    np.testing.assert_array_equal(np.asarray(got.clock), np.asarray(want.clock))
    np.testing.assert_array_equal(np.asarray(got.keys), np.asarray(want.keys))
    np.testing.assert_array_equal(
        np.asarray(got.entry_clocks), np.asarray(want.entry_clocks))
    np.testing.assert_array_equal(np.asarray(got.vals[0]), np.asarray(want.vals[0]))
    np.testing.assert_array_equal(np.asarray(got.vals[1]), np.asarray(want.vals[1]))
    assert got.to_scalar(uni) == maps  # full state incl. deferred

    out = got.to_wire(uni)
    assert out == blobs  # byte-identical egress
    assert MapBatch.from_wire(out, uni, vk).to_scalar(uni) == maps


def test_map_wire_non_mvreg_kernel_falls_back():
    """Map<K, Orswot> has no native codec — the Python path serves both
    directions with identical results (and bytes)."""
    from crdt_tpu.batch.map_batch import MapBatch
    from crdt_tpu.batch.val_kernels import OrswotKernel
    from crdt_tpu.scalar.map import Map
    from crdt_tpu.scalar.orswot import Orswot

    uni = _map_uni()
    vk = OrswotKernel.from_config(uni.config)
    m = Map(Orswot)
    ctx = m.get(3).derive_add_ctx(1)
    m.apply(m.update(3, ctx, lambda v, c: v.add(7, c)))
    blobs = [to_binary(m)]
    got = MapBatch.from_wire(blobs, uni, vk)
    assert got.to_scalar(uni) == [m]
    assert got.to_wire(uni) == blobs


def test_map_wire_overflow_and_actor_errors():
    from crdt_tpu.batch.map_batch import MapBatch
    from crdt_tpu.batch.val_kernels import MVRegKernel
    from crdt_tpu.scalar.map import Map
    from crdt_tpu.scalar.mvreg import MVReg

    uni = _map_uni()
    vk = MVRegKernel.from_config(uni.config)
    # key overflow: 5 keys > key_capacity 4 — same error class as
    # from_scalar
    m = Map(MVReg)
    for key in range(5):
        ctx = m.get(key).derive_add_ctx(0)
        m.apply(m.update(key, ctx, lambda v, c: v.set(1, c)))
    with pytest.raises(ValueError, match="key_capacity"):
        MapBatch.from_wire([to_binary(m)], uni, vk)
    # actor out of the identity range
    m2 = Map(MVReg)
    ctx = m2.get(1).derive_add_ctx(100)
    m2.apply(m2.update(1, ctx, lambda v, c: v.set(1, c)))
    with pytest.raises(ValueError, match="identity registry"):
        MapBatch.from_wire([to_binary(m2)], uni, vk)


def test_map_wire_mixed_patch_path():
    """A u64 counter >= 2^63 is outside the native zigzag (status 1) but
    fine for the Python big-int decoder — drives the row-patch splice
    alongside natively-parsed maps, and the egress guard routes the
    whole batch through the Python encoder."""
    from crdt_tpu.batch.map_batch import MapBatch
    from crdt_tpu.batch.val_kernels import MVRegKernel
    from crdt_tpu.scalar.map import Map
    from crdt_tpu.scalar.mvreg import MVReg

    rng = np.random.RandomState(103)
    uni = _map_uni(counter_bits=64)
    vk = MVRegKernel.from_config(uni.config)
    maps = _random_map_mvregs(rng, 8)
    big = Map(MVReg)
    ctx = big.get(2).derive_add_ctx(1)
    big.apply(big.update(2, ctx, lambda v, c: v.set(5, c)))
    big.clock.witness(3, 2**63 + 17)  # only the Python decoder lands this
    maps[4] = big
    blobs = [to_binary(m) for m in maps]
    got = MapBatch.from_wire(blobs, uni, vk)
    want = MapBatch.from_scalar([from_binary(b) for b in blobs], uni, vk)
    np.testing.assert_array_equal(np.asarray(got.clock), np.asarray(want.clock))
    np.testing.assert_array_equal(np.asarray(got.vals[0]), np.asarray(want.vals[0]))
    assert int(np.asarray(got.clock)[4, 3]) == 2**63 + 17
    assert got.to_wire(uni) == blobs  # python-path egress, byte-equal


def test_map_to_scalar_val_type_is_serializable():
    """to_scalar must hand back Maps whose val_type survives to_binary —
    the registered class (or MapOf for nesting), not the kernel's bound
    factory (which _encode_val_type rejects)."""
    from crdt_tpu.batch.map_batch import MapBatch
    from crdt_tpu.batch.val_kernels import MapKernel, MVRegKernel
    from crdt_tpu.scalar.map import Map
    from crdt_tpu.scalar.mvreg import MVReg

    uni = _map_uni()
    vk = MVRegKernel.from_config(uni.config)
    m = Map(MVReg)
    ctx = m.get(1).derive_add_ctx(0)
    m.apply(m.update(1, ctx, lambda v, c: v.set(9, c)))
    got = MapBatch.from_scalar([m], uni, vk).to_scalar(uni)
    assert from_binary(to_binary(got[0])) == m  # round-trips
    # nested kernel maps to MapOf(MVReg)
    nested = MapKernel.from_config(uni.config, vk)
    t = nested.scalar_val_type()
    from crdt_tpu.utils.serde import MapOf
    assert isinstance(t, MapOf) and t.inner is MVReg


def test_map_wire_deferred_and_value_overflow_errors():
    """Status 3 (deferred rows > deferred_capacity) and status 5 (value
    antichain > mv_capacity) raise the same error class as from_scalar."""
    from crdt_tpu.batch.map_batch import MapBatch
    from crdt_tpu.batch.val_kernels import MVRegKernel
    from crdt_tpu.scalar.map import Map
    from crdt_tpu.scalar.mvreg import MVReg

    uni = _map_uni()  # deferred_capacity=4, mv_capacity=2
    vk = MVRegKernel.from_config(uni.config)

    # 5 deferred rows > capacity 4
    m = Map(MVReg)
    for key in range(5):
        ctx = m.get(key).derive_rm_ctx()
        ctx.clock.witness(key % 8, 100 + key)  # future: buffers
        m.apply(m.rm(key, ctx))
    with pytest.raises(ValueError, match="deferred_capacity"):
        MapBatch.from_wire([to_binary(m)], uni, vk)

    # a 3-wide antichain > mv_capacity 2
    regs = []
    for actor in range(3):
        r = Map(MVReg)
        ctx = r.get(1).derive_add_ctx(actor)
        r.apply(r.update(1, ctx, lambda v, c, _a=actor: v.set(_a, c)))
        regs.append(r)
    merged = regs[0]
    merged.merge(regs[1])
    merged.merge(regs[2])
    with pytest.raises(ValueError, match="mv_capacity"):
        MapBatch.from_wire([to_binary(merged)], uni, vk)


def test_map_wire_duplicate_key_blob_falls_back():
    """An adversarial blob repeating an entry key (to_binary never emits
    one) must NOT fast-parse into two live slots — non-canonical key
    order falls back to the Python decoder, whose dict dedupes; the
    contract `from_wire == from_scalar(from_binary)` holds."""
    from crdt_tpu.batch.map_batch import MapBatch
    from crdt_tpu.batch.val_kernels import MVRegKernel
    from crdt_tpu.scalar.map import Map
    from crdt_tpu.scalar.mvreg import MVReg

    uni = _map_uni()
    vk = MVRegKernel.from_config(uni.config)
    uv, iv = _uv_bytes, _iv_bytes  # module-level blob-forging helpers

    clock_body = uv(1) + iv(1) + iv(1)          # {actor 1: 1}
    mvreg = b"\x25" + uv(1) + clock_body + iv(3)  # one (clock, val=3) pair
    entry = iv(7) + clock_body + mvreg           # key 7
    forged = (b"\x27" + b"\x50" + uv(5) + b"MVReg"
              + clock_body + uv(2) + entry + entry + uv(0))
    got = MapBatch.from_wire([forged], uni, vk)
    want = MapBatch.from_scalar([from_binary(forged)], uni, vk)
    np.testing.assert_array_equal(np.asarray(got.keys), np.asarray(want.keys))
    assert (np.asarray(got.keys)[0] != -1).sum() == 1  # deduped, one slot


@given(
    seed=st.integers(0, 999),
    pos=st.integers(0, 4096),
    byte=st.integers(0, 255),
    mode=st.sampled_from(["flip", "insert", "delete", "truncate"]),
    leg=st.sampled_from(["vclock", "pncounter", "map", "map_orswot", "map_map"]),
)
def test_new_leg_parsers_total_on_mutated_blobs(seed, pos, byte, mode, leg):
    """Mutation-fuzz totality for the round-4 parsers (clockish /
    PNCounter / Map<K, MVReg>) — same contract as the ORSWOT fuzz: any
    mutation of a valid blob either ingests to exactly what the Python
    pipeline produces through the dense engine, or raises the codec's
    contract exceptions.  Never crash, never silently diverge."""
    from crdt_tpu.batch.map_batch import MapBatch
    from crdt_tpu.batch.pncounter_batch import PNCounterBatch
    from crdt_tpu.batch.vclock_batch import VClockBatch
    from crdt_tpu.batch.val_kernels import MVRegKernel
    from crdt_tpu.scalar.gcounter import GCounter
    from crdt_tpu.scalar.pncounter import PNCounter

    rng = np.random.RandomState(seed)
    if leg == "map":
        uni = _map_uni()
        vk = MVRegKernel.from_config(uni.config)
        state = _random_map_mvregs(rng, 1)[0]
        ingest = lambda blob: MapBatch.from_wire([blob], uni, vk)
        pipeline = lambda blob: MapBatch.from_scalar(
            [from_binary(blob)], uni, vk)
    elif leg == "map_orswot":
        from crdt_tpu.batch.val_kernels import OrswotKernel

        uni = _map_uni()
        vk = OrswotKernel.from_config(uni.config)
        state = _random_map_orswots(rng, 1)[0]
        ingest = lambda blob: MapBatch.from_wire([blob], uni, vk)
        pipeline = lambda blob: MapBatch.from_scalar(
            [from_binary(blob)], uni, vk)
    elif leg == "map_map":
        uni = _map_uni()
        vk = _nested_kernel(uni)
        state = _random_nested_maps(rng, 1)[0]
        ingest = lambda blob: MapBatch.from_wire([blob], uni, vk)
        pipeline = lambda blob: MapBatch.from_scalar(
            [from_binary(blob)], uni, vk)
    elif leg == "pncounter":
        uni = _identity_uni()
        state = PNCounter(GCounter(_random_vclock(rng)),
                          GCounter(_random_vclock(rng)))
        ingest = lambda blob: PNCounterBatch.from_wire([blob], uni)
        pipeline = lambda blob: PNCounterBatch.from_scalar(
            [from_binary(blob)], uni)
    else:
        uni = _identity_uni()
        state = _random_vclock(rng)
        ingest = lambda blob: VClockBatch.from_wire([blob], uni)
        pipeline = lambda blob: VClockBatch.from_scalar(
            [from_binary(blob)], uni)

    data = bytearray(to_binary(state))
    if mode == "insert":
        pos %= len(data) + 1
        data.insert(pos, byte)
    else:
        pos %= max(1, len(data))
        if mode == "flip":
            data[pos] = byte
        elif mode == "delete":
            del data[pos]
        else:
            data = data[:pos]
    blob = bytes(data)

    try:
        want = pipeline(blob).to_scalar(uni)
    except Exception:
        want = None
    try:
        got = ingest(blob)
    except (ValueError, OverflowError, TypeError, AttributeError):
        # the python pipeline must reject it too (from_wire's fallback IS
        # the python pipeline, and its hard errors are the same checks)
        assert want is None, (
            f"{leg} from_wire rejected a blob the python pipeline accepts"
        )
        return
    assert want is not None, (
        f"{leg} from_wire accepted a blob the python pipeline rejects"
    )
    assert got.to_scalar(uni) == want


def _random_map_orswots(rng, n, n_actors=8):
    from crdt_tpu.scalar.map import Map
    from crdt_tpu.scalar.orswot import Orswot

    maps = []
    for i in range(n):
        m = Map(Orswot)
        for _ in range(int(rng.randint(0, 4))):
            key = int(rng.randint(0, 30))
            actor = int(rng.randint(0, n_actors))
            ctx = m.get(key).derive_add_ctx(actor)
            member = int(rng.randint(0, 40))
            m.apply(m.update(key, ctx, lambda v, c, _m=member: v.add(_m, c)))
        if rng.rand() < 0.3 and m.entries:
            key = next(iter(m.entries))
            ctx = m.get(key).derive_rm_ctx()
            ctx.clock.witness(int(rng.randint(0, n_actors)),
                              int(rng.randint(100, 200)))
            m.apply(m.rm(key, ctx))
        maps.append(m)
    return maps


@pytest.mark.parametrize("counter_bits", [32, 64])
def test_map_orswot_wire_roundtrip_and_parity(counter_bits):
    """Map<K, Orswot> leg — the reset-remove-over-sets composition."""
    from crdt_tpu.batch.map_batch import MapBatch
    from crdt_tpu.batch.val_kernels import OrswotKernel

    rng = np.random.RandomState(107)
    uni = _map_uni(counter_bits)
    vk = OrswotKernel.from_config(uni.config)
    maps = _random_map_orswots(rng, 30)
    blobs = [to_binary(m) for m in maps]

    got = MapBatch.from_wire(blobs, uni, vk)
    want = MapBatch.from_scalar([from_binary(b) for b in blobs], uni, vk)
    np.testing.assert_array_equal(np.asarray(got.clock), np.asarray(want.clock))
    np.testing.assert_array_equal(np.asarray(got.keys), np.asarray(want.keys))
    np.testing.assert_array_equal(
        np.asarray(got.entry_clocks), np.asarray(want.entry_clocks))
    # value member tables are wire-order deterministic
    np.testing.assert_array_equal(np.asarray(got.vals[1]), np.asarray(want.vals[1]))
    np.testing.assert_array_equal(np.asarray(got.vals[2]), np.asarray(want.vals[2]))
    assert got.to_scalar(uni) == maps  # full state incl. nested deferred

    out = got.to_wire(uni)
    assert out == blobs
    assert MapBatch.from_wire(out, uni, vk).to_scalar(uni) == maps


def test_map_orswot_wire_value_overflow_raises():
    from crdt_tpu.batch.map_batch import MapBatch
    from crdt_tpu.batch.val_kernels import OrswotKernel
    from crdt_tpu.scalar.map import Map
    from crdt_tpu.scalar.orswot import Orswot

    uni = Universe.identity(CrdtConfig(
        num_actors=8, key_capacity=4, deferred_capacity=4, member_capacity=2))
    vk = OrswotKernel.from_config(uni.config)
    m = Map(Orswot)
    for member in (1, 2, 3):  # 3 members > value member_capacity 2
        ctx = m.get(0).derive_add_ctx(0)
        m.apply(m.update(0, ctx, lambda v, c, _m=member: v.add(_m, c)))
    with pytest.raises(ValueError, match="member_capacity"):
        MapBatch.from_wire([to_binary(m)], uni, vk)


def _uv_bytes(v):
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _iv_bytes(v):  # 0x03 + zigzag varint (non-negative)
    return b"\x03" + _uv_bytes(v << 1)


def _orswot_blob_with_deferred(groups):
    """Hand-built ORSWOT blob: set clock {a0: 5}, one member 3 with the
    same entry clock, then a deferred section given as a list of
    ``(clock_pairs, members)`` groups IN THE GIVEN ORDER (so tests can
    craft non-canonical layouts to_binary would never emit)."""
    clock_body = _uv_bytes(1) + _iv_bytes(0) + _iv_bytes(5)  # {actor 0: 5}
    entry = _iv_bytes(3) + b"\x20" + clock_body
    out = b"\x26" + clock_body + _uv_bytes(1) + entry
    out += _uv_bytes(len(groups))
    for pairs, members in groups:
        out += b"\x08" + _uv_bytes(len(pairs))
        for actor, counter in pairs:
            out += b"\x08" + _uv_bytes(2) + _iv_bytes(actor) + _iv_bytes(counter)
        out += _uv_bytes(len(members))
        for m in members:
            out += _iv_bytes(m)
    return out


@pytest.mark.parametrize(
    "groups",
    [
        # duplicate clock-key groups (to_binary merges them into one)
        [([(0, 9)], [3]), ([(0, 9)], [4])],
        # members out of encoded-bytes order within a group
        [([(0, 9)], [4, 3])],
        # duplicate member within a group (set() would dedupe)
        [([(0, 9)], [3, 3])],
        # groups out of encoded clock-key-bytes order
        [([(1, 9)], [3]), ([(0, 9)], [4])],
    ],
    ids=["dup-group", "member-order", "dup-member", "group-order"],
)
def test_from_wire_non_canonical_deferred_falls_back(groups):
    """Adversarial deferred sections to_binary never emits (duplicate
    groups/members, unordered groups/members) must not fast-parse into
    extra dense rows: the parser's canonical-order checks route them to
    the Python decoder, which dedupes via dict/set — the documented
    ``from_wire == from_scalar(from_binary)`` contract."""
    uni = _identity_uni()
    blob = _orswot_blob_with_deferred(groups)
    got = OrswotBatch.from_wire([blob], uni)
    want = OrswotBatch.from_scalar([from_binary(blob)], uni)
    for name in ("clock", "ids", "dots", "d_ids", "d_clocks"):
        np.testing.assert_array_equal(
            np.asarray(getattr(got, name)),
            np.asarray(getattr(want, name)),
            err_msg=name,
        )


def test_from_wire_canonical_deferred_still_fast_parses():
    """The canonical layout (ascending groups, ascending members) must
    keep fast-parsing — guard the guard against over-rejection."""
    uni = _identity_uni()
    blob = _orswot_blob_with_deferred(
        [([(0, 9)], [3, 4]), ([(1, 9)], [5])]
    )
    got = OrswotBatch.from_wire([blob], uni)
    want = OrswotBatch.from_scalar([from_binary(blob)], uni)
    for name in ("clock", "ids", "dots", "d_ids", "d_clocks"):
        np.testing.assert_array_equal(
            np.asarray(getattr(got, name)),
            np.asarray(getattr(want, name)),
            err_msg=name,
        )
    assert (np.asarray(got.d_ids)[0] != -1).sum() == 3


def _random_nested_maps(rng, n, n_actors=8, deferred_frac=0.3):
    """Random ``Map<int, Map<int, MVReg>>`` states — the reference's
    canonical nesting (`/root/reference/test/map.rs:8`) — with deferred
    removes planted at BOTH map levels."""
    from crdt_tpu.scalar.map import Map
    from crdt_tpu.scalar.mvreg import MVReg
    from crdt_tpu.utils.serde import MapOf

    maps = []
    for i in range(n):
        m = Map(MapOf(MVReg))
        for _ in range(int(rng.randint(0, 4))):
            key = int(rng.randint(0, 30))
            ikey = int(rng.randint(0, 30))
            actor = int(rng.randint(0, n_actors))
            val = int(rng.randint(0, 100))
            ctx = m.get(key).derive_add_ctx(actor)
            m.apply(m.update(
                key, ctx,
                lambda v, c, _ik=ikey, _v=val: v.update(
                    _ik, c, lambda reg, c2: reg.set(_v, c2)
                ),
            ))
        if rng.rand() < deferred_frac and m.entries:
            # outer-level causally-future remove
            key = next(iter(m.entries))
            ctx = m.get(key).derive_rm_ctx()
            ctx.clock.witness(int(rng.randint(0, n_actors)),
                              int(rng.randint(100, 200)))
            m.apply(m.rm(key, ctx))
        if rng.rand() < deferred_frac and m.entries:
            # inner-level causally-future remove inside one value map
            key = next(iter(m.entries))
            inner = m.entries[key].val
            if inner.entries:
                ikey = next(iter(inner.entries))
                ctx = m.get(key).derive_add_ctx(int(rng.randint(0, n_actors)))
                ictx = inner.get(ikey).derive_rm_ctx()
                ictx.clock.witness(int(rng.randint(0, n_actors)),
                                   int(rng.randint(100, 200)))
                from crdt_tpu.scalar.map import Rm as MapRm, Up as MapUp
                m.apply(MapUp(dot=ctx.dot, key=key,
                              op=MapRm(clock=ictx.clock, key=ikey)))
        maps.append(m)
    return maps


def _nested_kernel(uni):
    from crdt_tpu.batch.val_kernels import MapKernel, MVRegKernel

    return MapKernel.from_config(uni.config, MVRegKernel.from_config(uni.config))


@pytest.mark.parametrize("counter_bits", [32, 64])
def test_map_map_mvreg_wire_roundtrip_and_parity(counter_bits):
    """Nested Map<K, Map<K2, MVReg>> leg: ingest matches the Python
    pipeline plane-for-plane, egress is byte-identical to to_binary,
    round trip is the identity on scalars incl. deferred at both
    levels."""
    from crdt_tpu.batch.map_batch import MapBatch

    rng = np.random.RandomState(211)
    uni = _map_uni(counter_bits)
    vk = _nested_kernel(uni)
    maps = _random_nested_maps(rng, 30)
    blobs = [to_binary(m) for m in maps]

    got = MapBatch.from_wire(blobs, uni, vk)
    want = MapBatch.from_scalar([from_binary(b) for b in blobs], uni, vk)
    import jax

    for g, w in zip(
        jax.tree_util.tree_leaves(got.state), jax.tree_util.tree_leaves(want.state)
    ):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
    assert got.to_scalar(uni) == maps  # full state incl. deferred

    out = got.to_wire(uni)
    assert out == blobs  # byte-identical egress
    assert MapBatch.from_wire(out, uni, vk).to_scalar(uni) == maps


def test_map_map_mvreg_wire_inner_overflow_raises():
    from crdt_tpu.batch.map_batch import MapBatch
    from crdt_tpu.scalar.map import Map
    from crdt_tpu.scalar.mvreg import MVReg
    from crdt_tpu.utils.serde import MapOf

    uni = _map_uni()
    vk = _nested_kernel(uni)
    m = Map(MapOf(MVReg))
    # 5 inner keys under one outer key > key_capacity 4
    for ikey in range(5):
        ctx = m.get(1).derive_add_ctx(0)
        m.apply(m.update(
            1, ctx,
            lambda v, c, _ik=ikey: v.update(_ik, c, lambda r, c2: r.set(7, c2)),
        ))
    with pytest.raises(ValueError, match="inner map"):
        MapBatch.from_wire([to_binary(m)], uni, vk)


def test_map_map_mvreg_wire_mixed_patch_path():
    """Blobs outside the native varint range (a u64 counter >= 2^63
    zigzags past the parser's u64) splice through the per-blob Python
    fallback while fast rows parse natively."""
    from crdt_tpu.batch.map_batch import MapBatch
    from crdt_tpu.scalar.map import Map
    from crdt_tpu.scalar.mvreg import MVReg
    from crdt_tpu.scalar.vclock import VClock
    from crdt_tpu.utils.serde import MapOf

    rng = np.random.RandomState(212)
    uni = _map_uni(64)
    vk = _nested_kernel(uni)
    maps = _random_nested_maps(rng, 6)
    big = Map(MapOf(MVReg))
    big.clock = VClock({3: 2**63 + 5})
    maps = maps[:3] + [big] + maps[3:]
    blobs = [to_binary(m) for m in maps]
    got = MapBatch.from_wire(blobs, uni, vk)
    assert got.to_scalar(uni) == maps
    assert int(np.asarray(got.clock)[3, 3]) == 2**63 + 5
