"""True multi-process distributed joins (2 OS processes over Gloo).

Spawns ``examples/multihost_cpu.py``: two processes × 4 virtual CPU
devices join one ``jax.distributed`` runtime and run the stock
collective join over the global mesh — XLA's cross-process collectives
carry the state, the collective layer is unchanged.  Both advertised
topologies must converge against the scalar oracle:

* ``replicas`` — the all-gather itself crosses the process boundary;
* ``hybrid``  — objects partition across processes (DCN tier, zero
  cross-process join traffic), replicas join on each process's own
  devices via ``object_axis=``.
"""

import os
import subprocess
import sys

import pytest


@pytest.mark.parametrize("topology", ["replicas", "hybrid"])
def test_two_process_join_converges(topology):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(repo, "examples", "multihost_cpu.py"),
            "--objects", "8", "--topology", topology,
        ],
        capture_output=True, text=True, timeout=400, env=env,
    )
    assert proc.returncode == 0, (proc.stdout[-400:], proc.stderr[-800:])
    assert "demo: MULTIHOST OK" in proc.stdout
    assert proc.stdout.count("MULTIHOST OK") == 3  # both workers + demo
