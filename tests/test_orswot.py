"""Orswot tests — mirrors `/root/reference/test/orswot.rs` and the in-module
suite `/root/reference/src/orswot.rs:246-355`.

Covers: convergence under interleavings across 2..10 simulated replicas
(`test/orswot.rs:36-77`), the riak_dt-ported regressions, deferred-remove
preservation, and reset-remove semantics via Map (`test/orswot.rs:270-307`).
"""

import pytest
from hypothesis import given, settings
from hypothesis import strategies as st

from crdt_tpu import Dot, Map, Orswot, RmCtx, VClock
from crdt_tpu.scalar.orswot import Add, Rm

ACTOR_MAX = 11

op_prims = st.lists(
    st.tuples(
        st.integers(0, 255),  # actor
        st.integers(0, 255),  # member
        st.integers(0, 255),  # choice
        st.integers(0, 2**64 - 1),  # counter
    ),
    max_size=20,
)


def build_opvec(prims):
    """`test/orswot.rs:14-34`: alternate Add/Rm ops from primitive tuples."""
    ops = []
    for actor, member, choice, counter in prims:
        if choice % 2 == 0:
            op = Add(dot=Dot(actor, counter), member=member)
        else:
            op = Rm(clock=Dot(actor, counter).to_vclock(), member=member)
        ops.append((actor, op))
    return ops


@given(op_prims)
def test_prop_merge_converges(prims):
    """`test/orswot.rs:37-76`: route each op to witnesses[actor % i] for
    every cluster size i in 2..11; all merged results must be identical."""
    ops = build_opvec(prims)
    result = None
    for i in range(2, ACTOR_MAX):
        witnesses = [Orswot() for _ in range(i)]
        for actor, op in ops:
            witnesses[actor % i].apply(op)
        merged = Orswot()
        for witness in witnesses:
            merged.merge(witness)
        # defer_plunger flushes deferred elements (`test/orswot.rs:61-62`)
        merged.merge(Orswot())
        if result is not None:
            assert result == merged, f"diverged at cluster size {i}"
        else:
            result = merged


def test_weird_highlight_1():
    """`test/orswot.rs:83-92`: identical clocks with different elements drop
    the non-common elements — don't reuse a witness across copies."""
    a, b = Orswot(), Orswot()
    op_a = a.add(1, a.value().derive_add_ctx(1))
    op_b = b.add(2, b.value().derive_add_ctx(1))
    a.apply(op_a)
    b.apply(op_b)
    a.merge(b)
    assert a.value().val == set()


def test_adds_dont_destroy_causality():
    """`test/orswot.rs:95-133`."""
    a = Orswot()
    b = a.clone()
    c = a.clone()

    c_ctx = c.value()
    c.apply(c.add("element", c_ctx.derive_add_ctx(1)))
    c.apply(c.add("element", c_ctx.derive_add_ctx(2)))

    c_element_ctx = c.contains("element")
    # the remove context should descend from vclock {1->1, 2->1}
    assert c_element_ctx.rm_clock == VClock.from_iter([(1, 1), (2, 1)])

    a_add_ctx = a.value().derive_add_ctx(7)
    a.apply(a.add("element", a_add_ctx))
    b.apply(c.remove("element", c_element_ctx.derive_rm_ctx()))

    a.apply(a.add("element", a.value().derive_add_ctx(1)))

    a.merge(b)
    assert a.value().val == {"element"}


def test_merge_clocks_of_identical_entries():
    """`test/orswot.rs:138-160`: identical entries with different clocks are
    merged, not removed."""
    a = Orswot()
    b = a.clone()
    a.apply(a.add(1, a.value().derive_add_ctx(3)))
    b.apply(b.add(1, b.value().derive_add_ctx(7)))
    a.merge(b)
    assert a.value().val == {1}
    final_clock = VClock.from_iter([(3, 1), (7, 1)])
    read_ctx = a.contains(1)
    assert read_ctx.val is True
    assert read_ctx.rm_clock == final_clock


def test_disjoint_merge():
    """`test/orswot.rs:163-188` (riak_dt port)."""
    a = Orswot()
    b = a.clone()

    a.apply(a.add(0, a.value().derive_add_ctx(1)))
    assert a.value().val == {0}

    b.apply(b.add(1, b.value().derive_add_ctx(2)))
    assert b.value().val == {1}

    c = a.clone()
    c.merge(b)
    assert c.value().val == {0, 1}

    a.apply(a.remove(0, a.contains(0).derive_rm_ctx()))
    d = a.clone()
    d.merge(c)
    assert d.value().val == {1}


def test_no_dots_left():
    """`test/orswot.rs:193-230` (riak_dt EQC port): dropping dots in merge
    is not enough if the value is then stored with an empty clock."""
    a, b = Orswot(), Orswot()
    a.apply(a.add(0, a.value().derive_add_ctx(1)))
    b.apply(b.add(0, b.value().derive_add_ctx(2)))
    c = a.clone()
    a.apply(a.remove(0, a.contains(0).derive_rm_ctx()))

    # replicate B to A, now A has B's entry
    a.merge(b)
    assert a.value().val == {0}
    assert a.value().add_clock == VClock.from_iter([(1, 1), (2, 1)])

    b.apply(b.remove(0, b.contains(0).derive_rm_ctx()))
    assert b.value().val == set()

    # replicate C to B, now B has A's old entry
    b.merge(c)
    assert b.value().val == {0}

    # merge everything: no entry must survive with no dots
    b.merge(a)
    b.merge(c)
    assert b.value().val == set()


def test_dead_node_update():
    """`test/orswot.rs:245-267`: remove at a with a context obtained from a
    node that then goes down forever."""
    a = Orswot()
    a_op = a.add(0, a.value().derive_add_ctx(1))
    assert a_op == Add(dot=Dot(1, 1), member=0)
    a.apply(a_op)
    assert a.contains(0).rm_clock == Dot(1, 1).to_vclock()

    b = a.clone()
    b.apply(b.add(1, b.value().derive_add_ctx(2)))
    bctx = b.value()
    assert bctx.add_clock == VClock.from_iter([(1, 1), (2, 1)])
    rm_op = a.remove(0, bctx.derive_rm_ctx())
    a.apply(rm_op)
    assert a.value().val == set()


def test_reset_remove_semantics():
    """`test/orswot.rs:270-307`: reset-remove via Map<u8, Orswot>."""
    m1 = Map(Orswot)

    op1 = m1.update(101, m1.get(101).derive_add_ctx(75), lambda s, ctx: s.add(1, ctx))
    m1.apply(op1)

    m2 = m1.clone()

    read_ctx = m1.get(101)
    op2 = m1.rm(101, read_ctx.derive_rm_ctx())
    m1.apply(op2)
    op3 = m2.update(101, m2.get(101).derive_add_ctx(93), lambda s, ctx: s.add(2, ctx))
    m2.apply(op3)

    assert m1.get(101).val is None
    assert m2.get(101).val.value().val == {1, 2}

    snapshot = m1.clone()
    m1.merge(m2)
    m2.merge(snapshot)

    assert m1 == m2
    assert m1.get(101).val.value().val == {2}


# -- in-module regressions (`src/orswot.rs:246-355`) ------------------------


def test_ensure_deferred_merges():
    """`src/orswot.rs:251-282`: deferred operations must be carried over
    after a merge."""
    a, b = Orswot(), Orswot()

    b_read_ctx = b.value()
    b.apply(b.add("element 1", b_read_ctx.derive_add_ctx(5)))

    # remove with a future context
    b.apply(b.remove("element 1", RmCtx(clock=Dot(5, 4).to_vclock())))

    a_read_ctx = a.value()
    a.apply(a.add("element 4", a_read_ctx.derive_add_ctx(6)))

    # remove with a future context
    b.apply(b.remove("element 9", RmCtx(clock=Dot(4, 4).to_vclock())))

    merged = Orswot()
    merged.merge(a)
    merged.merge(b)
    merged.merge(Orswot())
    assert len(merged.deferred) == 2


def test_preserve_deferred_across_merges():
    """`src/orswot.rs:286-315`: deferred removals survive merges."""
    a = Orswot()
    b = a.clone()
    c = a.clone()

    # add element 5 from witness 1
    a.apply(a.add(5, a.value().derive_add_ctx(1)))

    # remove 5 with an advanced clock for witnesses 1 and 4
    vc = VClock.from_iter([(1, 3), (4, 8)])

    # remove from b (has not yet seen the add for 5) with advanced ctx
    b.apply(b.remove(5, RmCtx(clock=vc)))
    assert len(b.deferred) == 1

    # deferred elements survive a merge
    c.merge(b)
    assert len(c.deferred) == 1

    # merging the deferred set with one containing an inferior member hides
    # the member and keeps the deferred info
    a.merge(c)
    assert a.value().val == set()


def test_present_but_removed():
    """`src/orswot.rs:320-354` (riak_dt EQC port): dots must be dropped in
    merge when an element is present in both sets."""
    a, b = Orswot(), Orswot()
    a.apply(a.add(0, a.value().derive_add_ctx("A")))
    # replicate to C so A has 0->{a, 1}
    c = a.clone()

    a.apply(a.remove(0, a.contains(0).derive_rm_ctx()))
    assert len(a.deferred) == 0

    b.apply(b.add(0, b.value().derive_add_ctx("B")))

    # replicate B to A: A has a 0 with dot {b,1} and clock [{a,1},{b,1}]
    a.merge(b)

    b.apply(b.remove(0, b.contains(0).derive_rm_ctx()))
    # both C and A have a 0, but after the merges it must be gone: C's was
    # removed by A's remove, and A's by B's remove.
    a.merge(b)
    a.merge(c)
    assert a.value().val == set()


class TestFoldMergeTree:
    """fold_merge_tree vs the sequential left fold.

    The ORSWOT join is associative in its *observable* state — value(),
    set clock, member table — which is the CRDT convergence guarantee.
    The dot tables are NOT bit-associative in the reference semantics:
    the only-in-self rule keeps the member's FULL clock when any dot is
    novel (`orswot.rs:94-103`), so which dominated lanes survive depends
    on which partner's clock was present at that pairing, and
    apply_deferred subtracts during every intermediate merge
    (`orswot.rs:195-211,235-243`).  The scalar engine reproduces both
    effects, so the contract tested here is: order-independent pieces
    bit-equal vs the sequential fold, and the full state bit-faithful to
    the SCALAR engine folding in the same tree order."""

    def _fleets(self, rng, n, a, m, d, r, deferred_frac):
        import jax.numpy as jnp

        from crdt_tpu.utils.testdata import anti_entropy_fleets

        fleets = anti_entropy_fleets(
            rng, n, a, m, d, r, base=4, novel=1, deferred_frac=deferred_frac
        )
        return tuple(
            jnp.stack([jnp.asarray(rep[k]) for rep in fleets]) for k in range(5)
        )

    @staticmethod
    def _seq_fold(stacked, r, m, d):
        from crdt_tpu.ops import orswot_ops

        acc = tuple(x[0] for x in stacked)
        for i in range(1, r):
            acc = orswot_ops.merge(*acc, *(x[i] for x in stacked), m, d)[:5]
        return orswot_ops.merge(*acc, *acc, m, d)[:5]

    @pytest.mark.parametrize("deferred_frac", [0.0, 0.5])
    @pytest.mark.parametrize("r", [2, 3, 5, 8])
    def test_tree_fold_parity(self, r, deferred_frac):
        import numpy as np

        from crdt_tpu.ops import orswot_ops
        from crdt_tpu.scalar.orswot import Orswot
        from crdt_tpu.utils.testdata import dense_row_to_scalar

        rng = np.random.RandomState(100 + r)
        n, a, m, d = 17, 8, 5 + r, 3
        stacked = self._fleets(rng, n, a, m, d, r, deferred_frac)
        acc = self._seq_fold(stacked, r, m, d)
        got = orswot_ops.fold_merge_tree(*stacked, m, d)[:5]

        # order-independent pieces: set clock and canonical member table
        assert np.array_equal(np.asarray(got[0]), np.asarray(acc[0]))
        assert np.array_equal(np.asarray(got[1]), np.asarray(acc[1]))

        # full state must be bit-faithful to the scalar engine folding in
        # the same tree order (evens-with-odds, odd fleet carries)
        for obj in range(n):
            lvl = [
                dense_row_to_scalar(*(np.asarray(x[i, obj]) for x in stacked))
                for i in range(r)
            ]
            while len(lvl) > 1:
                nxt = []
                for i in range(0, len(lvl) - 1, 2):
                    lvl[i].merge(lvl[i + 1])
                    nxt.append(lvl[i])
                if len(lvl) % 2:
                    nxt.append(lvl[-1])
                lvl = nxt
            oracle = lvl[0]
            oracle.merge(Orswot())

            want = {
                mid: {
                    i: int(c)
                    for i, c in enumerate(np.asarray(got[2][obj][s]))
                    if int(c)
                }
                for s, mid in enumerate(int(x) for x in np.asarray(got[1][obj]))
                if mid != -1
            }
            have = {k: dict(v.dots) for k, v in oracle.entries.items()}
            assert want == have, f"object {obj}: dense tree != scalar tree"

    def test_overflow_flag_propagates(self):
        import numpy as np

        from crdt_tpu.ops import orswot_ops
        from crdt_tpu.utils.testdata import random_orswot_arrays

        import jax.numpy as jnp

        rng = np.random.RandomState(7)
        # disjoint member universes force m_cap overflow somewhere in the tree
        reps = []
        for i in range(4):
            arrs = list(random_orswot_arrays(rng, 16, 4, 4, 2))
            ids = np.asarray(arrs[1])
            ids = np.where(ids != -1, ids + 100 * i, ids)
            arrs[1] = ids
            reps.append(tuple(jnp.asarray(x) for x in arrs))
        stacked = tuple(jnp.stack([rep[k] for rep in reps]) for k in range(5))
        out = orswot_ops.fold_merge_tree(*stacked, 2, 2)
        assert bool(np.asarray(out[5]).any()), "tree fold must surface overflow"



@given(op_prims)
@settings(max_examples=20, deadline=None)
def test_prop_batch_merge_converges(prims):
    """The device engine passes the same interleaving search as the scalar
    one (`test/orswot.rs:37-76` tier-2 idiom): route each op to
    ``witnesses[actor % i]``, pack every witness as a batch row, join with
    the batched merge + defer plunger — identical for every cluster size,
    and equal to the scalar N-way join."""
    from crdt_tpu.batch import OrswotBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.utils.interning import Universe

    ops = build_opvec(prims)
    uni = Universe(CrdtConfig(num_actors=32, member_capacity=24,
                              deferred_capacity=24))
    result = None
    for i in (2, 5, 10):
        witnesses = [Orswot() for _ in range(i)]
        for actor, op in ops:
            witnesses[actor % i].apply(op)
        acc = OrswotBatch.from_scalar([witnesses[0]], uni)
        for w in witnesses[1:]:
            acc = acc.merge(OrswotBatch.from_scalar([w], uni))
        acc = acc.merge(OrswotBatch.zeros(1, uni))  # defer plunger
        merged = acc.to_scalar(uni)[0]
        if result is None:
            result = merged
            # cross-engine: the scalar fold at this cluster size agrees
            scalar = Orswot()
            for w in witnesses:
                scalar.merge(w)
            scalar.merge(Orswot())
            assert merged == scalar, "batch fold != scalar fold"
        else:
            assert result == merged, f"batch fold diverged at cluster size {i}"
