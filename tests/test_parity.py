"""Scalar ↔ batch parity — the engine-split contract (SURVEY.md §7.0).

For every type: generate random scalar states from op sequences (the same
generators as the reference property tests), pack them into SoA batches,
merge on device (jit), unpack, and require **bit-identical** state vs the
scalar merge — clocks, entries, and deferred buffers, not just ``value()``.

These run on the CPU backend (conftest forces ``JAX_PLATFORMS=cpu``); the
same kernels run unchanged on TPU.
"""

import jax.numpy as jnp
from hypothesis import given, settings
from hypothesis import strategies as st

from crdt_tpu import Dot, GCounter, LWWReg, MVReg, Orswot, PNCounter, RmCtx, VClock
from crdt_tpu.batch import (
    GCounterBatch,
    LWWRegBatch,
    MVRegBatch,
    OrswotBatch,
    PNCounterBatch,
    VClockBatch,
)
from crdt_tpu.config import CrdtConfig
from crdt_tpu.scalar.orswot import Add, Rm
from crdt_tpu.utils.interning import Universe


def small_universe(**kw):
    defaults = dict(num_actors=8, member_capacity=24, deferred_capacity=16, mv_capacity=12)
    defaults.update(kw)
    return Universe(CrdtConfig(**defaults))


# -- strategies -------------------------------------------------------------

actors = st.integers(0, 7)
counters = st.integers(0, 9)

vclocks = st.lists(st.tuples(actors, counters), max_size=10).map(VClock.from_iter)


@st.composite
def orswots(draw):
    """Random Orswot built from an op sequence (mirrors `test/orswot.rs:14-34`)."""
    s = Orswot()
    for actor, member, choice, counter in draw(
        st.lists(st.tuples(actors, st.integers(0, 9), st.integers(0, 3), st.integers(1, 9)), max_size=12)
    ):
        if choice % 2 == 0:
            s.apply(Add(dot=Dot(actor, counter), member=member))
        else:
            s.apply(Rm(clock=Dot(actor, counter).to_vclock(), member=member))
    return s


@st.composite
def mvregs(draw):
    r = MVReg()
    for val, actor in draw(st.lists(st.tuples(st.integers(0, 20), actors), max_size=6)):
        r.apply(r.set(val, r.read().derive_add_ctx(actor)))
    return r


# -- helpers ----------------------------------------------------------------


def scalar_merge(a, b):
    out = a.clone()
    out.merge(b)
    return out


# -- VClock / counters ------------------------------------------------------


@given(st.lists(st.tuples(vclocks, vclocks), min_size=4, max_size=4))
def test_vclock_merge_parity(pairs):
    uni = small_universe()
    lhs = [a for a, _ in pairs]
    rhs = [b for _, b in pairs]
    expected = [scalar_merge(a, b) for a, b in pairs]

    ba = VClockBatch.from_scalar(lhs, uni)
    bb = VClockBatch.from_scalar(rhs, uni)
    got = ba.merge(bb).to_scalar(uni)
    assert got == expected

    # partial-order predicates agree too
    import numpy as np

    leq = np.asarray(ba.leq(bb))
    conc = np.asarray(ba.concurrent(bb))
    for i, (a, b) in enumerate(pairs):
        assert bool(leq[i]) == (a <= b)
        assert bool(conc[i]) == a.concurrent(b)


@given(st.lists(st.tuples(vclocks, vclocks), min_size=4, max_size=4))
def test_gcounter_merge_parity(pairs):
    uni = small_universe()
    lhs = [GCounter(a.clone()) for a, _ in pairs]
    rhs = [GCounter(b.clone()) for _, b in pairs]
    expected = [scalar_merge(a, b) for a, b in zip(lhs, rhs)]

    got = (
        GCounterBatch.from_scalar(lhs, uni)
        .merge(GCounterBatch.from_scalar(rhs, uni))
        .to_scalar(uni)
    )
    assert [g.value() for g in got] == [e.value() for e in expected]
    assert [g.inner for g in got] == [e.inner for e in expected]


@given(st.lists(st.tuples(vclocks, vclocks, vclocks, vclocks), min_size=4, max_size=4))
def test_pncounter_merge_parity(quads):
    from crdt_tpu.scalar.gcounter import GCounter as G

    uni = small_universe()
    lhs = [PNCounter(G(p.clone()), G(n.clone())) for p, n, _, _ in quads]
    rhs = [PNCounter(G(p.clone()), G(n.clone())) for _, _, p, n in quads]
    expected = [scalar_merge(a, b) for a, b in zip(lhs, rhs)]

    batch = PNCounterBatch.from_scalar(lhs, uni).merge(PNCounterBatch.from_scalar(rhs, uni))
    got = batch.to_scalar(uni)
    assert [g.value() for g in got] == [e.value() for e in expected]
    import numpy as np

    assert list(np.asarray(batch.value())) == [e.value() for e in expected]


# -- LWWReg -----------------------------------------------------------------


@given(
    st.lists(
        st.tuples(st.integers(0, 30), st.integers(0, 9), st.integers(0, 30), st.integers(0, 9)),
        min_size=6,
        max_size=6,
    )
)
def test_lwwreg_merge_parity(prims):
    from crdt_tpu.error import ConflictingMarker

    uni = small_universe()
    lhs = [LWWReg(val=v1, marker=m1) for v1, m1, _, _ in prims]
    rhs = [LWWReg(val=v2, marker=m2) for _, _, v2, m2 in prims]

    expected, conflicts = [], []
    for a, b in zip(lhs, rhs):
        out = a.clone()
        try:
            out.merge(b)
            conflicts.append(False)
        except ConflictingMarker:
            conflicts.append(True)
        expected.append(out)

    ba = LWWRegBatch.from_scalar(lhs, uni)
    bb = LWWRegBatch.from_scalar(rhs, uni)
    merged, bitmap = ba.merge_with_conflicts(bb)
    import numpy as np

    assert list(np.asarray(bitmap)) == conflicts
    got = merged.to_scalar(uni)
    for g, e, c in zip(got, expected, conflicts):
        if not c:
            assert g == e

    if any(conflicts):
        try:
            ba.merge(bb)
            assert False, "expected ConflictingMarker"
        except ConflictingMarker:
            pass


# -- MVReg ------------------------------------------------------------------


@given(st.lists(st.tuples(mvregs(), mvregs()), min_size=3, max_size=3))
@settings(max_examples=50)
def test_mvreg_merge_parity(pairs):
    uni = small_universe()
    lhs = [a for a, _ in pairs]
    rhs = [b for _, b in pairs]
    expected = [scalar_merge(a, b) for a, b in pairs]

    got = (
        MVRegBatch.from_scalar(lhs, uni)
        .merge(MVRegBatch.from_scalar(rhs, uni))
        .to_scalar(uni)
    )
    for g, e in zip(got, expected):
        assert g == e  # MVReg __eq__ is set-equality over (clock, val)


@given(mvregs(), st.integers(0, 20), actors)
@settings(max_examples=50)
def test_mvreg_apply_put_parity(reg, val, actor):
    uni = small_universe()
    ctx = reg.read().derive_add_ctx(actor)
    op = reg.set(val, ctx)

    expected = reg.clone()
    expected.apply(op)

    batch = MVRegBatch.from_scalar([reg], uni)
    op_clock = VClockBatch.from_scalar([op.clock], uni).clocks
    op_val = jnp.asarray([uni.member_id(op.val)])
    got = batch.apply_put(op_clock, op_val).to_scalar(uni)[0]
    assert got == expected


# -- Orswot -----------------------------------------------------------------


@given(st.lists(st.tuples(orswots(), orswots()), min_size=3, max_size=3))
@settings(max_examples=60)
def test_orswot_merge_parity(pairs):
    uni = small_universe()
    lhs = [a for a, _ in pairs]
    rhs = [b for _, b in pairs]
    expected = [scalar_merge(a, b) for a, b in pairs]

    got = (
        OrswotBatch.from_scalar(lhs, uni)
        .merge(OrswotBatch.from_scalar(rhs, uni))
        .to_scalar(uni)
    )
    for g, e in zip(got, expected):
        assert g == e, f"\nbatch:  {g!r}\nscalar: {e!r}"


@given(orswots(), actors, st.integers(0, 9))
@settings(max_examples=60)
def test_orswot_apply_add_parity(s, actor, member):
    uni = small_universe()
    ctx = s.value().derive_add_ctx(actor)
    op = s.add(member, ctx)

    expected = s.clone()
    expected.apply(op)

    batch = OrswotBatch.from_scalar([s], uni)
    got = batch.apply_add(
        jnp.asarray([uni.actor_idx(op.dot.actor)]),
        jnp.asarray([op.dot.counter]),
        jnp.asarray([uni.member_id(op.member)]),
    ).to_scalar(uni)[0]
    assert got == expected, f"\nbatch:  {got!r}\nscalar: {expected!r}"


@given(orswots(), st.integers(0, 9), vclocks)
@settings(max_examples=60)
def test_orswot_apply_remove_parity(s, member, rm_clock)    :
    uni = small_universe()
    op = s.remove(member, RmCtx(clock=rm_clock))

    expected = s.clone()
    expected.apply(op)

    batch = OrswotBatch.from_scalar([s], uni)
    got = batch.apply_remove(
        VClockBatch.from_scalar([op.clock], uni).clocks,
        jnp.asarray([uni.member_id(op.member)]),
    ).to_scalar(uni)[0]
    assert got == expected, f"\nbatch:  {got!r}\nscalar: {expected!r}"


def test_orswot_regressions_on_batch():
    """The riak_dt regression scenarios, replayed through the batch engine:
    pack → merge → unpack at each merge point (`test/orswot.rs:193-230`)."""
    uni = small_universe()

    def bmerge(a, b):
        return (
            OrswotBatch.from_scalar([a], uni)
            .merge(OrswotBatch.from_scalar([b], uni))
            .to_scalar(uni)[0]
        )

    # test_no_dots_left
    a, b = Orswot(), Orswot()
    a.apply(a.add(0, a.value().derive_add_ctx(1)))
    b.apply(b.add(0, b.value().derive_add_ctx(2)))
    c = a.clone()
    a.apply(a.remove(0, a.contains(0).derive_rm_ctx()))
    a = bmerge(a, b)
    assert a.value().val == {0}
    b.apply(b.remove(0, b.contains(0).derive_rm_ctx()))
    b = bmerge(b, c)
    assert b.value().val == {0}
    b = bmerge(b, a)
    b = bmerge(b, c)
    assert b.value().val == set()


# -- GSet -------------------------------------------------------------------


@given(
    st.lists(st.sets(st.integers(0, 15)), min_size=4, max_size=4),
    st.lists(st.sets(st.integers(0, 15)), min_size=4, max_size=4),
)
def test_gset_merge_parity(xs, ys):
    from crdt_tpu import GSet
    from crdt_tpu.batch import GSetBatch

    uni = small_universe()
    lhs = [GSet(x) for x in xs]
    rhs = [GSet(y) for y in ys]
    expected = [scalar_merge(a, b) for a, b in zip(lhs, rhs)]

    cap = 16
    got = (
        GSetBatch.from_scalar(lhs, uni, cap)
        .merge(GSetBatch.from_scalar(rhs, uni, cap))
        .to_scalar(uni)
    )
    assert got == expected


def test_gset_rejects_out_of_capacity_ids():
    import pytest

    from crdt_tpu.batch import GSetBatch

    b = GSetBatch.zeros(2, 4)
    with pytest.raises(ValueError):
        b.insert(jnp.asarray([4, 0]))
    with pytest.raises(ValueError):
        b.contains(jnp.asarray([9, 0]))


def test_orswot_join_fleet_parity():
    """OrswotBatch.join_fleet (tree reduction) value()-parity vs the
    scalar engine's merge-all loop (`test/orswot.rs:45-62`), including
    deferred removes flushed by the plunger."""
    import numpy as np

    from crdt_tpu.batch import OrswotBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.scalar.orswot import Orswot
    from crdt_tpu.utils.interning import Universe

    rng = np.random.RandomState(11)
    uni = Universe(CrdtConfig(num_actors=6, member_capacity=16, deferred_capacity=8))
    n, r = 9, 5
    fleets = []
    for _ in range(r):
        row = []
        for _ in range(n):
            s = Orswot()
            for _ in range(int(rng.randint(0, 6))):
                actor, member = int(rng.randint(0, 6)), int(rng.randint(0, 10))
                ctx = s.value().derive_add_ctx(actor)
                s.apply(s.add(member, ctx))
            if rng.rand() < 0.4 and s.entries:
                member = next(iter(s.entries))
                ctx = s.contains(member).derive_rm_ctx()
                ctx.clock.witness(int(rng.randint(0, 6)), int(rng.randint(50, 60)))
                s.apply(s.remove(member, ctx))  # causally-future: defers
            row.append(s)
        fleets.append(row)

    joined = OrswotBatch.join_fleet(
        [OrswotBatch.from_scalar(row, uni) for row in fleets]
    )
    got_sets = joined.value_sets(uni)

    expected = []
    for i in range(n):
        merged = Orswot()
        for row in fleets:
            merged.merge(row[i].clone())
        merged.merge(Orswot())  # plunger
        expected.append(merged.value().val)
    assert got_sets == expected


def test_counter_bits_32_parity():
    """counter_bits=32 — the TPU-native width (no 64-bit emulation) —
    must produce identical value() results through Orswot, MVReg and
    nested Map batch paths, with every counter plane actually uint32."""
    import numpy as np

    from crdt_tpu.batch import MapBatch, OrswotBatch
    from crdt_tpu.batch.val_kernels import MVRegKernel
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.scalar.map import Map
    from crdt_tpu.scalar.mvreg import MVReg
    from crdt_tpu.scalar.orswot import Orswot
    from crdt_tpu.utils.interning import Universe
    from crdt_tpu.utils.testdata import random_mvreg_map

    rng = np.random.RandomState(21)
    cfg32 = CrdtConfig(num_actors=8, member_capacity=12, deferred_capacity=4,
                       mv_capacity=6, key_capacity=8, counter_bits=32)
    uni = Universe(cfg32)

    # Orswot
    rows_a, rows_b = [], []
    for _ in range(12):
        x, y = Orswot(), Orswot()
        for j in range(int(rng.randint(1, 5))):
            x.apply(x.add(int(rng.randint(0, 9)), x.value().derive_add_ctx(j % 8)))
            y.apply(y.add(int(rng.randint(0, 9)), y.value().derive_add_ctx((j + 3) % 8)))
        rows_a.append(x)
        rows_b.append(y)
    ba = OrswotBatch.from_scalar(rows_a, uni)
    assert ba.clock.dtype == jnp.uint32 and ba.dots.dtype == jnp.uint32
    got = ba.merge(OrswotBatch.from_scalar(rows_b, uni)).value_sets(uni)
    for i in range(12):
        want = rows_a[i].clone()
        want.merge(rows_b[i])
        assert got[i] == want.value().val, i

    # nested Map<int, MVReg> through the value-kernel protocol
    maps_a = [random_mvreg_map(rng) for _ in range(6)]
    maps_b = [random_mvreg_map(rng) for _ in range(6)]
    kern = MVRegKernel.from_config(cfg32)
    assert kern.counter_bits == 32
    ma = MapBatch.from_scalar(maps_a, uni, kern)
    assert ma.clock.dtype == jnp.uint32
    merged = ma.merge(MapBatch.from_scalar(maps_b, uni, kern))
    back = merged.to_scalar(uni)
    for i in range(6):
        want = maps_a[i].clone()
        want.merge(maps_b[i])
        assert back[i] == want, i


def test_counter_bits_32_parity_fused_kernel():
    """u32-vs-u64 parity through the FUSED merge path (VERDICT r3 item 4):
    the same logical fleet packed at counter_bits=32 and joined through
    the pallas kernel (interpret emulation on the CPU test backend) must
    produce the same value() sets as the u64 pack joined through the rank
    reference — the product-default (u32, fused) and parity-oracle (u64,
    rank) configurations agree end-to-end, deferred removes included."""
    import numpy as np

    from crdt_tpu.batch import OrswotBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.scalar.orswot import Orswot
    from crdt_tpu.utils.interning import Universe

    rng = np.random.RandomState(37)
    base = dict(num_actors=8, member_capacity=12, deferred_capacity=4)
    uni32 = Universe(CrdtConfig(counter_bits=32, merge_impl="pallas", **base))
    uni64 = Universe(CrdtConfig(counter_bits=64, merge_impl="rank", **base))

    fleets = []
    for _ in range(4):
        row = []
        for _ in range(10):
            s = Orswot()
            for j in range(int(rng.randint(1, 5))):
                s.apply(s.add(int(rng.randint(0, 9)),
                              s.value().derive_add_ctx(j % 8)))
            if rng.rand() < 0.4 and s.entries:
                member = next(iter(s.entries))
                ctx = s.contains(member).derive_rm_ctx()
                ctx.clock.witness(int(rng.randint(0, 8)),
                                  int(rng.randint(50, 60)))
                s.apply(s.remove(member, ctx))  # causally-future: defers
            row.append(s)
        fleets.append(row)

    j32 = OrswotBatch.join_fleet(
        [OrswotBatch.from_scalar(row, uni32) for row in fleets],
        impl=uni32.config.merge_impl,
    )
    assert j32.clock.dtype == jnp.uint32
    j64 = OrswotBatch.join_fleet(
        [OrswotBatch.from_scalar(row, uni64) for row in fleets],
        impl=uni64.config.merge_impl,
    )
    assert j64.clock.dtype == jnp.uint64
    assert j32.value_sets(uni32) == j64.value_sets(uni64)
    # counters themselves agree (no narrowing happened at these counts)
    np.testing.assert_array_equal(
        np.asarray(j32.clock, dtype=np.uint64), np.asarray(j64.clock)
    )


def test_lww_markers_stay_64bit_under_counter_bits_32():
    """Markers are timestamps (u64, `lwwreg.rs:16-24`), not op counters:
    counter_bits=32 must not narrow them."""
    from crdt_tpu.batch import LWWRegBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.scalar.lwwreg import LWWReg
    from crdt_tpu.utils.interning import Universe

    uni = Universe(CrdtConfig(num_actors=4, counter_bits=32))
    epoch_micros = 1_785_375_612_441_000  # > 2**32
    regs = [LWWReg("v", epoch_micros)]
    batch = LWWRegBatch.from_scalar(regs, uni)
    assert batch.markers.dtype == jnp.uint64
    assert batch.to_scalar(uni)[0].marker == epoch_micros


# -- Causal::truncate --------------------------------------------------------


@given(st.lists(st.tuples(orswots(), vclocks), min_size=1, max_size=4))
@settings(max_examples=60)
def test_orswot_truncate_parity(pairs):
    """`orswot.rs:159-172` on the batch engine: bit-identical state vs the
    scalar truncate, per object."""
    uni = small_universe()
    states = [s for s, _ in pairs]
    clocks = [c for _, c in pairs]

    expected = []
    for s, c in pairs:
        e = s.clone()
        e.truncate(c)
        expected.append(e)

    batch = OrswotBatch.from_scalar(states, uni)
    got = batch.truncate(
        VClockBatch.from_scalar(clocks, uni).clocks
    ).to_scalar(uni)
    assert got == expected, f"\nbatch:  {got!r}\nscalar: {expected!r}"


@given(st.lists(st.tuples(mvregs(), vclocks), min_size=1, max_size=4))
@settings(max_examples=60)
def test_mvreg_truncate_parity(pairs):
    """`mvreg.rs:100-113` on the batch engine."""
    uni = small_universe()
    expected = []
    for r, c in pairs:
        e = r.clone()
        e.truncate(c)
        expected.append(e)

    batch = MVRegBatch.from_scalar([r for r, _ in pairs], uni)
    got = batch.truncate(
        VClockBatch.from_scalar([c for _, c in pairs], uni).clocks
    ).to_scalar(uni)
    assert got == expected, f"\nbatch:  {got!r}\nscalar: {expected!r}"


def test_truncate_empty_clock_is_identity():
    """Truncating by the empty clock must be a no-op (`vclock.rs:103-120`
    GLB with nothing removes nothing)."""
    uni = small_universe()
    s = Orswot()
    s.apply(s.add("m", s.value().derive_add_ctx(1)))
    batch = OrswotBatch.from_scalar([s], uni)
    got = batch.truncate(jnp.zeros_like(batch.clock)).to_scalar(uni)[0]
    assert got == s
