"""Capacity observatory tests — plane-occupancy kernels, growth/ETA
gauges, watermark states, /healthz body, oplog occupancy, regrow
timeline, fleet aggregates (crdt_tpu.obs.capacity +
crdt_tpu.batch.occupancy).

The long-soak acceptance run (3-node gossip fleet under churn, exact
plane-bytes parity, monotone growth, shrinking ETA) lives in
``tests/test_capacity_soak.py`` behind the ``slow`` marker; this module
pins the pieces at tier-1 speed.
"""

import json
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from crdt_tpu.batch import OrswotBatch
from crdt_tpu.batch.gcounter_batch import GCounterBatch
from crdt_tpu.batch.map_batch import MapBatch
from crdt_tpu.batch.occupancy import occupancy_of
from crdt_tpu.batch.pncounter_batch import PNCounterBatch
from crdt_tpu.batch.val_kernels import MVRegKernel
from crdt_tpu.batch.vclock_batch import VClockBatch
from crdt_tpu.cluster import ClusterNode
from crdt_tpu.config import CrdtConfig
from crdt_tpu.obs import capacity as obs_capacity
from crdt_tpu.obs import events as obs_events
from crdt_tpu.obs import export as obs_export
from crdt_tpu.obs import fleet as obs_fleet
from crdt_tpu.obs import metrics as obs_metrics
from crdt_tpu.obs import namespace
from crdt_tpu.obs.capacity import CapacityTracker, ETA_NOT_GROWING
from crdt_tpu.oplog import OpApplier, OpBatch, OpLog
from crdt_tpu.parallel import JoinExecutor, JoinStats
from crdt_tpu.scalar.ctx import RmCtx
from crdt_tpu.scalar.orswot import Orswot
from crdt_tpu.scalar.vclock import VClock
from crdt_tpu.utils.interning import Universe

pytestmark = pytest.mark.obs


def _uni(**kw):
    cfg = dict(num_actors=8, member_capacity=16, deferred_capacity=4,
               counter_bits=32)
    cfg.update(kw)
    return Universe.identity(CrdtConfig(**cfg))


def _orswot(uni, member_counts, deferred_on=()):
    """One Orswot per entry of ``member_counts``, the i-th holding that
    many members; objects in ``deferred_on`` also buffer one deferred
    remove (a rm witnessed by a clock the set has not seen)."""
    states = []
    for i, k in enumerate(member_counts):
        s = Orswot()
        for m in range(k):
            s.apply(s.add(m, s.value().derive_add_ctx(0)))
        if i in deferred_on:
            future = VClock()
            future.witness(5, 99)
            s.apply(s.remove(0, RmCtx(clock=future)))
            assert s.deferred
        states.append(s)
    return OrswotBatch.from_scalar(states, uni)


def _plane_nbytes(batch):
    return sum(x.nbytes for x in (batch.clock, batch.ids, batch.dots,
                                  batch.d_ids, batch.d_clocks))


# ---- the occupancy kernels -------------------------------------------------


def test_orswot_occupancy_counts_and_exact_bytes():
    uni = _uni()
    batch = _orswot(uni, [1, 3, 5], deferred_on=(1,))
    occ = occupancy_of(batch)
    assert occ.kind == "orswot"
    assert occ.objects == 3
    assert occ.slot_capacity == 16 and occ.slots == 3 * 16
    assert occ.live == 1 + 3 + 5
    assert occ.live_max == 5
    assert occ.tombstones == 1 and occ.tombstone_capacity == 4
    assert occ.actors == 8 and occ.actors_live == 1
    # the headline contract: reported bytes == actual buffer nbytes
    assert occ.bytes == _plane_nbytes(batch)
    assert 0.0 < occ.utilization < 1.0


def test_clock_and_counter_plane_occupancy():
    uni = _uni()
    vc_a, vc_b = VClock(), VClock()
    vc_a.witness(0, 3)
    vc_a.witness(2, 1)
    vc_b.witness(2, 7)
    vcb = VClockBatch.from_scalar([vc_a, vc_b], uni)
    occ = occupancy_of(vcb)
    assert occ.kind == "vclock"
    assert (occ.objects, occ.slot_capacity, occ.slots) == (2, 8, 16)
    assert occ.live == 3          # three populated dots
    assert occ.live_max == 2      # object 0 has two actors
    assert occ.actors_live == 2   # actor columns 0 and 2
    assert occ.bytes == vcb.clocks.nbytes

    gcb = GCounterBatch(clocks=vcb.clocks)
    assert occupancy_of(gcb).kind == "gcounter"

    planes = jnp.stack([vcb.clocks, jnp.zeros_like(vcb.clocks)], axis=1)
    pnb = PNCounterBatch(planes=planes)
    occ = occupancy_of(pnb)
    assert occ.kind == "pncounter"
    assert occ.live == 3 and occ.live_max == 2 and occ.actors_live == 2
    assert occ.slots == 2 * 2 * 8
    assert occ.bytes == planes.nbytes


def test_map_occupancy():
    uni = _uni(key_capacity=4, mv_capacity=2)
    batch = MapBatch.zeros(3, uni, MVRegKernel.from_config(uni.config))
    occ = occupancy_of(batch)
    assert occ.kind == "map"
    assert (occ.objects, occ.slot_capacity) == (3, 4)
    assert occ.live == 0 and occ.tombstones == 0
    assert occ.bytes == sum(
        x.nbytes for x in jax.tree_util.tree_leaves(batch.state))
    # populate two key slots on one object and re-measure
    batch = batch.replace(keys=batch.keys.at[1, 0].set(7).at[1, 1].set(9))
    occ = occupancy_of(batch)
    assert occ.live == 2 and occ.live_max == 2


def test_occupancy_rejects_unknown_batch_types():
    with pytest.raises(TypeError, match="no occupancy kernel"):
        occupancy_of(object())


# ---- the tracker: growth rates, ETA, watermark ------------------------------


def test_tracker_growth_rate_eta_and_watermark_transitions():
    uni = _uni(member_capacity=32)
    reg = obs_metrics.MetricsRegistry()
    t = [0.0]
    trk = CapacityTracker(reg, max_capacity=32, alpha=1.0,
                          clock=lambda: t[0])

    occ = trk.sample(_orswot(uni, [4]))
    g = reg.snapshot()["gauges"]
    assert g["capacity.orswot.live_max"] == 4
    assert g["capacity.orswot.eta_s"] == ETA_NOT_GROWING  # one sample: no rate
    assert "capacity.orswot.growth_rows_per_s" not in g
    assert g["capacity.orswot.watermark"] == 0
    assert reg.snapshot()["counters"]["capacity.samples"] == 1

    # steady growth: +4 rows per 10 s → rate 0.4 rows/s, shrinking ETA
    etas = []
    for live in (8, 12, 16):
        t[0] += 10.0
        trk.sample(_orswot(uni, [live]))
        g = reg.snapshot()["gauges"]
        assert g["capacity.orswot.growth_rows_per_s"] == pytest.approx(0.4)
        etas.append(g["capacity.orswot.eta_s"])
        assert etas[-1] == pytest.approx((32 - live) / 0.4)
    assert etas == sorted(etas, reverse=True)  # ETA shrinks as planes fill

    # warn at 0.7 * 32 = 22.4 rows, critical at 0.9 * 32 = 28.8
    t[0] += 10.0
    trk.sample(_orswot(uni, [24]))
    assert reg.snapshot()["gauges"]["capacity.orswot.watermark"] == 1
    assert trk.watermark()["state"] == "warn"
    t[0] += 10.0
    trk.sample(_orswot(uni, [30]))
    g = reg.snapshot()["gauges"]
    assert g["capacity.orswot.watermark"] == 2
    assert g["capacity.watermark"] == 2
    wm = trk.watermark()
    assert wm["state"] == "critical"
    assert wm["planes"]["orswot"]["ceiling"] == 32
    assert wm["planes"]["orswot"]["eta_s"] > 0

    # a flat plane stops growing: EWMA with alpha=1 → rate 0, eta sentinel
    t[0] += 10.0
    trk.sample(_orswot(uni, [30]))
    assert reg.snapshot()["gauges"]["capacity.orswot.eta_s"] \
        == ETA_NOT_GROWING


def test_tracker_label_and_ceiling_rules():
    uni = _uni()
    reg = obs_metrics.MetricsRegistry()
    trk = CapacityTracker(reg, max_capacity=1 << 10)
    with pytest.raises(ValueError, match="single metric segment"):
        trk.sample(_orswot(uni, [1]), label="a.b")
    # actor planes cap at their own width, not the executor ceiling
    vc = VClock()
    vc.witness(0, 1)
    trk.sample(VClockBatch.from_scalar([vc], uni))
    assert trk.planes()["vclock"].ceiling == 8


def test_every_published_name_has_a_manifest_row():
    uni = _uni()
    reg = obs_metrics.MetricsRegistry()
    trk = CapacityTracker(reg)
    trk.sample(_orswot(uni, [2, 3]))
    trk.sample(_orswot(uni, [2, 4]))  # second sample adds the rate gauge
    log = OpLog(uni, capacity=64)
    trk.sample_oplog(log)
    trk.sample_gap_buffer(OpApplier(uni))
    snap = reg.snapshot()
    for name in snap["gauges"]:
        assert namespace.match(name, "gauge") is not None, name
    for name in snap["counters"]:
        assert namespace.match(name, "counter") is not None, name


def test_tracker_ewma_reseeds_on_capacity_change():
    """The capacity-ETA edge causal GC exposes: after a shrink (or a
    regrow), the live_max delta measures the re-pack, not write demand
    — a stale positive EWMA must not keep counting down an overflow
    ETA against the new rung."""
    from crdt_tpu.gc.repack import repack_orswot

    uni = _uni(member_capacity=32)
    reg = obs_metrics.MetricsRegistry()
    t = [0.0]
    trk = CapacityTracker(reg, max_capacity=64, alpha=1.0,
                          clock=lambda: t[0])

    trk.sample(_orswot(uni, [4]))
    for live in (12, 20):
        t[0] += 10.0
        trk.sample(_orswot(uni, [live]))
    g = reg.snapshot()["gauges"]
    assert g["capacity.orswot.growth_rows_per_s"] == pytest.approx(0.8)
    assert g["capacity.orswot.eta_s"] > 0

    # GC re-packs the plane (32 -> 8 slots, live window back to 4):
    # the rate gauge must re-seed, not report the old +0.8 — and the
    # huge negative live_max delta must not poison the EWMA either
    shrunk, _ = repack_orswot(_orswot(uni, [4]), 8, 4, registry=reg)
    t[0] += 10.0
    trk.sample(shrunk)
    g = reg.snapshot()["gauges"]
    assert g["capacity.orswot.growth_rows_per_s"] == 0.0
    assert g["capacity.orswot.eta_s"] == ETA_NOT_GROWING

    # growth measured AFTER the shrink re-seeds from scratch (alpha-1:
    # the first post-shrink delta IS the rate; no pre-shrink memory)
    t[0] += 10.0
    trk.sample(repack_orswot(_orswot(uni, [6]), 8, 4,
                             registry=obs_metrics.MetricsRegistry())[0])
    g = reg.snapshot()["gauges"]
    assert g["capacity.orswot.growth_rows_per_s"] == pytest.approx(0.2)


# ---- /healthz --------------------------------------------------------------


def test_healthz_serves_capacity_watermark_json():
    uni = _uni()
    reg = obs_metrics.MetricsRegistry()
    trk = CapacityTracker(reg, max_capacity=4)
    trk.sample(_orswot(uni, [3]))  # 3/4 = 0.75 → warn
    srv = obs_export.start_metrics_server(port=0, registry=reg,
                                          capacity=trk)
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/healthz", timeout=10
        ) as resp:
            assert resp.status == 200  # a warn watermark is an alert,
            #                            not a liveness failure
            doc = json.loads(resp.read())
        assert doc["status"] == "warn"
        plane = doc["capacity"]["planes"]["orswot"]
        assert plane["state"] == "warn"
        assert plane["live_max"] == 3 and plane["ceiling"] == 4
        assert plane["eta_s"] == ETA_NOT_GROWING
        assert "uptime_s" in doc
    finally:
        srv.stop()


# ---- oplog occupancy (the PR 7 buffers, now loud before they throw) --------


def test_oplog_publishes_depth_and_watermark_gauges():
    uni = _uni()
    log = OpLog(uni, capacity=128)
    ops = OpBatch(
        kind=np.zeros(4, np.uint8), obj=np.arange(4) % 2,
        actor=np.zeros(4, np.int32),
        counter=np.arange(1, 5, dtype=np.uint64),
        member=np.arange(4, dtype=np.int32),
    )
    log.append(ops)
    g = obs_metrics.registry().snapshot()["gauges"]
    assert g["oplog.log_depth"] == 4
    assert g["oplog.watermark"] == 4
    o = log.occupancy()
    assert o["ops"] == 4 and o["capacity"] == 128 and o["segments"] == 1
    assert o["bytes"] == (ops.kind.nbytes + ops.obj.nbytes
                          + ops.actor.nbytes + ops.counter.nbytes
                          + ops.member.nbytes)
    assert o["watermark_max"] == 4
    log.drain()
    g = obs_metrics.registry().snapshot()["gauges"]
    assert g["oplog.log_depth"] == 0
    assert g["oplog.watermark"] == 4  # the high-watermark survives drains

    reg = obs_metrics.MetricsRegistry()
    trk = CapacityTracker(reg)
    trk.sample_oplog(log)
    g = reg.snapshot()["gauges"]
    assert g["capacity.oplog.slots"] == 128
    assert g["capacity.oplog.live"] == 0


def test_gap_buffer_occupancy_counts_parked_adds():
    uni = _uni()
    applier = OpApplier(uni, park_capacity=32)
    batch = _orswot(uni, [1, 1])
    gapped = OpBatch(
        kind=np.zeros(1, np.uint8), obj=np.zeros(1, np.int64),
        actor=np.zeros(1, np.int32),
        counter=np.asarray([9], np.uint64),  # clock is at 1: dots 2..8 missing
        member=np.asarray([7], np.int32),
    )
    _, report = applier.apply_ops(batch, gapped)
    assert report.parked == 1
    o = applier.occupancy()
    assert o["ops"] == 1 and o["capacity"] == 32 and o["bytes"] > 0
    reg = obs_metrics.MetricsRegistry()
    trk = CapacityTracker(reg)
    trk.sample_gap_buffer(applier)
    assert reg.snapshot()["gauges"]["capacity.oplog_gap.live"] == 1


# ---- regrow correlation ----------------------------------------------------


def test_executor_regrow_events_carry_before_after_stamps():
    uni = Universe(CrdtConfig(num_actors=8, member_capacity=2,
                              deferred_capacity=2, counter_bits=32))
    rows = [[("a", 0), ("b", 0)], [("c", 1), ("d", 1)], [("e", 2), ("f", 2)]]
    batches = []
    for row in rows:
        s = Orswot()
        for member, actor in row:
            s.apply(s.add(member, s.value().derive_add_ctx(actor)))
        batches.append(OrswotBatch.from_scalar([s], uni))
    obs_events.recorder().clear()
    stats = JoinStats()
    JoinExecutor(strategy="sequential").join_all(batches, stats=stats)
    assert stats.overflow_regrows >= 1
    timeline = obs_capacity.capacity_tracker().regrow_timeline()
    assert len(timeline) == stats.overflow_regrows
    for entry in timeline:
        before_m, after_m = entry["member_capacity"]
        assert after_m > before_m >= 2
        assert entry["schedule"] == "sequential"
        before_d, after_d = entry["deferred_capacity"]
        assert after_d == before_d  # only the overflowed axis regrew
    # the timeline is ordered and capacities walk the doubling ladder
    walks = [e["member_capacity"] for e in timeline]
    assert all(a == 2 * b for b, a in walks)


# ---- fleet aggregation -----------------------------------------------------


def _node_slice(node_id, bytes_, eta):
    reg = obs_metrics.MetricsRegistry()
    reg.gauge_set("capacity.orswot.bytes", bytes_)
    reg.gauge_set("capacity.orswot.eta_s", eta)
    reg.gauge_set("capacity.watermark", 1 if eta >= 0 else 0)
    return obs_fleet.capture_slice(node_id, registry=reg)


def test_fleet_capacity_sum_and_max_aggregates():
    snap = _node_slice("a", 100.0, ETA_NOT_GROWING) \
        .merge(_node_slice("b", 250.0, 50.0))
    cap = snap.fleet_capacity()
    assert cap["capacity.orswot.bytes"] == {
        "sum": 350.0, "max": 250.0, "nodes": 2}
    # the -1 "not growing" sentinel must not shadow the finite horizon
    assert cap["capacity.orswot.eta_s"]["max"] == 50.0
    assert cap["capacity.watermark"]["max"] == 1.0
    # every node flat → the sentinel IS the fleet max
    flat = _node_slice("a", 1.0, ETA_NOT_GROWING) \
        .merge(_node_slice("b", 2.0, ETA_NOT_GROWING))
    assert flat.fleet_capacity()["capacity.orswot.eta_s"]["max"] \
        == ETA_NOT_GROWING

    text = obs_fleet.fleet_prometheus_text(snap)
    assert "crdt_tpu_fleet_capacity_orswot_bytes_sum 350" in text
    assert "crdt_tpu_fleet_capacity_orswot_bytes_max 250" in text
    assert "crdt_tpu_fleet_capacity_orswot_eta_s_max 50" in text
    assert snap.to_json()["fleet"]["capacity"][
        "capacity.orswot.bytes"]["sum"] == 350.0


# ---- the cluster wiring ----------------------------------------------------


def test_cluster_node_samples_planes_and_op_buffers():
    uni = _uni()
    reg = obs_metrics.MetricsRegistry()
    trk = CapacityTracker(reg)
    node = ClusterNode("n0", _orswot(uni, [1, 2]), uni,
                       oplog=OpLog(uni, capacity=256),
                       capacity_tracker=trk)
    node.submit_writes([0, 1], [11, 12], actor=3)
    occs = node.sample_capacity()
    assert [o.kind for o in occs] == ["orswot", "oplog", "oplog_gap"]
    g = reg.snapshot()["gauges"]
    assert g["capacity.orswot.bytes"] == _plane_nbytes(node.batch)
    assert g["capacity.oplog.slots"] == 256
    assert "capacity.oplog_gap.live" in g
    assert trk.watermark()["state"] == "ok"
