"""Display parity (`vclock.rs:73-84`, `mvreg.rs:61-72`) + the pprint example
(`examples/pprint.rs:1-21`)."""

import pathlib
import subprocess
import sys

from crdt_tpu import MVReg, VClock


def test_vclock_display_sorted_by_actor():
    c = VClock()
    c.witness(31231, 2)
    c.witness(4829, 9)
    c.witness(87132, 32)
    # BTreeMap order: numerically sorted actors
    assert str(c) == "(4829->9, 31231->2, 87132->32)"


def test_vclock_display_empty():
    assert str(VClock()) == "()"


def test_mvreg_display_concurrent_vals():
    reg = MVReg()
    op1 = reg.set("some val", reg.read().derive_add_ctx(9742820))
    op2 = reg.set("some other val", reg.read().derive_add_ctx(648572))
    reg.apply(op1)
    reg.apply(op2)
    assert str(reg) == "|some val@(9742820->1), some other val@(648572->1)|"


def test_pprint_example_runs():
    root = pathlib.Path(__file__).resolve().parents[1]
    out = subprocess.run(
        [sys.executable, str(root / "examples" / "pprint.py")],
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert out.returncode == 0, out.stderr
    assert "vclock:\t(4829->9, 31231->2, 87132->32)" in out.stdout
    assert "reg:\t|some val@" in out.stdout
    assert "orswot[0]:\t{apple, pear}" in out.stdout
