"""Long-soak causal-GC acceptance — the PR 9 capacity oracle, flipped.

``tests/test_capacity_soak.py`` (kept unchanged as the GC-off control)
pins that without GC an add-churning fleet's planes grow monotonically
with a finite, shrinking time-to-overflow ETA.  This soak runs the
same 3-node gossip harness with sliding-window churn (adds + removes +
cross-node deferred tombstones) and GC ENABLED, and asserts the
opposite steady state:

* live slots stay bounded (the window, not the history),
* planes that a burst over-provisioned shrink back down the capacity
  ladder (``executor.shrink`` stamped, bytes reclaimed, EWMA re-seeded),
* deferred tombstones return to ~0 after quiescence,
* the overflow ETA ends growing or not-growing (-1) instead of
  counting down,
* and the fleet's digest vectors stay byte-identical at every epoch's
  converged point — GC reclaims representation, never state.
"""

import threading

import numpy as np
import pytest

from crdt_tpu.batch import OrswotBatch
from crdt_tpu.cluster import ClusterNode, GossipScheduler, Membership, queue_pair
from crdt_tpu.config import CrdtConfig
from crdt_tpu.gc import GcEngine, GcPolicy
from crdt_tpu.obs import convergence as obs_convergence
from crdt_tpu.obs import metrics as obs_metrics
from crdt_tpu.obs.capacity import CapacityTracker, ETA_NOT_GROWING
from crdt_tpu.oplog import OpLog
from crdt_tpu.oplog.records import derive_rm_ctx
from crdt_tpu.scalar.orswot import Orswot
from crdt_tpu.sync import digest as digest_mod
from crdt_tpu.utils.interning import Universe
from crdt_tpu.utils.workload import WorkloadGen

pytestmark = [pytest.mark.gc, pytest.mark.slow]

N_OBJECTS = 8
CFG_MEMBER_CAP = 16     # the config rung — GC's shrink floor
BURST_MEMBER_CAP = 64   # where an earlier burst left the planes
EPOCHS = 8
NEW_MEMBERS_PER_EPOCH = 2
WINDOW_EPOCHS = 1       # members live this many epochs before removal
EPOCH_DT = 10.0


def _plane_nbytes(batch):
    return sum(x.nbytes for x in (batch.clock, batch.ids, batch.dots,
                                  batch.d_ids, batch.d_clocks))


def _fleet(clock):
    uni = Universe.identity(CrdtConfig(
        num_actors=8, member_capacity=CFG_MEMBER_CAP, deferred_capacity=4,
        counter_bits=32))
    states = []
    for _ in range(N_OBJECTS):
        s = Orswot()
        for m in range(4):
            s.apply(s.add(m, s.value().derive_add_ctx(0)))
        states.append(s)
    # the fleet as a burst left it: planes regrown 4x above the config
    # rung (the executor's ladder), live occupancy nowhere near it
    base = OrswotBatch.from_scalar(states, uni).with_capacity(
        BURST_MEMBER_CAP, 16)

    regs = [obs_metrics.MetricsRegistry() for _ in range(3)]
    trackers = [
        CapacityTracker(regs[i], max_capacity=BURST_MEMBER_CAP, alpha=1.0,
                        clock=clock)
        for i in range(3)
    ]
    engines = [
        GcEngine(GcPolicy(interval_rounds=1),
                 capacity_tracker=trackers[i], registry=regs[i])
        for i in range(3)
    ]
    nodes = [
        ClusterNode(f"n{i}", base, uni, busy_timeout_s=5.0,
                    oplog=OpLog(uni, capacity=1 << 16),
                    capacity_tracker=trackers[i], gc=engines[i])
        for i in range(3)
    ]

    def make_dialer(i):
        def dial(peer):
            j = int(peer.peer_id[1:])
            ta, tb = queue_pair(default_timeout=10.0)

            def serve():
                try:
                    nodes[j].accept(tb, peer_id=f"n{i}")
                except Exception:
                    pass
                finally:
                    tb.close()

            threading.Thread(target=serve, daemon=True).start()
            return ta
        return dial

    scheds = []
    for i in range(3):
        m = Membership(suspect_after=3, dead_after=6)
        for j in range(3):
            if j != i:
                m.add(f"n{j}")
        scheds.append(GossipScheduler(
            nodes[i], m, make_dialer(i), fanout=2,
            session_timeout_s=30.0, seed=i,
        ))
    return uni, nodes, scheds, regs


def _converge(nodes, scheds, max_sweeps=6):
    for _ in range(max_sweeps):
        for sched in scheds:
            sched.run_round()
        digests = [np.asarray(digest_mod.digest_of(n.batch), np.uint64)
                   for n in nodes]
        if all((d == digests[0]).all() for d in digests[1:]):
            return digests
    raise AssertionError("fleet failed to converge within the sweep budget")


def test_gc_soak_bounded_slots_reclaimed_tombstones_growing_eta():
    t = [0.0]
    obs_convergence.tracker().reset()
    uni, nodes, scheds, regs = _fleet(clock=lambda: t[0])

    def gauges(i):
        return regs[i].snapshot()["gauges"]

    bytes_start = _plane_nbytes(nodes[0].batch)
    live_max_hist = []
    eta_hist = []
    tomb_seen = 0
    window = []  # (epoch, members) still live
    next_member = 100
    # user-shaped background traffic (ROADMAP carried item): Zipf/burst
    # re-adds of BASE members on skew-drawn objects ride every epoch —
    # clocks advance on hot keys through the op path (so GC's watermark
    # and compaction see realistic key skew) without adding/removing
    # slots, which keeps the bounded-live-slot arithmetic exact
    workload = WorkloadGen(N_OBJECTS, seed=55, zipf_s=1.2, burst_len=2)
    for epoch in range(EPOCHS):
        t[0] += EPOCH_DT
        bg = workload.draw(6)
        nodes[(epoch + 1) % 3].submit_writes(
            bg, (bg % 4).astype(np.int32), actor=1 + epoch % 3)
        # sliding-window churn on object 0: node 0 mints new members...
        members = list(range(next_member,
                             next_member + NEW_MEMBERS_PER_EPOCH))
        next_member += NEW_MEMBERS_PER_EPOCH
        nodes[0].submit_writes([0] * len(members), members, actor=0)
        window.append((epoch, members))
        # ...and removes the window's expired members (clock derived
        # from its own write view — applies immediately, frees slots)
        expired = [w for w in window if w[0] <= epoch - WINDOW_EPOCHS]
        window = [w for w in window if w[0] > epoch - WINDOW_EPOCHS]
        for _, olds in expired:
            nodes[0].submit_ops(derive_rm_ctx(
                nodes[0].write_clock(), [0] * len(olds), olds))
        # cross-node deferred tombstone: node 0 also writes object 1,
        # then a remove WITNESSED BY ITS ADVANCED CLOCK lands on node 1
        # before node 1 has synced the epoch's adds — the remove parks
        # in node 1's deferred table until anti-entropy catches up,
        # then settles (merge plunger or GC, whichever runs first)
        obj1_member = 500 + epoch
        nodes[0].submit_writes([1], [obj1_member], actor=0)
        nodes[1].submit_ops(derive_rm_ctx(
            nodes[0].write_clock(), [1], [obj1_member]))
        nodes[1].sample_capacity()
        tomb_seen = max(tomb_seen,
                        gauges(1)["capacity.orswot.tombstones"])

        digests = _converge(nodes, scheds)
        assert digests is not None
        for i in range(3):
            g = gauges(i)
            # the PR 9 identity still holds under GC: reported bytes ==
            # the live buffers, through every settle/shrink
            assert g["capacity.orswot.bytes"] \
                == _plane_nbytes(nodes[i].batch), (epoch, i)
        live_max_hist.append(gauges(0)["capacity.orswot.live_max"])
        if epoch >= 1:
            eta_hist.append(gauges(0)["capacity.orswot.eta_s"])

    # BOUNDED live slots: the window, not the history.  The GC-off
    # control (test_capacity_soak) grows monotonically by
    # NEW_MEMBERS_PER_EPOCH every epoch; here the busiest object must
    # stay under the config rung with room to spare.
    bound = 4 + NEW_MEMBERS_PER_EPOCH * (WINDOW_EPOCHS + 1) + 2
    assert max(live_max_hist) <= bound, live_max_hist
    assert live_max_hist[-1] <= bound
    assert live_max_hist != sorted(set(live_max_hist)) or \
        live_max_hist[-1] - live_max_hist[0] < (EPOCHS - 1) \
        * NEW_MEMBERS_PER_EPOCH  # NOT the control's monotone climb

    # capacity walked back down the ladder: every node re-packed to the
    # config rung and the planes shed real bytes
    for i in range(3):
        assert nodes[i].batch.member_capacity == CFG_MEMBER_CAP, i
        assert _plane_nbytes(nodes[i].batch) < bytes_start
        assert nodes[i].gc.total_reclaimed_bytes > 0
        assert regs[i].snapshot()["counters"]["gc.shrinks"] >= 1

    # quiescence: writes stopped — tombstones drain to zero everywhere
    # (the soak DID see tombstone rows in flight)
    assert tomb_seen >= 1
    t[0] += EPOCH_DT
    digests = _converge(nodes, scheds)
    for i in range(3):
        assert gauges(i)["capacity.orswot.tombstones"] == 0, i

    # the ETA story flipped: where the control's countdown shrank every
    # epoch, the GC'd fleet ends not-growing (or at worst further from
    # overflow than it started)
    final_eta = gauges(0)["capacity.orswot.eta_s"]
    assert final_eta == ETA_NOT_GROWING or final_eta >= eta_hist[0], (
        final_eta, eta_hist)

    # and the converged digests are byte-identical across the fleet
    assert all((d == digests[0]).all() for d in digests[1:])
