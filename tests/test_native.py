"""Native (C++) engine parity tests.

The three engines — scalar Python, JAX/XLA batch, native C++ — implement the
same dense-array contracts; here every native kernel is compared
byte-for-byte against the JAX kernels (which the rest of the suite pins to
the scalar reference semantics), over randomized states, both counter
dtypes, and the op paths.
"""

import numpy as np
import pytest

from crdt_tpu.native import available

pytestmark = pytest.mark.skipif(
    not available(), reason="native toolchain unavailable (g++/make)"
)


@pytest.fixture(scope="module")
def engines():
    import jax.numpy as jnp

    from crdt_tpu.native import engine
    from crdt_tpu.ops import clock_ops, lww_ops, mvreg_ops, orswot_ops

    return engine, clock_ops, lww_ops, mvreg_ops, orswot_ops, jnp


DTYPES = [np.uint32, np.uint64]


def rand_clocks(rng, shape, dtype, p_zero=0.4):
    x = rng.randint(0, 50, size=shape).astype(dtype)
    return np.where(rng.rand(*shape) < p_zero, np.zeros_like(x), x)


@pytest.mark.parametrize("dtype", DTYPES)
def test_vclock_ops_parity(engines, dtype):
    engine, clock_ops, *_ = engines
    rng = np.random.RandomState(0)
    a = rand_clocks(rng, (64, 16), dtype)
    b = rand_clocks(rng, (64, 16), dtype)
    for native_fn, jax_fn in [
        (engine.vclock_merge, clock_ops.merge),
        (engine.vclock_intersection, clock_ops.intersection),
        (engine.vclock_subtract, clock_ops.subtract),
        (engine.vclock_truncate, clock_ops.truncate),
    ]:
        np.testing.assert_array_equal(
            native_fn(a, b), np.asarray(jax_fn(a, b)).astype(dtype)
        )
    leq, geq = engine.vclock_compare(a, b)
    np.testing.assert_array_equal(leq, np.asarray(clock_ops.leq(a, b)))
    np.testing.assert_array_equal(geq, np.asarray(clock_ops.dominates_or_eq(a, b)))


@pytest.mark.parametrize("dtype", DTYPES)
def test_lww_merge_parity(engines, dtype):
    engine, _, lww_ops, *_ = engines
    rng = np.random.RandomState(1)
    n = 1000
    va = rng.randint(0, 5, size=n).astype(np.int64)
    vb = rng.randint(0, 5, size=n).astype(np.int64)
    ma = rng.randint(0, 10, size=n).astype(dtype)  # small range forces ties
    mb = rng.randint(0, 10, size=n).astype(dtype)
    val, marker, conflict = engine.lww_merge(va, ma, vb, mb)
    jval, jmarker, jconflict = lww_ops.merge(va, ma, vb, mb)
    np.testing.assert_array_equal(val, np.asarray(jval))
    np.testing.assert_array_equal(marker, np.asarray(jmarker).astype(dtype))
    np.testing.assert_array_equal(conflict, np.asarray(jconflict))
    assert conflict.any(), "test vector should include real conflicts"


@pytest.mark.parametrize("dtype", DTYPES)
def test_mvreg_merge_parity(engines, dtype):
    engine, _, _, mvreg_ops, _, jnp = engines
    rng = np.random.RandomState(2)
    n, k, a = 200, 4, 6
    ca = rand_clocks(rng, (n, k, a), dtype, p_zero=0.6)
    cb = rand_clocks(rng, (n, k, a), dtype, p_zero=0.6)
    # make some slots exact duplicates across sides (the dedup path)
    dup = rng.rand(n) < 0.3
    cb[dup, 0] = ca[dup, 0]
    va = rng.randint(1, 100, size=(n, k)).astype(np.int64)
    vb = rng.randint(1, 100, size=(n, k)).astype(np.int64)
    vb[dup, 0] = va[dup, 0]
    # zero the payload of dead slots (the JAX kernel masks them to 0)
    va = np.where((ca != 0).any(-1), va, 0)
    vb = np.where((cb != 0).any(-1), vb, 0)

    k_cap = 2 * k  # no truncation: compare full survivor sets
    clocks, vals, overflow = engine.mvreg_merge(ca, va, cb, vb, k_cap=k_cap)
    jc, jv, keep = mvreg_ops.merge(ca, va, cb, vb)
    jc, jv, joverflow = mvreg_ops.compact(jc, jv, keep, k_cap)
    np.testing.assert_array_equal(clocks, np.asarray(jc).astype(dtype))
    np.testing.assert_array_equal(vals, np.asarray(jv))
    np.testing.assert_array_equal(overflow, np.asarray(joverflow))
    assert not overflow.any()


def random_orswot_pair(rng, n, a, m, d, dtype):
    from crdt_tpu.utils.testdata import random_orswot_arrays

    lhs = random_orswot_arrays(rng, n, a, m, d, dtype=dtype)
    rhs = random_orswot_arrays(rng, n, a, m, d, dtype=dtype)
    return lhs, rhs


@pytest.mark.parametrize("dtype", DTYPES)
def test_orswot_merge_parity(engines, dtype):
    engine, *_, orswot_ops, jnp = engines
    rng = np.random.RandomState(3)
    n, a, m, d = 128, 8, 6, 3
    lhs, rhs = random_orswot_pair(rng, n, a, m, d, dtype)
    # output capacity 2m so nothing truncates and slot order is fully checked
    got = engine.orswot_merge(*lhs, *rhs, m_cap=2 * m, d_cap=2 * d)
    exp = orswot_ops.merge(*[jnp.asarray(x) for x in lhs],
                           *[jnp.asarray(x) for x in rhs], 2 * m, 2 * d)
    names = ["clock", "ids", "dots", "d_ids", "d_clocks", "overflow"]
    for g, e, name in zip(got, exp, names):
        np.testing.assert_array_equal(
            np.asarray(g), np.asarray(e), err_msg=f"orswot merge field {name}"
        )


@pytest.mark.parametrize("dtype", DTYPES)
def test_orswot_merge_with_deferred_parity(engines, dtype):
    """Deferred rows exercise dedup, replay, and the still-ahead filter."""
    engine, *_, orswot_ops, jnp = engines
    rng = np.random.RandomState(4)
    n, a, m, d = 64, 6, 5, 3
    lhs, rhs = random_orswot_pair(rng, n, a, m, d, dtype)
    lhs, rhs = list(lhs), list(rhs)

    # inject deferred removes: some targeting existing members with clocks
    # ahead of the set clock, some duplicated on both sides
    for side in (lhs, rhs):
        ids, d_ids, d_clocks = side[1], side[3], side[4]
        d_ids[:, 0] = ids[:, 0]  # remove the first member...
        d_clocks[:, 0, :] = rand_clocks(rng, (n, a), dtype, p_zero=0.3) + 1
    # duplicate row 0 of lhs into rhs for half the objects (dedup path)
    half = rng.rand(n) < 0.5
    rhs[3][half, 1] = lhs[3][half, 0]
    rhs[4][half, 1] = lhs[4][half, 0]

    got = engine.orswot_merge(*lhs, *rhs, m_cap=2 * m, d_cap=2 * d)
    exp = orswot_ops.merge(*[jnp.asarray(x) for x in lhs],
                           *[jnp.asarray(x) for x in rhs], 2 * m, 2 * d)
    for g, e, name in zip(got, exp, ["clock", "ids", "dots", "d_ids", "d_clocks", "overflow"]):
        np.testing.assert_array_equal(
            np.asarray(g), np.asarray(e), err_msg=f"field {name}"
        )


@pytest.mark.parametrize("dtype", DTYPES)
def test_orswot_apply_add_parity(engines, dtype):
    engine, *_, orswot_ops, jnp = engines
    rng = np.random.RandomState(5)
    n, a, m, d = 100, 6, 5, 2
    (state, _) = random_orswot_pair(rng, n, a, m, d, dtype)
    actor = rng.randint(0, a, size=n).astype(np.int32)
    # mix of novel counters (apply) and stale ones (dedup no-op)
    counter = rng.randint(1, 150, size=n).astype(dtype)
    member = rng.randint(0, 1 << 20, size=n).astype(np.int32)

    got = engine.orswot_apply_add(*state, actor, counter, member)
    exp = orswot_ops.apply_add(*[jnp.asarray(x) for x in state],
                               jnp.asarray(actor), jnp.asarray(counter),
                               jnp.asarray(member))
    for g, e, name in zip(got, exp, ["clock", "ids", "dots", "d_ids", "d_clocks", "overflow"]):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(e), err_msg=name)


@pytest.mark.parametrize("dtype", DTYPES)
def test_orswot_apply_remove_parity(engines, dtype):
    engine, *_, orswot_ops, jnp = engines
    rng = np.random.RandomState(6)
    n, a, m, d = 100, 6, 5, 3
    (state, _) = random_orswot_pair(rng, n, a, m, d, dtype)
    # remove an existing member for half the objects, a random id otherwise
    member = np.where(
        rng.rand(n) < 0.5, state[1][:, 0], rng.randint(0, 1 << 20, size=n)
    ).astype(np.int32)
    # rm clocks: mix of covered (apply now) and ahead (defer)
    rm_clock = rand_clocks(rng, (n, a), dtype, p_zero=0.5)
    ahead = rng.rand(n) < 0.4
    rm_clock[ahead] += 200

    got = engine.orswot_apply_remove(*state, rm_clock, member)
    exp = orswot_ops.apply_remove(*[jnp.asarray(x) for x in state],
                                  jnp.asarray(rm_clock), jnp.asarray(member))
    for g, e, name in zip(got, exp, ["clock", "ids", "dots", "d_ids", "d_clocks", "overflow"]):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(e), err_msg=name)


@pytest.mark.parametrize("dtype", DTYPES)
def test_orswot_merge_overflow_flag(engines, dtype):
    """Truncation must be flagged, never silent."""
    engine, *_, orswot_ops, jnp = engines
    rng = np.random.RandomState(7)
    n, a, m, d = 16, 4, 4, 2
    lhs, rhs = random_orswot_pair(rng, n, a, m, d, dtype)
    got = engine.orswot_merge(*lhs, *rhs, m_cap=1, d_cap=d)
    exp = orswot_ops.merge(*[jnp.asarray(x) for x in lhs],
                           *[jnp.asarray(x) for x in rhs], 1, d)
    np.testing.assert_array_equal(np.asarray(got[5]), np.asarray(exp[5]))
    assert np.asarray(got[5]).any()


def test_shape_validation_rejects_mismatches(engines):
    """The C kernels do raw pointer arithmetic; the wrappers must reject
    inconsistent shapes instead of reading out of bounds."""
    engine, *_ = engines
    rng = np.random.RandomState(9)
    n, a, m, d = 8, 4, 3, 2
    lhs, _ = random_orswot_pair(rng, n, a, m, d, np.uint64)
    short, _ = random_orswot_pair(rng, n // 2, a, m, d, np.uint64)
    with pytest.raises(ValueError, match="side shapes differ"):
        engine.orswot_merge(*lhs, *short)
    with pytest.raises(ValueError, match="inconsistent ORSWOT state"):
        engine.orswot_merge(*lhs[:2], lhs[2][:, :1], *lhs[3:], *lhs)
    with pytest.raises(ValueError, match="actor_idx"):
        engine.orswot_apply_add(
            *lhs, np.zeros(n // 2, np.int32), np.ones(n, np.uint64),
            np.zeros(n, np.int32),
        )
    with pytest.raises(ValueError, match="out of range"):
        engine.orswot_apply_add(
            *lhs, np.full(n, a, np.int32), np.ones(n, np.uint64),
            np.zeros(n, np.int32),
        )
    with pytest.raises(ValueError, match="rm_clock"):
        engine.orswot_apply_remove(
            *lhs, np.zeros((n, a + 1), np.uint64), np.zeros(n, np.int32)
        )
    with pytest.raises(ValueError, match="shape mismatch"):
        engine.lww_merge(
            np.zeros(4, np.int64), np.zeros(4, np.uint64),
            np.zeros(5, np.int64), np.zeros(5, np.uint64),
        )


def test_lww_merge_preserves_lead_shape(engines):
    engine, _, lww_ops, *_ = engines
    rng = np.random.RandomState(10)
    shape = (16, 8)
    va = rng.randint(0, 3, size=shape).astype(np.int64)
    vb = rng.randint(0, 3, size=shape).astype(np.int64)
    ma = rng.randint(0, 5, size=shape).astype(np.uint64)
    mb = rng.randint(0, 5, size=shape).astype(np.uint64)
    val, marker, conflict = engine.lww_merge(va, ma, vb, mb)
    assert val.shape == marker.shape == conflict.shape == shape
    jval, jmarker, jconflict = lww_ops.merge(va, ma, vb, mb)
    np.testing.assert_array_equal(conflict, np.asarray(jconflict))


def test_native_fold_matches_scalar_orswot():
    """End-to-end: native N-way left-fold join == scalar engine join."""
    from crdt_tpu.batch import OrswotBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.native import engine
    from crdt_tpu.scalar.orswot import Orswot
    from crdt_tpu.scalar.vclock import Dot
    from crdt_tpu.utils.interning import Universe

    rng = np.random.RandomState(8)
    uni = Universe(CrdtConfig(num_actors=6, member_capacity=12, deferred_capacity=6))
    n_rep, n_obj = 5, 4
    fleet = []
    for r in range(n_rep):
        row = []
        for i in range(n_obj):
            s = Orswot()
            for _ in range(rng.randint(0, 6)):
                actor = int(rng.randint(0, 6))
                counter = int(rng.randint(1, 5))
                member = int(rng.randint(0, 6))
                if rng.rand() < 0.7:
                    s.apply(
                        __import__("crdt_tpu.scalar.orswot", fromlist=["Add"]).Add(
                            dot=Dot(actor, counter), member=member
                        )
                    )
                else:
                    s.apply_remove(member, Dot(actor, counter).to_vclock())
            row.append(s)
        fleet.append(row)

    batches = [OrswotBatch.from_scalar(row, uni) for row in fleet]
    arrays = [
        tuple(np.asarray(x) for x in (b.clock, b.ids, b.dots, b.d_ids, b.d_clocks))
        for b in batches
    ]
    acc = arrays[0]
    for nxt in arrays[1:]:
        out = engine.orswot_merge(*acc, *nxt)
        assert not out[5].any()
        acc = out[:5]
    # defer plunger
    zero = tuple(
        np.asarray(x)
        for x in (
            np.zeros_like(acc[0]), np.full_like(acc[1], -1), np.zeros_like(acc[2]),
            np.full_like(acc[3], -1), np.zeros_like(acc[4]),
        )
    )
    acc = engine.orswot_merge(*acc, *zero)[:5]

    import jax.numpy as jnp

    merged_batch = OrswotBatch(
        clock=jnp.asarray(acc[0]), ids=jnp.asarray(acc[1]), dots=jnp.asarray(acc[2]),
        d_ids=jnp.asarray(acc[3]), d_clocks=jnp.asarray(acc[4]),
    )
    got = merged_batch.to_scalar(uni)

    expected = []
    for i in range(n_obj):
        merged = Orswot()
        for row in fleet:
            merged.merge(row[i])
        merged.merge(Orswot())
        expected.append(merged)
    assert got == expected


# -- Map<K, MVReg> merge (map.rs:192-269) ------------------------------------


def _random_map_batch_arrays(seed, n_obj, uni):
    """Random op-built Map<int, MVReg> fleet packed to dense arrays, plus
    the scalar states (for building the batch on both engines)."""
    import random as pyrandom

    from crdt_tpu import Dot, Map, MVReg, VClock
    from crdt_tpu.batch import MapBatch, MVRegKernel
    from crdt_tpu.scalar.map import Rm as MapRm, Up
    from crdt_tpu.scalar.mvreg import Put

    rng = pyrandom.Random(seed)
    states = []
    for _ in range(n_obj):
        m = Map(MVReg)
        for _ in range(rng.randrange(0, 10)):
            actor = rng.randrange(0, 6)
            counter = rng.randrange(1, 6)
            key = rng.randrange(0, 5)
            clock = VClock.from_iter([(actor, counter)])
            if rng.random() < 0.3:
                m.apply(MapRm(clock=clock, key=key))
            else:
                m.apply(Up(dot=Dot(actor, counter), key=key,
                           op=Put(clock=clock, val=rng.randrange(0, 9))))
        states.append(m)
    vk = MVRegKernel.from_config(uni.config)
    batch = MapBatch.from_scalar(states, uni, vk)
    mv_clocks, mv_vals = batch.vals
    return (
        np.asarray(batch.clock), np.asarray(batch.keys),
        np.asarray(batch.entry_clocks), np.asarray(mv_clocks),
        np.asarray(mv_vals), np.asarray(batch.d_keys),
        np.asarray(batch.d_clocks),
    ), batch


def test_map_mvreg_merge_parity(engines):
    """Native Map<K, MVReg> merge == jnp map_ops.merge, byte-for-byte —
    the composition path (`map.rs:192-269`) through the C++ oracle."""
    engine = engines[0]
    import jax.numpy as jnp

    from crdt_tpu.batch.val_kernels import MVRegKernel
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.ops import map_ops
    from crdt_tpu.utils.interning import Universe

    uni = Universe(CrdtConfig(
        num_actors=6, member_capacity=8, deferred_capacity=6,
        mv_capacity=8, key_capacity=8,
    ))
    vk = MVRegKernel.from_config(uni.config)
    n_obj = 32
    A, batch_a = _random_map_batch_arrays(101, n_obj, uni)
    B, batch_b = _random_map_batch_arrays(202, n_obj, uni)

    k_cap = A[1].shape[-1]
    d_cap = A[5].shape[-1]
    got_state, got_over = engine.map_mvreg_merge(A, B, k_cap, d_cap)

    state_a = (batch_a.clock, batch_a.keys, batch_a.entry_clocks,
               batch_a.vals, batch_a.d_keys, batch_a.d_clocks)
    state_b = (batch_b.clock, batch_b.keys, batch_b.entry_clocks,
               batch_b.vals, batch_b.d_keys, batch_b.d_clocks)
    want_state, want_over = map_ops.merge(state_a, state_b, vk, k_cap, d_cap)
    w_clock, w_keys, w_e, (w_mvc, w_mvv), w_dk, w_dc = want_state

    np.testing.assert_array_equal(got_state[0], np.asarray(w_clock))
    np.testing.assert_array_equal(got_state[1], np.asarray(w_keys))
    np.testing.assert_array_equal(got_state[2], np.asarray(w_e))
    np.testing.assert_array_equal(got_state[3], np.asarray(w_mvc))
    np.testing.assert_array_equal(got_state[4], np.asarray(w_mvv))
    np.testing.assert_array_equal(got_state[5], np.asarray(w_dk))
    np.testing.assert_array_equal(got_state[6], np.asarray(w_dc))
    np.testing.assert_array_equal(got_over, np.asarray(want_over))


def test_map_mvreg_merge_deferred_parity(engines):
    """Causally-future Map removes buffer and replay identically in the
    C++ and jnp engines (`map.rs:256-267`)."""
    engine = engines[0]

    from crdt_tpu import Dot, Map, MVReg, VClock
    from crdt_tpu.batch import MapBatch, MVRegKernel
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.ops import map_ops
    from crdt_tpu.scalar.map import Rm as MapRm, Up
    from crdt_tpu.scalar.mvreg import Put
    from crdt_tpu.utils.interning import Universe

    uni = Universe(CrdtConfig(
        num_actors=6, member_capacity=8, deferred_capacity=6,
        mv_capacity=8, key_capacity=8,
    ))
    vk = MVRegKernel.from_config(uni.config)

    writer = Map(MVReg)
    clock = VClock.from_iter([(0, 3)])
    writer.apply(Up(dot=Dot(0, 3), key=1, op=Put(clock=clock, val=7)))

    remover = Map(MVReg)
    remover.apply(MapRm(clock=VClock.from_iter([(0, 3)]), key=1))  # future
    assert remover.deferred

    ba = MapBatch.from_scalar([writer], uni, vk)
    bb = MapBatch.from_scalar([remover], uni, vk)

    def arrays(b):
        mvc, mvv = b.vals
        return (np.asarray(b.clock), np.asarray(b.keys),
                np.asarray(b.entry_clocks), np.asarray(mvc), np.asarray(mvv),
                np.asarray(b.d_keys), np.asarray(b.d_clocks))

    got_state, got_over = engine.map_mvreg_merge(arrays(ba), arrays(bb))
    want_state, want_over = map_ops.merge(
        (ba.clock, ba.keys, ba.entry_clocks, ba.vals, ba.d_keys, ba.d_clocks),
        (bb.clock, bb.keys, bb.entry_clocks, bb.vals, bb.d_keys, bb.d_clocks),
        vk, ba.keys.shape[-1], ba.d_keys.shape[-1],
    )
    w_clock, w_keys, w_e, (w_mvc, w_mvv), w_dk, w_dc = want_state
    for got, want in zip(
        got_state,
        (w_clock, w_keys, w_e, w_mvc, w_mvv, w_dk, w_dc),
    ):
        np.testing.assert_array_equal(got, np.asarray(want))
    # the asymmetric discard (`map.rs:256-260`): the remover's buffered row
    # is already covered by the writer's clock, so it is dropped WITHOUT
    # effect — the key survives and the deferred buffer drains
    assert np.any(got_state[1] != -1), "covered deferred row must not remove"
    assert np.all(got_state[5] == -1), "covered deferred row must drain"
    np.testing.assert_array_equal(got_over, np.asarray(want_over))


# -- Map<K, Orswot> merge (map.rs:192-269 over orswot.rs:89-156) --------------


def _random_map_orswot_states(seed, n_obj, uni):
    """Random op-built Map<int, Orswot> fleet + its dense MapBatch — the
    hardest composition path (nested member tables, nested deferred rows,
    reset-remove truncates through the nested set)."""
    import random as pyrandom

    from crdt_tpu import Dot, Map, Orswot
    from crdt_tpu.batch import MapBatch, OrswotKernel
    from crdt_tpu.scalar.map import Rm as MapRm, Up
    from crdt_tpu.scalar.orswot import Add as OrswotAdd, Rm as OrswotRm

    rng = pyrandom.Random(seed)
    states = []
    for _ in range(n_obj):
        m = Map(Orswot)
        for _ in range(rng.randrange(0, 12)):
            actor = rng.randrange(0, 6)
            counter = rng.randrange(1, 6)
            key = rng.randrange(0, 5)
            member = rng.randrange(0, 9)
            dot = Dot(actor, counter)
            p = rng.random()
            if p < 0.2:
                m.apply(MapRm(clock=dot.to_vclock(), key=key))
            elif p < 0.4:
                m.apply(Up(dot=dot, key=key,
                           op=OrswotRm(clock=dot.to_vclock(), member=member)))
            else:
                m.apply(Up(dot=dot, key=key, op=OrswotAdd(dot=dot, member=member)))
        states.append(m)
    vk = OrswotKernel.from_config(uni.config)
    batch = MapBatch.from_scalar(states, uni, vk)
    state = (batch.clock, batch.keys, batch.entry_clocks, batch.vals,
             batch.d_keys, batch.d_clocks)
    import jax

    arrays = jax.tree_util.tree_map(np.asarray, state)
    return arrays, state, states, vk


def _map_orswot_uni():
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.utils.interning import Universe

    return Universe(CrdtConfig(
        num_actors=6, member_capacity=8, deferred_capacity=6, key_capacity=8,
    ))


def test_map_orswot_merge_parity(engines):
    """Native Map<K, Orswot> merge == jnp map_ops.merge under OrswotKernel,
    byte-for-byte including nested member-slot order and truncate holes."""
    engine = engines[0]

    from crdt_tpu.ops import map_ops

    uni = _map_orswot_uni()
    A, state_a, _, vk = _random_map_orswot_states(303, 32, uni)
    B, state_b, _, _ = _random_map_orswot_states(404, 32, uni)

    k_cap = A[1].shape[-1]
    d_cap = A[4].shape[-1]
    got_state, got_over = engine.map_orswot_merge(A, B, k_cap, d_cap)
    want_state, want_over = map_ops.merge(state_a, state_b, vk, k_cap, d_cap)

    import jax

    got_flat = jax.tree_util.tree_leaves(got_state)
    want_flat = jax.tree_util.tree_leaves(want_state)
    assert len(got_flat) == len(want_flat) == 10
    for g, w in zip(got_flat, want_flat):
        np.testing.assert_array_equal(g, np.asarray(w))
    np.testing.assert_array_equal(got_over, np.asarray(want_over))


def test_map_orswot_three_engine_agreement():
    """C++ N-way fold == scalar Python N-way merge (value semantics), with
    the JAX engine pinned byte-for-byte in the parity test above — all
    three engines meet on the hardest composition path."""
    import jax.numpy as jnp

    from crdt_tpu.batch import MapBatch
    from crdt_tpu.native import engine

    uni = _map_orswot_uni()
    rows = [_random_map_orswot_states(500 + i, 8, uni) for i in range(4)]

    acc_arrays = rows[0][0]
    for arrays, *_ in rows[1:]:
        acc_arrays, over = engine.map_orswot_merge(acc_arrays, arrays)
        assert not over.any()

    import jax

    from crdt_tpu.batch import MapKernel

    mk = MapKernel.from_config(uni.config, rows[0][3])
    merged = MapBatch.from_state(
        jax.tree_util.tree_map(jnp.asarray, acc_arrays), mk
    )
    got = merged.to_scalar(uni)

    expected = []
    for i in range(8):
        m = rows[0][2][i].clone()
        for _, _, states, _ in rows[1:]:
            m.merge(states[i])
        expected.append(m)
    assert got == expected


# -- Map<K, Map<K2, MVReg>> merge (map.rs:192-269 recursing at :229) ----------


def _random_nested_map_states(seed, n_obj, uni):
    """Random op-built Map<int, Map<int, MVReg>> fleet (`test/map.rs:8`
    shape) + its dense MapBatch under a nested MapKernel."""
    import random as pyrandom

    from crdt_tpu import Dot, Map, MVReg
    from crdt_tpu.batch import MapBatch, MapKernel, MVRegKernel
    from crdt_tpu.scalar.map import Rm as MapRm, Up
    from crdt_tpu.scalar.mvreg import Put
    from crdt_tpu.scalar.vclock import VClock

    rng = pyrandom.Random(seed)
    states = []
    for _ in range(n_obj):
        m = Map(lambda: Map(MVReg))
        for _ in range(rng.randrange(0, 12)):
            actor = rng.randrange(0, 6)
            counter = rng.randrange(1, 6)
            key = rng.randrange(0, 4)
            ikey = rng.randrange(0, 4)
            dot = Dot(actor, counter)
            clock = VClock.from_iter([(actor, counter)])
            p = rng.random()
            if p < 0.2:
                m.apply(MapRm(clock=clock, key=key))
            elif p < 0.4:
                m.apply(Up(dot=dot, key=key, op=MapRm(clock=clock, key=ikey)))
            else:
                m.apply(Up(dot=dot, key=key,
                           op=Up(dot=dot, key=ikey,
                                 op=Put(clock=clock,
                                        val=rng.randrange(0, 9)))))
        states.append(m)
    inner = MapKernel.from_config(uni.config, MVRegKernel.from_config(uni.config))
    batch = MapBatch.from_scalar(states, uni, inner)
    state = (batch.clock, batch.keys, batch.entry_clocks, batch.vals,
             batch.d_keys, batch.d_clocks)
    import jax

    arrays = jax.tree_util.tree_map(np.asarray, state)
    return arrays, state, states, inner


def _nested_map_uni():
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.utils.interning import Universe

    return Universe(CrdtConfig(
        num_actors=6, mv_capacity=6, deferred_capacity=5, key_capacity=6,
    ))


def test_map_map_mvreg_merge_parity(engines):
    """Native nested-map merge == jnp map_ops.merge under a nested
    MapKernel, byte-for-byte — all three engines now cover Map-in-Map."""
    engine = engines[0]

    from crdt_tpu.ops import map_ops

    uni = _nested_map_uni()
    A, state_a, _, vk = _random_nested_map_states(606, 24, uni)
    B, state_b, _, _ = _random_nested_map_states(707, 24, uni)

    k_cap = A[1].shape[-1]
    d_cap = A[4].shape[-1]
    got_state, got_over = engine.map_map_mvreg_merge(A, B, k_cap, d_cap)
    want_state, want_over = map_ops.merge(state_a, state_b, vk, k_cap, d_cap)

    import jax

    got_flat = jax.tree_util.tree_leaves(got_state)
    want_flat = jax.tree_util.tree_leaves(want_state)
    assert len(got_flat) == len(want_flat) == 12
    for g, w in zip(got_flat, want_flat):
        np.testing.assert_array_equal(g, np.asarray(w))
    np.testing.assert_array_equal(got_over, np.asarray(want_over))


def test_map_map_mvreg_three_engine_agreement():
    """C++ N-way nested-map fold == scalar Python N-way merge, with the JAX
    engine pinned byte-for-byte above — three engines on the deepest
    composition shape the reference tests (`test/map.rs:8`)."""
    import jax
    import jax.numpy as jnp

    from crdt_tpu.batch import MapBatch, MapKernel
    from crdt_tpu.native import engine

    uni = _nested_map_uni()
    rows = [_random_nested_map_states(800 + i, 6, uni) for i in range(4)]

    acc_arrays = rows[0][0]
    for arrays, *_ in rows[1:]:
        acc_arrays, over = engine.map_map_mvreg_merge(acc_arrays, arrays)
        assert not over.any()

    mk = MapKernel.from_config(uni.config, rows[0][3])
    merged = MapBatch.from_state(
        jax.tree_util.tree_map(jnp.asarray, acc_arrays), mk
    )
    got = merged.to_scalar(uni)

    expected = []
    for i in range(6):
        m = rows[0][2][i].clone()
        for _, _, states, _ in rows[1:]:
            m.merge(states[i])
        expected.append(m)
    assert got == expected


@pytest.mark.parametrize("dtype", DTYPES)
def test_orswot_fold_parity(engines, dtype):
    """The bench's native-fold headline path (sequential R-way fold +
    defer plunger, bench.py native_fold_join) must be bit-identical to
    the jnp fold on anti-entropy-shaped fleets with deferred rows."""
    import jax

    engine, *_, orswot_ops, jnp = engines
    from crdt_tpu.utils.testdata import anti_entropy_fleets

    rng = np.random.RandomState(11)
    n, a, m, d, r = 64, 8, 8, 2, 4
    reps = anti_entropy_fleets(
        rng, n, a, m, d, r, base=3, novel=1, deferred_frac=0.3, dtype=dtype
    )
    stack = [tuple(np.asarray(x) for x in rep) for rep in reps]

    acc = stack[0]
    for i in range(1, r):
        acc = engine.orswot_merge(*acc, *stack[i])[:5]
    acc = engine.orswot_merge(*acc, *acc)[:5]  # defer plunger

    jacc = tuple(jnp.asarray(x) for x in stack[0])
    for i in range(1, r):
        jacc = orswot_ops.merge(*jacc, *(jnp.asarray(x) for x in stack[i]), m, d)[:5]
    jacc = orswot_ops.merge(*jacc, *jacc, m, d)[:5]
    jax.block_until_ready(jacc)

    for k, (x, y) in enumerate(zip(acc, jacc)):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=f"plane {k} diverged"
        )


@pytest.mark.parametrize("dtype", DTYPES)
def test_orswot_merge_out_buffers(engines, dtype):
    """The out= reuse path (bench fold ping-pong) must be bit-identical
    to fresh allocation, reject shape/dtype mismatches, and reject
    buffers aliasing an input."""
    engine, *_ = engines
    from crdt_tpu.utils.testdata import anti_entropy_fleets

    rng = np.random.RandomState(5)
    n, a, m, d = 32, 8, 8, 2
    lhs, rhs = [
        tuple(np.asarray(x) for x in rep)
        for rep in anti_entropy_fleets(
            rng, n, a, m, d, 2, base=3, novel=1, deferred_frac=0.3,
            dtype=dtype,
        )
    ]
    want = engine.orswot_merge(*lhs, *rhs)

    out = (
        np.empty((n, a), dtype), np.empty((n, m), np.int32),
        np.empty((n, m, a), dtype), np.empty((n, d), np.int32),
        np.empty((n, d, a), dtype),
    )
    got = engine.orswot_merge(*lhs, *rhs, out=out)
    for x, y in zip(want, got):
        np.testing.assert_array_equal(x, y)
    assert got[0] is out[0]  # actually wrote into the caller's buffer

    # second reuse of the same buffers still exact (full overwrite)
    got2 = engine.orswot_merge(*rhs, *lhs, out=out)
    want2 = engine.orswot_merge(*rhs, *lhs)
    for x, y in zip(want2, got2):
        np.testing.assert_array_equal(x, y)

    with pytest.raises(ValueError, match="out\\[clock\\]"):
        engine.orswot_merge(
            *lhs, *rhs, out=(np.empty((n, a + 1), dtype),) + out[1:]
        )
    with pytest.raises(ValueError, match="aliases"):
        engine.orswot_merge(*lhs, *rhs, out=(lhs[0],) + out[1:])


def test_orswot_merge_out_rejects_mutual_aliasing(engines):
    """Same buffer passed as two outputs (ids/d_ids share shape+dtype
    when m == d) must be rejected, not silently corrupted."""
    engine, *_ = engines
    from crdt_tpu.utils.testdata import anti_entropy_fleets

    rng = np.random.RandomState(6)
    n, a, m, d = 8, 4, 2, 2  # m == d: ids/d_ids shapes coincide
    lhs, rhs = [
        tuple(np.asarray(x) for x in rep)
        for rep in anti_entropy_fleets(rng, n, a, m, d, 2, base=1, novel=0)
    ]
    ids_buf = np.empty((n, m), np.int32)
    out = (
        np.empty((n, a), np.uint32), ids_buf,
        np.empty((n, m, a), np.uint32), ids_buf,
        np.empty((n, d, a), np.uint32),
    )
    with pytest.raises(ValueError, match="alias each other"):
        engine.orswot_merge(*lhs, *rhs, out=out)
