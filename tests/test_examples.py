"""Smoke-run the self-verifying examples (their asserts are the test)."""

import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_wire_zoo_example_runs():
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", "wire_zoo.py")],
        capture_output=True, text=True, timeout=600, cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stderr
    assert "all 9 type families converged" in proc.stdout


def test_anti_entropy_example_runs():
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", "anti_entropy.py")],
        capture_output=True, text=True, timeout=600, cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stderr
    assert "anti-entropy walkthrough: OK" in proc.stdout
