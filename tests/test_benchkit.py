"""Unit tests for the bench harness machinery extracted into
``benchkit`` (VERDICT r4 item 8) — the pieces whose failure loses round
artifacts, tested without running the full bench.

The end-to-end contracts stay where they were: the watchdog subprocess
rescue in ``tests/test_bench_paths.py`` and the SMALL-mode full run the
rounds exercise.
"""

import json

import pytest


def _fresh_core(monkeypatch, budget="540"):
    """Import a pristine benchkit.core with a controlled budget env."""
    import sys

    monkeypatch.setenv("CRDT_BENCH_BUDGET_S", budget)
    for name in [n for n in sys.modules if n.startswith("benchkit")]:
        sys.modules.pop(name)
    import benchkit.core as core

    return core


def test_emit_prints_only_with_value(monkeypatch, capsys):
    core = _fresh_core(monkeypatch)
    core.emit(config4_merges_per_sec=5.0)  # no headline value yet
    assert capsys.readouterr().out == ""
    core.emit(value=2e6)
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["value"] == 2e6
    assert rec["vs_baseline"] == 0.2  # value / 1e7
    assert rec["config4_merges_per_sec"] == 5.0  # earlier field retained


def test_run_stage_skips_on_budget_and_absorbs_errors(monkeypatch, capsys):
    core = _fresh_core(monkeypatch, budget="0")
    assert core.run_stage("x", 10, lambda: 1) is None
    core.emit(value=1.0)  # make the state printable
    out = capsys.readouterr().out
    assert json.loads(out.strip().splitlines()[-1])["x_skipped"] == "budget"

    core = _fresh_core(monkeypatch, budget="10000")

    def boom():
        raise RuntimeError("kaput")

    assert core.run_stage("y", 1, boom) is None
    core.emit(value=1.0)
    out = capsys.readouterr().out
    assert "RuntimeError: kaput" in json.loads(
        out.strip().splitlines()[-1]
    )["y_error"]
    # and a healthy stage returns its value
    assert core.run_stage("z", 1, lambda: 42) == 42


def test_banked_seed_and_headline_rules(monkeypatch, capsys):
    core = _fresh_core(monkeypatch)
    import benchkit.banked as banked

    rec = {"platform": "tpu", "value": 3.17e6, "captured_at": "T"}
    # banked TPU headline seeded (as main() does after load_banked — the
    # load itself is covered by test_load_banked_rejects_non_tpu_and_
    # garbage); a CPU-fallback live run must file under live_* and keep
    # the banked top-level record
    banked.BANKED_HEADLINE = True
    core.emit(value=rec["value"], platform="tpu",
              headline_source="banked_window")
    capsys.readouterr()
    banked.emit_headline(1234.5, {"kernel": "native_fold"}, "cpu", True)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["value"] == 3.17e6 and out["platform"] == "tpu"
    assert out["live_value"] == 1234.5
    assert out["live_kernel"] == "native_fold"
    assert out["live_backend_fallback"] is True
    assert out["headline_source"] == "banked_window"

    # a live TPU measurement DOES take the top-level slot, and clears
    # the banked flag (the run now carries its own on-chip evidence)
    banked.emit_headline(5e6, {"kernel": "jnp_fold"}, "tpu", False)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["value"] == 5e6
    assert out["headline_source"] == "live"
    assert banked.BANKED_HEADLINE is False


def test_load_banked_rejects_non_tpu_and_garbage(monkeypatch, tmp_path):
    _fresh_core(monkeypatch)
    import benchkit.banked as banked

    root = tmp_path
    monkeypatch.setattr(
        banked.os.path, "abspath", lambda _p: str(root / "benchkit" / "x.py")
    )
    (root / "benchkit").mkdir()
    path = root / "BENCH_tpu_window.json"

    assert banked.load_banked() is None  # missing file
    path.write_text("not json")
    assert banked.load_banked() is None
    path.write_text(json.dumps({"platform": "cpu", "value": 5.0}))
    assert banked.load_banked() is None  # non-TPU record refused
    path.write_text(json.dumps({"platform": "tpu", "value": "NaNish"}))
    assert banked.load_banked() is None  # non-numeric value refused
    good = {"platform": "tpu", "value": 7.0, "captured_rev": "abc"}
    path.write_text(json.dumps(good))
    assert banked.load_banked() == good


def test_axon_art_meta_identity_fields(monkeypatch):
    _fresh_core(monkeypatch)
    import benchkit.axon_bank as ab

    monkeypatch.setenv("CRDT_PALLAS_KERNEL", "fused")
    meta = ab.axon_art_meta(20, 62_500, 8)
    assert meta["kernel"] == "fused"
    assert meta["counts"] == {"n_chunks": 20, "chunk": 62_500, "r": 8}
    monkeypatch.delenv("CRDT_PALLAS_KERNEL")
    assert ab.axon_art_meta(20, 62_500, 8)["kernel"] == "aligned"
    # identity mismatch on any field must compare unequal
    assert meta != ab.axon_art_meta(20, 62_500, 8)


def test_watchdog_fires_and_emits(monkeypatch, capsys):
    core = _fresh_core(monkeypatch, budget="0")
    fired = {}
    monkeypatch.setattr(core.os, "_exit", lambda rc: fired.setdefault("rc", rc))
    core.emit(value=9.0, platform="tpu", headline_source="live")
    capsys.readouterr()
    core.install_budget_watchdog(grace_s=0.0)
    import time

    for _ in range(100):
        if fired:
            break
        time.sleep(0.1)
    assert fired.get("rc") == 0
    out = capsys.readouterr().out
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["budget_watchdog"] == "fired"
    assert rec["value"] == 9.0
