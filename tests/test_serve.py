"""Batched read front-end tests — gather kernels, session-consistency
admission, the read frame codec, the serve loop (crdt_tpu/serve,
ISSUE 17).

The acceptance pins: (1) ONE jitted gather step resolves a ≥4k-read
batch byte-identical — val, add-clock and rm-clock rows — to the
scalar ``ReadCtx`` loop (`orswot.rs:60-83` read semantics); (2) the
batched ReadCtx clocks feed ``derive_rm_ctx`` into removes
byte-identical to the scalar clone-read-remove loop; (3) the
consistency modes behave as admission predicates — read-your-writes
parks/admits against the log-inclusive write clock, monotonic tokens
never regress, frontier-stable reads stamp per-row stability against
the PR 15 frontier and reject loudly (typed) when no frontier exists.
"""

import numpy as np
import pytest

from crdt_tpu import serve
from crdt_tpu.batch import (
    GCounterBatch,
    LWWRegBatch,
    MapBatch,
    MVRegBatch,
    OrswotBatch,
    PNCounterBatch,
)
from crdt_tpu.cluster import ClusterNode
from crdt_tpu.config import CrdtConfig
from crdt_tpu.error import (
    ConsistencyUnavailableError,
    SyncProtocolError,
    WireFormatError,
)
from crdt_tpu.obs import metrics as obs_metrics
from crdt_tpu.oplog import OpApplier, OpLog, derive_rm_ctx
from crdt_tpu.scalar.gcounter import GCounter
from crdt_tpu.scalar.lwwreg import LWWReg
from crdt_tpu.scalar.map import Map
from crdt_tpu.scalar.mvreg import MVReg
from crdt_tpu.scalar.orswot import Orswot
from crdt_tpu.scalar.pncounter import PNCounter
from crdt_tpu.sync import digest as sync_digest
from crdt_tpu.utils import tracing
from crdt_tpu.utils.interning import Universe

pytestmark = pytest.mark.serve


def _uni(num_actors=8, member_capacity=16, deferred_capacity=4):
    return Universe.identity(CrdtConfig(
        num_actors=num_actors, member_capacity=member_capacity,
        deferred_capacity=deferred_capacity, counter_bits=32))


def _orswot_fleet(n, seed, actors=4, members=24, removes=True):
    """N scalar sets with real history: multi-actor adds and (opt)
    removes, so witnessing clocks genuinely differ per member."""
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        s = Orswot()
        for _ in range(rng.randint(2, 7)):
            s.apply(s.add(int(rng.randint(0, members)),
                          s.value().derive_add_ctx(int(
                              rng.randint(0, actors)))))
        if removes and rng.rand() < 0.5 and s.value().val:
            m = sorted(s.value().val)[0]
            s.apply(s.remove(m, s.value().derive_rm_ctx()))
        out.append(s)
    return out


def _row(vc, width):
    """Dense clock row from a scalar VClock under an identity universe
    (actor names ARE dense columns)."""
    r = np.zeros(width, np.uint64)
    for actor, cnt in vc.dots.items():
        r[int(actor)] = cnt
    return r


# ---------------------------------------------------------------------------
# gather parity: the jitted kernels vs the scalar ReadCtx loop
# ---------------------------------------------------------------------------


def test_orswot_gather_4k_parity_one_step():
    """THE acceptance bar: one gather resolves a 4096-read mixed
    contains/value batch byte-identical to the scalar ReadCtx loop,
    through ONE jitted kernel call."""
    uni = _uni()
    n, b = 64, 4096
    sets = _orswot_fleet(n, seed=11)
    batch = OrswotBatch.from_scalar(sets, uni)
    rng = np.random.RandomState(12)
    obj = rng.randint(0, n, b)
    member = rng.randint(0, 30, b).astype(np.int32)
    member[rng.rand(b) < 0.5] = serve.NO_MEMBER  # value() reads

    before = obs_metrics.registry().counters_snapshot().get(
        "kernel.serve_gather_orswot.calls", 0)
    frame = serve.gather(batch, obj, member=member)
    after = obs_metrics.registry().counters_snapshot().get(
        "kernel.serve_gather_orswot.calls", 0)
    assert after - before == 1, "a 4k batch must be ONE gather step"

    assert len(frame) == b and frame.add_clock.shape == (b, 8)
    a = frame.add_clock.shape[1]
    for i in range(b):
        s = sets[int(obj[i])]
        if member[i] == serve.NO_MEMBER:
            rc = s.value()
            want = len(rc.val)
        else:
            rc = s.contains(int(member[i]))
            want = int(bool(rc.val))
        assert int(frame.val[i]) == want, i
        assert np.array_equal(frame.add_clock[i], _row(rc.add_clock, a)), i
        assert np.array_equal(frame.rm_clock[i], _row(rc.rm_clock, a)), i


def test_counter_gather_parity():
    uni = _uni()
    rng = np.random.RandomState(3)
    gcs = [GCounter() for _ in range(12)]
    pns = [PNCounter() for _ in range(12)]
    for g, p in zip(gcs, pns):
        for _ in range(rng.randint(1, 8)):
            a = int(rng.randint(0, 4))
            g.apply(g.inc(a))
            p.apply(p.inc(a))
            if rng.rand() < 0.5:
                p.apply(p.dec(a))
    gb = GCounterBatch.from_scalar(gcs, uni)
    pb = PNCounterBatch.from_scalar(pns, uni)
    obj = rng.randint(0, 12, 64)
    gf = serve.gather(gb, obj)
    pf = serve.gather(pb, obj)
    assert gf.kind[0] == serve.K_GCOUNTER
    assert pf.kind[0] == serve.K_PNCOUNTER
    for i, o in enumerate(obj):
        assert int(gf.val[i]) == gcs[int(o)].value()
        # PN value rides u64 wrap-around arithmetic: p - n mod 2^64
        want = (pns[int(o)].value()) % (1 << 64)
        assert int(pf.val[i]) == want
    # the counter clock plane IS the AddCtx base: add == rm == row
    assert np.array_equal(gf.add_clock, gf.rm_clock)


def test_lww_mvreg_map_gather_parity():
    from crdt_tpu.batch import MVRegKernel

    uni = _uni()
    rng = np.random.RandomState(5)
    lws = [LWWReg() for _ in range(8)]
    mvs = [MVReg() for _ in range(8)]
    mps = [Map(MVReg) for _ in range(8)]
    for i, (lw, mv, mp) in enumerate(zip(lws, mvs, mps)):
        lw.update(int(rng.randint(0, 99)), i + 1)
        mv.apply(mv.set(int(rng.randint(0, 99)),
                        mv.read().derive_add_ctx(int(rng.randint(0, 4)))))
        for _ in range(rng.randint(0, 3)):
            v = int(rng.randint(0, 99))
            mp.apply(mp.update(
                int(rng.randint(0, 6)),
                mp.len().derive_add_ctx(int(rng.randint(0, 4))),
                lambda r, c, v=v: r.set(v, c)))
    obj = rng.randint(0, 8, 32)
    lf = serve.gather(LWWRegBatch.from_scalar(lws, uni), obj)
    assert lf.add_clock.shape == (32, 0)  # clockless kind
    mf = serve.gather(MVRegBatch.from_scalar(mvs, uni), obj)
    key = rng.randint(-1, 6, 32).astype(np.int32)  # -1 = len() reads
    pf = serve.gather(
        MapBatch.from_scalar(mps, uni, MVRegKernel.from_config(uni.config)),
        obj, member=key)
    a = mf.add_clock.shape[1]
    for i, o in enumerate(obj):
        assert int(lf.val[i]) == lws[int(o)].val
        rc = mvs[int(o)].read()
        assert int(mf.val[i]) == len(rc.val)  # live concurrent slots
        assert np.array_equal(mf.add_clock[i], _row(rc.add_clock, a)), i
        m = mps[int(o)]
        if key[i] < 0:
            rc = m.len()
            assert int(pf.val[i]) == rc.val
            assert np.array_equal(pf.rm_clock[i], _row(rc.rm_clock, a))
        else:
            rc = m.get(int(key[i]))
            assert int(pf.val[i]) == int(rc.val is not None)
            assert np.array_equal(pf.add_clock[i], _row(rc.add_clock, a))
            assert np.array_equal(pf.rm_clock[i], _row(rc.rm_clock, a))


def test_gather_validates_object_range_and_counts():
    uni = _uni()
    batch = OrswotBatch.from_scalar(_orswot_fleet(4, seed=2), uni)
    before = tracing.counters().get("serve.reads", 0)
    serve.gather(batch, np.array([0, 3]))
    assert tracing.counters().get("serve.reads", 0) - before == 2
    with pytest.raises(IndexError):
        serve.gather(batch, np.array([4]))  # out of range
    with pytest.raises(IndexError):
        serve.gather(batch, np.array([-1]))


def test_query_engine_mixed_kinds():
    uni = _uni()
    sets = _orswot_fleet(6, seed=7)
    gcs = [GCounter() for _ in range(6)]
    for i, g in enumerate(gcs):
        for _ in range(i + 1):
            g.apply(g.inc(0))
    eng = serve.QueryEngine({
        serve.K_ORSWOT: OrswotBatch.from_scalar(sets, uni),
        serve.K_GCOUNTER: GCounterBatch.from_scalar(gcs, uni),
    })
    obj = np.array([0, 1, 2, 3])
    kind = np.array([serve.K_GCOUNTER, serve.K_ORSWOT,
                     serve.K_GCOUNTER, serve.K_ORSWOT], np.uint8)
    frame = eng.gather(obj, kind)
    assert int(frame.val[0]) == 1 and int(frame.val[2]) == 3
    assert int(frame.val[1]) == len(sets[1].value().val)
    assert int(frame.val[3]) == len(sets[3].value().val)
    assert np.array_equal(frame.kind, kind)


# ---------------------------------------------------------------------------
# the parity PIN: gathered ReadCtx clocks drive removes (ISSUE 17 (e))
# ---------------------------------------------------------------------------


def test_gathered_rm_ctx_drives_removes_byte_identical():
    """Property (seeded trials): value-read rm-clock rows from the
    batched gather, fed through ``derive_rm_ctx`` and the scatter-fold,
    produce removes byte-identical to the scalar clone-read-remove
    loop (`ctx.rs:56-60` + `orswot.rs:80-83`)."""
    for seed in (21, 22, 23, 24, 25):
        uni = _uni()
        n = 16
        sets = _orswot_fleet(n, seed=seed, removes=False)
        batch = OrswotBatch.from_scalar(sets, uni)
        rng = np.random.RandomState(seed * 100)
        # remove one live member from each object that has any
        obj, member = [], []
        for i, s in enumerate(sets):
            live = sorted(s.value().val)
            if live:
                obj.append(i)
                member.append(live[int(rng.randint(0, len(live)))])
        obj = np.array(obj, np.int64)
        member = np.array(member, np.int32)

        # batched: gather value() reads, scatter their rm-clock rows
        # back into an [N, A] base, and let derive_rm_ctx clone them
        frame = serve.gather(batch, obj, member=None)
        base = np.zeros((n, frame.rm_clock.shape[1]), np.uint64)
        base[obj] = frame.rm_clock
        ops = derive_rm_ctx(base, obj, member)
        folded, rep = OpApplier(uni).apply_ops(batch, ops)
        assert rep.still_parked == 0

        # scalar: the clone-read-remove loop on twin objects
        for i in range(obj.size):
            s = sets[int(obj[i])]
            s.apply(s.remove(int(member[i]), s.value().derive_rm_ctx()))
        ref = OrswotBatch.from_scalar(sets, uni)

        assert np.array_equal(
            np.asarray(sync_digest.digest_of(folded)),
            np.asarray(sync_digest.digest_of(ref)),
        ), f"seed {seed}: batched rm-ctx remove != scalar loop"
        for i in range(obj.size):
            assert sets[int(obj[i])].contains(int(member[i])).val is False


# ---------------------------------------------------------------------------
# consistency: the admission predicates
# ---------------------------------------------------------------------------


def test_covers_zero_pads_widths():
    assert serve.covers(np.array([2, 1], np.uint64),
                        np.array([2], np.uint64))
    assert not serve.covers(np.array([2], np.uint64),
                            np.array([2, 1], np.uint64))
    assert serve.covers(np.array([], np.uint64), None)
    assert serve.covers(np.array([], np.uint64),
                        np.array([], np.uint64))


def test_admit_modes():
    vis = np.array([3, 2], np.uint64)
    ok = serve.admit(serve.MODE_EVENTUAL, np.array([9], np.uint64), vis)
    assert ok.admitted  # eventual ignores the floor
    assert serve.admit(serve.MODE_RYW, np.array([3, 2], np.uint64),
                       vis).admitted
    parked = serve.admit(serve.MODE_RYW, np.array([4], np.uint64), vis)
    assert not parked.admitted and parked.reason == "not_visible"
    assert serve.admit(serve.MODE_MONOTONIC, None, vis).admitted
    no_f = serve.admit(serve.MODE_FRONTIER, None, vis, frontier_vv=None)
    assert not no_f.admitted and no_f.reason == "no_frontier"
    assert serve.admit(serve.MODE_FRONTIER, None, vis,
                       frontier_vv=np.array([1], np.uint64)).admitted
    with pytest.raises(ValueError):
        serve.admit("linearizable", None, vis)


def test_stability_statuses_per_row():
    frame = serve.ResultFrame(
        obj=np.array([0, 1, 2, 3]),
        kind=np.full(4, serve.K_ORSWOT, np.uint8),
        member=np.full(4, serve.NO_MEMBER, np.int32),
        status=np.zeros(4, np.uint8),
        val=np.zeros(4, np.uint64),
        add_clock=np.array([[1, 0], [2, 0], [0, 3], [1, 1]], np.uint64),
        rm_clock=np.zeros((4, 2), np.uint64),
        token=np.zeros(2, np.uint64),
    )
    # two subtrees of span 2 with different frontier clocks
    subtrees = np.array([[1, 0], [1, 1]], np.uint64)
    st = serve.stability_statuses(frame, subtrees, span=2)
    assert st.tolist() == [serve.ST_OK, serve.ST_NOT_STABLE,
                           serve.ST_NOT_STABLE, serve.ST_OK]


# ---------------------------------------------------------------------------
# wire: the versioned + CRC'd read/result codec
# ---------------------------------------------------------------------------


def _req(b=5, w=4, mode=serve.MODE_RYW, seed=9):
    rng = np.random.RandomState(seed)
    return serve.ReadRequest(
        obj=rng.randint(0, 50, b),
        kind=np.full(b, serve.K_ORSWOT, np.uint8),
        member=rng.randint(-1, 9, b).astype(np.int32),
        mode=mode,
        require=rng.randint(0, 9, w).astype(np.uint64),
    )


def test_read_request_roundtrip():
    req = _req()
    back = serve.decode_read_request(serve.encode_read_request(req))
    assert np.array_equal(back.obj, req.obj)
    assert np.array_equal(back.kind, req.kind)
    assert np.array_equal(back.member, req.member)
    assert back.mode == req.mode
    assert np.array_equal(back.require, req.require)


def test_result_frame_roundtrip():
    uni = _uni()
    batch = OrswotBatch.from_scalar(_orswot_fleet(8, seed=31), uni)
    frame = serve.gather(batch, np.arange(8))
    frame.token = np.array([7, 0, 3, 0, 0, 0, 0, 1], np.uint64)
    back = serve.decode_result_frame(serve.encode_result_frame(frame))
    for field in ("obj", "kind", "member", "status", "val",
                  "add_clock", "rm_clock", "token"):
        assert np.array_equal(getattr(back, field),
                              getattr(frame, field)), field


def test_wire_rejects_are_typed_and_counted():
    frame = serve.encode_read_request(_req())
    with pytest.raises(SyncProtocolError):
        serve.decode_read_request(frame[:6])  # truncated
    bad_ver = bytearray(frame)
    bad_ver[0] = 99
    with pytest.raises(SyncProtocolError):
        serve.decode_read_request(bytes(bad_ver))
    with pytest.raises(SyncProtocolError):  # wrong frame type
        serve.decode_result_frame(frame)
    corrupt = bytearray(frame)
    corrupt[-1] ^= 0xFF
    before = tracing.counters().get("serve.frames.rejected.crc_mismatch", 0)
    with pytest.raises(SyncProtocolError):
        serve.decode_read_request(bytes(corrupt))
    assert tracing.counters().get(
        "serve.frames.rejected.crc_mismatch", 0) == before + 1
    with pytest.raises(WireFormatError):  # grammar: object out of range
        serve.decode_read_request(frame, num_objects=3)
    with pytest.raises(SyncProtocolError):  # trailing bytes
        serve.decode_read_request(frame + b"\x00")


def test_wire_rejects_bad_columns():
    req = _req()
    req.kind = np.full(len(req), 99, np.uint8)
    with pytest.raises(WireFormatError):
        serve.decode_read_request(serve.encode_read_request(req))
    req = _req()
    enc = serve.encode_read_request(req)
    # corrupt the mode byte inside the payload, re-CRC honestly — the
    # grammar check must fire, not CRC luck
    import struct
    import zlib

    hdr = struct.Struct("<BBIQ")
    payload = bytearray(enc[hdr.size:])
    payload[struct.calcsize("<IH")] = 250  # mode code out of range
    new = bytes(payload)
    reframed = hdr.pack(serve.SERVE_PROTOCOL_VERSION, serve.FRAME_READ,
                        zlib.crc32(new), len(new)) + new
    with pytest.raises(WireFormatError):
        serve.decode_read_request(reframed)


# ---------------------------------------------------------------------------
# the serve loop on a live ClusterNode
# ---------------------------------------------------------------------------


def _node(n=16, seed=41, uni=None):
    uni = uni or _uni()
    batch = OrswotBatch.from_scalar(_orswot_fleet(n, seed=seed), uni)
    return ClusterNode("n0", batch, uni, oplog=OpLog(uni))


def test_serve_ryw_sees_every_acknowledged_write():
    node = _node()
    for k in range(6):
        node.submit_writes(np.array([k], np.int64),
                           np.array([200 + k], np.int32), actor=2)
        ack = node.write_vv()
        frame = node.serve_reads(serve.ReadRequest.reads(
            [k], member=200 + k, mode="ryw", require=ack))
        assert int(frame.val[0]) == 1, f"RYW violated on write {k}"
        assert serve.covers(frame.token, ack)


def test_serve_monotonic_token_never_regresses():
    node = _node()
    tok = node.read_token()
    for k in range(5):
        node.submit_writes(np.array([k], np.int64),
                           np.array([210], np.int32), actor=1)
        frame = node.serve_reads(serve.ReadRequest.reads(
            np.arange(4), mode="monotonic", require=tok))
        assert np.all(frame.token >= tok)
        tok = frame.token


def test_serve_ryw_unreachable_floor_parks_then_rejects():
    node = _node()
    node.serve_reads(serve.ReadRequest.reads([0]))  # build the loop
    node._serve_loop.park_timeout_s = 0.02
    with pytest.raises(ConsistencyUnavailableError) as ei:
        node.serve_reads(serve.ReadRequest.reads(
            [0], mode="ryw", require=np.full(8, 99, np.uint64)))
    assert ei.value.mode == serve.MODE_RYW
    assert ei.value.reason == "not_visible"


def test_serve_frontier_rejects_without_a_frontier():
    node = _node()
    with pytest.raises(ConsistencyUnavailableError) as ei:
        node.serve_reads(serve.ReadRequest.reads([0], mode="frontier"))
    assert ei.value.reason == "no_frontier"


def test_serve_frames_pipeline_and_rejects():
    node = _node()
    good = [serve.encode_read_request(serve.ReadRequest.reads(
        np.arange(8))) for _ in range(4)]
    bad = serve.encode_read_request(serve.ReadRequest.reads(
        [0], mode="ryw", require=np.full(8, 99, np.uint64)))
    node.serve_reads(serve.ReadRequest.reads([0]))
    node._serve_loop.park_timeout_s = 0.02
    out, stats = node._serve_loop.serve_frames(good + [bad])
    assert stats["frames"] == 5 and stats["rejected"] == 1
    assert out[-1] is None and all(o is not None for o in out[:4])
    decoded = serve.decode_result_frame(out[0])
    assert len(decoded) == 8
    assert set(stats["stage_s"]) == {"decode", "serve", "encode"}
    with pytest.raises(ValueError):
        serve.ServeLoop(node, depth=1)


def test_serve_read_ctx_bridges_to_scalar():
    uni = _uni()
    sets = _orswot_fleet(4, seed=51)
    batch = OrswotBatch.from_scalar(sets, uni)
    frame = serve.gather(batch, np.array([1]))
    rc = frame.read_ctx(0, uni)
    ref = sets[1].value()
    assert rc.add_clock == ref.add_clock
    assert rc.rm_clock == ref.rm_clock
