"""LWWReg tests — mirrors `/root/reference/test/lwwreg.rs` and the doctests
in `/root/reference/src/lwwreg.rs:49-55,84-103`."""

import pytest
from hypothesis import assume, given
from hypothesis import strategies as st

from crdt_tpu import ConflictingMarker, LWWReg


def test_default():
    reg = LWWReg(val="", marker=0)
    assert reg == LWWReg("", 0)


def test_update():
    """`test/lwwreg.rs:15-37`."""
    reg = LWWReg(val=123, marker=0)

    # normal update: new marker descends the current marker
    reg.update(32, 2)
    assert reg == LWWReg(32, 2)

    # stale update: marker is an ancestor — no-op
    reg.update(57, 1)
    assert reg == LWWReg(32, 2)

    # redundant update: same marker and val — no-op
    reg.update(32, 2)
    assert reg == LWWReg(32, 2)

    # bad update: same marker, different val — error
    with pytest.raises(ConflictingMarker):
        reg.update(4000, 2)
    assert reg == LWWReg(32, 2)


def test_merge_conflict_doc():
    """`lwwreg.rs:49-55`: equal marker, different val errors."""
    l1 = LWWReg(val=1, marker=2)
    l2 = LWWReg(val=3, marker=2)
    with pytest.raises(ConflictingMarker):
        l1.merge(l2)


def build_from_prim(prim):
    """`test/lwwreg.rs:39-45`: tuple marker avoids conflicts."""
    val, m = prim
    return LWWReg(val=val, marker=(m, val))


prims = st.tuples(st.integers(0, 255), st.integers(0, 2**16 - 1))


def _conflicting(r1, r2):
    return r1.marker == r2.marker and r1.val != r2.val


@given(prims, prims, prims)
def test_prop_associative(p1, p2, p3):
    r1, r2, r3 = build_from_prim(p1), build_from_prim(p2), build_from_prim(p3)
    assume(not (_conflicting(r1, r2) or _conflicting(r1, r3) or _conflicting(r2, r3)))

    r1_snapshot = r1.clone()

    # (r1 ^ r2) ^ r3
    r1.merge(r2)
    r1.merge(r3)

    # r1 ^ (r2 ^ r3)
    r2.merge(r3)
    r1_snapshot.merge(r2)

    assert r1 == r1_snapshot


@given(prims, prims)
def test_prop_commutative(p1, p2):
    r1, r2 = build_from_prim(p1), build_from_prim(p2)
    assume(not _conflicting(r1, r2))
    r1_snapshot = r1.clone()
    r1.merge(r2)
    r2.merge(r1_snapshot)
    assert r1 == r2


@given(prims)
def test_prop_idempotent(p):
    r = build_from_prim(p)
    r_snapshot = r.clone()
    r.merge(r_snapshot)
    assert r == r_snapshot


def test_default_constructed_is_usable():
    """LWWReg() must behave like the reference Default (marker = 0)."""
    reg = LWWReg()
    reg.update(5, 1)
    assert reg == LWWReg(5, 1)
