"""Mesh-sharded fleet tests (crdt_tpu.mesh): one logical replica in S pieces.

The acceptance surface of the mesh subsystem, on the conftest-forced
8-device CPU mesh:

- layout math — subtree-granule shard bounds, rebase/unbase round-trip
  (the routed-leaf exemption's runtime half), heat-priced granule choice
  agreeing with the PR 18 planner's ``mesh:S`` pricing;
- mesh-size invariance — seeded random op/merge/GC histories run through
  the ONE pjit'd anti-entropy step on mesh {1,2,4,8} produce digest
  vectors and digest-tree roots byte-identical to the unsharded control,
  padding rows staying digest-invisible throughout;
- the one-launch pin — a 64k-object fleet's full anti-entropy round is
  ONE ``mesh.step.anti_entropy`` kernel call (kernel-observatory call
  counters; the flat-path kernels don't move);
- the runtime↔static contract cross-check — the mesh dispatch consumes
  exactly the kernels the shardcheck manifest declares shardable, and
  refuses host_only / replicated / unknown / wrong-mesh-size kernels
  with a typed :class:`~crdt_tpu.error.MeshContractError`;
- shard-subset sync — only the diverged shard's subtree bytes ship
  (counter-pinned), converged fleets ship nothing, and the ClusterNode
  wiring repairs under the session busy-lock discipline;
- per-shard durability — fleet checkpoint/restore round-trip, the
  shards-then-manifest write order surviving a simulated kill -9, and
  typed rejection (+ counters) for every manifest/shard corruption mode.
"""

import json
import os

import jax
import numpy as np
import pytest

from crdt_tpu import Dot, Orswot, mesh
from crdt_tpu.analysis.kernels import MANIFEST
from crdt_tpu.batch import OrswotBatch
from crdt_tpu.cluster import ClusterNode
from crdt_tpu.config import CrdtConfig
from crdt_tpu.error import (
    CheckpointFormatError,
    DurabilityError,
    MeshContractError,
)
from crdt_tpu.gc.compact import settle_orswot
from crdt_tpu.mesh import durable as mesh_durable
from crdt_tpu.mesh import step as mesh_step
from crdt_tpu.obs import kernels as obs_kernels
from crdt_tpu.obs import metrics as obs_metrics
from crdt_tpu.obs.heat import HeatTracker, mesh_bounds
from crdt_tpu.obs.stability import subtree_layout
from crdt_tpu.scalar.orswot import Add, Rm
from crdt_tpu.sync import digest as digest_mod
from crdt_tpu.sync import tree as tree_mod
from crdt_tpu.utils import tracing
from crdt_tpu.utils.interning import Universe
from crdt_tpu.utils.testdata import anti_entropy_fleets

pytestmark = [
    pytest.mark.mesh,
    pytest.mark.skipif(
        len(jax.devices()) < 8,
        reason="needs the 8-device CPU mesh (see conftest)",
    ),
]


def small_universe():
    return Universe(CrdtConfig(num_actors=8, member_capacity=16,
                               deferred_capacity=8))


def _scalar_row(seed, n):
    """n scalar Orswots with seeded random op histories (actors 0-6;
    actor 7 is reserved for :func:`_with_extras` divergence dots)."""
    row = []
    for i in range(n):
        rng = np.random.RandomState(seed * 100_003 + i)
        s = Orswot()
        for _ in range(rng.randint(1, 7)):
            actor = int(rng.randint(0, 7))
            member = int(rng.randint(0, 8))
            counter = int(rng.randint(1, 6))
            if rng.rand() < 0.75:
                s.apply(Add(dot=Dot(actor, counter), member=member))
            else:
                s.apply(Rm(clock=Dot(actor, counter).to_vclock(),
                           member=member))
        row.append(s)
    return row


def _history_batches(n, uni):
    """Two replicas of one fleet with a seeded op/merge/GC history:
    each side is a merge of two independently grown batches, settled
    through the GC compaction pass (divergent at most rows)."""
    a = OrswotBatch.from_scalar(_scalar_row(1, n), uni).merge(
        OrswotBatch.from_scalar(_scalar_row(2, n), uni))
    a, _ = settle_orswot(a)
    b = OrswotBatch.from_scalar(_scalar_row(2, n), uni).merge(
        OrswotBatch.from_scalar(_scalar_row(3, n), uni))
    b, _ = settle_orswot(b)
    return a, b


def _with_extras(batch, uni, n, extra_ids):
    """``batch`` plus one fresh actor-7 dot at each of ``extra_ids``
    — divergence confined to exactly those rows (actor 7 appears in
    no base history, so the new dots always dominate)."""
    row = [Orswot() for _ in range(n)]
    for i in extra_ids:
        row[i].apply(Add(dot=Dot(7, 9), member=int(i) % 8))
    return batch.merge(OrswotBatch.from_scalar(row, uni))


# -- layout math -------------------------------------------------------------


def test_layout_bounds_rebase_roundtrip():
    lay = mesh.choose_layout(100, 4, granule=16)
    assert lay.bounds == tuple(mesh_bounds(100, 4, granule=16))
    assert lay.bounds == (0, 32, 64, 96, 100)
    assert lay.padded == 4 * lay.per_shard
    # ranges partition [0, n)
    covered = [i for lo, hi in lay.ranges() for i in range(lo, hi)]
    assert covered == list(range(100))
    ids = np.arange(100, dtype=np.int64)
    shard_idx, local = lay.rebase(ids)
    for s, (lo, hi) in enumerate(lay.ranges()):
        assert (shard_idx[lo:hi] == s).all()
        assert lay.objects_of(s) == hi - lo
        for i in (lo, hi - 1) if hi > lo else ():
            assert lay.shard_of(i) == s
    assert np.array_equal(lay.unbase(shard_idx, local), ids)
    with pytest.raises(IndexError):
        lay.rebase(np.array([100]))
    with pytest.raises(IndexError):
        lay.shard_of(-1)
    for bad in (0, 3, -16):
        with pytest.raises(ValueError):
            mesh.choose_layout(100, 4, granule=bad)


def test_choose_layout_prices_granules_like_the_planner():
    """With a heat tracker, choose_layout picks the candidate granule
    (span, 2*span, 4*span) whose mesh:S pricing has the lowest
    imbalance — the same score_plan the /heat route serves."""
    from crdt_tpu.obs.heat import score_plan

    n, shards = 64, 2
    span = subtree_layout(n)[1]
    trk = HeatTracker(registry=obs_metrics.MetricsRegistry())
    # heavy heat in the first subtree, light elsewhere: the coarsest
    # candidate granule (one shard-sized slab) prices terribly, the
    # finer ones balance — the search must pick a finer one
    trk.record_writes(np.zeros(500, dtype=np.int64), n)
    trk.record_writes(np.arange(n, dtype=np.int64), n)
    hv = trk.heat_vector()
    lay = mesh.choose_layout(n, shards, heat=hv)
    candidates = {
        g: score_plan(f"mesh:{shards}", hv, n=n, span=span,
                      granule=g)["imbalance"]
        for g in (span, 2 * span, 4 * span)
    }
    assert lay.granule in candidates
    assert lay.imbalance == pytest.approx(min(candidates.values()))
    assert lay.bounds == tuple(mesh_bounds(n, shards,
                                           granule=lay.granule))


def test_padding_rows_are_digest_invisible():
    """Empty rows digest to 0 (the XOR identity), so the tail shard's
    padding never shows in digests, shard roots, or tree roots."""
    uni = small_universe()
    zeros = OrswotBatch.zeros(8, uni)
    assert (np.asarray(digest_mod.digest_of(zeros, uni)) == 0).all()
    a, _ = _history_batches(12, uni)  # 12 rows: S=8 pads every shard
    control = np.asarray(digest_mod.digest_of(a, uni), dtype=np.uint64)
    sa = mesh.ShardedBatch.shard(a, uni, shards=8, granule=2)
    assert sa.layout.padded > sa.layout.n
    res = mesh.anti_entropy_step(sa, mesh.ShardedBatch.shard(
        a, uni, shards=8, granule=2))
    assert np.array_equal(res.digests, control)
    assert res.digests.size == sa.layout.n
    assert tree_mod.build_tree(res.digests).root == \
        tree_mod.build_tree(control).root


# -- mesh-size invariance ----------------------------------------------------


def test_mesh_size_invariance_digests_and_roots():
    """Seeded random op/merge/GC history on mesh {1,2,4,8} + the
    unsharded control: digest vectors and digest-tree roots must be
    byte-identical at every mesh size."""
    uni = small_universe()
    n = 48
    a, b = _history_batches(n, uni)
    b = _with_extras(b, uni, n, (3, 17, 40))
    control = np.asarray(
        digest_mod.digest_of(a.merge(b), uni), dtype=np.uint64)
    control_root = tree_mod.build_tree(control).root
    for shards in mesh.MESH_SIZES:
        sa = mesh.ShardedBatch.shard(a, uni, shards=shards, granule=4)
        sb = mesh.ShardedBatch.shard(b, uni, shards=shards, granule=4)
        res = mesh.anti_entropy_step(sa, sb)
        assert control.dtype == res.digests.dtype
        assert np.array_equal(res.digests, control), \
            f"digest vector diverged from control at mesh={shards}"
        assert tree_mod.build_tree(res.digests).root == control_root
        # the merged fleet re-digests to the same vector off-mesh
        merged = np.asarray(
            digest_mod.digest_of(res.batch.logical(), uni),
            dtype=np.uint64)
        assert np.array_equal(merged, control)


def test_mesh_step_version_vector_and_members_match_control():
    uni = small_universe()
    a, b = _history_batches(24, uni)
    merged = a.merge(b)
    vv = np.asarray(jax.device_get(merged.clock)).max(axis=0)
    from crdt_tpu.ops import orswot_ops
    live = int((np.asarray(jax.device_get(merged.ids))
                != orswot_ops.EMPTY).sum())
    for shards in (1, 4):
        res = mesh.anti_entropy_step(
            mesh.ShardedBatch.shard(a, uni, shards=shards, granule=4),
            mesh.ShardedBatch.shard(b, uni, shards=shards, granule=4))
        assert np.array_equal(res.version_vector, vv.astype(np.uint64))
        assert res.live_members == live


# -- the one-launch acceptance pin -------------------------------------------


def _profile_calls(names):
    obs = obs_kernels.kernel_observatory()
    return {name: obs.profile(name).calls for name in names}


def test_64k_fleet_one_pjit_step_on_8way_mesh():
    """The acceptance run: a 64k-object fleet's FULL anti-entropy round
    (merge + digests + fleet summaries) is ONE mesh.step.anti_entropy
    launch on the 8-way mesh — the flat-path kernels (per-row digest,
    shard-local merge, batch merge) never fire during the step."""
    n = 65_536
    a_cap, m_cap, d_cap = 8, 8, 2
    uni = Universe.identity(CrdtConfig(
        num_actors=a_cap, member_capacity=m_cap, deferred_capacity=d_cap,
        counter_bits=32))
    rng = np.random.RandomState(29)
    reps = anti_entropy_fleets(rng, n, a_cap, m_cap, d_cap, 2,
                               base=3, novel=1, deferred_frac=0.25)
    A, B = OrswotBatch(*reps[0]), OrswotBatch(*reps[1])
    # control digest BEFORE the baselines: digest_of is itself a
    # sync.digest.orswot launch and must not pollute the deltas
    control = np.asarray(digest_mod.digest_of(A.merge(B), uni),
                         dtype=np.uint64)
    sa = mesh.ShardedBatch.shard(A, uni, shards=8)
    sb = mesh.ShardedBatch.shard(B, uni, shards=8)
    assert sa.layout.bounds == tuple(mesh_bounds(n, 8,
                                                 granule=sa.layout.granule))
    watched = ("mesh.step.anti_entropy", "sync.digest.orswot",
               "parallel.shard_local_merge", "batch.orswot.merge")
    before = _profile_calls(watched)
    trace_before = tracing.counters()
    res = mesh.anti_entropy_step(sa, sb)
    deltas = {k: v - before[k] for k, v in _profile_calls(watched).items()}
    assert deltas == {"mesh.step.anti_entropy": 1,
                      "sync.digest.orswot": 0,
                      "parallel.shard_local_merge": 0,
                      "batch.orswot.merge": 0}, deltas
    assert np.array_equal(res.digests, control)
    trace = tracing.counters_since(trace_before)
    assert trace.get("mesh.step.rounds") == 1
    assert trace.get("mesh.step.digest_bytes") == control.nbytes


# -- runtime <-> static contract cross-check ---------------------------------


def test_contract_map_mirrors_shardcheck_manifest():
    """The runtime gate reads THE manifest shardcheck checks: every
    contract-bearing kernel row, nothing else."""
    declared = {s.name for s in MANIFEST if s.sharding is not None}
    assert set(mesh.contract_map()) == declared
    # full coverage is shardcheck's SC04; the runtime gate inherits it
    assert "mesh.step.anti_entropy" in declared


def test_step_consumes_exactly_the_declared_contract_set():
    """The runtime-consumed contract set == the step's declared kernel
    bill, and every consumed contract is statically shardable."""
    uni = small_universe()
    a, b = _history_batches(8, uni)
    mesh.anti_entropy_step(
        mesh.ShardedBatch.shard(a, uni, shards=2, granule=2),
        mesh.ShardedBatch.shard(b, uni, shards=2, granule=2))
    expected = set(mesh_step._SHARDED_KERNELS) | \
        set(mesh_step._SHARD_LOCAL_KERNELS)
    assert expected == {"mesh.step.anti_entropy", "sync.digest.orswot",
                        "parallel.shard_local_merge"}
    consumed = mesh.consumed_contracts()
    assert consumed == frozenset(expected)
    cmap = mesh.contract_map()
    for name in consumed:
        assert cmap[name].sclass in mesh.SHARDABLE_CLASSES


def test_contract_gate_refuses_with_typed_errors():
    cases = [
        ("utils.benchtime.sync_probe", 1, "host_only"),
        ("obs.heat.sketch_update", 2, "replicated"),
        ("parallel.shard_local_merge", 2, "pointwise"),  # mesh_sizes=(1,)
    ]
    for name, size, sclass in cases:
        before = tracing.counters()
        with pytest.raises(MeshContractError) as ei:
            mesh.require_shardable(name, size)
        assert isinstance(ei.value, TypeError)  # typed: a contract error
        assert ei.value.kernel == name
        assert ei.value.sclass == sclass
        assert tracing.counters_since(before).get(
            "mesh.contract.refused") == 1
    with pytest.raises(MeshContractError) as ei:
        mesh.require_shardable("no.such.kernel", 1)
    assert ei.value.kernel == "no.such.kernel"
    # refusals never enter the consumed set
    assert "utils.benchtime.sync_probe" not in mesh.consumed_contracts()


# -- shard-subset sync -------------------------------------------------------


def test_shard_subset_sync_ships_only_the_diverged_shard():
    """Divergence confined to one shard: its subtree bytes ship, the
    skipped shards contribute ZERO descent/delta bytes (counter-pinned),
    and the merged fleet matches the full-merge control."""
    uni = small_universe()
    n = 40
    lay = mesh.choose_layout(n, 4, granule=16)  # bounds (0,16,32,40,40)
    diverged_ids = (33, 34, 36)                 # all inside shard 2
    a, _ = _history_batches(n, uni)
    b = _with_extras(a, uni, n, diverged_ids)
    before = tracing.counters()
    merged, stats = mesh.shard_subset_sync(a, b, lay, uni)
    control = np.asarray(digest_mod.digest_of(a.merge(b), uni),
                         dtype=np.uint64)
    assert np.array_equal(
        np.asarray(digest_mod.digest_of(merged, uni), dtype=np.uint64),
        control)
    assert stats.shards_synced == 1
    assert stats.shards_skipped == 3
    assert set(stats.per_shard) == {2}
    assert stats.objects == len(diverged_ids)
    assert sorted(stats.object_ids.tolist()) == sorted(diverged_ids)
    assert stats.root_bytes == 8 * lay.shards
    assert stats.descent_bytes == stats.per_shard[2]["descent_bytes"] > 0
    assert stats.delta_bytes == stats.per_shard[2]["delta_bytes"] > 0
    # the rebased local rows land inside shard 2's leaf range
    lo, hi = lay.ranges()[2]
    assert all(0 <= r < hi - lo for r in stats.per_shard[2]["local_rows"])
    deltas = tracing.counters_since(before)
    assert deltas.get("mesh.sync.rounds") == 1
    assert deltas.get("mesh.sync.shards_synced") == 1
    assert deltas.get("mesh.sync.shards_skipped") == 3
    assert deltas.get("mesh.sync.objects") == len(diverged_ids)
    assert deltas.get("mesh.sync.delta_bytes") == stats.delta_bytes


def test_shard_roots_detect_identical_twin_updates():
    """Two rows in ONE shard taking IDENTICAL updates must still
    diverge the shard root: the roots are position-mixed digest-tree
    roots, not a raw XOR fold of row digests (whose twin per-row
    deltas would cancel and silently skip the repair)."""
    uni = small_universe()
    n = 32
    a, _ = _history_batches(n, uni)
    row = [Orswot() for _ in range(n)]
    for i in (20, 21):  # same shard, same extra dot AND member; member 9
        # appears in NO base history, so both rows take the IDENTICAL
        # digest delta (same new cell, same actor-7 clock bump)
        row[i].apply(Add(dot=Dot(7, 9), member=9))
    b = a.merge(OrswotBatch.from_scalar(row, uni))
    lay = mesh.choose_layout(n, 4, granule=8)  # rows 20,21 -> shard 2
    da = np.asarray(digest_mod.digest_of(a, uni), dtype=np.uint64)
    db = np.asarray(digest_mod.digest_of(b, uni), dtype=np.uint64)
    assert int((da != db).sum()) == 2
    # the raw XOR fold really would cancel here — the screw is live
    lo, hi = lay.ranges()[2]
    assert np.bitwise_xor.reduce(da[lo:hi]) == \
        np.bitwise_xor.reduce(db[lo:hi])
    assert mesh.diverged_shards(da, db, lay).tolist() == [2]
    # same roots the fleet snapshot manifest records per shard
    for s, (slo, shi) in enumerate(lay.ranges()):
        assert mesh.shard_roots(da, lay)[s] == \
            mesh_durable.shard_root_of(da[slo:shi])
    merged, stats = mesh.shard_subset_sync(a, b, lay, uni)
    assert stats.shards_synced == 1 and stats.objects == 2
    assert np.array_equal(
        np.asarray(digest_mod.digest_of(merged, uni), dtype=np.uint64),
        db)


def test_shard_subset_sync_converged_ships_nothing():
    uni = small_universe()
    a, _ = _history_batches(32, uni)
    lay = mesh.choose_layout(32, 4, granule=8)
    merged, stats = mesh.shard_subset_sync(a, a, lay, uni)
    assert stats.shards_synced == 0
    assert stats.shards_skipped == 4
    assert stats.objects == stats.descent_bytes == stats.delta_bytes == 0
    assert stats.object_ids.size == 0
    assert stats.root_bytes == 8 * 4  # the only bytes a converged pass pays


def test_cluster_node_shard_subset_sync_repairs_and_records_heat():
    """The ClusterNode wiring: both busy locks held, only the diverged
    shard pulled, repaired rows fed to the initiator's heat tracker —
    zero full-state frames by construction (no session ran)."""
    uni = small_universe()
    n = 40
    lay = mesh.choose_layout(n, 4, granule=16)
    diverged_ids = (17, 20)  # shard 1 of bounds (0,16,32,40,40)
    a, _ = _history_batches(n, uni)
    b = _with_extras(a, uni, n, diverged_ids)
    n0 = ClusterNode("n0", a, uni)
    n1 = ClusterNode("n1", b, uni)
    before = tracing.counters()
    stats = n0.sync_shard_subset(n1, lay)
    assert stats.shards_synced == 1 and set(stats.per_shard) == {1}
    control = np.asarray(digest_mod.digest_of(a.merge(b), uni),
                         dtype=np.uint64)
    with n0._lock:
        repaired = n0._batch
    assert np.array_equal(
        np.asarray(digest_mod.digest_of(repaired, uni), dtype=np.uint64),
        control)
    # repair heat landed on the initiator's tracker, at the right rows
    span = subtree_layout(n)[1]
    heat = np.asarray(n0.heat.heat_vector())
    hot = {i for i in diverged_ids}
    assert sum(heat[i // span] for i in hot) > 0
    deltas = tracing.counters_since(before)
    assert deltas.get("mesh.sync.rounds") == 1
    # no sync session ran: no full-state frames, no session counters
    assert not any(k.startswith("sync.full_state") for k in deltas)


# -- per-shard durability ----------------------------------------------------


def _digest(batch, uni):
    return np.asarray(digest_mod.digest_of(batch, uni), dtype=np.uint64)


def test_fleet_snapshot_roundtrip(tmp_path):
    uni = small_universe()
    n = 24
    lay = mesh.choose_layout(n, 4, granule=4)
    a, _ = _history_batches(n, uni)
    store = mesh_durable.MeshSnapshotStore(tmp_path, lay)
    before = tracing.counters()
    manifest = store.write_fleet(a, uni, node_id="n0", wal_seq=7)
    assert manifest["wal_seq"] == 7
    assert len(manifest["generations"]) == lay.shards
    restored, loaded = store.load_fleet(uni)
    assert loaded["node_id"] == "n0"
    assert np.array_equal(_digest(restored, uni), _digest(a, uni))
    deltas = tracing.counters_since(before)
    assert deltas.get("mesh.durable.snapshots") == 1
    assert deltas.get("mesh.durable.restores") == 1


def test_fleet_snapshot_kill9_before_manifest_restores_old_cut(tmp_path):
    """Simulated kill -9 between the per-shard writes and the manifest
    rename: the manifest still points at generation-1 everywhere, so
    the restore is the CONSISTENT old cut — never a torn mix."""
    uni = small_universe()
    n = 24
    lay = mesh.choose_layout(n, 4, granule=4)
    a, _ = _history_batches(n, uni)
    store = mesh_durable.MeshSnapshotStore(tmp_path, lay)
    store.write_fleet(a, uni, node_id="n0")
    # the crash: every shard store advances a generation, the manifest
    # write never happens (write_fleet's order is shards-then-manifest)
    newer = _with_extras(a, uni, n, (2, 9, 21))
    for s, (lo, hi) in enumerate(lay.ranges()):
        part = jax.tree_util.tree_map(lambda x: x[lo:hi], newer)
        store.store(s).write(part, uni, node_id="n0")
    restored, manifest = store.load_fleet(uni)
    assert np.array_equal(_digest(restored, uni), _digest(a, uni))
    # ...and a rejoin from a live peer ships ONLY the diverged shards'
    # rows, no full-state frames (the snapshot restore + subset-sync
    # recovery path)
    merged, stats = mesh.shard_subset_sync(restored, newer, lay, uni)
    assert np.array_equal(_digest(merged, uni), _digest(newer, uni))
    assert 0 < stats.shards_synced < lay.shards
    assert stats.delta_bytes > 0


def test_fleet_restore_rejections_are_typed_and_counted(tmp_path):
    uni = small_universe()
    n = 16
    lay = mesh.choose_layout(n, 4, granule=4)
    a, _ = _history_batches(n, uni)

    # manifest_missing: a fresh directory is "nothing to restore"
    empty = mesh_durable.MeshSnapshotStore(tmp_path / "empty", lay)
    assert empty.latest_manifest() is None
    before = tracing.counters()
    with pytest.raises(DurabilityError):
        empty.load_fleet(uni)
    assert tracing.counters_since(before).get(
        "mesh.durable.rejected.manifest_missing") == 1

    store = mesh_durable.MeshSnapshotStore(tmp_path / "fleet", lay)
    store.write_fleet(a, uni, node_id="n0")

    # root_mismatch: tamper a recorded root, keep the CRC honest
    manifest = store.read_manifest()
    manifest["roots"][0] ^= 0xDEAD
    del manifest["crc"]
    manifest["crc"] = mesh_durable._manifest_crc(manifest)
    with open(store.manifest_path, "w") as f:
        json.dump(manifest, f)
    before = tracing.counters()
    with pytest.raises(CheckpointFormatError):
        store.load_fleet(uni)
    assert tracing.counters_since(before).get(
        "mesh.durable.rejected.root_mismatch") == 1

    # manifest_corrupt: torn write (CRC mismatch)
    store.write_fleet(a, uni, node_id="n0")
    raw = open(store.manifest_path).read()
    with open(store.manifest_path, "w") as f:
        f.write(raw[: len(raw) // 2])
    before = tracing.counters()
    with pytest.raises(CheckpointFormatError):
        store.read_manifest()
    assert tracing.counters_since(before).get(
        "mesh.durable.rejected.manifest_corrupt") == 1

    # layout_mismatch: same directory, different shard map
    store.write_fleet(a, uni, node_id="n0")
    other = mesh_durable.MeshSnapshotStore(
        tmp_path / "fleet", mesh.choose_layout(n, 2, granule=4))
    before = tracing.counters()
    with pytest.raises(CheckpointFormatError):
        other.load_fleet(uni)
    assert tracing.counters_since(before).get(
        "mesh.durable.rejected.layout_mismatch") == 1

    # shard_missing: a shard directory vanished out from under the
    # manifest
    import shutil

    shutil.rmtree(os.path.join(store.dirpath, "shard-01"))
    fresh = mesh_durable.MeshSnapshotStore(tmp_path / "fleet", lay)
    before = tracing.counters()
    with pytest.raises(CheckpointFormatError):
        fresh.load_fleet(uni)
    assert tracing.counters_since(before).get(
        "mesh.durable.rejected.shard_missing") == 1


# -- gauges ------------------------------------------------------------------


def test_publish_gauges_rows_the_placement_surface():
    uni = small_universe()
    a, _ = _history_batches(32, uni)
    sa = mesh.ShardedBatch.shard(a, uni, shards=4, granule=8)
    span = subtree_layout(32)[1]
    heat = np.ones(-(-32 // span), dtype=np.float64)
    reg = obs_metrics.MetricsRegistry()
    sa.publish_gauges(registry=reg, heat_vector=heat, span=span)
    gauges = reg.snapshot()["gauges"]
    assert gauges["mesh.layout.shards"] == 4
    assert gauges["mesh.layout.granule"] == 8
    for s, (lo, hi) in enumerate(sa.layout.ranges()):
        assert gauges[f"mesh.shard.{s}.objects"] == hi - lo
        assert f"mesh.shard.{s}.load" in gauges
    # measured loads cover the whole fleet's heat
    loads = mesh.shard_loads(sa.layout, heat, span)
    assert float(loads.sum()) == pytest.approx(float(heat.sum()))
