"""Fleet observatory tests — CRDT-merged telemetry, trace propagation.

The acceptance bar (ISSUE 6): a 5-node gossip fleet under 20% injected
frame loss converges AND yields a merged fleet snapshot in which every
fleet counter equals the sum of the per-node counters (despite the
duplicated snapshot delivery a lossy ARQ + gossip echo produce), and
both peers' flight-recorder events for one sync session carry the same
hello-negotiated trace ID.  Everything else here pins the pieces: the
snapshot lattice's ACI contract (seeded property sweep — the suite
must run on boxes without hypothesis), the frame codec's loud
rejections, the per-kind merge semantics, the ``/fleet`` surface under
concurrent gossip, the ring-overflow ``dropped`` gauge, and the
collective all-gather path.
"""

import itertools
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from crdt_tpu.batch import OrswotBatch
from crdt_tpu.cluster import (
    ClusterNode,
    FaultPlan,
    FaultyTransport,
    GossipScheduler,
    Membership,
    ResilientTransport,
    RetryPolicy,
    queue_pair,
)
from crdt_tpu.config import CrdtConfig
from crdt_tpu.error import SyncProtocolError
from crdt_tpu.obs import convergence as obs_convergence
from crdt_tpu.obs import events as obs_events
from crdt_tpu.obs import export as obs_export
from crdt_tpu.obs import fleet as obs_fleet
from crdt_tpu.obs import metrics as obs_metrics
from crdt_tpu.obs import namespace as obs_namespace
from crdt_tpu.scalar.orswot import Orswot
from crdt_tpu.sync.session import SyncSession, sync_pair
from crdt_tpu.utils import tracing
from crdt_tpu.utils.interning import Universe

pytestmark = pytest.mark.obs

FAST = RetryPolicy(send_deadline_s=3.0, recv_deadline_s=3.0,
                   ack_timeout_s=0.05, max_backoff_s=0.3,
                   retry_budget=400)


def _uni(**kw):
    cfg = dict(num_actors=8, member_capacity=16, deferred_capacity=4,
               counter_bits=32)
    cfg.update(kw)
    return Universe.identity(CrdtConfig(**cfg))


def _orswot_fleet(n, seed, actor=1, extra_on=()):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        s = Orswot()
        for _ in range(rng.randint(1, 5)):
            s.apply(s.add(int(rng.randint(0, 50)),
                          s.value().derive_add_ctx(0)))
        out.append(s)
    for i in extra_on:
        s = out[i]
        s.apply(s.add(900 + actor, s.value().derive_add_ctx(actor)))
    return out


# ---- the lattice: ACI property sweep ---------------------------------------


def _random_snapshot(rng: np.random.RandomState) -> obs_fleet.FleetSnapshot:
    """A structurally valid random snapshot: a few nodes from a shared
    pool (so merges collide on node ids), random counters/gauges/
    histograms with random capture stamps, a random event tail."""
    names = ["sync.sessions", "cluster.rounds", "wire.sync.delta.bytes",
             "sync.errors"]
    gnames = ["sync.peer.a.divergence", "cluster.peers.alive",
              "obs.fleet.nodes"]
    hnames = ["sync.digest_exchange", "cluster.round"]
    slices = {}
    for node in rng.choice(["n0", "n1", "n2", "n3"],
                           size=rng.randint(1, 4), replace=False):
        ts = float(rng.randint(0, 50))
        seq = int(rng.randint(1, 50))
        counters = {
            nm: int(rng.randint(0, 1000))
            for nm in rng.choice(names, size=rng.randint(1, len(names) + 1),
                                 replace=False)
        }
        gauges = {
            nm: [float(rng.randint(0, 50)), int(rng.randint(1, 50)),
                 float(rng.randint(0, 100))]
            for nm in rng.choice(gnames, size=rng.randint(0, len(gnames) + 1),
                                 replace=False)
        }
        hists = {
            nm: [float(rng.randint(0, 50)), int(rng.randint(1, 50)),
                 {"count": int(rng.randint(1, 20)),
                  "sum": float(rng.randint(0, 100)),
                  "min": 0.5, "max": 8.0,
                  "buckets": {str(int(e)): int(rng.randint(1, 9))
                              for e in rng.choice([0, 1, 2, 3],
                                                  size=rng.randint(1, 4),
                                                  replace=False)}}]
            for nm in rng.choice(hnames, size=rng.randint(0, len(hnames) + 1),
                                 replace=False)
        }
        events = [
            {"seq": int(s), "ts": float(s), "wall": float(s),
             "kind": "sync.phase", "fields": {"phase": "digest"}}
            for s in sorted(rng.choice(200, size=rng.randint(0, 6),
                                       replace=False))
        ]
        slices[str(node)] = {
            "ts": ts, "seq": seq,
            "counters": counters, "gauges": gauges, "histograms": hists,
            "convergence": [ts, seq, {"peer": {"divergence":
                                               int(rng.randint(0, 9))}}],
            "events_dropped": int(rng.randint(0, 9)),
            "events": events,
        }
    return obs_fleet.FleetSnapshot(slices)


def test_merge_is_commutative_associative_idempotent():
    """The ACI contract, property-swept with a seeded generator (this
    suite must run where hypothesis is absent): for random snapshots
    a, b, c — a∨b == b∨a, (a∨b)∨c == a∨(b∨c), a∨a == a, and
    re-delivering a constituent into the merge is a no-op (the
    duplicated-snapshot-delivery property the gossip transport needs)."""
    rng = np.random.RandomState(7)
    for _ in range(80):
        a, b, c = (_random_snapshot(rng) for _ in range(3))
        ab = a.merge(b)
        assert ab == b.merge(a), "merge is not commutative"
        assert ab.merge(c) == a.merge(b.merge(c)), "merge is not associative"
        assert a.merge(a) == a, "merge is not idempotent"
        # re-delivery of a constituent (a's own snapshot echoed back
        # by a peer, an ARQ retransmit) changes nothing
        assert ab.merge(a) == ab, "re-delivered snapshot was not a no-op"
        assert ab.merge(b) == ab, "re-delivered snapshot was not a no-op"


def test_fleet_counter_is_sum_of_per_node_g_counters():
    """Per-kind semantics: counters per-node max (G-Counter), summed
    fleet-wide; gauges LWW by capture stamp; histograms bucket-wise."""
    a = obs_fleet.FleetSnapshot({
        "n0": {"ts": 1.0, "seq": 1,
               "counters": {"sync.sessions": 10},
               "gauges": {"cluster.peers.alive": [1.0, 1, 3.0]},
               "histograms": {"cluster.round": [1.0, 1, {
                   "count": 2, "sum": 3.0, "min": 1.0, "max": 2.0,
                   "buckets": {"1": 2}}]},
               "convergence": [1.0, 1, {}], "events_dropped": 0,
               "events": []},
    })
    # a NEWER capture of n0 (counter grew, gauge moved) + a second node
    b = obs_fleet.FleetSnapshot({
        "n0": {"ts": 2.0, "seq": 2,
               "counters": {"sync.sessions": 15},
               "gauges": {"cluster.peers.alive": [2.0, 2, 4.0]},
               "histograms": {"cluster.round": [2.0, 2, {
                   "count": 5, "sum": 9.0, "min": 1.0, "max": 4.0,
                   "buckets": {"1": 2, "2": 3}}]},
               "convergence": [2.0, 2, {}], "events_dropped": 1,
               "events": []},
        "n1": {"ts": 1.5, "seq": 1,
               "counters": {"sync.sessions": 7},
               "gauges": {"cluster.peers.alive": [1.5, 1, 2.0]},
               "histograms": {"cluster.round": [1.5, 1, {
                   "count": 1, "sum": 0.5, "min": 0.5, "max": 0.5,
                   "buckets": {"0": 1}}]},
               "convergence": [1.5, 1, {}], "events_dropped": 0,
               "events": []},
    })
    merged = a.merge(b)
    # counter: n0 contributes its LATEST value once (15, not 10+15),
    # fleet = sum over nodes — and a re-delivery of `a` changes nothing
    assert merged.fleet_counters()["sync.sessions"] == 15 + 7
    assert merged.merge(a).fleet_counters()["sync.sessions"] == 22
    assert merged.counters_by_node("sync.sessions") == {"n0": 15, "n1": 7}
    # gauge: LWW by capture stamp fleet-wide (n0's ts=2.0 capture wins)
    assert merged.fleet_gauges()["cluster.peers.alive"] == 4.0
    # histogram: per-node LWW (n0's newer capture), bucket-wise summed
    # across nodes
    h = merged.fleet_histograms()["cluster.round"]
    assert h["count"] == 5 + 1 and h["buckets"] == {"1": 2, "2": 3, "0": 1}
    assert h["min"] == 0.5 and h["max"] == 4.0


# ---- the frame codec -------------------------------------------------------


def test_snapshot_frame_roundtrip():
    reg = obs_metrics.MetricsRegistry()
    reg.counter_inc("sync.sessions", 3)
    reg.gauge_set("cluster.peers.alive", 2.0)
    reg.observe("cluster.round", 0.25)
    snap = obs_fleet.capture_slice(
        "node-a", registry=reg,
        tracker=obs_convergence.ConvergenceTracker(registry=reg),
        recorder=obs_events.FlightRecorder(capacity=8),
    )
    frame = obs_fleet.encode_snapshot(snap)
    assert obs_fleet.decode_snapshot(frame) == snap


@pytest.mark.parametrize(
    "mutate", ["truncate", "version", "type", "crc", "payload"]
)
def test_snapshot_frame_rejections_are_loud(mutate):
    """Every malformed fleet frame is a SyncProtocolError plus a
    reason-tagged rejection counter — never a misparse, never a crash
    in the JSON layer."""
    snap = obs_fleet.FleetSnapshot(
        {"n0": {"ts": 1.0, "seq": 1, "counters": {"sync.sessions": 1},
                "gauges": {}, "histograms": {},
                "convergence": [1.0, 1, {}], "events_dropped": 0,
                "events": []}}
    )
    frame = bytearray(obs_fleet.encode_snapshot(snap))
    if mutate == "truncate":
        frame = frame[:7]
    elif mutate == "version":
        frame[0] ^= 0x01
    elif mutate == "type":
        frame[1] = 0x7F
    elif mutate == "crc":
        frame[-1] ^= 0x40
    elif mutate == "payload":
        # valid envelope around non-object JSON
        import struct
        import zlib

        payload = b"[1, 2, 3]"
        frame = bytearray(struct.pack(
            "<BBIQ", obs_fleet.FLEET_PROTOCOL_VERSION,
            obs_fleet.FRAME_FLEET_SNAPSHOT, zlib.crc32(payload),
            len(payload)) + payload)
    before = tracing.counters()
    with pytest.raises(SyncProtocolError):
        obs_fleet.decode_snapshot(bytes(frame))
    deltas = tracing.counters_since(before)
    assert any(k.startswith("obs.fleet.frames.rejected.") for k in deltas), (
        f"rejection left no reason counter: {deltas}"
    )


def test_mixed_versions_fail_loudly():
    snap = obs_fleet.FleetSnapshot({})
    frame = bytearray(obs_fleet.encode_snapshot(snap))
    frame[0] = obs_fleet.FLEET_PROTOCOL_VERSION + 1
    with pytest.raises(SyncProtocolError, match="version mismatch"):
        obs_fleet.decode_snapshot(bytes(frame))


def test_bad_frame_does_not_touch_observatory_state():
    obs = obs_fleet.FleetObservatory(
        "iso", registry=obs_metrics.MetricsRegistry(),
        tracker=obs_convergence.ConvergenceTracker(),
        recorder=obs_events.FlightRecorder(capacity=8),
    )
    obs.capture()
    before = obs.merged(refresh=False)
    with pytest.raises(SyncProtocolError):
        obs.merge_frame(b"garbage")
    assert obs.merged(refresh=False) == before


# ---- trace propagation -----------------------------------------------------


def test_sync_session_peers_share_one_trace_id():
    """THE trace acceptance pin: one session's two halves mint distinct
    session IDs but adopt the SAME hello-negotiated trace ID, and every
    flight-recorder event either peer wrote for that session carries
    it."""
    uni = _uni()
    a = OrswotBatch.from_scalar(_orswot_fleet(24, seed=3, actor=1,
                                              extra_on=[1]), uni)
    b = OrswotBatch.from_scalar(_orswot_fleet(24, seed=3, actor=2,
                                              extra_on=[2]), uni)
    sa, sb = SyncSession(a, uni, peer="b"), SyncSession(b, uni, peer="a")
    ra, rb = sync_pair(sa, sb)
    assert ra.converged and rb.converged
    assert ra.trace_id is not None
    assert ra.trace_id == rb.trace_id == sa.trace_id == sb.trace_id
    # the shared ID is one of the two proposals (the lexicographic min)
    assert ra.trace_id == min(sa.session_id, sb.session_id)
    for session in (sa, sb):
        evs = obs_events.recorder().snapshot(session=session.session_id)
        assert evs, f"no events for {session.session_id}"
        stamped = [e for e in evs if "fields" in e]
        assert stamped and all(
            e["fields"].get("trace") == ra.trace_id for e in stamped
        ), f"events missing the shared trace: {stamped}"


def test_stitch_trace_interleaves_both_peers():
    uni = _uni()
    a = OrswotBatch.from_scalar(_orswot_fleet(16, seed=5, actor=1,
                                              extra_on=[0]), uni)
    b = OrswotBatch.from_scalar(_orswot_fleet(16, seed=5, actor=2), uni)
    oa = obs_fleet.FleetObservatory("peer-a")
    ob = obs_fleet.FleetObservatory("peer-b")
    sa = SyncSession(a, uni, peer="peer-b", observatory=oa)
    sb = SyncSession(b, uni, peer="peer-a", observatory=ob)
    ra, _rb = sync_pair(sa, sb)
    merged = oa.merged()
    timeline = obs_fleet.stitch_trace(merged, ra.trace_id)
    assert timeline, "stitcher found no events for the trace"
    sessions = {e.get("session") for e in timeline if "session" in e}
    # both halves of the session appear in one ordered timeline
    assert {sa.session_id, sb.session_id} <= sessions
    walls = [e["wall_ts"] for e in timeline]
    assert walls == sorted(walls)
    # duration math uses the monotonic stamp (skew-immune), which every
    # event carries NEXT TO the wall stamp — and per-process mono
    # deltas are non-negative in recording order
    assert all("mono_ts" in e for e in timeline)


# ---- the 5-node lossy-gossip acceptance run --------------------------------


def _gossip_fleet_with_observatories(n_nodes, n_objects, *, loss):
    uni = _uni(num_actors=max(8, n_nodes + 2))
    nodes = []
    for i in range(n_nodes):
        extra = [(3 * i + k) % n_objects for k in range(3)]
        batch = OrswotBatch.from_scalar(
            _orswot_fleet(n_objects, seed=41, actor=i + 1, extra_on=extra),
            uni)
        nodes.append(ClusterNode(
            f"n{i}", batch, uni, busy_timeout_s=5.0,
            observatory=obs_fleet.FleetObservatory(f"n{i}"),
        ))

    seeds = itertools.count(5000)

    def make_dialer(i):
        def dial(peer):
            j = int(peer.peer_id[1:])
            s = next(seeds)
            ta, tb = queue_pair(default_timeout=10.0)
            fa = FaultyTransport(ta, FaultPlan(seed=s, drop=loss),
                                 name=f"n{i}->n{j}")
            fb = FaultyTransport(tb, FaultPlan(seed=s + 1, drop=loss),
                                 name=f"n{j}->n{i}")
            ra = ResilientTransport(fa, FAST, name=f"n{i}->n{j}", seed=s + 2)
            rb = ResilientTransport(fb, FAST, name=f"n{j}->n{i}", seed=s + 3)

            def serve():
                try:
                    nodes[j].accept(rb, peer_id=f"n{i}")
                except Exception:
                    pass
                finally:
                    rb.close()

            threading.Thread(target=serve, daemon=True).start()
            return ra
        return dial

    scheds = []
    for i in range(n_nodes):
        m = Membership(suspect_after=2, dead_after=5)
        for j in range(n_nodes):
            if j != i:
                m.add(f"n{j}")
        scheds.append(GossipScheduler(
            nodes[i], m, make_dialer(i), fanout=2,
            session_timeout_s=60.0, seed=i,
        ))
    return nodes, scheds


def test_acceptance_five_node_fleet_snapshot_under_loss():
    """ISSUE 6 acceptance: 5 nodes gossiping under 20% frame loss on
    every link (duplicates/retransmits included) converge AND any one
    node's merged fleet snapshot (a) spans all 5 nodes — slices spread
    on the gossip itself, no scraper — (b) holds fleet counters equal
    to the sum of the per-node counters despite every snapshot having
    been delivered many times, and (c) stitches one sync session's
    cross-peer timeline from the shared trace ID."""
    nodes, scheds = _gossip_fleet_with_observatories(5, 32, loss=0.20)
    deadline = time.monotonic() + 240.0
    converged = False
    for _ in range(16):
        for sched in scheds:
            sched.run_round()
        digests = [n.digest() for n in nodes]
        if all(np.array_equal(digests[0], d) for d in digests[1:]):
            converged = True
            break
        assert time.monotonic() < deadline, "fleet failed to converge"
    assert converged, "5-node fleet did not converge under 20% loss"

    # every node's slice reached node 0 on the gossip piggyback alone
    merged = nodes[0].observatory.merged()
    assert merged.nodes() == ["n0", "n1", "n2", "n3", "n4"]

    # G-Counter identity: every fleet counter is the sum of per-node
    # values — duplicated snapshot delivery (ARQ retransmits, gossip
    # echoes, this node's own slice bounced back) must not double-count
    fleet_counters = merged.fleet_counters()
    assert fleet_counters, "merged snapshot carries no counters"
    for name, total in fleet_counters.items():
        per_node = merged.counters_by_node(name)
        assert total == sum(per_node.values()), (
            f"fleet counter {name}: {total} != sum {per_node}"
        )
    # and the fleet saw real gossip traffic
    assert fleet_counters.get("sync.sessions", 0) > 0
    assert fleet_counters.get("cluster.rounds", 0) > 0

    # the last converged session's trace stitches BOTH peers' events
    trace = next(
        (n.last_report.trace_id for n in reversed(nodes)
         if n.last_report is not None), None,
    )
    assert trace, "no converged session left a trace ID"
    evs = [e for e in obs_events.recorder().snapshot()
           if e.get("fields", {}).get("trace") == trace]
    sessions = {e["session"] for e in evs if "session" in e}
    assert len(sessions) == 2, (
        f"expected both halves of the session under trace {trace}, "
        f"got sessions {sessions}"
    )

    # round-health gauges landed (the /fleet "is the fleet converging"
    # surface): attempted peers recorded, divergence settled to 0
    gauges = obs_metrics.registry().snapshot()["gauges"]
    assert "cluster.gossip.attempted" in gauges
    assert gauges.get("cluster.gossip.fleet_divergence_max") == 0.0
    assert gauges.get("cluster.gossip.eta_rounds") == 0.0


def test_fleet_endpoint_concurrent_with_gossip_round():
    """Thread-safety: ``/fleet`` scraped (Prom text + JSON + trace
    query) while gossip rounds are actively merging snapshots — every
    response parses, no 500s, no torn snapshots."""
    nodes, scheds = _gossip_fleet_with_observatories(3, 16, loss=0.0)
    srv = obs_export.start_metrics_server(
        port=0, observatory=nodes[0].observatory
    )
    errors: list = []
    stop = threading.Event()

    def scraper():
        while not stop.is_set():
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/fleet", timeout=10
                ) as r:
                    assert r.status == 200
                    text = r.read().decode()
                    assert "crdt_tpu_fleet_nodes" in text
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/fleet?format=json",
                    timeout=10,
                ) as r:
                    doc = json.loads(r.read().decode())
                    assert set(doc["slices"]) == set(doc["fleet"] and
                                                     doc["nodes"])
                    # every slice internally consistent under the scrape
                    for name, total in doc["fleet"]["counters"].items():
                        by_node = sum(
                            sl["counters"].get(name, 0)
                            for sl in doc["slices"].values()
                        )
                        assert total == by_node
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)
                return

    t = threading.Thread(target=scraper, daemon=True)
    t.start()
    try:
        for _ in range(4):
            for sched in scheds:
                sched.run_round()
    finally:
        stop.set()
        t.join(timeout=30)
        srv.stop()
    assert not errors, f"concurrent /fleet scrape failed: {errors[0]!r}"


def test_fleet_endpoint_trace_query():
    uni = _uni()
    a = OrswotBatch.from_scalar(_orswot_fleet(12, seed=9, actor=1,
                                              extra_on=[1]), uni)
    b = OrswotBatch.from_scalar(_orswot_fleet(12, seed=9, actor=2), uni)
    oa = obs_fleet.FleetObservatory("qa")
    ob = obs_fleet.FleetObservatory("qb")
    sa = SyncSession(a, uni, peer="qb", observatory=oa)
    sb = SyncSession(b, uni, peer="qa", observatory=ob)
    ra, _ = sync_pair(sa, sb)
    srv = obs_export.start_metrics_server(port=0, observatory=oa)
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/fleet?trace={ra.trace_id}",
            timeout=10,
        ) as r:
            doc = json.loads(r.read().decode())
    finally:
        srv.stop()
    assert doc["trace"] == ra.trace_id
    assert doc["timeline"], "trace query returned an empty timeline"
    assert all(
        e.get("fields", {}).get("trace") == ra.trace_id
        or e.get("session") == ra.trace_id
        for e in doc["timeline"]
    )


# ---- the dropped-count gauge (satellite) -----------------------------------


def test_ring_overflow_surfaces_as_dropped_gauge():
    """Overflow the (global) flight-recorder ring, then scrape: the
    ``crdt_tpu_obs_events_dropped`` gauge must report the eviction
    count — refreshed at scrape time, since ``dropped`` is a live
    property, not a write-through metric."""
    rec = obs_events.recorder()
    base_dropped = rec.dropped
    for i in range(rec.capacity + 64):
        rec.record("obs.overflow.probe", n=i)
    assert rec.dropped >= base_dropped + 64
    text = obs_export.prometheus_text()
    line = next(
        (ln for ln in text.splitlines()
         if ln.startswith("crdt_tpu_obs_events_dropped ")), None,
    )
    assert line is not None, "dropped gauge missing from /metrics"
    assert float(line.split()[1]) >= base_dropped + 64
    # and the name is manifest-documented (the namespace satellite)
    assert obs_namespace.match("obs.events.dropped", "gauge") is not None


def test_private_registry_scrape_leaves_dropped_gauge_alone():
    """The PR 3 review discipline: scraping a PRIVATE registry must not
    write global recorder state into it (or touch the global one)."""
    reg = obs_metrics.MetricsRegistry()
    reg.counter_inc("sync.sessions")
    text = obs_export.prometheus_text(reg)
    assert "crdt_tpu_obs_events_dropped" not in text


# ---- namespace coverage ----------------------------------------------------


def test_new_names_are_manifest_documented():
    for name, kind in [
        ("obs.fleet.merges", "counter"),
        ("obs.fleet.frames.decoded", "counter"),
        ("obs.fleet.frames.rejected.crc_mismatch", "counter"),
        ("obs.fleet.nodes", "gauge"),
        ("obs.fleet.exchange", "histogram"),
        ("obs.fleet.snapshot_bytes", "histogram"),
        ("obs.events.dropped", "gauge"),
        ("cluster.gossip.attempted", "gauge"),
        ("cluster.gossip.fleet_divergence_max", "gauge"),
        ("cluster.gossip.eta_rounds", "gauge"),
        ("wire.sync.hello.bytes", "counter"),
        ("wire.sync.fleet.bytes", "counter"),
        ("sync.frame.hello.decoded", "counter"),
    ]:
        assert obs_namespace.match(name, kind) is not None, (
            f"{name} ({kind}) is not manifest-documented"
        )


# ---- the collective all-gather path ----------------------------------------


def test_allgather_fleet_snapshots_single_process():
    """The mesh path (scraper-free aggregation for pjit deployments):
    on a single-process harness it degrades to a local capture+merge —
    the multi-process fan-in is the same merge over process_allgather
    frames."""
    from crdt_tpu.parallel.collective import allgather_fleet_snapshots

    obs = obs_fleet.FleetObservatory(
        "mesh-0", registry=obs_metrics.MetricsRegistry(),
        tracker=obs_convergence.ConvergenceTracker(),
        recorder=obs_events.FlightRecorder(capacity=8),
    )
    snap = allgather_fleet_snapshots(obs)
    assert "mesh-0" in snap.nodes()
    # and a frame from another "process" folds in via the same codec
    other = obs_fleet.FleetObservatory(
        "mesh-1", registry=obs_metrics.MetricsRegistry(),
        tracker=obs_convergence.ConvergenceTracker(),
        recorder=obs_events.FlightRecorder(capacity=8),
    )
    obs.merge_frame(other.encode())
    assert obs.merged(refresh=False).nodes() == ["mesh-0", "mesh-1"]
