"""crdtlint self-tests: the repo-wide gate, fixture contracts, and the
regression pins the acceptance criteria name.

Everything here is jax-free by construction (the lint's hard contract);
the repo-gate test additionally proves it in a subprocess, because this
pytest session itself imports jax via conftest.
"""

import json
import os
import subprocess
import sys

import pytest

from crdt_tpu.analysis import Baseline, ParsedFile, load_files, run_lint
from crdt_tpu.analysis.core import default_targets, repo_root
from crdt_tpu.obs import namespace

pytestmark = pytest.mark.analysis

REPO = repo_root()
FIXTURES = os.path.join(REPO, "tests", "analysis_fixtures")


def _lint_paths(paths):
    files, errors = load_files(paths, root=REPO)
    assert not errors, errors
    return run_lint(files)


# ---- the tier-1 gate: the shipped tree is clean, fast, and jax-free --------


def test_repo_lint_clean_fast_and_jax_free():
    """`python -m crdt_tpu.analysis` exits 0 on the shipped tree in
    <5 s without importing jax (the acceptance criterion, verbatim)."""
    probe = (
        # some environments preload jax via a site hook (see
        # test_import_hygiene) — only assert absence when the
        # interpreter started without it
        "import sys, json\n"
        "pre_jax = 'jax' in sys.modules\n"
        "pre_np = 'numpy' in sys.modules\n"
        "from crdt_tpu.analysis.__main__ import main\n"
        "rc = main(['--json'])\n"
        "assert pre_jax or 'jax' not in sys.modules, 'lint imported jax'\n"
        "assert pre_np or 'numpy' not in sys.modules, "
        "'lint imported numpy'\n"
        "sys.exit(rc)\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", probe], cwd=REPO, capture_output=True,
        text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout)
    assert out["ok"] is True
    assert out["files"] > 50  # the walk really covered the tree
    assert out["elapsed_s"] < 5.0, f"lint took {out['elapsed_s']}s (budget 5s)"


def test_shipped_baseline_is_empty_for_telemetry():
    """The shipped baseline parks nothing for the telemetry rules (and,
    as it happens, nothing at all — every finding was fixed)."""
    path = os.path.join(REPO, "crdt_tpu", "analysis", "baseline.json")
    with open(path) as fh:
        entries = json.load(fh)
    assert [e for e in entries
            if e["rule"].startswith("metric-")] == []


# ---- fixture suite: each rule family fires where pinned, twins stay clean --


def _findings_by_file(result):
    out = {}
    for f in result.findings:
        out.setdefault(os.path.basename(f.path), []).append(f)
    return out


@pytest.fixture(scope="module")
def fixture_result():
    paths = sorted(
        os.path.join(FIXTURES, p)
        for p in os.listdir(FIXTURES) if p.endswith(".py")
    )
    return _lint_paths(paths)


def test_fixture_bad_files_trigger(fixture_result):
    by_file = _findings_by_file(fixture_result)
    rules = {name: sorted({f.rule for f in fs})
             for name, fs in by_file.items()}
    assert rules["telemetry_bad.py"] == [
        "metric-namespace", "metric-type-collision"]
    assert rules["locks_bad.py"] == [
        "hold-and-block", "lock-discipline", "lock-order-cycle",
        "unlocked-rmw"]
    assert rules["tracer_bad.py"] == [
        "jit-dict-order", "jit-host-coercion", "pallas-int64"]
    assert rules["wire_bad.py"] == [
        "wire-bare-valueerror", "wire-missing-record",
        "wire-swallowed-except"]
    # the coercion rule saw all three sites (if + bool + float)
    coercions = [f for f in by_file["tracer_bad.py"]
                 if f.rule == "jit-host-coercion"]
    assert len(coercions) == 3
    # the order rule saw both deadlock shapes (a<->b cycle, re-acquire)
    cycles = [f for f in by_file["locks_bad.py"]
              if f.rule == "lock-order-cycle"]
    assert len(cycles) == 2
    # hold-and-block saw all three blocking families (fsync/send/sleep)
    blocked = [f for f in by_file["locks_bad.py"]
               if f.rule == "hold-and-block"]
    assert len(blocked) == 3


def test_fixture_ok_twins_are_suppressed_not_clean(fixture_result):
    by_file = _findings_by_file(fixture_result)
    for ok in ("telemetry_ok.py", "locks_ok.py", "tracer_ok.py",
               "wire_ok.py"):
        assert ok not in by_file, (
            f"{ok} produced live findings: {by_file.get(ok)}")
    # the pragmas suppressed real findings — the twins aren't just inert
    suppressed_files = {os.path.basename(f.path)
                        for f in fixture_result.suppressed}
    assert {"telemetry_ok.py", "locks_ok.py",
            "tracer_ok.py"} <= suppressed_files


def test_findings_carry_location_and_render(fixture_result):
    f = fixture_result.findings[0]
    assert f.line > 0 and f.path.startswith("tests/analysis_fixtures/")
    assert f.location() in f.render() and f.rule in f.render()


# ---- acceptance regressions: reintroduce each bug class, lint must fail ----


def test_regrow_cross_type_collision_fails_cli(tmp_path):
    """Reintroducing an executor.regrow-style cross-type metric name
    makes the CLI exit non-zero, naming the rule and file:line."""
    bad = tmp_path / "regressed.py"
    bad.write_text(
        "from crdt_tpu.utils import tracing\n"
        "def recover():\n"
        "    tracing.count('executor.regrow')\n"
        "    with tracing.span('executor.regrow'):\n"
        "        pass\n"
    )
    proc = subprocess.run(
        [sys.executable, "-m", "crdt_tpu.analysis", str(bad)],
        cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "metric-type-collision" in proc.stdout
    assert "regressed.py:4" in proc.stdout  # rule anchors the later site


def test_unlocked_write_to_guarded_attr_fails():
    src = (
        "import threading\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.n = 0\n"
        "    def locked(self):\n"
        "        with self._lock:\n"
        "            self.n = 1\n"
        "    def racy(self):\n"
        "        self.n = 2\n"
    )
    pf = ParsedFile("x", "crdt_tpu/obs/regressed.py", src)
    result = run_lint([pf])
    assert [f.rule for f in result.findings] == ["lock-discipline"]
    assert result.findings[0].line == 10


def test_bare_valueerror_in_decode_path_fails():
    src = (
        "def decode_frame(frame):\n"
        "    if not frame:\n"
        "        raise ValueError('empty')\n"
        "    return frame\n"
    )
    pf = ParsedFile("x", "crdt_tpu/sync/regressed.py", src)
    result = run_lint([pf])
    assert [f.rule for f in result.findings] == ["wire-bare-valueerror"]
    assert result.findings[0].line == 3


def test_converted_valueerror_is_sanctioned():
    src = (
        "from crdt_tpu.error import SyncProtocolError\n"
        "def decode_frame(frame):\n"
        "    try:\n"
        "        if not frame:\n"
        "            raise ValueError('empty')\n"
        "    except (TypeError, ValueError) as e:\n"
        "        raise SyncProtocolError(str(e)) from None\n"
        "    return frame\n"
    )
    pf = ParsedFile("x", "crdt_tpu/sync/regressed.py", src)
    assert run_lint([pf]).findings == []


# ---- baseline mechanics -----------------------------------------------------


def test_baseline_parks_finding_and_reports_stale():
    src = (
        "def decode_frame(frame):\n"
        "    raise ValueError('nope')\n"
    )
    pf = ParsedFile("x", "crdt_tpu/sync/regressed.py", src)
    live = run_lint([pf]).findings
    assert len(live) == 1
    baseline = Baseline([
        {"rule": live[0].rule, "path": live[0].path,
         "message": live[0].message, "justification": "test park"},
        {"rule": "metric-namespace", "path": "crdt_tpu/gone.py",
         "message": "whatever", "justification": "stale entry"},
    ])
    result = run_lint([pf], baseline=baseline)
    assert result.findings == [] and len(result.baselined) == 1
    assert [e["path"] for e in result.stale_baseline] == ["crdt_tpu/gone.py"]
    # prefix matching: a trailing * survives message drift
    baseline2 = Baseline([
        {"rule": live[0].rule, "path": live[0].path,
         "message": live[0].message[:20] + "*",
         "justification": "prefix park"},
    ])
    assert run_lint([pf], baseline=baseline2).findings == []


def test_baseline_rejects_malformed_entries():
    with pytest.raises(ValueError, match="justification"):
        Baseline([{"rule": "r", "path": "p", "message": "m"}])


# ---- the namespace manifest -------------------------------------------------


def test_manifest_is_well_formed():
    seen = set()
    for spec in namespace.NAMESPACE:
        assert spec.kind in namespace.KINDS
        assert spec.pattern not in seen, f"duplicate row {spec.pattern}"
        seen.add(spec.pattern)
        assert spec.doc


def test_manifest_match_and_prometheus_names():
    assert namespace.match("wire.sync.delta.bytes", "counter") is not None
    assert namespace.match("wire.sync.delta.bytes", "gauge") is None
    assert namespace.match("no.such.metric") is None
    assert namespace.prometheus_name("wire.sync.delta.bytes", "counter") \
        == "crdt_tpu_wire_sync_delta_bytes_total"
    assert namespace.prometheus_name("sync.peer.a-1.staleness_s", "gauge") \
        == "crdt_tpu_sync_peer_a_1_staleness_s"


def test_every_declared_metric_is_documented():
    """Direct form of the namespace gate: every name the tree declares
    matches a manifest row of the same type (the lint enforces this;
    this test keeps the property visible even if rule scoping drifts)."""
    from crdt_tpu.analysis.telemetry import extract_decls

    files, _ = load_files(default_targets(), root=REPO)
    for d in extract_decls(files):
        specs = [s for s in namespace.NAMESPACE
                 if namespace_overlap(d.pattern, s.pattern, s.kind, d.kind)]
        assert specs, f"undocumented metric {d.pattern!r} at {d.path}:{d.line}"


def namespace_overlap(decl, pattern, spec_kind, decl_kind):
    from crdt_tpu.analysis.core import patterns_overlap

    return spec_kind == decl_kind and patterns_overlap(decl, pattern)
