"""Causal-GC tests — fleet low-watermark clocks, compaction kernels,
plane re-packing, policy wiring (crdt_tpu.gc).

THE acceptance property lives here at tier-1 speed: for seeded random
op/merge histories, GC-compacting any replica at the fleet
low-watermark and then merging it with any peer (compacted or not)
yields digest vectors byte-identical to the never-compacted fleet —
compaction reclaims representation (tombstones the next plunge would
settle anyway, slot padding, witnessed op-buffer rows), never state.
The long-soak flip of the PR 9 capacity oracle (bounded live slots
under churn with GC on) is ``tests/test_gc_soak.py`` behind ``slow``.
"""

import threading

import numpy as np
import pytest

from crdt_tpu.batch import OrswotBatch
from crdt_tpu.batch.occupancy import occupancy_of
from crdt_tpu.cluster import ClusterNode, GossipScheduler, Membership, queue_pair
from crdt_tpu.config import CrdtConfig
from crdt_tpu.gc import FleetWatermark, GcEngine, GcPolicy
from crdt_tpu.gc.compact import (
    compact_gap_buffer,
    compact_oplog,
    settle_orswot,
    truncate_orswot,
    witnessed_ops_mask,
)
from crdt_tpu.gc.repack import repack_orswot, shrink_plan
from crdt_tpu.obs import convergence as obs_convergence
from crdt_tpu.obs import events as obs_events
from crdt_tpu.obs import metrics as obs_metrics
from crdt_tpu.obs import namespace
from crdt_tpu.oplog import OpApplier, OpBatch, OpLog
from crdt_tpu.scalar.ctx import RmCtx
from crdt_tpu.scalar.orswot import Orswot
from crdt_tpu.scalar.vclock import VClock
from crdt_tpu.sync import digest as digest_mod
from crdt_tpu.utils.interning import Universe

pytestmark = pytest.mark.gc


def _uni(**kw):
    cfg = dict(num_actors=8, member_capacity=8, deferred_capacity=4,
               counter_bits=32)
    cfg.update(kw)
    return Universe.identity(CrdtConfig(**cfg))


def _digest(batch) -> np.ndarray:
    return np.asarray(digest_mod.digest_of(batch), dtype=np.uint64)


def _plunged(batch):
    """Canonical form: the defer-plunger self-merge every join ends
    with (`test/orswot.rs:61-62`)."""
    return batch.merge(batch)


def _join(a, b):
    """The production pair join: equalize capacities, merge with
    elastic regrowth on overflow, end with the plunger — exactly what
    ``JoinExecutor`` does when anti-entropy folds two replicas (a
    shrink-to-fit batch legitimately regrows when a union outgrows its
    rung; GC and the executor's ladder are inverses, not rivals)."""
    from crdt_tpu.parallel import JoinExecutor

    return JoinExecutor(strategy="sequential").join_all([a, b])


def _plane_nbytes(batch):
    return sum(x.nbytes for x in (batch.clock, batch.ids, batch.dots,
                                  batch.d_ids, batch.d_clocks))


def _random_replicas(seed: int, n_objects: int = 12, n_replicas: int = 3):
    """Seeded random op/merge histories: a shared base history, then
    per-replica adds/removes (some removes witnessed by ANOTHER
    replica's clock, so deferred rows appear), then a partial gossip
    pass — the divergence shape real anti-entropy sees."""
    rng = np.random.RandomState(seed)
    uni = _uni()
    fleets = []
    for r in range(n_replicas):
        row = []
        for i in range(n_objects):
            s = Orswot()
            # shared prefix: same (seeded per-object) ops on actor 0
            for j in range((i % 3) + 1):
                s.apply(s.add((i * 7 + j) % 11, s.value().derive_add_ctx(0)))
            row.append(s)
        fleets.append(row)
    # divergent per-replica ops
    for r in range(n_replicas):
        for _ in range(n_objects * 2):
            i = int(rng.randint(n_objects))
            s = fleets[r][i]
            if rng.rand() < 0.7:
                s.apply(s.add(int(rng.randint(20, 40)),
                              s.value().derive_add_ctx(r + 1)))
            else:
                read = s.value()
                if read.val:
                    m = sorted(read.val)[int(rng.randint(len(read.val)))]
                    s.apply(s.remove(m, s.contains(m).derive_rm_ctx()))
    # cross-replica removes: witness clocks from a PEER's copy, so the
    # local apply defers (tombstone rows) until anti-entropy catches up
    for r in range(n_replicas):
        for _ in range(n_objects // 2):
            i = int(rng.randint(n_objects))
            peer = fleets[(r + 1) % n_replicas][i]
            target = sorted(peer.value().val)
            if not target:
                continue
            m = target[int(rng.randint(len(target)))]
            ctx = RmCtx(clock=peer.value().add_clock.clone())
            fleets[r][i].apply(fleets[r][i].remove(m, ctx))
    batches = [OrswotBatch.from_scalar(row, uni) for row in fleets]
    return uni, batches


def _fleet_watermark_of(batches) -> np.ndarray:
    vvs = [np.asarray(digest_mod.version_vector(b), np.uint64)
           for b in batches]
    wm = vvs[0]
    for v in vvs[1:]:
        wm = np.minimum(wm, v)
    return wm


def _gc(batch, uni, *, tracker=None, peers=None, reg=None):
    eng = GcEngine(
        GcPolicy(interval_rounds=1, member_floor=None, deferred_floor=None),
        tracker=tracker or obs_convergence.ConvergenceTracker(
            reg or obs_metrics.MetricsRegistry()),
        registry=reg or obs_metrics.MetricsRegistry(),
    )
    out, report = eng.collect(batch, universe=uni, peers=peers)
    return out, report


# ---- THE acceptance property ------------------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_gc_then_merge_matches_never_gcd_fleet(seed):
    """Compact any replica at the fleet low-watermark, merge with any
    peer (compacted or not): digest vectors byte-identical to the
    never-compacted fleet's same merge (both sides plunged — the
    canonical form every join ends in)."""
    uni, batches = _random_replicas(seed)
    # over-provision one replica as if a burst had regrown it: the GC
    # must also walk the capacity back down without touching state
    batches[0] = batches[0].with_capacity(32, 16)

    for victim in range(len(batches)):
        gcd, report = _gc(batches[victim], uni)
        assert report.watermark is not None
        for peer_idx in range(len(batches)):
            if peer_idx == victim:
                continue
            for peer in (batches[peer_idx], _gc(batches[peer_idx], uni)[0]):
                want = _digest(_join(batches[victim], peer))
                got = _digest(_join(gcd, peer))
                assert np.array_equal(got, want), (seed, victim, peer_idx)
        # and the compacted replica alone, plunged, is the replica
        got_alone = _digest(_plunged(gcd))
        want_alone = _digest(_plunged(batches[victim]))
        assert np.array_equal(got_alone, want_alone), (seed, victim)


def test_gc_fleet_join_matches_never_gcd_join():
    """A whole-fleet join where one replica was GC-compacted first
    converges to the never-compacted join's digest vector."""
    uni, batches = _random_replicas(seed=7)
    want = _digest(OrswotBatch.join_fleet(batches))
    gcd, _ = _gc(batches[1], uni)
    mixed = [batches[0], gcd.with_capacity(batches[0].member_capacity,
                                           batches[0].deferred_capacity),
             batches[2]]
    assert np.array_equal(_digest(OrswotBatch.join_fleet(mixed)), want)


# ---- the watermark ----------------------------------------------------------


def _tracker_with(reg, vvs, ts=None):
    trk = obs_convergence.ConvergenceTracker(reg)
    for peer, vv in vvs.items():
        trk.observe_version_vector(peer, vv)
    return trk


def test_watermark_is_elementwise_min_including_local():
    reg = obs_metrics.MetricsRegistry()
    trk = _tracker_with(reg, {"p1": [5, 2, 0], "p2": [3, 9, 1]})
    wm = FleetWatermark(trk, registry=reg)
    report = wm.compute([4, 4, 4])
    assert report.clock.tolist() == [3, 2, 0]
    assert report.peers == 2 and not report.frozen
    g = reg.snapshot()["gauges"]
    assert g["gc.watermark.peers"] == 2
    assert g["gc.watermark.max_counter"] == 3
    assert g["gc.watermark.lag"] == 4  # actor 2: local 4 vs wm 0


def test_watermark_aligns_mixed_widths_by_implied_zero():
    reg = obs_metrics.MetricsRegistry()
    trk = _tracker_with(reg, {"narrow": [7]})
    report = FleetWatermark(trk, registry=reg).compute([5, 6, 7])
    # the narrow peer has implied-0 counters for actors it never saw
    assert report.clock.tolist() == [5, 0, 0]


def test_watermark_staleness_freezes_and_quarantine_excludes():
    t = [0.0]
    reg = obs_metrics.MetricsRegistry()
    trk = obs_convergence.ConvergenceTracker(reg)
    trk.observe_version_vector("p1", [2, 2], at=0.0)
    wm = FleetWatermark(trk, stale_after_s=10.0, quarantine_s=100.0,
                        registry=reg, clock=lambda: t[0])
    # within stale_after: fresh contribution
    t[0] = 5.0
    r = wm.compute([9, 9])
    assert r.clock.tolist() == [2, 2] and r.stale == 0

    # past stale_after: still contributes (the freeze), counted stale
    t[0] = 50.0
    r = wm.compute([9, 9])
    assert r.clock.tolist() == [2, 2]
    assert r.stale == 1 and r.frozen

    # past quarantine: excluded — the watermark advances to local
    t[0] = 200.0
    r = wm.compute([9, 9])
    assert r.clock.tolist() == [9, 9]
    assert r.excluded == 1 and r.peers == 0


def test_watermark_unheard_roster_peer_pins_zero_until_quarantined():
    t = [0.0]
    reg = obs_metrics.MetricsRegistry()
    trk = obs_convergence.ConvergenceTracker(reg)
    trk.observe_version_vector("p1", [4, 4], at=0.0)
    wm = FleetWatermark(trk, stale_after_s=10.0, quarantine_s=60.0,
                        registry=reg, clock=lambda: t[0])
    r = wm.compute([9, 9], peers=["p1", "ghost"])
    assert r.clock.tolist() == [0, 0]  # ghost: nothing is known-stable
    assert r.unheard == 1 and r.frozen
    # the ghost quarantines off its first sighting
    t[0] = 120.0
    r = wm.compute([9, 9], peers=["p1", "ghost"])
    assert r.unheard == 0 and r.excluded >= 1
    # p1 is ALSO past quarantine by now (observed at t=0)
    assert r.clock.tolist() == [9, 9]


def test_session_digest_exchange_feeds_version_vector_cache():
    from crdt_tpu.sync.session import SyncSession, sync_pair

    uni = _uni()
    s = Orswot()
    s.apply(s.add(1, s.value().derive_add_ctx(0)))
    batch = OrswotBatch.from_scalar([s], uni)
    obs_convergence.tracker().reset()
    a = SyncSession(batch, uni, peer="gc-vv-b")
    b = SyncSession(batch, uni, peer="gc-vv-a")
    sync_pair(a, b)
    vvs = obs_convergence.tracker().version_vectors()
    assert "gc-vv-b" in vvs and "gc-vv-a" in vvs
    vv, ts = vvs["gc-vv-b"]
    assert vv[0] == 1 and ts is not None


# ---- compaction kernels -----------------------------------------------------


def _batch_with_dominated_tombstones(uni):
    """Dense planes carrying deferred rows the object clock ALREADY
    dominates — the shape a replica holds right after ingesting state
    that settled elsewhere (scalar states can't express it: their
    apply_deferred runs eagerly)."""
    s = Orswot()
    for m in (1, 2, 3):
        s.apply(s.add(m, s.value().derive_add_ctx(0)))
    base = OrswotBatch.from_scalar([s], uni)
    # deferred row: remove member 2 witnessed by (actor 0, counter 2)
    # — dominated by the set clock (actor 0 at 3)
    (co, ca, cv), (do, dm, da, dv), _q, _h = base.to_coo()
    return OrswotBatch.from_coo(
        1, uni, clock_coords=(co, ca, cv), dot_coords=(do, dm, da, dv),
        deferred_members=([0], [0], [2]),
        deferred_coords=([0], [0], [0], [2]),
    ), s


def test_settle_clears_dominated_tombstones_like_the_plunger():
    uni = _uni()
    batch, scalar = _batch_with_dominated_tombstones(uni)
    assert occupancy_of(batch).tombstones == 1
    settled, stats = settle_orswot(batch)
    assert stats["tombstones_cleared"] == 1
    assert occupancy_of(settled).tombstones == 0
    # the replayed remove dropped member 2, exactly as the scalar
    # plunger (merge with an empty set) would
    ref = scalar.clone()
    ref.apply_remove(2, VClock({0: 2}))
    ref.merge(Orswot())
    want = _digest(OrswotBatch.from_scalar([ref], uni))
    assert np.array_equal(_digest(settled), want)
    # settle == plunger: the unsettled twin's self-merge agrees too
    assert np.array_equal(_digest(_plunged(batch)), want)


def test_settle_keeps_future_tombstones_parked():
    uni = _uni()
    s = Orswot()
    s.apply(s.add(1, s.value().derive_add_ctx(0)))
    future = VClock()
    future.witness(5, 99)
    s.apply(s.remove(1, RmCtx(clock=future)))
    batch = OrswotBatch.from_scalar([s], uni)
    settled, stats = settle_orswot(batch)
    assert stats["tombstones_cleared"] == 0
    assert occupancy_of(settled).tombstones == 1  # still causally ahead


def test_truncate_matches_scalar_reference():
    """The batched reset truncate == scalar `Causal::truncate` per
    object (`orswot.rs:159-172`), including deferred replay."""
    uni, batches = _random_replicas(seed=11, n_replicas=2)
    scal = batches[0].to_scalar(uni)
    wm = np.asarray([2, 1, 0, 0, 0, 0, 0, 0], np.uint64)
    clock = VClock({0: 2, 1: 1})
    got = truncate_orswot(batches[0], wm)
    for s in scal:
        s.truncate(clock)
    want = OrswotBatch.from_scalar(scal, uni)
    assert np.array_equal(_digest(got), _digest(want))


# ---- re-packing -------------------------------------------------------------


def test_shrink_plan_hysteresis_and_floors():
    uni = _uni()
    s = Orswot()
    for m in range(3):
        s.apply(s.add(m, s.value().derive_add_ctx(0)))
    occ = occupancy_of(OrswotBatch.from_scalar([s], uni)
                       .with_capacity(64, 16))
    # live_max 3 → fitted rung 4, but floors win
    assert shrink_plan(occ, member_floor=8, deferred_floor=4) == (8, 4)
    # hysteresis: at 0.25, one rung down (4/8 = 0.5) is not enough
    # headroom — only a >=4x over-provisioned axis shrinks
    occ_tight = occupancy_of(OrswotBatch.from_scalar(
        [s], uni).with_capacity(8, 4))
    assert shrink_plan(occ_tight, member_floor=4, deferred_floor=4,
                       hysteresis=0.25) is None
    # at the default 0.5 the same fit IS allowed
    assert shrink_plan(occ_tight, member_floor=4, deferred_floor=4,
                       hysteresis=0.5) == (4, 4)
    with pytest.raises(ValueError, match="hysteresis"):
        shrink_plan(occ, member_floor=8, deferred_floor=4, hysteresis=0.0)


def test_repack_reclaims_bytes_and_stamps_shrink_event():
    uni = _uni()
    s = Orswot()
    for m in range(3):
        s.apply(s.add(m, s.value().derive_add_ctx(0)))
    big = OrswotBatch.from_scalar([s], uni).with_capacity(64, 16)
    obs_events.recorder().clear()
    reg = obs_metrics.MetricsRegistry()
    shrunk, reclaimed = repack_orswot(big, 8, 4, registry=reg)
    assert (shrunk.member_capacity, shrunk.deferred_capacity) == (8, 4)
    assert reclaimed == _plane_nbytes(big) - _plane_nbytes(shrunk) > 0
    assert np.array_equal(_digest(shrunk), _digest(big))
    snap = reg.snapshot()["counters"]
    assert snap["gc.shrinks"] == 1
    assert snap["gc.reclaimed_bytes"] == reclaimed
    events = obs_events.recorder().snapshot(kind="executor.shrink")
    assert len(events) == 1
    f = events[0]["fields"]
    assert (f["member_capacity_before"], f["member_capacity"]) == (64, 8)
    assert (f["deferred_capacity_before"], f["deferred_capacity"]) == (16, 4)
    assert f["reclaimed_bytes"] == reclaimed


def test_repack_refuses_to_drop_live_rows_or_grow():
    uni = _uni()
    s = Orswot()
    for m in range(6):
        s.apply(s.add(m, s.value().derive_add_ctx(0)))
    batch = OrswotBatch.from_scalar([s], uni)
    with pytest.raises(ValueError, match="live rows"):
        repack_orswot(batch, 4, 4, registry=obs_metrics.MetricsRegistry())
    with pytest.raises(ValueError, match="cannot grow"):
        repack_orswot(batch, 16, 4, registry=obs_metrics.MetricsRegistry())


def test_delta_applier_takes_jnp_route_for_nonconfig_capacities():
    """The warm native delta buffers are config-shaped; a repacked or
    regrown batch must fall through to the shape-polymorphic route
    instead of handing mismatched planes to out= (the latent bug the
    GC shrink exposes)."""
    from crdt_tpu.sync.delta import OrswotDeltaApplier

    uni = _uni()
    s = Orswot()
    s.apply(s.add(1, s.value().derive_add_ctx(0)))
    peer = Orswot()
    peer.apply(peer.add(2, peer.value().derive_add_ctx(1)))
    batch = OrswotBatch.from_scalar([s], uni).with_capacity(16, 8)
    from crdt_tpu import to_binary

    merged = OrswotDeltaApplier(uni).apply(
        batch, np.asarray([0]), [to_binary(peer)])
    assert merged.member_capacity == 16  # capacity preserved
    want = s.clone()
    want.merge(peer)
    assert np.array_equal(
        _digest(merged),
        _digest(OrswotBatch.from_scalar([want], uni).with_capacity(16, 8)))


# ---- op-buffer compaction ---------------------------------------------------


def _ops(kind, obj, actor, counter, member):
    return OpBatch(kind=np.asarray(kind, np.uint8),
                   obj=np.asarray(obj, np.int64),
                   actor=np.asarray(actor, np.int32),
                   counter=np.asarray(counter, np.uint64),
                   member=np.asarray(member, np.int32))


def test_witnessed_mask_drops_only_dominated_dotted_ops():
    clock = np.zeros((2, 4), np.uint64)
    clock[0, 0] = 3
    ops = _ops([0, 0, 1, 0], [0, 0, 0, 1], [0, 0, 0, 0], [2, 5, 0, 1],
               [7, 8, 7, 9])
    # no watermark: local witness criterion only
    mask = witnessed_ops_mask(ops, clock)
    assert mask.tolist() == [True, False, False, False]  # rm never drops
    # watermark gate: actor 0 only stable to counter 1 → nothing drops
    mask = witnessed_ops_mask(ops, clock, np.asarray([1, 0, 0, 0],
                                                     np.uint64))
    assert mask.tolist() == [False, False, False, False]


def test_compact_oplog_and_gap_buffer_reclaim_witnessed_dots():
    uni = _uni()
    log = OpLog(uni, capacity=64)
    clock = np.zeros((2, 8), np.uint64)
    clock[0, 0] = 4
    log.append(_ops([0, 0], [0, 0], [0, 0], [2, 9], [5, 6]))
    res = compact_oplog(log, clock, np.asarray([8] * 8, np.uint64))
    assert res["ops_dropped"] == 1 and res["bytes_reclaimed"] > 0
    assert len(log) == 1
    survivor = log.pending()
    assert survivor.counter.tolist() == [9]
    # high-watermark survives compaction (it records dots SEEN)
    assert int(log.watermark.max()) == 9

    applier = OpApplier(uni)
    batch = OrswotBatch.zeros(2, uni)
    gapped = _ops([0], [0], [0], [9], [7])
    applier.apply_ops(batch, gapped)
    assert len(applier.parked) == 1
    # the gap closed through state sync: the dot is witnessed now
    closed = np.zeros((2, 8), np.uint64)
    closed[0, 0] = 9
    res = compact_gap_buffer(applier, closed,
                             np.asarray([9] * 8, np.uint64))
    assert res["ops_dropped"] == 1
    assert len(applier.parked) == 0


# ---- the engine + cluster wiring -------------------------------------------


def test_engine_due_cadence_and_capacity_trigger():
    from crdt_tpu.obs.capacity import CapacityTracker

    reg = obs_metrics.MetricsRegistry()
    trk = CapacityTracker(reg, max_capacity=4)
    eng = GcEngine(GcPolicy(interval_rounds=3, utilization_trigger="warn"),
                   tracker=obs_convergence.ConvergenceTracker(reg),
                   capacity_tracker=trk, registry=reg)
    assert eng.due(3) and eng.due(6)
    assert not eng.due(1)
    # 3/4 of the ceiling → warn → the trigger fires off-cadence
    uni = _uni()
    s = Orswot()
    for m in range(3):
        s.apply(s.add(m, s.value().derive_add_ctx(0)))
    trk.sample(OrswotBatch.from_scalar([s], uni))
    assert eng.due(1)


def test_engine_publishes_gc_counters_and_every_name_is_manifested():
    uni = _uni()
    batch, _ = _batch_with_dominated_tombstones(uni)
    batch = batch.with_capacity(64, 16)
    reg = obs_metrics.MetricsRegistry()
    trk = _tracker_with(reg, {"p1": [9] * 8})
    log = OpLog(uni, capacity=64)
    log.append(_ops([0], [0], [0], [3], [1]))  # witnessed: clock[0,0]=3
    eng = GcEngine(GcPolicy(interval_rounds=1), tracker=trk, registry=reg)
    out, report = eng.collect(batch, universe=uni, oplog=log,
                              applier=OpApplier(uni), peers=["p1"])
    assert report.tombstones_cleared == 1
    assert report.shrunk and report.member_capacity == (64, 8)
    assert report.oplog_ops_dropped == 1
    assert report.reclaimed_bytes > 0
    assert eng.total_reclaimed_bytes == report.reclaimed_bytes
    snap = reg.snapshot()
    c = snap["counters"]
    assert c["gc.runs"] == 1 and c["gc.shrinks"] == 1
    assert c["gc.tombstones_cleared"] == 1
    assert c["gc.oplog_ops_dropped"] == 1
    for name in list(c) + list(snap["gauges"]):
        kind = "counter" if name in c else "gauge"
        assert namespace.match(name, kind) is not None, name


def test_cluster_round_runs_gc_between_sessions():
    """A 3-node fleet with over-provisioned planes: the scheduler's
    round-end hook settles + shrinks on the engine's cadence, the
    fleet still converges byte-identically, and GC never runs while a
    session holds the node (the busy lock is the pin)."""
    uni = _uni(num_actors=8, member_capacity=8, deferred_capacity=4)
    s = Orswot()
    for m in range(3):
        s.apply(s.add(m, s.value().derive_add_ctx(0)))
    base = OrswotBatch.from_scalar([s] * 4, uni).with_capacity(32, 16)

    regs = [obs_metrics.MetricsRegistry() for _ in range(3)]
    nodes = []
    for i in range(3):
        trk = obs_convergence.ConvergenceTracker(regs[i])
        eng = GcEngine(GcPolicy(interval_rounds=1), tracker=trk,
                       registry=regs[i])
        # sessions feed the process-global tracker; give the engine
        # the global one so watermarks see real peer vectors
        eng.watermark._tracker = obs_convergence.tracker()
        nodes.append(ClusterNode(f"g{i}", base, uni, busy_timeout_s=5.0,
                                 gc=eng))

    def make_dialer(i):
        def dial(peer):
            j = int(peer.peer_id[1:])
            ta, tb = queue_pair(default_timeout=10.0)

            def serve():
                try:
                    nodes[j].accept(tb, peer_id=f"g{i}")
                except Exception:
                    pass
                finally:
                    tb.close()

            threading.Thread(target=serve, daemon=True).start()
            return ta
        return dial

    scheds = []
    for i in range(3):
        m = Membership(suspect_after=3, dead_after=6)
        for j in range(3):
            if j != i:
                m.add(f"g{j}")
        scheds.append(GossipScheduler(nodes[i], m, make_dialer(i),
                                      fanout=2, session_timeout_s=30.0,
                                      seed=i))
    obs_convergence.tracker().reset()
    for _ in range(3):
        for sched in scheds:
            sched.run_round()
    digests = [n.digest() for n in nodes]
    assert all(np.array_equal(digests[0], d) for d in digests[1:])
    for n in nodes:
        report = n.last_gc_report
        assert report is not None
        assert n.batch.member_capacity == 8  # shrank back to the config rung
        assert n.gc.runs >= 1


def test_collect_garbage_skips_while_session_holds_busy_lock():
    uni = _uni()
    batch = OrswotBatch.zeros(1, uni)
    eng = GcEngine(GcPolicy(interval_rounds=1),
                   tracker=obs_convergence.ConvergenceTracker(
                       obs_metrics.MetricsRegistry()),
                   registry=obs_metrics.MetricsRegistry())
    node = ClusterNode("busy", batch, uni, gc=eng)
    assert node._busy.acquire(blocking=False)
    try:
        assert node.collect_garbage() is None  # skipped, not queued
    finally:
        node._busy.release()
    assert node.collect_garbage() is not None


def test_gc_skips_batch_types_without_compaction_kernels():
    from crdt_tpu.batch.gcounter_batch import GCounterBatch

    uni = _uni()
    import jax.numpy as jnp

    eng = GcEngine(GcPolicy(interval_rounds=1),
                   tracker=obs_convergence.ConvergenceTracker(
                       obs_metrics.MetricsRegistry()),
                   registry=obs_metrics.MetricsRegistry())
    batch = GCounterBatch(clocks=jnp.zeros((2, 8), jnp.uint32))
    out, report = eng.collect(batch, universe=uni)
    assert out is batch
    assert report.skipped and "GCounterBatch" in report.skipped
