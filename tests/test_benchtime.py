"""The shared chained timer (crdt_tpu.utils.benchtime).

Every capture path (bench.py, profile_stages, tpu_experiments,
tpu_validate) times through this helper; what matters for correctness is
that the chain really executes its iterations data-dependently and that
consts arrive as jit parameters (the closure-inlining failure mode is a
remote-compile rejection — reports/TPU_LATENCY.md item 4 — which cannot
be reproduced on CPU, so here we pin the calling convention instead).
"""
import jax.numpy as jnp
import numpy as np

from crdt_tpu.utils.benchtime import chain_timer, sync_overhead


def test_chain_executes_every_iteration():
    y = jnp.arange(256, dtype=jnp.uint32)
    t, out = chain_timer(
        lambda c, yy: (jnp.maximum(c[0], yy) + 1,),
        (jnp.zeros(256, jnp.uint32),),
        iters=10,
        consts=(y,),
        sync_overhead_s=0.0,
    )
    assert t > 0
    # 10 data-dependent iterations: the running max gains +1 each step
    assert int(np.asarray(out[0]).max()) == 255 + 10


def test_consts_are_positional_varargs():
    a = jnp.full((8,), 3, jnp.uint32)
    b = jnp.full((8,), 5, jnp.uint32)
    _, out = chain_timer(
        lambda c, x, y: (c[0] + x + y,),
        (jnp.zeros(8, jnp.uint32),),
        iters=4,
        consts=(a, b),
        sync_overhead_s=0.0,
    )
    assert np.asarray(out[0]).tolist() == [32] * 8  # 4 * (3 + 5)


def test_sync_overhead_nonnegative():
    s = sync_overhead(reps=2)
    assert 0 <= s < 60
