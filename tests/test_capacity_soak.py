"""Long-soak capacity acceptance — the future causal-GC oracle.

ISSUE 9's acceptance bar, and the measurement half of ROADMAP's causal-
GC item: a 3-node gossip fleet under sustained write churn, where at
every epoch

* the reported plane bytes EXACTLY equal the live device buffers'
  nbytes on every node (the gauge is the footprint, not an estimate),
* the growth gauges are monotone (live slots never "un-fill" under
  add-dominated churn — until a causal-GC truncate exists, planes only
  grow, which is precisely what this observatory exists to prove), and
* the writer node's time-to-overflow ETA is finite and shrinking
  (steady growth against a fixed regrow ceiling must read as a
  countdown, not noise).

When batched ``Causal::truncate`` lands, this test is its acceptance
oracle flipped: the same fleet with GC on must show bounded live slots
and a growing ETA.
"""

import threading

import numpy as np
import pytest

from crdt_tpu.batch import OrswotBatch
from crdt_tpu.cluster import ClusterNode, GossipScheduler, Membership, queue_pair
from crdt_tpu.config import CrdtConfig
from crdt_tpu.obs import metrics as obs_metrics
from crdt_tpu.obs.capacity import CapacityTracker
from crdt_tpu.oplog import OpLog
from crdt_tpu.oplog.records import derive_rm_ctx
from crdt_tpu.scalar.orswot import Orswot
from crdt_tpu.sync import digest as digest_mod
from crdt_tpu.utils.interning import Universe
from crdt_tpu.utils.workload import WorkloadGen

pytestmark = [pytest.mark.obs, pytest.mark.slow]

N_OBJECTS = 8
MEMBER_CAP = 64
EPOCHS = 8
NEW_MEMBERS_PER_EPOCH = 4
EPOCH_DT = 10.0  # fake-clock seconds per epoch (deterministic rates)


def _plane_nbytes(batch):
    return sum(x.nbytes for x in (batch.clock, batch.ids, batch.dots,
                                  batch.d_ids, batch.d_clocks))


def _fleet(clock):
    uni = Universe.identity(CrdtConfig(
        num_actors=8, member_capacity=MEMBER_CAP, deferred_capacity=4,
        counter_bits=32))
    states = []
    for _ in range(N_OBJECTS):
        s = Orswot()
        for m in range(4):
            s.apply(s.add(m, s.value().derive_add_ctx(0)))
        states.append(s)
    base = OrswotBatch.from_scalar(states, uni)

    regs = [obs_metrics.MetricsRegistry() for _ in range(3)]
    trackers = [
        CapacityTracker(regs[i], max_capacity=MEMBER_CAP, alpha=1.0,
                        clock=clock)
        for i in range(3)
    ]
    nodes = [
        ClusterNode(f"n{i}", base, uni, busy_timeout_s=5.0,
                    oplog=OpLog(uni, capacity=1 << 16),
                    capacity_tracker=trackers[i])
        for i in range(3)
    ]

    def make_dialer(i):
        def dial(peer):
            j = int(peer.peer_id[1:])
            ta, tb = queue_pair(default_timeout=10.0)

            def serve():
                try:
                    nodes[j].accept(tb, peer_id=f"n{i}")
                except Exception:
                    pass
                finally:
                    tb.close()

            threading.Thread(target=serve, daemon=True).start()
            return ta
        return dial

    scheds = []
    for i in range(3):
        m = Membership(suspect_after=3, dead_after=6)
        for j in range(3):
            if j != i:
                m.add(f"n{j}")
        scheds.append(GossipScheduler(
            nodes[i], m, make_dialer(i), fanout=2,
            session_timeout_s=30.0, seed=i,
        ))
    return uni, nodes, scheds, regs


def test_soak_plane_bytes_exact_growth_monotone_eta_shrinking():
    t = [0.0]
    uni, nodes, scheds, regs = _fleet(clock=lambda: t[0])

    def gauges(i):
        return regs[i].snapshot()["gauges"]

    live_hist = {i: [] for i in range(3)}
    live_max_hist = []
    eta_hist = []
    next_member = 100
    # user-shaped background traffic (ROADMAP carried item: the soak
    # drivers run against Zipf/burst keys, not uniform sprays): each
    # epoch re-adds BASE members on skew-drawn objects — dots advance
    # on hot keys through the same op path, while slot occupancy stays
    # untouched, so the monotone-growth / exact-bytes / deterministic-
    # ETA assertions below keep holding to the digit
    workload = WorkloadGen(N_OBJECTS, seed=77, zipf_s=1.1, burst_len=2)
    for epoch in range(EPOCHS):
        t[0] += EPOCH_DT
        bg = workload.draw(8)
        nodes[epoch % 3].submit_writes(
            bg, (bg % 4).astype(np.int32), actor=1 + epoch % 3)
        # churn: node 0 mints NEW members onto object 0 (plane growth),
        # plus a no-op remove of a never-added member riding the same
        # rounds (rm traffic through the op path without shrinking
        # planes — nothing un-fills a slot until causal GC exists)
        members = list(range(next_member, next_member
                             + NEW_MEMBERS_PER_EPOCH))
        next_member += NEW_MEMBERS_PER_EPOCH
        nodes[0].submit_writes([0] * len(members), members, actor=0)
        nodes[0].submit_ops(derive_rm_ctx(
            np.asarray(nodes[0].batch.clock, dtype=np.uint64),
            [1], [999_999]))
        for sched in scheds:
            sched.run_round()  # each round ends in a capacity sample

        for i in range(3):
            g = gauges(i)
            # THE acceptance identity: the gauge is the real footprint
            assert g["capacity.orswot.bytes"] \
                == _plane_nbytes(nodes[i].batch), (epoch, i)
            live_hist[i].append(g["capacity.orswot.live"])
        live_max_hist.append(gauges(0)["capacity.orswot.live_max"])
        if epoch >= 1:
            eta_hist.append(gauges(0)["capacity.orswot.eta_s"])

    # growth gauges monotone: planes only fill under add churn
    for i in range(3):
        assert live_hist[i] == sorted(live_hist[i]), live_hist[i]
    assert live_max_hist == sorted(live_max_hist)
    # the writer's busiest object grew every epoch
    assert live_max_hist[-1] >= live_max_hist[0] \
        + (EPOCHS - 1) * NEW_MEMBERS_PER_EPOCH

    # ETA finite and shrinking: steady growth against a fixed ceiling
    # reads as a countdown (rates are deterministic: fake clock, EWMA
    # alpha 1, constant members/epoch)
    assert all(e > 0 for e in eta_hist), eta_hist
    assert eta_hist == sorted(eta_hist, reverse=True), eta_hist
    assert gauges(0)["capacity.orswot.growth_rows_per_s"] \
        == pytest.approx(NEW_MEMBERS_PER_EPOCH / EPOCH_DT)

    # soak sanity: with writes stopped the fleet still converges, and
    # every node's capacity view agrees on the busiest object
    for _ in range(3):
        for sched in scheds:
            sched.run_round()
    digests = [np.asarray(digest_mod.digest_of(n.batch), dtype=np.uint64)
               for n in nodes]
    assert all((d == digests[0]).all() for d in digests[1:])
    t[0] += EPOCH_DT
    for node in nodes:
        node.sample_capacity()
    finals = [gauges(i)["capacity.orswot.live_max"] for i in range(3)]
    assert len(set(finals)) == 1, finals
