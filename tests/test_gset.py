"""GSet tests.

The reference's `test/gset.rs` is an empty stub; these cover the doctests in
`/root/reference/src/gset.rs:19-62` plus basic lattice properties.
"""

from hypothesis import given
from hypothesis import strategies as st

from crdt_tpu import GSet

elems = st.lists(st.integers(0, 255), max_size=20)


def test_doc_examples():
    a, b = GSet(), GSet()
    a.insert(1)
    b.insert(2)
    a.merge(b)
    assert a.contains(1)
    assert a.contains(2)


@given(elems)
def test_prop_merge_idempotent(xs):
    a = GSet(set(xs))
    snapshot = a.clone()
    a.merge(snapshot)
    assert a == snapshot


@given(elems, elems)
def test_prop_merge_commutative(xs, ys):
    a, b = GSet(set(xs)), GSet(set(ys))
    ab = a.clone()
    ab.merge(b)
    ba = b.clone()
    ba.merge(a)
    assert ab == ba


@given(elems, elems, elems)
def test_prop_merge_associative(xs, ys, zs):
    a, b, c = GSet(set(xs)), GSet(set(ys)), GSet(set(zs))
    left = a.clone()
    left.merge(b)
    left.merge(c)
    bc = b.clone()
    bc.merge(c)
    right = a.clone()
    right.merge(bc)
    assert left == right


def test_bitmap_widens_for_new_members():
    """The bitmap's member-universe bound grows like the other types'
    capacities: widen, insert a member past the old bound, merge with a
    narrower batch (auto-widened — union over missing columns is a
    no-op)."""
    import numpy as np

    from crdt_tpu.batch import GSetBatch
    from crdt_tpu.utils.interning import Universe

    uni = Universe()
    import pytest

    a = GSetBatch.from_scalar([GSet({"x"})], uni, member_capacity=2)
    assert a.member_capacity == 2 and a.deferred_capacity == 0
    with pytest.raises(ValueError, match="bitmap capacity"):
        a.insert(np.array([5]))
    grown = a.with_capacity(8)
    # intern filler members so the next id truly lands past the old bound
    while uni.members.intern(f"fill{len(uni.members)}") < 2:
        pass
    yid = uni.members.intern("y")
    assert yid >= 2  # past the original capacity-2 bitmap
    grown = grown.insert(np.array([yid]))
    merged = grown.merge(a)  # narrower side auto-widens
    assert merged.member_capacity == 8
    assert bool(merged.contains(np.array([yid]))[0])
    back = merged.to_scalar(uni)[0]
    assert back.contains("x") and back.contains("y")

    # the executor's uniform merge path accepts GSet fleets
    from crdt_tpu.parallel import JoinExecutor

    joined = JoinExecutor(strategy="sequential").join_all(
        [grown, a], plunger=False
    )
    assert joined.to_scalar(uni)[0] == back
    with pytest.raises(ValueError, match="cannot shrink"):
        grown.with_capacity(2)
