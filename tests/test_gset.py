"""GSet tests.

The reference's `test/gset.rs` is an empty stub; these cover the doctests in
`/root/reference/src/gset.rs:19-62` plus basic lattice properties.
"""

from hypothesis import given
from hypothesis import strategies as st

from crdt_tpu import GSet

elems = st.lists(st.integers(0, 255), max_size=20)


def test_doc_examples():
    a, b = GSet(), GSet()
    a.insert(1)
    b.insert(2)
    a.merge(b)
    assert a.contains(1)
    assert a.contains(2)


@given(elems)
def test_prop_merge_idempotent(xs):
    a = GSet(set(xs))
    snapshot = a.clone()
    a.merge(snapshot)
    assert a == snapshot


@given(elems, elems)
def test_prop_merge_commutative(xs, ys):
    a, b = GSet(set(xs)), GSet(set(ys))
    ab = a.clone()
    ab.merge(b)
    ba = b.clone()
    ba.merge(a)
    assert ab == ba


@given(elems, elems, elems)
def test_prop_merge_associative(xs, ys, zs):
    a, b, c = GSet(set(xs)), GSet(set(ys)), GSet(set(zs))
    left = a.clone()
    left.merge(b)
    left.merge(c)
    bc = b.clone()
    bc.merge(c)
    right = a.clone()
    right.merge(bc)
    assert left == right
