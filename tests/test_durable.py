"""Durable-replica tests: snapshot store, op-log WAL, crash recovery.

The acceptance contract (ISSUE 12): a write acknowledged by a durable
node survives kill -9 — restore from the newest good snapshot
generation (torn/truncated/version-skewed files rejected LOUDLY with a
fallback to the previous generation), verify the restored planes
digest-identical to the snapshot via the sync-tree root, replay the
WAL's complete frames through the causal-gap apply path, and rejoin
the fleet through normal delta sync — zero full-state frames shipped
just because a node restarted.
"""

import glob
import os
import struct
import threading

import numpy as np
import pytest

from crdt_tpu.batch import OrswotBatch
from crdt_tpu.cluster import (
    ClusterNode, CrashPlan, GossipScheduler, InjectedCrash, Membership,
    TornWriter, arm_crashes, disarm_crashes, queue_pair,
)
from crdt_tpu.config import CrdtConfig
from crdt_tpu.durable import (
    Durability, SnapshotStore, WalWriter, recover, replay_frames,
    split_frames,
)
from crdt_tpu.durable.snapshot import (
    FRAME_SNAPSHOT, SNAPSHOT_MAGIC, SNAPSHOT_VERSION, default_writer,
)
from crdt_tpu.error import CheckpointFormatError, CrdtError, DurabilityError
from crdt_tpu.obs import convergence as obs_convergence
from crdt_tpu.oplog import OpLog
from crdt_tpu.oplog.records import OpBatch
from crdt_tpu.scalar.orswot import Orswot
from crdt_tpu.sync import digest as digest_mod
from crdt_tpu.utils import tracing
from crdt_tpu.utils.interning import Universe

pytestmark = pytest.mark.durable


def _uni(num_actors=8):
    return Universe.identity(CrdtConfig(
        num_actors=num_actors, member_capacity=16, deferred_capacity=4,
        counter_bits=32))


def _fixture_batch(uni, n=8, seed=3):
    rng = np.random.RandomState(seed)
    states = []
    for i in range(n):
        s = Orswot()
        for _ in range(int(rng.randint(1, 4))):
            s.apply(s.add(int(rng.randint(0, 32)),
                          s.value().derive_add_ctx(int(rng.randint(0, 4)))))
        states.append(s)
    return OrswotBatch.from_scalar(states, uni)


def _digest(batch, uni):
    return np.asarray(digest_mod.digest_of(batch, uni), np.uint64)


def _ops(obj, member, counter=1, actor=0):
    obj = np.atleast_1d(np.asarray(obj))
    return OpBatch(
        kind=np.zeros(obj.shape[0], np.uint8), obj=obj,
        actor=np.full(obj.shape[0], actor, np.int32),
        counter=np.full(obj.shape[0], counter, np.uint64),
        member=np.atleast_1d(np.asarray(member)).astype(np.int32))


# ---- snapshot store --------------------------------------------------------


def test_snapshot_roundtrip_with_vv_watermark_parked(tmp_path):
    uni = _uni()
    batch = _fixture_batch(uni)
    store = SnapshotStore(tmp_path, retain=2)
    wm = np.arange(8, dtype=np.uint64)
    parked = _ops([0, 1], [7, 8], counter=50)
    snap = store.write(batch, uni, wal_seq=17, watermark=wm,
                       parked=parked, node_id="n0")
    assert snap.generation == 1
    loaded = store.load_latest()
    assert loaded.generation == 1
    assert loaded.wal_seq == 17
    assert loaded.node_id == "n0"
    np.testing.assert_array_equal(loaded.watermark, wm)
    np.testing.assert_array_equal(
        loaded.vv, digest_mod.version_vector(batch))
    assert len(loaded.parked) == 2
    assert list(loaded.parked.member) == [7, 8]
    np.testing.assert_array_equal(
        _digest(loaded.batch, loaded.universe), _digest(batch, uni))


def test_snapshot_generations_retained_and_pruned(tmp_path):
    uni = _uni()
    batch = _fixture_batch(uni)
    store = SnapshotStore(tmp_path, retain=2)
    for seq in (1, 2, 3, 4):
        store.write(batch, uni, wal_seq=seq)
    assert store.generations() == [3, 4]
    assert store.load_latest().wal_seq == 4


@pytest.mark.parametrize("corrupt", ["truncate", "crc", "version", "magic"])
def test_snapshot_rejects_torn_and_skewed_loudly(tmp_path, corrupt):
    uni = _uni()
    batch = _fixture_batch(uni)
    store = SnapshotStore(tmp_path, retain=2)
    store.write(batch, uni, wal_seq=1)
    path = store.path_of(1)
    data = bytearray(open(path, "rb").read())
    if corrupt == "truncate":
        data = data[: len(data) // 2]
    elif corrupt == "crc":
        data[-1] ^= 0xFF
    elif corrupt == "version":
        data[len(SNAPSHOT_MAGIC)] = SNAPSHOT_VERSION + 1
    else:
        data[:4] = b"XXXX"
    open(path, "wb").write(bytes(data))
    before = tracing.counters()
    with pytest.raises(CheckpointFormatError) as ei:
        store.load(1)
    # the taxonomy: a CrdtError that is also a ValueError (the seed
    # loader's historical contract)
    assert isinstance(ei.value, CrdtError) and isinstance(
        ei.value, ValueError)
    after = tracing.counters()
    rejected = {k: v for k, v in after.items()
                if k.startswith("durable.snapshot.rejected.")}
    assert sum(rejected.values()) > sum(
        v for k, v in before.items()
        if k.startswith("durable.snapshot.rejected."))


def test_snapshot_fallback_to_previous_generation(tmp_path):
    uni = _uni()
    batch1 = _fixture_batch(uni, seed=1)
    batch2 = _fixture_batch(uni, seed=2)
    store = SnapshotStore(tmp_path, retain=3)
    store.write(batch1, uni, wal_seq=1)
    store.write(batch2, uni, wal_seq=2)
    path = store.path_of(2)
    data = open(path, "rb").read()
    open(path, "wb").write(data[: len(data) - 7])  # torn newest
    snap = store.load_latest()
    assert snap.generation == 1
    np.testing.assert_array_equal(
        _digest(snap.batch, snap.universe), _digest(batch1, uni))


def test_snapshot_root_mismatch_rejected(tmp_path):
    """A snapshot whose payload decodes but whose planes are not
    digest-identical to the recorded tree root must reject — the
    rejoin self-check."""
    uni = _uni()
    batch = _fixture_batch(uni)
    store = SnapshotStore(tmp_path, retain=2)
    store.write(batch, uni, wal_seq=1)
    # forge: re-encode the payload with a flipped root but a VALID crc
    import zlib

    from crdt_tpu.durable import snapshot as snap_mod
    from crdt_tpu.utils import serde

    path = store.path_of(1)
    data = open(path, "rb").read()
    head = len(SNAPSHOT_MAGIC) + snap_mod._HEADER.size
    meta = serde.from_binary(data[head:])
    meta["root"] = int(meta["root"]) ^ 1
    payload = serde.to_binary(meta)
    forged = SNAPSHOT_MAGIC + snap_mod._HEADER.pack(
        SNAPSHOT_VERSION, FRAME_SNAPSHOT, zlib.crc32(payload),
        len(payload)) + payload
    open(path, "wb").write(forged)
    with pytest.raises(CheckpointFormatError, match="digest-identical"):
        store.load(1)


def test_all_generations_bad_raises_durability_error(tmp_path):
    uni = _uni()
    store = SnapshotStore(tmp_path, retain=3)
    store.write(_fixture_batch(uni), uni)
    for path in glob.glob(str(tmp_path / "*.crdtsnap")):
        open(path, "wb").write(b"not a snapshot")
    with pytest.raises(DurabilityError):
        store.load_latest()
    assert not isinstance(DurabilityError("x"), ValueError)


def test_empty_store_returns_none_and_ignores_tmp(tmp_path):
    store = SnapshotStore(tmp_path)
    assert store.load_latest() is None
    # a crashed mid-write checkpoint's temp file is not a generation
    open(os.path.join(tmp_path, "snap-0000000001.crdtsnap.tmp"),
         "wb").write(b"half")
    assert store.load_latest() is None
    assert store.generations() == []


def test_torn_writer_models_short_write(tmp_path):
    uni = _uni()
    batch = _fixture_batch(uni)
    writer = TornWriter(default_writer, at_write=2, keep_frac=0.4)
    store = SnapshotStore(tmp_path, retain=3, writer=writer)
    store.write(batch, uni, wal_seq=1)
    store.write(batch, uni, wal_seq=2)  # torn on disk
    assert writer.injected == 1
    snap = store.load_latest()
    assert snap.generation == 1  # fell back past the short write


# ---- WAL -------------------------------------------------------------------


def test_wal_append_replay_roundtrip(tmp_path):
    w = WalWriter(tmp_path, segment_bytes=64)
    seqs = [w.append(_ops([i], [i + 10], counter=i + 1)) for i in range(6)]
    assert seqs == list(range(6))
    w.close()
    frames = list(replay_frames(tmp_path))
    assert [s for s, _ in frames] == list(range(6))
    # bounded replay from a snapshot seq
    assert [s for s, _ in replay_frames(tmp_path, from_seq=4)] == [4, 5]
    # small segment_bytes forced a multi-segment layout
    assert len(glob.glob(str(tmp_path / "wal-*.log"))) > 1


def test_wal_torn_tail_stops_loudly_and_writer_resumes(tmp_path):
    w = WalWriter(tmp_path)
    for i in range(3):
        w.append(_ops([i], [i], counter=1 + i))
    w.close()
    seg = glob.glob(str(tmp_path / "wal-*.log"))[0]
    data = open(seg, "rb").read()
    open(seg, "wb").write(data[:-9])  # tear the last frame
    before = tracing.counters().get("durable.wal.torn", 0)
    assert [s for s, _ in replay_frames(tmp_path)] == [0, 1]
    assert tracing.counters().get("durable.wal.torn", 0) == before + 1
    # a restarted writer truncates the tear and continues the sequence
    w2 = WalWriter(tmp_path)
    assert w2.head_seq == 2
    assert w2.append(_ops([9], [9])) == 2
    w2.close()
    assert [s for s, _ in replay_frames(tmp_path)] == [0, 1, 2]


def test_wal_truncate_below_drops_covered_segments(tmp_path):
    w = WalWriter(tmp_path, segment_bytes=1)  # one frame per segment
    for i in range(4):
        w.append(_ops([i], [i]))
    w.roll()
    assert len(glob.glob(str(tmp_path / "wal-*.log"))) == 4
    dropped = w.truncate_below(3)
    assert dropped == 3
    assert [s for s, _ in replay_frames(tmp_path)] == [3]
    w.close()


def test_split_frames_framing():
    frame = b"".join([
        struct.pack("<BBIQ", 1, 0x31, 0, 5), b"abcde",
    ])
    frames, torn = split_frames(frame * 2 + frame[:7])
    assert len(frames) == 2 and torn == 7


# ---- checkpoint loader taxonomy (satellite: crdtlint wire contract) --------


def test_checkpoint_loader_speaks_crdt_taxonomy():
    from crdt_tpu.utils import checkpoint

    with pytest.raises(CheckpointFormatError) as ei:
        checkpoint.load_bytes(b"garbage-not-a-zip")
    assert isinstance(ei.value, CrdtError)
    assert isinstance(ei.value, ValueError)  # historical contract kept


# ---- crash plans -----------------------------------------------------------


def test_crash_plan_fires_scheduled_hit_once():
    from crdt_tpu.cluster import crash_point

    state = arm_crashes(CrashPlan(at={"oplog.fold": 2}))
    try:
        crash_point("oplog.fold")  # hit 1: survives
        with pytest.raises(InjectedCrash):
            crash_point("oplog.fold")  # hit 2: dies
        crash_point("oplog.fold")  # one-shot: the "process" is gone
        assert state.fired == ["oplog.fold"]
    finally:
        disarm_crashes()


# ---- single-node kill -9 cycle ---------------------------------------------


def test_node_kill9_recover_digest_identical(tmp_path):
    """Acknowledged writes survive: WAL-ahead ingest + checkpoint +
    post-checkpoint writes, kill -9 (abandon the object), recover —
    the restored replica is digest-identical to the dead one."""
    uni = _uni()
    node = ClusterNode("n0", _fixture_batch(uni), uni,
                       oplog=OpLog(uni),
                       durability=Durability(tmp_path))
    node.submit_writes([0, 1, 2], [100, 101, 102], actor=1)
    snap = node.checkpoint()
    assert snap is not None and snap.generation == 1
    node.submit_writes([3, 4], [200, 201], actor=2)  # WAL only
    want = node.digest()

    rec = recover(tmp_path)
    assert rec.report.replayed_ops >= 2
    assert rec.report.wall_s > 0
    np.testing.assert_array_equal(
        _digest(rec.batch, rec.universe), want)


def test_node_mid_fold_crash_recovers_drained_ops(tmp_path):
    """The nastiest window: ops drained OUT of the in-memory log but
    not yet folded when the process dies — they exist only in the WAL,
    and recovery must replay them."""
    uni = _uni()
    node = ClusterNode("n0", _fixture_batch(uni), uni,
                       oplog=OpLog(uni),
                       durability=Durability(tmp_path))
    node.checkpoint()
    arm_crashes(CrashPlan(at={"oplog.fold": 1}))
    try:
        with pytest.raises(InjectedCrash):
            node.submit_writes([0, 5], [150, 151], actor=1)
    finally:
        disarm_crashes()
    rec = recover(tmp_path)
    assert rec.report.replayed_ops == 2
    vals = rec.batch.to_scalar(rec.universe)
    assert 150 in vals[0].value().val and 151 in vals[5].value().val


def test_mid_checkpoint_crash_keeps_previous_generation(tmp_path):
    """kill -9 between the temp write and the rename: the store still
    serves the previous generation, and the WAL (never truncated —
    truncation follows the rename) still covers the gap."""
    uni = _uni()
    node = ClusterNode("n0", _fixture_batch(uni), uni,
                       oplog=OpLog(uni),
                       durability=Durability(tmp_path))
    node.submit_writes([0], [100], actor=1)
    node.checkpoint()  # generation 1
    node.submit_writes([1], [110], actor=1)
    want = node.digest()
    arm_crashes(CrashPlan(at={"durable.snapshot.pre_rename": 1}))
    try:
        with pytest.raises(InjectedCrash):
            node.checkpoint()
    finally:
        disarm_crashes()
    rec = recover(tmp_path)
    assert rec.report.generation == 1
    np.testing.assert_array_equal(_digest(rec.batch, rec.universe), want)


# ---- the rejoin: 3-node fleet, kill -9 mid-gossip, delta-only catch-up -----


def _mesh(nodes, seeds=(0, 1, 2)):
    """queue_pair gossip mesh over a MUTABLE node list: dialing a
    slot whose node is None fails like a dead host."""
    from crdt_tpu.error import PeerUnavailableError

    def make_dialer(i):
        def dial(peer):
            j = int(peer.peer_id[1:])
            if nodes[j] is None:
                raise PeerUnavailableError(f"n{j} is down (killed)")
            ta, tb = queue_pair(default_timeout=10.0)

            def serve(target=nodes[j], label=f"n{i}"):
                try:
                    target.accept(tb, peer_id=label)
                except InjectedCrash:
                    raise  # never swallow the kill
                except Exception:
                    pass
                finally:
                    tb.close()

            threading.Thread(target=serve, daemon=True).start()
            return ta
        return dial

    scheds = []
    for i in range(len(nodes)):
        m = Membership(suspect_after=3, dead_after=8)
        for j in range(len(nodes)):
            if j != i:
                m.add(f"n{j}")
        scheds.append(GossipScheduler(
            nodes[i], m, make_dialer(i), fanout=2,
            session_timeout_s=30.0, seed=seeds[i % len(seeds)],
        ))
    return scheds


def test_fleet_kill9_rejoin_converges_delta_only(tmp_path):
    """The ISSUE 12 acceptance shape, tier-1 sized: kill -9 a durable
    node mid-gossip (crash point in the fold path), keep the survivors
    writing, restore from snapshot + WAL, rejoin — the fleet converges
    to byte-identical digest vectors and the rejoin ships ZERO
    full-state frames."""
    try:
        _fleet_kill9_rejoin(tmp_path)
    finally:
        # the tracker is process-global; a later gossip test's round-
        # health gauges must not fold this fleet's peer entries in
        obs_convergence.tracker().reset()


def _fleet_kill9_rejoin(tmp_path):
    obs_convergence.tracker().reset()
    uni = _uni()
    base = _fixture_batch(uni, n=32, seed=7)
    nodes = [
        ClusterNode(f"n{i}", base, uni, busy_timeout_s=5.0,
                    oplog=OpLog(uni),
                    durability=Durability(tmp_path / f"n{i}"))
        for i in range(3)
    ]
    scheds = _mesh(nodes)

    def converge(max_sweeps=8):
        for _ in range(max_sweeps):
            for i, sched in enumerate(scheds):
                if nodes[i] is not None:
                    sched.run_round()
            ds = [n.digest() for n in nodes if n is not None]
            if all(np.array_equal(ds[0], d) for d in ds[1:]):
                return ds
        raise AssertionError("no convergence within the sweep budget")

    # warm traffic + a checkpoint cadence round on every node
    nodes[1].submit_writes([0, 1, 2, 3], [300, 301, 302, 303], actor=2)
    converge()

    # kill -9 node 1 mid-gossip: the crash fires inside its fold path
    # while a write lands, after its durability layer WAL'd the ops
    arm_crashes(CrashPlan(at={"oplog.fold": 1}))
    try:
        with pytest.raises(InjectedCrash):
            nodes[1].submit_writes([4, 5], [310, 311], actor=2)
    finally:
        disarm_crashes()
    dead_dir = tmp_path / "n1"
    nodes[1] = None  # the process is gone; nothing cleans up

    # the fleet keeps moving while n1 is down
    nodes[0].submit_writes([6, 7], [320, 321], actor=1)
    converge()

    # restore + rejoin: delta sync only
    fallbacks_before = tracing.counters().get("sync.full_state_fallback", 0)
    rec = recover(dead_dir)
    assert rec.report.replayed_ops >= 2  # the mid-fold WAL'd writes
    nodes[1] = ClusterNode(
        "n1", rec.batch, rec.universe, busy_timeout_s=5.0,
        oplog=OpLog(rec.universe), applier=rec.applier,
        durability=Durability(dead_dir))
    scheds[1:2] = [_mesh(nodes)[1]]

    digests = converge()
    assert all(np.array_equal(digests[0], d) for d in digests[1:])
    # zero full-state frames shipped during the rejoin
    assert tracing.counters().get(
        "sync.full_state_fallback", 0) == fallbacks_before
    # the rejoined node saw every write, including the ones that only
    # ever existed in its WAL
    vals = nodes[1].batch.to_scalar(rec.universe)
    assert 310 in vals[4].value().val and 311 in vals[5].value().val
    assert 320 in vals[6].value().val and 321 in vals[7].value().val
