"""Named fixtures for the reference's historical convergence bugs.

`/root/reference/quickcheck_evolution.log` documents six classes of
convergence bugs quickcheck/EQC found in riak_dt and the reference port
(SURVEY.md §4).  Each becomes a named fixture here, asserted on BOTH
engines: the scalar path directly, and the batch/TPU path by packing the
same witnesses through ``OrswotBatch`` and comparing full unpacked state.

Log line references below are to `quickcheck_evolution.log`.
"""

from crdt_tpu import Orswot, VClock
from crdt_tpu.batch import OrswotBatch
from crdt_tpu.config import CrdtConfig
from crdt_tpu.scalar.ctx import AddCtx, RmCtx
from crdt_tpu.scalar.vclock import Dot
from crdt_tpu.utils.interning import Universe


def _universe():
    return Universe(
        CrdtConfig(num_actors=8, member_capacity=16, deferred_capacity=8)
    )


def _clock(*pairs):
    c = VClock()
    for actor, counter in pairs:
        c.witness(actor, counter)
    return c


def _add(s, member, actor, counter, seen=None):
    """Apply an Add with an explicit dot (and optionally explicit ctx clock)."""
    clock = seen.clone() if seen is not None else s.value().add_clock.clone()
    dot = Dot(actor, counter)
    clock.apply(dot)
    op = s.add(member, AddCtx(clock=clock, dot=dot))
    s.apply(op)
    return op


def _scalar_join(witnesses):
    acc = Orswot()
    for w in witnesses:
        acc.merge(w)
    acc.merge(Orswot())  # defer plunger (`test/orswot.rs:61-62`)
    return acc


def _batch_join(witnesses, uni):
    batches = [OrswotBatch.from_scalar([w], uni) for w in witnesses]
    acc = OrswotBatch.from_scalar([Orswot()], uni)
    for b in batches:
        acc = acc.merge(b)
    acc = acc.merge(OrswotBatch.from_scalar([Orswot()], uni))
    return acc.to_scalar(uni)[0]


def _assert_convergent(witnesses):
    """All merge orders agree, scalar and batch produce identical state."""
    expected = _scalar_join([w.clone() for w in witnesses])
    reversed_join = _scalar_join([w.clone() for w in reversed(witnesses)])
    assert expected == reversed_join, "merge order changed the join"
    uni = _universe()
    got = _batch_join([w.clone() for w in witnesses], uni)
    assert got == expected, f"batch != scalar\nbatch:  {got!r}\nscalar: {expected!r}"
    return expected


def test_same_dot_adds_from_different_replicas():
    """log:51-57 — two replicas applying the SAME dot's add must not look
    like a delete ('when both clocks are the same but the element is not
    present')."""
    a, b = Orswot(), Orswot()
    op = _add(a, "m", actor=0, counter=1)
    b.apply(op)  # same op (same dot) routed to a second replica
    joined = _assert_convergent([a, b])
    assert joined.value().val == {"m"}


def test_context_free_removes_do_not_diverge():
    """log:83-87 — removing an element a replica never saw is safe exactly
    because removes carry their read context ('always use context')."""
    a, b = Orswot(), Orswot()
    _add(a, "m", actor=0, counter=1)
    # b never saw the add; it removes with a's read ctx (shipped over)
    rm = b.remove("m", a.contains("m").derive_rm_ctx())
    b.apply(rm)
    joined = _assert_convergent([a, b])
    assert joined.value().val == set()


def test_entry_clock_vs_set_clock_in_merge():
    """log:117-120 — common entries with disjoint per-entry dots must
    converge to the union of the dots ({a:1},{b:1} → {a:1,b:1}); comparing
    against the other's SET clock instead of the entry clock drops them."""
    a, b = Orswot(), Orswot()
    _add(a, "foo", actor=0, counter=1)
    _add(b, "foo", actor=1, counter=1)
    joined = _assert_convergent([a, b])
    assert joined.value().val == {"foo"}
    assert joined.entries["foo"] == _clock((0, 1), (1, 1))


def test_deferred_only_in_other_survives_merge():
    """log:189-193 — a deferred remove present only in the OTHER set must
    be adopted by merge, and must fire once the add catches up."""
    a, b = Orswot(), Orswot()
    # b holds a deferred remove for "A" at a clock it hasn't witnessed
    rm_clock = _clock((0, 3), (5, 7))
    rm = b.remove("A", RmCtx(clock=rm_clock))
    b.apply(rm)
    assert b.deferred, "fixture must actually defer"
    merged = a.clone()
    merged.merge(b)
    assert merged.deferred, "deferred-only-in-other was dropped by merge"
    # when the adds catch up, the buffered remove must land
    catchup = Orswot()
    for counter in (1, 2, 3):
        _add(catchup, "A", actor=0, counter=counter, seen=_clock((0, counter - 1)))
    late = Orswot()
    for counter in range(1, 8):
        _add(late, "A", actor=5, counter=counter, seen=_clock((5, counter - 1)))
    joined = _assert_convergent([a, b, catchup, late])
    assert joined.value().val == set(), "deferred remove failed to fire"


def test_deferred_partial_dots_not_descendence():
    """log:426-428 — deferred clocks that are CONCURRENT with the merged
    clock (partially unseen dots) must survive the merge; testing for full
    descendence instead silently drops them."""
    holder, other = Orswot(), Orswot()
    rm = holder.remove(1, RmCtx(clock=_clock((0, 3), (1, 5), (2, 4))))
    holder.apply(rm)
    _add(other, 1, actor=5, counter=1)
    merged = other.clone()
    merged.merge(holder)
    # merged clock {5:1} is concurrent with the rm clock — not dominated,
    # not dominating — so the row must still be buffered
    assert merged.deferred, "concurrent deferred clock dropped"
    joined = _assert_convergent([holder, other])
    assert joined.value().val == {1}, "member with unseen dots must survive"


def test_add_does_not_blindly_overwrite_causality():
    """log:491-492 — adds for the same element on one replica must extend
    the member's dot clock (witness), never overwrite it."""
    a = Orswot()
    _add(a, 2, actor=0, counter=1)
    _add(a, 2, actor=7, counter=1, seen=_clock((7, 0)))
    assert a.entries[2] == _clock((0, 1), (7, 1)), "second add lost the first dot"
    # a remove that only saw the first dot must not kill the member
    b = Orswot()
    rm = b.remove(2, RmCtx(clock=_clock((0, 1))))
    b.apply(rm)
    joined = _assert_convergent([a, b])
    assert joined.value().val == {2}


def test_catalogue_cases_converge_pairwise_with_batch():
    """Cross-check: every pair of fixture states converges identically on
    scalar and batch paths (a mini interleaving sweep over the catalogue)."""
    states = []
    s1 = Orswot(); _add(s1, "x", 0, 1); states.append(s1)
    s2 = Orswot(); s2.apply(s2.remove("x", RmCtx(clock=_clock((0, 2))))); states.append(s2)
    s3 = Orswot(); _add(s3, "y", 1, 1); _add(s3, "x", 2, 1); states.append(s3)
    s4 = Orswot(); states.append(s4)
    uni = _universe()
    for i in range(len(states)):
        for j in range(len(states)):
            sc = states[i].clone(); sc.merge(states[j]); sc.merge(Orswot())
            got = _batch_join([states[i].clone(), states[j].clone()], uni)
            assert got == sc, (i, j)
