"""The bench harness's timing-path invariants, at test scale.

bench.py's north star streams chunk folds through a salted ``lax.scan``
(one dispatch, tunnel sync paid once).  The work-elision check — replay
the exact salt chain as per-step dispatches XLA cannot hoist across and
demand bit-equality — used to live in the timed bench; it cost 113s per
run at full scale and contributed to a lost round artifact (VERDICT r3),
so the bench now runs it opt-in (``CRDT_RUN_ELISION_CHECK=1``) and the
invariant lives HERE at small shapes: if the scan's while-loop were
invariant-hoisted or partially DCE'd into computing fewer folds, the
data-dependent salts would diverge and the replay would not match.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from crdt_tpu.ops import orswot_ops
from crdt_tpu.utils.testdata import anti_entropy_fleets


@pytest.mark.parametrize("n_chunks", [4, 6])
def test_salted_scan_matches_stepped_replay(n_chunks):
    rng = np.random.RandomState(2)
    chunk, a, m, d, r = 64, 8, 8, 2, 4

    templates = []
    for _ in range(2):
        reps = anti_entropy_fleets(
            rng, chunk, a, m, d, r, base=3, novel=1, deferred_frac=0.25,
        )
        templates.append(
            tuple(jnp.stack([rep[k] for rep in reps]) for k in range(5))
        )
    t0_, t1_ = templates

    def fold_join(stack):
        acc = tuple(x[0] for x in stack)
        for i in range(1, r):
            acc = orswot_ops.merge(*acc, *(x[i] for x in stack), m, d)[:5]
        return orswot_ops.merge(*acc, *acc, m, d)[:5]  # defer plunger

    def salted_fold(tpl, salt):
        return fold_join((tpl[0] ^ salt,) + tpl[1:])

    def next_salt(acc):
        # max-reduce the DOTS plane: keeps the expensive member pipeline
        # live under DCE (see bench.py bench_north_star)
        return (jnp.max(acc[2]) & jnp.uint32(7)) | jnp.uint32(1)

    @jax.jit
    def run_chunks(t0_, t1_):
        def body(carry, _):
            salt, _prev = carry
            o0 = salted_fold(t0_, salt)
            o1 = salted_fold(t1_, next_salt(o0))
            return (next_salt(o1), o1), None

        init = (jnp.uint32(1), tuple(x[0] for x in t0_))
        (_salt, out), _ = lax.scan(body, init, None, length=n_chunks // 2)
        return out

    scan_out = run_chunks(t0_, t1_)

    # per-step replay: separately compiled programs, same salt chain
    sf = jax.jit(salted_fold)
    ns = jax.jit(next_salt)
    salt = jnp.uint32(1)
    out = None
    for _ in range(n_chunks // 2):
        o0 = sf(t0_, salt)
        o1 = sf(t1_, ns(o0))
        salt = ns(o1)
        out = o1

    for i, (g, w) in enumerate(zip(scan_out, out)):
        np.testing.assert_array_equal(
            np.asarray(g), np.asarray(w),
            err_msg=f"plane {i}: scan diverged from per-step replay",
        )


def test_budget_watchdog_rescues_blocked_run():
    """A stage blocked past the wall budget (e.g. a PJRT call into a
    tunnel that wedged mid-run, 2026-08-01 window) must still produce a
    parseable artifact line and rc=0 — the driver's own timeout killing
    the bench at rc=124 is exactly what lost the round-3 artifact."""
    import json
    import subprocess
    import sys

    code = """
import os, sys, time
os.environ["CRDT_BENCH_BUDGET_S"] = "1"
sys.path.insert(0, %r)
import bench
bench.emit(value=123.4, platform="tpu", kernel="x", headline_source="live")
bench._install_budget_watchdog(grace_s=2.0)
time.sleep(120)  # a blocked PJRT call never returns
"""
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-c", code % repo],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr[-500:]
    lines = [l for l in proc.stdout.splitlines() if l.startswith('{"metric"')]
    assert lines, proc.stdout
    rec = json.loads(lines[-1])
    assert rec["value"] == 123.4
    assert rec["budget_watchdog"] == "fired"
    assert "WATCHDOG" in proc.stderr
