"""Latency-observatory tests (ISSUE 13).

The acceptance bar: SRTT converges under injected fixed + jittered
delay; the adaptive retransmit timer never leaves the RetryPolicy
bounds (and the PR 5 TIME_WAIT close-drain stays wall-bounded under
it); a session profile's parts + unaccounted residual equal the wall
to the nanosecond; the lag sidecar degrades loudly against a faithful
old-version peer; the per-peer lag gauges reduce onto ``/fleet``; and
a 3-node shaped-RTT fleet measures finite write-to-visible lag that
drains to zero after quiescence.
"""

import itertools
import threading
import time

import numpy as np
import pytest

from crdt_tpu.batch import OrswotBatch
from crdt_tpu.cluster import (
    ClusterNode,
    GossipScheduler,
    LatencyTransport,
    Membership,
    ResilientTransport,
    RetryPolicy,
    latency_pair,
    queue_pair,
)
from crdt_tpu.config import CrdtConfig
from crdt_tpu.obs import events as obs_events
from crdt_tpu.obs import fleet as obs_fleet
from crdt_tpu.obs import metrics as obs_metrics
from crdt_tpu.obs.latency import (
    LagTracker,
    RttEstimator,
    SessionProfile,
)
from crdt_tpu.scalar.orswot import Orswot
from crdt_tpu.sync.session import SyncSession, sync_pair
from crdt_tpu.utils import tracing
from crdt_tpu.utils.workload import WorkloadGen

pytestmark = pytest.mark.cluster


def _uni(**kw):
    from crdt_tpu.utils.interning import Universe

    cfg = dict(num_actors=8, member_capacity=16, deferred_capacity=4,
               counter_bits=32)
    cfg.update(kw)
    return Universe.identity(CrdtConfig(**cfg))


def _orswot_fleet(n, seed, actor=1, extra_on=()):
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        s = Orswot()
        for _ in range(rng.randint(1, 5)):
            s.apply(s.add(int(rng.randint(0, 50)),
                          s.value().derive_add_ctx(0)))
        out.append(s)
    for i in extra_on:
        s = out[i]
        s.apply(s.add(900 + actor, s.value().derive_add_ctx(actor)))
    return out


# ---- SRTT estimation --------------------------------------------------------


def test_rtt_estimator_converges_on_fixed_delay():
    est = RttEstimator()
    assert est.rto(0.01, 2.0) is None           # no samples, no default
    assert est.rto(0.01, 2.0, default_s=0.1) == 0.1
    for _ in range(64):
        est.observe(0.050)
    snap = est.snapshot()
    assert abs(snap["srtt_s"] - 0.050) < 1e-9
    assert snap["rttvar_s"] < 1e-3              # variance decays to ~0
    assert snap["samples"] == 64


def test_rtt_estimator_converges_under_jitter():
    rng = np.random.RandomState(7)
    est = RttEstimator()
    for _ in range(256):
        est.observe(0.100 + 0.020 * rng.random())
    snap = est.snapshot()
    # srtt lands inside the jitter band, rttvar tracks its width
    assert 0.095 < snap["srtt_s"] < 0.125
    assert 0.0 < snap["rttvar_s"] < 0.020
    # negative samples (a stepped clock) are rejected, not folded
    before = est.snapshot()["samples"]
    est.observe(-1.0)
    assert est.snapshot()["samples"] == before


def test_transport_samples_rtt_over_shaped_link():
    """A live ARQ link over a 20 ms one-way delay: SRTT must converge
    to ~the 40 ms RTT, per Karn (clean first-transmission acks only),
    and the per-link gauges must publish."""
    ta, tb = latency_pair(0.02, default_timeout=5.0)
    pol = RetryPolicy(send_deadline_s=10.0, recv_deadline_s=10.0,
                      ack_timeout_s=0.5, max_backoff_s=2.0)
    ra = ResilientTransport(ta, pol, name="rtt-probe-a", seed=1)
    rb = ResilientTransport(tb, pol, name="rtt-probe-b", seed=2)
    got = []

    def consume():
        for _ in range(8):
            got.append(rb.recv(timeout=10.0))

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    for i in range(8):
        ra.send(b"frame-%d" % i)
    ra.flush(timeout=30.0)  # the delivery barrier processes the acks
    t.join(timeout=30.0)
    assert len(got) == 8 and ra.retransmits == 0
    snap = ra.rtt.snapshot()
    assert snap["samples"] == 8
    assert 0.035 < snap["srtt_s"] < 0.080       # ~RTT, scheduling slack
    gauges = obs_metrics.registry().snapshot()["gauges"]
    assert gauges["cluster.transport.rtt_probe_a.rtt_samples"] == 8
    assert gauges["cluster.transport.rtt_probe_a.rtt_srtt_s"] > 0.03
    assert gauges["cluster.transport.rtt_probe_a.rtt_rto_s"] \
        <= pol.max_backoff_s


# ---- the adaptive retransmit timer ------------------------------------------


def test_adaptive_rto_clamped_to_policy_bounds():
    pol = RetryPolicy(ack_timeout_s=0.1, max_backoff_s=0.5, min_rto_s=0.02)
    ta, _tb = queue_pair(default_timeout=1.0)
    r = ResilientTransport(ta, pol, name="clamp")
    # pre-sample: the static timer applies
    assert r.current_rto() == pytest.approx(pol.ack_timeout_s)
    # a poisoned-huge estimate can never exceed max_backoff_s
    r.rtt.observe(100.0)
    assert r.current_rto() == pol.max_backoff_s
    # a near-zero estimate can never drop below min_rto_s
    r2 = ResilientTransport(queue_pair()[0], pol, name="clamp2")
    for _ in range(32):
        r2.rtt.observe(1e-6)
    assert r2.current_rto() == pol.min_rto_s
    # adaptive=False pins the static timer regardless of samples
    pol_static = RetryPolicy(ack_timeout_s=0.1, adaptive=False)
    r3 = ResilientTransport(queue_pair()[0], pol_static, name="clamp3")
    r3.rtt.observe(100.0)
    assert r3.current_rto() == pytest.approx(0.1)


def test_close_drain_stays_bounded_under_adaptive_rto():
    """The PR 5 TIME_WAIT drain regression pin: close() keeps answering
    retransmits for ~2 retransmit timers, and the ADAPTIVE timer must
    keep that drain inside the static drain's wall-time envelope — a
    poisoned-huge estimator clamps at max_backoff_s, so quiet <= 1.0 s
    and the drain <= ~3 quiet windows either way."""
    pol = RetryPolicy(ack_timeout_s=0.1, max_backoff_s=2.0, min_rto_s=0.01)
    ta, _tb = queue_pair(default_timeout=5.0)
    r = ResilientTransport(ta, pol, name="drain-slow")
    r.rtt.observe(100.0)                      # rto clamps to 2.0, quiet to 1.0
    t0 = time.monotonic()
    r.close()
    assert time.monotonic() - t0 < 3.5        # 3 quiet windows + slack
    # a loopback-tight estimator drains in milliseconds, not the
    # static timer's ~0.2 s window
    ta2, _tb2 = queue_pair(default_timeout=5.0)
    r2 = ResilientTransport(ta2, pol, name="drain-fast")
    for _ in range(16):
        r2.rtt.observe(0.001)
    t0 = time.monotonic()
    r2.close()
    assert time.monotonic() - t0 < 0.15


def test_loopback_adaptive_rto_tighter_than_static():
    """The acceptance pin: on a loopback-shaped link the adaptive
    timer ends up well under the static default after a few acked
    frames."""
    pol = RetryPolicy(send_deadline_s=5.0, recv_deadline_s=5.0,
                      ack_timeout_s=0.1, max_backoff_s=2.0)
    ta, tb = queue_pair(default_timeout=5.0)
    ra = ResilientTransport(ta, pol, name="loop-a", seed=1)
    rb = ResilientTransport(tb, pol, name="loop-b", seed=2)
    got = []

    def consume():
        for _ in range(8):
            got.append(rb.recv(timeout=5.0))

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    for i in range(8):
        ra.send(b"x%d" % i)
    ra.flush(timeout=10.0)  # process the tail acks into the estimator
    t.join(timeout=10.0)
    assert len(got) == 8
    assert ra.current_rto() < pol.ack_timeout_s


# ---- session profile --------------------------------------------------------


def test_profile_accounting_identity_to_the_ns():
    uni = _uni()
    a = OrswotBatch.from_scalar(
        _orswot_fleet(24, seed=31, actor=1, extra_on=[1, 5]), uni)
    b = OrswotBatch.from_scalar(
        _orswot_fleet(24, seed=31, actor=2, extra_on=[4]), uni)
    ra, rb = sync_pair(SyncSession(a, uni, peer="pb"),
                       SyncSession(b, uni, peer="pa"))
    for rep in (ra, rb):
        assert rep.converged
        p = rep.profile
        assert p is not None and p.wall_ns > 0
        # the identity holds EXACTLY — integer nanoseconds throughout
        assert (p.serialize_ns + p.network_ns + p.kernel_ns + p.other_ns
                + p.unaccounted_ns) == p.wall_ns
        assert p.frames_sent >= 3 and p.frames_received >= 3
        assert 0.0 <= p.network_wait_frac <= 1.0
    # the histograms and per-peer gauges published
    snap = obs_metrics.registry().snapshot()
    assert snap["histograms"]["sync.profile.wall_s"]["count"] >= 2
    assert "sync.peer.pb.network_wait_frac" in snap["gauges"]
    assert "sync.peer.pb.unaccounted_frac" in snap["gauges"]


def test_profile_network_dominates_on_shaped_link():
    """Over a 25 ms one-way link a lock-step session is wire-bound:
    network-wait must dominate the profile and the unaccounted
    residual must stay under the 10% acceptance bar."""
    uni = _uni()
    a = OrswotBatch.from_scalar(
        _orswot_fleet(24, seed=33, actor=1, extra_on=[1]), uni)
    b = OrswotBatch.from_scalar(
        _orswot_fleet(24, seed=33, actor=2, extra_on=[4]), uni)
    # warm the kernels: compile time must not masquerade as protocol
    warm_a, warm_b = sync_pair(SyncSession(a, uni), SyncSession(b, uni))
    assert warm_a.converged and warm_b.converged
    a2 = OrswotBatch.from_scalar(
        _orswot_fleet(24, seed=34, actor=1, extra_on=[2]), uni)
    b2 = OrswotBatch.from_scalar(
        _orswot_fleet(24, seed=34, actor=2, extra_on=[6]), uni)
    ta, tb = latency_pair(0.025, default_timeout=20.0)
    sa = SyncSession(a2, uni, peer="wan-b")
    sb = SyncSession(b2, uni, peer="wan-a")
    res = {}

    def run_b():
        res["b"] = sb.sync(tb)

    t = threading.Thread(target=run_b, daemon=True)
    t.start()
    res["a"] = sa.sync(ta)
    t.join(timeout=60.0)
    p = res["a"].profile
    assert res["a"].converged and res["b"].converged
    assert p.network_wait_frac > 0.5
    assert abs(p.unaccounted_ns) <= 0.10 * p.wall_ns


# ---- the lag sidecar --------------------------------------------------------


def test_lag_sidecar_measures_write_to_visible():
    uni = _uni()
    a = OrswotBatch.from_scalar(
        _orswot_fleet(16, seed=41, actor=1, extra_on=[1]), uni)
    b = OrswotBatch.from_scalar(
        _orswot_fleet(16, seed=41, actor=2, extra_on=[4]), uni)
    la, lb = LagTracker(), LagTracker()
    # stamp a dot A's planes already witness: it becomes visible at B
    # when the session merges the diverged rows
    la.record_ingest(1, int(np.asarray(a.clock)[:, 1].max()))
    ra, rb = sync_pair(
        SyncSession(a, uni, peer="pb", lag_tracker=la),
        SyncSession(b, uni, peer="pa", lag_tracker=lb))
    assert ra.converged and rb.converged
    assert ra.lag_entries_sent == 1
    assert rb.lag_entries_received == 1
    peers = lb.snapshot()["peers"]
    assert peers["pa"]["samples"] == 1
    assert peers["pa"]["outstanding"] == 0
    assert 0.0 <= peers["pa"]["p99_s"] < 60.0   # finite, sane
    # re-delivery of the same sidecar entry must not re-measure
    assert lb.ingest_sidecar(
        "pa", [(1, int(np.asarray(a.clock)[:, 1].max()),
                time.monotonic_ns())], origin_proc=lb.proc_tag) == 0


def test_lag_sidecar_capability_fallback_with_old_peer():
    """A lag-capable session against a faithful old-version peer (no
    ``lag`` hello key — same wire shape as a build predating the
    sidecar): the session converges, ships NO lag frame, and counts
    the degradation loudly."""
    uni = _uni()
    a = OrswotBatch.from_scalar(
        _orswot_fleet(16, seed=43, actor=1, extra_on=[1]), uni)
    b = OrswotBatch.from_scalar(
        _orswot_fleet(16, seed=43, actor=2, extra_on=[4]), uni)
    before = tracing.counters()
    la = LagTracker()
    la.record_ingest(1, 7)
    ra, rb = sync_pair(
        SyncSession(a, uni, peer="pb", lag_tracker=la),
        SyncSession(b, uni, peer="pa"))          # no tracker = no capability
    assert ra.converged and rb.converged
    assert ra.lag_bytes_sent == 0 and rb.lag_bytes_sent == 0
    assert ra.lag_entries_sent == 0
    deltas = tracing.counters_since(before)
    assert deltas.get("sync.lag.fallback.capability", 0) == 1
    # ... and the flight recorder explains why
    evs = [e for e in obs_events.recorder().snapshot(kind="sync.lag_fallback")
           if e.get("session") == ra.trace_id
           or e.get("fields", {}).get("trace") == ra.trace_id]
    assert any(e["fields"]["reason"] == "capability" for e in evs)


def test_lag_sidecar_rejects_foreign_clock_domain():
    lt = LagTracker()
    before = tracing.counters()
    accepted = lt.ingest_sidecar(
        "px", [(0, 5, time.monotonic_ns())], origin_proc="not-this-proc")
    assert accepted == 0
    assert tracing.counters_since(before).get(
        "sync.lag.fallback.clock_domain") == 1


def test_fleet_lag_reduction_on_fleet_surface():
    """The /fleet reduction: per lag leaf, the MAX over every
    (node, origin) series — the worst write-to-visible lag anywhere."""
    def slice_with(gauges):
        ts, seq = time.time(), 1
        return {"ts": ts, "seq": seq, "counters": {},
                "gauges": {k: [ts, seq, v] for k, v in gauges.items()},
                "histograms": {}, "events": []}

    snap = obs_fleet.FleetSnapshot({
        "n0": slice_with({"sync.peer.n1.lag_p99_s": 0.25,
                          "sync.peer.n1.lag_current_s": 0.0}),
        "n1": slice_with({"sync.peer.n0.lag_p99_s": 0.75,
                          "sync.peer.n0.lag_current_s": 0.0}),
    })
    lag = snap.fleet_lag()
    assert lag["lag_p99_s"] == {"max": 0.75, "series": 2}
    assert lag["lag_current_s"]["max"] == 0.0
    text = obs_fleet.fleet_prometheus_text(snap)
    assert "crdt_tpu_fleet_sync_lag_p99_s_max 0.75" in text
    assert "crdt_tpu_fleet_sync_lag_current_s_max 0" in text
    assert snap.to_json()["fleet"]["lag"]["lag_p99_s"]["max"] == 0.75


# ---- the 3-node shaped-RTT fleet -------------------------------------------


def _latency_fleet(n_nodes, n_objects, one_way_s):
    """N in-process replicas over shaped-delay queue links (the
    test_cluster gossip harness with LatencyTransport under the ARQ)."""
    uni = _uni(num_actors=max(8, n_nodes + 2))
    policy = RetryPolicy(send_deadline_s=30.0, recv_deadline_s=30.0,
                         ack_timeout_s=0.5, max_backoff_s=2.0,
                         retry_budget=256)
    nodes = []
    for i in range(n_nodes):
        extra = [(3 * i + k) % n_objects for k in range(2)]
        batch = OrswotBatch.from_scalar(
            _orswot_fleet(n_objects, seed=51, actor=i + 1, extra_on=extra),
            uni)
        nodes.append(ClusterNode(f"n{i}", batch, uni, busy_timeout_s=15.0,
                                 oplog=__import__(
                                     "crdt_tpu.oplog",
                                     fromlist=["OpLog"]).OpLog(uni)))

    seeds = itertools.count(500)

    def make_dialer(i):
        def dial(peer):
            j = int(peer.peer_id[1:])
            s = next(seeds)
            ta, tb = latency_pair(one_way_s, seed=s, default_timeout=30.0)
            ra = ResilientTransport(ta, policy, name=f"n{i}-n{j}", seed=s)
            rb = ResilientTransport(tb, policy, name=f"n{j}-n{i}",
                                    seed=s + 1)

            def serve():
                try:
                    nodes[j].accept(rb, peer_id=f"n{i}")
                except Exception:
                    pass
                finally:
                    rb.close()

            threading.Thread(target=serve, daemon=True).start()
            return ra
        return dial

    scheds = []
    for i in range(n_nodes):
        m = Membership()
        for j in range(n_nodes):
            if j != i:
                m.add(f"n{j}")
        scheds.append(GossipScheduler(
            nodes[i], m, make_dialer(i), fanout=2,
            session_timeout_s=60.0, seed=i))
    return uni, nodes, scheds


def test_three_node_shaped_fleet_lag_drains_to_zero():
    """The acceptance fleet: 3 nodes over ~100 ms-RTT links; writes
    land on n0, ride sessions as sidecar stamps, and the observers'
    lag gauges are finite, outstanding never grows once writes stop,
    and everything reads zero-outstanding after quiescence."""
    uni, nodes, scheds = _latency_fleet(3, 12, one_way_s=0.05)
    # writes at the origin: distinct members on a few objects
    nodes[0].submit_writes(
        np.asarray([0, 1, 2, 3], np.int64),
        np.asarray([700, 701, 702, 703], np.int32), actor=1)

    outstanding_per_round = []
    converged = False
    for _ in range(5):
        for sched in scheds:
            sched.run_round()
        outstanding_per_round.append(tuple(
            sum(p["outstanding"]
                for p in n.lag_tracker.snapshot()["peers"].values())
            for n in nodes[1:]))
        digests = [n.digest() for n in nodes]
        if all(np.array_equal(digests[0], d) for d in digests[1:]):
            converged = True
            break
    assert converged, "shaped fleet failed to converge"

    # observers measured finite lag from the origin
    measured = 0
    for n in nodes[1:]:
        for origin, st in n.lag_tracker.snapshot()["peers"].items():
            assert np.isfinite(st["p50_s"]) and np.isfinite(st["p99_s"])
            assert 0.0 <= st["p50_s"] <= st["p99_s"] < 120.0
            measured += st["samples"]
    assert measured > 0, "no write-to-visible samples were taken"

    # outstanding is monotone non-increasing once writes stopped
    for prev, cur in zip(outstanding_per_round, outstanding_per_round[1:]):
        assert all(c <= p for p, c in zip(prev, cur))

    # one quiescent sweep more: every stamped write is visible
    # everywhere — outstanding and current lag read ZERO fleet-wide
    for sched in scheds:
        sched.run_round()
    for n in nodes:
        n.lag_tracker.refresh()
        for origin, st in n.lag_tracker.snapshot()["peers"].items():
            assert st["outstanding"] == 0
    # the SLO gauge published (rounds were observed)
    gauges = obs_metrics.registry().snapshot()["gauges"]
    assert 0.0 <= gauges["sync.slo.converged_frac"] <= 1.0
    # network-wait fraction gauges exist for the shaped peers and the
    # sessions were wire-dominated
    fracs = [v for k, v in gauges.items()
             if k.startswith("sync.peer.n") and k.endswith("network_wait_frac")]
    assert fracs and max(fracs) > 0.5


# ---- workload knobs ---------------------------------------------------------


def test_workload_read_mix_rides_its_own_stream():
    gen_w = WorkloadGen(1000, seed=9, zipf_s=1.1)
    gen_m = WorkloadGen(1000, seed=9, zipf_s=1.1, read_frac=0.8)
    keys_w = gen_w.draw(512)
    keys_m, reads = gen_m.draw_mixed(512)
    # the read knob never perturbs the key stream (seed-replayable)
    assert np.array_equal(keys_w, keys_m)
    assert 0.6 < reads.mean() < 0.95            # ~read_frac of draws
    # deterministic across generators with the same seed
    gen_m2 = WorkloadGen(1000, seed=9, zipf_s=1.1, read_frac=0.8)
    _, reads2 = gen_m2.draw_mixed(512)
    assert np.array_equal(reads, reads2)
    # read_frac=0 is all-writes and costs no coin flips
    assert not WorkloadGen(10, seed=1).draw_mixed(8)[1].any()
    with pytest.raises(ValueError):
        WorkloadGen(10, read_frac=1.5)


def test_workload_hot_object_growth_shape():
    gen = WorkloadGen(100, seed=5, zipf_s=1.2)
    obj1, m1 = gen.hot_object_members(8)
    obj2, m2 = gen.hot_object_members(8)
    assert obj1 == obj2                          # ONE hot object
    members = np.concatenate([m1, m2])
    assert len(np.unique(members)) == 16         # distinct, continuing
    assert np.array_equal(members, np.sort(members))
    # seed-stable pick, decoupled from the draw stream
    gen2 = WorkloadGen(100, seed=5, zipf_s=1.2)
    gen2.draw(64)
    assert gen2.hot_object_members(1)[0] == obj1


def test_workload_hot_object_forces_member_growth():
    """The growth shape end to end: distinct members on one object
    walk its live-slot count up — the regrow driver."""
    uni = _uni(member_capacity=8)
    batch = OrswotBatch.from_scalar([Orswot() for _ in range(4)], uni)
    node = ClusterNode("g0", batch, uni)
    gen = WorkloadGen(4, seed=3)
    obj, members = gen.hot_object_members(6)
    node.submit_writes(np.full(6, obj, np.int64),
                       members.astype(np.int32) + 100, actor=1)
    ids = np.asarray(node.batch.ids)[obj]
    assert (ids >= 0).sum() >= 6                 # the hot object grew


# ---- event clocks -----------------------------------------------------------


def test_events_carry_both_clocks():
    rec = obs_events.FlightRecorder(capacity=8)
    t0 = time.monotonic()
    rec.record("probe.one")
    rec.record("probe.two")
    evs = rec.snapshot()
    for ev in evs:
        assert "mono_ts" in ev and "wall_ts" in ev
        # mono_ts is on the process monotonic clock (duration math)
        assert abs(ev["mono_ts"] - t0) < 60.0
    # per-process recording order is monotone on mono_ts
    assert evs[0]["mono_ts"] <= evs[1]["mono_ts"]
    # the fleet ordering key is wall_ts (mono shares no cross-process
    # epoch and stays out of the merge key)
    snap = obs_fleet.FleetSnapshot({"nx": {
        "ts": 1.0, "seq": 1, "counters": {}, "gauges": {},
        "histograms": {}, "events": [dict(e) for e in evs],
    }})
    walls = [e["wall_ts"] for e in snap.events()]
    assert walls == sorted(walls)
