"""PNCounter tests — mirrors `/root/reference/test/pncounter.rs`."""

from hypothesis import given, settings
from hypothesis import strategies as st

from crdt_tpu import Dot, PNCounter
from crdt_tpu.scalar.pncounter import Dir, Op

ACTOR_MAX = 11


def build_op(prims):
    """`test/pncounter.rs:9-19`."""
    actor, counter, dir_choice = prims
    return Op(dot=Dot(actor, counter), dir=Dir.POS if dir_choice else Dir.NEG)


@given(
    st.lists(
        st.tuples(
            st.integers(0, 255),
            st.integers(0, 2**64 - 1),
            st.booleans(),
        ),
        max_size=20,
    )
)
def test_prop_merge_converges(op_prims):
    """`test/pncounter.rs:22-51`: interleaving over 2..11 witnesses converges."""
    ops = [build_op(p) for p in op_prims]
    results = set()
    for i in range(2, ACTOR_MAX):
        witnesses = [PNCounter() for _ in range(i)]
        for op in ops:
            witnesses[op.dot.actor % i].apply(op)
        merged = PNCounter()
        for witness in witnesses:
            merged.merge(witness)
        results.add(merged.value())
    assert len(results) == 1


def test_basic():
    """`test/pncounter.rs:55-74`."""
    a = PNCounter()
    assert a.value() == 0

    a.apply(a.inc("A"))
    assert a.value() == 1

    a.apply(a.inc("A"))
    assert a.value() == 2

    a.apply(a.dec("A"))
    assert a.value() == 1

    a.apply(a.inc("A"))
    assert a.value() == 2



@given(
    st.lists(
        st.tuples(
            st.integers(0, 255),
            st.integers(0, 2**32 - 1),
            st.booleans(),
        ),
        max_size=20,
    )
)
@settings(max_examples=20, deadline=None)
def test_prop_batch_merge_converges(op_prims):
    """The batched engine passes the same interleaving search
    (`test/pncounter.rs:22-51` tier-2 idiom) and agrees with the scalar
    fold.  Counters capped at u32 range so the P/N plane sums fit the
    value read-out exactly on every engine."""
    from crdt_tpu.batch import PNCounterBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.utils.interning import Universe

    ops = [build_op(p) for p in op_prims]
    uni = Universe(CrdtConfig(num_actors=32))
    result = None
    for i in (2, 5, 10):
        witnesses = [PNCounter() for _ in range(i)]
        for op in ops:
            witnesses[op.dot.actor % i].apply(op)
        acc = PNCounterBatch.from_scalar([witnesses[0]], uni)
        for w in witnesses[1:]:
            acc = acc.merge(PNCounterBatch.from_scalar([w], uni))
        value = int(acc.value()[0])
        if result is None:
            result = value
            scalar = PNCounter()
            for w in witnesses:
                scalar.merge(w)
            assert value == scalar.value(), "batch fold != scalar fold"
        else:
            assert result == value, f"diverged at cluster size {i}"
