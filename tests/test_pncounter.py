"""PNCounter tests — mirrors `/root/reference/test/pncounter.rs`."""

from hypothesis import given
from hypothesis import strategies as st

from crdt_tpu import Dot, PNCounter
from crdt_tpu.scalar.pncounter import Dir, Op

ACTOR_MAX = 11


def build_op(prims):
    """`test/pncounter.rs:9-19`."""
    actor, counter, dir_choice = prims
    return Op(dot=Dot(actor, counter), dir=Dir.POS if dir_choice else Dir.NEG)


@given(
    st.lists(
        st.tuples(
            st.integers(0, 255),
            st.integers(0, 2**64 - 1),
            st.booleans(),
        ),
        max_size=20,
    )
)
def test_prop_merge_converges(op_prims):
    """`test/pncounter.rs:22-51`: interleaving over 2..11 witnesses converges."""
    ops = [build_op(p) for p in op_prims]
    results = set()
    for i in range(2, ACTOR_MAX):
        witnesses = [PNCounter() for _ in range(i)]
        for op in ops:
            witnesses[op.dot.actor % i].apply(op)
        merged = PNCounter()
        for witness in witnesses:
            merged.merge(witness)
        results.add(merged.value())
    assert len(results) == 1


def test_basic():
    """`test/pncounter.rs:55-74`."""
    a = PNCounter()
    assert a.value() == 0

    a.apply(a.inc("A"))
    assert a.value() == 1

    a.apply(a.inc("A"))
    assert a.value() == 2

    a.apply(a.dec("A"))
    assert a.value() == 1

    a.apply(a.inc("A"))
    assert a.value() == 2
