"""Wire-path triage plumbing: the shared ``wirebulk`` flows (per-blob
patch splice, hard-status raise, u64 zigzag egress guard), the
native-vs-fallback counters they feed, and the bench-side consumers
(``native_fraction``, round-over-round ``regression_warnings``,
budget-proof required stages).
"""

import json

import numpy as np
import pytest

from crdt_tpu import from_binary, to_binary
from crdt_tpu.batch import GCounterBatch, PNCounterBatch, VClockBatch
from crdt_tpu.batch.wirebulk import (
    counters_overflow_zigzag,
    probe_engine,
    record_wire,
)
from crdt_tpu.config import CrdtConfig
from crdt_tpu.scalar.gcounter import GCounter
from crdt_tpu.scalar.vclock import VClock
from crdt_tpu.utils import tracing
from crdt_tpu.utils.interning import Universe


def _identity_uni(**kw):
    base = dict(num_actors=8, member_capacity=8, deferred_capacity=4)
    base.update(kw)
    return Universe.identity(CrdtConfig(**base))


_HAVE_ENGINE = probe_engine(
    _identity_uni(counter_bits=32), "clockish_ingest_wire", np.uint32
) is not None


# ---- planes_from_wire triage ------------------------------------------------


@pytest.mark.skipif(not _HAVE_ENGINE, reason="native engine unavailable")
def test_planes_from_wire_patch_splice_status1():
    """A u64 counter >= 2^63 zigzags past the native varint (status 1)
    but decodes fine in Python — its row must arrive via the per-blob
    patch splice, bit-equal to the full Python decode, with the mixed
    native/fallback counts recorded."""
    uni = _identity_uni(counter_bits=64)
    clocks = []
    for i in range(8):
        c = VClock()
        c.witness(i % 4, i + 1)
        clocks.append(c)
    big = VClock()
    big.witness(2, 1 << 63)
    clocks[5] = big
    blobs = [to_binary(c) for c in clocks]

    before = tracing.counters()
    got = VClockBatch.from_wire(blobs, uni)
    deltas = tracing.counters_since(before)
    want = VClockBatch.from_scalar([from_binary(b) for b in blobs], uni)
    np.testing.assert_array_equal(np.asarray(got.clocks),
                                  np.asarray(want.clocks))
    assert int(np.asarray(got.clocks)[5, 2]) == 1 << 63
    assert deltas["wire.vclock.from_wire.native"] == 7
    assert deltas["wire.vclock.from_wire.fallback"] == 1
    assert deltas["wire.vclock.from_wire.fallback_reason.grammar"] == 1
    assert tracing.native_fraction(
        deltas, "wire.vclock.from_wire"
    ) == pytest.approx(7 / 8)


@pytest.mark.skipif(not _HAVE_ENGINE, reason="native engine unavailable")
def test_planes_from_wire_hard_status_raises():
    """An actor at/past num_actors is a hard status (4): the identity
    registry cannot represent it, so the batch ingest must raise with
    the caller's blob index — not fall back, not truncate."""
    uni = _identity_uni(num_actors=4, counter_bits=32)
    good = GCounter()
    good.apply(good.inc(1))
    bad = GCounter()
    bad.apply(bad.inc(7))  # actor 7 >= num_actors 4
    blobs = [to_binary(good), to_binary(bad)]
    with pytest.raises(ValueError, match="object 1.*identity registry"):
        GCounterBatch.from_wire(blobs, uni)


# ---- counters_overflow_zigzag ----------------------------------------------


def test_counters_overflow_zigzag_u64():
    below = np.full((2, 3), (1 << 63) - 1, dtype=np.uint64)
    at = below.copy()
    at[1, 2] = 1 << 63
    assert not counters_overflow_zigzag((below,))
    assert counters_overflow_zigzag((below, at))


def test_counters_overflow_zigzag_skips_u32_and_empty():
    u32_max = np.full((4,), 0xFFFFFFFF, dtype=np.uint32)
    assert not counters_overflow_zigzag((u32_max,))
    assert not counters_overflow_zigzag((np.zeros((0,), dtype=np.uint64),))


@pytest.mark.skipif(not _HAVE_ENGINE, reason="native engine unavailable")
def test_egress_zigzag_guard_takes_python_path_and_counts():
    """u64 counters >= 2^63 force the Python encoder (the C emitter's
    zigzag would overflow) — output must still be byte-identical to
    to_binary, and the fallback reason recorded."""
    uni = _identity_uni(counter_bits=64)
    c = VClock()
    c.witness(1, 1 << 63)
    batch = VClockBatch.from_scalar([c], uni)
    before = tracing.counters()
    blobs = batch.to_wire(uni)
    deltas = tracing.counters_since(before)
    assert blobs == [to_binary(c)]
    assert deltas["wire.vclock.to_wire.fallback"] == 1
    assert deltas["wire.vclock.to_wire.fallback_reason.overflow_zigzag"] == 1
    assert tracing.native_fraction(deltas, "wire.vclock.to_wire") == 0.0


@pytest.mark.skipif(not _HAVE_ENGINE, reason="native engine unavailable")
def test_pncounter_wire_counters_native():
    uni = _identity_uni(counter_bits=32)
    from crdt_tpu.scalar.pncounter import PNCounter

    s = PNCounter()
    s.apply(s.inc(2))
    before = tracing.counters()
    batch = PNCounterBatch.from_wire([to_binary(s)], uni)
    batch.to_wire(uni)
    deltas = tracing.counters_since(before)
    assert deltas["wire.pncounter.from_wire.native"] == 1
    assert deltas["wire.pncounter.to_wire.native"] == 1


# ---- tracing counter API ----------------------------------------------------


def test_tracing_counters_thread_safe_and_reset():
    t = tracing.Tracer(enabled=False)
    t.count("x", 3)
    t.count("x")
    t.count("zero", 0)  # dropped — absent from the snapshot
    assert t.counters() == {"x": 4}
    assert "x" in t.report()
    t.reset()
    assert t.counters() == {}


def test_native_fraction_none_when_no_traffic():
    assert tracing.native_fraction({}, "wire.orswot.from_wire") is None
    assert tracing.native_fraction(
        {"wire.orswot.from_wire.native": 10}, "wire.orswot.from_wire"
    ) == 1.0


def test_record_wire_shapes_counter_names():
    before = tracing.counters()
    record_wire("testleg", "from_wire", native=5, fallback=2, reason="grammar")
    deltas = tracing.counters_since(before)
    assert deltas == {
        "wire.testleg.from_wire.native": 5,
        "wire.testleg.from_wire.fallback": 2,
        "wire.testleg.from_wire.fallback_reason.grammar": 2,
    }


# ---- round-over-round artifact diffing --------------------------------------


def test_regression_warnings_flags_30pct_movers():
    from benchkit import artifacts

    prior = {"ingest_obj_per_sec": 157000.0, "egress_obj_per_sec": 50000.0,
             "value": 3.1e6, "kernel": "jnp_fold", "ingest_objects": 1000000,
             "vs_baseline": 0.31, "zeroed": 5.0}
    current = {"ingest_obj_per_sec": 100000.0,  # -36%: flagged
               "egress_obj_per_sec": 55000.0,   # +10%: fine
               "value": 3.1e6, "kernel": "native_fold",
               "ingest_objects": 20000,          # workload size: ignored
               "vs_baseline": 0.31, "zeroed": 0}
    warns = artifacts.regression_warnings(prior, current)
    fields = {w["field"] for w in warns}
    assert fields == {"ingest_obj_per_sec", "zeroed"}
    ingest = next(w for w in warns if w["field"] == "ingest_obj_per_sec")
    assert ingest["ratio"] == pytest.approx(100000 / 157000, abs=1e-3)
    zeroed = next(w for w in warns if w["field"] == "zeroed")
    assert zeroed["ratio"] is None  # collapse to 0: ratio undefined
    assert artifacts.regression_warnings(prior, dict(prior)) == []


def test_latest_prior_artifact_picks_highest_round(tmp_path):
    from benchkit import artifacts

    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps({"n": 1, "parsed": {"metric": "m", "value": 1.0}})
    )
    (tmp_path / "BENCH_r05.json").write_text(
        json.dumps({"n": 5, "parsed": {"metric": "m", "value": 5.0}})
    )
    name, parsed = artifacts.latest_prior_artifact(str(tmp_path))
    assert name == "BENCH_r05.json"
    assert parsed["value"] == 5.0
    assert artifacts.latest_prior_artifact(str(tmp_path / "nope")) == (None, None)


def test_latest_prior_artifact_tolerates_garbage(tmp_path):
    from benchkit import artifacts

    (tmp_path / "BENCH_r09.json").write_text("{not json")
    name, parsed = artifacts.latest_prior_artifact(str(tmp_path))
    assert (name, parsed) == (None, None)


# ---- budget-proof validation stages -----------------------------------------


def test_run_stage_required_ignores_budget(monkeypatch, capsys):
    import sys

    monkeypatch.setenv("CRDT_BENCH_BUDGET_S", "0")
    for name in [n for n in sys.modules if n.startswith("benchkit")]:
        sys.modules.pop(name)
    import benchkit.core as core

    ran = []
    assert core.run_stage("opt", 10, lambda: ran.append("opt")) is None
    assert core.run_stage(
        "val", 10, lambda: ran.append("val") or "ok", required=True
    ) == "ok"
    assert ran == ["val"]
    core.emit(value=1.0)
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["opt_skipped"] == "budget"
    assert "val_skipped" not in rec
