"""Heat & placement observatory tests — per-subtree traffic
attribution, the on-device top-k/Zipf sketch, the shard/ring placement
planner, and the ``/heat`` route (crdt_tpu/obs/heat.py, ISSUE 18).

The acceptance pins: (1) per-subtree attribution lands in exactly the
bins PR 15's ``subtree_layout`` defines (one scatter-add, checked
against a host ``np.bincount``); (2) on a seeded
``WorkloadGen(zipf_s=1.2)`` mixed run the sketch's top-16 recall is
>= 0.9 against exact counts and the fitted Zipf exponent is within
+-0.15 of ground truth; (3) heat rides the PR 6 fleet lattice with its
ACI guarantees — re-delivered slices never double-count, and the
fleet-merged per-subtree heat of a live 3-node gossip fleet equals the
sum of the per-node trackers; (4) ``GET /heat?plan=mesh:8`` returns a
scored placement report while the fleet is gossiping.
"""

import itertools
import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from crdt_tpu.batch import OrswotBatch
from crdt_tpu.cluster import (
    ClusterNode,
    GossipScheduler,
    Membership,
    RetryPolicy,
    queue_pair,
)
from crdt_tpu.config import CrdtConfig
from crdt_tpu.obs import export as obs_export
from crdt_tpu.obs import fleet as obs_fleet
from crdt_tpu.obs import heat as obs_heat
from crdt_tpu.obs import metrics as obs_metrics
from crdt_tpu.obs.stability import subtree_layout
from crdt_tpu.scalar.orswot import Orswot
from crdt_tpu.utils.interning import Universe
from crdt_tpu.utils.workload import WorkloadGen

pytestmark = pytest.mark.heat

FAST = RetryPolicy(send_deadline_s=3.0, recv_deadline_s=3.0,
                   ack_timeout_s=0.05, max_backoff_s=0.3,
                   retry_budget=400)


def _http_get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode()


def _tracker(**kw):
    kw.setdefault("registry", obs_metrics.MetricsRegistry())
    return obs_heat.HeatTracker(**kw)


# ---- subtree attribution ---------------------------------------------------


def test_fold_alignment_matches_subtree_layout():
    """The scatter-add lands every object row in exactly the bin
    ``subtree_layout`` assigns it — checked against a host bincount
    over ids // span, per traffic class."""
    n = 1_000
    subtrees, span = subtree_layout(n)
    rng = np.random.RandomState(7)
    trk = _tracker()
    reads = rng.randint(0, n, 3_000).astype(np.int64)
    writes = rng.randint(0, n, 1_500).astype(np.int64)
    repair = rng.randint(0, n, 700).astype(np.int64)
    trk.record_reads(reads, n)
    trk.record_writes(writes, n)
    trk.record_repair(repair, n)
    snap = trk.snapshot()
    assert snap["layout"] == {"objects": n, "subtrees": subtrees,
                              "span": span}
    for cls, ids in (("reads", reads), ("writes", writes),
                     ("repair", repair)):
        want = np.bincount(ids // span, minlength=subtrees)
        got = np.array([row[cls] for row in snap["subtree"]])
        assert np.array_equal(got, want), f"{cls} mis-binned"
    assert snap["rows"] == {"reads": 3_000, "writes": 1_500,
                            "repair": 700}


def test_layout_regrowth_rebins_exactly():
    """Growing the object space re-bins accumulated heat onto the new
    span without losing a row: old spans divide new spans (TREE_K
    powers), so the re-bin is exact, and post-growth attribution equals
    a tracker that saw the large layout from the start."""
    small_n, big_n = 100, 10_000
    ids = np.arange(small_n, dtype=np.int64)
    late = np.random.RandomState(3).randint(
        0, big_n, 2_000).astype(np.int64)
    grown = _tracker()
    grown.record_reads(ids, small_n)
    grown.record_reads(late, big_n)
    fresh = _tracker()
    fresh.record_reads(ids, big_n)
    fresh.record_reads(late, big_n)
    gs, fs = grown.snapshot(), fresh.snapshot()
    assert gs["layout"] == fs["layout"]
    assert [r["reads"] for r in gs["subtree"]] == \
        [r["reads"] for r in fs["subtree"]]
    assert int(sum(r["reads"] for r in gs["subtree"])) == \
        small_n + 2_000


# ---- the top-k / Zipf sketch -----------------------------------------------


def test_sketch_topk_recall_and_zipf_estimate():
    """ISSUE 18 acceptance on the sketch: seeded
    ``WorkloadGen(zipf_s=1.2)`` mixed traffic at N=1000 — top-16
    recall >= 0.9 vs exact counts, fitted exponent within +-0.15."""
    n, batch, total = 1_000, 4_096, 40_960
    gen = WorkloadGen(n, seed=29, zipf_s=1.2, read_frac=0.5)
    trk = _tracker()
    exact = np.zeros(n, np.int64)
    for _ in range(total // batch):
        keys, is_read = gen.draw_mixed(batch)
        np.add.at(exact, keys, 1)
        reads, writes = keys[is_read], keys[~is_read]
        if reads.size:
            trk.record_reads(reads, n)
        if writes.size:
            trk.record_writes(writes, n)
    hot = trk.hot(16)
    true_top = set(np.argsort(-exact, kind="stable")[:16].tolist())
    recall = len({h["obj"] for h in hot} & true_top) / 16
    assert recall >= 0.9, f"top-16 recall {recall}"
    snap = trk.snapshot()
    s_hat = snap["zipf"]["s_hat"]
    assert s_hat is not None and abs(s_hat - 1.2) <= 0.15, \
        f"zipf estimate {s_hat} vs ground truth 1.2"
    # Space-Saving guarantee: count overestimates by at most err, and
    # count - err never exceeds the exact frequency
    for h in hot:
        assert h["count"] >= exact[h["obj"]] >= h["count"] - h["err"]
    assert snap["sketch"]["error_bound"] >= 0


def test_merge_hot_is_a_join():
    """Cross-node hot-list merging is a commutative, obj-keyed sum —
    the host-side half of the sketch's semilattice join."""
    a = [{"obj": 1, "count": 10, "err": 1},
         {"obj": 2, "count": 5, "err": 0}]
    b = [{"obj": 2, "count": 7, "err": 2},
         {"obj": 3, "count": 6, "err": 0}]
    ab, ba = obs_heat.merge_hot([a, b]), obs_heat.merge_hot([b, a])
    assert ab == ba
    assert ab[0] == {"obj": 2, "count": 12, "err": 2}
    assert {h["obj"]: h["count"] for h in ab} == {1: 10, 2: 12, 3: 6}


# ---- the placement planner -------------------------------------------------


def test_plan_parse_and_scores():
    heat = np.array([100.0, 10.0, 10.0, 10.0])
    n, span = 64, 16
    mesh = obs_heat.score_plan("mesh:2", heat, n=n, span=span)
    assert mesh["kind"] == "mesh" and mesh["shards"] == 2
    # shard 0 carries the hot half: subtrees 0+1 = 110 of 130
    assert mesh["loads"] == [110.0, 20.0]
    assert mesh["imbalance"] == pytest.approx(110.0 / 65.0, abs=1e-3)
    ring = obs_heat.score_plan("ring:5,k=3", heat, n=n, span=span)
    assert ring["kind"] == "ring" and ring["owners"] == 5
    assert ring["k"] == 3
    # every unit of heat is replicated onto exactly k owners at 1/k
    # weight, so the ring conserves total heat
    assert sum(ring["loads"].values()) == pytest.approx(130.0)
    assert ring["skew"] >= 1.0 and 0.0 <= ring["movement_frac"] <= 1.0
    for bad in ("mesh:0", "ring:3,k=0", "tree:4", "mesh:x", ""):
        with pytest.raises(ValueError):
            obs_heat.parse_plan(bad)


def test_mesh_bounds_granule_snapping():
    """``mesh_bounds`` is the ONE boundary formula: no granule keeps
    the historical even split; with one, every boundary is a granule
    multiple clipped to n — and junk granules (zero, non-pow2,
    negative) are typed errors, not silent misalignment."""
    assert obs_heat.mesh_bounds(64, 4) == [0, 16, 32, 48, 64]
    assert obs_heat.mesh_bounds(64, 4, granule=16) == [0, 16, 32, 48, 64]
    # n=100 over 4 shards snaps ceil(25/16)*16 = 32 rows/shard, clipped
    assert obs_heat.mesh_bounds(100, 4, granule=16) == [0, 32, 64, 96, 100]
    for bad in (0, 3, 24, -16):
        with pytest.raises(ValueError):
            obs_heat.mesh_bounds(64, 4, granule=bad)


def test_score_plan_granule_prices_buildable_layouts():
    """A granule-scored mesh plan reports the exact bounds
    ``crdt_tpu.mesh.state.choose_layout`` instantiates — the planner
    prices only buildable layouts; granule on a ring plan is a typed
    error."""
    heat = np.array([100.0, 10.0, 10.0, 10.0])
    rep = obs_heat.score_plan("mesh:2", heat, n=64, span=16, granule=16)
    assert rep["granule"] == 16 and rep["bounds"] == [0, 32, 64]
    assert rep["loads"] == [110.0, 20.0]
    from crdt_tpu.mesh.state import choose_layout
    lay = choose_layout(64, 2, granule=16)
    assert list(lay.bounds) == rep["bounds"]
    with pytest.raises(ValueError):
        obs_heat.score_plan("ring:5,k=3", heat, n=64, span=16,
                            granule=16)


def test_heat_route_accepts_granule():
    """``GET /heat?plan=mesh:S&granule=G``: subtree-aligned boundaries
    ride the scored report; a non-pow2 (or non-numeric) granule 400s
    like any bogus plan spec."""
    trk = _tracker()
    trk.record_reads(np.zeros(64, np.int64), 64)
    rep = trk.plan_report("mesh:4", granule=16)
    assert rep["granule"] == 16 and rep["bounds"][-1] == 64
    srv = obs_export.start_metrics_server(port=0, heat=trk)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        status, body = _http_get(f"{base}/heat?plan=mesh:4&granule=16")
        rep2 = json.loads(body)["report"]
        assert status == 200 and rep2["granule"] == 16
        assert rep2["bounds"] == rep["bounds"]
        for junk in ("12", "abc"):
            try:
                _http_get(f"{base}/heat?plan=mesh:4&granule={junk}")
            except urllib.error.HTTPError as e:
                assert e.code == 400
            else:
                raise AssertionError(f"granule={junk} did not 400")
    finally:
        srv.stop()


def test_plan_report_prefers_balanced_split():
    """A deliberately lopsided heat vector scores worse (higher
    imbalance) under fewer shards than under subtree-granular shards —
    the signal an operator reads off the report."""
    n = 256
    trk = _tracker()
    hot = np.zeros(4_000, np.int64)  # all heat in subtree 0
    trk.record_reads(hot, n)
    one = trk.plan_report("mesh:1")
    sixteen = trk.plan_report("mesh:16")
    assert one["imbalance"] == 1.0  # one shard is trivially "balanced"
    assert sixteen["imbalance"] > 1.0
    assert sixteen["max_load"] == pytest.approx(4_000.0)


# ---- the fleet lattice ride ------------------------------------------------


def test_fleet_merge_never_double_counts():
    """ACI sweep: per-node heat counters ride the fleet G-Counter read
    — merging a re-delivered slice (idempotence), merging in any order
    (commutativity), and bracketed groupings (associativity) all
    produce the same fleet heat."""
    slices = []
    per_node = []
    for i in range(3):
        reg = obs_metrics.MetricsRegistry()
        trk = _tracker(registry=reg)
        ids = np.arange(0, 1_000, i + 1, dtype=np.int64)
        trk.record_reads(ids, 1_000)
        trk.record_writes(ids[: ids.size // 2], 1_000)
        trk.publish()
        per_node.append(trk)
        slices.append(obs_fleet.capture_slice(f"n{i}", registry=reg))

    def heat_of(snap):
        return snap.fleet_heat()

    merged = slices[0].merge(slices[1]).merge(slices[2])
    want = heat_of(merged)
    # idempotence: re-delivering n1's slice changes nothing
    assert heat_of(merged.merge(slices[1])) == want
    # commutativity + associativity
    assert heat_of(slices[2].merge(slices[0]).merge(slices[1])) == want
    assert heat_of(slices[0].merge(slices[1].merge(slices[2]))) == want
    # and the fleet value IS the sum of the per-node trackers
    vecs = [t.heat_vector() for t in per_node]
    for i in range(max(v.size for v in vecs)):
        fleet_total = sum(
            v for name, v in want["subtree"].items()
            if name.startswith(f"heat.subtree.{i}."))
        assert fleet_total == sum(
            int(v[i]) for v in vecs if i < v.size)


# ---- the live 3-node fleet + /heat route -----------------------------------


def _uni(num_actors=8, member_capacity=24, deferred_capacity=4):
    return Universe.identity(CrdtConfig(
        num_actors=num_actors, member_capacity=member_capacity,
        deferred_capacity=deferred_capacity, counter_bits=32))


def _orswot_fleet(n, seed, actor=1, extra_on=()):
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        s = Orswot()
        for _ in range(rng.randint(1, 5)):
            s.apply(s.add(int(rng.randint(0, 50)),
                          s.value().derive_add_ctx(0)))
        out.append(s)
    for i in extra_on:
        s = out[i]
        s.apply(s.add(900 + actor, s.value().derive_add_ctx(actor)))
    return out


def _mesh(n_nodes, n_objects):
    """Clean 3-way queue-pair gossip mesh; every node carries a PRIVATE
    HeatTracker + MetricsRegistry so per-node attribution stays apart
    in one process (what distinct hosts get for free)."""
    uni = _uni(num_actors=max(8, n_nodes + 2))
    nodes, regs = [], []
    for i in range(n_nodes):
        batch = OrswotBatch.from_scalar(
            _orswot_fleet(n_objects, seed=41, actor=i + 1,
                          extra_on=[(3 * i + k) % n_objects
                                    for k in range(3)]), uni)
        reg = obs_metrics.MetricsRegistry()
        regs.append(reg)
        nodes.append(ClusterNode(
            f"n{i}", batch, uni, busy_timeout_s=5.0,
            heat_tracker=obs_heat.HeatTracker(registry=reg)))

    seeds = itertools.count(9_000)

    def make_dialer(i):
        def dial(peer):
            j = int(peer.peer_id[1:])
            s = next(seeds)
            ta, tb = queue_pair(default_timeout=10.0)
            from crdt_tpu.cluster import ResilientTransport
            ra = ResilientTransport(ta, FAST, name=f"n{i}->n{j}",
                                    seed=s)
            rb = ResilientTransport(tb, FAST, name=f"n{j}->n{i}",
                                    seed=s + 1)

            def serve():
                try:
                    nodes[j].accept(rb, peer_id=f"n{i}")
                except Exception:
                    pass
                finally:
                    rb.close()

            threading.Thread(target=serve, daemon=True).start()
            return ra
        return dial

    scheds = []
    for i in range(n_nodes):
        m = Membership(suspect_after=3, dead_after=6)
        for j in range(n_nodes):
            if j != i:
                m.add(f"n{j}")
        scheds.append(GossipScheduler(
            nodes[i], m, make_dialer(i), fanout=n_nodes - 1,
            session_timeout_s=60.0, seed=i))
    return nodes, regs, scheds


def test_acceptance_fleet_heat_on_live_gossip():
    """ISSUE 18 acceptance: a live 3-node gossip fleet with writes,
    serve reads and sync repair — the fleet-merged per-subtree heat
    equals the sum of the per-node trackers, and ``GET /heat`` answers
    (prom text, JSON, and a scored ``?plan=mesh:8`` report) while the
    fleet is still gossiping."""
    n_objects = 96
    nodes, regs, scheds = _mesh(3, n_objects)
    gen = WorkloadGen(n_objects, seed=17, zipf_s=1.1)
    rng = np.random.RandomState(17)
    srv = obs_export.start_metrics_server(port=0, heat=nodes[0].heat)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        for rnd in range(4):
            for i, node in enumerate(nodes):
                if rnd < 2:
                    node.submit_writes(
                        gen.draw(40),
                        rng.randint(200, 216, 40).astype(np.int32),
                        actor=i + 1)
                scheds[i].run_round()
            if rnd == 1:
                # scrape mid-run: the observatory answers while sync
                # sessions are in flight
                status, text = _http_get(f"{base}/heat")
                assert status == 200
                assert "crdt_tpu_heat_updates_total" in text
        from crdt_tpu.serve import ReadRequest
        for i, node in enumerate(nodes):
            node.serve_reads(ReadRequest.reads(gen.draw(64) % n_objects))

        status, body = _http_get(f"{base}/heat?format=json")
        snap = json.loads(body)
        assert status == 200 and snap["updates"] > 0
        assert sum(snap["rows"].values()) > 0

        status, body = _http_get(f"{base}/heat?plan=mesh:8")
        rep = json.loads(body)["report"]
        assert status == 200 and rep["kind"] == "mesh"
        assert rep["shards"] == 8 and len(rep["loads"]) == 8
        assert rep["imbalance"] >= 1.0

        status, body = _http_get(f"{base}/heat?plan=ring:5,k=3")
        rep = json.loads(body)["report"]
        assert rep["kind"] == "ring" and rep["k"] == 3

        try:
            _http_get(f"{base}/heat?plan=tree:9")
        except urllib.error.HTTPError as e:
            assert e.code == 400
        else:
            raise AssertionError("bogus plan spec did not 400")

        # the fleet reduction: merged per-subtree heat == sum of the
        # per-node trackers (each node published, so hot gauges ride
        # along too)
        for node in nodes:
            node.heat.publish()
        merged = obs_fleet.capture_slice("n0", registry=regs[0])
        for i in range(1, 3):
            merged = merged.merge(
                obs_fleet.capture_slice(f"n{i}", registry=regs[i]))
        fh = merged.fleet_heat()
        vecs = [node.heat.heat_vector() for node in nodes]
        width = max(v.size for v in vecs)
        assert width > 0, "no heat attributed on a live fleet"
        for i in range(width):
            fleet_total = sum(
                v for name, v in fh["subtree"].items()
                if name.startswith(f"heat.subtree.{i}."))
            assert fleet_total == sum(
                int(v[i]) for v in vecs if i < v.size), \
                f"fleet heat != sum of per-node heat in subtree {i}"
        # all three planes fired: writes on every node, repair on any
        # node that applied a delta, reads on every node
        rows = [node.heat.snapshot()["rows"] for node in nodes]
        assert all(r["writes"] > 0 for r in rows)
        assert all(r["reads"] > 0 for r in rows)
        assert any(r["repair"] > 0 for r in rows)
        assert srv.scraped("/heat")
    finally:
        srv.stop()


# ---- serve latency satellites ----------------------------------------------


def test_serve_latency_histograms_and_healthz_durations():
    """Satellite (a): the serve loop publishes per-mode
    ``serve.latency.<mode>`` histograms plus ``serve.park_wait_s``,
    and ``/healthz`` reports durations (count/mean/max), not just
    counts."""
    uni = _uni()
    batch = OrswotBatch.from_scalar(_orswot_fleet(16, seed=5), uni)
    from crdt_tpu.oplog import OpLog
    node = ClusterNode("nh", batch, uni, oplog=OpLog(uni))
    from crdt_tpu.serve import ReadRequest
    before = obs_metrics.registry().snapshot()["histograms"]
    n_ev = before.get("serve.latency.eventual", {}).get("count", 0)
    node.serve_reads(ReadRequest.reads(np.arange(8)))
    node.submit_writes(np.array([1], np.int64),
                       np.array([201], np.int32), actor=2)
    node.serve_reads(ReadRequest.reads(
        [1], member=201, mode="ryw", require=node.write_vv()))
    hists = obs_metrics.registry().snapshot()["histograms"]
    assert hists["serve.latency.eventual"]["count"] == n_ev + 1
    assert hists["serve.latency.ryw"]["count"] >= 1
    assert hists["serve.latency.eventual"]["sum"] > 0

    srv = obs_export.start_metrics_server(port=0)
    try:
        status, body = _http_get(
            f"http://127.0.0.1:{srv.port}/healthz")
        serve_sec = json.loads(body)["serve"]
        assert serve_sec["latency"]["eventual"]["count"] >= 1
        assert serve_sec["latency"]["eventual"]["mean_s"] >= 0.0
        assert "max_s" in serve_sec["latency"]["eventual"]
        assert "park_wait" in serve_sec
    finally:
        srv.stop()


def test_park_wait_duration_histogram():
    """A parked-then-admitted RYW read records its wait as a duration
    (``serve.park_wait_s``), so /healthz can answer "how long do reads
    wait behind the fold lock" in seconds — staged here by holding the
    node's fold lock while the write sits queued, then releasing it
    mid-park."""
    uni = _uni()
    batch = OrswotBatch.from_scalar(_orswot_fleet(8, seed=6), uni)
    from crdt_tpu.oplog import OpLog
    node = ClusterNode("np", batch, uni, oplog=OpLog(uni))
    from crdt_tpu.serve import ReadRequest
    node.serve_reads(ReadRequest.reads([0]))  # build the loop
    node._serve_loop.park_timeout_s = 5.0
    before = obs_metrics.registry().snapshot()["histograms"]
    n0 = before.get("serve.park_wait_s", {}).get("count", 0)
    assert node._busy.acquire(timeout=5.0)  # a "gossip session"
    try:
        node.submit_writes(np.array([0], np.int64),
                           np.array([205], np.int32), actor=2)
        ack = node.write_vv()  # log-inclusive: covers the queued op
    finally:
        t = threading.Timer(0.05, node._busy.release)
        t.start()
    frame = node.serve_reads(ReadRequest.reads(
        [0], member=205, mode="ryw", require=ack))
    assert int(frame.val[0]) == 1
    h = obs_metrics.registry().snapshot()["histograms"]
    assert h["serve.park_wait_s"]["count"] == n0 + 1
    assert h["serve.park_wait_s"]["max"] >= 0.02
