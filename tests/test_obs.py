"""The observability subsystem (`crdt_tpu.obs`).

Covers the obs PR's acceptance bar: the typed registry (counters,
gauges, log2 histograms) and its Prometheus/JSON export, the bounded
flight recorder, thread-safety of concurrent span/count/event appends
against exporter scrapes, sync-session phase events stamped with
session IDs (a forced digest collision must leave a
``full_state_fallback`` event), wire-loop gauges, convergence
telemetry, the counter-family regression differ, and the live
``/metrics`` + ``/events`` HTTP surface — in-process and through a real
``replicate_tcp --metrics-port`` run.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from crdt_tpu.batch import OrswotBatch
from crdt_tpu.config import CrdtConfig
from crdt_tpu.obs import convergence as obs_convergence
from crdt_tpu.obs import events as obs_events
from crdt_tpu.obs import export as obs_export
from crdt_tpu.obs import metrics as obs_metrics
from crdt_tpu.scalar.orswot import Orswot
from crdt_tpu.sync.session import SyncSession, sync_pair
from crdt_tpu.utils import tracing
from crdt_tpu.utils.interning import Universe

pytestmark = pytest.mark.obs


def _uni(**kw):
    cfg = dict(num_actors=8, member_capacity=16, deferred_capacity=4,
               counter_bits=32)
    cfg.update(kw)
    return Universe.identity(CrdtConfig(**cfg))


def _orswot_fleet(n, seed, actor=1, extra_on=()):
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        s = Orswot()
        for _ in range(rng.randint(1, 5)):
            s.apply(s.add(int(rng.randint(0, 50)),
                          s.value().derive_add_ctx(0)))
        out.append(s)
    for i in extra_on:
        s = out[i]
        s.apply(s.add(900 + actor, s.value().derive_add_ctx(actor)))
    return out


def _http_get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode()


# ---- metrics registry ------------------------------------------------------


def test_registry_counter_gauge_histogram():
    reg = obs_metrics.MetricsRegistry()
    reg.counter_inc("c", 3)
    reg.counter("c").inc(2)
    reg.gauge_set("g", 7.5)
    reg.observe("h", 3.0)
    snap = reg.snapshot()
    assert snap["counters"]["c"] == 5
    assert snap["gauges"]["g"] == 7.5
    assert snap["histograms"]["h"]["count"] == 1
    assert snap["histograms"]["h"]["sum"] == 3.0


def test_registry_rejects_type_flips():
    reg = obs_metrics.MetricsRegistry()
    reg.counter_inc("x")
    with pytest.raises(ValueError):
        reg.gauge_set("x", 1.0)
    with pytest.raises(ValueError):
        reg.observe("x", 1.0)


def test_histogram_log2_buckets():
    h = obs_metrics.Histogram("h")
    # bucket e holds (2**(e-1), 2**e]: 3.0 -> bound 4.0, and exactly
    # 4.0 ALSO -> bound 4.0 (Prometheus le is inclusive); 5.0 -> 8.0
    h.observe(3.0)
    h.observe(4.0)
    h.observe(5.0)
    h.observe(0.0)       # zero/negative -> floor bucket, bound 0.0
    h.observe(1e-9)
    bounds = dict(h.cumulative())
    assert h.count == 5
    assert 4.0 in bounds and 8.0 in bounds
    assert 0.0 in bounds and bounds[0.0] == 1  # only the zero landed there
    assert bounds[4.0] == 4                    # 0.0, 1e-9, 3.0, 4.0 are <= 4
    assert bounds[8.0] - bounds[4.0] == 1      # only 5.0 sits in (4, 8]
    # cumulative counts are monotone and end at count
    cum = [c for _, c in h.cumulative()]
    assert cum == sorted(cum) and cum[-1] == h.count
    assert h.min == 0.0 and h.max == 5.0


def test_counter_handle_is_thread_safe():
    """Cached counter handles mutate outside the registry lock; the
    counter's own lock must keep concurrent increments exact (the
    monotonic contract — a gauge may lose races, a counter may not)."""
    reg = obs_metrics.MetricsRegistry()
    c = reg.counter("c")
    n_threads, per_thread = 8, 5_000
    threads = [
        threading.Thread(target=lambda: [c.inc() for _ in range(per_thread)])
        for _ in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert c.value == n_threads * per_thread


def test_prometheus_text_rendering():
    reg = obs_metrics.MetricsRegistry()
    reg.counter_inc("wire.sync.delta.bytes", 123)
    reg.gauge_set("wireloop.staging_free", 2)
    reg.observe("sync.digest_exchange", 0.003)
    text = obs_export.prometheus_text(reg)
    assert "# TYPE crdt_tpu_wire_sync_delta_bytes_total counter" in text
    assert "crdt_tpu_wire_sync_delta_bytes_total 123" in text
    assert "crdt_tpu_wireloop_staging_free 2" in text
    assert "# TYPE crdt_tpu_sync_digest_exchange histogram" in text
    assert 'crdt_tpu_sync_digest_exchange_bucket{le="+Inf"} 1' in text
    assert "crdt_tpu_sync_digest_exchange_count 1" in text


# ---- flight recorder -------------------------------------------------------


def test_flight_recorder_bounded_and_filtered():
    rec = obs_events.FlightRecorder(capacity=8)
    for i in range(20):
        rec.record("probe.tick", session="s1" if i % 2 else "s2", i=i)
    evs = rec.snapshot()
    assert len(evs) == 8
    assert rec.dropped == 12
    # oldest-first, monotone seq, latest retained
    seqs = [e["seq"] for e in evs]
    assert seqs == sorted(seqs) and seqs[-1] == 20
    assert all(e["kind"] == "probe.tick" for e in evs)
    only_s1 = rec.snapshot(session="s1")
    assert only_s1 and all(e["session"] == "s1" for e in only_s1)
    # kind filter matches whole dotted segments only
    assert rec.snapshot(kind="probe") == evs
    assert rec.snapshot(kind="prob") == []
    rec.clear()
    assert rec.snapshot() == [] and rec.dropped == 0


def test_session_ids_are_unique():
    ids = {obs_events.new_session_id() for _ in range(100)}
    assert len(ids) == 100


# ---- thread-safety: writers vs scrapes -------------------------------------


def test_concurrent_spans_counts_events_vs_scrapes():
    """Wireloop-parse-thread shape: several writer threads hammer
    span/count/event appends while a scraper renders snapshots and
    Prometheus text; nothing tears, and the final totals are exact."""
    tracing.reset()
    rec = obs_events.recorder()
    rec.clear()
    tracing.enable(True)
    tracing.count("obs.threads.primer")  # scraper may win the race to an
    # otherwise-empty registry; give it one guaranteed sample
    n_threads, per_thread = 4, 500
    errors = []
    stop = threading.Event()

    def writer(tid):
        try:
            for i in range(per_thread):
                tracing.count("obs.threads.counter", 1)
                with tracing.span("obs.threads.span"):
                    pass
                obs_events.record("obs.threads.event", tid=tid, i=i)
        except Exception as e:  # noqa: BLE001 — surfaced after join
            errors.append(e)

    def scraper():
        try:
            while not stop.is_set():
                text = obs_export.prometheus_text()
                assert "crdt_tpu_" in text
                snap = obs_metrics.registry().snapshot()
                # a torn histogram would violate sum(buckets) == count
                for h in snap["histograms"].values():
                    assert sum(h["buckets"].values()) == h["count"]
                rec.snapshot()
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    s = threading.Thread(target=scraper)
    s.start()
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
    finally:
        stop.set()
        s.join(timeout=60)
        tracing.enable(False)
    assert not errors, errors[0]
    total = n_threads * per_thread
    assert tracing.counters()["obs.threads.counter"] == total
    assert tracing.get_tracer().stats["obs.threads.span"].count == total
    reg_snap = obs_metrics.registry().snapshot()
    assert reg_snap["counters"]["obs.threads.counter"] == total
    assert reg_snap["histograms"]["obs.threads.span"]["count"] == total
    assert len(rec.snapshot(kind="obs.threads.event")) + rec.dropped >= total
    tracing.reset()


# ---- sync session events + convergence telemetry ---------------------------


def test_sync_session_phase_events_and_convergence_gauges():
    obs_events.recorder().clear()
    uni = _uni()
    a = SyncSession(
        OrswotBatch.from_scalar(_orswot_fleet(32, 7, actor=1,
                                              extra_on=[3]), uni),
        uni, peer="b",
    )
    b = SyncSession(
        OrswotBatch.from_scalar(_orswot_fleet(32, 7, actor=2,
                                              extra_on=[9]), uni),
        uni, peer="a",
    )
    assert a.session_id != b.session_id
    ra, rb = sync_pair(a, b)
    assert ra.converged and rb.converged

    evs_a = obs_events.recorder().snapshot(session=a.session_id)
    phases = [e["fields"]["phase"] for e in evs_a
              if e["kind"] == "sync.phase"]
    assert phases[0] == "start"
    assert "digest_exchange" in phases
    assert "delta_exchange" in phases
    assert phases[-1] == "converged"
    # peer A's events never carry peer B's session id
    assert all(e["session"] == a.session_id for e in evs_a)

    conv = obs_convergence.tracker().snapshot()
    assert conv["b"]["divergence"] == 2  # rows 3 and 9 diverged
    assert conv["b"]["rounds_to_converge"] == ra.digest_rounds
    assert conv["b"]["staleness_s"] is not None
    g = obs_metrics.registry().snapshot()["gauges"]
    assert g["sync.peer.b.divergence"] == 2.0
    assert g["sync.peer.b.rounds_to_converge"] == float(ra.digest_rounds)


def test_forced_digest_collision_leaves_fallback_event():
    """Acceptance bar: a forced digest collision must leave a
    ``sync.full_state_fallback`` event (reason ``digest_collision``) in
    the flight recorder, and the session still converges."""
    obs_events.recorder().clear()
    uni = _uni()
    collide = lambda batch: np.zeros(  # noqa: E731 — constant digest
        batch.clock.shape[0], dtype=np.uint64
    )
    a = SyncSession(
        OrswotBatch.from_scalar(_orswot_fleet(16, 11, actor=1,
                                              extra_on=[2]), uni),
        uni, digest_fn=collide,
    )
    b = SyncSession(
        OrswotBatch.from_scalar(_orswot_fleet(16, 11, actor=2,
                                              extra_on=[5]), uni),
        uni, digest_fn=collide,
    )
    ra, rb = sync_pair(a, b)
    assert ra.converged and ra.full_state_fallback
    falls = obs_events.recorder().snapshot(kind="sync.full_state_fallback",
                                           session=a.session_id)
    assert falls and falls[0]["fields"]["reason"] == "digest_collision"
    colls = obs_events.recorder().snapshot(kind="sync.digest_collision",
                                           session=a.session_id)
    assert colls


def test_delta_ratio_gauge_populates_with_reference():
    """The per-peer delta_ratio gauge and history populate when the
    session knows a full-state reference — the constructor hint on the
    delta path, the shipped full frame itself on fallback paths."""
    uni = _uni()
    fa = OrswotBatch.from_scalar(_orswot_fleet(32, 21, actor=1,
                                               extra_on=[3]), uni)
    fb = OrswotBatch.from_scalar(_orswot_fleet(32, 21, actor=2,
                                               extra_on=[9]), uni)
    full_ref = sum(len(b) for b in fa.to_wire(uni))
    a = SyncSession(fa, uni, peer="ratio-b", full_state_bytes=full_ref)
    b = SyncSession(fb, uni, peer="ratio-a", full_state_bytes=full_ref)
    ra, _ = sync_pair(a, b)
    assert ra.converged and not ra.full_state_fallback
    g = obs_metrics.registry().snapshot()["gauges"]
    ratio = g["sync.peer.ratio-b.delta_ratio"]
    assert 0.0 < ratio < 1.0  # 2/32 rows diverged: far below full state
    hist = obs_convergence.tracker().snapshot()["ratio-b"][
        "delta_ratio_history"]
    assert hist and hist[-1] == pytest.approx(ratio)

    # fallback path, NO hint: the full frame itself is the reference,
    # so the ratio lands at >= 1.0 (full state shipped plus framing)
    collide = lambda batch: np.zeros(  # noqa: E731 — constant digest
        batch.clock.shape[0], dtype=np.uint64
    )
    fc = OrswotBatch.from_scalar(_orswot_fleet(16, 23, actor=1,
                                               extra_on=[2]), uni)
    fd = OrswotBatch.from_scalar(_orswot_fleet(16, 23, actor=2,
                                               extra_on=[5]), uni)
    c = SyncSession(fc, uni, digest_fn=collide, peer="ratio-d")
    d = SyncSession(fd, uni, digest_fn=collide, peer="ratio-c")
    rc, _ = sync_pair(c, d)
    assert rc.converged and rc.full_state_fallback
    g = obs_metrics.registry().snapshot()["gauges"]
    assert g["sync.peer.ratio-d.delta_ratio"] >= 1.0


def test_private_registry_scrape_keeps_global_state_untouched():
    """Rendering a caller-owned registry must refresh the caller's
    tracker (so its staleness gauges are live) and must NOT write the
    process-global tracker's gauges into the global registry."""
    import time

    # seed the global tracker so a buggy refresh would visibly rewrite
    # the global staleness gauge
    obs_convergence.tracker().observe_session("leak-probe", converged=True,
                                              rounds=1)
    time.sleep(0.01)
    before = obs_metrics.registry().snapshot()["gauges"]

    reg = obs_metrics.MetricsRegistry()
    trk = obs_convergence.ConvergenceTracker(reg)
    trk.observe_session("px", converged=True, rounds=2)
    time.sleep(0.01)
    text = obs_export.prometheus_text(reg, tracker=trk)
    assert "crdt_tpu_sync_peer_px_rounds_to_converge 2" in text
    staleness = [ln for ln in text.splitlines()
                 if ln.startswith("crdt_tpu_sync_peer_px_staleness_s ")]
    assert staleness and float(staleness[0].split()[1]) > 0.0  # refreshed

    obs_export.prometheus_text(reg)  # private registry, no tracker
    after = obs_metrics.registry().snapshot()["gauges"]
    assert after == before


def test_protocol_error_recorded():
    from crdt_tpu.error import SyncProtocolError
    from crdt_tpu.sync.delta import decode_frame

    obs_events.recorder().clear()
    before = tracing.counters().get("sync.frame.rejected.truncated", 0)
    with pytest.raises(SyncProtocolError):
        decode_frame(b"\x01")
    evs = obs_events.recorder().snapshot(kind="sync.protocol_error")
    assert evs and evs[-1]["fields"]["reason"] == "truncated"
    assert tracing.counters()["sync.frame.rejected.truncated"] == before + 1


# ---- wireloop gauges -------------------------------------------------------


def test_wireloop_publishes_gauges():
    from crdt_tpu.batch.wireloop import PipelinedWireLoop

    uni = _uni()
    fleet = _orswot_fleet(8, 13)
    blobs = OrswotBatch.from_scalar(fleet, uni).to_wire(uni)
    loop = PipelinedWireLoop(uni)
    res = loop.run([[blobs, blobs]], overlap=True)
    assert res["rounds"] == 1
    g = obs_metrics.registry().snapshot()["gauges"]
    assert "wireloop.staging_free" in g
    assert "wireloop.parsed_depth" in g
    text = obs_export.prometheus_text()
    assert "crdt_tpu_wireloop_staging_free" in text


# ---- counter-family regression differ --------------------------------------


def test_counter_family_warnings():
    from benchkit import artifacts

    prior = {
        "wire.orswot.from_wire.native": 100,
        "wire.orswot.from_wire.fallback": 2,
        "wire.orswot.from_wire.fallback_reason.grammar": 2,
        "wire.gset.to_wire.native": 5,
        "sync.sessions": 3,
    }
    # gset family vanished entirely; orswot lost its .native leaf while
    # the family survives (the silent-fallback smell)
    current = {
        "wire.orswot.from_wire.fallback": 90,
        "sync.sessions": 4,
    }
    warns = artifacts.counter_family_warnings(prior, current)
    kinds = {(w["kind"], w["family"]) for w in warns}
    assert ("family_vanished", "wire.gset.to_wire") in kinds
    assert ("native_vanished", "wire.orswot.from_wire") in kinds
    # a reason counter that stops firing is NOT a warning on its own
    assert not any("fallback_reason" in str(w) for w in warns)
    # no priors / no currents -> no warnings, never a crash
    assert artifacts.counter_family_warnings(None, current) == []
    assert artifacts.counter_family_warnings(prior, None) == []
    assert artifacts.counter_family_warnings(prior, dict(prior)) == []


# ---- the HTTP export surface -----------------------------------------------


def test_http_exporter_serves_metrics_events_healthz():
    tracing.count("obs.http.probe_counter", 9)
    obs_events.record("obs.http.probe_event", session="sess-http", x=1)
    srv = obs_export.start_metrics_server(port=0)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        status, text = _http_get(f"{base}/metrics")
        assert status == 200
        assert "crdt_tpu_obs_http_probe_counter_total 9" in text

        status, body = _http_get(f"{base}/events?session=sess-http")
        assert status == 200
        doc = json.loads(body)
        assert any(e["kind"] == "obs.http.probe_event"
                   for e in doc["events"])
        assert all(e["session"] == "sess-http" for e in doc["events"])

        status, body = _http_get(f"{base}/healthz")
        assert status == 200
        assert json.loads(body)["status"] == "ok"

        try:
            _http_get(f"{base}/nope")
        except urllib.error.HTTPError as e:
            assert e.code == 404
        else:
            raise AssertionError("unknown route did not 404")
        assert srv.scraped("/metrics", "/events", "/healthz")
    finally:
        srv.stop()
    srv.stop()  # idempotent


def test_replicate_tcp_metrics_endpoint_live():
    """The acceptance criterion end-to-end: during a ``replicate_tcp
    --metrics-port`` sync session, ``GET /metrics`` serves Prometheus
    text with ``wire.sync.*`` counters and phase latency histograms,
    and ``GET /events`` serves the session's phase-transition events
    carrying its session ID."""
    import os
    import socket
    import subprocess
    import sys
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        sync_port = probe.getsockname()[1]
    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        metrics_port = probe.getsockname()[1]

    base = [sys.executable, os.path.join(repo, "examples",
                                         "replicate_tcp.py")]
    common = ["--port", str(sync_port), "--objects", "64",
              "--divergence", "0.05", "--platform", "cpu"]
    srv = subprocess.Popen(
        base + ["server"] + common
        + ["--metrics-port", str(metrics_port), "--linger", "60"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    cli = subprocess.Popen(base + ["client"] + common,
                           stdout=subprocess.PIPE,
                           stderr=subprocess.PIPE, text=True)
    murl = f"http://127.0.0.1:{metrics_port}"
    text = events_doc = None
    try:
        # poll until the scrape shows the finished session: wire.sync
        # counters AND the span histograms (a scrape can race the sync
        # mid-phase, so wait for everything rather than asserting on a
        # half-told story)
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            try:
                _, text = _http_get(f"{murl}/metrics", timeout=5)
                if ("crdt_tpu_wire_sync_digest_bytes_total" in text
                        and "crdt_tpu_sync_digest_exchange_bucket" in text):
                    break
            except OSError:
                pass
            if srv.poll() is not None:
                break
            time.sleep(0.2)
        assert text is not None and \
            "crdt_tpu_wire_sync_digest_bytes_total" in text, (
                f"never saw wire.sync counters on /metrics; "
                f"server rc={srv.poll()} "
                f"stderr={(srv.stderr.read() or '')[-800:] if srv.poll() is not None else '(running)'}"
            )
        # latency histograms (spans are enabled by --metrics-port)
        assert "crdt_tpu_sync_digest_exchange_bucket" in text
        assert "crdt_tpu_sync_digest_exchange_count" in text
        # poll /events until the converged phase lands (mid-sync scrapes
        # see a prefix of the phase transitions); the server lingers
        # until both routes are scraped AFTER its sync finished, so the
        # polling itself is what eventually releases it
        while time.monotonic() < deadline:
            try:
                _, body = _http_get(f"{murl}/events?kind=sync.phase",
                                    timeout=5)
                events_doc = json.loads(body)
                if any(e["fields"]["phase"] == "converged"
                       for e in events_doc["events"]):
                    break
            except OSError:
                pass
            if srv.poll() is not None:
                break
            time.sleep(0.2)
        # release the linger: scrape both routes once more, tolerating
        # the server winning the race and exiting first
        for route in ("/metrics", "/events"):
            try:
                _http_get(f"{murl}{route}", timeout=5)
            except OSError:
                pass
    finally:
        try:
            srv.wait(timeout=120)
            cli.wait(timeout=120)
        except subprocess.TimeoutExpired:
            srv.kill()
            cli.kill()
    assert srv.returncode == 0, (srv.stderr.read() or "")[-800:]
    assert cli.returncode == 0, (cli.stderr.read() or "")[-800:]
    out = srv.stdout.read()
    assert "CONVERGED" in out
    # the printed session id matches the /events stream
    sid = next(tok.split("=", 1)[1] for tok in out.split()
               if tok.startswith("session="))
    phases = [e for e in events_doc["events"] if e.get("session") == sid]
    assert phases, f"no events for session {sid}: {events_doc['events'][:4]}"
    names = [e["fields"]["phase"] for e in phases]
    assert "digest_exchange" in names and "converged" in names
