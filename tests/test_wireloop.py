"""PipelinedWireLoop — the double-buffered wire replication loop
(`crdt_tpu/batch/wireloop.py`).

Contract under test: the loop's blobs-out are BYTE-identical to
``to_binary`` of the scalar engine's left fold + defer-plunger
self-merge over ``from_binary`` of the blobs-in, for every mode
(native/jnp fold, overlapped/serial), with reused staging buffers never
leaking state between rounds, and with the per-stage native-vs-fallback
accounting the bench JSON reports.
"""

import numpy as np
import pytest

from crdt_tpu import Orswot, from_binary, to_binary
from crdt_tpu.batch import OrswotBatch
from crdt_tpu.batch.wireloop import PipelinedWireLoop, _native_fold_engine
from crdt_tpu.config import CrdtConfig
from crdt_tpu.utils.interning import Universe
from crdt_tpu.utils.testdata import anti_entropy_fleets

_HAVE_ENGINE = _native_fold_engine() is not None


def _identity_uni(**kw):
    base = dict(num_actors=8, member_capacity=8, deferred_capacity=4,
                counter_bits=32)
    base.update(kw)
    return Universe.identity(CrdtConfig(**base))


def _fleet_blobs(uni, rng, n, r, **kw):
    cfg = uni.config
    shape = dict(base=4, novel=1, deferred_frac=0.25,
                 dtype=np.uint64 if cfg.counter_bits == 64 else np.uint32)
    shape.update(kw)
    reps = anti_entropy_fleets(
        rng, n, cfg.num_actors, cfg.member_capacity, cfg.deferred_capacity,
        r, **shape,
    )
    return [OrswotBatch(*rep).to_wire(uni) for rep in reps]


def _scalar_fold_blob(rep_blobs, i):
    acc = from_binary(rep_blobs[0][i])
    for rr in range(1, len(rep_blobs)):
        acc.merge(from_binary(rep_blobs[rr][i]))
    acc.merge(acc.clone())  # defer plunger, as the loop
    return to_binary(acc)


_FOLD_PATHS = (["native"] if _HAVE_ENGINE else []) + ["jnp"]


@pytest.mark.parametrize("fold_path", _FOLD_PATHS)
@pytest.mark.parametrize("overlap", [True, False])
def test_loop_matches_scalar_fold(fold_path, overlap):
    uni = _identity_uni(num_actors=16)
    rng = np.random.RandomState(3)
    rep_blobs = _fleet_blobs(uni, rng, 200, 4)
    loop = PipelinedWireLoop(uni, fold_path=fold_path)
    res = loop.run([rep_blobs], overlap=overlap)
    assert res["pipeline"] == ("overlapped" if overlap else "serial")
    assert res["fold_path"] == fold_path
    assert res["merges"] == 200 * 4
    assert len(res["out_blobs"]) == 200
    for i in range(0, 200, 23):
        assert res["out_blobs"][i] == _scalar_fold_blob(rep_blobs, i)


@pytest.mark.parametrize("fold_path", _FOLD_PATHS)
def test_staging_reuse_does_not_leak_between_rounds(fold_path):
    """Rounds with DIFFERENT data through one loop instance: the reused
    staging/accumulator buffers must not leak rows between rounds (this
    is the contract the native parser's self-clearing `clear` flag
    exists for)."""
    uni = _identity_uni(num_actors=16)
    loop = PipelinedWireLoop(uni, fold_path=fold_path)
    outs = {}
    for seed in (7, 8):
        rep_blobs = _fleet_blobs(uni, np.random.RandomState(seed), 64, 3)
        res = loop.run([rep_blobs], overlap=True)
        outs[seed] = (rep_blobs, res["out_blobs"])
    for seed, (rep_blobs, blobs) in outs.items():
        for i in range(64):
            assert blobs[i] == _scalar_fold_blob(rep_blobs, i), (seed, i)
    # and a denser round after a sparser one (stale high slots)
    sparse = _fleet_blobs(uni, np.random.RandomState(9), 64, 3, base=1,
                          deferred_frac=0.0)
    res = loop.run([sparse], overlap=True)
    for i in range(64):
        assert res["out_blobs"][i] == _scalar_fold_blob(sparse, i)


def test_overlapped_equals_serial_bytes():
    uni = _identity_uni()
    rep_blobs = _fleet_blobs(uni, np.random.RandomState(5), 128, 4)
    loop = PipelinedWireLoop(uni)
    a = loop.run([rep_blobs] * 2, overlap=True)["out_blobs"]
    b = loop.run([rep_blobs] * 2, overlap=False)["out_blobs"]
    assert a == b


@pytest.mark.skipif(not _HAVE_ENGINE, reason="native engine unavailable")
def test_e2e_shaped_blobs_take_native_path():
    """Regression for the round-5 ingest-collapse hypothesis: e2e-shaped
    blobs (A=64, ~7 members, deferred sections, native-encoded) must
    report native_fraction == 1.0 through the loop — the collapse was
    allocation churn, NOT a silent fallback, and this pins that the
    realistic shapes stay on the native parser."""
    uni = _identity_uni(num_actors=64, member_capacity=16,
                        deferred_capacity=2)
    rep_blobs = _fleet_blobs(
        uni, np.random.RandomState(11), 256, 8, base=6, novel=1,
        deferred_frac=0.25,
    )
    loop = PipelinedWireLoop(uni, fold_path="native")
    res = loop.run([rep_blobs], overlap=True)
    assert res["ingest_native_fraction"] == 1.0
    assert res["egress_native_fraction"] == 1.0
    assert not any(
        ".fallback_reason." in k for k in res["wire_counters"]
    ), res["wire_counters"]
    for i in range(0, 256, 37):
        assert res["out_blobs"][i] == _scalar_fold_blob(rep_blobs, i)


@pytest.mark.skipif(not _HAVE_ENGINE, reason="native engine unavailable")
def test_grammar_fallback_blob_splices_through_loop():
    """A blob outside the fast-path grammar (u64 counter >= 2^63 zigzags
    past the native varint) rides the per-blob Python splice inside the
    loop's staging parse, and the accounting shows a fractional
    native_fraction with the `grammar` reason."""
    uni = _identity_uni(counter_bits=64)
    n, r = 32, 2
    rep_blobs = _fleet_blobs(uni, np.random.RandomState(6), n, r,
                             deferred_frac=0.0)
    big = Orswot()
    big.clock.witness(1, 1 << 63)
    big.entries[5] = big.clock.clone()
    rep_blobs[0][3] = to_binary(big)
    loop = PipelinedWireLoop(uni, fold_path="native")
    res = loop.run([rep_blobs], overlap=True)
    assert res["ingest_native_fraction"] == pytest.approx(
        (n * r - 1) / (n * r)
    )
    assert res["wire_counters"][
        "wire.orswot.from_wire.fallback_reason.grammar"
    ] == 1
    assert res["out_blobs"][3] == _scalar_fold_blob(rep_blobs, 3)


def test_non_identity_universe_python_route():
    """String actors/members: no native path at all — the loop still
    produces byte-faithful output through the Python codec, and the
    counters say why."""
    uni = Universe(CrdtConfig(num_actors=4, member_capacity=4,
                              deferred_capacity=2))
    states = []
    for i in range(8):
        s = Orswot()
        s.apply(s.add(f"m{i}", s.value().derive_add_ctx("alice")))
        states.append(s)
    blobs = [to_binary(s) for s in states]
    loop = PipelinedWireLoop(uni, fold_path="jnp")
    res = loop.run([[blobs]])  # one round, one fleet
    assert res["ingest_native_fraction"] == 0.0
    assert res["egress_native_fraction"] == 0.0
    reasons = {k for k in res["wire_counters"] if ".fallback_reason." in k}
    assert any("non_identity" in k or "no_engine" in k for k in reasons)
    for i in range(8):
        acc = from_binary(blobs[i])
        acc.merge(acc.clone())
        assert res["out_blobs"][i] == to_binary(acc)


@pytest.mark.parametrize("fold_path", _FOLD_PATHS)
def test_single_replica_round_is_plunger_only(fold_path):
    uni = _identity_uni()
    rep_blobs = _fleet_blobs(uni, np.random.RandomState(2), 16, 1)
    res = PipelinedWireLoop(uni, fold_path=fold_path).run([rep_blobs])
    for i in range(16):
        acc = from_binary(rep_blobs[0][i])
        acc.merge(acc.clone())
        assert res["out_blobs"][i] == to_binary(acc)


def test_empty_rounds_and_collect_modes():
    uni = _identity_uni()
    loop = PipelinedWireLoop(uni)
    assert loop.run([])["merges"] == 0
    rep_blobs = _fleet_blobs(uni, np.random.RandomState(4), 8, 2)
    seen = []
    res = loop.run([rep_blobs] * 3, collect="all",
                   on_round=lambda i, b: seen.append(i))
    assert seen == [0, 1, 2]
    assert len(res["out_blobs"]) == 3
    assert res["out_blobs"][0] == res["out_blobs"][2]
    assert loop.run([rep_blobs], collect="none")["out_blobs"] == []
    with pytest.raises(ValueError):
        loop.run([rep_blobs], collect="bogus")


@pytest.mark.skipif(not _HAVE_ENGINE, reason="native engine unavailable")
def test_fold_overflow_raises():
    """Disjoint member sets that overflow member_capacity on the join
    must raise CapacityOverflowError, not silently truncate."""
    from crdt_tpu.error import CapacityOverflowError

    uni = _identity_uni(num_actors=4, member_capacity=2,
                        deferred_capacity=2)
    fleets = []
    for rep in range(3):
        row = []
        for i in range(4):
            s = Orswot()
            for j in range(2):  # 3 fleets x 2 distinct members > cap 2
                s.apply(s.add(rep * 2 + j,
                              s.value().derive_add_ctx(rep)))
            row.append(s)
        fleets.append([to_binary(s) for s in row])
    loop = PipelinedWireLoop(uni, fold_path="native")
    with pytest.raises(CapacityOverflowError):
        loop.run([fleets])
