"""Test harness configuration.

Forces JAX onto a virtual 8-device CPU mesh so multi-chip sharding paths
(`crdt_tpu.parallel`) are exercised without TPU hardware, and enables x64 so
counters are u64 like the reference (`/root/reference/src/vclock.rs:23`).

Must set env vars before the first ``import jax`` anywhere in the test run.
"""

import os

# FORCE cpu: the environment presets JAX_PLATFORMS=axon (a remote TPU
# tunnel) whose per-op latency makes property tests pathologically slow;
# kernels are platform-agnostic.  The site hook preloads jax before this
# conftest runs, so setting the env var is not enough — update the live
# config too.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    # registered here (not pyproject) so the marker set lives next to the
    # harness that polices it.  `sync` tags the delta anti-entropy suite —
    # deliberately NOT `slow`, so the tier-1 command (`-m 'not slow'`)
    # picks the sync tests up without marker collisions; `slow` stays the
    # opt-out for long property soaks.
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from the tier-1 run"
    )
    config.addinivalue_line(
        "markers",
        "sync: digest/delta anti-entropy subsystem tests (crdt_tpu.sync)",
    )
    config.addinivalue_line(
        "markers",
        "obs: observability subsystem tests (crdt_tpu.obs — metrics "
        "registry, flight recorder, exporter); tier-1 like `sync`",
    )
    config.addinivalue_line(
        "markers",
        "analysis: crdtlint static-analysis tests (crdt_tpu.analysis — "
        "rule engine, fixtures, and the repo-wide lint gate); tier-1, "
        "jax-free",
    )
    config.addinivalue_line(
        "markers",
        "cluster: cluster-runtime tests (crdt_tpu.cluster — transports, "
        "membership, gossip scheduler, fault injection); tier-1 like "
        "`sync`",
    )
    config.addinivalue_line(
        "markers",
        "oplog: op-based write front-end tests (crdt_tpu.oplog — "
        "columnar op log, batched causal contexts, scatter-fold apply, "
        "op-frame codec); tier-1 like `sync`",
    )
    config.addinivalue_line(
        "markers",
        "gc: causal garbage-collection tests (crdt_tpu.gc — fleet "
        "low-watermark clocks, compaction kernels, plane re-packing, "
        "GC policy); tier-1 like `sync`",
    )
    config.addinivalue_line(
        "markers",
        "durable: durability tests (crdt_tpu.durable — snapshot store, "
        "op-log WAL, crash-recovery rejoin, fault injection); tier-1 "
        "like `sync`",
    )
    config.addinivalue_line(
        "markers",
        "stability: convergence-observatory tests (crdt_tpu.obs."
        "stability — divergence aging, the fleet stability frontier, "
        "the runtime lattice auditor); tier-1 like `sync`",
    )
    config.addinivalue_line(
        "markers",
        "serve: batched read front-end tests (crdt_tpu.serve — gather "
        "kernels, session-consistency admission, read frame codec, "
        "serve loop); tier-1 like `sync`",
    )
    config.addinivalue_line(
        "markers",
        "heat: heat & placement observatory tests (crdt_tpu.obs.heat — "
        "subtree traffic attribution, the top-k/Zipf sketch, the "
        "placement planner, the /heat route); tier-1 like `sync`",
    )
    config.addinivalue_line(
        "markers",
        "mesh: mesh-sharded fleet tests (crdt_tpu.mesh — shard layout, "
        "the one-step pjit'd anti-entropy round, shard-subset sync, "
        "per-shard snapshots, the runtime contract gate); tier-1 like "
        "`sync`, runs on the forced 8-device CPU mesh",
    )


# -- jax 0.4.x Pallas/Mosaic version gate ------------------------------------
#
# The Mosaic kernel suites fail wholesale under jax 0.4.x: i64 scalars
# lowering into the interpret-mode Pallas kernels recurse forever in
# Mosaic's int64→int32 truncation (ROADMAP "jax 0.4.x Pallas skew"; the
# PR 2 compat shims recovered the collectives/executor suites but not
# the kernels themselves).  The kernels now gate this THEMSELVES: the
# entry points call `crdt_tpu.config.pallas_mosaic_skew()` and raise a
# typed `UnsupportedBackendError` with a remediation message instead of
# failing deep in Mosaic — and this harness keys its xfail marking off
# the SAME predicate, so the test gate and the runtime gate can never
# drift.  A THIRD gate hangs off the same predicate: kernelcheck's KC01
# (jaxpr tier, `python -m crdt_tpu.analysis --kernels`) proves the
# Mosaic kernels are 64-bit-clean at the trace level, records
# `pallas_mosaic_skew()` as its `skew_reason`, and re-flags any KC01
# pragma as a stale sanction the moment the skew lifts — so this xfail
# can only ever cover the version skew, never real 64-bit content
# (cross-check pinned in tests/test_kernelcheck.py::
# test_kc01_agrees_with_conftest_skew_gate).  xfail — NOT skip — so the
# tier-1 output distinguishes "known skew" (x) from a new regression,
# and a jax>=0.5 box runs the full suite ungated.  The exempt tests never enter a Mosaic kernel (u64
# rejection / dispatch selection) and pass on 0.4.x; they stay live so
# the gate can't mask regressions in the dispatch/rejection logic.

_MOSAIC_SKEW_FILES = ("test_orswot_pallas.py", "test_orswot_fold_aligned.py")
_MOSAIC_SKEW_EXEMPT_PREFIXES = (
    "test_u64_counters_rejected",
    "test_ops_fold_merge_dispatch_parity[rank]",
    "test_ops_fold_merge_pallas_u64_degrades_to_sequential",
    # the gate's own pin: asserts UnsupportedBackendError surfaces (with
    # its remediation text) instead of a deep Mosaic failure, so it must
    # PASS exactly where the rest of the suite xfails
    "test_mosaic_skew_gate_raises_typed_error",
)


def _mosaic_skew():
    """The kernel-side gate's reason string (None when the jax version
    is fine) — conftest marks xfails with the SAME text the runtime
    error carries."""
    from crdt_tpu.config import pallas_mosaic_skew

    return pallas_mosaic_skew()


# -- CPU-backend multiprocess gate -------------------------------------------
#
# The two-OS-process Gloo tests (`test_multihost_mp.py`) need XLA's
# cross-process collectives, which the CPU backend does not implement
# ("Multiprocess computations aren't implemented on the CPU backend") —
# and this harness forces JAX_PLATFORMS=cpu (see the top of this file).
# Gate them as xfail — NOT skip — the same way as the Mosaic skews: the
# tier-1 output shows 'x' for the known backend limitation, a real TPU/
# GPU box runs them ungated, and an unexpected pass (the backend grew
# the feature) surfaces as XPASS instead of being silently skipped.

_MULTIHOST_MP_FILE = "test_multihost_mp.py"
_MULTIHOST_MP_REASON = (
    "known CPU-backend limitation: XLA multiprocess collectives are "
    "not implemented on the CPU backend, and the test harness forces "
    "JAX_PLATFORMS=cpu; not a regression — runs ungated on TPU/GPU"
)


def pytest_collection_modifyitems(config, items):
    import pytest

    skew = _mosaic_skew()
    if skew is not None:
        marker = pytest.mark.xfail(
            reason=f"known jax 0.4.x Pallas/Mosaic skew (gated as "
                   f"UnsupportedBackendError by the kernels): {skew}",
            strict=False,
        )
        for item in items:
            if item.fspath.basename not in _MOSAIC_SKEW_FILES:
                continue
            if item.name.startswith(_MOSAIC_SKEW_EXEMPT_PREFIXES):
                continue
            item.add_marker(marker)

    if jax.default_backend() == "cpu":
        marker = pytest.mark.xfail(reason=_MULTIHOST_MP_REASON,
                                   strict=False)
        for item in items:
            if item.fspath.basename == _MULTIHOST_MP_FILE:
                item.add_marker(marker)

# hypothesis is an optional dependency of the property suites only: on
# boxes without it the non-property tests must still collect and run, so
# the import is gated and the @given modules are ignored rather than
# erroring the whole session.
try:
    from hypothesis import HealthCheck, settings
except ModuleNotFoundError:
    import pathlib
    import re

    collect_ignore = sorted(
        p.name
        for p in pathlib.Path(__file__).parent.glob("test_*.py")
        if re.search(r"^\s*(from|import) hypothesis", p.read_text(), re.M)
    )
else:
    # quickcheck's default is 100 cases per property (SURVEY.md §6); mirror
    # that.  CRDT_HYP_EXAMPLES overrides for soak runs (e.g. 500 for a deep
    # pass).
    try:
        _max_examples = int(os.environ.get("CRDT_HYP_EXAMPLES", "100"))
    except ValueError:
        import warnings

        warnings.warn("CRDT_HYP_EXAMPLES is not an int; using 100")
        _max_examples = 100
    settings.register_profile(
        "crdt",
        max_examples=_max_examples,
        deadline=None,
        suppress_health_check=[HealthCheck.too_slow],
    )
    settings.load_profile("crdt")


def assert_no_collectives(hlo: str, what: str) -> None:
    """Assert a compiled HLO moves no cross-device traffic — the
    zero-collective claim shared by the shard-local merge/truncate and
    member-sharding tests.  One home for the op-name list so new
    collective ops get covered everywhere at once."""
    for collective in (
        "all-gather", "all-reduce", "collective-permute", "all-to-all",
        "ragged-all-to-all", "reduce-scatter",
    ):
        assert collective not in hlo, f"{what} emitted {collective}"
