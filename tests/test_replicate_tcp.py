"""The TCP replication example as an end-to-end test.

Two real OS processes, each a replica with its own actor and op
history, exchange full state over a localhost socket via the native
bulk wire codec and must converge to identical value() digests — the
framework's analogue of the reference's simulated-replica convergence
tests (`/root/reference/test/orswot.rs:37-76`), but over an actual
transport boundary.
"""

import os
import subprocess
import sys

import pytest


@pytest.mark.parametrize("n_objects", [64, 256])
def test_tcp_demo_converges(n_objects):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(repo, "examples", "replicate_tcp.py"),
            "--platform", "cpu",
            "--objects", str(n_objects),
        ],
        capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    assert "demo: CONVERGED" in proc.stdout
    assert "DIVERGED" not in proc.stdout
