"""The TCP replication example as an end-to-end test.

Two real OS processes, each a replica with its own actor and op
history, exchange full state over a localhost socket via the native
bulk wire codec and must converge to identical value() digests — the
framework's analogue of the reference's simulated-replica convergence
tests (`/root/reference/test/orswot.rs:37-76`), but over an actual
transport boundary.
"""

import os
import subprocess
import sys

import pytest


@pytest.mark.parametrize("n_objects", [64, 256])
def test_tcp_demo_converges(n_objects):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(repo, "examples", "replicate_tcp.py"),
            "--platform", "cpu",
            "--objects", str(n_objects),
        ],
        capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    assert "demo: CONVERGED" in proc.stdout
    assert "DIVERGED" not in proc.stdout


@pytest.mark.sync
@pytest.mark.parametrize("mode", ["delta", "full-state"])
def test_tcp_sync_modes_converge_identically(mode):
    """Two-process round trip in both protocol modes: the delta session
    and the legacy full-state exchange must both converge, and the
    delta mode must actually ship deltas (not fall back to full
    frames)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    args = [
        sys.executable,
        os.path.join(repo, "examples", "replicate_tcp.py"),
        "--platform", "cpu",
        "--objects", "200",
        "--divergence", "0.05",
    ]
    if mode == "full-state":
        args.append("--full-state")
    proc = subprocess.run(args, capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stderr[-800:]
    assert "demo: CONVERGED" in proc.stdout
    if mode == "delta":
        # both peers shipped a delta frame and no full-state frame
        for line in proc.stdout.splitlines():
            if "mode=delta" in line:
                assert "full=0B" in line, line
                assert "delta_objects=10" in line, line
    else:
        assert "mode=full-state" in proc.stdout


@pytest.mark.durable
def test_tcp_gossip_durable_kill9_recovers_and_converges(tmp_path):
    """The --durable demo end-to-end: a 3-peer gossip fleet with
    snapshot+WAL durability, node n1 killed -9 mid-run (listener
    closed, state dropped), restored from disk, rejoined via delta
    sync — the demo asserts zero full-state frames itself; here we
    assert the printed recovery evidence and convergence."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(repo, "examples", "replicate_tcp.py"),
            "--platform", "cpu",
            "--gossip", "3",
            "--objects", "48",
            "--ops", "10",
            "--durable", str(tmp_path),
        ],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-1500:]
    assert "kill: n1 killed -9" in proc.stdout
    assert "recovery: n1 restored generation" in proc.stdout
    assert "full-state fallbacks=0" in proc.stdout
    assert "CONVERGED" in proc.stdout
