"""Member-universe sharding (SURVEY.md §5's context-parallel analogue):
huge sets hash-partitioned across a mesh axis, merged shard-locally,
clocks joined globally — bit-equal to the scalar oracle.

Reference semantics being preserved: `/root/reference/src/orswot.rs:89-156`
(merge) and `orswot.rs:195-211` (deferred removes).
"""

import numpy as np
import pytest

from conftest import assert_no_collectives

import jax

from crdt_tpu.batch import OrswotBatch
from crdt_tpu.config import CrdtConfig
from crdt_tpu.parallel.member_sharding import (
    member_sharded_merge,
    partition_dense,
    rebroadcast_clock,
    sharded_apply_add,
    unpartition_dense,
)
from crdt_tpu.parallel.mesh import make_mesh
from crdt_tpu.scalar.orswot import Orswot
from crdt_tpu.utils.interning import Universe

N_SHARDS = 8
M_CAP = 64          # logical member capacity (exceeds any single shard's)
M_CAP_SHARD = 16    # per-device member table — 40-member sets don't fit one
D_CAP = 8
D_CAP_SHARD = 4


def big_universe():
    return Universe(
        CrdtConfig(num_actors=8, member_capacity=M_CAP, deferred_capacity=D_CAP)
    )


def build_replicas(seed, n_members=40, n_objects=4):
    """Two replica fleets of sets whose member count exceeds M_CAP_SHARD."""
    rng = np.random.RandomState(seed)
    fleets = [[], []]
    for _ in range(n_objects):
        base = [int(x) for x in rng.choice(1 << 16, size=n_members, replace=False)]
        for f in range(2):
            s = Orswot()
            for m in base:
                if rng.rand() < 0.8:  # each replica has most members
                    actor = int(rng.randint(0, 8))
                    ctx = s.value().derive_add_ctx(actor)
                    s.apply(s.add(m, ctx))
            # a few causal removes
            for m in base[:3]:
                if m in s.value().val and rng.rand() < 0.5:
                    s.apply(s.remove(m, s.contains(m).derive_rm_ctx()))
            fleets[f].append(s)
    return fleets


def to_sharded(states, uni, mesh):
    batch = OrswotBatch.from_scalar(states, uni)
    parts = partition_dense(
        batch.clock, batch.ids, batch.dots, batch.d_ids, batch.d_clocks,
        N_SHARDS, M_CAP_SHARD, D_CAP_SHARD,
    )
    from crdt_tpu.parallel.mesh import shard_batch  # noqa: F401  (spec helper below)
    from jax.sharding import NamedSharding, PartitionSpec as P

    put = lambda x: jax.device_put(
        jax.numpy.asarray(x), NamedSharding(mesh, P("members"))
    )
    return tuple(put(x) for x in parts)


def from_sharded(state, uni):
    arrays = unpartition_dense(*state, m_cap=M_CAP, d_cap=D_CAP)
    import jax.numpy as jnp

    return OrswotBatch(*(jnp.asarray(x) for x in arrays)).to_scalar(uni)


def scalar_merge(a_states, b_states):
    out = []
    for a, b in zip(a_states, b_states):
        m = a.clone()
        m.merge(b)
        out.append(m)
    return out


def test_huge_set_merge_matches_scalar_oracle():
    """A set larger than one device's member table merges bit-equal to the
    scalar reference across a member-sharded mesh."""
    mesh = make_mesh({"members": N_SHARDS})
    uni = big_universe()
    fleet_a, fleet_b = build_replicas(seed=11)
    assert max(len(s.entries) for s in fleet_a) > M_CAP_SHARD  # genuinely huge

    sharded_a = to_sharded(fleet_a, uni, mesh)
    sharded_b = to_sharded(fleet_b, uni, mesh)
    merged = member_sharded_merge(sharded_a, sharded_b, mesh, "members")
    got = from_sharded(merged, uni)
    want = scalar_merge(fleet_a, fleet_b)
    for g, w in zip(got, want):
        assert g.value().val == w.value().val
        assert g.clock == w.clock
        assert g.entries == w.entries


def test_partition_roundtrip_identity():
    mesh = make_mesh({"members": N_SHARDS})
    uni = big_universe()
    fleet_a, _ = build_replicas(seed=13, n_objects=2)
    batch = OrswotBatch.from_scalar(fleet_a, uni)
    parts = partition_dense(
        batch.clock, batch.ids, batch.dots, batch.d_ids, batch.d_clocks,
        N_SHARDS, M_CAP_SHARD, D_CAP_SHARD,
    )
    back = unpartition_dense(*parts, m_cap=M_CAP, d_cap=D_CAP)
    import jax.numpy as jnp

    restored = OrswotBatch(*(jnp.asarray(x) for x in back)).to_scalar(uni)
    for r, s in zip(restored, fleet_a):
        assert r == s


def test_deferred_remove_routes_and_resolves_across_shards():
    """A causally-future remove buffers on the owning member's shard and
    resolves once a merge brings the covering clock — the `orswot.rs:195-211`
    dance, shard-locally."""
    mesh = make_mesh({"members": N_SHARDS})
    uni = big_universe()

    # replica A: many members incl. the victim, with a clock the remover
    # hasn't seen; replica B: a fresh state carrying only a future remove
    a = Orswot()
    members = list(range(100, 140))
    for m in members:
        a.apply(a.add(m, a.value().derive_add_ctx("w1")))
    victim = members[5]

    # build the future remove against a *later* state of A
    a_future = a.clone()
    a_future.apply(a_future.add(999, a_future.value().derive_add_ctx("w2")))
    rm = a_future.remove(victim, a_future.contains(victim).derive_rm_ctx())

    b = Orswot()
    b.apply(rm)  # clock ahead of b's state ⇒ defers
    assert b.deferred

    want = a_future.clone()
    want.merge(b)
    want.merge(Orswot())  # plunger

    sharded_a = to_sharded([a_future], uni, mesh)
    sharded_b = to_sharded([b], uni, mesh)
    merged = member_sharded_merge(sharded_a, sharded_b, mesh, "members")
    empty = to_sharded([Orswot()], uni, mesh)
    merged = member_sharded_merge(merged, empty, mesh, "members")
    got = from_sharded(merged, uni)[0]
    assert victim not in got.value().val
    assert got.value().val == want.value().val
    assert got.entries == want.entries


def test_sharded_apply_add_then_merge_coherent():
    """Adds route to the owning shard; after the clock rebroadcast the
    sharded state merges identically to the scalar op path."""
    mesh = make_mesh({"members": N_SHARDS})
    uni = big_universe()
    for i in range(4):
        uni.actors.intern(i)

    s = Orswot()
    for m in range(200, 230):
        s.apply(s.add(m, s.value().derive_add_ctx(0)))
    sharded = to_sharded([s], uni, mesh)

    # one add per object (N=1): actor 1 adds member 777
    want = s.clone()
    ctx = want.value().derive_add_ctx(1)
    want.apply(want.add(777, ctx))

    actor_idx = np.array([uni.actors.intern(1)], dtype=np.int32)
    counter = np.asarray([ctx.dot.counter], dtype=np.asarray(sharded[0]).dtype)
    member_id = np.array([uni.members.intern(777)], dtype=np.int32)
    out = sharded_apply_add(
        sharded, jax.numpy.asarray(actor_idx), jax.numpy.asarray(counter),
        jax.numpy.asarray(member_id), mesh, "members",
    )
    got = from_sharded(out, uni)[0]
    assert got.value().val == want.value().val
    assert got.clock == want.clock

    # clock copies are coherent on every shard after rebroadcast
    clocks = np.asarray(out[0])
    for sh in range(1, N_SHARDS):
        np.testing.assert_array_equal(clocks[0], clocks[sh])


def test_apply_add_coherent_with_multiple_shard_rows_per_device():
    """n_shards > mesh size (K=2 shard rows per device): the clock
    rebroadcast must join across co-located rows too, not just
    row-for-row across devices."""
    mesh = make_mesh({"members": 4}, devices=jax.devices()[:4])  # 8 shards / 4 devices
    uni = big_universe()
    for i in range(4):
        uni.actors.intern(i)

    s = Orswot()
    for m in range(300, 330):
        s.apply(s.add(m, s.value().derive_add_ctx(0)))
    sharded = to_sharded([s], uni, mesh)

    want = s.clone()
    ctx = want.value().derive_add_ctx(1)
    want.apply(want.add(777, ctx))

    actor_idx = np.array([uni.actors.intern(1)], dtype=np.int32)
    counter = np.asarray([ctx.dot.counter], dtype=np.asarray(sharded[0]).dtype)
    member_id = np.array([uni.members.intern(777)], dtype=np.int32)
    out = sharded_apply_add(
        sharded, jax.numpy.asarray(actor_idx), jax.numpy.asarray(counter),
        jax.numpy.asarray(member_id), mesh, "members",
    )
    got = from_sharded(out, uni)[0]
    assert got.value().val == want.value().val
    assert got.clock == want.clock
    clocks = np.asarray(out[0])
    for sh in range(1, N_SHARDS):
        np.testing.assert_array_equal(clocks[0], clocks[sh])


def test_member_sharded_merge_emits_no_collectives():
    """The merge itself is provably shard-local (the collective lives only
    in rebroadcast_clock / value materialization)."""
    mesh = make_mesh({"members": N_SHARDS})
    uni = big_universe()
    fleet_a, fleet_b = build_replicas(seed=17, n_objects=2)
    sharded_a = to_sharded(fleet_a, uni, mesh)
    sharded_b = to_sharded(fleet_b, uni, mesh)

    import functools

    from crdt_tpu.parallel._compat import shard_map
    from jax.sharding import PartitionSpec as P

    from crdt_tpu.ops import orswot_ops

    spec = P("members")

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=((spec,) * 5, (spec,) * 5),
        out_specs=(spec,) * 5,
        check_vma=False,
    )
    def _local(sa, sb):
        return orswot_ops.merge(*sa, *sb, M_CAP_SHARD, D_CAP_SHARD)[:5]

    hlo = _local.lower(tuple(sharded_a), tuple(sharded_b)).compile().as_text()
    assert_no_collectives(hlo, "member-sharded merge")
