"""Kill -9 acceptance soak — durable replicas under user-shaped churn.

The ISSUE 12 acceptance bar: a 3-node gossip fleet with durability ON
(WAL-ahead ingest, checkpoint cadence at round end, causal GC running
between sessions) takes Zipf/burst write traffic
(:class:`crdt_tpu.utils.workload.WorkloadGen` — the ROADMAP carried
item: soak numbers run against user-shaped keys, not uniform sprays);
a node is killed -9 mid-gossip through the :mod:`crdt_tpu.cluster.
faults` crash points; the survivors keep writing; the dead node
restores from snapshot + WAL, rejoins through normal delta sync, and
the fleet converges to byte-identical digest vectors with ZERO
full-state frames shipped during the rejoin.  A torn newest snapshot
(short-write disk fault) must reject loudly and fall back to the
previous generation — and still converge.
"""

import threading

import numpy as np
import pytest

from crdt_tpu.batch import OrswotBatch
from crdt_tpu.cluster import (
    ClusterNode, CrashPlan, GossipScheduler, InjectedCrash, Membership,
    TornWriter, arm_crashes, disarm_crashes, queue_pair,
)
from crdt_tpu.config import CrdtConfig
from crdt_tpu.durable import Durability, recover
from crdt_tpu.durable.snapshot import default_writer
from crdt_tpu.error import PeerUnavailableError
from crdt_tpu.gc import GcEngine, GcPolicy
from crdt_tpu.obs import convergence as obs_convergence
from crdt_tpu.oplog import OpLog
from crdt_tpu.scalar.orswot import Orswot
from crdt_tpu.utils import tracing
from crdt_tpu.utils.interning import Universe
from crdt_tpu.utils.workload import WorkloadGen

pytestmark = [pytest.mark.durable, pytest.mark.slow]

N_OBJECTS = 32
N_NODES = 3
EPOCHS = 4
WRITES_PER_EPOCH = 6


def _fleet(tmp_path, torn_writer_for=None):
    uni = Universe.identity(CrdtConfig(
        num_actors=8, member_capacity=64, deferred_capacity=8,
        counter_bits=32))
    states = []
    for _ in range(N_OBJECTS):
        s = Orswot()
        for m in range(4):
            s.apply(s.add(m, s.value().derive_add_ctx(0)))
        states.append(s)
    base = OrswotBatch.from_scalar(states, uni)

    nodes = []
    for i in range(N_NODES):
        writer = None
        if torn_writer_for is not None and i == torn_writer_for[0]:
            writer = torn_writer_for[1]
        nodes.append(ClusterNode(
            f"n{i}", base, uni, busy_timeout_s=5.0,
            oplog=OpLog(uni, capacity=1 << 16),
            gc=GcEngine(GcPolicy(interval_rounds=1)),
            durability=Durability(tmp_path / f"n{i}", interval_rounds=1,
                                  retain=2, writer=writer),
        ))
    return uni, nodes


def _scheds(nodes, seed_base=0):
    def make_dialer(i):
        def dial(peer):
            j = int(peer.peer_id[1:])
            if nodes[j] is None:
                raise PeerUnavailableError(f"n{j} is down (killed)")
            ta, tb = queue_pair(default_timeout=10.0)

            def serve(target=nodes[j], label=f"n{i}"):
                try:
                    target.accept(tb, peer_id=label)
                except InjectedCrash:
                    raise
                except Exception:
                    pass
                finally:
                    tb.close()

            threading.Thread(target=serve, daemon=True).start()
            return ta
        return dial

    scheds = []
    for i in range(N_NODES):
        m = Membership(suspect_after=3, dead_after=8)
        for j in range(N_NODES):
            if j != i:
                m.add(f"n{j}")
        scheds.append(GossipScheduler(
            nodes[i], m, make_dialer(i), fanout=2,
            session_timeout_s=30.0, seed=seed_base + i,
        ))
    return scheds


def _converge(nodes, scheds, max_sweeps=8):
    for _ in range(max_sweeps):
        for i, sched in enumerate(scheds):
            if nodes[i] is not None:
                sched.run_round()
        digests = [n.digest() for n in nodes if n is not None]
        if all(np.array_equal(digests[0], d) for d in digests[1:]):
            return digests
    raise AssertionError("fleet failed to converge within the sweep budget")


def _inject(gen, nodes, epoch, next_member):
    """One epoch of user-shaped writes: Zipf/burst object keys onto
    live nodes round-robin, fresh member ids per write."""
    keys = gen.draw(WRITES_PER_EPOCH)
    live = [n for n in nodes if n is not None]
    for k, obj in enumerate(keys):
        node = live[k % len(live)]
        node.submit_writes([int(obj)], [next_member + k],
                           actor=int(node.node_id[1:]) + 1)
    return next_member + len(keys)


def _kill_mid_checkpoint(nodes, scheds):
    """The kill lands at n1's round-end checkpoint — after its
    sessions ran, between the WAL capture and the snapshot write."""
    arm_crashes(CrashPlan(at={"durable.checkpoint.n1": 1}))
    try:
        with pytest.raises(InjectedCrash):
            for _ in range(4):
                scheds[1].run_round()
    finally:
        disarm_crashes()


def _kill_mid_session(nodes, scheds):
    """The kill lands right after n1 takes its busy lock for an
    anti-entropy session — mid-gossip in the narrowest sense."""
    ta, tb = queue_pair(default_timeout=10.0)

    def serve():
        try:
            nodes[0].accept(tb, peer_id="n1")
        except Exception:
            pass  # the peer vanished mid-hello — expected
        finally:
            tb.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    arm_crashes(CrashPlan(at={"cluster.session.n1": 1}))
    try:
        with pytest.raises(InjectedCrash):
            nodes[1].sync_with("n0", ta)
    finally:
        disarm_crashes()
        ta.close()
        t.join(timeout=10)


def _kill_mid_fold(nodes, scheds):
    """The kill lands after n1 drained its in-memory op log but before
    the fold — the drained ops exist only in the WAL."""
    arm_crashes(CrashPlan(at={"oplog.fold.n1": 1}))
    try:
        with pytest.raises(InjectedCrash):
            nodes[1].submit_writes([0, 1], [90, 91], actor=2)
    finally:
        disarm_crashes()


def _run_soak(tmp_path, kill, torn_writer_for=None):
    try:
        return _run_soak_inner(tmp_path, kill, torn_writer_for)
    finally:
        # the tracker is process-global; a later gossip test's round-
        # health gauges must not fold this fleet's peer entries in
        obs_convergence.tracker().reset()


def _run_soak_inner(tmp_path, kill, torn_writer_for=None):
    obs_convergence.tracker().reset()
    uni, nodes = _fleet(tmp_path, torn_writer_for=torn_writer_for)
    scheds = _scheds(nodes)
    gen = WorkloadGen(N_OBJECTS, seed=99, zipf_s=1.1, burst_len=2)
    next_member = 1000

    # warm epochs: traffic + gossip + GC + checkpoints on every node
    for epoch in range(EPOCHS):
        next_member = _inject(gen, nodes, epoch, next_member)
        _converge(nodes, scheds)
    for node in nodes:
        assert node.durability.snapshots_written >= 1, node.node_id

    # kill -9 node 1 mid-gossip through its node-scoped crash point
    next_member = _inject(gen, nodes, EPOCHS, next_member)
    kill(nodes, scheds)
    dead_dir = tmp_path / "n1"
    nodes[1] = None  # nothing cleans up — that is the point

    # the fleet keeps taking writes while n1 is down
    for epoch in range(2):
        next_member = _inject(gen, nodes, EPOCHS + 1 + epoch, next_member)
        _converge(nodes, scheds)

    # restore + rejoin: snapshot -> root verify -> WAL replay -> delta
    fallbacks_before = tracing.counters().get("sync.full_state_fallback", 0)
    full_bytes_before = tracing.counters().get("wire.sync.full.bytes", 0)
    rec = recover(dead_dir)
    assert rec is not None
    engine = GcEngine(GcPolicy(interval_rounds=1))
    if rec.watermark is not None:
        # resume GC's stability frontier from the persisted clock
        engine.restore_watermark(rec.watermark)
    nodes[1] = ClusterNode(
        "n1", rec.batch, rec.universe, busy_timeout_s=5.0,
        oplog=OpLog(rec.universe, capacity=1 << 16),
        applier=rec.applier, gc=engine,
        durability=Durability(dead_dir, interval_rounds=1, retain=2))
    scheds[1] = _scheds(nodes, seed_base=10)[1]

    digests = _converge(nodes, scheds)
    assert all(np.array_equal(digests[0], d) for d in digests[1:])
    # zero full-state frames shipped during the rejoin
    assert tracing.counters().get(
        "sync.full_state_fallback", 0) == fallbacks_before
    assert tracing.counters().get(
        "wire.sync.full.bytes", 0) == full_bytes_before
    return rec


def test_durable_soak_kill9_mid_checkpoint_rejoin_delta_only(tmp_path):
    rec = _run_soak(tmp_path, _kill_mid_checkpoint)
    # the recovery audit trail is populated
    assert rec.report.generation >= 1
    assert rec.report.wall_s > 0


def test_durable_soak_kill9_mid_session_rejoin(tmp_path):
    """The mid-session kill shape: the crash fires right after the
    busy lock is taken for an anti-entropy session."""
    rec = _run_soak(tmp_path, _kill_mid_session)
    assert rec.report.generation >= 1


def test_durable_soak_torn_snapshot_falls_back_and_converges(tmp_path):
    """Short-write disk fault on n1's LAST checkpoint before a
    mid-fold kill: recovery must reject the torn generation loudly,
    fall back to the previous one, and the fleet must still converge
    delta-only (the WAL + delta sync cover the difference — the WAL
    retains frames back to the OLDEST retained generation precisely
    for this fallback)."""
    writer = TornWriter(default_writer, at_write=1 << 30, keep_frac=0.5)

    def kill(nodes, scheds):
        # tear n1's NEXT checkpoint — its newest generation is then a
        # short write on disk — and kill it mid-fold right after
        writer.at_write = writer.calls + 1
        assert nodes[1].checkpoint() is not None
        assert writer.injected == 1
        _kill_mid_fold(nodes, scheds)

    before = tracing.counters()
    rejected_before = sum(
        v for k, v in before.items()
        if k.startswith("durable.snapshot.rejected."))
    fallback_before = before.get("durable.snapshot.fallbacks", 0)
    rec = _run_soak(tmp_path, kill, torn_writer_for=(1, writer))
    assert writer.injected == 1
    after = tracing.counters()
    assert sum(
        v for k, v in after.items()
        if k.startswith("durable.snapshot.rejected.")) > rejected_before
    assert after.get("durable.snapshot.fallbacks", 0) > fallback_before
    assert rec.report.generation >= 1
