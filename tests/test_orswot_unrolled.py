"""Parity: the unrolled ORSWOT merge vs the production rank path.

``crdt_tpu.ops.orswot_unrolled.merge_unrolled`` (the TPU default since
the round-3 on-chip layout A/B — `reports/LAYOUT_AB_TPU.md`) must be
bit-identical to ``orswot_ops.merge``'s rank pipeline, which is itself
bit-exact against the scalar engine (``tests/test_parity.py``) and
thereby the reference (`/root/reference/src/orswot.rs:89-156`).
Deferred-bearing states are included: ``random_orswot_arrays(
deferred_frac=...)`` plants causally-future remove rows, so the replay
path is exercised, not just the fast path.
"""

import functools

import numpy as np
import pytest

import jax as _jax
import jax.numpy as jnp
from hypothesis import given, settings
from hypothesis import strategies as st

from crdt_tpu.ops import orswot_ops, orswot_unrolled
from crdt_tpu.utils.testdata import random_orswot_arrays


def _pair(rng, n, a, m, d, deferred_frac=0.0):
    lhs = tuple(
        jnp.asarray(x)
        for x in random_orswot_arrays(
            rng, n, a, m, d, np.uint32, deferred_frac=deferred_frac
        )
    )
    rhs = tuple(
        jnp.asarray(x)
        for x in random_orswot_arrays(
            rng, n, a, m, d, np.uint32, deferred_frac=deferred_frac
        )
    )
    return lhs, rhs


def _assert_same(ref, got):
    """Bit-equality on every object the production path doesn't flag as
    overflowed.  ``orswot_ops`` counts member survivors *pre*-replay (the
    conservative contract — the host discards flagged objects and
    regrows), while the unrolled tile math replays before compaction and
    only overflows when the *post*-replay survivors exceed capacity, so
    on ref-flagged objects the two legitimately diverge; everywhere else
    they must agree exactly, and the unrolled flag must never fire where
    the conservative one didn't."""
    ref_over = np.asarray(ref[5])
    got_over = np.asarray(got[5])
    ok = ~ref_over.any(axis=-1)
    assert not (got_over & ~ref_over).any(), "unrolled overflow without ref overflow"
    names = ("clock", "ids", "dots", "d_ids", "d_clocks")
    for name, r, g in zip(names, ref[:5], got[:5]):
        np.testing.assert_array_equal(
            np.asarray(r)[ok], np.asarray(g)[ok], err_msg=name
        )


@pytest.mark.parametrize("deferred_frac", [0.0, 0.4])
@pytest.mark.parametrize("shape", [(17, 4, 3, 2), (33, 8, 4, 2), (21, 16, 8, 4)])
def test_unrolled_merge_parity(shape, deferred_frac):
    n, a, m, d = shape
    rng = np.random.RandomState(11)
    lhs, rhs = _pair(rng, n, a, m, d, deferred_frac)
    _assert_same(
        orswot_ops.merge(*lhs, *rhs, m, d),
        orswot_unrolled.merge_unrolled(*lhs, *rhs, m, d),
    )


def test_merge_impl_dispatch(monkeypatch):
    """The explicit ``impl=`` argument routes orswot_ops.merge to each
    variant — no env vars, no jit-cache clearing (VERDICT r3 weak #4);
    all implementations agree on non-overflow objects, including
    stacked (rank > 2) batches — the tile math is rank-polymorphic."""
    rng = np.random.RandomState(23)
    lhs, rhs = _pair(rng, 19, 4, 3, 2, deferred_frac=0.3)
    outs = {}
    for impl in ("rank", "unrolled", "pallas"):
        # pallas: 2-D batch dispatch to the fused kernel (interpret-mode
        # emulation on the CPU test backend)
        outs[impl] = orswot_ops.merge(*lhs, *rhs, 3, 2, impl=impl)
    _assert_same(outs["rank"], outs["unrolled"])
    _assert_same(outs["rank"], outs["pallas"])

    # rank > 2 (e.g. the tree fold's [R/2, N, ...] batches)
    stacked_l = tuple(jnp.stack([x, x]) for x in lhs)
    stacked_r = tuple(jnp.stack([x, x]) for x in rhs)
    got = orswot_ops.merge(*stacked_l, *stacked_r, 3, 2, impl="unrolled")
    want = orswot_ops.merge(*stacked_l, *stacked_r, 3, 2, impl="rank")
    _assert_same(want, got)

    # unknown impl names error instead of silently picking a variant
    # (the deleted lanes-last variant must now be rejected too) — both
    # through the explicit argument and the env-var override
    for bad in ("lanes", "nway"):
        with pytest.raises(ValueError, match="CRDT_MERGE_IMPL"):
            orswot_ops.merge(*lhs, *rhs, 3, 2, impl=bad)
        monkeypatch.setenv("CRDT_MERGE_IMPL", bad)
        with pytest.raises(ValueError, match="CRDT_MERGE_IMPL"):
            orswot_ops.merge(*lhs, *rhs, 3, 2)
        monkeypatch.delenv("CRDT_MERGE_IMPL")

    # an explicit impl beats a conflicting env var (config wins; the env
    # var only fills the "auto" default).  The env value is INVALID, so
    # if the env were consulted despite the explicit arg this would raise
    # — rank/unrolled outputs agree on these inputs, so comparing outputs
    # alone could not pin the precedence.
    monkeypatch.setenv("CRDT_MERGE_IMPL", "lanes")
    _assert_same(outs["rank"], orswot_ops.merge(*lhs, *rhs, 3, 2, impl="rank"))
    monkeypatch.delenv("CRDT_MERGE_IMPL")

    # pallas on a rank>2 batch falls through to a non-pallas path
    # (the pallas_call grid blocks a 2-D leading axis only)
    got = orswot_ops.merge(*stacked_l, *stacked_r, 3, 2, impl="pallas")
    _assert_same(want, got)


@functools.lru_cache(maxsize=None)
def _jitted(impl, m, d):
    """One compiled merge per (impl, caps): example iterations then cost
    dispatch, not tracing (eager tiny-shape merges are ~1s each).  The
    rank reference pins ``impl="rank"`` explicitly — otherwise a TPU
    backend would dispatch merge to unrolled and the parity property
    would compare unrolled against itself."""
    if impl == "rank":
        def fn(*args):
            return orswot_ops.merge(*args, impl="rank")
    else:
        fn = orswot_unrolled.merge_unrolled
    return _jax.jit(lambda lhs, rhs: fn(*lhs, *rhs, m, d))


@pytest.mark.parametrize(
    "shape", [(7, 1, 1, 1), (7, 3, 2, 1), (7, 8, 5, 3)]
)
@settings(max_examples=25)  # shapes fixed → 3 compiles per impl, data varies
@given(seed=st.integers(0, 2**31 - 1), deferred_frac=st.sampled_from([0.0, 0.5]))
def test_impl_agreement_property(shape, seed, deferred_frac):
    """Both merge implementations agree on random states across the
    shape grid (incl. single-slot tables and deferred-bearing batches) —
    the randomized analogue of the fixed-seed parity cases above."""
    n, a, m, d = shape
    rng = np.random.RandomState(seed)
    lhs, rhs = _pair(rng, n, a, m, d, deferred_frac)
    ref = _jitted("rank", m, d)(lhs, rhs)
    _assert_same(ref, _jitted("unrolled", m, d)(lhs, rhs))


def test_full_uint32_counter_range_parity():
    """The tile math works in the bias-mapped signed domain
    (``x ^ 0x8000_0000``); counters at and above ``2**31`` must stay
    bit-exact through the unrolled variant."""
    rng = np.random.RandomState(29)
    n, a, m, d = 16, 4, 4, 2
    lhs, rhs = _pair(rng, n, a, m, d, deferred_frac=0.4)

    def inflate(state):
        clock, ids, dots, dids, dclocks = state
        big = jnp.uint32(1 << 31)
        up = lambda x: jnp.where(x > 0, x + big, x)  # keep 0 = absent
        return up(clock), ids, up(dots), dids, up(dclocks)

    lhs, rhs = inflate(lhs), inflate(rhs)
    ref = orswot_ops.merge(*lhs, *rhs, m, d)
    _assert_same(ref, orswot_unrolled.merge_unrolled(*lhs, *rhs, m, d))
    assert int(np.asarray(ref[0]).max()) >= 1 << 31


def test_batch_engine_pallas_impl_roundtrip():
    """The user-facing batch path with ``impl="pallas"``: scalar states
    in, merge through the fused kernel (interpret emulation on the CPU
    test backend), value() parity with the scalar fold out.  The impl is
    threaded explicitly — no env var, no jit-cache clearing: the impl is
    a static jit argument, so each choice compiles its own entry."""
    from crdt_tpu.batch import OrswotBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.scalar.orswot import Orswot
    from crdt_tpu.utils.interning import Universe

    uni = Universe(CrdtConfig(num_actors=4, member_capacity=4,
                              deferred_capacity=2, counter_bits=32,
                              merge_impl="pallas"))
    a, b = Orswot(), Orswot()
    # one actor per replica — the same actor issuing dots at two replicas
    # would forge duplicate dots, which merge correctly cancels
    for actor, member, st in [("p", "x", a), ("q", "y", b), ("q", "z", b)]:
        op = st.add(member, st.value().derive_add_ctx(actor))
        st.apply(op)
    rm = b.remove("y", b.contains("y").derive_rm_ctx())
    b.apply(rm)

    impl = uni.config.merge_impl
    ba = OrswotBatch.from_scalar([a], uni)
    bb = OrswotBatch.from_scalar([b], uni)
    merged = ba.merge(bb, impl=impl).merge(
        OrswotBatch.from_scalar([Orswot()], uni), impl=impl
    )
    got = merged.to_scalar(uni)[0].value().val

    oracle = Orswot()
    oracle.merge(a)
    oracle.merge(b)
    oracle.merge(Orswot())
    assert got == oracle.value().val == {"x", "z"}
