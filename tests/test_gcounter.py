"""GCounter tests — mirrors `/root/reference/test/gcounter.rs` plus the
doc-test from `/root/reference/src/gcounter.rs:9-23`."""

from hypothesis import given
from hypothesis import strategies as st

from crdt_tpu import GCounter


def test_basic():
    a, b = GCounter(), GCounter()
    a_op = a.inc("A")
    b_op = b.inc("B")
    a.apply(a_op)
    b.apply(b_op)
    assert a.value() == b.value()
    assert a == b

    a_op2 = a.inc("A")
    a.apply(a_op2)
    assert a > b


def test_doc_example():
    """`gcounter.rs:9-23`: an unapplied inc does not mutate."""
    a, b = GCounter(), GCounter()
    op_a1 = a.inc("A")
    op_b = b.inc("B")
    a.apply(op_a1)
    b.apply(op_b)
    assert a.value() == b.value()
    assert a == b
    op_a2 = a.inc("A")
    a.inc("A")  # pure: doesn't mutate
    a.apply(op_a2)
    assert a > b


@given(st.lists(st.integers(0, 10), max_size=30))
def test_prop_value_is_sum_and_merge_idempotent(actors):
    a = GCounter()
    for actor in actors:
        a.apply(a.inc(actor))
    assert a.value() == len(actors)
    snapshot = a.clone()
    a.merge(snapshot)
    assert a == snapshot


@given(st.lists(st.integers(0, 5), max_size=20), st.lists(st.integers(0, 5), max_size=20))
def test_prop_merge_commutative(xs, ys):
    a, b = GCounter(), GCounter()
    for actor in xs:
        a.apply(a.inc(actor))
    for actor in ys:
        b.apply(b.inc(actor))
    ab = a.clone()
    ab.merge(b)
    ba = b.clone()
    ba.merge(a)
    assert ab.inner == ba.inner
