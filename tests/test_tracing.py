"""Tracing subsystem (SURVEY.md §5): spans, kernel timing, profiler capture."""

import jax
import jax.numpy as jnp

from crdt_tpu.utils import tracing


def test_span_aggregation():
    tr = tracing.Tracer()
    for _ in range(3):
        with tr.span("work"):
            pass
    with tr.span("other"):
        pass
    assert tr.stats["work"].count == 3
    assert tr.stats["other"].count == 1
    assert tr.stats["work"].total_s >= tr.stats["work"].max_s
    rep = tr.report()
    assert "work" in rep and "other" in rep


def test_disabled_tracer_records_nothing():
    tr = tracing.Tracer(enabled=False)
    with tr.span("work"):
        pass
    assert tr.stats == {}


def test_span_records_on_exception():
    tr = tracing.Tracer()
    try:
        with tr.span("boom"):
            raise ValueError("x")
    except ValueError:
        pass
    assert tr.stats["boom"].count == 1


def test_timed_kernel_blocks_and_records():
    tracing.reset()
    tracing.enable(True)
    try:
        @tracing.timed_kernel("add1")
        def add1(x):
            return x + 1

        out = add1(jnp.zeros((8,)))
        assert out[0] == 1
        assert tracing.get_tracer().stats["add1"].count == 1
    finally:
        tracing.enable(False)
        tracing.reset()


def test_timed_kernel_zero_cost_when_disabled():
    tracing.enable(False)
    tracing.reset()

    @tracing.timed_kernel("noop")
    def f(x):
        return x

    f(jnp.zeros((2,)))
    assert tracing.get_tracer().stats == {}


def test_profile_context_tolerates_unsupported_backend(tmp_path):
    from crdt_tpu.ops import clock_ops

    with tracing.profile(str(tmp_path / "trace")):
        out = jax.jit(clock_ops.merge)(jnp.zeros((4, 4), jnp.uint32),
                                       jnp.ones((4, 4), jnp.uint32))
        jax.block_until_ready(out)


def test_profile_propagates_caller_exceptions(tmp_path):
    try:
        with tracing.profile(str(tmp_path / "trace2")):
            raise RuntimeError("inner")
    except RuntimeError as e:
        assert str(e) == "inner"
    else:
        raise AssertionError("exception swallowed")


def test_empty_report():
    assert "no spans" in tracing.Tracer().report()
