"""Tracing subsystem (SURVEY.md §5): spans, kernel timing, profiler capture."""

import jax
import jax.numpy as jnp

from crdt_tpu.utils import tracing


def test_span_aggregation():
    tr = tracing.Tracer()
    for _ in range(3):
        with tr.span("work"):
            pass
    with tr.span("other"):
        pass
    assert tr.stats["work"].count == 3
    assert tr.stats["other"].count == 1
    assert tr.stats["work"].total_s >= tr.stats["work"].max_s
    rep = tr.report()
    assert "work" in rep and "other" in rep


def test_disabled_tracer_records_nothing():
    tr = tracing.Tracer(enabled=False)
    with tr.span("work"):
        pass
    assert tr.stats == {}


def test_span_records_on_exception():
    tr = tracing.Tracer()
    try:
        with tr.span("boom"):
            raise ValueError("x")
    except ValueError:
        pass
    assert tr.stats["boom"].count == 1


def test_timed_kernel_blocks_and_records():
    tracing.reset()
    tracing.enable(True)
    try:
        @tracing.timed_kernel("add1")
        def add1(x):
            return x + 1

        out = add1(jnp.zeros((8,)))
        assert out[0] == 1
        assert tracing.get_tracer().stats["add1"].count == 1
    finally:
        tracing.enable(False)
        tracing.reset()


def test_timed_kernel_zero_cost_when_disabled():
    tracing.enable(False)
    tracing.reset()

    @tracing.timed_kernel("noop")
    def f(x):
        return x

    f(jnp.zeros((2,)))
    assert tracing.get_tracer().stats == {}


def test_profile_context_tolerates_unsupported_backend(tmp_path):
    from crdt_tpu.ops import clock_ops

    with tracing.profile(str(tmp_path / "trace")):
        out = jax.jit(clock_ops.merge)(jnp.zeros((4, 4), jnp.uint32),
                                       jnp.ones((4, 4), jnp.uint32))
        jax.block_until_ready(out)


def test_profile_propagates_caller_exceptions(tmp_path):
    try:
        with tracing.profile(str(tmp_path / "trace2")):
            raise RuntimeError("inner")
    except RuntimeError as e:
        assert str(e) == "inner"
    else:
        raise AssertionError("exception swallowed")


def test_empty_report():
    assert "no spans" in tracing.Tracer().report()


def test_report_widens_to_longest_span_name():
    """Span names longer than the old fixed 32-char column must not tear
    the table: the name column widens to the longest name, so the count
    field sits at the same offset on every row."""
    tr = tracing.Tracer()
    long = "wire.sync.full_state_exchange.with.an.absurdly.long.suffix"
    assert len(long) > 32
    tr.add(long, 0.001)
    tr.add("short", 0.002)
    lines = tr.report().splitlines()
    header, row_a, row_b = lines[0], lines[1], lines[2]
    w = len(long)  # the longest name defines the column width
    assert header[:w].rstrip() == "span"
    assert header[w:w + 8] == f" {'count':>7}"
    row_long, row_short = (row_a, row_b) if row_a.startswith(long) \
        else (row_b, row_a)
    assert row_long[:w].rstrip() == long
    assert row_short[:w].rstrip() == "short"
    # both spans ran once: identical, aligned count fields
    assert row_long[w:w + 8] == row_short[w:w + 8] == f" {1:>7}"


def test_timed_kernel_failure_counts_inputs_only_and_errors():
    """A raising kernel must record a span with INPUT bytes only plus a
    per-label `kernel.<label>.errors` counter (satellite: failing calls
    previously risked counting phantom output bytes)."""
    tracing.reset()
    tracing.enable(True)
    try:
        x = jnp.zeros((128,), jnp.uint32)

        @tracing.timed_kernel("boomk", count_bytes=True)
        def boomk(v):
            raise RuntimeError("kernel exploded")

        try:
            boomk(x)
        except RuntimeError:
            pass
        st = tracing.get_tracer().stats["boomk"]
        assert st.count == 1
        assert st.bytes_total == x.nbytes  # inputs only, no output bytes
        assert tracing.counters()["kernel.boomk.errors"] == 1

        # a successful call still counts inputs + outputs and no error
        @tracing.timed_kernel("okk", count_bytes=True)
        def okk(v):
            return v + 1

        okk(x)
        st = tracing.get_tracer().stats["okk"]
        assert st.bytes_total == 2 * x.nbytes
        assert "kernel.okk.errors" not in tracing.counters()
    finally:
        tracing.enable(False)
        tracing.reset()


def test_global_tracer_forwards_into_obs_registry():
    """The legacy span/count API re-routes into the typed obs registry
    (the tentpole's no-churn contract): counters land as registry
    counters, spans as log2 latency histograms."""
    from crdt_tpu.obs import metrics as obs_metrics

    tracing.reset()
    reg = obs_metrics.registry()
    tracing.count("wire.trace_forward_probe.native", 7)
    snap = reg.snapshot()
    assert snap["counters"]["wire.trace_forward_probe.native"] >= 7

    tracing.enable(True)
    try:
        with tracing.span("trace_forward_probe.span"):
            pass
    finally:
        tracing.enable(False)
        tracing.reset()
    h = reg.snapshot()["histograms"]["trace_forward_probe.span"]
    assert h["count"] >= 1 and h["sum"] >= 0.0


def test_forwarding_name_conflict_warns_instead_of_raising():
    """A name already claimed as another metric type in the obs registry
    must not make instrumentation raise through the instrumented code
    path (the executor.regrow counter-vs-span collision): forwarding
    drops the observation with one RuntimeWarning per name, and the
    tracer's own span stats still record."""
    import warnings

    from crdt_tpu.obs import metrics as obs_metrics

    tracing.reset()
    name = "trace_conflict_probe.span"
    obs_metrics.registry().counter_inc(name)  # claim the name as a counter
    tracing.enable(True)
    try:
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            for _ in range(2):  # second conflict must stay silent
                with tracing.span(name):
                    pass
    finally:
        tracing.enable(False)
    conflicts = [w for w in caught
                 if issubclass(w.category, RuntimeWarning)
                 and name in str(w.message)]
    assert len(conflicts) == 1
    assert tracing.get_tracer().stats[name].count == 2
    assert name not in obs_metrics.registry().snapshot()["histograms"]
    tracing.reset()


def test_bare_tracer_does_not_forward():
    """Non-global Tracer instances stay self-contained — tests and
    scoped measurements must not pollute the process registry."""
    from crdt_tpu.obs import metrics as obs_metrics

    tr = tracing.Tracer()
    tr.count("bare_tracer_probe.counter", 3)
    with tr.span("bare_tracer_probe.span"):
        pass
    snap = obs_metrics.registry().snapshot()
    assert "bare_tracer_probe.counter" not in snap["counters"]
    assert "bare_tracer_probe.span" not in snap["histograms"]


def test_profile_setup_failure_is_counted_and_flight_recorded(
        tmp_path, monkeypatch):
    """A swallowed profiler-setup failure must leave a diagnosable
    trail: the obs.profiler_unavailable counter counts every failure,
    the flight-recorder event fires ONCE per exception class — so "the
    trace directory is empty" is answerable from /events."""
    import jax

    from crdt_tpu.obs import events as obs_events
    from crdt_tpu.obs import metrics as obs_metrics

    class ProfilerBroken(RuntimeError):
        pass

    def boom(log_dir):
        raise ProfilerBroken("no profiler on this backend")

    monkeypatch.setattr(jax.profiler, "trace", boom)
    tracing._PROFILER_UNAVAILABLE_SEEN.discard("ProfilerBroken")
    before = obs_metrics.registry().counters_snapshot()
    for _ in range(2):  # caller body still runs, failures still count
        ran = False
        with tracing.profile(str(tmp_path / "trace")):
            ran = True
        assert ran
    after = obs_metrics.registry().counters_snapshot()
    assert after.get("obs.profiler_unavailable", 0) - \
        before.get("obs.profiler_unavailable", 0) == 2
    evs = [e for e in obs_events.recorder().snapshot(
               kind="obs.profiler_unavailable")
           if e["fields"]["error"] == "ProfilerBroken"]
    assert len(evs) == 1  # one event per exception class, not per failure
    assert "no profiler" in evs[0]["fields"]["detail"]
