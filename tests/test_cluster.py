"""Cluster runtime tests — hardened transports, membership, gossip.

The acceptance bar (ISSUE 5): a seeded fault-injection run converges a
5-replica fleet to byte-identical digest vectors under 20% injected
frame loss plus one flapping peer, with bounded retries, and the
flight recorder shows the retry/backoff/peer-state story afterwards.
Everything else here pins the pieces that make that possible: the ARQ
wrapper's exactly-once in-order delivery under each fault kind, the
deadline/budget bounds (`SyncTimeoutError`/`PeerUnavailableError`,
never a hang), the alive→suspect→dead→alive membership thresholds, and
the scheduler's staleness-first peer ranking with per-endpoint session
locks.
"""

import itertools
import threading
import time
from dataclasses import replace as policy_replace

import numpy as np
import pytest

from crdt_tpu.batch import OrswotBatch
from crdt_tpu.cluster import (
    ClusterNode,
    FaultPlan,
    FaultyTransport,
    FlappingDialer,
    GossipScheduler,
    Membership,
    ResilientTransport,
    RetryPolicy,
    queue_pair,
)
from crdt_tpu.cluster import membership as membership_mod
from crdt_tpu.cluster import transport as transport_mod
from crdt_tpu.config import CrdtConfig
from crdt_tpu.error import (
    PeerUnavailableError,
    SyncTimeoutError,
    TransportClosedError,
    TransportError,
    TransportFrameError,
)
from crdt_tpu.obs import convergence as obs_convergence
from crdt_tpu.obs import events as obs_events
from crdt_tpu.obs import metrics as obs_metrics
from crdt_tpu.scalar.orswot import Orswot
from crdt_tpu.sync import digest as sync_digest
from crdt_tpu.sync.session import SyncSession
from crdt_tpu.utils import tracing
from crdt_tpu.utils.interning import Universe

pytestmark = pytest.mark.cluster

#: test-speed retry policy: milliseconds where production defaults use
#: hundreds of ms, but the same shape (bounded budget, jittered backoff).
#: Deadlines are deliberately tight — a failed session leg must resolve
#: in seconds so failure cascades can't dominate the fleet tests.
FAST = RetryPolicy(send_deadline_s=3.0, recv_deadline_s=3.0,
                   ack_timeout_s=0.05, max_backoff_s=0.3,
                   retry_budget=400)


def _uni(**kw):
    cfg = dict(num_actors=8, member_capacity=16, deferred_capacity=4,
               counter_bits=32)
    cfg.update(kw)
    return Universe.identity(CrdtConfig(**cfg))


def _orswot_fleet(n, seed, actor=1, extra_on=()):
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        s = Orswot()
        for _ in range(rng.randint(1, 5)):
            s.apply(s.add(int(rng.randint(0, 50)),
                          s.value().derive_add_ctx(0)))
        out.append(s)
    for i in extra_on:
        s = out[i]
        s.apply(s.add(900 + actor, s.value().derive_add_ctx(actor)))
    return out


# ---- raw transports --------------------------------------------------------


def test_queue_pair_roundtrip_and_close():
    a, b = queue_pair(default_timeout=1.0)
    a.send(b"hello")
    assert b.recv(timeout=1.0) == b"hello"
    b.send(b"back")
    assert a.recv(timeout=1.0) == b"back"
    # timeout surfaces as the taxonomy, not queue.Empty
    with pytest.raises(SyncTimeoutError):
        a.recv(timeout=0.01)
    # a closed peer is a loud TransportClosedError, repeatedly
    b.close()
    for _ in range(2):
        with pytest.raises(TransportClosedError):
            a.recv(timeout=1.0)
    with pytest.raises(TransportClosedError):
        b.send(b"after close")


def test_decode_envelope_rejects_malformed():
    env = transport_mod.encode_envelope(transport_mod._DATA, 7, b"payload")
    kind, seq, payload = transport_mod.decode_envelope(env)
    assert (kind, seq, payload) == (transport_mod._DATA, 7, b"payload")
    with pytest.raises(TransportFrameError):
        transport_mod.decode_envelope(env[:10])        # truncated header
    with pytest.raises(TransportFrameError):
        transport_mod.decode_envelope(env[:-2])        # truncated payload
    corrupt = bytearray(env)
    corrupt[-1] ^= 0xFF
    with pytest.raises(TransportFrameError):
        transport_mod.decode_envelope(bytes(corrupt))  # CRC mismatch
    bad_kind = bytearray(env)
    bad_kind[0] = 0x7F
    with pytest.raises(TransportFrameError):
        transport_mod.decode_envelope(bytes(bad_kind))
    # TransportFrameError is catchable at the transport boundary
    assert issubclass(TransportFrameError, TransportError)


def _pump_frames(ra, rb, n, payload=b"frame-%04d"):
    """Ship ``n`` frames a→b through two resilient endpoints, driving
    the receive side in a thread (the ack path needs it live).  The
    sender flushes at the end: a windowed ``send`` only guarantees
    window admission, and the retransmit timers for any lost tail
    frames are serviced by the flush pump."""
    got = []
    err = []

    def consume():
        try:
            for _ in range(n):
                got.append(rb.recv(timeout=10.0))
        except BaseException as e:  # surfaced in the caller
            err.append(e)

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    for i in range(n):
        ra.send(payload % i)
    ra.flush(timeout=30.0)
    t.join(timeout=30.0)
    assert not t.is_alive(), "receiver hung"
    if err:
        raise err[0]
    return got


def test_resilient_clean_channel_is_transparent():
    ta, tb = queue_pair(default_timeout=5.0)
    ra = ResilientTransport(ta, FAST, name="a", seed=1)
    rb = ResilientTransport(tb, FAST, name="b", seed=2)
    got = _pump_frames(ra, rb, 8)
    assert got == [b"frame-%04d" % i for i in range(8)]
    assert ra.retransmits == 0
    assert rb.corrupt == 0


@pytest.mark.parametrize("plan", [
    FaultPlan(seed=3, drop=0.3),
    FaultPlan(seed=4, truncate=0.3),
    FaultPlan(seed=5, duplicate=0.3),
    FaultPlan(seed=6, delay=0.3),
    FaultPlan(seed=7, drop=0.1, truncate=0.1, duplicate=0.1, delay=0.1),
], ids=["drop", "truncate", "duplicate", "delay", "mixed"])
def test_resilient_delivers_exactly_once_under_faults(plan):
    """Every fault kind: the ARQ still delivers every frame, in order,
    exactly once — and the recovery machinery demonstrably ran."""
    ta, tb = queue_pair(default_timeout=5.0)
    fa = FaultyTransport(ta, plan, name="faulty-a")
    ra = ResilientTransport(fa, FAST, name="a", seed=11)
    rb = ResilientTransport(tb, FAST, name="b", seed=12)
    got = _pump_frames(ra, rb, 24)
    assert got == [b"frame-%04d" % i for i in range(24)]
    assert sum(fa.injected.values()) > 0, "plan injected nothing"
    # dropped/truncated frames force retransmits; duplicates are
    # suppressed; a delay-reordered frame lands in the out-of-order
    # buffer and is selectively acked — some recovery path must fire
    recovered = (ra.retransmits + rb.duplicates + rb.corrupt
                 + ra.transient_errors + rb.ooo_buffered)
    assert recovered > 0


def test_resilient_send_deadline_and_budget_are_bounded():
    # a peer that never acks: the send leg must fail in bounded time.
    # window=1 keeps the classic blocking-send shape — the error
    # surfaces from send() itself, not a later flush
    ta, _tb = queue_pair(default_timeout=5.0)
    policy = RetryPolicy(send_deadline_s=0.3, recv_deadline_s=0.3,
                         ack_timeout_s=0.02, max_backoff_s=0.05,
                         retry_budget=1000, window=1)
    ra = ResilientTransport(ta, policy, name="deadline", seed=13)
    t0 = time.monotonic()
    with pytest.raises(SyncTimeoutError):
        ra.send(b"into the void")
    assert time.monotonic() - t0 < 5.0
    # a tiny retry budget: PeerUnavailableError before the deadline
    ta2, _tb2 = queue_pair(default_timeout=5.0)
    tight = RetryPolicy(send_deadline_s=30.0, recv_deadline_s=30.0,
                        ack_timeout_s=0.01, max_backoff_s=0.02,
                        retry_budget=3, window=1)
    ra2 = ResilientTransport(ta2, tight, name="budget", seed=14)
    with pytest.raises(PeerUnavailableError):
        ra2.send(b"into the void")
    assert ra2.retransmits <= 4  # budget bounds the spin, not the clock
    # the windowed shape of the same bound: send() admits the frame
    # (the window has room), flush() is the delivery barrier that
    # surfaces the deadline
    ta3, _tb3 = queue_pair(default_timeout=5.0)
    ra3 = ResilientTransport(ta3, policy_replace(policy, window=8),
                             name="deadline-w8", seed=15)
    ra3.send(b"into the void")
    t0 = time.monotonic()
    with pytest.raises(SyncTimeoutError):
        ra3.flush()
    assert time.monotonic() - t0 < 5.0


def test_resilient_recv_deadline():
    ta, _tb = queue_pair(default_timeout=5.0)
    policy = RetryPolicy(recv_deadline_s=0.2, ack_timeout_s=0.02)
    ra = ResilientTransport(ta, policy, name="recv-deadline", seed=15)
    t0 = time.monotonic()
    with pytest.raises(SyncTimeoutError):
        ra.recv()
    assert time.monotonic() - t0 < 5.0


# ---- windowed ARQ ----------------------------------------------------------


class _DropSeq(transport_mod.Transport):
    """Inner transport that drops the DATA envelope with one chosen seq
    exactly once — deterministic loss, so the selective-ack pin can say
    WHICH frame died (FaultyTransport's coin flips cannot)."""

    def __init__(self, inner, seq):
        self._inner = inner
        self._seq = seq
        self.dropped = 0

    def send(self, frame):
        if self.dropped == 0 and len(frame) >= transport_mod._ENV.size:
            kind, seq, _crc, _plen = transport_mod._ENV.unpack_from(frame)
            if kind == transport_mod._DATA and seq == self._seq:
                self.dropped += 1
                return
        self._inner.send(frame)

    def recv(self, timeout=None):
        return self._inner.recv(timeout)

    def close(self):
        self._inner.close()


def test_windowed_selective_ack_retransmits_only_lost_frames():
    """Drop exactly one DATA frame out of eight: the frames behind the
    hole are buffered out-of-order and selectively acked, so the sender
    retransmits ONE frame — the lost one — not the whole window."""
    before = tracing.counters()
    ta, tb = queue_pair(default_timeout=5.0)
    drop = _DropSeq(ta, seq=2)
    # a generous ack timeout so the seq-2 retransmit timer fires ONCE,
    # well after the SACKs for seqs 3..7 have landed
    ra = ResilientTransport(drop, policy_replace(FAST, ack_timeout_s=0.3),
                            name="a", seed=31)
    rb = ResilientTransport(tb, FAST, name="b", seed=32)
    got = _pump_frames(ra, rb, 8)
    assert got == [b"frame-%04d" % i for i in range(8)]
    assert drop.dropped == 1
    # the selective-repeat pin: exactly the one lost frame went again
    assert ra.retransmits == 1
    assert rb.ooo_buffered >= 1       # frames behind the hole were held
    assert rb.sacks_sent >= 1         # ...and advertised to the sender
    assert ra.frames_sacked >= 1      # ...which excluded them from timers
    assert ra.window_hw >= 2          # the window genuinely pipelined
    deltas = tracing.counters_since(before)
    assert deltas.get("cluster.transport.window.sacked", 0) >= 1
    assert deltas.get("cluster.transport.window.ooo", 0) >= 1
    ra.close()
    rb.close()


def test_windowed_close_drains_whole_window():
    """Regression pin: close() with SEVERAL unacked frames in flight
    drains the whole window over a lossy link — not just the classic
    stop-and-wait single straggler — and stays inside the documented
    drain cap (6 quiet periods, quiet ≤ 1s)."""
    ta, tb = queue_pair(default_timeout=5.0)
    fa = FaultyTransport(ta, FaultPlan(seed=41, drop=0.3), name="lossy")
    ra = ResilientTransport(fa, FAST, name="a", seed=42)
    rb = ResilientTransport(tb, FAST, name="b", seed=43)
    got, err = [], []

    def consume():
        try:
            for _ in range(6):
                got.append(rb.recv(timeout=10.0))
        except BaseException as e:
            err.append(e)

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    for i in range(6):
        ra.send(b"drain-%04d" % i)
    # no flush: close() itself must be the delivery barrier
    t0 = time.monotonic()
    ra.close()
    elapsed = time.monotonic() - t0
    t.join(timeout=30.0)
    assert not t.is_alive(), "receiver hung"
    if err:
        raise err[0]
    assert got == [b"drain-%04d" % i for i in range(6)]
    assert elapsed < 8.0, f"close drained for {elapsed:.2f}s"


def _sync_sessions_over(a, b, uni, ta, tb, *, timeout_s=120.0, **session_kw):
    """Run one SyncSession pair over a pair of connected transports,
    peer side in a thread; returns ``(sa, sb, rep_a, rep_b)``."""
    sa = SyncSession(a, uni, peer="b", **session_kw)
    sb = SyncSession(b, uni, peer="a", **session_kw)
    res, err = {}, []
    a_done = threading.Event()

    def serve(tr, until):
        # a returned session stops pumping its transport, so over a
        # lossy link the peer's final in-flight frame (its ack lost)
        # can strand past the close-drain window — whichever side
        # finishes first keeps servicing acks until the other is done
        deadline = time.monotonic() + timeout_s
        while not until() and time.monotonic() < deadline:
            try:
                tr.recv(timeout=0.05)
            except SyncTimeoutError:
                continue
            except TransportError:
                return

    def run_b():
        try:
            res["b"] = sb.sync(tb)
            serve(tb, a_done.is_set)
        except BaseException as e:
            err.append(e)

    t = threading.Thread(target=run_b, daemon=True)
    t.start()
    try:
        res["a"] = sa.sync(ta)
    finally:
        a_done.set()
        serve(ta, lambda: not t.is_alive())
        ta.close()
        tb.close()
    t.join(timeout=timeout_s)
    assert not t.is_alive(), "peer session hung"
    if err:
        raise err[0]
    return sa, sb, res["a"], res["b"]


#: WAN-shaped retry policy: the initial RTO must sit near the injected
#: RTT or every first flight spuriously retransmits and burns budget
_WAN = RetryPolicy(send_deadline_s=20.0, recv_deadline_s=20.0,
                   ack_timeout_s=0.25, max_backoff_s=0.5,
                   retry_budget=2000)


@pytest.mark.parametrize("one_way_s", [0.025, 0.1],
                         ids=["rtt50ms", "rtt200ms"])
def test_windowed_sync_byte_identical_under_wan_faults(one_way_s):
    """The ISSUE acceptance rung: windowed sessions over 50–200ms RTT
    links with 20% loss and frame reordering converge byte-identical to
    a stop-and-wait control pair on the same histories."""
    from crdt_tpu.cluster import latency_pair

    uni = _uni()
    seed = int(one_way_s * 1000)
    rows_a = list(range(0, 64, 3))
    rows_b = list(range(1, 64, 5))
    a = OrswotBatch.from_scalar(
        _orswot_fleet(64, seed=61, actor=1, extra_on=rows_a), uni)
    b = OrswotBatch.from_scalar(
        _orswot_fleet(64, seed=61, actor=2, extra_on=rows_b), uni)
    ref = a.merge(b).to_wire(uni)

    def wan_link(s):
        la, lb = latency_pair(one_way_s, seed=s, default_timeout=30.0)
        fa = FaultyTransport(la, FaultPlan(seed=s + 10, drop=0.2,
                                           delay=0.25), name=f"wan-a{s}")
        fb = FaultyTransport(lb, FaultPlan(seed=s + 11, drop=0.2,
                                           delay=0.25), name=f"wan-b{s}")
        return fa, fb

    # windowed run
    fa, fb = wan_link(seed)
    ra = ResilientTransport(fa, _WAN, name="w-a", seed=seed + 1)
    rb = ResilientTransport(fb, _WAN, name="w-b", seed=seed + 2)
    sa, sb, rep_a, rep_b = _sync_sessions_over(a, b, uni, ra, rb)
    assert rep_a.converged and rep_b.converged
    assert rep_a.window > 1 and rep_b.window > 1
    assert sum(fa.injected.values()) + sum(fb.injected.values()) > 0
    assert sa.batch.to_wire(uni) == ref == sb.batch.to_wire(uni)

    # stop-and-wait control on the same histories
    fa2, fb2 = wan_link(seed + 100)
    ra2 = ResilientTransport(fa2, policy_replace(_WAN, window=1),
                             name="sw-a", seed=seed + 3)
    rb2 = ResilientTransport(fb2, policy_replace(_WAN, window=1),
                             name="sw-b", seed=seed + 4)
    sa2, sb2, rep2a, rep2b = _sync_sessions_over(a, b, uni, ra2, rb2)
    assert rep2a.converged and rep2b.converged
    assert rep2a.window == 1 and not rep2a.streaming
    # byte-identical across ARQ modes — the ISSUE's equivalence bar
    assert sa2.batch.to_wire(uni) == ref == sb2.batch.to_wire(uni)


def test_mixed_window_fleet_falls_back_to_stop_and_wait():
    """A window-16 node syncing with a window-1 node: the hello clamps
    both to stop-and-wait, the fallback counter fires, streaming stays
    off, and the result is still byte-identical to the merge."""
    before = tracing.counters()
    uni = _uni()
    a = OrswotBatch.from_scalar(
        _orswot_fleet(48, seed=91, actor=1, extra_on=[3, 9]), uni)
    b = OrswotBatch.from_scalar(
        _orswot_fleet(48, seed=91, actor=2, extra_on=[17]), uni)
    ref = a.merge(b).to_wire(uni)
    ta, tb = queue_pair(default_timeout=10.0)
    ra = ResilientTransport(ta, FAST, name="a", seed=92)
    rb = ResilientTransport(tb, policy_replace(FAST, window=1),
                            name="b", seed=93)
    sa, sb, rep_a, rep_b = _sync_sessions_over(a, b, uni, ra, rb)
    assert rep_a.converged and rep_b.converged
    assert rep_a.window == 1 and rep_b.window == 1
    assert not rep_a.streaming and not rep_b.streaming
    deltas = tracing.counters_since(before)
    assert deltas.get("cluster.transport.fallback.window", 0) >= 1
    assert sa.batch.to_wire(uni) == ref == sb.batch.to_wire(uni)


def test_session_accepts_transport_directly():
    """The Transport-object API of SyncSession.sync — the callable pair
    stays as a shim, the cluster runtime passes transports."""
    uni = _uni()
    a = OrswotBatch.from_scalar(
        _orswot_fleet(16, seed=21, actor=1, extra_on=[1]), uni)
    b = OrswotBatch.from_scalar(
        _orswot_fleet(16, seed=21, actor=2, extra_on=[4]), uni)
    ta, tb = queue_pair(default_timeout=10.0)
    sa = SyncSession(a, uni, peer="tb")
    sb = SyncSession(b, uni, peer="ta")
    res = {}

    def run_b():
        res["b"] = sb.sync(tb)

    t = threading.Thread(target=run_b, daemon=True)
    t.start()
    res["a"] = sa.sync(ta)
    t.join(timeout=30.0)
    assert res["a"].converged and res["b"].converged
    assert np.array_equal(
        np.asarray(sync_digest.digest_of(sa.batch)),
        np.asarray(sync_digest.digest_of(sb.batch)),
    )


# ---- membership ------------------------------------------------------------


def test_membership_thresholds_and_gauges():
    reg = obs_metrics.MetricsRegistry()
    m = Membership(suspect_after=2, dead_after=4, registry=reg)
    m.add("p1")
    m.add("p2")
    assert m.get("p1").state == membership_mod.ALIVE

    m.record_failure("p1")
    assert m.get("p1").state == membership_mod.ALIVE  # one blip tolerated
    m.record_failure("p1")
    assert m.get("p1").state == membership_mod.SUSPECT
    m.record_failure("p1")
    m.record_failure("p1")
    assert m.get("p1").state == membership_mod.DEAD
    assert m.get("p1").consecutive_failures == 4

    # one success from ANY state re-admits
    m.record_success("p1")
    assert m.get("p1").state == membership_mod.ALIVE
    assert m.get("p1").consecutive_failures == 0
    assert m.get("p1").sessions_failed == 4
    assert m.get("p1").sessions_ok == 1

    snap = reg.snapshot()["gauges"]
    assert snap["cluster.peers.alive"] == 2.0
    assert snap["cluster.peers.suspect"] == 0.0
    assert snap["cluster.peers.dead"] == 0.0
    assert snap["cluster.peer.p1.state"] == 0.0
    assert snap["cluster.peer.p1.consecutive_failures"] == 0.0
    assert m.counts() == {"alive": 2, "suspect": 0, "dead": 0}


def test_membership_transitions_hit_recorder_and_counters():
    reg = obs_metrics.MetricsRegistry()
    m = Membership(suspect_after=1, dead_after=2, registry=reg)
    m.add("flappy")
    before = tracing.counters()
    m.record_failure("flappy")   # -> suspect
    m.record_failure("flappy")   # -> dead
    m.record_success("flappy")   # -> alive
    deltas = tracing.counters_since(before)
    assert deltas.get("cluster.peer_transition.suspect") == 1
    assert deltas.get("cluster.peer_transition.dead") == 1
    assert deltas.get("cluster.peer_transition.alive") == 1
    evs = [e for e in obs_events.recorder().snapshot(kind="cluster.peer_state")
           if e["fields"]["peer"] == "flappy"]
    assert [(e["fields"]["old"], e["fields"]["new"]) for e in evs[-3:]] == [
        ("alive", "suspect"), ("suspect", "dead"), ("dead", "alive")]


# ---- gossip scheduling -----------------------------------------------------


def _mk_node(node_id, uni, seed=31, extra_on=(1,)):
    batch = OrswotBatch.from_scalar(
        _orswot_fleet(12, seed=seed, actor=1, extra_on=extra_on), uni)
    return ClusterNode(node_id, batch, uni)


def test_rank_peers_staleness_first():
    uni = _uni()
    tracker = obs_convergence.ConvergenceTracker(
        registry=obs_metrics.MetricsRegistry())
    m = Membership(suspect_after=2, dead_after=4,
                   registry=obs_metrics.MetricsRegistry())
    for p in ("fresh", "stale", "never"):
        m.add(p)
    tracker.observe_session("stale", converged=True, rounds=1)
    time.sleep(0.05)
    tracker.observe_session("fresh", converged=True, rounds=1)
    sched = GossipScheduler(_mk_node("n0", uni), m,
                            dialer=lambda peer: (_ for _ in ()).throw(
                                PeerUnavailableError("unused")),
                            tracker=tracker)
    ranked = [p.peer_id for p in sched.rank_peers(round_no=1)]
    assert ranked[0] == "never"             # never-synced outranks all
    assert ranked[1:] == ["stale", "fresh"]  # then oldest converged sync


def test_rank_peers_dead_only_on_probe_rounds():
    uni = _uni()
    m = Membership(suspect_after=1, dead_after=2,
                   registry=obs_metrics.MetricsRegistry())
    m.add("ok")
    m.add("gone")
    m.record_failure("gone")
    m.record_failure("gone")
    assert m.get("gone").state == membership_mod.DEAD
    tracker = obs_convergence.ConvergenceTracker(
        registry=obs_metrics.MetricsRegistry())
    sched = GossipScheduler(_mk_node("n0", uni), m,
                            dialer=lambda p: None, probe_dead_every=4,
                            tracker=tracker)
    assert [p.peer_id for p in sched.rank_peers(round_no=1)] == ["ok"]
    assert sorted(p.peer_id for p in sched.rank_peers(round_no=4)) == \
        ["gone", "ok"]


def test_round_skips_endpoint_with_session_in_flight():
    """Per-endpoint session locks: a peer whose previous session is
    still running is SKIPPED (never queued behind), so two rounds can
    never interleave frames on one endpoint."""
    uni = _uni()
    m = Membership(registry=obs_metrics.MetricsRegistry())
    m.add("busy-peer")
    tracker = obs_convergence.ConvergenceTracker(
        registry=obs_metrics.MetricsRegistry())
    sched = GossipScheduler(
        _mk_node("n0", uni), m,
        dialer=lambda p: (_ for _ in ()).throw(
            PeerUnavailableError("dial should not happen")),
        tracker=tracker, session_timeout_s=5.0,
    )
    lock = sched._endpoint_lock("busy-peer")
    assert lock.acquire(blocking=False)
    try:
        report = sched.run_round()
    finally:
        lock.release()
    assert report.skipped_busy == ["busy-peer"]
    assert report.attempted == 0
    assert m.get("busy-peer").sessions_failed == 0  # a skip is not a failure


def test_cluster_node_busy_bound():
    uni = _uni()
    node = _mk_node("n0", uni)
    node.busy_timeout_s = 0.05
    assert node._busy.acquire(blocking=False)
    try:
        ta, _tb = queue_pair(default_timeout=1.0)
        with pytest.raises(PeerUnavailableError):
            node.accept(ta, peer_id="px")
    finally:
        node._busy.release()


# ---- the acceptance run ----------------------------------------------------


def _gossip_fleet(n_nodes, n_objects, *, loss, flap_schedule,
                  suspect_after=2, dead_after=4, probe_dead_every=4):
    """N in-process replicas over fault-injected queue links.  Node 0's
    link to the last node goes through ``flap_schedule`` at the dial
    level (the flapping peer); EVERY link drops ``loss`` of its frames.
    Returns (nodes, schedulers, the flapping peer id)."""
    uni = _uni(num_actors=max(8, n_nodes + 2))
    nodes = []
    for i in range(n_nodes):
        extra = [(3 * i + k) % n_objects for k in range(3)]
        batch = OrswotBatch.from_scalar(
            _orswot_fleet(n_objects, seed=41, actor=i + 1, extra_on=extra),
            uni)
        nodes.append(ClusterNode(f"n{i}", batch, uni, busy_timeout_s=5.0))

    seeds = itertools.count(1000)

    def make_dialer(i):
        def dial(peer):
            j = int(peer.peer_id[1:])
            s = next(seeds)
            ta, tb = queue_pair(default_timeout=10.0)
            fa = FaultyTransport(ta, FaultPlan(seed=s, drop=loss),
                                 name=f"n{i}->n{j}")
            fb = FaultyTransport(tb, FaultPlan(seed=s + 1, drop=loss),
                                 name=f"n{j}->n{i}")
            ra = ResilientTransport(fa, FAST, name=f"n{i}->n{j}", seed=s + 2)
            rb = ResilientTransport(fb, FAST, name=f"n{j}->n{i}", seed=s + 3)

            def serve():
                try:
                    nodes[j].accept(rb, peer_id=f"n{i}")
                except Exception:  # failed inbound leg: the initiator's
                    pass           # error drives the bookkeeping
                finally:
                    rb.close()  # a stuck initiator must fail fast, not
                    #             wait out its deadline on a dead leg

            threading.Thread(target=serve, daemon=True).start()
            return ra
        return dial

    flappy = f"n{n_nodes - 1}"
    scheds = []
    for i in range(n_nodes):
        m = Membership(suspect_after=suspect_after, dead_after=dead_after)
        for j in range(n_nodes):
            if j != i:
                m.add(f"n{j}")
        dial = make_dialer(i)
        if i == 0 and flap_schedule:
            flap = FlappingDialer(dial, flap_schedule)

            def dial0(peer, _dial=dial, _flap=flap):
                return _flap(peer) if peer.peer_id == flappy else _dial(peer)

            dial = dial0
        # node 0 gossips to the whole roster each round so the flapping
        # link is exercised on a deterministic dial schedule
        scheds.append(GossipScheduler(
            nodes[i], m, dial,
            fanout=(n_nodes - 1) if i == 0 else 2,
            probe_dead_every=probe_dead_every,
            session_timeout_s=60.0, seed=i,
        ))
    return nodes, scheds, flappy


def test_acceptance_five_replicas_20pct_loss_flapping_peer():
    """THE acceptance run: 5 replicas, every link dropping 20% of its
    frames, node 4 flapping at the dial level through a full
    alive→suspect→dead→probe→alive cycle — the fleet must still reach
    byte-identical digest vectors, with bounded retries, and the flight
    recorder must tell the whole story afterwards."""
    before = tracing.counters()
    nodes, scheds, flappy = _gossip_fleet(
        5, 40, loss=0.20,
        # node 0's dials to n4: 4 refusals (alive→suspect→dead), then the
        # link comes back up; dead peers are probed every 4th round
        # (dials 5, 6, 7 — all scheduled up), which re-admits n4
        flap_schedule=[False] * 4 + [True] * 4,
        suspect_after=2, dead_after=4, probe_dead_every=4,
    )
    m0 = scheds[0].membership

    # the flight recorder is a 2048-event ring and a lossy fleet is
    # chatty — harvest new events every sweep so early peer-state
    # transitions can't be evicted before the assertions read them.
    # Start past whatever is already in the ring: earlier tests in this
    # process leave their own transport.retry events behind (with their
    # own policies' backoffs), and this test's assertions must read only
    # this fleet's story.
    events = []
    last_seq = max((e["seq"] for e in obs_events.recorder().snapshot()),
                   default=0)

    def harvest():
        nonlocal last_seq
        fresh = [e for e in obs_events.recorder().snapshot()
                 if e["seq"] > last_seq]
        if fresh:
            last_seq = fresh[-1]["seq"]
            events.extend(fresh)

    deadline = time.monotonic() + 240.0
    converged = False
    for _sweep in range(20):
        for sched in scheds:
            sched.run_round()
        harvest()
        digests = [n.digest() for n in nodes]
        identical = all(np.array_equal(digests[0], d) for d in digests[1:])
        flappy_back = m0.get(flappy).sessions_ok >= 1
        if identical and flappy_back:
            converged = True
            break
        assert time.monotonic() < deadline, "fleet failed to converge in time"
    assert converged, (
        f"not converged after sweeps: flappy={m0.snapshot().get(flappy)}"
    )

    # byte-identical digest vectors fleet-wide
    digests = [n.digest() for n in nodes]
    for d in digests[1:]:
        assert np.array_equal(digests[0], d)
        assert digests[0].tobytes() == d.tobytes()

    # the flapping peer went through the whole health cycle and came back
    transitions = [
        (e["fields"]["old"], e["fields"]["new"])
        for e in events
        if e["kind"] == "cluster.peer_state"
        and e["fields"]["peer"] == flappy
    ]
    assert ("alive", "suspect") in transitions
    assert ("suspect", "dead") in transitions
    assert ("dead", "alive") in transitions
    assert m0.get(flappy).state == membership_mod.ALIVE

    # retries/backoff happened, were recorded, and were BOUNDED: the
    # per-link budget is 400 and no link exhausted it (exhaustion would
    # have surfaced as PeerUnavailableError sessions that never heal)
    deltas = tracing.counters_since(before)
    assert deltas.get("cluster.transport.retransmits", 0) > 0
    assert deltas.get("cluster.rounds", 0) > 0
    assert deltas.get("cluster.sessions.ok", 0) > 0
    retry_events = [e for e in events
                    if e["kind"] == "cluster.transport.retry"]
    assert retry_events, "no retry/backoff events in the flight recorder"
    assert all(e["fields"]["backoff_s"] <= FAST.max_backoff_s * 2
               for e in retry_events)
    assert any(e["kind"] == "cluster.round" for e in events), \
        "rounds left no flight-recorder trace"


def test_small_fleet_converges_under_loss_fast():
    """The tier-1-sized sibling of the acceptance run: 3 replicas, 20%
    loss, no flap — seconds, not minutes."""
    nodes, scheds, _ = _gossip_fleet(3, 24, loss=0.20, flap_schedule=None)
    for _sweep in range(8):
        for sched in scheds:
            sched.run_round()
        digests = [n.digest() for n in nodes]
        if all(np.array_equal(digests[0], d) for d in digests[1:]):
            return
    raise AssertionError("3-replica fleet failed to converge in 8 sweeps")


def test_gossip_example_mode_converges():
    """The example's --gossip N mode end to end over real loopback TCP
    (subprocess, like the other replicate_tcp tests)."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(repo, "examples", "replicate_tcp.py"),
            "--gossip", "3", "--objects", "24", "--platform", "cpu",
        ],
        capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0, (proc.stdout[-400:], proc.stderr[-800:])
    assert "gossip: 3 peers" in proc.stdout
    assert "CONVERGED" in proc.stdout


def test_gossip_example_windowed_matches_stop_and_wait_control():
    """The example's --window smoke: a windowed gossip fleet must land
    on the byte-identical lattice point a stop-and-wait control fleet
    does — asserted via the digest fingerprint both runs print."""
    import os
    import re
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    shas = {}
    for label, window in (("windowed", "16"), ("stopwait", "0")):
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(repo, "examples", "replicate_tcp.py"),
                "--gossip", "3", "--objects", "24", "--platform", "cpu",
                "--window", window,
            ],
            capture_output=True, text=True, timeout=240,
        )
        assert proc.returncode == 0, \
            (label, proc.stdout[-400:], proc.stderr[-800:])
        m = re.search(r"fleet digest sha256=([0-9a-f]+)", proc.stdout)
        assert m, (label, proc.stdout[-400:])
        shas[label] = m.group(1)
        assert f"transport: window={'16' if window == '16' else '1'}" \
            in proc.stdout
    assert shas["windowed"] == shas["stopwait"]
