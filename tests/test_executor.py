"""Host-level join executor: elastic capacity recovery + transient retry
(SURVEY.md §5 'failure detection / elastic recovery')."""

import numpy as np
import pytest

from crdt_tpu import Orswot
from crdt_tpu.batch import OrswotBatch
from crdt_tpu.config import CrdtConfig
from crdt_tpu.parallel import JoinError, JoinExecutor, JoinStats, join_all
from crdt_tpu.utils.interning import Universe


def _universe(m=2, d=2, a=8):
    return Universe(CrdtConfig(num_actors=a, member_capacity=m, deferred_capacity=d))


def _fleet(uni, rows):
    """rows: list of lists of (member, actor) adds — one Orswot per list."""
    out = []
    for row in rows:
        s = Orswot()
        for member, actor in row:
            s.apply(s.add(member, s.value().derive_add_ctx(actor)))
        out.append(s)
    return out


def test_join_all_matches_scalar_fold():
    uni = _universe(m=8)
    fleets = [
        _fleet(uni, [[("a", 0), ("b", 0)]]),
        _fleet(uni, [[("c", 1)]]),
        _fleet(uni, [[("a", 2), ("d", 2)]]),
    ]
    batches = [OrswotBatch.from_scalar(f, uni) for f in fleets]
    stats = JoinStats()
    joined = JoinExecutor().join_all(batches, stats=stats)
    assert stats.joins == 3  # 2 folds + plunger
    assert stats.overflow_regrows == 0
    expected = Orswot()
    for f in fleets:
        expected.merge(f[0])
    expected.merge(Orswot())
    assert joined.to_scalar(uni)[0] == expected


def test_overflow_triggers_regrowth():
    # capacity 2, but the union of members is 6 → must regrow to succeed
    uni = _universe(m=2)
    rows = [
        [[("a", 0), ("b", 0)]],
        [[("c", 1), ("d", 1)]],
        [[("e", 2), ("f", 2)]],
    ]
    batches = [OrswotBatch.from_scalar(_fleet(uni, r), uni) for r in rows]
    stats = JoinStats()
    joined = JoinExecutor().join_all(batches, stats=stats)
    assert stats.overflow_regrows >= 1
    assert stats.final_member_capacity >= 6
    assert joined.value_sets(uni)[0] == {"a", "b", "c", "d", "e", "f"}


def test_only_overflowed_axis_regrows():
    """A deferred-table overflow must not double the (much larger) member
    axis — the error names the axis and the executor grows only it."""
    from crdt_tpu.scalar.ctx import RmCtx
    from crdt_tpu.scalar.vclock import VClock

    uni = Universe(CrdtConfig(num_actors=8, member_capacity=4, deferred_capacity=1))

    def deferred_state(actor, counter, member):
        s = Orswot()
        c = VClock()
        c.witness(actor, counter)
        s.apply(s.remove(member, RmCtx(clock=c)))
        assert s.deferred
        return s

    batches = [
        OrswotBatch.from_scalar([deferred_state(1, 5, "x")], uni),
        OrswotBatch.from_scalar([deferred_state(2, 5, "y")], uni),
    ]
    stats = JoinStats()
    joined = JoinExecutor().join_all(batches, stats=stats)
    assert stats.overflow_regrows >= 1
    assert stats.final_deferred_capacity > 1
    assert stats.final_member_capacity == 4, "member axis grew needlessly"
    assert len([i for i in joined.to_scalar(uni)[0].deferred]) == 2


def test_regrow_with_tracing_enabled_does_not_collide_in_registry():
    """Regression: with spans enabled (CRDT_TRACE=1 / --metrics-port),
    the ``executor.regrow`` span forwards a histogram into the obs
    registry while the recovery counter lives under
    ``executor.recovery.regrow`` — the names must stay disjoint, or the
    registry's one-type-per-name claim raises ValueError out of
    ``join_all`` instead of recovering."""
    from crdt_tpu.obs import metrics as obs_metrics
    from crdt_tpu.utils import tracing

    uni = _universe(m=2)
    rows = [
        [[("a", 0), ("b", 0)]],
        [[("c", 1), ("d", 1)]],
        [[("e", 2), ("f", 2)]],
    ]
    batches = [OrswotBatch.from_scalar(_fleet(uni, r), uni) for r in rows]
    stats = JoinStats()
    tracing.enable(True)
    try:
        joined = JoinExecutor().join_all(batches, stats=stats)
    finally:
        tracing.enable(False)
    assert stats.overflow_regrows >= 1
    assert joined.value_sets(uni)[0] == {"a", "b", "c", "d", "e", "f"}
    snap = obs_metrics.registry().snapshot()
    assert snap["counters"]["executor.recovery.regrow"] >= 1
    assert snap["histograms"]["executor.regrow"]["count"] >= 1


def test_overflow_beyond_max_capacity_raises():
    uni = _universe(m=2)
    rows = [
        [[("a", 0), ("b", 0)]],
        [[("c", 1), ("d", 1)]],
        [[("e", 2), ("f", 2)]],
    ]
    batches = [OrswotBatch.from_scalar(_fleet(uni, r), uni) for r in rows]
    with pytest.raises(JoinError, match="max_capacity"):
        JoinExecutor(max_capacity=4).join_all(batches)


def test_transient_failures_requeued():
    uni = _universe(m=8)
    batches = [
        OrswotBatch.from_scalar(_fleet(uni, [[("a", 0)]]), uni),
        OrswotBatch.from_scalar(_fleet(uni, [[("b", 1)]]), uni),
    ]

    class Flaky:
        """Duck-typed batch whose merge fails transiently twice."""

        def __init__(self, inner, failures):
            self.inner = inner
            self.failures = failures

        member_capacity = property(lambda self: self.inner.member_capacity)
        deferred_capacity = property(lambda self: self.inner.deferred_capacity)

        def with_capacity(self, m, d):
            return Flaky(self.inner.with_capacity(m, d), self.failures)

        def merge(self, other, check=True):
            if self.failures:
                self.failures.pop()
                raise RuntimeError("simulated device preemption")
            inner = other.inner if isinstance(other, Flaky) else other
            return Flaky(self.inner.merge(inner, check=check), self.failures)

    stats = JoinStats()
    joined = JoinExecutor(max_retries=2, retry_backoff_s=0).join_all(
        [Flaky(batches[0], ["x", "y"]), Flaky(batches[1], [])], stats=stats
    )
    assert stats.transient_retries == 2
    assert joined.inner.value_sets(uni)[0] == {"a", "b"}


def test_transient_failures_exhaust_retries():
    uni = _universe(m=8)
    b = OrswotBatch.from_scalar(_fleet(uni, [[("a", 0)]]), uni)

    class AlwaysDown:
        member_capacity = 8
        deferred_capacity = 2

        def with_capacity(self, m, d):
            return self

        def merge(self, other, check=True):
            raise RuntimeError("device gone")

    with pytest.raises(JoinError, match="retries"):
        JoinExecutor(max_retries=1, retry_backoff_s=0).join_all([AlwaysDown(), b])


def test_mismatched_capacities_equalized():
    uni = _universe(m=4)
    b_small = OrswotBatch.from_scalar(_fleet(uni, [[("a", 0)]]), uni)
    b_big = OrswotBatch.from_scalar(
        _fleet(uni, [[("b", 1), ("c", 1), ("d", 1)]]), uni
    ).with_capacity(8, 4)
    joined = join_all([b_small, b_big])
    assert joined.member_capacity == 8  # equalized up, not down
    assert joined.value_sets(uni)[0] == {"a", "b", "c", "d"}


def test_with_capacity_replica_stacked():
    """Regrowth must handle arbitrary leading batch axes (replica stacks)."""
    import jax
    import jax.numpy as jnp

    uni = _universe(m=2)
    rows = [OrswotBatch.from_scalar(_fleet(uni, [[("a", 0)]]), uni) for _ in range(3)]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *rows)
    grown = stacked.with_capacity(4, 4)
    assert grown.ids.shape == (3, 1, 4)
    assert grown.dots.shape == (3, 1, 4, uni.config.num_actors)
    assert grown.d_clocks.shape == (3, 1, 4, uni.config.num_actors)
    # live slots untouched
    assert jnp.array_equal(grown.ids[..., :2], stacked.ids)


def test_with_capacity_cannot_shrink():
    uni = _universe(m=4)
    b = OrswotBatch.from_scalar(_fleet(uni, [[("a", 0)]]), uni)
    with pytest.raises(ValueError, match="shrink"):
        b.with_capacity(2, 2)


def test_non_overflow_value_errors_propagate():
    uni = _universe(m=8)
    b = OrswotBatch.from_scalar(_fleet(uni, [[("a", 0)]]), uni)

    class Broken:
        member_capacity = 8
        deferred_capacity = 2

        def with_capacity(self, m, d):
            return self

        def merge(self, other, check=True):
            raise ValueError("shape mismatch")

    with pytest.raises(ValueError, match="shape mismatch"):
        JoinExecutor().join_all([Broken(), b])


class TestTreeStrategy:
    """join_all with strategy='tree' — the join_fleet schedule behind the
    same elastic recoveries as the sequential fold."""

    def _fleets(self, member_lists, uni):
        from crdt_tpu.batch import OrswotBatch
        from crdt_tpu.scalar.orswot import Orswot

        fleets = []
        for r, members in enumerate(member_lists):
            row = []
            for i, ms in enumerate(members):
                s = Orswot()
                for m in ms:
                    s.apply(s.add(m, s.value().derive_add_ctx(f"n{r}")))
                row.append(s)
            fleets.append(OrswotBatch.from_scalar(row, uni))
        return fleets

    def test_matches_sequential_strategy(self):
        from crdt_tpu.config import CrdtConfig
        from crdt_tpu.parallel.executor import JoinExecutor, JoinStats
        from crdt_tpu.utils.interning import Universe

        uni = Universe(CrdtConfig(num_actors=8, member_capacity=16,
                                  deferred_capacity=4))
        members = [
            [[f"a{i}", f"b{(i + r) % 5}"] for i in range(6)] for r in range(5)
        ]
        seq = JoinExecutor(strategy="sequential").join_all(
            self._fleets(members, uni)
        )
        stats = JoinStats()
        tree = JoinExecutor(strategy="tree").join_all(
            self._fleets(members, uni), stats=stats
        )
        assert tree.value_sets(uni) == seq.value_sets(uni)
        assert stats.joins == 5  # 4 tree merges + plunger

    def test_tree_overflow_regrows_all_fleets(self):
        from crdt_tpu.config import CrdtConfig
        from crdt_tpu.parallel.executor import JoinExecutor, JoinStats
        from crdt_tpu.utils.interning import Universe

        # disjoint members force the union past the starting capacity
        uni = Universe(CrdtConfig(num_actors=8, member_capacity=2,
                                  deferred_capacity=2))
        members = [[[f"r{r}m{j}" for j in range(2)] for _ in range(3)]
                   for r in range(4)]
        stats = JoinStats()
        out = JoinExecutor(strategy="tree").join_all(
            self._fleets(members, uni), stats=stats
        )
        assert stats.overflow_regrows >= 1
        assert out.member_capacity > 2
        got = out.value_sets(uni)
        want = {f"r{r}m{j}" for r in range(4) for j in range(2)}
        assert all(s == want for s in got)

    def test_auto_resolves_by_backend(self):
        from crdt_tpu.parallel.executor import JoinExecutor

        ex = JoinExecutor(strategy="auto")

        class HasFleet:
            @classmethod
            def join_fleet(cls, *a, **k):  # pragma: no cover - marker only
                raise NotImplementedError

        import jax

        expected = jax.default_backend() == "tpu"
        assert ex._use_tree([HasFleet(), HasFleet()]) is expected
        assert JoinExecutor(strategy="sequential")._use_tree(
            [HasFleet(), HasFleet()]
        ) is False
        import pytest

        with pytest.raises(ValueError, match="strategy"):
            JoinExecutor(strategy="bogus")._use_tree([HasFleet(), HasFleet()])

    def test_forced_tree_without_join_fleet_raises(self):
        import pytest

        from crdt_tpu.parallel.executor import JoinExecutor

        class NoFleet:
            pass

        with pytest.raises(ValueError, match="join_fleet"):
            JoinExecutor(strategy="tree")._use_tree([NoFleet(), NoFleet()])

    def test_module_level_join_all_forwards_strategy(self):
        from crdt_tpu.batch import OrswotBatch
        from crdt_tpu.config import CrdtConfig
        from crdt_tpu.parallel.executor import join_all
        from crdt_tpu.scalar.orswot import Orswot
        from crdt_tpu.utils.interning import Universe

        uni = Universe(CrdtConfig(num_actors=4, member_capacity=8,
                                  deferred_capacity=2))
        def fleet(tag):
            row = []
            for i in range(3):
                s = Orswot()
                s.apply(s.add(f"{tag}{i}", s.value().derive_add_ctx(tag)))
                row.append(s)
            return OrswotBatch.from_scalar(row, uni)

        out = join_all([fleet("x"), fleet("y")], strategy="tree")
        assert out.value_sets(uni) == [{f"x{i}", f"y{i}"} for i in range(3)]


# -- MVReg elasticity (the antichain axis under the generic protocol) --------


def _concurrent_regs(n_actors):
    """One register per replica, all written concurrently by distinct
    actors — the N-way join's antichain holds all N values."""
    from crdt_tpu.scalar.mvreg import MVReg

    regs = []
    for actor in range(n_actors):
        r = MVReg()
        r.apply(r.set(f"v{actor}", r.read().derive_add_ctx(actor)))
        regs.append(r)
    return regs


def test_mvreg_overflow_triggers_regrowth():
    """mv_capacity 2, five concurrent values: the executor must regrow the
    antichain axis (reported under the protocol's member slot) and the
    joined register must hold all five concurrent values."""
    from crdt_tpu.batch import MVRegBatch

    uni = Universe(CrdtConfig(num_actors=8, mv_capacity=2))
    regs = _concurrent_regs(5)
    batches = [MVRegBatch.from_scalar([r], uni) for r in regs]
    stats = JoinStats()
    joined = JoinExecutor().join_all(batches, plunger=False, stats=stats)
    assert stats.overflow_regrows >= 1
    assert stats.final_member_capacity >= 5
    assert stats.final_deferred_capacity == 0

    expected = regs[0].clone()
    for r in regs[1:]:
        expected.merge(r)
    got = joined.to_scalar(uni)[0]
    assert got == expected and len(got.vals) == 5


def test_mvreg_with_capacity_contract():
    from crdt_tpu.batch import MVRegBatch
    from crdt_tpu.error import CapacityOverflowError

    uni = Universe(CrdtConfig(num_actors=8, mv_capacity=2))
    regs = _concurrent_regs(3)
    a = MVRegBatch.from_scalar([regs[0]], uni)
    b = MVRegBatch.from_scalar([regs[1]], uni)
    c = MVRegBatch.from_scalar([regs[2]], uni)
    with pytest.raises(CapacityOverflowError) as ei:
        a.merge(b).merge(c)
    assert ei.value.member and not ei.value.deferred

    grown = a.with_capacity(4)
    assert grown.member_capacity == 4 and grown.deferred_capacity == 0
    # padded slots are dead (empty clocks); state is unchanged
    assert grown.to_scalar(uni) == a.to_scalar(uni)
    with pytest.raises(ValueError, match="cannot shrink"):
        grown.with_capacity(2)
    with pytest.raises(ValueError, match="no deferred axis"):
        a.with_capacity(4, 2)


# -- Map elasticity (key + deferred + NESTED value axes grow together) -------


def _map_writer(key_vals, actor):
    """A Map<int, MVReg> with one Put per (key, val), all by ``actor``."""
    from crdt_tpu import Map, MVReg
    from crdt_tpu.scalar.map import Up
    from crdt_tpu.scalar.mvreg import Put
    from crdt_tpu.scalar.vclock import Dot, VClock

    m = Map(MVReg)
    for c, (key, val) in enumerate(key_vals, start=1):
        m.apply(Up(dot=Dot(actor, c), key=key,
                   op=Put(clock=VClock({actor: c}), val=val)))
    return m


def test_map_key_overflow_triggers_regrowth():
    """key_capacity 2, six distinct keys across the fleet: the executor
    regrows the key axis and the joined map matches the scalar fold."""
    from crdt_tpu.batch import MapBatch, MVRegKernel

    uni = Universe(CrdtConfig(num_actors=8, key_capacity=2, mv_capacity=4,
                              deferred_capacity=2))
    vk = MVRegKernel.from_config(uni.config)
    maps = [
        _map_writer([(0, 1), (1, 2)], actor=0),
        _map_writer([(2, 3), (3, 4)], actor=1),
        _map_writer([(4, 5), (5, 6)], actor=2),
    ]
    batches = [MapBatch.from_scalar([m], uni, vk) for m in maps]
    stats = JoinStats()
    joined = JoinExecutor().join_all(batches, plunger=False, stats=stats)
    assert stats.overflow_regrows >= 1
    assert stats.final_member_capacity >= 6

    expected = maps[0].clone()
    for m in maps[1:]:
        expected.merge(m)
    assert joined.to_scalar(uni)[0] == expected


def test_map_nested_value_overflow_triggers_regrowth():
    """mv_capacity 1, three concurrent writers to the SAME key: the
    overflow is in the NESTED antichain, which only the scaled value
    kernel can absorb — the collapsed flag must still converge."""
    from crdt_tpu.batch import MapBatch, MVRegKernel

    uni = Universe(CrdtConfig(num_actors=8, key_capacity=4, mv_capacity=1,
                              deferred_capacity=2))
    vk = MVRegKernel.from_config(uni.config)
    maps = [_map_writer([(7, 10 + actor)], actor=actor) for actor in range(3)]
    batches = [MapBatch.from_scalar([m], uni, vk) for m in maps]
    stats = JoinStats()
    joined = JoinExecutor().join_all(batches, plunger=False, stats=stats)
    assert stats.overflow_regrows >= 1

    expected = maps[0].clone()
    for m in maps[1:]:
        expected.merge(m)
    got = joined.to_scalar(uni)[0]
    assert got == expected
    # all three concurrent values survive in the nested antichain
    assert sorted(got.entries[7].val.read().val) == [10, 11, 12]


def test_map_with_capacity_contract():
    from crdt_tpu.batch import MapBatch, MVRegKernel

    uni = Universe(CrdtConfig(num_actors=8, key_capacity=2, mv_capacity=2,
                              deferred_capacity=2))
    vk = MVRegKernel.from_config(uni.config)
    b = MapBatch.from_scalar([_map_writer([(0, 1)], actor=0)], uni, vk)
    grown = b.with_capacity(5, 2)
    # named axes pad EXACTLY (executor max_capacity bound holds for them);
    # nested antichain scales by the key factor ceil(5/2)=3
    assert grown.member_capacity == 5 and grown.deferred_capacity == 2
    assert grown.kernel.val_kernel.mv_capacity == 6
    assert grown.to_scalar(uni) == b.to_scalar(uni)
    with pytest.raises(ValueError, match="cannot shrink"):
        grown.with_capacity(2, 2)
    # capacity-mismatched batches unify automatically on merge
    merged = grown.merge(b)
    assert merged.kernel == grown.kernel
    assert merged.to_scalar(uni) == b.to_scalar(uni)


def test_map_merge_unifies_path_dependent_kernels():
    """Stepwise vs one-shot regrowth compound the NESTED capacities
    differently; merge must unify to the pointwise max, not raise —
    the shape JoinExecutor(max_capacity=...) produces when a clamp makes
    one side regrow in more steps than the other."""
    from crdt_tpu.batch import MapBatch, MVRegKernel
    from crdt_tpu.scalar.mvreg import MVReg

    uni = Universe(CrdtConfig(num_actors=8, key_capacity=2, mv_capacity=2,
                              deferred_capacity=2))
    vk = MVRegKernel.from_config(uni.config)
    a = MapBatch.from_scalar([_map_writer([(0, 1)], actor=0)], uni, vk)
    b = MapBatch.from_scalar([_map_writer([(1, 2)], actor=1)], uni, vk)
    a2 = a.with_capacity(4, 4).with_capacity(6, 6)   # nested mv 2->4->8
    b2 = b.with_capacity(6, 6)                       # nested mv 2->6
    assert a2.kernel != b2.kernel
    merged = a2.merge(b2)
    assert merged.kernel.val_kernel.mv_capacity == 8  # pointwise max
    want = _map_writer([(0, 1)], actor=0)
    want.merge(_map_writer([(1, 2)], actor=1))
    assert merged.to_scalar(uni)[0] == want

    # a genuinely incompatible kernel still raises
    other_uni = Universe(CrdtConfig(num_actors=4, key_capacity=2,
                                    mv_capacity=2, deferred_capacity=2))
    c = MapBatch.from_scalar(
        [_map_writer([(0, 1)], actor=0)], other_uni,
        MVRegKernel.from_config(other_uni.config),
    )
    with pytest.raises(ValueError, match="incompatible"):
        a2.merge(c)
