"""Parity: fused Pallas ORSWOT kernels vs the jnp path.

The jnp path (``orswot_ops``) is itself bit-exact against the scalar engine
(``tests/test_parity.py``), so equality here gives transitive parity with
the reference semantics (`/root/reference/src/orswot.rs:89-156`).

Kernels run in Pallas interpret mode on the CPU test mesh.  Compiled-mode
behavior is validated offline by the local v5e AOT loop
(``scripts/aot_compile_check.py``, `reports/PALLAS_LOCAL_AOT.md`) and
on-chip by the benchmark harness / ``scripts/tpu_validate.py --pallas``
when the tunnel is up.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from crdt_tpu.ops import orswot_ops, orswot_pallas
from crdt_tpu.utils.testdata import random_orswot_arrays


def _pair(rng, n, a, m, d):
    lhs = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, m, d, np.uint32))
    rhs = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, m, d, np.uint32))
    return lhs, rhs


def _assert_same(ref, got):
    names = ("clock", "ids", "dots", "d_ids", "d_clocks", "overflow")
    for name, r, g in zip(names, ref, got):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g), err_msg=name)


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("shape", [(17, 4, 3, 2), (33, 8, 4, 2)])
def test_pairwise_merge_parity(seed, shape):
    n, a, m, d = shape
    rng = np.random.RandomState(seed)
    lhs, rhs = _pair(rng, n, a, m, d)
    _assert_same(
        orswot_ops.merge(*lhs, *rhs, m, d),
        orswot_pallas.merge(*lhs, *rhs, m, d, interpret=True),
    )


def test_pairwise_merge_not_multiple_of_tile():
    # n deliberately prime so the object axis needs padding
    rng = np.random.RandomState(7)
    lhs, rhs = _pair(rng, 13, 4, 3, 2)
    _assert_same(
        orswot_ops.merge(*lhs, *rhs, 3, 2),
        orswot_pallas.merge(*lhs, *rhs, 3, 2, interpret=True),
    )


def test_fold_merge_matches_sequential_fold():
    rng = np.random.RandomState(3)
    n, a, m, d, r = 21, 8, 4, 2, 5
    reps = [
        tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, m, d, np.uint32))
        for _ in range(r)
    ]
    stacked = tuple(jnp.stack([rep[i] for rep in reps]) for i in range(5))
    acc = tuple(x[0] for x in stacked)
    over = jnp.zeros((n, 2), bool)
    for i in range(1, r):
        out = orswot_ops.merge(*acc, *(x[i] for x in stacked), m, d)
        acc, over = out[:5], over | out[5]
    out = orswot_ops.merge(*acc, *acc, m, d)  # defer plunger
    acc, over = out[:5], over | out[5]
    got = orswot_pallas.fold_merge(*stacked, m, d, interpret=True)
    _assert_same(acc + (over,), got)

    # the pre-biased entry point (bench hot path): pad+bias once outside,
    # fold in the kernel domain, unbias once after — bit-equal
    padded = orswot_pallas.pad_to_tile(stacked, m, d, n_states=r + 1)
    biased = orswot_pallas.to_kernel_domain(padded)
    gb = orswot_pallas.fold_merge(
        *biased, m, d, interpret=True, prebiased=True
    )
    unb = (
        orswot_pallas.from_kernel_domain(gb[0], jnp.uint32)[:n],
        gb[1][:n],
        orswot_pallas.from_kernel_domain(gb[2], jnp.uint32)[:n],
        gb[3][:n],
        orswot_pallas.from_kernel_domain(gb[4], jnp.uint32)[:n],
        gb[5][:n],
    )
    _assert_same(acc + (over,), unb)


def test_overflow_flag_parity():
    # force member-capacity overflow: disjoint member sets, tiny m_cap
    rng = np.random.RandomState(4)
    n, a, m, d = 9, 4, 4, 2
    lhs, rhs = _pair(rng, n, a, m, d)
    ref = orswot_ops.merge(*lhs, *rhs, 2, d)
    got = orswot_pallas.merge(*lhs, *rhs, 2, d, interpret=True)
    _assert_same(ref, got)
    assert bool(np.asarray(ref[5]).any()), "fixture should overflow somewhere"


def test_u64_counters_rejected():
    rng = np.random.RandomState(5)
    lhs = tuple(
        jnp.asarray(x) for x in random_orswot_arrays(rng, 4, 4, 3, 2, np.uint64)
    )
    with pytest.raises(TypeError, match="32-bit"):
        orswot_pallas.merge(*lhs, *lhs, 3, 2, interpret=True)


def test_mosaic_skew_gate_raises_typed_error():
    """The jax 0.4.x version gate: on a skewed jax, an interpret-mode
    kernel launch must surface the typed UnsupportedBackendError — with
    its remediation text — at the API boundary, never a deep Mosaic
    failure.  (On jax>=0.5 there is nothing to gate; conftest keeps
    this test OUT of the xfail set so the gate itself stays pinned.)"""
    from crdt_tpu.config import pallas_mosaic_skew
    from crdt_tpu.error import UnsupportedBackendError

    if pallas_mosaic_skew() is None:
        pytest.skip("jax >= 0.5: the Mosaic i64 skew does not apply")
    rng = np.random.RandomState(5)
    lhs = tuple(
        jnp.asarray(x) for x in random_orswot_arrays(rng, 4, 4, 3, 2, np.uint32)
    )
    with pytest.raises(UnsupportedBackendError, match="jax"):
        orswot_pallas.merge(*lhs, *lhs, 3, 2, interpret=True)
    # u64 rejection still outranks the version gate (caller bug first)
    as_u64 = tuple(
        x.astype(jnp.uint64) if x.dtype != jnp.int32 else x for x in lhs
    )
    with pytest.raises(TypeError, match="32-bit"):
        orswot_pallas.merge(*as_u64, *as_u64, 3, 2, interpret=True)


def test_full_uint32_counter_range_parity():
    """Counters at and above 2**31 must merge bit-identically — the kernel
    works in a bias-mapped signed domain (x ^ 0x8000_0000) precisely so
    the full uint32 range stays exact (a plain int32 cast would wrap and
    silently corrupt the merge)."""
    rng = np.random.RandomState(6)
    n, a, m, d = 16, 4, 4, 2
    lhs, rhs = _pair(rng, n, a, m, d)

    def inflate(state):
        clock, ids, dots, dids, dclocks = state
        big = jnp.uint32(1 << 31)
        # preserve the 0 = absent-lane invariant while pushing every live
        # counter into the high half of the uint32 range
        up = lambda x: jnp.where(x > 0, x + big, x)
        return up(clock), ids, up(dots), dids, up(dclocks)

    lhs, rhs = inflate(lhs), inflate(rhs)
    ref = orswot_ops.merge(*lhs, *rhs, m, d)
    got = orswot_pallas.merge(*lhs, *rhs, m, d, interpret=True)
    _assert_same(ref, got)
    assert int(np.asarray(got[0]).max()) >= 1 << 31, "fixture must exercise the high half"


def test_salt_chain_commutes_with_bias():
    """The bench's headline attempt salts in the kernel's biased domain
    (bench.py bench_pallas_north_star): XOR commutes with the x^0x80000000
    bias, so salting-then-biasing equals biasing-then-salting, and the
    biased-domain next_salt (max & 7 | 1) picks the same salt values."""
    rng = np.random.RandomState(7)
    n, a, m, d, r = 17, 8, 4, 2, 4
    reps = [
        tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, m, d, np.uint32))
        for _ in range(r)
    ]
    stacked = tuple(jnp.stack([rep[i] for rep in reps]) for i in range(5))
    padded = orswot_pallas.pad_to_tile(stacked, m, d, n_states=r + 1)
    biased = orswot_pallas.to_kernel_domain(padded)

    salt = 5
    # unbiased domain: salt the clock plane, fold, read next_salt bits
    u_salted = (padded[0] ^ jnp.uint32(salt),) + padded[1:]
    u_out = orswot_pallas.fold_merge(*u_salted, m, d, interpret=True)[:5]
    u_next = int(jnp.max(u_out[2]) & jnp.uint32(7)) | 1

    # biased domain: same salt applied to the biased plane
    b_salted = (biased[0] ^ jnp.int32(salt),) + biased[1:]
    b_out = orswot_pallas.fold_merge(
        *b_salted, m, d, interpret=True, prebiased=True
    )[:5]
    b_next = int(jnp.max(b_out[2]).astype(jnp.int32) & jnp.int32(7)) | 1

    assert u_next == b_next, "next_salt must agree across domains"
    for k, (u, b) in enumerate(zip(u_out, b_out)):
        if k in (1, 3):  # id planes are unbiased in both
            assert jnp.array_equal(u, b), f"plane {k}"
        else:
            unb = orswot_pallas.from_kernel_domain(b, jnp.uint32)
            assert jnp.array_equal(u, unb), f"plane {k}"
